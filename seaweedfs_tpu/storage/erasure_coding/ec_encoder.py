"""EC encode/rebuild file pipeline
(weed/storage/erasure_coding/ec_encoder.go).

`.dat` -> `.ec00..ecNN`: the volume stream is striped into rows of
data_shards blocks (1GB rows first, then 1MB rows for the tail, zero-
padded past EOF), parity blocks are computed per row, and each block is
appended to its shard file.  The file geometry is identical to the
reference for ANY batch size that divides the block size — the Go path
encodes in 256KB batches (ec_encoder.go:61), the TPU path uses 64MB
batches to amortize device dispatch; outputs are byte-identical.

Rebuild regenerates missing shards from >= data_shards survivors in
1MB steps (ec_encoder.go:323 rebuildEcFiles).
"""

from __future__ import annotations

import os

import numpy as np

from .. import idx as idxmod
from .. import types
from ..volume_info import (EcShardConfig, VolumeInfo,
                           maybe_load_volume_info, save_volume_info)
from .ec_context import (ECContext, LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE,
                         to_ext)  # noqa: F401  (re-exported)


# --- .ecx generation ----------------------------------------------------

def write_sorted_file_from_idx(base_file_name: str, ext: str = ".ecx"
                               ) -> None:
    """Generate the sorted needle index (ec_encoder.go:31
    WriteSortedFileFromIdx): replay .idx with memdb semantics — a delete
    REMOVES the key entirely (readNeedleMap ec_encoder.go:387-393 routes
    tombstones through MemDb.Delete), so pre-encode deletes never appear
    in .ecx — then write live entries ascending by key."""
    with open(base_file_name + ".idx", "rb") as f:
        live = idxmod.live_entries(f.read())
    entries = sorted(live.items())
    with open(base_file_name + ext, "wb") as out:
        if entries:
            keys = [k for k, _ in entries]
            offs = [o for _, (o, _) in entries]
            sizes = [s for _, (_, s) in entries]
            out.write(idxmod.pack_index(keys, offs, sizes))


# --- encode -------------------------------------------------------------

def write_ec_files(base_file_name: str, ctx: ECContext | None = None
                   ) -> None:
    """ec_encoder.go:61 WriteEcFiles / :67 WriteEcFilesWithContext."""
    ctx = ctx or ECContext()
    _generate_ec_files(base_file_name, ctx)


def _next_pow2(n: int) -> int:
    return 1 << (n - 1).bit_length()


def _encode_work_items(dat_size: int, ctx: ECContext
                       ) -> "list[tuple[int, int, int, int, int]]":
    """The exact batch schedule of ec_encoder.go:280 encodeDatFile
    (1GB rows, then 1MB rows for the tail) as a flat work list of
    (row_start, block_size, batch_offset, batch_bytes, real_rows):

    - large rows (1GB blocks) are chunked WITHIN a block: one item per
      (row, batch-offset), real_rows == 1, and the reader gathers the
      d strided block slices at batch_offset;
    - small rows (1MB blocks) are AGGREGATED: one item covers
      real_rows consecutive rows read contiguously and stacked on the
      batch axis (batch_bytes = padded_rows * block_size per shard).
      batch_bytes is padded up to a power-of-two row count so the
      whole volume compiles to a handful of device kernel shapes; the
      writer emits only real_rows * block_size bytes per shard.

    Either way the shard files are byte-identical to the reference:
    shard i's file is the in-order concatenation of row blocks i, and
    both chunking-within-a-block and stacking-whole-rows preserve that
    order."""
    work = []
    large_row = LARGE_BLOCK_SIZE * ctx.data_shards
    small_row = SMALL_BLOCK_SIZE * ctx.data_shards
    remaining = dat_size
    processed = 0
    while remaining >= large_row:
        batch = ctx.batch_size(LARGE_BLOCK_SIZE)
        for b0 in range(0, LARGE_BLOCK_SIZE, batch):
            work.append((processed, LARGE_BLOCK_SIZE, b0, batch, 1))
        remaining -= large_row
        processed += large_row
    rows_left = (remaining + small_row - 1) // small_row
    r_full = ctx.rows_per_launch(SMALL_BLOCK_SIZE)
    while rows_left > 0:
        g = min(r_full, rows_left)
        padded = min(r_full, _next_pow2(g))
        work.append((processed, SMALL_BLOCK_SIZE, 0,
                     padded * SMALL_BLOCK_SIZE, g))
        rows_left -= g
        processed += g * small_row
    return work


class _Stopped(Exception):
    """Internal: a pipeline stage was asked to abort."""


class _StageTimer:
    """Wraps one pipeline-stage callback to measure its true window:
    wall-clock start of the first call, end of the last call, and
    cumulative busy seconds.  The three stage windows OVERLAP by
    design (the triple-buffered pipeline) — emitted as sibling trace
    spans they show exactly that overlap (tracing.py), which is the
    stage-level timing arXiv:1908.01527 says repair tuning needs."""

    def __init__(self, fn):
        import time as _time
        self._fn = fn
        # forwarded so _staged_run still sees a lazy-capable writer
        # through the timing wrap
        self.accepts_lazy = getattr(fn, "accepts_lazy", False)
        self._clock = _time.perf_counter
        self._wall = _time.time
        self.start_wall = 0.0
        self.first = 0.0
        self.last = 0.0
        self.busy = 0.0
        self.calls = 0

    def __call__(self, *args):
        t0 = self._clock()
        if not self.calls:
            self.first = t0
            self.start_wall = self._wall()
        try:
            return self._fn(*args)
        finally:
            t1 = self._clock()
            self.busy += t1 - t0
            self.last = t1
            self.calls += 1

    def emit(self, name: str, trace_ctx, **attrs) -> None:
        """Record the stage window as a trace span parented to the
        span active when the rebuild started (`trace_ctx` from
        tracing.current_ids() — stages ran on other threads, so the
        contextvar cannot be relied on here)."""
        if not self.calls:
            return
        from ... import tracing
        attrs.update(busySeconds=round(self.busy, 6),
                     calls=self.calls)
        tracing.emit_span(
            name, self.start_wall, self.last - self.first,
            role=trace_ctx[2] if trace_ctx else "",
            parent=trace_ctx[1] if trace_ctx else "",
            trace_id=trace_ctx[0] if trace_ctx else "",
            attrs=attrs)


class _OverlappedFlusher:
    """Background thread that round-robins flush+fdatasync over the
    output files while the pipeline runs, so disk/network flush
    overlaps reads+compute instead of serializing after them.  Without
    it the whole 1.4x shard output sits in page cache until a final
    fsync — measured as 50% of e2e encode wall-clock on a 1GB volume
    (and sync_file_range is a silent no-op on network filesystems like
    the v9fs this was measured on, so a real fdatasync from a side
    thread is the only portable overlap).  Flush errors are latched and
    re-raised by stop(): a failing disk must fail the encode, not be
    swallowed by the helper thread."""

    def __init__(self, files, interval: float = 0.05):
        import threading
        self._files = list(files)
        self._interval = interval
        self._stop = threading.Event()
        self._error: BaseException | None = None
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        import os as _os
        while not self._stop.wait(self._interval):
            for f in self._files:
                if self._stop.is_set():
                    return
                try:
                    f.flush()
                    _os.fdatasync(f.fileno())
                except ValueError:  # closed under us at teardown
                    return
                except OSError as e:
                    self._error = e
                    return

    def stop(self, final: bool = True):
        """Join the flusher; when `final`, leave every file durably
        flushed and raise the first flush error, if any.  With
        final=False (pipeline already failing) latched errors are
        dropped so this never masks the caller's original exception."""
        import os as _os
        self._stop.set()
        self._t.join()
        if not final:
            return
        if self._error is not None:
            raise self._error
        for f in self._files:
            f.flush()
            _os.fdatasync(f.fileno())


def _staged_run(work, read_item, compute, write_item) -> None:
    """Triple-buffered staging pipeline (SURVEY §7 "hard parts" #2),
    shared by encode and rebuild: a reader thread stages disk batches
    into host buffers, the calling thread runs the GF kernel (device
    round-trip on the TPU backend), and a writer thread appends to the
    shard files — so disk reads, the codec, and disk writes overlap
    instead of serializing.

    read_item(item, buf) -> payload: fill (or replace) the recycled
    buffer; the payload's FIRST element must be the buffer to recycle.
    compute(payload) -> result: may return a lazy handle exposing
    .materialize() (async device dispatch; the writer materializes, so
    D2H of launch k overlaps H2D+kernel of k+1 — materializing before
    the recycle is also the aliasing contract of *_lazy: the kernel has
    consumed the buffer once its output is fetchable).
    write_item(payload, result) -> None: append to the output files.

    Host memory is bounded by a pool of 3 recycled buffers (one per
    stage — read/compute/write), so peak RSS stays ~3x one batch
    instead of growing with queue depth.  A shared stop event unblocks
    every stage on any error or interrupt: a parked producer can never
    deadlock the join, and a writer failure (ENOSPC) aborts the read +
    compute stages promptly rather than after the whole volume.
    Output append order is preserved because every stage is FIFO."""
    import queue
    import threading

    q_read: "queue.Queue" = queue.Queue()
    q_write: "queue.Queue" = queue.Queue()
    pool: "queue.Queue" = queue.Queue()
    for _ in range(3):
        pool.put(None)  # lazy-allocated buffer slots
    stop = threading.Event()
    errors: list[BaseException] = []

    def _blocking(q_op, *args):
        """put/get that stays interruptible by the stop event; returns
        the result or raises _Stopped."""
        while True:
            try:
                return q_op(*args, timeout=0.2)
            except (queue.Full, queue.Empty):
                if stop.is_set():
                    raise _Stopped() from None

    def reader():
        try:
            for item in work:
                buf = _blocking(pool.get)
                _blocking(q_read.put, read_item(item, buf))
        except _Stopped:
            pass
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)
            stop.set()
        finally:
            q_read.put(None)

    def writer():
        try:
            while True:
                item = _blocking(q_write.get)
                if item is None:
                    return
                payload, result = item
                if hasattr(result, "materialize") and \
                        not getattr(write_item, "accepts_lazy", False):
                    result = result.materialize()
                write_item(payload, result)
                pool.put(payload[0])  # recycle the slot for the reader
        except _Stopped:
            pass
        except BaseException as e:  # noqa: BLE001
            errors.append(e)
            stop.set()  # abort reader+compute promptly (don't encode
            # the rest of a 30GB volume just to report ENOSPC)

    rt = threading.Thread(target=reader, daemon=True)
    wt = threading.Thread(target=writer, daemon=True)
    rt.start()
    wt.start()
    try:
        while not stop.is_set():
            payload = q_read.get()
            if payload is None:
                break
            q_write.put((payload, compute(payload)))
    except BaseException as e:  # noqa: BLE001 — incl. KeyboardInterrupt
        errors.insert(0, e)
    finally:
        stop.set()  # unblocks any parked stage (timeouted puts/gets)
        q_write.put(None)
        rt.join()
        wt.join()
        # unwind path: compute results still queued were never
        # materialized — a staged device launch (ops.staging) parked
        # there must stop its stager thread NOW, not wait for GC
        while True:
            try:
                item = q_write.get_nowait()
            except queue.Empty:
                break
            if item is not None and hasattr(item[1], "abort"):
                item[1].abort()
    if errors:
        raise errors[0]


def _generate_ec_files(base_file_name: str, ctx: ECContext,
                       sinks: "list | None" = None,
                       stats=None) -> None:
    """Staged encode: .dat batches -> GF parity -> d+p shard streams.

    `sinks` (shard_sink.ShardSink, one per shard id) parameterizes the
    write stage: None keeps the seed semantics (LocalShardSink per
    `.ecNN` file on this node), the scatter path passes RemoteShardSink
    streams to each shard's placement target.  Ownership transfers
    either way: on success every sink is finish()ed (delivery
    verified), on failure every sink is abort()ed (staged bytes
    discarded — a failed encode leaves no partial shard for discovery
    to mistake for a real one).  COMMIT remains the caller's step:
    sidecars must land on the destinations before shards become
    visible."""
    from .shard_sink import LocalShardSink, ScatterStats
    dat_path = base_file_name + ".dat"
    dat_size = os.path.getsize(dat_path)
    codec = ctx.create_codec()
    d = ctx.data_shards
    work = _encode_work_items(dat_size, ctx)
    own_sinks = sinks is None
    if sinks is None:
        sinks = [LocalShardSink(base_file_name + ctx.to_ext(i))
                 for i in range(ctx.total)]
    if stats is None:
        stats = ScatterStats()
    for s in sinks:
        if hasattr(s, "set_stats"):
            s.set_stats(stats)
    dat = open(dat_path, "rb")

    def read_item(item, buf):
        row_start, block_size, b0, batch, real_rows = item
        if buf is None or buf.shape != (d, batch):
            buf = np.empty((d, batch), dtype=np.uint8)
        # NO full-buffer memset (the same lesson the rebuild reader
        # learned): only short/EOF read TAILS are zeroed — that is the
        # reference's zero-padding (ec_encoder.go:258-262) and the
        # only region whose stale recycled-buffer bytes could reach
        # the output.  Rows padded past real_rows (device-shape
        # padding) are left dirty on purpose: the GF apply is
        # byte-column-independent and the writer truncates at `real`,
        # so their content can never affect an emitted byte.
        if batch <= block_size:
            # chunk WITHIN one (large) row: gather the d strided
            # block slices at batch offset b0
            for i in range(d):
                dat.seek(row_start + i * block_size + b0)
                got = dat.readinto(memoryview(buf[i])[:batch])
                if got < batch:
                    buf[i, got:] = 0
        else:
            # real_rows stacked small rows: one strictly sequential
            # pass over the contiguous region
            dat.seek(row_start)
            for r in range(real_rows):
                base = r * block_size
                for i in range(d):
                    got = dat.readinto(
                        memoryview(buf[i])[base:base + block_size])
                    if got < block_size:
                        buf[i, base + got:base + block_size] = 0
        real = min(batch, real_rows * block_size)
        return (buf, real)

    lazy = getattr(codec, "parity_lazy", None)

    def compute(payload):
        buf, _real = payload
        if lazy is not None:
            return lazy(buf)  # async dispatch; writer materializes
        return np.ascontiguousarray(np.asarray(codec.parity(buf)))

    def write_item(payload, parity):
        buf, real = payload
        for i in range(d):
            sinks[i].write(buf[i, :real].data)
        if hasattr(parity, "windows"):
            # windowed staged launch (ops.staging): push each parity
            # window to its shard sink AS IT LANDS, so the d2h fetch
            # of window k and the scatter-sink sends overlap the h2d
            # staging of windows k+1, k+2...  Always drain fully —
            # a partial drain would recycle staging buffers the
            # stager thread is still copying from.
            for w0, chunk in parity.windows():
                n = min(chunk.shape[1], real - w0)
                if n <= 0:
                    continue  # device-shape padding beyond `real`
                for j in range(ctx.total - d):
                    sinks[d + j].write(chunk[j, :n].data)
            return
        if hasattr(parity, "materialize"):
            # legacy one-shot lazy handle (windowing disabled, or a
            # single-device batch inside one window): accepts_lazy
            # means _staged_run no longer materializes for us
            parity = parity.materialize()
        for j in range(ctx.total - d):
            sinks[d + j].write(parity[j, :real].data)

    write_item.accepts_lazy = True

    # stage spans (tracing.py): capture the caller's span context NOW
    # — the reader/writer stages run on pipeline threads where the
    # contextvar does not follow.  encode.read / encode.codec /
    # encode.write windows OVERLAP by design (the triple buffer);
    # per-destination encode.scatter.<sid> spans come from the remote
    # sinks' send threads.
    from ... import tracing
    trace_ctx = tracing.current_ids()
    read_item = _StageTimer(read_item)
    compute = _StageTimer(compute)
    write_item = _StageTimer(write_item)

    flusher = _OverlappedFlusher(
        [s.file for s in sinks if hasattr(s, "file")])
    ok = False
    try:
        _staged_run(work, read_item, compute, write_item)
        for s in sinks:
            s.end_stream()   # all tail chunks + receiver responses
        for s in sinks:      # drain concurrently, then verify each
            s.finish()
        ok = True
    finally:
        dat.close()
        try:
            flusher.stop(final=ok)
        except Exception:
            ok = False
            raise
        finally:
            if not ok:
                for s in sinks:
                    try:
                        s.abort()
                    except OSError:
                        pass
            elif own_sinks:
                # seed semantics: local files land in place now; the
                # scatter caller commits AFTER pushing sidecars
                for s in sinks:
                    s.commit()
            by_dest = stats.snapshot()[0]
            read_item.emit("encode.read", trace_ctx,
                           datBytes=dat_size, windows=len(work))
            compute.emit("encode.codec", trace_ctx,
                         dataShards=d, parityShards=ctx.total - d,
                         backend=ctx.backend)
            write_item.emit("encode.write", trace_ctx,
                            bytesByDest=by_dest, aborted=not ok)


# --- rebuild ------------------------------------------------------------

def scheme_from_vif(base_file_name: str) -> ECContext | None:
    """Recover the EC scheme persisted to .vif
    (server/volume_grpc_erasure_coding.go:132); None when absent or
    recorded without a scheme.  The single recovery point for every
    consumer (rebuild, decode-to-volume, shell)."""
    vi = maybe_load_volume_info(base_file_name + ".vif")
    if vi is not None and vi.ec_shard_config is not None and \
            vi.ec_shard_config.data_shards:
        return ECContext(vi.ec_shard_config.data_shards,
                         vi.ec_shard_config.parity_shards)
    return None


def rebuild_ec_files(base_file_name: str, ctx: ECContext | None = None,
                     additional_dirs: list[str] | None = None
                     ) -> list[int]:
    """ec_encoder.go:74 RebuildEcFiles: recover the scheme from .vif,
    then regenerate missing shard files from survivors.  Returns the
    generated shard ids."""
    if ctx is None:
        ctx = scheme_from_vif(base_file_name) or ECContext()
    return _generate_missing_ec_files(
        base_file_name, ctx, additional_dirs or [])


def _find_shard_file(base_file_name: str, ext: str,
                     additional_dirs: list[str]) -> str | None:
    """ec_encoder.go:131 findShardFile: primary path, then extra dirs."""
    primary = base_file_name + ext
    if os.path.exists(primary):
        return primary
    base = os.path.basename(base_file_name)
    for d in additional_dirs:
        cand = os.path.join(d, base + ext)
        if os.path.exists(cand):
            return cand
    return None


def discover_shard_files(base_file_name: str, ctx: ECContext,
                         additional_dirs: list[str]
                         ) -> "tuple[dict[int, str], list[int]]":
    """(present shard paths by id, locally-missing shard ids) — the
    discovery half of the two-pass rebuild (ec_encoder.go:146), shared
    with the streaming server handler which fills the gaps with remote
    sources instead of erroring."""
    present_paths: dict[int, str] = {}
    missing: list[int] = []
    for sid in range(ctx.total):
        p = _find_shard_file(base_file_name, ctx.to_ext(sid),
                             additional_dirs)
        if p is not None:
            present_paths[sid] = p
        else:
            missing.append(sid)
    return present_paths, missing


def _generate_missing_ec_files(base_file_name: str, ctx: ECContext,
                               additional_dirs: list[str]) -> list[int]:
    """Two-pass discover-then-create (ec_encoder.go:146), local files
    only — every survivor must already be on this node's disks."""
    present_paths, missing = discover_shard_files(
        base_file_name, ctx, additional_dirs)
    if len(present_paths) < ctx.data_shards:
        raise ValueError(
            f"not enough shards to rebuild {base_file_name}: found "
            f"{len(present_paths)}, need {ctx.data_shards}, "
            f"missing {missing}")
    if not missing:
        return []
    from .shard_source import LocalShardSource
    sources = {sid: LocalShardSource(p)
               for sid, p in present_paths.items()}
    return rebuild_from_sources(base_file_name, ctx, sources, missing)


def rebuild_from_sources(base_file_name: str, ctx: ECContext,
                         sources: dict, missing: list[int],
                         stats=None, slice_bytes: int | None = None,
                         shard_size: int | None = None) -> list[int]:
    """Regenerate `missing` shard files from survivor `sources`
    ({shard_id: ShardSource}) through the staged pipeline: a
    MultiSourceFetcher streams slice windows (one concurrent ranged
    stream per prefetching source), the GF kernel applies the
    reconstruction matrix, and the writer appends to the new shard
    files — fetch, codec, and writes overlap end to end.  Slice
    boundaries never change output bytes (the GF apply is
    byte-independent), so this is byte-identical to the local
    collect-then-rebuild path for any window size.  Closes every
    source."""
    from ...ops import rs_matrix
    from .shard_source import MultiSourceFetcher
    outputs: dict = {}
    fetcher = None
    try:
        if len(sources) < ctx.data_shards:
            raise ValueError(
                f"not enough shards to rebuild {base_file_name}: "
                f"found {len(sources)}, need {ctx.data_shards}, "
                f"missing {missing}")
        codec = ctx.create_codec()
        # One matrix maps the first data_shards survivors directly
        # onto ALL missing rows (data and parity targets alike), so
        # each step is a single [len(missing), d] x [d, batch] apply
        # over only the bytes that are actually regenerated — no
        # full-array copies.
        present_mask = tuple(sid in sources
                             for sid in range(ctx.total))
        rec_matrix, survivor_rows = \
            rs_matrix.cached_reconstruction_matrix(
                ctx.data_shards, ctx.parity_shards, present_mask,
                tuple(missing))
        used = {sid: sources[sid] for sid in survivor_rows}
        for sid in sources:
            if sid not in used:  # survivors beyond the first d: unused
                sources[sid].close()
        if shard_size is None:
            # every shard file is the same length by construction, so
            # a caller holding ANY shard passes the size and spares
            # one metadata round-trip per remote source (they were
            # serial and measurably front-loaded the repair)
            shard_size = max(src.size() for src in used.values())
        for sid in missing:
            outputs[sid] = open(base_file_name + ctx.to_ext(sid), "wb")
        if slice_bytes:
            # `slice_bytes` caps the window; small shards get windows
            # cut to ~1/8 of the shard (floor 1MB, or the explicit cap
            # when smaller) so the per-source prefetch pipelines
            # actually overlap fetch with compute instead of
            # degenerating to one or two giant slices
            step = max(min(slice_bytes, -(-shard_size // 8)),
                       min(slice_bytes, 1 << 20))
        else:
            step = ctx.batch_size(LARGE_BLOCK_SIZE)
        work = [(pos, min(step, shard_size - pos))
                for pos in range(0, shard_size, step)]
        d = ctx.data_shards
        fetcher = MultiSourceFetcher(used, work, stats=stats)
    except BaseException:
        # setup failed before the pipeline owned these resources: a
        # retrying caller (worker cron) must not leak one fd set per
        # attempt, nor leave empty target files for discovery to
        # mistake for survivors
        if fetcher is not None:
            fetcher.close()
        else:
            for src in sources.values():
                src.close()
        for sid, f in outputs.items():
            f.close()
            try:
                os.remove(base_file_name + ctx.to_ext(sid))
            except OSError:
                pass
        raise

    def read_item(item, buf):
        pos, n = item
        if buf is None or buf.shape != (d, n):
            buf = np.empty((d, n), dtype=np.uint8)
        # every source fills its staging row in place (local files
        # readinto it directly; remote windows are copied once out of
        # a recycled receive buffer).  Only the short tail of a row is
        # zeroed (EOF zero-padding, ec_encoder.go:258-262) — a
        # full-buffer memset per window was measurably the pipeline's
        # single largest memory cost.
        filled = fetcher.get(
            item, rows={sid: memoryview(buf[row])
                        for row, sid in enumerate(survivor_rows)})
        for row, sid in enumerate(survivor_rows):
            got = filled[sid]
            if got < n:
                buf[row, got:] = 0
        return (buf, n)

    lazy = getattr(codec, "apply_matrix_lazy", None)

    def compute(payload):
        buf, _n = payload
        if lazy is not None:
            return lazy(rec_matrix, buf)
        return np.ascontiguousarray(
            np.asarray(codec.apply_matrix(rec_matrix, buf)))

    def write_item(payload, rec):
        _buf, n = payload
        for row, sid in enumerate(missing):
            outputs[sid].write(rec[row, :n].data)

    # stage spans (tracing.py): capture the caller's span context NOW
    # — the reader/writer stages run on pipeline threads where the
    # contextvar does not follow
    from ... import tracing
    trace_ctx = tracing.current_ids()
    read_item = _StageTimer(read_item)
    compute = _StageTimer(compute)
    write_item = _StageTimer(write_item)

    flusher = _OverlappedFlusher(outputs.values())
    ok = False
    try:
        _staged_run(work, read_item, compute, write_item)
        ok = True
    finally:
        try:
            flusher.stop(final=ok)
        finally:
            fetcher.close()  # joins prefetch threads, closes sources
            for sid, f in outputs.items():
                f.close()
                if not ok:
                    # a truncated .ecNN left behind would be counted
                    # as a SURVIVOR by the next rebuild's discovery —
                    # failed repairs must leave no partial targets
                    try:
                        os.remove(base_file_name + ctx.to_ext(sid))
                    except OSError:
                        pass
            by_source = stats.snapshot()[0] if stats is not None \
                else {}
            read_item.emit("rebuild.fetch", trace_ctx,
                           bytesBySource=by_source,
                           windows=len(work), sliceBytes=step)
            compute.emit("rebuild.codec", trace_ctx,
                         missingShards=list(missing),
                         dataShards=ctx.data_shards)
            write_item.emit("rebuild.write", trace_ctx,
                            bytesWritten=len(missing) * shard_size,
                            aborted=not ok)
    return missing


def save_ec_volume_info(base_file_name: str, ctx: ECContext,
                        dat_file_size: int, version: int) -> None:
    """Persist the EC scheme to .vif so rebuild/decode can recover it
    (server/volume_grpc_erasure_coding.go:132)."""
    vi = maybe_load_volume_info(base_file_name + ".vif") or VolumeInfo()
    vi.version = version
    vi.dat_file_size = dat_file_size
    vi.ec_shard_config = EcShardConfig(ctx.data_shards, ctx.parity_shards)
    save_volume_info(base_file_name + ".vif", vi)
