"""EC scheme context and codec backend selection.

Mirrors weed/storage/erasure_coding/ec_encoder.go:19-27 constants and
ec_context.go:11-46 ECContext.  The codec backend is chosen once per
context: "cpu" (numpy twin) or "jax" (TPU kernels) — both bit-identical
to klauspost/reedsolomon.
"""

from __future__ import annotations

from dataclasses import dataclass, field

DATA_SHARDS_COUNT = 10
PARITY_SHARDS_COUNT = 4
TOTAL_SHARDS_COUNT = DATA_SHARDS_COUNT + PARITY_SHARDS_COUNT
MAX_SHARD_COUNT = 32          # ShardBits is uint32
MIN_TOTAL_DISKS = TOTAL_SHARDS_COUNT // PARITY_SHARDS_COUNT + 1
LARGE_BLOCK_SIZE = 1024 * 1024 * 1024   # 1GB
SMALL_BLOCK_SIZE = 1024 * 1024          # 1MB

# Batch bytes per encode step (the Go path uses 256KB,
# ec_encoder.go:61-67; any batch that divides the block size yields
# byte-identical shard files, so the TPU path uses far larger batches
# to amortize dispatch: geometry is preserved either way).
CPU_BATCH_SIZE = 1024 * 1024
TPU_BATCH_SIZE = 64 * 1024 * 1024


def to_ext(shard_id: int) -> str:
    """Shard file extension ".ecNN" (ec_encoder.go:107 ToExt) — single
    definition; ECContext.to_ext delegates here."""
    return f".ec{shard_id:02d}"


def default_backend() -> str:
    """TPU kernels when a TPU is attached; else the native C++ engine;
    numpy as the last resort."""
    try:
        import jax
        if jax.default_backend() == "tpu":
            return "jax"
    except Exception:  # pragma: no cover
        pass
    try:
        from ...ops import rs_native
        if rs_native.available():
            return "native"
    except Exception:  # pragma: no cover
        pass
    return "cpu"


@dataclass
class ECContext:
    """Carries the RS scheme for one volume's EC operations."""

    data_shards: int = DATA_SHARDS_COUNT
    parity_shards: int = PARITY_SHARDS_COUNT
    collection: str = ""
    volume_id: int = 0
    backend: str = field(default_factory=default_backend)

    @property
    def total(self) -> int:
        return self.data_shards + self.parity_shards

    def __post_init__(self):
        if not (0 < self.data_shards and
                0 < self.parity_shards and
                self.total <= MAX_SHARD_COUNT):
            raise ValueError(
                f"bad EC scheme {self.data_shards}+{self.parity_shards}")

    def to_ext(self, shard_id: int) -> str:
        return to_ext(shard_id)

    def create_codec(self):
        if self.backend == "jax":
            from ...ops.rs_jax import ReedSolomonJax
            return ReedSolomonJax(self.data_shards, self.parity_shards)
        if self.backend == "native":
            from ...ops.rs_native import ReedSolomonNative
            return ReedSolomonNative(self.data_shards,
                                     self.parity_shards)
        from ...ops.rs_cpu import ReedSolomonCPU
        return ReedSolomonCPU(self.data_shards, self.parity_shards)

    def batch_size(self, block_size: int) -> int:
        pref = TPU_BATCH_SIZE if self.backend == "jax" else CPU_BATCH_SIZE
        return min(pref, block_size)

    def rows_per_launch(self, block_size: int) -> int:
        """How many independent stripe rows to stack into one codec
        launch.  Rows are independent — shard i's file is the in-order
        concatenation of every row's block i — so stacking R rows on the
        batch axis yields byte-identical output while amortizing device
        dispatch over R*data_shards*block_size input bytes.  This is
        what lets the 1MB small-block tail geometry
        (ec_encoder.go:304-319) feed the TPU in 64MB launches instead
        of one blocking round-trip per 1MB block (the round-2 3,000x
        end-to-end collapse)."""
        pref = TPU_BATCH_SIZE if self.backend == "jax" else CPU_BATCH_SIZE
        return max(1, pref // block_size)

    def __str__(self) -> str:
        return f"{self.data_shards}+{self.parity_shards}"
