"""EC scheme context and codec backend selection.

Mirrors weed/storage/erasure_coding/ec_encoder.go:19-27 constants and
ec_context.go:11-46 ECContext.  The codec backend is chosen once per
context: "cpu" (numpy twin) or "jax" (TPU kernels) — both bit-identical
to klauspost/reedsolomon.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

DATA_SHARDS_COUNT = 10
PARITY_SHARDS_COUNT = 4
TOTAL_SHARDS_COUNT = DATA_SHARDS_COUNT + PARITY_SHARDS_COUNT
MAX_SHARD_COUNT = 32          # ShardBits is uint32
MIN_TOTAL_DISKS = TOTAL_SHARDS_COUNT // PARITY_SHARDS_COUNT + 1
LARGE_BLOCK_SIZE = 1024 * 1024 * 1024   # 1GB
SMALL_BLOCK_SIZE = 1024 * 1024          # 1MB

# Batch bytes per encode step (the Go path uses 256KB,
# ec_encoder.go:61-67; any batch that divides the block size yields
# byte-identical shard files, so the TPU path uses far larger batches
# to amortize dispatch: geometry is preserved either way).
CPU_BATCH_SIZE = 1024 * 1024
TPU_BATCH_SIZE = 64 * 1024 * 1024


def to_ext(shard_id: int) -> str:
    """Shard file extension ".ecNN" (ec_encoder.go:107 ToExt) — single
    definition; ECContext.to_ext delegates here."""
    return f".ec{shard_id:02d}"


def _cpu_engine() -> str:
    try:
        from ...ops import rs_native
        if rs_native.available():
            return "native"
    except Exception:  # noqa: SWFS004 — pragma: no cover; probing an
        pass           # optional native build must never fail open
    return "cpu"


def _probe_path() -> str:
    """Cache file next to the native build artifacts (the one writable
    per-machine cache dir this package already maintains)."""
    from ... import native
    d = os.path.join(os.path.dirname(os.path.abspath(native.__file__)),
                     "_build")
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, "ec_backend_probe.json")


def _measure_cpu_engine_gbps(engine: str) -> float:
    """Throughput of the host codec at pipeline batch size (1MB/shard)."""
    import time

    import numpy as np
    if engine == "native":
        from ...ops.rs_native import ReedSolomonNative as RS
    else:
        from ...ops.rs_cpu import ReedSolomonCPU as RS
    codec = RS(DATA_SHARDS_COUNT, PARITY_SHARDS_COUNT)
    data = np.random.default_rng(0).integers(
        0, 256, size=(DATA_SHARDS_COUNT, CPU_BATCH_SIZE), dtype=np.uint8)
    codec.parity(data[:, :4096])  # warmup
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        codec.parity(data)
        best = min(best, time.perf_counter() - t0)
    return data.size / best / 1e9


def _measure_h2d_gbps() -> float:
    """Host->device feed rate — the e2e ceiling of the device backend
    (input bytes move host->device 1:1).  A device->host scalar fetch is
    the fence: over a tunneled TPU, block_until_ready does not truly
    synchronize (see bench.py)."""
    import time

    import jax
    import numpy as np
    host = np.random.default_rng(1).integers(
        0, 2**32, size=(8 << 20) // 4, dtype=np.uint32)
    int(jax.device_put(host[:1024])[0])  # warmup
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        dev = jax.device_put(host)
        int(dev[0])
        best = min(best, time.perf_counter() - t0)
    return host.nbytes / best / 1e9


def probe_backend(force: bool = False) -> dict:
    """Measure (once per machine, cached on disk) the feed rates that
    decide the encode backend: host codec GB/s vs host->device GB/s.
    Returns {"cpu_engine": "native"|"cpu", "cpu_gbps": float,
    "h2d_gbps": float|None, "choice": str}."""
    import json

    path = _probe_path()
    if not force:
        try:
            with open(path) as f:
                rec = json.load(f)
            if rec.get("version") == _PROBE_VERSION:
                return rec
        except (OSError, ValueError):
            pass
    engine = _cpu_engine()
    rec = {"version": _PROBE_VERSION, "cpu_engine": engine,
           "cpu_gbps": round(_measure_cpu_engine_gbps(engine), 3),
           "h2d_gbps": None, "choice": engine}
    try:
        import jax
        if jax.default_backend() == "tpu":
            rec["h2d_gbps"] = round(_measure_h2d_gbps(), 3)
            if rec["h2d_gbps"] > rec["cpu_gbps"]:
                rec["choice"] = "jax"
    except Exception:  # noqa: SWFS004 — pragma: no cover; a wedged
        pass           # or absent TPU must not fail the CPU probe
    try:
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, path)
    except OSError:  # pragma: no cover — read-only install
        pass
    return rec


_PROBE_VERSION = 2
_cached_default: str | None = None


def default_backend() -> str:
    """Pick the engine that wins END-TO-END on this machine, not the
    one with the fastest kernel: a TPU behind a slow host->device path
    (e.g. a tunneled chip at 0.03 GB/s) loses to the native GFNI engine
    (~11 GB/s) by orders of magnitude, so the backends are chosen by a
    one-time feed-rate probe (cached on disk).  Override with
    SEAWEEDFS_TPU_EC_BACKEND=jax|native|cpu."""
    global _cached_default
    env = os.environ.get("SEAWEEDFS_TPU_EC_BACKEND")
    if env in ("jax", "native", "cpu"):
        return env
    if _cached_default is not None:
        return _cached_default
    try:
        import jax
        on_tpu = jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        on_tpu = False
    if not on_tpu:
        _cached_default = _cpu_engine()
        return _cached_default
    try:
        _cached_default = probe_backend()["choice"]
    except Exception:  # pragma: no cover — probe must never break IO
        _cached_default = "jax"
    return _cached_default


@dataclass
class ECContext:
    """Carries the RS scheme for one volume's EC operations."""

    data_shards: int = DATA_SHARDS_COUNT
    parity_shards: int = PARITY_SHARDS_COUNT
    collection: str = ""
    volume_id: int = 0
    backend: str = field(default_factory=default_backend)

    @property
    def total(self) -> int:
        return self.data_shards + self.parity_shards

    def __post_init__(self):
        if not (0 < self.data_shards and
                0 < self.parity_shards and
                self.total <= MAX_SHARD_COUNT):
            raise ValueError(
                f"bad EC scheme {self.data_shards}+{self.parity_shards}")

    def to_ext(self, shard_id: int) -> str:
        return to_ext(shard_id)

    def create_codec(self):
        if self.backend == "jax":
            from ...ops.rs_jax import ReedSolomonJax
            return ReedSolomonJax(self.data_shards, self.parity_shards)
        if self.backend == "native":
            from ...ops.rs_native import ReedSolomonNative
            return ReedSolomonNative(self.data_shards,
                                     self.parity_shards)
        from ...ops.rs_cpu import ReedSolomonCPU
        return ReedSolomonCPU(self.data_shards, self.parity_shards)

    def batch_size(self, block_size: int) -> int:
        pref = TPU_BATCH_SIZE if self.backend == "jax" else CPU_BATCH_SIZE
        return min(pref, block_size)

    def rows_per_launch(self, block_size: int) -> int:
        """How many independent stripe rows to stack into one codec
        launch.  Rows are independent — shard i's file is the in-order
        concatenation of every row's block i — so stacking R rows on the
        batch axis yields byte-identical output while amortizing device
        dispatch over R*data_shards*block_size input bytes.  This is
        what lets the 1MB small-block tail geometry
        (ec_encoder.go:304-319) feed the TPU in 64MB launches instead
        of one blocking round-trip per 1MB block (the round-2 3,000x
        end-to-end collapse)."""
        pref = TPU_BATCH_SIZE if self.backend == "jax" else CPU_BATCH_SIZE
        return max(1, pref // block_size)

    def __str__(self) -> str:
        return f"{self.data_shards}+{self.parity_shards}"
