"""EC striping geometry: map logical .dat ranges to (shard, offset)
intervals (weed/storage/erasure_coding/ec_locate.go).

A volume byte-stream lays out row-major: N large rows of
data_shards x 1GB blocks, then small rows of data_shards x 1MB blocks.
Every read resolves through this pure interval math.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Interval:
    block_index: int          # index within large-blocks or small-blocks
    inner_block_offset: int
    size: int
    is_large_block: bool
    large_block_rows_count: int

    def to_shard_id_and_offset(self, large_block_size: int,
                               small_block_size: int,
                               data_shards: int) -> tuple[int, int]:
        """ec_locate.go:88 ToShardIdAndOffset."""
        offset = self.inner_block_offset
        row_index = self.block_index // data_shards
        if self.is_large_block:
            offset += row_index * large_block_size
        else:
            offset += (self.large_block_rows_count * large_block_size +
                       row_index * small_block_size)
        return self.block_index % data_shards, offset


def locate_data(large_block_size: int, small_block_size: int,
                shard_dat_size: int, offset: int, size: int,
                data_shards: int) -> list[Interval]:
    """ec_locate.go:16 LocateData: intervals covering [offset, offset+size)
    of the logical .dat stream.  shard_dat_size is the per-shard file size
    (used to derive the large-row count)."""
    block_index, is_large, n_large_rows, inner = _locate_offset(
        large_block_size, small_block_size, shard_dat_size, offset,
        data_shards)
    intervals: list[Interval] = []
    while size > 0:
        block_len = large_block_size if is_large else small_block_size
        remaining = block_len - inner
        if remaining <= 0:
            block_index, is_large = _next_block(
                block_index, is_large, n_large_rows, data_shards)
            inner = 0
            continue
        take = min(size, remaining)
        intervals.append(Interval(block_index, inner, take, is_large,
                                  n_large_rows))
        size -= take
        if size <= 0:
            break
        block_index, is_large = _next_block(
            block_index, is_large, n_large_rows, data_shards)
        inner = 0
    return intervals


def _next_block(block_index: int, is_large: bool, n_large_rows: int,
                data_shards: int) -> tuple[int, bool]:
    nxt = block_index + 1
    if is_large and nxt == n_large_rows * data_shards:
        return 0, False
    return nxt, is_large


def _locate_offset(large_block_size: int, small_block_size: int,
                   shard_dat_size: int, offset: int,
                   data_shards: int) -> tuple[int, bool, int, int]:
    large_row_size = large_block_size * data_shards
    n_large_rows = shard_dat_size // large_block_size
    if offset < n_large_rows * large_row_size:
        return (offset // large_block_size, True, n_large_rows,
                offset % large_block_size)
    offset -= n_large_rows * large_row_size
    return (offset // small_block_size, False, n_large_rows,
            offset % small_block_size)
