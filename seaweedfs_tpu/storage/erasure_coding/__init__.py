"""Erasure coding subsystem (north star; SURVEY §2.2).

File-format compatible with the reference's `.ec00..ecNN` + `.ecx` +
`.ecj` + `.vif` contract (weed/storage/erasure_coding), with the RS math
running on the TPU kernels in ops/ (or their CPU twin).
"""

from .ec_context import ECContext, DATA_SHARDS_COUNT, PARITY_SHARDS_COUNT, \
    TOTAL_SHARDS_COUNT, LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE  # noqa: F401
from .ec_locate import Interval, locate_data  # noqa: F401
from .ec_encoder import (  # noqa: F401
    write_ec_files, write_sorted_file_from_idx, rebuild_ec_files, to_ext)
from .ec_decoder import (  # noqa: F401
    write_dat_file, write_idx_file_from_ec_index, find_dat_file_size,
    has_live_needles)
from .ec_volume import EcVolume  # noqa: F401
from .shard_sink import (  # noqa: F401
    ShardSink, LocalShardSink, RemoteShardSink, ScatterStats)
