"""Mounted EC volume (weed/storage/erasure_coding/ec_volume.go,
ec_shard.go, ec_volume_delete.go).

Holds locally-present shard files, serves needle locate via binary
search over the sorted `.ecx`, records deletes by tombstoning `.ecx`
in place and journaling the needle id to `.ecj`, and reads needle data
through the striping interval math.
"""

from __future__ import annotations

import os
import struct
import threading

from .. import idx as idxmod
from .. import types
from ..needle import Needle, get_actual_size, needle_body_length
from ..super_block import SuperBlock
from ..volume_info import maybe_load_volume_info
from .ec_context import (DATA_SHARDS_COUNT, ECContext, LARGE_BLOCK_SIZE,
                         PARITY_SHARDS_COUNT, SMALL_BLOCK_SIZE)
from .ec_locate import Interval, locate_data


class NotFoundError(KeyError):
    pass


class EcVolumeShard:
    """One local .ecNN shard file (ec_shard.go)."""

    def __init__(self, base_file_name: str, shard_id: int, path: str):
        self.shard_id = shard_id
        self.path = path
        self._f = open(path, "rb")
        self.size = os.path.getsize(path)

    def read_at(self, offset: int, size: int) -> bytes:
        self._f.seek(offset)
        return self._f.read(size)

    def close(self) -> None:
        self._f.close()


class EcVolume:
    """ec_volume.go:26 EcVolume: a volume mounted as EC shards."""

    def __init__(self, directory: str, volume_id: int, collection: str = "",
                 ctx: ECContext | None = None,
                 index_directory: str | None = None):
        self.dir = directory
        self.index_dir = index_directory or directory
        self.id = volume_id
        self.collection = collection
        self.shards: dict[int, EcVolumeShard] = {}
        self.lock = threading.RLock()
        base = self.base_file_name()
        vi = maybe_load_volume_info(self.index_base_file_name() + ".vif") \
            or maybe_load_volume_info(base + ".vif")
        if ctx is None:
            if vi is not None and vi.ec_shard_config is not None and \
                    vi.ec_shard_config.data_shards:
                ctx = ECContext(vi.ec_shard_config.data_shards,
                                vi.ec_shard_config.parity_shards,
                                collection, volume_id)
            else:
                ctx = ECContext(DATA_SHARDS_COUNT, PARITY_SHARDS_COUNT,
                                collection, volume_id)
        self.ctx = ctx
        self.dat_file_size = vi.dat_file_size if vi else 0
        self.expire_at_sec = vi.expire_at_sec if vi else 0
        for sid in range(ctx.total):
            p = base + ctx.to_ext(sid)
            if os.path.exists(p):
                self.shards[sid] = EcVolumeShard(base, sid, p)
        ecx = self.index_base_file_name() + ".ecx"
        self._ecx = open(ecx, "r+b") if os.path.exists(ecx) else None
        self._ecj_path = self.index_base_file_name() + ".ecj"
        self.version = self._read_version(vi)

    # -- naming ----------------------------------------------------------

    def _name(self, d: str) -> str:
        name = f"{self.id}"
        if self.collection:
            name = f"{self.collection}_{name}"
        return os.path.join(d, name)

    def base_file_name(self) -> str:
        return self._name(self.dir)

    def index_base_file_name(self) -> str:
        return self._name(self.index_dir)

    def _read_version(self, vi) -> int:
        """Version from .vif when recorded (the authoritative source,
        ec_volume.go:84-87), else the superblock at the head of a local
        shard 0, else the current default."""
        if vi is not None and vi.version:
            return vi.version
        shard0 = self.shards.get(0)
        if shard0 is not None:
            return SuperBlock.parse(shard0.read_at(0, 8),
                                    require_extra=False).version
        return types.CURRENT_VERSION

    @property
    def shard_ids(self) -> list[int]:
        return sorted(self.shards)

    def shard_size(self) -> int:
        for s in self.shards.values():
            return s.size
        return 0

    # -- .ecx search (ec_volume.go:283-346) -------------------------------

    def locate_needle(self, needle_id: int) -> tuple[int, int, list[Interval]]:
        """LocateEcShardNeedle: returns (actual_offset, size, intervals).
        Raises NotFoundError when absent; a tombstoned entry returns
        size = TOMBSTONE_FILE_SIZE with no intervals."""
        offset, size = self.search_sorted_index(needle_id)
        if types.size_is_deleted(size):
            return types.to_actual_offset(offset), size, []
        shard_size = self.shard_dat_size()
        intervals = locate_data(
            LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE, shard_size,
            types.to_actual_offset(offset),
            get_actual_size(size, self.version),
            self.ctx.data_shards)
        return types.to_actual_offset(offset), size, intervals

    def shard_dat_size(self) -> int:
        """Per-shard logical size for the locate math
        (ec_volume.go:295-308 LocateEcShardNeedleInterval): datFileSize
        from .vif is authoritative; the fallback subtracts 1 from the
        shard file size to disambiguate an exact large-block multiple
        that actually holds small blocks."""
        if self.dat_file_size > 0:
            return self.dat_file_size // self.ctx.data_shards
        return self.shard_size() - 1

    def search_sorted_index(self, needle_id: int,
                            mark_deleted: bool = False
                            ) -> tuple[int, int]:
        """Binary search .ecx (ec_volume.go:319
        SearchNeedleFromSortedIndex).  Returns (stored_offset, size).
        Holds the volume lock: the shared file handle's seek/read pairs
        must not interleave across threads."""
        if self._ecx is None:
            raise NotFoundError(f"no .ecx for volume {self.id}")
        with self.lock:
            return self._search_locked(needle_id, mark_deleted)

    def _search_locked(self, needle_id: int, mark_deleted: bool
                       ) -> tuple[int, int]:
        self._ecx.seek(0, os.SEEK_END)
        n_entries = self._ecx.tell() // types.NEEDLE_MAP_ENTRY_SIZE
        lo, hi = 0, n_entries
        while lo < hi:
            mid = (lo + hi) // 2
            self._ecx.seek(mid * types.NEEDLE_MAP_ENTRY_SIZE)
            buf = self._ecx.read(types.NEEDLE_MAP_ENTRY_SIZE)
            key, offset, size = struct.unpack(">QIi", buf)
            if key == needle_id:
                if mark_deleted:
                    self._ecx.seek(mid * types.NEEDLE_MAP_ENTRY_SIZE +
                                   types.NEEDLE_ID_SIZE + types.OFFSET_SIZE)
                    self._ecx.write(struct.pack(
                        ">i", types.TOMBSTONE_FILE_SIZE))
                    self._ecx.flush()
                return offset, size
            if key < needle_id:
                lo = mid + 1
            else:
                hi = mid
        raise NotFoundError(f"needle {needle_id:x} not in ecx")

    # -- delete (ec_volume_delete.go) -------------------------------------

    def delete_needle(self, needle_id: int) -> None:
        """Tombstone in .ecx + append id to .ecj journal
        (ec_volume_delete.go:27 DeleteNeedleFromEcx)."""
        with self.lock:
            try:
                self.search_sorted_index(needle_id, mark_deleted=True)
            except NotFoundError:
                return
            with open(self._ecj_path, "ab") as ecj:
                ecj.write(struct.pack(">Q", needle_id))

    def rebuild_ecx_file(self) -> None:
        """Replay .ecj tombstones into .ecx (ec_volume_delete.go:51)."""
        if not os.path.exists(self._ecj_path):
            return
        with self.lock:
            with open(self._ecj_path, "rb") as ecj:
                while True:
                    b = ecj.read(types.NEEDLE_ID_SIZE)
                    if len(b) != types.NEEDLE_ID_SIZE:
                        break
                    try:
                        self.search_sorted_index(
                            int.from_bytes(b, "big"), mark_deleted=True)
                    except NotFoundError:
                        pass

    # -- reads (local shards only; cross-server reads live in the store
    #    layer, weed/storage/store_ec.go) --------------------------------

    def read_needle_with(self, interval_reader, needle_id: int,
                         cookie: int | None = None) -> Needle:
        """Read + decode a needle, fetching each interval through
        `interval_reader` (local shard files here; the server-side
        EcReader passes its scatter/reconstruct resolver)."""
        _, size, intervals = self.locate_needle(needle_id)
        if types.size_is_deleted(size):
            raise NotFoundError(f"needle {needle_id:x} deleted")
        data = b"".join(interval_reader(iv) for iv in intervals)
        n = Needle.from_bytes(data, self.version, expected_size=size)
        if cookie is not None and n.cookie != cookie:
            raise ValueError(f"cookie mismatch on needle {needle_id:x}")
        return n

    def read_needle_local(self, needle_id: int, cookie: int | None = None
                          ) -> Needle:
        """Read a needle when ALL its intervals are locally present
        (store_ec.go:141 ReadEcShardNeedle, local-only path)."""
        return self.read_needle_with(self.read_interval, needle_id,
                                     cookie=cookie)

    def read_interval(self, iv: Interval) -> bytes:
        sid, off = iv.to_shard_id_and_offset(
            LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE, self.ctx.data_shards)
        shard = self.shards.get(sid)
        if shard is None:
            raise NotFoundError(
                f"shard {sid} of volume {self.id} not local")
        with self.lock:  # shared handle: seek/read must not interleave
            return shard.read_at(off, iv.size)

    # -- scrub (ec_volume_scrub.go) ---------------------------------------

    def scrub_index(self) -> tuple[int, list[str]]:
        """:14 ScrubIndex: keys strictly ascending, entries well-formed.
        Returns (entry_count, errors)."""
        if self._ecx is None:
            return 0, [f"no .ecx for volume {self.id}"]
        errors: list[str] = []
        count = 0
        last_key = -1
        for key, off, size in self.walk_index():
            count += 1
            if key <= last_key:
                errors.append(
                    f"ecx keys out of order: {key} after {last_key}")
            last_key = key
        if count == 0:
            errors.append(f"zero-size .ecx for volume {self.id}")
        return count, errors

    def scrub_local(self) -> tuple[int, list[int], list[str]]:
        """:27 ScrubLocal: verify every needle whose intervals are
        locally present — chunk bounds, read success, and full-needle
        CRC when no chunk is remote.  Returns (entries, broken_shard_ids,
        errors)."""
        _, errors = self.scrub_index()
        broken: set[int] = set()
        count = 0
        for key, off, size in self.walk_index():
            count += 1
            if types.size_is_deleted(size):
                continue
            try:
                _, _, intervals = self.locate_needle(key)
            except NotFoundError:
                continue
            has_remote = False
            chunk_failed = False
            data = b""
            for iv in intervals:
                sid, soff = iv.to_shard_id_and_offset(
                    LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE,
                    self.ctx.data_shards)
                shard = self.shards.get(sid)
                if shard is None:
                    has_remote = True
                    continue
                if soff + iv.size > shard.size:
                    broken.add(sid)
                    chunk_failed = True
                    errors.append(
                        f"shard {sid} too short for needle {key:x}")
                    continue
                with self.lock:
                    chunk = shard.read_at(soff, iv.size)
                if len(chunk) != iv.size:
                    broken.add(sid)
                    chunk_failed = True
                    errors.append(
                        f"short read shard {sid} needle {key:x}")
                    continue
                if not has_remote:
                    data += chunk
            # a failed chunk already produced its own precise error; a
            # CRC check on the incomplete byte string would only add a
            # misleading second one
            if not has_remote and not chunk_failed and data:
                try:
                    Needle.from_bytes(data, self.version,
                                      expected_size=size)
                except Exception as e:  # noqa: BLE001 — collect, continue
                    errors.append(f"needle {key:x} corrupt: {e}")
        return count, sorted(broken), errors

    # -- info ------------------------------------------------------------

    def walk_index(self):
        if self._ecx is None:
            return
        with self.lock:
            self._ecx.seek(0)
            buf = self._ecx.read()
        yield from idxmod.walk_index(buf)

    def close(self) -> None:
        for s in self.shards.values():
            s.close()
        if self._ecx is not None:
            self._ecx.close()

    def destroy(self) -> None:
        self.close()
        base = self.base_file_name()
        for sid in range(self.ctx.total):
            try:
                os.remove(base + self.ctx.to_ext(sid))
            except FileNotFoundError:
                pass
        for ext in (".ecx", ".ecj", ".vif"):
            try:
                os.remove(self.index_base_file_name() + ext)
            except FileNotFoundError:
                pass
