"""EC -> normal volume decode (weed/storage/erasure_coding/ec_decoder.go).

`.ec00..09` -> `.dat` by interleaved block copy (large rows then small
rows); `.ecx` + `.ecj` -> `.idx`; dat size inferred from the max .ecx
entry when no .vif records it.
"""

from __future__ import annotations

import os

from .. import idx as idxmod
from .. import types
from ..needle import get_actual_size
from ..super_block import SUPER_BLOCK_SIZE, SuperBlock
from .ec_context import LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE

_COPY_CHUNK = 8 * 1024 * 1024


def iterate_ecx_file(index_base_file_name: str):
    """Yield (key, stored_offset, size) from .ecx (ec_decoder.go:113)."""
    with open(index_base_file_name + ".ecx", "rb") as f:
        yield from idxmod.walk_index(f.read())


def iterate_ecj_file(index_base_file_name: str):
    """Yield deleted needle ids from .ecj (ec_decoder.go:143)."""
    path = index_base_file_name + ".ecj"
    if not os.path.exists(path):
        return
    with open(path, "rb") as f:
        while True:
            b = f.read(types.NEEDLE_ID_SIZE)
            if len(b) != types.NEEDLE_ID_SIZE:
                return
            yield int.from_bytes(b, "big")


def has_live_needles(index_base_file_name: str) -> bool:
    """ec_decoder.go:23 HasLiveNeedles (no-op guard for ec.decode)."""
    for _, _, size in iterate_ecx_file(index_base_file_name):
        if not types.size_is_deleted(size):
            return True
    return False


def write_idx_file_from_ec_index(base_file_name: str) -> None:
    """.ecx + .ecj -> .idx (ec_decoder.go:35): copy .ecx then append a
    tombstone entry per journaled delete."""
    with open(base_file_name + ".idx", "wb") as out:
        with open(base_file_name + ".ecx", "rb") as ecx:
            while True:
                chunk = ecx.read(_COPY_CHUNK)
                if not chunk:
                    break
                out.write(chunk)
        for key in iterate_ecj_file(base_file_name):
            out.write(idxmod.entry_bytes(key, 0,
                                         types.TOMBSTONE_FILE_SIZE))


def read_ec_volume_version(base_file_name: str) -> int:
    """Superblock lives at the start of .ec00 (ec_decoder.go:94)."""
    with open(base_file_name + ".ec00", "rb") as f:
        return SuperBlock.read_from(f).version


def find_dat_file_size(data_base_file_name: str,
                       index_base_file_name: str) -> int:
    """Max (offset + record size) over live .ecx entries
    (ec_decoder.go:65); at least the superblock size."""
    version = read_ec_volume_version(data_base_file_name)
    dat_size = SUPER_BLOCK_SIZE
    for _, stored_off, size in iterate_ecx_file(index_base_file_name):
        if types.size_is_deleted(size):
            continue
        stop = types.to_actual_offset(stored_off) + \
            get_actual_size(size, version)
        dat_size = max(dat_size, stop)
    return dat_size


def write_dat_file(base_file_name: str, dat_file_size: int,
                   shard_file_names: list[str]) -> None:
    """ec_decoder.go:176 WriteDatFile: interleave data shard blocks back
    into the contiguous volume stream.  The row geometry follows the
    number of data shards actually passed (callers pass exactly the
    data shards, default 10; RS(6,3) volumes pass 6), so alternate
    schemes decode with the same stripe layout they were encoded
    with."""
    inputs = [open(p, "rb") for p in shard_file_names]
    n_data = len(inputs)
    try:
        with open(base_file_name + ".dat", "wb") as dat:
            remaining = dat_file_size
            while remaining >= n_data * LARGE_BLOCK_SIZE:
                for f in inputs:
                    _copy_n(f, dat, LARGE_BLOCK_SIZE)
                    remaining -= LARGE_BLOCK_SIZE
            while remaining > 0:
                for f in inputs:
                    to_read = min(remaining, SMALL_BLOCK_SIZE)
                    if to_read <= 0:
                        break
                    _copy_n(f, dat, to_read)
                    remaining -= to_read
    finally:
        for f in inputs:
            f.close()


def _copy_n(src, dst, n: int) -> None:
    left = n
    while left > 0:
        chunk = src.read(min(_COPY_CHUNK, left))
        if not chunk:
            raise IOError(f"short read copying {n} bytes from shard")
        dst.write(chunk)
        left -= len(chunk)
