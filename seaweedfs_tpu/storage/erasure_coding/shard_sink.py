"""Pluggable encode outputs: where freshly-encoded shard slices go.

The scatter-encode path ("the I/O funnel, not the codec, bounds online
erasure coding" — arXiv:1709.05365; the mirror image of PR 2's repair
pipelining, arXiv:1908.01527) replaces encode-locally-then-balance —
write all d+p shard files on the source node's disks, then have
`ec.balance` re-read and re-write most of them a second time to move
them off — with a slice pipeline OUT of the GF kernel: each shard's
output windows stream through a ShardSink (a local file when the shard
is placed on this node, ONE long chunked `/admin/ec/shard_write` HTTP
stream when it is placed remotely), one concurrent send thread per
remote destination with a bounded in-flight queue and recycled
buffers.  Shards destined elsewhere never touch the source disk, so
the source's 1.4x shard write amplification collapses to the sidecar
files only (~0.07x) and aggregate write bandwidth becomes the SUM of
the destinations' disks.

Commit protocol (the no-partial-stripe invariant): the receiver
streams each shard into a `.scatter.<uploadId>` temp file with an
incremental CRC32 and registers it UNMOUNTED; only an explicit
`shard_write_commit` carrying the sender's own running CRC renames it
to its final `.ecNN` name (and optionally mounts it).  Any failure —
sender, receiver, or wire — leaves nothing but an unregistered temp
file, which the receiver removes; a stripe is only ever visible whole.

Memory stays bounded by sinks x (inflight + 1) x window bytes: the
defaults (16MB windows, 2 in flight) keep a 14-shard scatter under
~0.7GB of staged slices.
"""

from __future__ import annotations

import os
import queue
import threading
import time
import uuid
import zlib


def scatter_window_bytes() -> int:
    """Send window per destination stream.  The GF apply is
    byte-independent so the window never changes output bytes; bigger
    windows amortize chunk framing, smaller ones bound staging RAM.
    SEAWEEDFS_TPU_EC_SCATTER_WINDOW_MB overrides."""
    try:
        mb = int(os.environ.get("SEAWEEDFS_TPU_EC_SCATTER_WINDOW_MB",
                                "16"))
    except ValueError:
        mb = 16
    return max(1, min(mb, 1024)) << 20


def scatter_inflight_depth() -> int:
    """Windows queued ahead per destination stream (>= 2 so the send of
    window k overlaps the codec on k+1 even when one destination
    hiccups).  SEAWEEDFS_TPU_EC_SCATTER_INFLIGHT overrides."""
    try:
        d = int(os.environ.get("SEAWEEDFS_TPU_EC_SCATTER_INFLIGHT", "2"))
    except ValueError:
        d = 2
    return max(1, d)


class ScatterStats:
    """Per-encode telemetry accumulator: bytes pushed per destination,
    window send latencies, local bytes.  Thread-safe (send threads
    record concurrently); summarized once at the end."""

    def __init__(self):
        self._lock = threading.Lock()
        self.bytes_by_dest: dict[str, int] = {}
        self.local_bytes = 0
        self.latencies: list[float] = []
        self.windows = 0

    def record(self, dest: str, nbytes: int, seconds: float) -> None:
        with self._lock:
            self.bytes_by_dest[dest] = \
                self.bytes_by_dest.get(dest, 0) + nbytes
            self.latencies.append(seconds)
            self.windows += 1

    def record_local(self, nbytes: int) -> None:
        with self._lock:
            self.local_bytes += nbytes

    def snapshot(self) -> "tuple[dict[str, int], list[float], int]":
        with self._lock:
            return (dict(self.bytes_by_dest), list(self.latencies),
                    self.local_bytes)

    @staticmethod
    def _pct(sorted_vals: "list[float]", q: float) -> float:
        if not sorted_vals:
            return 0.0
        i = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1)))
        return sorted_vals[i]

    def summary(self, volume_bytes: int, wall_seconds: float) -> dict:
        """JSON-able summary; `volume_bytes` is the .dat size (how
        `weed shell` encode throughput is judged everywhere else)."""
        with self._lock:
            lats = sorted(self.latencies)
            by_dest = dict(self.bytes_by_dest)
            local = self.local_bytes
        total = sum(by_dest.values())
        wall = max(wall_seconds, 1e-9)
        return {
            "bytesScatteredByDest": by_dest,
            "bytesScatteredTotal": total,
            "localWriteBytes": local,
            "windows": self.windows,
            "windowP50Ms": round(self._pct(lats, 0.50) * 1e3, 3),
            "windowP95Ms": round(self._pct(lats, 0.95) * 1e3, 3),
            "wallSeconds": round(wall, 3),
            "scatterGbps": round(total / wall / 1e9, 6),
            "volumeGbps": round(volume_bytes / wall / 1e9, 6),
        }


class ShardSink:
    """One shard's ordered byte stream to wherever placement put it.

    Lifecycle: write(window)* -> finish() -> commit(); abort() on any
    failure; close() is idempotent and aborts anything unfinished, so
    `with` / close-in-finally is always safe (SWFS008)."""

    label = "?"

    def write(self, data) -> None:
        """Append one window (bytes/memoryview).  The buffer may be
        recycled by the caller as soon as write() returns."""
        raise NotImplementedError

    def end_stream(self) -> None:
        """Signal that no more windows are coming, WITHOUT waiting for
        delivery — call this on every sink first, then finish() each:
        all the tail chunks and receiver responses then overlap instead
        of serializing one stream-drain per sink."""

    def finish(self) -> None:
        """End the stream and verify delivery (remote: join the send
        thread, check the receiver's byte count + CRC against the
        sender's running CRC)."""

    def commit(self, mount: bool = False) -> None:
        """Make the shard visible at its final name (remote: the
        receiver's atomic rename, optionally mount-on-commit)."""

    def abort(self) -> None:
        """Tear the stream down and discard anything staged."""

    def close(self) -> None:
        pass

    def __enter__(self) -> "ShardSink":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()


class LocalShardSink(ShardSink):
    """A shard file on this node's disks — the seed's only output.
    `temp=True` (the scatter path) stages to a `.scatter.<id>` sibling
    and renames on commit, matching the remote sink's
    nothing-visible-until-commit contract; `temp=False` keeps the
    seed's write-in-place semantics byte-for-byte."""

    label = "local"

    def __init__(self, path: str, temp: bool = False,
                 stats: "ScatterStats | None" = None):
        self.path = path
        self._final = path
        if temp:
            self.path = f"{path}.scatter.{uuid.uuid4().hex}"
        self._stats = stats
        self.file = open(self.path, "wb")
        self.bytes = 0
        self._committed = False
        self._closed = False

    def write(self, data) -> None:
        self.file.write(data)
        n = len(data)
        self.bytes += n
        if self._stats is not None:
            self._stats.record_local(n)

    def finish(self) -> None:
        # flush only: durability comes from the encode pipeline's
        # _OverlappedFlusher, which covers every local sink's file and
        # fdatasyncs on its final stop — a second sync here would
        # serialize 14 fsyncs after the pipeline already overlapped them
        self.file.flush()

    def commit(self, mount: bool = False) -> None:
        self.file.close()
        self._closed = True
        if self.path != self._final:
            os.replace(self.path, self._final)
        self._committed = True

    def abort(self) -> None:
        if not self._closed:
            self.file.close()
            self._closed = True
        if not self._committed:
            try:
                os.remove(self.path)
            except OSError:
                pass

    def close(self) -> None:
        if self._closed:
            return
        if self._committed:
            self.file.close()
            self._closed = True
        else:
            self.abort()


class _SinkAborted(Exception):
    """The sink was aborted while a stage was parked on its queue."""


class RemoteShardSink(ShardSink):
    """One shard streamed to its placement target as a single long
    chunked `POST /admin/ec/shard_write` — a dedicated send thread per
    destination pulls windows off a bounded queue (backpressure: the
    pipeline's writer stage blocks when a destination falls more than
    `depth` windows behind) with recycled send buffers, so the hot
    loop allocates nothing after warm-up.  The sender keeps a running
    CRC32; finish() verifies the receiver saw the same byte count and
    CRC, commit() performs the receiver-side atomic rename (+ mount)."""

    def __init__(self, url: str, vid: int, sid: int,
                 collection: str = "", headers=None,
                 timeout: float = 600.0, depth: int | None = None,
                 window_bytes: int | None = None):
        self.url = url
        self.vid = vid
        self.sid = sid
        self.collection = collection
        self.label = url
        self.upload_id = uuid.uuid4().hex
        self._headers = headers or (lambda: {})
        self._timeout = timeout
        self._window = window_bytes or scatter_window_bytes()
        depth = depth or scatter_inflight_depth()
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._pool: "queue.Queue" = queue.Queue()
        for _ in range(depth + 1):
            self._pool.put(None)  # lazy-allocated slots
        self._stop = threading.Event()
        self._error: BaseException | None = None
        self._response: dict | None = None
        self._cur: "bytearray | None" = None  # coalescing buffer
        self._fill = 0
        self.bytes = 0
        self.crc = 0
        self._committed = False
        self._finished = False
        self._truncated = False  # armed truncate fault fired mid-send
        self._stats: "ScatterStats | None" = None
        # span context of the caller (the scatter handler): the send
        # thread emits one per-destination stream span, and the
        # contextvar does not follow threading.Thread (tracing.py)
        from ... import tracing
        self._trace_ctx = tracing.current_ids()
        self._t = threading.Thread(target=self._send_loop, daemon=True)
        self._t.start()

    def set_stats(self, stats: "ScatterStats | None") -> None:
        self._stats = stats

    # -- producer side (pipeline writer stage) -------------------------

    def _take_slot(self):
        while True:
            try:
                b = self._pool.get(timeout=0.2)
                return b
            except queue.Empty:
                if self._stop.is_set() or self._error is not None:
                    raise self._error or _SinkAborted() from None

    def _put(self, item) -> None:
        while True:
            try:
                self._q.put(item, timeout=0.2)
                return
            except queue.Full:
                if self._stop.is_set() or self._error is not None:
                    raise self._error or _SinkAborted() from None

    def write(self, data) -> None:
        """COALESCES small writes up to the send window: the encode
        pipeline produces one block-sized slice per work item (1MB on
        the CPU backend), and enqueueing each separately costs a
        queue hop + chunk frame + socket wakeup per MB — batching to
        the window (16MB default) amortizes all three."""
        mv = memoryview(data)
        off = 0
        while off < len(mv):
            if self._error is not None:
                raise self._error
            if self._cur is None:
                b = self._take_slot()
                if b is None or len(b) != self._window:
                    b = bytearray(self._window)
                self._cur = b
                self._fill = 0
            take = min(len(mv) - off, self._window - self._fill)
            piece = mv[off:off + take]
            self._cur[self._fill:self._fill + take] = piece
            self.crc = zlib.crc32(piece, self.crc)
            self.bytes += take
            self._fill += take
            off += take
            if self._fill == self._window:
                self._put((self._cur, self._fill))
                self._cur = None

    def _flush_partial(self) -> None:
        if self._cur is not None and self._fill:
            self._put((self._cur, self._fill))
            self._cur = None
            self._fill = 0

    # -- send thread ----------------------------------------------------

    def _chunks(self):
        """Generator the chunked-POST body pulls from: windows off the
        queue until the None sentinel.  Wire time per window (the gap
        between yields, minus queue wait) is recorded so a slow codec
        never shows up as a slow destination."""
        from ... import faults
        while True:
            try:
                item = self._q.get(timeout=0.2)
            except queue.Empty:
                if self._stop.is_set():
                    raise _SinkAborted() from None
                continue
            if item is None:
                return
            buf, n = item
            # background-priority pacing (qos.py): while foreground
            # request_seconds p99 violates the SLO, each window waits
            # the throttle's pace before touching the wire — the
            # bounded queue backpressures the codec stage behind it
            from ... import qos
            qos.ec_pace("encode")
            directive = faults.fire("ec.encode.window", key=self.url)
            if directive == "truncate":
                # stop mid-shard with CLEAN chunked framing: the
                # receiver banks a short stream, and the commit
                # handshake's byte-count/CRC verify MUST refuse it.
                # _truncated lets the send loop turn the premature end
                # into a dest-attributed error once the response is in
                self._truncated = True
                self._pool.put(buf)
                return
            if directive == "drop":
                # FaultInjected (not plain OSError) so
                # http_stream_request skips its receiver-verdict probe
                # — with both ends alive that probe would block on a
                # receiver still waiting for chunks — and tears the
                # connection down instead
                self._pool.put(buf)
                raise faults.FaultInjected(
                    f"shard_write {self.vid}.{self.sid} -> "
                    f"{self.url}: fault-injected drop")
            t0 = time.perf_counter()
            yield memoryview(buf)[:n]
            if self._stats is not None:
                self._stats.record(self.url, n,
                                   time.perf_counter() - t0)
            self._pool.put(buf)

    def _send_loop(self) -> None:
        from ... import tracing
        from ...server.httpd import http_stream_request
        from ...util.request_id import HEADER as _RID_HEADER
        span_start = time.time()
        t0 = time.perf_counter()
        failed = False
        try:
            headers = dict(self._headers())
            ctx = self._trace_ctx
            if ctx:
                # this thread bypasses the pooled-client funnel, so
                # forward the id/trace headers ourselves — the
                # receiver's shard_write server span must hang under
                # the encode trace, not mint a fresh one
                headers.setdefault(_RID_HEADER, ctx[0])
                headers.setdefault(tracing.HEADER,
                                   f"{ctx[0]}-{ctx[1]}")
            status, body = http_stream_request(
                "POST",
                f"{self.url}/admin/ec/shard_write?volumeId={self.vid}"
                f"&shardId={self.sid}&collection={self.collection}"
                f"&uploadId={self.upload_id}",
                self._chunks(), headers=headers,
                timeout=self._timeout)
            import json
            try:
                self._response = json.loads(body or b"{}")
            except ValueError:
                self._response = {"error": body[:200].decode(
                    errors="replace")}
            if status != 200 or "error" in self._response:
                raise OSError(
                    f"shard_write {self.vid}.{self.sid} -> {self.url}: "
                    f"HTTP {status} {self._response.get('error', '')}")
            if self._truncated:
                # the armed truncation ended the stream early with
                # clean framing; the receiver banked a short upload —
                # surface it as this DESTINATION's failure so the
                # caller aborts (and can re-plan around the dest)
                # instead of discovering the mismatch only at finish()
                raise OSError(
                    f"shard_write {self.vid}.{self.sid} -> {self.url}: "
                    f"stream truncated at "
                    f"{self._response.get('bytes')} bytes")
        except _SinkAborted:
            pass
        except BaseException as e:  # noqa: BLE001 — re-raised by the
            # producer (write/finish); the send thread must never die
            # silently mid-encode
            failed = True
            self._error = e
        finally:
            # unblock a producer parked on a full queue/empty pool
            self._stop.set()
            self._pool.put(None)
            ctx = self._trace_ctx
            tracing.emit_span(
                f"encode.scatter.{self.sid}", span_start,
                time.perf_counter() - t0,
                role=ctx[2] if ctx else "",
                parent=ctx[1] if ctx else "",
                trace_id=ctx[0] if ctx else "",
                attrs={"shard": self.sid, "dest": self.url,
                       "bytes": self.bytes},
                error=failed)

    # -- completion ------------------------------------------------------

    def end_stream(self) -> None:
        if not self._finished:
            self._flush_partial()
            self._put(None)
            self._finished = True

    def finish(self) -> None:
        self.end_stream()
        self._t.join(timeout=self._timeout)
        if self._t.is_alive():
            self._stop.set()
            raise OSError(
                f"shard_write {self.vid}.{self.sid} -> {self.url}: "
                f"send thread stuck past {self._timeout}s")
        if self._error is not None:
            raise self._error
        r = self._response or {}
        if int(r.get("bytes", -1)) != self.bytes or \
                int(r.get("crc32", -1)) != self.crc:
            raise OSError(
                f"shard_write {self.vid}.{self.sid} -> {self.url}: "
                f"receiver saw {r.get('bytes')} bytes crc "
                f"{r.get('crc32')}, sent {self.bytes} crc {self.crc}")

    def mark_committed(self) -> None:
        """The owner committed this shard out-of-band (the scatter
        handler's batched one-round-trip-per-destination
        `shard_write_commit`, the only commit path remote shards have)
        — close() must no longer abort it."""
        self._committed = True

    def abort(self) -> None:
        self._stop.set()
        # drain the queue so a parked producer can't deadlock, then
        # join the (now aborting) send thread
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._pool.put(None)
        self._t.join(timeout=5)
        from ...server.httpd import http_json
        try:
            http_json("POST",
                      f"{self.url}/admin/ec/shard_write_abort",
                      {"volumeId": self.vid,
                       "collection": self.collection,
                       "shardId": self.sid,
                       "uploadId": self.upload_id},
                      timeout=10, headers=self._headers())
        except OSError:
            pass  # receiver also reaps stale temps on its own

    def close(self) -> None:
        if not self._committed:
            self.abort()
