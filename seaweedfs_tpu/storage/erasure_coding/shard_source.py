"""Pluggable rebuild inputs: where survivor shard slices come from.

The streaming distributed rebuild ("Repair Pipelining for Erasure-Coded
Storage", arXiv:1908.01527) replaces the collect-then-rebuild shape —
pull every survivor file whole onto one node, then reconstruct — with a
slice pipeline: each survivor is read in fixed windows through a
ShardSource (a local file today, a ranged `/admin/ec/shard_read` HTTP
stream for remote survivors), one concurrent stream per source with a
bounded prefetch queue, feeding the GF kernel through the same
`_staged_run` triple-buffer the encode path uses.  Repair wall-clock
then overlaps network fetch, the codec, and shard-file writes instead
of serializing d full-file copies through one ingest link (repair
ingest, not the codec, dominates at scale — arXiv:1709.05365).

Memory stays bounded by sources x (prefetch_depth + 1) x slice bytes:
the defaults (8MB slices, depth 2) keep a 10-survivor rebuild under
~¼GB of staged slices.
"""

from __future__ import annotations

import os
import queue
import threading
import time
import urllib.parse


def rebuild_slice_bytes() -> int:
    """Slice window per survivor stream.  8–64MB all work (the GF apply
    is byte-independent so the window never changes output bytes);
    bigger windows amortize per-request overhead, smaller ones bound
    staging RAM.  SEAWEEDFS_TPU_EC_REBUILD_SLICE_MB overrides."""
    try:
        mb = int(os.environ.get("SEAWEEDFS_TPU_EC_REBUILD_SLICE_MB", "8"))
    except ValueError:
        mb = 8
    return max(1, min(mb, 1024)) << 20


def rebuild_prefetch_depth() -> int:
    """Slices queued ahead per survivor stream (>= 2 so the fetch of
    slice k+1 overlaps the codec on slice k even when one source
    hiccups).  SEAWEEDFS_TPU_EC_REBUILD_PREFETCH overrides."""
    try:
        d = int(os.environ.get("SEAWEEDFS_TPU_EC_REBUILD_PREFETCH", "2"))
    except ValueError:
        d = 2
    return max(1, d)


class ShardSource:
    """One survivor shard's byte range reader.  `prefetch` marks
    sources worth a dedicated fetch thread (remote streams); local
    files are read inline by the pipeline's reader stage."""

    prefetch = False
    label = "?"

    def size(self) -> int:
        raise NotImplementedError

    def read_at(self, pos: int, n: int) -> bytes:
        """Bytes [pos, pos+n) of the shard; short only at EOF (the
        rebuild zero-pads short survivors, ec_encoder.go:258-262)."""
        raise NotImplementedError

    def read_into(self, pos: int, n: int, out) -> int:
        """read_at straight into a writable memoryview (the staging
        buffer row) — inline sources skip one bytes alloc + copy per
        window.  Returns bytes filled; short only at EOF."""
        data = self.read_at(pos, n)
        out[:len(data)] = data
        return len(data)

    def iter_slices(self, work: "list[tuple[int, int]]"):
        """Yield the shard's bytes window by window.  Sources with a
        cheaper sequential plan (one long ranged stream instead of a
        request per window) override this."""
        for pos, n in work:
            yield self.read_at(pos, n)

    def close(self) -> None:
        pass


class LocalShardSource(ShardSource):
    """A shard file on this node's disks (the only source the seed's
    collect-then-rebuild path ever had)."""

    label = "local"

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "rb")

    def size(self) -> int:
        return os.path.getsize(self.path)

    def read_at(self, pos: int, n: int) -> bytes:
        self._f.seek(pos)
        return self._f.read(n)

    def read_into(self, pos: int, n: int, out) -> int:
        self._f.seek(pos)
        return self._f.readinto(out[:n])

    def close(self) -> None:
        self._f.close()


class RemoteShardSource(ShardSource):
    """Ranged reads of a survivor mounted on another volume server via
    `/admin/ec/shard_read` (volume_server.proto:101 VolumeEcShardRead)
    with failover across every node that holds the shard.  No whole-file
    pre-copy: slices stream straight into the rebuild pipeline."""

    prefetch = True

    def __init__(self, urls: "list[str]", vid: int, sid: int,
                 headers=None, timeout: float = 60.0):
        if not urls:
            raise ValueError(f"shard {sid}: no source urls")
        self._urls = list(urls)
        self.vid = vid
        self.sid = sid
        self.label = self._urls[0]
        # callable -> auth headers (the owning server's admin creds);
        # the global-config auto-attach covers the default case
        self._headers = headers or (lambda: {})
        self._timeout = timeout
        self._size: int | None = None

    def size(self) -> int:
        if self._size is None:
            from ...server.httpd import http_json
            last = "no urls"
            for url in self._urls:
                try:
                    r = http_json(
                        "GET", f"{url}/admin/ec/info?volumeId={self.vid}",
                        timeout=10, headers=self._headers())
                except OSError as e:
                    last = repr(e)
                    continue
                if "error" not in r:
                    self._size = int(r.get("shardSize", 0))
                    return self._size
                last = r["error"]
            raise OSError(
                f"shard {self.vid}.{self.sid}: size lookup failed on "
                f"{self._urls}: {last}")
        return self._size

    def read_at(self, pos: int, n: int) -> bytes:
        from ...server.httpd import http_bytes
        last = "no urls"
        for url in self._urls:
            try:
                status, body, _ = http_bytes(
                    "GET",
                    f"{url}/admin/ec/shard_read?volumeId={self.vid}"
                    f"&shardId={self.sid}&offset={pos}&size={n}",
                    timeout=self._timeout, headers=self._headers())
            except OSError as e:
                last = repr(e)
                self._count_failover(url)
                continue
            if status == 200 and len(body) <= n:
                # short only at EOF; the pipeline zero-pads
                self.label = url
                return body
            last = f"HTTP {status} ({len(body)} bytes)"
            self._count_failover(url)
        raise OSError(
            f"shard {self.vid}.{self.sid} slice @{pos}+{n}: every "
            f"source failed, last: {last}")

    # -- sequential streaming plan ------------------------------------

    def _open_stream(self, url: str, pos: int, n: int):
        """One ranged GET covering [pos, pos+n); the response is read
        incrementally, so a whole rebuild costs ONE request per source
        (sendfile on the serving side end to end) instead of a request
        per slice — per-request overhead was measured at ~20x the
        loopback wire time of a 1MB slice.  Returns (conn, resp,
        promised) where `promised` is the Content-Length the server
        committed to: fewer delivered bytes mean a dead donor, NOT a
        short shard."""
        import http.client

        from ...server.httpd import _auth_for, _dial
        full, ctx = _dial(url)
        parsed = urllib.parse.urlsplit(full)
        if parsed.scheme == "https":
            conn = http.client.HTTPSConnection(
                parsed.netloc, timeout=self._timeout, context=ctx)
        else:
            conn = http.client.HTTPConnection(parsed.netloc,
                                              timeout=self._timeout)
        conn.request(
            "GET",
            f"/admin/ec/shard_read?volumeId={self.vid}"
            f"&shardId={self.sid}&offset={pos}&size={n}",
            headers=_auth_for(url, self._headers()))
        resp = conn.getresponse()
        if resp.status != 200:
            conn.close()
            raise OSError(f"shard_read {url}: HTTP {resp.status}")
        promised = resp.length if resp.length is not None else n
        return conn, resp, promised

    def iter_slices(self, work: "list[tuple[int, int]]"):
        for buf, got in self.iter_slices_into(
                work, lambda n: bytearray(n)):
            yield bytes(buf[:got]) if buf is not None else b""

    def iter_slices_into(self, work: "list[tuple[int, int]]",
                         take_buf, record=None):
        """Window stream with RECYCLED receive buffers: `take_buf(n)`
        hands back a writable buffer (the fetcher recycles a small
        pool, so the hot loop allocates nothing), each window is
        readinto'd straight off the socket, and (buffer, filled) pairs
        are yielded.  A mid-stream source death resumes at the CURRENT
        window from the next url — already-yielded windows stay
        valid.  `record(label, nbytes, seconds)` is called with the
        time spent on the WIRE only (connect + readinto) — waiting for
        a recycled buffer is consumer backpressure, and billing it as
        fetch latency would make a slow codec look like a slow
        donor."""
        if not work:
            return
        from ... import faults, qos
        end = work[-1][0] + work[-1][1]
        i = 0
        conn = resp = None
        promised = 0  # bytes the current response committed to deliver
        delivered = 0  # bytes consumed from the current response
        eof = False
        failures = 0
        budget = 2 * len(self._urls)
        buf = None  # held across failover retries of the SAME window:
        # taking a fresh pool buffer per retry would strand the old
        # one and starve take_buf into a deadlock
        try:
            while i < len(work):
                pos, n = work[i]
                if eof:
                    yield None, 0
                    i += 1
                    continue
                # background-priority pacing (qos.py): rebuild slice
                # fetches yield to degraded foreground traffic the
                # same way encode window pushes do.  Deliberately
                # outside the `wire` timer — a QoS stall must not be
                # billed as donor latency.
                qos.ec_pace("rebuild")
                wire = 0.0
                if resp is None:
                    url = self._urls[failures % len(self._urls)]
                    t0 = time.perf_counter()
                    try:
                        conn, resp, promised = self._open_stream(
                            url, pos, end - pos)
                    except OSError:
                        failures += 1
                        self._count_failover(url)
                        if failures > budget:
                            raise
                        continue
                    wire += time.perf_counter() - t0
                    delivered = 0
                    self.label = url
                # what THIS response still owes for this window: the
                # Content-Length is the server's commitment, so fewer
                # bytes than `expect` is a dead/truncating donor to
                # fail over from — NOT a short shard to zero-pad
                # (HTTPResponse.readinto reports a premature clean
                # close as plain EOF, never an error)
                expect = min(n, promised - delivered)
                if buf is None:
                    buf = take_buf(n)
                t0 = time.perf_counter()
                try:
                    # armed `ec.rebuild.slice` faults surface HERE so
                    # they ride the real failover machinery: error and
                    # drop read as a dead donor (resume this window
                    # from the next url), truncate as a donor that
                    # closed early with clean framing
                    directive = faults.fire("ec.rebuild.slice",
                                            key=self.label)
                    if directive is not None:
                        raise OSError(
                            f"shard_read {self.label}: fault-injected "
                            f"{directive} mid-stream")
                    got = self._read_exact_into(resp, buf, expect)
                    if got < expect:
                        raise OSError(
                            f"shard_read {self.label}: stream "
                            f"truncated at {delivered + got} of "
                            f"{promised} promised bytes")
                except OSError:
                    conn.close()
                    conn = resp = None
                    failures += 1
                    self._count_failover(self.label)
                    if failures > budget:
                        raise
                    continue
                wire += time.perf_counter() - t0
                delivered += got
                failures = 0  # a delivered window proves the donor
                # set healthy again: the budget bounds consecutive
                # failures, not total blips over a multi-GB stream
                if got < n:
                    eof = True  # short shard: zero-pad from here on
                if record is not None:
                    record(self.label, got, wire)
                yield buf, got
                buf = None  # ownership passed to the consumer
                i += 1
        finally:
            if conn is not None:
                conn.close()

    @staticmethod
    def _count_failover(url: str) -> None:
        from ... import stats
        stats.PROCESS.counter_add(
            "ec_read_source_failovers_total", 1.0,
            help_text="EC reads that abandoned a shard source "
                      "(transport failure, short body, open breaker)",
            peer=url)

    @staticmethod
    def _read_exact_into(resp, buf, n: int) -> int:
        """Fill buf[:n] from the response; short only at EOF."""
        mv = memoryview(buf)
        filled = 0
        while filled < n:
            k = resp.readinto(mv[filled:n])
            if not k:
                break
            filled += k
        return filled


class RebuildStats:
    """Per-rebuild telemetry accumulator: bytes fetched per source,
    slice fetch latencies, wall clock.  Thread-safe (prefetch threads
    record concurrently); summarized once at the end."""

    def __init__(self):
        self._lock = threading.Lock()
        self.bytes_by_source: dict[str, int] = {}
        self.latencies: list[float] = []
        self.slices = 0

    def record(self, label: str, nbytes: int, seconds: float) -> None:
        with self._lock:
            self.bytes_by_source[label] = \
                self.bytes_by_source.get(label, 0) + nbytes
            self.latencies.append(seconds)
            self.slices += 1

    def snapshot(self) -> "tuple[dict[str, int], list[float]]":
        """(bytes by source, latencies) copied under the lock — a
        straggler prefetch thread surviving fetcher.close()'s bounded
        join may still be recording."""
        with self._lock:
            return dict(self.bytes_by_source), list(self.latencies)

    @staticmethod
    def _pct(sorted_vals: "list[float]", q: float) -> float:
        if not sorted_vals:
            return 0.0
        i = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1)))
        return sorted_vals[i]

    def summary(self, volume_bytes: int, wall_seconds: float) -> dict:
        """JSON-able summary; `volume_bytes` is the data_shards x
        shard_size volume-equivalent (how `weed shell` throughput is
        judged everywhere else in this repo)."""
        with self._lock:
            lats = sorted(self.latencies)
            by_source = dict(self.bytes_by_source)
        total = sum(by_source.values())
        wall = max(wall_seconds, 1e-9)
        return {
            "bytesFetchedBySource": by_source,
            "bytesFetchedTotal": total,
            "slices": self.slices,
            "sliceP50Ms": round(self._pct(lats, 0.50) * 1e3, 3),
            "sliceP95Ms": round(self._pct(lats, 0.95) * 1e3, 3),
            "sliceMaxMs": round((lats[-1] if lats else 0.0) * 1e3, 3),
            "wallSeconds": round(wall, 3),
            "fetchGbps": round(total / wall / 1e9, 6),
            "volumeGbps": round(volume_bytes / wall / 1e9, 6),
        }


class _SourceAborted(Exception):
    """The fetcher was closed while a stage was parked on a queue."""


class MultiSourceFetcher:
    """One concurrent slice stream per prefetching source.

    Every source walks the SAME slice schedule (`work`: ordered
    (pos, n) windows).  Prefetching sources get a dedicated thread
    filling a bounded queue `depth` slices ahead; inline sources
    (local files) are read on demand by the consumer.  `get(i, item)`
    must be called in schedule order (the rebuild pipeline's reader
    stage is FIFO) and returns {sid: bytes} for that window.

    A source failure is delivered in-band: the worker parks the
    exception at its queue head and the next `get` re-raises it, so
    the pipeline aborts promptly instead of rebuilding garbage."""

    def __init__(self, sources: "dict[int, ShardSource]",
                 work: "list[tuple[int, int]]",
                 depth: int | None = None,
                 stats: "RebuildStats | None" = None):
        self.sources = sources
        self.work = work
        self.stats = stats
        self._stop = threading.Event()
        self._queues: dict[int, "queue.Queue"] = {}
        self._pools: dict[int, "queue.Queue"] = {}
        self._threads: list[threading.Thread] = []
        # span context of the caller (the rebuild handler): prefetch
        # threads emit one per-source stream span each, and the
        # contextvar does not follow threading.Thread (tracing.py)
        from ... import tracing
        self._trace_ctx = tracing.current_ids()
        depth = depth or rebuild_prefetch_depth()
        for sid, src in sources.items():
            if src.prefetch:
                q: "queue.Queue" = queue.Queue(maxsize=depth)
                pool: "queue.Queue" = queue.Queue()
                for _ in range(depth + 1):  # lazy-allocated slots
                    pool.put(None)
                self._queues[sid] = q
                self._pools[sid] = pool
                t = threading.Thread(target=self._fetch_loop,
                                     args=(src, q, pool, sid),
                                     daemon=True)
                self._threads.append(t)
                t.start()

    def _read(self, src: ShardSource, pos: int, n: int) -> bytes:
        t0 = time.perf_counter()
        data = src.read_at(pos, n)
        if self.stats is not None:
            self.stats.record(src.label, len(data),
                              time.perf_counter() - t0)
        return data

    def _put(self, q: "queue.Queue", item) -> bool:
        while not self._stop.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _fetch_loop(self, src: ShardSource, q: "queue.Queue",
                    pool: "queue.Queue", sid: int = -1) -> None:
        # one span per survivor stream: start at thread launch, finish
        # at stream exhaustion/abort, bytes + final donor url in attrs
        # — trace.show then shows every donor's fetch window next to
        # the codec/write stage windows
        span_start = time.time()
        t0 = time.perf_counter()
        fetched = 0
        failed = False

        def _emit_source_span():
            from ... import tracing
            ctx = self._trace_ctx
            tracing.emit_span(
                f"rebuild.source.{sid}", span_start,
                time.perf_counter() - t0,
                role=ctx[2] if ctx else "",
                parent=ctx[1] if ctx else "",
                trace_id=ctx[0] if ctx else "",
                attrs={"shard": sid, "source": src.label,
                       "bytes": fetched},
                error=failed)

        def take_buf(n: int):
            """Recycle a receive buffer from the pool — the hot loop
            allocates nothing after warm-up (fresh >1MB bytes objects
            are mmap'd and page-fault on every fill)."""
            while True:
                try:
                    b = pool.get(timeout=0.2)
                    break
                except queue.Empty:
                    if self._stop.is_set():
                        raise _SourceAborted() from None
            if b is None or len(b) < n:
                b = bytearray(n)
            return b

        try:
            if hasattr(src, "iter_slices_into"):
                # the source records its own wire-only latency, so
                # take_buf backpressure never shows up as fetch time
                record = self.stats.record if self.stats is not None \
                    else None
                it = src.iter_slices_into(self.work, take_buf,
                                          record=record)
                for buf, got in it:
                    fetched += got
                    if not self._put(q, (buf, got)):
                        return
                return
            it = ((buf, len(buf)) for buf in
                  src.iter_slices(self.work))
            while True:
                t_read = time.perf_counter()
                try:
                    buf, got = next(it)
                except StopIteration:
                    return
                fetched += got
                if self.stats is not None:
                    self.stats.record(src.label, got,
                                      time.perf_counter() - t_read)
                if not self._put(q, (buf, got)):
                    return
        except _SourceAborted:
            pass
        except BaseException as e:  # noqa: BLE001 — re-raised by get()
            failed = True
            self._put(q, e)
        finally:
            _emit_source_span()

    def get(self, item: "tuple[int, int]", rows=None
            ) -> "dict[int, int]":
        """Fill each source's staging row for this window; returns
        {sid: bytes filled}.  `rows` maps sid -> writable memoryview.
        Inline (local) sources read STRAIGHT into their row; queued
        (remote) windows are copied out of the recycled receive buffer
        which is then returned to its pool."""
        pos, n = item
        out: dict[int, int] = {}
        for sid, src in self.sources.items():
            q = self._queues.get(sid)
            row = rows[sid] if rows is not None else None
            if q is None:
                if row is not None:
                    t0 = time.perf_counter()
                    got = src.read_into(pos, n, row)
                    if self.stats is not None:
                        self.stats.record(src.label, got,
                                          time.perf_counter() - t0)
                    out[sid] = got
                else:
                    data = self._read(src, pos, n)
                    out[sid] = len(data)
                continue
            while True:
                try:
                    v = q.get(timeout=0.2)
                    break
                except queue.Empty:
                    if self._stop.is_set():
                        raise _SourceAborted() from None
            if isinstance(v, BaseException):
                raise v
            buf, got = v
            if got and row is not None:
                row[:got] = memoryview(buf)[:got]
            if buf is not None:
                self._pools[sid].put(buf)
            out[sid] = got
        return out

    def close(self) -> None:
        self._stop.set()
        for q in self._queues.values():  # unblock parked producers
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
        for pool in self._pools.values():  # and buffer-starved ones
            pool.put(None)
        for t in self._threads:
            t.join(timeout=5)
        for src in self.sources.values():
            src.close()
