"""Storage engine: needle/volume file formats, indexes, and volumes.

Byte-compatible with the reference's on-disk contracts
(weed/storage/needle, weed/storage/types, weed/storage/super_block,
weed/storage/idx) so volumes written by either implementation are
readable by the other.  Internals are idiomatic Python/numpy — bulk
index parsing is vectorized instead of looped, and the hot data paths
hand off to the JAX/TPU kernels in ops/.
"""

from . import types  # noqa: F401
from .needle import Needle  # noqa: F401
from .super_block import SuperBlock  # noqa: F401
