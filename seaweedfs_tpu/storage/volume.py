"""Append-only needle volume: `.dat` + `.idx` pair.

Mirrors weed/storage/volume.go / volume_write.go / volume_read.go /
volume_vacuum.go semantics: superblock header, cookie-checked writes,
delete-as-appended-tombstone, monotonic AppendAtNs, vacuum via shadow
`.cpd`/`.cpx` + commit rename with compaction-revision bump.  The
file-access locking of the Go implementation collapses to a simple
threading.Lock here (one process, one writer).
"""

from __future__ import annotations

import os
import threading
import time

from ..util.group_commit import CommitBarrier
from . import types
from .needle import Needle, get_actual_size, needle_body_length
from .needle_map import NeedleMap
from .replica_placement import ReplicaPlacement
from .super_block import SuperBlock
from .ttl import EMPTY_TTL, TTL
from .volume_info import VolumeInfo, maybe_load_volume_info, save_volume_info


def walk_dat(path: str):
    """Sequentially yield (needle, actual_offset) for every record in
    a .dat file — live writes AND tombstones, in append order (the
    reference's volume scan used by check/fix tooling,
    storage/volume_checking.go shape).  Records with data are writes;
    zero-data records are delete tombstones (delete_needle appends
    exactly that, and write_needle never maps 0-size needles)."""
    with open(path, "rb") as f:
        sb = SuperBlock.read_from(f)
        version = sb.version
        total = os.fstat(f.fileno()).st_size
        # records start AFTER any superblock extra blob, rounded up
        # to the 8-byte record alignment the append path enforces
        # (_append realigns unaligned tails) — scanning from the
        # fixed 8 bytes on an extra-carrying volume would read
        # garbage "headers" out of the blob (and the fix tool would
        # then replace a healthy .idx with an empty one)
        offset = (sb.block_size() + types.NEEDLE_PADDING_SIZE - 1) \
            // types.NEEDLE_PADDING_SIZE * types.NEEDLE_PADDING_SIZE
        while offset + types.NEEDLE_HEADER_SIZE <= total:
            f.seek(offset)
            header = f.read(types.NEEDLE_HEADER_SIZE)
            if len(header) < types.NEEDLE_HEADER_SIZE:
                break
            n = Needle.parse_header(header)
            # high-bit sizes mark in-place deletions in the reference
            # format (the C++ scanner masks identically,
            # native/volume_tool.cc:244): the record body length uses
            # the LOW 31 bits — feeding the signed int32 into the
            # record math yields a negative length and the offline
            # fix/merge recovery dies on the first deleted record
            deleted_mark = n.size < 0
            masked = n.size
            if deleted_mark:
                masked = 0 if types.size_is_tombstone(n.size) else \
                    types.size_to_u32(n.size) & 0x7FFFFFFF
            rec_len = get_actual_size(masked, version)
            if offset + rec_len > total:
                break                      # truncated tail
            f.seek(offset)
            buf = f.read(rec_len)
            n = Needle.parse_header(buf)
            n.size = masked
            n.parse_body(
                buf[types.NEEDLE_HEADER_SIZE:
                    types.NEEDLE_HEADER_SIZE +
                    needle_body_length(masked, version)],
                version, check_crc=False)
            if deleted_mark:
                # a deleted-marked record is a DELETION wherever it
                # appears in append order: consumers (fix's index
                # replay, merge's last-write-wins fold) key liveness
                # on n.data, so surface it as the zero-data tombstone
                # shape rather than resurrecting the stale payload
                n.data = b""
            yield n, offset
            offset += rec_len


class NeedleNotFound(KeyError):
    pass


class NeedleDeleted(KeyError):
    pass


class CookieMismatch(ValueError):
    pass


class Volume:
    """One volume on disk: <dir>/<collection_prefix><vid>.{dat,idx,vif}."""

    # remap only once the .dat outgrows the read map by this much;
    # smaller fresh tails are served by the handle fallback so a
    # write-then-read workload doesn't pay a remap per append
    MMAP_REMAP_CHUNK = 4 << 20

    def __init__(self, directory: str, volume_id: int, collection: str = "",
                 replica_placement: ReplicaPlacement | None = None,
                 ttl: TTL = EMPTY_TTL,
                 version: int = types.CURRENT_VERSION,
                 mmap_read_mb: int = 0, fsync: bool = False):
        self.dir = directory
        self.id = volume_id
        self.collection = collection
        self.lock = threading.RLock()
        self.last_append_at_ns = 0
        self.read_only = False
        self.is_remote = False
        # -fsync tier (the reference volume server's -fsync flag):
        # every acked write survives POWER LOSS, not just SIGKILL —
        # the group-commit barrier makes this affordable by sharing
        # one fsync across every writer in the commit window
        self.fsync = bool(fsync)
        # dat+idx durability barrier, shared by concurrent writers
        # (group commit): one flush — and one fsync on the -fsync
        # tier — per commit window instead of per needle
        self._barrier = CommitBarrier(self._group_commit_flush,
                                      site="volume.needle")
        # memory-mapped read path (backend/memory_map role, the
        # `-memoryMapMaxSizeMb` flag): needle reads slice the page
        # cache directly instead of seek+read syscalls.  0 disables;
        # volumes larger than the cap fall back to handle reads.
        self.mmap_limit = int(mmap_read_mb) * (1 << 20)
        self._mm = None
        self._mm_f = None
        self._mm_skip = False
        base = self.file_name("")
        dat_path = base + ".dat"
        vi = maybe_load_volume_info(base + ".vif")
        remote = next(
            (f for f in (vi.files if vi else [])
             if f.get("extension", ".dat") == ".dat"), None)
        if remote is not None and not os.path.exists(dat_path):
            # tiered volume: the .dat lives on a remote backend
            # (volume_tier.go LoadRemoteFile); reads go through ranged
            # backend requests, writes are refused
            from .backend import RemoteDatFile, get_backend
            storage = get_backend(remote.get("backendId", "default"))
            self._dat = RemoteDatFile(storage, remote["key"],
                                      int(remote["fileSize"]))
            self.super_block = SuperBlock.read_from(self._dat)
            self.read_only = True
            self.is_remote = True
        elif os.path.exists(dat_path):
            self._dat = open(dat_path, "r+b")
            self.super_block = SuperBlock.read_from(self._dat)
            self._dat.seek(0, os.SEEK_END)
        else:
            self.super_block = SuperBlock(
                version=version,
                replica_placement=replica_placement or ReplicaPlacement(),
                ttl=ttl)
            self._dat = open(dat_path, "w+b")
            self._dat.write(self.super_block.to_bytes())
            self._dat.flush()
        self.nm = NeedleMap(base + ".idx")
        # native write plane attachment (server/write_plane.py): while
        # set, the C++ plane owns the .dat tail — Python appends route
        # through wp.append under the plane's per-volume mutex, and
        # completed native appends drain back into self.nm before any
        # index-dependent operation runs
        self._wp = None
        if not self.is_remote:
            # the .dat is the write-ahead log, the .idx a checkpoint
            # that may trail it (native-plane acks don't wait for the
            # .idx record): replay the unindexed tail so every acked
            # write is reachable after a crash
            self._replay_dat_tail()
        self.volume_info = vi or VolumeInfo(
            version=self.super_block.version,
            replication=str(self.super_block.replica_placement))

    # -- native write plane (server/write_plane.py) ----------------------

    def _replay_dat_tail(self) -> None:
        """Crash recovery for the native-write-plane contract: scan
        .dat records past the .idx checkpoint (the newest indexed PUT)
        and re-apply them to the needle map — a native-acked write is
        durable in the .dat the moment write(2) returned, so the index
        must be reconstructible from it.  Idempotent (re-scanned
        records that already match the map are skipped), and the scan
        stops at the first torn record (CRC/bounds failure): records
        are strictly append-ordered, so nothing valid can follow a
        tear, and an unacked half-write never half-appears."""
        last = self.nm.last_put
        if last is not None:
            start = types.to_actual_offset(last[0]) + \
                get_actual_size(last[1], self.version)
        else:
            start = (self.super_block.block_size() +
                     types.NEEDLE_PADDING_SIZE - 1) // \
                types.NEEDLE_PADDING_SIZE * types.NEEDLE_PADDING_SIZE
        try:
            total = os.path.getsize(self.file_name(".dat"))
        except OSError:
            return
        if start >= total:
            return
        import struct as _struct
        with open(self.file_name(".dat"), "rb") as f:
            offset = start
            while offset + types.NEEDLE_HEADER_SIZE <= total:
                f.seek(offset)
                header = f.read(types.NEEDLE_HEADER_SIZE)
                if len(header) < types.NEEDLE_HEADER_SIZE:
                    break
                n = Needle.parse_header(header)
                deleted_mark = n.size < 0
                masked = n.size
                if deleted_mark:
                    masked = 0 if types.size_is_tombstone(n.size) \
                        else types.size_to_u32(n.size) & 0x7FFFFFFF
                rec_len = get_actual_size(masked, self.version)
                if offset + rec_len > total:
                    break                       # truncated tail
                f.seek(offset)
                buf = f.read(rec_len)
                n = Needle.parse_header(buf)
                n.size = masked
                try:
                    n.parse_body(
                        buf[types.NEEDLE_HEADER_SIZE:
                            types.NEEDLE_HEADER_SIZE +
                            needle_body_length(masked, self.version)],
                        self.version, check_crc=True)
                except (ValueError, _struct.error):
                    break                       # torn record: stop
                if deleted_mark:
                    n.data = b""
                stored = types.to_stored_offset(offset)
                if n.data and types.size_is_valid(n.size):
                    if self.nm._m.get(n.id) != (stored, n.size):
                        self.nm.put(n.id, stored, n.size)
                elif self.nm.get(n.id) is not None:
                    self.nm.delete(n.id)        # tombstone record
                if n.append_at_ns > self.last_append_at_ns:
                    self.last_append_at_ns = n.append_at_ns
                offset += rec_len
        self.nm.flush()  # noqa: SWFS012 — one-time open-path recovery checkpoint

    def attach_native(self, wp) -> bool:
        """Hand the .dat tail to the native write plane.  Returns
        False (and stays detached) for shapes the plane can't own:
        remote/readonly volumes, pre-v3 formats, TTL'd superblocks,
        replicated placements — their write semantics need Python."""
        with self.lock:
            if self._wp is not None:
                return True
            if self.is_remote or self.read_only or \
                    self.version != types.VERSION3 or \
                    bool(self.super_block.ttl) or \
                    self.super_block.replica_placement.byte() or \
                    self.id >= 0x80000000:
                return False
            # the plane appends with its own fd: the buffered tail
            # must be on the file before the plane snapshots it
            self._dat.flush()  # noqa: SWFS012 — one-time attach handoff, not a write ack
            self._dat.seek(0, os.SEEK_END)
            tail = self._dat.tell()
            if not wp.add_volume(self.id, self.file_name(".dat"),
                                 tail, self.last_append_at_ns,
                                 self.fsync):
                return False
            # every key ever mapped (live AND tombstoned) falls back
            # to the Python port: overwrite cookie/dedup semantics
            # stay in one place.  The plane stays DISARMED (404s
            # everything) until the set is complete — arm() closes
            # the mark-window an early native overwrite could slip
            # through.
            wp.mark_keys(self.id, self.nm._m.keys())
            if not wp.arm(self.id):
                wp.remove_volume(self.id)
                return False
            self._wp = wp
            return True

    def detach_native(self) -> None:
        """Take the tail back: stop native appends, then drain every
        completed append into the index so the .idx checkpoint is
        complete before whatever required the detach (compaction,
        readonly freeze, close) proceeds."""
        with self.lock:
            wp = self._wp
            if wp is None:
                return
            self._wp = None
            wp.remove_volume(self.id)
            self._apply_native_entries(wp.drain(self.id))
            self.nm.flush()  # noqa: SWFS012 — detach checkpoint (freeze/compact/close path)

    def drain_native(self) -> list:
        """Apply completed native appends to the in-memory index and
        the .idx checkpoint (the pump thread's tick, and the
        read-your-native-writes hook).  Returns the applied entries so
        the volume server can warm the read plane."""
        wp = self._wp
        if wp is None:
            return []
        with self.lock:
            return self._apply_native_entries(wp.drain(self.id))

    def _drain_if_pending(self) -> None:
        """Index-op prologue (caller holds the lock): make the needle
        map current with every native append completed so far."""
        wp = self._wp
        if wp is not None and wp.pending(self.id):
            self._apply_native_entries(wp.drain(self.id))

    def _apply_native_entries(self, entries: list) -> list:
        for e in entries:
            self.nm.put(e.key, types.to_stored_offset(e.offset),
                        e.size)
            if e.append_ns > self.last_append_at_ns:
                self.last_append_at_ns = e.append_ns
        return entries

    # -- naming (volume.go FileName) -------------------------------------

    def file_name(self, ext: str) -> str:
        name = f"{self.id}{ext}"
        if self.collection:
            name = f"{self.collection}_{name}"
        return os.path.join(self.dir, name)

    @property
    def version(self) -> int:
        return self.super_block.version

    # -- stats -----------------------------------------------------------

    def dat_size(self) -> int:
        with self.lock:
            self._dat.seek(0, os.SEEK_END)
            return self._dat.tell()

    def content_size(self) -> int:
        return self.nm.content_size()

    def file_count(self) -> int:
        return self.nm.metrics.file_count

    def max_file_key(self) -> int:
        """Largest needle id in this volume (volume.go MaxFileKey)."""
        return self.nm.max_key()

    def configure_replication(self, replication: str) -> None:
        """Rewrite the replica placement in the superblock
        (volume_super_block.go MaybeWriteSuperBlock path used by
        VolumeConfigure): the placement byte lives in the .dat header
        and in the cached volume_info."""
        if self.is_remote:
            raise ValueError("cannot configure a remote-tier volume")
        rp = ReplicaPlacement.from_string(replication)
        with self.lock:
            self.super_block.replica_placement = rp
            pos = self._dat.tell()
            self._dat.seek(0)
            self._dat.write(self.super_block.to_bytes())
            self._dat.flush()  # noqa: SWFS012 — rare admin superblock rewrite, not a write ack
            self._dat.seek(pos)
            self.volume_info.replication = str(rp)

    def deleted_count(self) -> int:
        return self.nm.metrics.deleted_count

    def deleted_bytes(self) -> int:
        return self.nm.metrics.deleted_bytes

    def garbage_level(self) -> float:
        """volume_vacuum.go:22 garbageLevel."""
        content = self.content_size()
        if content == 0:
            return 0.0
        return self.deleted_bytes() / content

    # -- write path (volume_write.go:112-218) ----------------------------

    def _next_append_at_ns(self) -> int:
        self.last_append_at_ns = max(time.time_ns(),
                                     self.last_append_at_ns + 1)
        return self.last_append_at_ns

    def write_needle(self, n: Needle, check_cookie: bool = True
                     ) -> tuple[int, int, bool]:
        """Returns (actual_offset, size, is_unchanged).

        Cookie semantics follow doWriteRequest (volume_write.go:141): an
        overwrite must present the existing needle's cookie unless
        check_cookie is False (replication/tail replay), which adopts it.
        """
        # stage decomposition (profiling.py): when the volume server
        # opened a write track for this request, the lock wait, the
        # index lookup+update, the append, and the durability flush
        # each report their own write_stage_seconds cell — no-op
        # context reads otherwise
        from .. import profiling
        with profiling.stage("lock"):
            self.lock.acquire()
        try:
            if self.read_only:
                raise PermissionError(f"volume {self.id} is read-only")
            if not n.has_ttl() and self.super_block.ttl:
                n.set_ttl(self.super_block.ttl)
            self._drain_if_pending()   # read-your-native-writes
            with profiling.stage("index"):
                existing = self.nm.get(n.id)
            if existing is not None:
                old = self._read_at(existing[0], existing[1])
                if old.data == n.data and old.cookie == n.cookie:
                    return types.to_actual_offset(existing[0]), \
                        len(n.data), True
                if n.cookie == 0 and not check_cookie:
                    n.cookie = old.cookie
                if old.cookie != n.cookie:
                    raise CookieMismatch(
                        f"mismatching cookie {n.cookie:x}")
            n.append_at_ns = self._next_append_at_ns()
            with profiling.stage("append"):
                offset = self._append(n)
            if types.size_is_valid(n.size):
                with profiling.stage("index"):
                    self.nm.put(n.id, types.to_stored_offset(offset),
                                n.size)
        finally:
            self.lock.release()
        # ack-after-kernel, GROUP-COMMITTED: the buffered append (and
        # its idx record) must reach the OS before the caller acks the
        # client — a SIGKILLed process must not lose an acknowledged
        # write (needle_write.go acks after pwrite the same way; power
        # loss is the -fsync tier, folded into the same barrier).  The
        # barrier is shared: concurrent writers append under the lock
        # above, then one leader flushes once for the whole window —
        # a single in-flight writer passes straight through.
        with profiling.stage("flush"):
            self._barrier.commit()
        return offset, len(n.data), False

    def _group_commit_flush(self) -> None:
        """The barrier's designated flush helper (one leader at a
        time).  Deliberately lock-free: BufferedRandom/BufferedWriter
        serialize each call internally, so the leader drains the
        buffer WHILE appenders keep appending under the volume lock —
        holding the lock here would stall every writer for the flush
        (and the whole fsync on the -fsync tier).  The one racer that
        can invalidate the handles mid-flush is a compaction/merge
        commit swap; its close() of the OLD handles flushes everything
        buffered, so the process-crash tier is satisfied either way —
        but the -fsync tier's platter promise is not, so on that tier
        the flush re-runs against the NEW handles (commit_compact
        fsyncs the shadows it installs, so the swap itself never
        leaves acked bytes unfsynced).  Any ValueError with the
        handles UNCHANGED is a real defect and must fail the batch,
        not ack it."""
        while True:
            dat, nm = self._dat, self.nm
            try:
                dat.flush()
                nm.flush()
                if self.fsync and not self.is_remote:
                    os.fsync(dat.fileno())
                return
            except ValueError:
                if dat is self._dat and nm is self.nm:
                    raise           # not the swap race: surface it
                if not (self.fsync and not self.is_remote):
                    return          # old handles were flushed by close()
                # -fsync tier: go again on the swapped-in handles

    def _append(self, n: Needle) -> int:
        wp = self._wp
        if wp is not None:
            # the plane owns the tail: route this record through the
            # shared per-volume mutex so it never interleaves with a
            # native HTTP append.  write(2) semantics make the record
            # page-cache durable before return — at least as durable
            # as the buffered path's barrier flush.
            rec = n.to_bytes(self.version)
            off = wp.append(self.id, n.id, rec, n.append_at_ns)
            if off >= 0:
                return off
            # plane refused (pwrite failure / shutdown race): a FULL
            # detach, not just a local flag clear — the plane must
            # stop acking native writes (it still thought it owned
            # the tail) and its journal must drain into the index
            # before Python takes the tail back, or both sides would
            # append at the same offsets
            self.detach_native()
        self._dat.seek(0, os.SEEK_END)
        offset = self._dat.tell()
        if offset % types.NEEDLE_PADDING_SIZE != 0:
            # realign like needle_write.go Append does on corrupt tails
            pad = types.NEEDLE_PADDING_SIZE - (
                offset % types.NEEDLE_PADDING_SIZE)
            self._dat.write(b"\x00" * pad)
            offset += pad
        self._dat.write(n.to_bytes(self.version))
        return offset

    def flush(self) -> None:
        """Flush buffered .dat appends to the OS file so OUT-OF-HANDLE
        readers (the native read plane's fd, sendfile paths) see them;
        the in-process read path shares the buffered handle and never
        needs this.  Near-free when nothing is pending."""
        with self.lock:
            try:
                self._dat.flush()  # noqa: SWFS012 — out-of-handle read visibility (native plane), not a write ack
            except AttributeError:  # tiered RemoteDatFile
                pass

    def delete_needle(self, n: Needle) -> int:
        """Appends a zero-data tombstone record then tombstones the map
        (volume_write.go:222 doDeleteRequest).  Returns freed size."""
        with self.lock:
            if self.read_only:
                raise PermissionError(f"volume {self.id} is read-only")
            self._drain_if_pending()
            existing = self.nm.get(n.id)
            if existing is None:
                return 0
            size = existing[1]
            tomb = Needle(cookie=n.cookie, id=n.id)
            tomb.append_at_ns = self._next_append_at_ns()
            self._append(tomb)
            self.nm.delete(n.id)
        # same ack-after-kernel rule as write_needle, same shared
        # barrier: an acked delete must survive SIGKILL
        self._barrier.commit()
        return size

    # -- read path (volume_read.go:21 readNeedle) ------------------------

    def _read_at(self, stored_offset: int, size: int,
                 check_crc: bool = True) -> Needle:
        offset = types.to_actual_offset(stored_offset)
        length = get_actual_size(size, self.version)
        buf = self._mmap_read(offset, length) \
            if self.mmap_limit else None
        if buf is None:
            self._dat.seek(offset)
            buf = self._dat.read(length)
        return Needle.from_bytes(buf, self.version, expected_size=size,
                                 check_crc=check_crc)

    # -- mmap read path (backend/memory_map analog) ----------------------

    def _mmap_read(self, offset: int, length: int) -> "bytes | None":
        """Serve a read from the mapped .dat, remapping when the file
        has grown past the map; None falls back to the handle read
        (map failed, volume over the cap, or a remote .dat)."""
        if self.is_remote or self._mm_skip:
            return None
        if self._mm is not None and \
                offset + length <= len(self._mm):
            return self._mm[offset:offset + length]
        # read beyond the map (fresh tail) or no map yet.  Remap only
        # when the file has outgrown the map by a real margin —
        # write-then-read workloads would otherwise pay a full
        # drop/open/mmap cycle per appended needle; a small tail is
        # served by the handle fallback with the map intact.
        import mmap as _mmap
        try:
            size = os.path.getsize(self.file_name(".dat"))
        except OSError:
            return None
        if self._mm is not None and \
                size - len(self._mm) < self.MMAP_REMAP_CHUNK:
            return None                # handle read serves the tail
        self._drop_mmap()
        try:
            self._dat.flush()          # appended tail must be mapped
            f = open(self.file_name(".dat"), "rb")
            size = os.fstat(f.fileno()).st_size
            if size > self.mmap_limit or size == 0:
                f.close()
                # the file only grows between .dat swaps: once over
                # the cap, stop paying open+fstat per read
                # (_drop_mmap at swap points clears the skip)
                self._mm_skip = size > self.mmap_limit
                return None
            self._mm_f = f
            self._mm = _mmap.mmap(f.fileno(), 0,
                                  access=_mmap.ACCESS_READ)
        except (OSError, ValueError, AttributeError):
            self._drop_mmap()
            self._mm_skip = True
            return None
        if offset + length > len(self._mm):
            return None                # still beyond: buffered tail
        return self._mm[offset:offset + length]

    def _drop_mmap(self) -> None:
        """The map pins the OLD inode across compaction/merge renames
        — callers that swap the .dat must drop it first."""
        self._mm_skip = False      # re-probe against the new file
        if self._mm is not None:
            try:
                self._mm.close()
            except OSError:
                pass
            self._mm = None
        if self._mm_f is not None:
            try:
                self._mm_f.close()
            except OSError:
                pass
            self._mm_f = None

    def read_needle(self, needle_id: int, cookie: int | None = None
                    ) -> Needle:
        with self.lock:
            self._drain_if_pending()   # read-your-native-writes
            got = self.nm.get(needle_id)
            if got is None:
                raw = self.nm._m.get(needle_id)
                if raw is not None and types.size_is_deleted(raw[1]):
                    raise NeedleDeleted(f"needle {needle_id:x} deleted")
                raise NeedleNotFound(f"needle {needle_id:x} not found")
            n = self._read_at(got[0], got[1])
            if cookie is not None and n.cookie != cookie:
                raise CookieMismatch(
                    f"cookie mismatch for needle {needle_id:x}")
            if n.has_ttl() and n.has_last_modified_date():
                ttl_sec = n.ttl.to_seconds()
                if ttl_sec and n.last_modified + ttl_sec < time.time():
                    raise NeedleNotFound(f"needle {needle_id:x} expired")
            return n

    # -- vacuum (volume_vacuum.go) ---------------------------------------

    def compact(self) -> None:
        """Copy live needles to shadow .cpd/.cpx
        (volume_vacuum.go:53 CompactByVolumeData).

        The bulk copy runs WITHOUT the volume lock — writes keep
        landing in the live .dat/.idx while a multi-GB compaction
        streams — reading through a private handle over a snapshot of
        the needle map.  commit_compact() replays everything appended
        after the snapshot (the reference's makeupDiff,
        volume_vacuum.go:241) before the rename."""
        if self.is_remote:
            raise PermissionError(
                f"volume {self.id} is tiered to a remote backend; "
                f"fetch it back before compacting")
        # the compaction snapshot AND the makeupDiff tail replay read
        # the .idx — a native plane appending past both would lose
        # records in the swap, so the plane gives the tail back first
        # (the volume server re-attaches after commit)
        self.detach_native()
        cpd = self.file_name(".cpd")
        cpx = self.file_name(".cpx")
        with self.lock:
            if getattr(self, "_compacting", False):
                raise RuntimeError(
                    f"volume {self.id} is already compacting")
            self._compacting = True
            # drop shadows left by a crashed previous compaction —
            # NeedleMap would otherwise replay + append after stale
            # entries
            for stale in (cpd, cpx):
                if os.path.exists(stale):
                    os.remove(stale)
            self._dat.flush()  # noqa: SWFS012 — compaction snapshot point (offline maintenance)
            self.nm.flush()  # noqa: SWFS012 — compaction snapshot point (offline maintenance)
            snapshot = sorted(self.nm.items(), key=lambda t: t[1])
            idx_snapshot = os.path.getsize(self.file_name(".idx"))
            dst_sb = SuperBlock(
                version=self.super_block.version,
                replica_placement=self.super_block.replica_placement,
                ttl=self.super_block.ttl,
                compaction_revision=(
                    self.super_block.compaction_revision + 1) & 0xFFFF,
                extra=self.super_block.extra)
        try:
            dst_nm = NeedleMap(cpx)
            with open(self.file_name(".dat"), "rb") as src, \
                    open(cpd, "wb") as dst:
                dst.write(dst_sb.to_bytes())
                # records are 8-byte aligned: an extra blob whose
                # length is not a multiple of 8 would otherwise put
                # every needle at an offset stored offsets (bytes/8)
                # cannot express — silent corruption on read-back
                pad = (-dst.tell()) % types.NEEDLE_PADDING_SIZE
                if pad:
                    dst.write(b"\x00" * pad)
                for key, stored_off, size in snapshot:
                    n = self._read_at_from(src, stored_off, size)
                    new_off = dst.tell()
                    dst.write(n.to_bytes(self.version))
                    dst_nm.put(key, types.to_stored_offset(new_off),
                               size)
            dst_nm.close()
            with self.lock:
                self._idx_snapshot = idx_snapshot
        except BaseException:
            with self.lock:
                self._compacting = False
            raise

    def _makeup_diff(self) -> None:
        """Replay writes/deletes that landed AFTER the compaction
        snapshot onto the shadow files (volume_vacuum.go:241
        makeupDiff).  Caller holds the lock; the live .idx tail past
        the snapshot byte offset is the authoritative diff."""
        from . import idx as idxmod
        idx_snapshot = getattr(self, "_idx_snapshot", None)
        if idx_snapshot is None:
            return
        self._dat.flush()
        self.nm.flush()
        with open(self.file_name(".idx"), "rb") as f:
            f.seek(idx_snapshot)
            tail = f.read()
        self._idx_snapshot = None
        if not tail:
            return
        cpx_nm = NeedleMap(self.file_name(".cpx"))
        with open(self.file_name(".cpd"), "r+b") as dst:
            dst.seek(0, os.SEEK_END)
            for key, off, size in idxmod.walk_index(tail):
                if off == 0 or types.size_is_deleted(size):
                    if cpx_nm.get(key) is not None:
                        cpx_nm.delete(key)
                    continue
                n = self._read_at(off, size)
                new_off = dst.tell()
                dst.write(n.to_bytes(self.version))
                cpx_nm.put(key, types.to_stored_offset(new_off), size)
        cpx_nm.close()

    def commit_compact(self) -> None:
        """makeupDiff replay + rename shadows over the live files and
        reload (volume_vacuum.go:141 CommitCompact)."""
        with self.lock:
            self._makeup_diff()
            if self.fsync:
                # -fsync tier: acked writes are platter-durable in the
                # OLD .dat; the shadows must reach the platter before
                # they REPLACE it or a power cut after the rename
                # could lose them
                for shadow in (self.file_name(".cpd"),
                               self.file_name(".cpx")):
                    with open(shadow, "rb") as f:
                        os.fsync(f.fileno())  # noqa: SWFS012 — compaction commit point
            # AFTER the diff replay (whose _read_at may legitimately
            # use — and recreate — a map of the OLD .dat) and BEFORE
            # the renames: a map surviving the swap would serve
            # old-layout bytes at new-layout offsets
            self._drop_mmap()
            self.nm.close()
            self._dat.close()
            os.replace(self.file_name(".cpd"), self.file_name(".dat"))
            os.replace(self.file_name(".cpx"), self.file_name(".idx"))
            self._dat = open(self.file_name(".dat"), "r+b")
            self.super_block = SuperBlock.read_from(self._dat)
            self._dat.seek(0, os.SEEK_END)
            self.nm = NeedleMap(self.file_name(".idx"))
            self._compacting = False

    def _read_at_from(self, src, stored_offset: int, size: int
                      ) -> Needle:
        """_read_at over a caller-supplied handle (the lock-free
        compaction copy must not share the live handle's seek cursor
        with concurrent writers)."""
        offset = types.to_actual_offset(stored_offset)
        length = get_actual_size(size, self.version)
        src.seek(offset)
        buf = src.read(length)
        return Needle.from_bytes(buf, self.version,
                                 expected_size=size, check_crc=True)

    def vacuum(self) -> None:
        self.compact()
        self.commit_compact()

    def merge_from(self, peer_dat_paths: "list[str]") -> int:
        """volume.merge core (shell/command_volume_merge.go): union
        this volume's records with peer replicas' .dat files in
        AppendAtNs order, last-write-wins per needle (a newer
        tombstone deletes).  Rewrites this volume in place via the
        same shadow + rename dance as compaction.  Returns the merged
        live-needle count.  The volume must be read-only — merging
        under writes would lose the race's loser silently."""
        self.detach_native()   # readonly normally already detached
        with self.lock:
            if not self.read_only:
                raise PermissionError(
                    f"volume {self.id} must be readonly to merge")
            self._dat.flush()  # noqa: SWFS012 — readonly-merge snapshot point (offline maintenance)
        records: list = []   # (append_at_ns, seq, needle)
        seq = 0
        for path in [self.file_name(".dat")] + list(peer_dat_paths):
            for n, _off in walk_dat(path):
                records.append((n.append_at_ns, seq, n))
                seq += 1
        records.sort(key=lambda t: (t[0], t[1]))
        live: dict = {}
        last_ns: dict = {}
        for ns_, _s, n in records:
            if n.append_at_ns and \
                    last_ns.get(n.id) == n.append_at_ns:
                continue                    # duplicate record
            last_ns[n.id] = n.append_at_ns
            if n.data:
                live[n.id] = n
            else:
                live.pop(n.id, None)        # tombstone
        cpd, cpx = self.file_name(".cpd"), self.file_name(".cpx")
        with self.lock:
            self._drop_mmap()      # the map pins the pre-swap inode
            for stale in (cpd, cpx):
                if os.path.exists(stale):
                    os.remove(stale)
            dst_sb = SuperBlock(
                version=self.super_block.version,
                replica_placement=self.super_block.replica_placement,
                ttl=self.super_block.ttl,
                compaction_revision=(
                    self.super_block.compaction_revision + 1) & 0xFFFF,
                extra=self.super_block.extra)
            dst_nm = NeedleMap(cpx)
            with open(cpd, "wb") as dst:
                dst.write(dst_sb.to_bytes())
                pad = (-dst.tell()) % types.NEEDLE_PADDING_SIZE
                if pad:                  # same alignment rule as
                    dst.write(b"\x00" * pad)  # the compact writer
                for _id, n in sorted(
                        live.items(),
                        key=lambda kv: last_ns.get(kv[0], 0)):
                    off = dst.tell()
                    dst.write(n.to_bytes(self.version))
                    dst_nm.put(n.id, types.to_stored_offset(off),
                               n.size)
            dst_nm.close()
            self._idx_snapshot = None   # no diff replay: readonly
            self.nm.close()
            self._dat.close()
            os.replace(cpd, self.file_name(".dat"))
            os.replace(cpx, self.file_name(".idx"))
            self._dat = open(self.file_name(".dat"), "r+b")
            self.super_block = SuperBlock.read_from(self._dat)
            self._dat.seek(0, os.SEEK_END)
            self.nm = NeedleMap(self.file_name(".idx"))
        return len(live)

    # -- scrub (server/volume_grpc_scrub.go analog) -----------------------

    def scrub(self) -> tuple[int, list[str]]:
        """Read + CRC-verify every live needle.  Returns
        (checked_count, errors)."""
        errors: list[str] = []
        count = 0
        with self.lock:  # snapshot keys only; offsets re-resolved fresh
            keys = [k for k, _, _ in self.nm.items()]
        for key in keys:
            count += 1
            try:
                with self.lock:
                    # re-fetch under the lock: a concurrent compaction
                    # commit swaps .dat + needle map, so snapshotted
                    # offsets would read garbage from the new layout
                    got = self.nm.get(key)
                    if got is None:
                        continue  # deleted meanwhile
                    self._read_at(got[0], got[1])
            except Exception as e:  # noqa: BLE001 — collect all
                errors.append(f"needle {key:x}: {e}")
        return count, errors

    # -- lifecycle -------------------------------------------------------

    def sync(self) -> None:
        with self.lock:
            # callers copy/inspect the .idx next: fold undrained
            # native appends into the checkpoint first
            self._drain_if_pending()
            self._dat.flush()  # noqa: SWFS012 — explicit full-volume barrier (copy/admin paths)
            if not self.is_remote:
                os.fsync(self._dat.fileno())  # noqa: SWFS012 — explicit full-volume barrier
            self.nm.flush()  # noqa: SWFS012 — explicit full-volume barrier

    def save_volume_info(self) -> None:
        self.volume_info.version = self.version
        self.volume_info.dat_file_size = self.dat_size()
        save_volume_info(self.file_name(".vif"), self.volume_info)

    def close(self) -> None:
        self.detach_native()
        with self.lock:
            self._drop_mmap()
            self._dat.flush()
            self._dat.close()
            self.nm.close()

    def destroy(self) -> None:
        self.close()
        exts = [".dat", ".idx", ".cpd", ".cpx"]
        if not (os.path.exists(self.file_name(".ecx")) or
                os.path.exists(self.file_name(".ec00"))):
            # the .vif is shared with a live EC conversion of this
            # volume: it records the RS scheme rebuild/decode recover
            # (ec_encoder.scheme_from_vif), so deleting the original
            # volume after ec.encode must leave it for the shards
            exts.append(".vif")
        for ext in exts:
            try:
                os.remove(self.file_name(ext))
            except FileNotFoundError:
                pass
