"""2-byte TTL encoding (weed/storage/needle/volume_ttl.go).

Stored as (count, unit) where unit escalates minute→year; ReadTTL parses
"3m"/"4h"/"5d"/"6w"/"7M"/"8y" (bare numbers mean minutes) and
fit_ttl_count re-normalizes seconds into the largest exact unit < 256.
"""

from __future__ import annotations

from dataclasses import dataclass

UNIT_EMPTY = 0
UNIT_MINUTE = 1
UNIT_HOUR = 2
UNIT_DAY = 3
UNIT_WEEK = 4
UNIT_MONTH = 5
UNIT_YEAR = 6

_UNIT_SECONDS = {
    UNIT_EMPTY: 0,
    UNIT_MINUTE: 60,
    UNIT_HOUR: 3600,
    UNIT_DAY: 24 * 3600,
    UNIT_WEEK: 7 * 24 * 3600,
    UNIT_MONTH: 30 * 24 * 3600,
    UNIT_YEAR: 365 * 24 * 3600,
}

_CHAR_UNIT = {"m": UNIT_MINUTE, "h": UNIT_HOUR, "d": UNIT_DAY,
              "w": UNIT_WEEK, "M": UNIT_MONTH, "y": UNIT_YEAR}
_UNIT_CHAR = {v: k for k, v in _CHAR_UNIT.items()}


@dataclass(frozen=True)
class TTL:
    count: int = 0
    unit: int = UNIT_EMPTY

    def to_seconds(self) -> int:
        return self.count * _UNIT_SECONDS[self.unit]

    def to_bytes(self) -> bytes:
        return bytes([self.count & 0xFF, self.unit & 0xFF])

    def to_u32(self) -> int:
        if self.count == 0:
            return 0
        return (self.count << 8) | self.unit

    def __str__(self) -> str:
        if self.count == 0:
            return ""
        return f"{self.count}{_UNIT_CHAR.get(self.unit, '')}"

    def __bool__(self) -> bool:
        return self.count != 0 and self.unit != UNIT_EMPTY


EMPTY_TTL = TTL()


def load_ttl_from_bytes(b: bytes) -> TTL:
    if b[0] == 0 and b[1] == 0:
        return EMPTY_TTL
    return TTL(b[0], b[1])


def load_ttl_from_u32(v: int) -> TTL:
    return load_ttl_from_bytes(bytes([(v >> 8) & 0xFF, v & 0xFF]))


def read_ttl(s: str) -> TTL:
    """Parse a human TTL string (volume_ttl.go:33 ReadTTL)."""
    if not s:
        return EMPTY_TTL
    unit_char = s[-1]
    if unit_char.isdigit():
        count, unit = int(s), UNIT_MINUTE
    else:
        count, unit = int(s[:-1]), _CHAR_UNIT.get(unit_char, UNIT_EMPTY)
    return fit_ttl_count(count, unit)


def fit_ttl_count(count: int, unit: int) -> TTL:
    """Re-fit seconds into the largest exactly-dividing unit with
    count < 256, else the largest unit that fits (volume_ttl.go:49)."""
    seconds = count * _UNIT_SECONDS[unit]
    if seconds == 0:
        return EMPTY_TTL
    for u in (UNIT_YEAR, UNIT_MONTH, UNIT_WEEK, UNIT_DAY, UNIT_HOUR):
        us = _UNIT_SECONDS[u]
        if seconds % us == 0 and seconds // us < 256:
            return TTL(seconds // us, u)
    if seconds // 60 < 256:
        return TTL(seconds // 60, UNIT_MINUTE)
    for u in (UNIT_HOUR, UNIT_DAY, UNIT_WEEK, UNIT_MONTH, UNIT_YEAR):
        if seconds // _UNIT_SECONDS[u] < 256:
            return TTL(seconds // _UNIT_SECONDS[u], u)
    return EMPTY_TTL
