"""CRC32-Castagnoli needle checksums (weed/storage/needle/crc.go).

Uses the C-accelerated google_crc32c when present; falls back to a
table-driven pure-Python implementation (only hit in stripped-down
environments — the fallback is correct but slow).
"""

from __future__ import annotations

try:
    import google_crc32c

    def crc32c(data: bytes, value: int = 0) -> int:
        return google_crc32c.extend(value, bytes(data))

except ImportError:  # pragma: no cover
    _POLY = 0x82F63B78  # reflected Castagnoli

    _TABLE = []
    for _i in range(256):
        _c = _i
        for _ in range(8):
            _c = (_c >> 1) ^ _POLY if _c & 1 else _c >> 1
        _TABLE.append(_c)

    def crc32c(data: bytes, value: int = 0) -> int:
        c = value ^ 0xFFFFFFFF
        for b in data:
            c = _TABLE[(c ^ b) & 0xFF] ^ (c >> 8)
        return c ^ 0xFFFFFFFF


def crc_value(c: int) -> int:
    """Deprecated legacy .Value() transform kept for pre-3.09 volumes
    (crc.go:25-27): rotl17(c) + 0xa282ead8 mod 2^32."""
    rot = ((c >> 15) | (c << 17)) & 0xFFFFFFFF
    return (rot + 0xA282EAD8) & 0xFFFFFFFF
