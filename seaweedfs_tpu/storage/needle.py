"""Needle: a single stored blob record, byte-compatible with the
reference's v2/v3 on-disk format.

Layout (weed/storage/needle/needle_write_v2.go:11-80 writeNeedleCommon,
needle_write_v3.go:10-16, needle_read.go):

    header:  Cookie(4) NeedleId(8) Size(4)              [16B]
    if Size > 0:
      DataSize(4) Data Flags(1)
      [NameSize(1) Name]       if FlagHasName
      [MimeSize(1) Mime]       if FlagHasMime
      [LastModified(5)]        if FlagHasLastModifiedDate
      [TTL(2)]                 if FlagHasTtl
      [PairsSize(2) Pairs]     if FlagHasPairs
    footer:  CRC32C(4) [AppendAtNs(8) in v3] padding to 8B

Size counts everything between the header and the footer; v1 stored raw
data only and is read- but not write-supported.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from . import types
from .crc import crc32c, crc_value
from .ttl import EMPTY_TTL, TTL, load_ttl_from_bytes

# flags (needle_read.go:15-25)
FLAG_IS_COMPRESSED = 0x01
FLAG_HAS_NAME = 0x02
FLAG_HAS_MIME = 0x04
FLAG_HAS_LAST_MODIFIED_DATE = 0x08
FLAG_HAS_TTL = 0x10
FLAG_HAS_PAIRS = 0x20
FLAG_IS_CHUNK_MANIFEST = 0x80

LAST_MODIFIED_BYTES_LENGTH = 5
TTL_BYTES_LENGTH = 2


class SizeMismatchError(ValueError):
    pass


class CrcError(ValueError):
    pass


def padding_length(needle_size: int, version: int) -> int:
    """needle_read_tail.go:36 — NOTE the reference pads 8 bytes (not 0)
    when already aligned; reproduce exactly."""
    footer = types.NEEDLE_CHECKSUM_SIZE
    if version == types.VERSION3:
        footer += types.TIMESTAMP_SIZE
    return types.NEEDLE_PADDING_SIZE - (
        (types.NEEDLE_HEADER_SIZE + needle_size + footer)
        % types.NEEDLE_PADDING_SIZE)


def needle_body_length(needle_size: int, version: int) -> int:
    footer = types.NEEDLE_CHECKSUM_SIZE
    if version == types.VERSION3:
        footer += types.TIMESTAMP_SIZE
    return needle_size + footer + padding_length(needle_size, version)


def get_actual_size(size: int, version: int) -> int:
    """Total on-disk record size (needle_read.go:286)."""
    return types.NEEDLE_HEADER_SIZE + needle_body_length(size, version)


@dataclass
class Needle:
    """In-memory needle (weed/storage/needle/needle.go:25-45)."""

    cookie: int = 0
    id: int = 0
    size: int = 0            # on-disk Size field (set by serialize/parse)
    data: bytes = b""
    flags: int = 0
    name: bytes = b""
    mime: bytes = b""
    pairs: bytes = b""       # opaque marshaled name/value pairs
    last_modified: int = 0   # unix seconds, 5 bytes on disk
    ttl: TTL = EMPTY_TTL
    checksum: int = 0        # CRC32C of data
    append_at_ns: int = 0    # v3 only
    crc_legacy: bool = False  # parsed from a pre-3.09 volume (crc.Value())

    # -- flag helpers ----------------------------------------------------

    def has_name(self) -> bool:
        return bool(self.flags & FLAG_HAS_NAME)

    def has_mime(self) -> bool:
        return bool(self.flags & FLAG_HAS_MIME)

    def has_last_modified_date(self) -> bool:
        return bool(self.flags & FLAG_HAS_LAST_MODIFIED_DATE)

    def has_ttl(self) -> bool:
        return bool(self.flags & FLAG_HAS_TTL)

    def has_pairs(self) -> bool:
        return bool(self.flags & FLAG_HAS_PAIRS)

    def is_compressed(self) -> bool:
        return bool(self.flags & FLAG_IS_COMPRESSED)

    def is_chunked_manifest(self) -> bool:
        return bool(self.flags & FLAG_IS_CHUNK_MANIFEST)

    def set_name(self, name: bytes) -> None:
        self.name = name[:255]
        self.flags |= FLAG_HAS_NAME

    def set_mime(self, mime: bytes) -> None:
        self.mime = mime
        self.flags |= FLAG_HAS_MIME

    def set_last_modified(self, ts: int) -> None:
        self.last_modified = ts
        self.flags |= FLAG_HAS_LAST_MODIFIED_DATE

    def set_ttl(self, ttl: TTL) -> None:
        self.ttl = ttl
        if ttl:
            self.flags |= FLAG_HAS_TTL

    def set_pairs(self, pairs: bytes) -> None:
        self.pairs = pairs
        self.flags |= FLAG_HAS_PAIRS

    def etag(self) -> str:
        return struct.pack(">I", self.checksum).hex()

    # -- serialization ---------------------------------------------------

    def _body_size(self) -> int:
        """The on-disk Size value (writeNeedleCommon:29-48)."""
        if not self.data:
            return 0
        size = 4 + len(self.data) + 1
        if self.has_name():
            size += 1 + min(len(self.name), 255)
        if self.has_mime():
            size += 1 + len(self.mime)
        if self.has_last_modified_date():
            size += LAST_MODIFIED_BYTES_LENGTH
        if self.has_ttl():
            size += TTL_BYTES_LENGTH
        if self.has_pairs():
            size += 2 + len(self.pairs)
        return size

    def to_bytes(self, version: int = types.CURRENT_VERSION) -> bytes:
        """Serialize the full on-disk record (header..padding)."""
        if version not in (types.VERSION2, types.VERSION3):
            raise ValueError(f"cannot write needle version {version}")
        self.size = self._body_size()
        self.checksum = crc32c(self.data)
        parts = [struct.pack(">IQI", self.cookie, self.id,
                             types.size_to_u32(self.size))]
        if self.data:
            parts.append(struct.pack(">I", len(self.data)))
            parts.append(self.data)
            parts.append(bytes([self.flags]))
            if self.has_name():
                name = self.name[:255]
                parts.append(bytes([len(name)]))
                parts.append(name)
            if self.has_mime():
                parts.append(bytes([len(self.mime)]))
                parts.append(self.mime)
            if self.has_last_modified_date():
                parts.append(struct.pack(">Q", self.last_modified)[
                    8 - LAST_MODIFIED_BYTES_LENGTH:])
            if self.has_ttl():
                parts.append(self.ttl.to_bytes())
            if self.has_pairs():
                parts.append(struct.pack(">H", len(self.pairs)))
                parts.append(self.pairs)
        crc_field = crc_value(self.checksum) if self.crc_legacy \
            else self.checksum
        parts.append(struct.pack(">I", crc_field))
        if version == types.VERSION3:
            parts.append(struct.pack(">Q", self.append_at_ns))
        # Bit-identity quirk: the reference pads from a stale 24-byte
        # scratch buffer (needle_write_v2.go writeNeedleCommon), not with
        # zeros.  v3 padding re-exposes header[12:16] (the big-endian
        # Size field) then zeros.  v2 padding re-exposes header[4:12]:
        # normally the big-endian needle id, but when LastModified was
        # written the Uint64toBytes(header[0:8], ...) scratch write
        # leaves LastModified's low-half in header[4:8].
        pad = padding_length(self.size, version)
        if version == types.VERSION3:
            stale = struct.pack(">I", types.size_to_u32(self.size)) + \
                b"\x00" * 4
        else:
            stale = bytearray(struct.pack(">Q", self.id))
            if self.data and self.has_last_modified_date():
                stale[0:4] = struct.pack(">Q", self.last_modified)[4:8]
        parts.append(bytes(stale[:pad]))
        return b"".join(parts)

    # -- parsing ---------------------------------------------------------

    @classmethod
    def parse_header(cls, buf: bytes) -> "Needle":
        cookie, nid, size_u32 = struct.unpack_from(">IQI", buf, 0)
        n = cls(cookie=cookie, id=nid)
        n.size = types.u32_to_size(size_u32)
        return n

    def parse_body(self, body: bytes, version: int,
                   check_crc: bool = True) -> None:
        """Parse bytes after the 16B header (body includes footer+padding);
        mirrors ReadBytes (needle_read.go:54) for v2/v3."""
        size = self.size
        if version == types.VERSION1:
            self.data = bytes(body[:size])
        else:
            self._parse_body_v2(body[:size])
        tail = body[size:]
        expected = struct.unpack(">I", tail[:4])[0]
        if self.data:
            actual = crc32c(self.data)
            # pre-3.09 volumes stored crc.Value() (needle_read_tail.go:14)
            if check_crc and expected not in (actual, crc_value(actual)):
                raise CrcError(
                    f"needle {self.id:x} CRC mismatch: "
                    f"got {actual:08x}, want {expected:08x}")
            self.crc_legacy = (expected != actual and
                               expected == crc_value(actual))
            self.checksum = actual
        else:
            self.checksum = expected
        if version == types.VERSION3:
            self.append_at_ns = struct.unpack(">Q", tail[4:12])[0]

    def _parse_body_v2(self, b: bytes) -> None:
        idx = 0
        if idx < len(b):
            (data_size,) = struct.unpack_from(">I", b, idx)
            idx += 4
            if data_size + idx > len(b):
                raise ValueError("needle data out of range")
            self.data = bytes(b[idx:idx + data_size])
            idx += data_size
        if idx < len(b):
            self.flags = b[idx]
            idx += 1
        if idx < len(b) and self.has_name():
            name_size = b[idx]
            idx += 1
            self.name = bytes(b[idx:idx + name_size])
            idx += name_size
        if idx < len(b) and self.has_mime():
            mime_size = b[idx]
            idx += 1
            self.mime = bytes(b[idx:idx + mime_size])
            idx += mime_size
        if idx < len(b) and self.has_last_modified_date():
            raw = b"\x00" * 3 + bytes(
                b[idx:idx + LAST_MODIFIED_BYTES_LENGTH])
            self.last_modified = struct.unpack(">Q", raw)[0]
            idx += LAST_MODIFIED_BYTES_LENGTH
        if idx < len(b) and self.has_ttl():
            self.ttl = load_ttl_from_bytes(b[idx:idx + TTL_BYTES_LENGTH])
            idx += TTL_BYTES_LENGTH
        if idx < len(b) and self.has_pairs():
            (pairs_size,) = struct.unpack_from(">H", b, idx)
            idx += 2
            self.pairs = bytes(b[idx:idx + pairs_size])
            idx += pairs_size

    @classmethod
    def from_bytes(cls, buf: bytes, version: int,
                   expected_size: int | None = None,
                   check_crc: bool = True) -> "Needle":
        """Parse one full on-disk record."""
        n = cls.parse_header(buf)
        if expected_size is not None and n.size != expected_size:
            raise SizeMismatchError(
                f"needle {n.id:x}: size {n.size} != expected "
                f"{expected_size}")
        n.parse_body(buf[types.NEEDLE_HEADER_SIZE:
                         types.NEEDLE_HEADER_SIZE +
                         needle_body_length(n.size, version)],
                     version, check_crc=check_crc)
        return n

    def disk_size(self, version: int) -> int:
        return get_actual_size(self.size, version)
