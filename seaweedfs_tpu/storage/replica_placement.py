"""Replica placement "xyz" code (weed/storage/super_block/replica_placement.go).

Encoded as one byte = dc*100 + rack*10 + node: copies on other DCs /
other racks (same DC) / other servers (same rack).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class ReplicaPlacement:
    diff_data_center_count: int = 0
    diff_rack_count: int = 0
    same_rack_count: int = 0

    @classmethod
    def from_string(cls, t: str) -> "ReplicaPlacement":
        t = (t or "").rjust(3, "0")
        if len(t) != 3 or not t.isdigit():
            raise ValueError(f"unknown replication type: {t!r}")
        rp = cls(int(t[0]), int(t[1]), int(t[2]))
        if rp.byte() > 255:
            raise ValueError(f"unexpected replication type: {t!r}")
        return rp

    @classmethod
    def from_byte(cls, b: int) -> "ReplicaPlacement":
        return cls.from_string(f"{b:03d}")

    def byte(self) -> int:
        return (self.diff_data_center_count * 100 +
                self.diff_rack_count * 10 + self.same_rack_count)

    def has_replication(self) -> bool:
        return self.byte() != 0

    def copy_count(self) -> int:
        return (self.diff_data_center_count + self.diff_rack_count +
                self.same_rack_count + 1)

    def __str__(self) -> str:
        return (f"{self.diff_data_center_count}"
                f"{self.diff_rack_count}{self.same_rack_count}")
