""".vif volume-info file (weed/storage/volume_info/volume_info.go).

protojson-encoded VolumeInfo (pb/volume_server.proto:560-575): version,
replication, bytesOffset, datFileSize, expireAtSec, readOnly, and the
optional ecShardConfig that ec.rebuild uses to recover the RS scheme
(ec_encoder.go:77-95).  Implemented as plain JSON with protojson's
camelCase field names and default-omission so files interop with the Go
reader — no protobuf runtime needed for this contract.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

from . import types


@dataclass
class EcShardConfig:
    data_shards: int = 0
    parity_shards: int = 0


@dataclass
class VolumeInfo:
    version: int = types.CURRENT_VERSION
    replication: str = ""
    bytes_offset: int = types.OFFSET_SIZE
    dat_file_size: int = 0
    expire_at_sec: int = 0
    read_only: bool = False
    ec_shard_config: EcShardConfig | None = None
    files: list = field(default_factory=list)  # remote-tier files, opaque

    def to_json(self) -> str:
        # protojson omits default-valued fields; int64 serializes as string
        out: dict = {}
        if self.files:
            out["files"] = self.files
        if self.version:
            out["version"] = self.version
        if self.replication:
            out["replication"] = self.replication
        if self.bytes_offset:
            out["bytesOffset"] = self.bytes_offset
        if self.dat_file_size:
            out["datFileSize"] = str(self.dat_file_size)
        if self.expire_at_sec:
            out["expireAtSec"] = str(self.expire_at_sec)
        if self.read_only:
            out["readOnly"] = True
        if self.ec_shard_config is not None:
            ec = {}
            if self.ec_shard_config.data_shards:
                ec["dataShards"] = self.ec_shard_config.data_shards
            if self.ec_shard_config.parity_shards:
                ec["parityShards"] = self.ec_shard_config.parity_shards
            out["ecShardConfig"] = ec
        return json.dumps(out, indent=2)

    @classmethod
    def from_json(cls, text: str) -> "VolumeInfo":
        d = json.loads(text) if text.strip() else {}
        ec = None
        if "ecShardConfig" in d:
            ecd = d["ecShardConfig"]
            ec = EcShardConfig(int(ecd.get("dataShards", 0)),
                              int(ecd.get("parityShards", 0)))
        return cls(
            version=int(d.get("version", 0)),
            replication=d.get("replication", ""),
            bytes_offset=int(d.get("bytesOffset", 0)),
            dat_file_size=int(d.get("datFileSize", 0)),
            expire_at_sec=int(d.get("expireAtSec", 0)),
            read_only=bool(d.get("readOnly", False)),
            ec_shard_config=ec,
            files=d.get("files", []),
        )


def maybe_load_volume_info(path: str) -> "VolumeInfo | None":
    """Returns None when absent or empty (volume_info.go:16
    MaybeLoadVolumeInfo treats empty files as non-existent)."""
    if not os.path.exists(path):
        return None
    with open(path) as f:
        text = f.read()
    if not text.strip():
        return None
    return VolumeInfo.from_json(text)


def save_volume_info(path: str, vi: VolumeInfo) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(vi.to_json())
    os.replace(tmp, path)
