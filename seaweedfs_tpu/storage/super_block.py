"""8-byte volume superblock (weed/storage/super_block/super_block.go).

Byte 0: version; byte 1: replica placement; bytes 2-3: TTL;
bytes 4-5: compaction revision; bytes 6-7: extra-pb size (optional
protobuf blob follows).  The extra blob is preserved opaquely.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from . import types
from .replica_placement import ReplicaPlacement
from .ttl import EMPTY_TTL, TTL, load_ttl_from_bytes

SUPER_BLOCK_SIZE = 8


@dataclass
class SuperBlock:
    version: int = types.CURRENT_VERSION
    replica_placement: ReplicaPlacement = field(
        default_factory=ReplicaPlacement)
    ttl: TTL = EMPTY_TTL
    compaction_revision: int = 0
    extra: bytes = b""

    def block_size(self) -> int:
        return SUPER_BLOCK_SIZE + len(self.extra)

    def to_bytes(self) -> bytes:
        if len(self.extra) > 256 * 256 - 2:
            raise ValueError("super block extra too large")
        header = struct.pack(
            ">BB2sHH", self.version, self.replica_placement.byte(),
            self.ttl.to_bytes(), self.compaction_revision,
            len(self.extra))
        return header + self.extra

    @classmethod
    def parse(cls, data: bytes, require_extra: bool = True) -> "SuperBlock":
        """Parse a superblock from `data`.  With require_extra=False a
        buffer holding only the 8-byte header is accepted even when it
        advertises an extra blob (callers that only need version/ttl)."""
        if len(data) < SUPER_BLOCK_SIZE:
            raise ValueError("superblock truncated")
        version, rp_byte = data[0], data[1]
        ttl = load_ttl_from_bytes(data[2:4])
        compaction_revision, extra_size = struct.unpack(">HH", data[4:8])
        extra = bytes(data[8:8 + extra_size]) if extra_size else b""
        if extra_size and len(extra) < extra_size:
            if require_extra:
                raise ValueError("superblock extra truncated")
            extra = b""
        return cls(version, ReplicaPlacement.from_byte(rp_byte), ttl,
                   compaction_revision, extra)

    @classmethod
    def read_from(cls, f) -> "SuperBlock":
        f.seek(0)
        head = f.read(SUPER_BLOCK_SIZE)
        extra_size = struct.unpack(">H", head[6:8])[0] \
            if len(head) >= SUPER_BLOCK_SIZE else 0
        return cls.parse(head + (f.read(extra_size) if extra_size else b""))
