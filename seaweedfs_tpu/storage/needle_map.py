"""In-memory needle map: needleId -> (offset, size) per volume, backed by
an append-only .idx file.

The reference's CompactMap (weed/storage/needle_map/compact_map.go) is a
segmented sorted-array map tuned for Go's memory model; in Python a dict
of int -> packed int is both the idiomatic and the fast choice, and the
bulk .idx load is a vectorized numpy pass (storage/idx.py) instead of a
row loop.  Metrics semantics follow weed/storage/needle_map_metric.go:
deletions append a tombstone entry to .idx and subtract live bytes.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from . import idx, types


@dataclass
class MapMetrics:
    file_count: int = 0
    deleted_count: int = 0
    deleted_bytes: int = 0
    maximum_key: int = 0


class NeedleMap:
    """needleId -> (stored_offset, size); size < 0 means deleted."""

    def __init__(self, idx_path: str | None = None):
        self._m: dict[int, tuple[int, int]] = {}
        self.metrics = MapMetrics()
        # the newest PUT entry applied, in .idx append order — the
        # .dat-tail replay floor (storage/volume.py _replay_dat_tail):
        # every record at or before this entry's end is indexed, so
        # crash recovery only scans past it.  Tombstone entries don't
        # advance it (their .idx offset field is 0); the replay's
        # idempotent re-apply absorbs the re-scan.
        self.last_put: "tuple[int, int] | None" = None
        self._idx_path = idx_path
        self._idx_file = None
        if idx_path is not None:
            mode = "r+b" if os.path.exists(idx_path) else "w+b"
            self._idx_file = open(idx_path, mode)
            self._load()

    # -- loading ---------------------------------------------------------

    def _load(self) -> None:
        self._idx_file.seek(0)
        buf = self._idx_file.read()
        arr = idx.parse_index(buf)
        m = self.metrics
        # vectorized metrics; the dict replay preserves last-wins order
        for key, offset, size in zip(arr["key"].tolist(),
                                     arr["offset"].tolist(),
                                     arr["size"].tolist()):
            self._apply(key, offset, size)
        if len(arr):
            m.maximum_key = int(arr["key"].max())
        self._idx_file.seek(0, os.SEEK_END)

    def _apply(self, key: int, offset: int, size: int) -> None:
        m = self.metrics
        if not types.size_is_deleted(size):
            self.last_put = (offset, size)
            old = self._m.get(key)
            # every put counts a file; an overwrite additionally counts
            # the replaced record as deleted (needle_map_metric.go logPut)
            m.file_count += 1
            if old is not None and types.size_is_valid(old[1]):
                m.deleted_count += 1
                m.deleted_bytes += old[1]
            self._m[key] = (offset, size)
        else:
            old = self._m.get(key)
            if old is not None and types.size_is_valid(old[1]):
                m.deleted_count += 1
                m.deleted_bytes += old[1]
            if old is not None:
                # keep the offset so vacuums can find the tombstoned record
                self._m[key] = (old[0], types.TOMBSTONE_FILE_SIZE)

    # -- mutation --------------------------------------------------------

    def put(self, key: int, stored_offset: int, size: int) -> None:
        self._apply(key, stored_offset, size)
        self.metrics.maximum_key = max(self.metrics.maximum_key, key)
        if self._idx_file is not None:
            self._idx_file.write(idx.entry_bytes(key, stored_offset, size))

    def delete(self, key: int) -> bool:
        """Marks deleted; appends a tombstone .idx entry with offset 0
        (needle_map_memory.go Delete appends size TombstoneFileSize)."""
        old = self._m.get(key)
        if old is None or not types.size_is_valid(old[1]):
            return False
        self._apply(key, old[0], types.TOMBSTONE_FILE_SIZE)
        if self._idx_file is not None:
            self._idx_file.write(
                idx.entry_bytes(key, 0, types.TOMBSTONE_FILE_SIZE))
        return True

    # -- lookup ----------------------------------------------------------

    def get(self, key: int) -> tuple[int, int] | None:
        """Returns (stored_offset, size) for live needles, else None."""
        v = self._m.get(key)
        if v is None or not types.size_is_valid(v[1]):
            return None
        return v

    def __contains__(self, key: int) -> bool:
        return self.get(key) is not None

    def __len__(self) -> int:
        return self.metrics.file_count - self.metrics.deleted_count

    def items(self):
        for k, (o, s) in self._m.items():
            if types.size_is_valid(s):
                yield k, o, s

    def max_key(self) -> int:
        """Largest needle id ever mapped (0 when empty) — the
        per-volume input to the master's sequencer fencing
        (master.proto Heartbeat.max_file_key).  Reads the monotonic
        metric (maintained by put() and _load()) rather than scanning
        the dict: the heartbeat thread calls this concurrently with
        writer-thread put()s, and iterating the live dict there would
        race a resize."""
        return self.metrics.maximum_key

    def content_size(self) -> int:
        return sum(s for _, _, s in self.items())

    # -- persistence -----------------------------------------------------

    def flush(self) -> None:
        if self._idx_file is not None:
            self._idx_file.flush()

    def close(self) -> None:
        if self._idx_file is not None:
            self._idx_file.flush()
            self._idx_file.close()
            self._idx_file = None

