"""Core storage types and on-disk scalar encodings.

Mirrors weed/storage/types/needle_types.go and offset_4bytes.go: all
integers are BIG-endian on disk (weed/util/bytes.go:34-74); offsets are
stored divided by the 8-byte needle padding, giving 32GB max volume size
with 4-byte offsets (offset_4bytes.go:14-16).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

# --- sizes (needle_types.go:52-61) -------------------------------------
COOKIE_SIZE = 4
NEEDLE_ID_SIZE = 8
SIZE_SIZE = 4
NEEDLE_HEADER_SIZE = COOKIE_SIZE + NEEDLE_ID_SIZE + SIZE_SIZE  # 16
DATA_SIZE_SIZE = 4
OFFSET_SIZE = 4
NEEDLE_MAP_ENTRY_SIZE = NEEDLE_ID_SIZE + OFFSET_SIZE + SIZE_SIZE  # 16
TIMESTAMP_SIZE = 8
NEEDLE_PADDING_SIZE = 8
NEEDLE_CHECKSUM_SIZE = 4

TOMBSTONE_FILE_SIZE = -1  # Size(-1), needle_types.go:59

# 4-byte offsets x 8-byte padding = 32GB (offset_4bytes.go:14-16)
MAX_POSSIBLE_VOLUME_SIZE = 4 * 1024 * 1024 * 1024 * 8

# --- volume versions (needle/volume_version.go) ------------------------
VERSION1 = 1
VERSION2 = 2
VERSION3 = 3
CURRENT_VERSION = VERSION3


# --- Size semantics (needle_types.go:17-46) ----------------------------

def size_is_tombstone(size: int) -> bool:
    return size == TOMBSTONE_FILE_SIZE


def size_is_deleted(size: int) -> bool:
    """Negative or tombstone == deleted; 0 is anomalous-but-active."""
    return size < 0 or size == TOMBSTONE_FILE_SIZE


def size_is_valid(size: int) -> bool:
    return size > 0 and size != TOMBSTONE_FILE_SIZE


def size_raw(size: int) -> int:
    if size == TOMBSTONE_FILE_SIZE:
        return 0
    return -size if size < 0 else size


def size_to_u32(size: int) -> int:
    """Size is an int32 stored as uint32 on disk."""
    return size & 0xFFFFFFFF


def u32_to_size(v: int) -> int:
    return v - (1 << 32) if v >= (1 << 31) else v


# --- offset encoding (offset_4bytes.go) --------------------------------

def to_stored_offset(actual_offset: int) -> int:
    """Byte offset -> stored unit (divided by padding)."""
    return actual_offset // NEEDLE_PADDING_SIZE


def to_actual_offset(stored_offset: int) -> int:
    return stored_offset * NEEDLE_PADDING_SIZE


# --- file ids (needle/file_id.go, needle.go:153) -----------------------

@dataclass(frozen=True)
class FileId:
    """volumeId,needleId+cookie — e.g. "3,01637037d6"."""

    volume_id: int
    key: int
    cookie: int

    def __str__(self) -> str:
        return f"{self.volume_id},{format_needle_id_cookie(self.key, self.cookie)}"


def format_needle_id_cookie(key: int, cookie: int) -> str:
    """Hex needle id (leading zero bytes dropped) + 8-hex-digit cookie
    (needle/file_id.go formatNeedleIdCookie)."""
    kb = struct.pack(">Q", key).lstrip(b"\x00") or b""
    return kb.hex() + struct.pack(">I", cookie).hex()


def parse_needle_id_cookie(s: str) -> tuple[int, int]:
    """Parse "<hexkey><8-hex cookie>" (needle/needle.go:153
    ParseNeedleIdCookie)."""
    if len(s) <= 8:
        raise ValueError(f"key-cookie string too short: {s!r}")
    if len(s) % 2 == 1:
        s = "0" + s
    key = int(s[:-8], 16)
    cookie = int(s[-8:], 16)
    return key, cookie


def parse_file_id(fid: str) -> FileId:
    """Parse "vid,keycookie" (split at first ','; file_id.go
    ParseFileIdFromString)."""
    comma = fid.find(",")
    if comma <= 0:
        raise ValueError(f"invalid file id {fid!r}")
    vid = int(fid[:comma])
    key, cookie = parse_needle_id_cookie(fid[comma + 1:])
    return FileId(vid, key, cookie)
