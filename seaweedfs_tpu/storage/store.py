"""Store: the per-volume-server storage manager
(weed/storage/store.go, disk_location.go).

Owns one or more disk locations (one per -dir), loads/creates volumes
and mounted EC shards, routes needle reads/writes by volume id, and
assembles the heartbeat snapshot the master consumes.
"""

from __future__ import annotations

import glob
import os
import re
import threading

from . import types
from .erasure_coding import ECContext, EcVolume
from .erasure_coding.ec_context import to_ext
from .needle import Needle
from .replica_placement import ReplicaPlacement
from .ttl import EMPTY_TTL, read_ttl
from .volume import Volume

_VOL_RE = re.compile(r"^(?:(?P<col>.+)_)?(?P<vid>\d+)\.dat$")
_VIF_RE = re.compile(r"^(?:(?P<col>.+)_)?(?P<vid>\d+)\.vif$")
_EC_RE = re.compile(r"^(?:(?P<col>.+)_)?(?P<vid>\d+)\.ec00$")


def _vif_is_remote(vif_path: str) -> bool:
    """True when the .vif records a remote-tiered .dat
    (storage/volume_tier.go: files[] carries the backend copy)."""
    from .volume_info import maybe_load_volume_info
    try:
        vi = maybe_load_volume_info(vif_path)
    except ValueError:
        return False
    return bool(vi and vi.files)


# process-wide mmap read cap in MB (backend/memory_map role, the
# volume server's -memoryMapMaxSizeMb flag); 0 disables.  Set by the
# CLI before Store construction.
MMAP_READ_MB = 0


class DiskLocation:
    """One storage directory (weed/storage/disk_location.go)."""

    def __init__(self, directory: str, max_volume_count: int = 8,
                 index_directory: str | None = None,
                 fsync: bool = False):
        self.directory = os.path.abspath(directory)
        self.index_directory = index_directory or self.directory
        self.max_volume_count = max_volume_count
        self.fsync = fsync
        self.volumes: dict[int, Volume] = {}
        self.ec_volumes: dict[int, EcVolume] = {}
        os.makedirs(self.directory, exist_ok=True)

    def load_existing(self) -> None:
        for path in glob.glob(os.path.join(self.directory, "*.dat")):
            m = _VOL_RE.match(os.path.basename(path))
            if not m:
                continue
            vid = int(m.group("vid"))
            self.volumes[vid] = Volume(
                self.directory, vid, collection=m.group("col") or "",
                mmap_read_mb=MMAP_READ_MB, fsync=self.fsync)
        # tiered volumes have no local .dat; their .vif names the
        # remote copy (volume_tier.go)
        for path in glob.glob(os.path.join(self.directory, "*.vif")):
            m = _VIF_RE.match(os.path.basename(path))
            if not m:
                continue
            vid = int(m.group("vid"))
            if vid in self.volumes or not _vif_is_remote(path):
                continue
            try:
                self.volumes[vid] = Volume(
                    self.directory, vid,
                    collection=m.group("col") or "")   # remote: no mmap
            except KeyError as e:
                # backend not configured on this server: the tiered
                # volume is unavailable, but one bad .vif must not
                # abort startup and take every healthy volume with it
                import sys
                print(f"volume {vid}: cannot open tiered volume: {e} "
                      f"(start with -tierBackend)", file=sys.stderr)
        for path in glob.glob(os.path.join(self.directory, "*.ec00")):
            m = _EC_RE.match(os.path.basename(path))
            if not m:
                continue
            vid = int(m.group("vid"))
            self.ec_volumes[vid] = EcVolume(
                self.directory, vid, collection=m.group("col") or "")


class Store:
    """storage/store.go:88 NewStore."""

    def __init__(self, directories: list[str], ip: str = "localhost",
                 port: int = 0, public_url: str = "",
                 fsync: bool = False):
        self.ip = ip
        self.port = port
        self.public_url = public_url or f"{ip}:{port}"
        # -fsync: every volume's group-commit barrier also fsyncs (the
        # power-loss durability tier, one fsync per commit window)
        self.fsync = fsync
        self.locations = [DiskLocation(d, fsync=fsync)
                          for d in directories]
        self.lock = threading.RLock()
        for loc in self.locations:
            loc.load_existing()

    # -- volume lookup ----------------------------------------------------

    def find_volume(self, vid: int) -> Volume | None:
        for loc in self.locations:
            v = loc.volumes.get(vid)
            if v is not None:
                return v
        return None

    def find_ec_volume(self, vid: int) -> EcVolume | None:
        for loc in self.locations:
            ev = loc.ec_volumes.get(vid)
            if ev is not None:
                return ev
        return None

    def _location_for_new_volume(self) -> DiskLocation:
        best, slack = None, -1
        for loc in self.locations:
            s = loc.max_volume_count - len(loc.volumes)
            if s > slack:
                best, slack = loc, s
        if best is None:
            raise RuntimeError("no disk locations")
        return best

    # -- volume admin -----------------------------------------------------

    def add_volume(self, vid: int, collection: str = "",
                   replication: str = "", ttl: str = "") -> Volume:
        with self.lock:
            if self.find_volume(vid) is not None:
                raise ValueError(f"volume {vid} already exists")
            loc = self._location_for_new_volume()
            v = Volume(
                loc.directory, vid, collection=collection,
                replica_placement=ReplicaPlacement.from_string(replication),
                ttl=read_ttl(ttl) if ttl else EMPTY_TTL,
                mmap_read_mb=MMAP_READ_MB, fsync=loc.fsync)
            loc.volumes[vid] = v
            return v

    def delete_volume(self, vid: int) -> None:
        with self.lock:
            for loc in self.locations:
                v = loc.volumes.pop(vid, None)
                if v is not None:
                    v.destroy()
                    return
            raise KeyError(f"volume {vid} not found")

    def unmount_volume(self, vid: int) -> None:
        with self.lock:
            for loc in self.locations:
                v = loc.volumes.pop(vid, None)
                if v is not None:
                    v.close()
                    return
            raise KeyError(f"volume {vid} not found")

    def mount_volume(self, vid: int, collection: str = "") -> Volume:
        with self.lock:
            for loc in self.locations:
                name = (f"{collection}_" if collection else "") + \
                    f"{vid}"
                base = os.path.join(loc.directory, name)
                # a tiered volume has no local .dat — its .vif names
                # the remote copy (storage/volume_tier.go)
                if os.path.exists(base + ".dat") or \
                        _vif_is_remote(base + ".vif"):
                    v = Volume(loc.directory, vid,
                               collection=collection,
                               mmap_read_mb=MMAP_READ_MB,
                               fsync=loc.fsync)
                    loc.volumes[vid] = v
                    return v
            raise KeyError(f"volume {vid} files not found")

    def set_volume_read_only(self, vid: int, read_only: bool) -> None:
        v = self.find_volume(vid)
        if v is None:
            raise KeyError(f"volume {vid} not found")
        if read_only:
            # freeze means freeze: the native write plane must stop
            # acking appends the Python side would now refuse (the
            # volume server re-attaches on un-freeze via its
            # eligibility sync)
            v.detach_native()
        v.read_only = read_only

    # -- needle IO (store.go:580/:604) ------------------------------------

    def write_needle(self, vid: int, n: Needle,
                     check_cookie: bool = True) -> tuple[int, bool]:
        v = self.find_volume(vid)
        if v is None:
            raise KeyError(f"volume {vid} not found")
        _, size, unchanged = v.write_needle(n, check_cookie=check_cookie)
        return size, unchanged

    def read_needle(self, vid: int, needle_id: int,
                    cookie: int | None = None, ec_reader=None) -> Needle:
        """store.go:604 ReadVolumeNeedle.  For EC volumes, `ec_reader`
        (server/store_ec.EcReader) enables scatter/degraded resolution;
        without it only locally-complete needles are readable."""
        v = self.find_volume(vid)
        if v is not None:
            return v.read_needle(needle_id, cookie=cookie)
        ev = self.find_ec_volume(vid)
        if ev is not None:
            if ec_reader is not None:
                return ec_reader.read_needle(ev, needle_id, cookie=cookie)
            return ev.read_needle_local(needle_id, cookie=cookie)
        raise KeyError(f"volume {vid} not found")

    def delete_needle(self, vid: int, n: Needle) -> int:
        v = self.find_volume(vid)
        if v is not None:
            return v.delete_needle(n)
        ev = self.find_ec_volume(vid)
        if ev is not None:
            ev.delete_needle(n.id)
            return 0
        raise KeyError(f"volume {vid} not found")

    # -- EC shard admin (store_ec.go) -------------------------------------

    def mount_ec_shards(self, vid: int, collection: str,
                        shard_ids: list[int]) -> EcVolume:
        """Open an EcVolume over locally-present shard files
        (store_ec.go MountEcShards equivalent)."""
        with self.lock:
            ev = self.find_ec_volume(vid)
            if ev is not None:
                ev.close()
            for loc in self.locations:
                base = os.path.join(
                    loc.directory,
                    (f"{collection}_" if collection else "") + str(vid))
                if any(os.path.exists(base + to_ext(s))
                       for s in (shard_ids or range(32))):
                    ev = EcVolume(loc.directory, vid, collection=collection)
                    loc.ec_volumes[vid] = ev
                    return ev
            raise KeyError(f"no local shards for volume {vid}")

    def unmount_ec_shards(self, vid: int,
                          shard_ids: "list[int] | None" = None) -> None:
        """Unmount EC shards of `vid`.  shard_ids=None unmounts the
        whole EC volume (internal full-unmount callers); an EMPTY list
        is a no-op, matching the reference servicer which only loops
        over req.ShardIds (volume_grpc_erasure_coding.go:463-481) — a
        reference-compatible tool sending no ids must not take every
        shard offline.  A non-empty subset closes only those shards:
        a balance unmounting one migrated shard must not take the
        node's other shards of that volume offline."""
        if shard_ids is not None and not shard_ids:
            return
        with self.lock:
            for loc in self.locations:
                ev = loc.ec_volumes.get(vid)
                if ev is None:
                    continue
                if shard_ids is None:
                    loc.ec_volumes.pop(vid).close()
                    return
                for sid in shard_ids:
                    shard = ev.shards.pop(int(sid), None)
                    if shard is not None:
                        shard.close()
                if not ev.shards:
                    loc.ec_volumes.pop(vid).close()
                return

    # -- heartbeat (store.go:371 CollectHeartbeat) ------------------------

    def collect_heartbeat(self) -> dict:
        volumes = []
        ec_shards = []
        max_volume_count = 0
        max_file_key = 0
        for loc in self.locations:
            max_volume_count += loc.max_volume_count
            for vid, v in loc.volumes.items():
                max_file_key = max(max_file_key, v.max_file_key())
                volumes.append({
                    "id": vid,
                    "collection": v.collection,
                    "size": v.dat_size(),
                    "fileCount": v.file_count(),
                    "deleteCount": v.deleted_count(),
                    "deletedByteCount": v.deleted_bytes(),
                    "readOnly": v.read_only,
                    "replicaPlacement":
                        v.super_block.replica_placement.byte(),
                    "ttl": v.super_block.ttl.to_u32(),
                    "version": v.version,
                    # master.proto VolumeInformationMessage
                    # remote_storage_name (field 21) role: lets
                    # volume.tier.compact select tiered volumes
                    "remoteTiered": v.is_remote,
                })
            for vid, ev in loc.ec_volumes.items():
                ec_shards.append({
                    "id": vid,
                    "collection": ev.collection,
                    "ecIndexBits": sum(1 << s for s in ev.shard_ids),
                    "dataShards": ev.ctx.data_shards,
                    "parityShards": ev.ctx.parity_shards,
                })
        return {
            "ip": self.ip,
            "port": self.port,
            "publicUrl": self.public_url,
            "maxVolumeCount": max_volume_count,
            # sequencer fencing input (master.proto Heartbeat
            # max_file_key field 5): a new leader floors its file-id
            # sequence above every key any volume server has stored
            "maxFileKey": max_file_key,
            "volumes": volumes,
            "ecShards": ec_shards,
        }

    def close(self) -> None:
        for loc in self.locations:
            for v in loc.volumes.values():
                v.close()
            for ev in loc.ec_volumes.values():
                ev.close()
