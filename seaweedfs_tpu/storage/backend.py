"""Pluggable `.dat` storage backends (weed/storage/backend/backend.go
BackendStorageFile + s3_backend/s3_backend.go).

A tiered volume's `.dat` lives as ONE object in an S3-compatible store;
local needle reads become ranged GETs.  The reference's own test trick
is pointing the S3 backend at seaweedfs' own gateway — ours does the
same (tests tier volumes onto the in-repo S3ApiServer).

The active backends are a process-level registry configured like the
reference's `[storage.backend.s3.default]` master.toml section
(backend.go LoadConfiguration): `configure_s3_backend("default", ...)`
then `.vif` files entries reference the backend by id.
"""

from __future__ import annotations

import threading
import urllib.parse

from ..server.httpd import http_bytes


class S3BackendStorage:
    """One named S3 target (s3_backend.go S3BackendStorage)."""

    def __init__(self, backend_id: str, endpoint: str, bucket: str,
                 access_key: str = "", secret_key: str = ""):
        self.id = backend_id
        self.endpoint = endpoint  # host:port of an S3-compatible API
        self.bucket = bucket
        self.access_key = access_key
        self.secret_key = secret_key

    # -- request plumbing -------------------------------------------------

    def _request(self, method: str, key: str, body: bytes | None = None,
                 extra_headers: dict | None = None,
                 query: dict | None = None
                 ) -> "tuple[int, bytes, dict]":
        path = f"/{self.bucket}/{key}"
        query = query or {}
        headers: dict = {}
        if self.access_key:
            from ..s3.auth import sign_request
            headers = sign_request(method, self.endpoint, path, query,
                                   {}, body or b"", self.access_key,
                                   self.secret_key)
        # Range is not a signed-header class in SigV4 — attach after
        headers.update(extra_headers or {})
        qs = urllib.parse.urlencode(query)
        url = self.endpoint + urllib.parse.quote(path) + \
            (f"?{qs}" if qs else "")
        return http_bytes(method, url, body, headers)

    def ensure_bucket(self) -> None:
        st, resp, _ = self._request("PUT", "")
        if st >= 300 and st != 409:  # 409: already exists
            raise RuntimeError(
                f"s3 backend {self.id}: create bucket "
                f"{self.bucket}: {st} {resp[:200]!r}")

    def upload(self, local_path: str, key: str,
               chunk_size: int = 64 * 1024 * 1024) -> int:
        """Upload a file, streaming in chunks so a multi-GB volume
        .dat never sits whole in RSS (s3_backend.go uses the SDK's
        multipart uploader for the same reason)."""
        import os
        size = os.path.getsize(local_path)
        if size <= chunk_size:
            with open(local_path, "rb") as f:
                data = f.read()
            st, resp, _ = self._request("PUT", key, data)
            if st >= 300:
                raise RuntimeError(
                    f"s3 backend {self.id}: upload {key}: "
                    f"{st} {resp[:200]!r}")
            return size
        # S3 multipart: initiate -> per-chunk UploadPart -> complete
        st, resp, _ = self._request("POST", key,
                                    query={"uploads": ""})
        if st >= 300:
            raise RuntimeError(f"s3 backend {self.id}: initiate "
                               f"multipart {key}: {st}")
        import re
        m = re.search(rb"<UploadId>([^<]+)</UploadId>", resp)
        if not m:
            raise RuntimeError("no UploadId in initiate response")
        upload_id = m.group(1).decode()
        part_xml = []
        with open(local_path, "rb") as f:
            part = 1
            while True:
                chunk = f.read(chunk_size)
                if not chunk:
                    break
                st, resp, _ = self._request(
                    "PUT", key, chunk,
                    query={"partNumber": str(part),
                           "uploadId": upload_id})
                if st >= 300:
                    raise RuntimeError(
                        f"s3 backend {self.id}: part {part}: {st}")
                part_xml.append(f"<Part><PartNumber>{part}"
                                f"</PartNumber></Part>")
                part += 1
        body = ("<CompleteMultipartUpload>" + "".join(part_xml) +
                "</CompleteMultipartUpload>").encode()
        st, resp, _ = self._request("POST", key, body,
                                    query={"uploadId": upload_id})
        if st >= 300:
            raise RuntimeError(f"s3 backend {self.id}: complete "
                               f"multipart {key}: {st}")
        return size

    def put_bytes(self, key: str, data: bytes) -> None:
        """Single-request PUT for in-memory payloads (sink objects,
        manifests); bulk volume files go through `upload`."""
        st, resp, _ = self._request("PUT", key, data)
        if st >= 300:
            raise RuntimeError(
                f"s3 backend {self.id}: put {key}: "
                f"{st} {resp[:200]!r}")

    def download(self, key: str, local_path: str,
                 chunk_size: int = 64 * 1024 * 1024) -> int:
        """Ranged-GET the object in chunks straight to disk (constant
        memory for multi-GB volumes)."""
        import os
        size = self.size_of(key)
        tmp = local_path + ".tmp"
        with open(tmp, "wb") as f:
            pos = 0
            while pos < size:
                n = min(chunk_size, size - pos)
                f.write(self.read_range(key, pos, n))
                pos += n
        os.replace(tmp, local_path)
        return size

    def size_of(self, key: str) -> int:
        st, _, hdrs = self._request("HEAD", key)
        if st != 200:
            raise RuntimeError(f"s3 backend {self.id}: head {key}: "
                               f"{st}")
        return int(hdrs.get("Content-Length", 0))

    def delete(self, key: str) -> None:
        self._request("DELETE", key)

    def read_range(self, key: str, offset: int, size: int) -> bytes:
        st, data, _ = self._request(
            "GET", key, extra_headers={
                "Range": f"bytes={offset}-{offset + size - 1}"})
        if st not in (200, 206):
            raise RuntimeError(f"s3 backend {self.id}: ranged read "
                               f"{key}@{offset}+{size}: {st}")
        if st == 200:  # server ignored Range: slice locally
            data = data[offset:offset + size]
        return data


class RemoteDatFile:
    """File-like adapter over a remote `.dat` object so the Volume read
    path (seek/read/tell) works unchanged on a tiered volume
    (backend.go BackendStorageFile ReadAt)."""

    def __init__(self, storage: S3BackendStorage, key: str, size: int):
        self._storage = storage
        self._key = key
        self._size = size
        self._pos = 0
        self._lock = threading.Lock()

    def seek(self, offset: int, whence: int = 0) -> int:
        with self._lock:
            if whence == 0:
                self._pos = offset
            elif whence == 1:
                self._pos += offset
            else:
                self._pos = self._size + offset
            return self._pos

    def tell(self) -> int:
        with self._lock:
            return self._pos

    def read(self, n: int = -1) -> bytes:
        with self._lock:
            if n < 0:
                n = self._size - self._pos
            n = max(0, min(n, self._size - self._pos))
            if n == 0:
                return b""
            data = self._storage.read_range(self._key, self._pos, n)
            self._pos += len(data)
            return data

    def flush(self) -> None:  # read-only: nothing to flush
        pass

    def close(self) -> None:
        pass

    def write(self, data: bytes) -> int:
        raise PermissionError("tiered volume .dat is read-only "
                              "(volume.tier.move'd to "
                              f"{self._storage.id})")


# -- registry (backend.go LoadConfiguration) ------------------------------

_REGISTRY: dict[str, S3BackendStorage] = {}
_REG_LOCK = threading.Lock()


def configure_s3_backend(backend_id: str, endpoint: str, bucket: str,
                         access_key: str = "", secret_key: str = ""
                         ) -> S3BackendStorage:
    s = S3BackendStorage(backend_id, endpoint, bucket, access_key,
                         secret_key)
    with _REG_LOCK:
        _REGISTRY[backend_id] = s
    return s


def get_backend(backend_id: str) -> S3BackendStorage:
    with _REG_LOCK:
        s = _REGISTRY.get(backend_id)
    if s is None:
        raise KeyError(
            f"s3 backend {backend_id!r} not configured on this server "
            f"(configure_s3_backend / -tierBackend)")
    return s
