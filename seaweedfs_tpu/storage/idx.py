""".idx needle-index file codec (weed/storage/idx/walk.go).

16-byte big-endian entries: NeedleId(8) + StoredOffset(4) + Size(4).
Instead of the reference's sequential 1024-row walker, parsing is
vectorized: the whole file maps to a numpy structured view in one shot
(idiomatic for our stack, and orders of magnitude faster in Python).
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

from . import types

_DTYPE = np.dtype([("key", ">u8"), ("offset", ">u4"), ("size", ">i4")])


def parse_index(buf: bytes) -> np.ndarray:
    """Parse .idx bytes -> structured array with fields key/offset/size.
    offset is in stored units (multiply by 8 for bytes); size is int32
    with tombstone/deleted semantics (types.size_is_deleted)."""
    usable = len(buf) - len(buf) % types.NEEDLE_MAP_ENTRY_SIZE
    return np.frombuffer(buf[:usable], dtype=_DTYPE)


def walk_index(buf: bytes) -> Iterator[tuple[int, int, int]]:
    """Yield (key, stored_offset, size) per entry, in file order
    (WalkIndexFile equivalent)."""
    arr = parse_index(buf)
    for key, offset, size in zip(arr["key"].tolist(),
                                 arr["offset"].tolist(),
                                 arr["size"].tolist()):
        yield key, offset, size


def entry_bytes(key: int, stored_offset: int, size: int) -> bytes:
    out = np.zeros(1, dtype=_DTYPE)
    out[0] = (key, stored_offset, size)
    return out.tobytes()


def pack_index(keys, offsets, sizes) -> bytes:
    """Vectorized writer: arrays -> .idx bytes."""
    n = len(keys)
    out = np.zeros(n, dtype=_DTYPE)
    out["key"] = keys
    out["offset"] = offsets
    out["size"] = sizes
    return out.tobytes()


def live_entries(buf: bytes) -> "dict[int, tuple[int, int]]":
    """Replay an .idx stream into the LIVE needle map — a delete (zero
    offset or tombstone size) REMOVES the key (memdb semantics,
    ec_encoder.go:387-393 readNeedleMap routes tombstones through
    MemDb.Delete).  Single definition shared by the EC .ecx writer and
    the repair plane's volume inventory."""
    from . import types
    live: dict[int, tuple[int, int]] = {}
    for key, off, size in walk_index(buf):
        if off != 0 and not types.size_is_deleted(size):
            live[key] = (off, size)
        else:
            live.pop(key, None)
    return live
