"""In-process distributed tracer riding the request-id plane.

The request id (util/request_id) already crosses every hop —
gateway -> filer -> volume -> master -> worker — so it IS the trace
id; this module hangs spans on it.  A span records one timed unit of
work (an HTTP handler, a gRPC method, an EC pipeline stage) with
explicit parentage, so `weed shell trace.show <request_id>` can
reassemble one request's cross-node tree and show where the time went
(stage-level timing, not aggregate counters, is what exposes the
bottleneck stage — arXiv:1709.05365 §5, arXiv:1908.01527 §2).

Design constraints, in order:

- always-on and allocation-cheap: the data plane runs with tracing
  enabled, so a span is one small object + one deque append; no
  locks on the hot path beyond the deque's own;
- in-process ring buffer only (`SEAWEEDFS_TPU_TRACE_BUFFER` spans,
  default 4096): no exporter, no background thread — the debug plane
  (`GET /debug/traces`) reads the buffer and `trace.show` fans out;
- context propagation over HTTP via `X-Trace-Parent:
  <trace_id>-<span_id>` next to `X-Request-ID`, over gRPC via
  `x-trace-parent` metadata, and across the worker job boundary via
  the job payload;
- sampling (`SEAWEEDFS_TPU_TRACE_SAMPLE`, 0.0-1.0, default 1.0)
  drops span RECORDING, never id propagation, so a sampled-out parent
  still stitches its children to the same trace;
- spans slower than `SEAWEEDFS_TPU_SLOW_MS` are written through
  util/wlog at WARN with their attrs (the slow-request log).

API shapes the SWFS007 lint understands:

    with tracing.span("GET /path", role="filer") as sp:
        sp.set("status", 200)          # preferred: leak-proof

    sp = tracing.start_span("job", role="worker")
    try: ...
    finally: sp.finish()               # manual pair — lint enforces

    tracing.emit_span("rebuild.fetch", start, duration, ...)
    # post-hoc emission for work measured elsewhere (pipeline stages)
"""

from __future__ import annotations

import contextvars
import itertools
import os
import random
import secrets
import threading
import time
from collections import deque

from .util.request_id import get_request_id

HEADER = "X-Trace-Parent"
GRPC_METADATA_KEY = "x-trace-parent"


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def buffer_size() -> int:
    """SEAWEEDFS_TPU_TRACE_BUFFER: spans kept per process."""
    return max(16, _env_int("SEAWEEDFS_TPU_TRACE_BUFFER", 4096))


def sample_rate() -> float:
    """SEAWEEDFS_TPU_TRACE_SAMPLE in [0, 1]: fraction of spans
    recorded to the ring buffer (propagation is never sampled)."""
    return min(1.0, max(0.0, _env_float("SEAWEEDFS_TPU_TRACE_SAMPLE",
                                        1.0)))


def slow_ms() -> float:
    """SEAWEEDFS_TPU_SLOW_MS: spans at least this slow are logged at
    WARN through wlog; unset or <= 0 disables the slow log."""
    return _env_float("SEAWEEDFS_TPU_SLOW_MS", 0.0)


_buffer: "deque[dict]" = deque(maxlen=buffer_size())
_buffer_lock = threading.Lock()

# (trace_id, span_id, role) of the active span on this context; the
# trace id mirrors the request id so children minted on this thread
# parent correctly even when the request id was set separately
_current: contextvars.ContextVar["tuple[str, str, str] | None"] = \
    contextvars.ContextVar("weed_trace_span", default=None)


# span ids need uniqueness (per process, and across the nodes a
# trace.show merge sees), not unpredictability; secrets.token_hex per
# span was a measurable slice of the filer's write-path CPU profile
# (several spans are minted per request).  6 random hex chars pin the
# process, a C-level counter distinguishes spans.
_SPAN_PREFIX = secrets.token_hex(3)
_span_counter = itertools.count(1)


def new_span_id() -> str:
    return f"{_SPAN_PREFIX}{next(_span_counter) & 0xFFFFFF:06x}"


class Span:
    """One unit of timed work.  Cheap on purpose: plain attributes,
    no dict allocated until an attr is set."""

    __slots__ = ("trace_id", "span_id", "parent_id", "role", "name",
                 "start", "duration", "attrs", "error", "_token",
                 "_t0", "_finished")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: str, role: str):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.role = role
        self.start = time.time()
        self.duration = 0.0
        self.attrs: "dict | None" = None
        self.error = False
        self._token = None
        self._t0 = time.perf_counter()
        self._finished = False

    def set(self, key: str, value) -> "Span":
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = value
        return self

    def set_error(self, err=None) -> "Span":
        self.error = True
        if err is not None:
            self.set("error", f"{type(err).__name__}: {err}")
        return self

    def finish(self) -> None:
        """Close the span: compute duration, restore the previous
        current-span context, record to the ring buffer (sampled) and
        the slow log.  Idempotent — a double finish is a no-op."""
        if self._finished:
            return
        self._finished = True
        self.duration = time.perf_counter() - self._t0
        if self._token is not None:
            try:
                _current.reset(self._token)
            except ValueError:   # finished on a different context
                pass
            self._token = None
        _record(self.to_dict())

    def to_dict(self) -> dict:
        d = {"traceId": self.trace_id, "spanId": self.span_id,
             "parentId": self.parent_id, "role": self.role,
             "name": self.name, "start": self.start,
             "durationMs": round(self.duration * 1e3, 3)}
        if self.error:
            d["error"] = True
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        return d

    # -- context manager ------------------------------------------------

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            self.set_error(exc)
        self.finish()


def _slow_log_and_sample(doc: dict, threshold: float,
                         rate: float) -> bool:
    """The per-doc half of recording, shared by _record and
    emit_span_batch: the slow log fires regardless of sampling (a
    dropped-from-buffer span that took 4s is still operator-
    actionable), then the sampling gate decides whether the doc is
    kept."""
    if threshold > 0 and doc["durationMs"] >= threshold:
        from .util import wlog
        wlog.warning(
            "slow span %s (%s) %.1fms trace=%s span=%s attrs=%s",
            doc["name"], doc["role"] or "-", doc["durationMs"],
            doc["traceId"], doc["spanId"], doc.get("attrs") or {},
            component="trace")
    return not (rate < 1.0 and random.random() >= rate)


def _buffer_extend(docs) -> None:
    global _buffer
    with _buffer_lock:
        if _buffer.maxlen != buffer_size():
            # env knob changed since import (tests): rebuild, keeping
            # the newest spans
            _buffer = deque(_buffer, maxlen=buffer_size())
        _buffer.extend(docs)


def _record(doc: dict) -> None:
    if _slow_log_and_sample(doc, slow_ms(), sample_rate()):
        _buffer_extend((doc,))


def start_span(name: str, role: str = "", parent: "str | None" = None,
               trace_id: "str | None" = None) -> Span:
    """Open a span and make it the context's current span.  The caller
    MUST finish() it (or use span() / the with-statement form); the
    SWFS007 lint flags call sites that do neither.

    Parentage: explicit `parent` wins, else the context's current
    span.  Trace id: explicit wins, else the current span's, else the
    active request id, else a fresh id (a traced unit outside any
    request still gets a coherent trace)."""
    cur = _current.get()
    if parent is None:
        parent = cur[1] if cur else ""
    if not role and cur:
        role = cur[2]
    if trace_id is None:
        trace_id = (cur[0] if cur else "") or get_request_id() or \
            secrets.token_hex(8)
    sp = Span(name, trace_id, new_span_id(), parent, role)
    sp._token = _current.set((sp.trace_id, sp.span_id, sp.role))
    return sp


def span(name: str, role: str = "", parent: "str | None" = None,
         trace_id: "str | None" = None) -> Span:
    """Context-manager form (the default way to trace a block)."""
    return start_span(name, role=role, parent=parent,
                      trace_id=trace_id)


def emit_span(name: str, start: float, duration: float,
              role: str = "", parent: str = "",
              trace_id: str = "", attrs: "dict | None" = None,
              error: bool = False) -> dict:
    """Record an already-measured span (work timed outside the
    tracer — pipeline stages whose lifetime spans threads).  Returns
    the recorded document."""
    cur = _current.get()
    doc = {
        "traceId": trace_id or (cur[0] if cur else "") or
        get_request_id() or secrets.token_hex(8),
        "spanId": new_span_id(),
        "parentId": parent or (cur[1] if cur else ""),
        "role": role or (cur[2] if cur else ""),
        "name": name, "start": start,
        "durationMs": round(duration * 1e3, 3)}
    if error:
        doc["error"] = True
    if attrs:
        doc["attrs"] = dict(attrs)
    _record(doc)
    return doc


def emit_span_batch(items: "list[dict]") -> None:
    """Batch emit_span for a stage track's sibling spans: the
    slow-log / sample-rate / buffer-size knobs are env lookups and
    were read three times PER SPAN through emit_span — on a
    stage-tracked write that made them the tracer's dominant hot-path
    cost.  Each item carries emit_span's kwargs (name, start,
    duration, role, parent, trace_id, attrs, error)."""
    if not items:
        return
    cur = _current.get()
    threshold = slow_ms()
    rate = sample_rate()
    out = []
    for it in items:
        doc = {
            "traceId": it.get("trace_id") or (cur[0] if cur else "")
            or get_request_id() or secrets.token_hex(8),
            "spanId": new_span_id(),
            "parentId": it.get("parent") or (cur[1] if cur else ""),
            "role": it.get("role") or (cur[2] if cur else ""),
            "name": it["name"], "start": it["start"],
            "durationMs": round(it["duration"] * 1e3, 3)}
        if it.get("error"):
            doc["error"] = True
        attrs = it.get("attrs")
        if attrs:
            doc["attrs"] = dict(attrs)
        if _slow_log_and_sample(doc, threshold, rate):
            out.append(doc)
    if out:
        _buffer_extend(out)


def emit_plane_hop(name: str, role: str, trace_id: str,
                   start: float, duration: float,
                   stages: "list[tuple[str, float]]",
                   attrs: "dict | None" = None,
                   error: bool = False) -> dict:
    """Synthesize one native-plane hop as a span tree: a root hop
    span plus one child per non-zero stage (ISSUE 18 — the C++ planes
    record stage ns in their flight ring; the Python drainer calls
    this post-hoc, so plane-served requests stitch into the same
    trace as the Python hops that share the request id).  Stage spans
    are laid out back-to-back from the hop start — the planes measure
    stages as consecutive windows of one event-loop pass."""
    hop = emit_span(name, start, duration, role=role, parent="",
                    trace_id=trace_id, attrs=attrs, error=error)
    items = []
    at = start
    for stage_name, stage_s in stages:
        if stage_s <= 0.0:
            continue
        items.append({"name": f"plane.{stage_name}", "start": at,
                      "duration": stage_s, "role": role,
                      "parent": hop["spanId"], "trace_id": trace_id})
        at += stage_s
    emit_span_batch(items)
    return hop


# -- context / propagation helpers ----------------------------------------

def current_ids() -> "tuple[str, str, str] | None":
    """(trace_id, span_id, role) of the active span, or None.  Capture
    this BEFORE handing work to another thread — contextvars do not
    follow threading.Thread — and pass it back as span(parent=...)."""
    return _current.get()


def traceparent_header() -> str:
    """`<trace_id>-<span_id>` for the outbound X-Trace-Parent header;
    empty when no span is active."""
    cur = _current.get()
    return f"{cur[0]}-{cur[1]}" if cur else ""


def parse_traceparent(value: "str | None") -> "tuple[str, str]":
    """(trace_id, parent_span_id) from an inbound header; ("", "")
    when absent/malformed."""
    if not value or "-" not in value:
        return "", ""
    trace_id, _, span_id = value.rpartition("-")
    if not trace_id or not span_id:
        return "", ""
    return trace_id, span_id


def adopt_remote_parent(header_value: "str | None",
                        role: str = "") -> None:
    """Make an inbound trace-parent the context's current span
    without opening a local span (the worker/gRPC boundary adopts the
    caller's context, then opens its own child spans).  An absent/
    malformed value CLEARS the context instead — a long-lived loop
    thread (the worker) must never leak the previous job's ancestry
    into the next one."""
    trace_id, span_id = parse_traceparent(header_value)
    _current.set((trace_id, span_id, role) if trace_id else None)


# -- buffer access (the /debug/traces feed) -------------------------------

def ingest(spans: "list[dict]") -> int:
    """Re-record span documents produced by ANOTHER process into this
    process's ring buffer (the admin ingests worker job spans from
    completion reports — workers have no HTTP listener of their own
    for trace.show to query).  Malformed entries are dropped, span
    ids already buffered are skipped (at-least-once reports must not
    duplicate); returns how many were added."""
    global _buffer
    added = 0
    with _buffer_lock:
        have = {d["spanId"] for d in _buffer}
        for doc in spans or []:
            if not isinstance(doc, dict):
                continue
            if not (doc.get("traceId") and doc.get("spanId") and
                    doc.get("name")):
                continue
            if doc["spanId"] in have:
                continue
            doc = dict(doc)
            doc.setdefault("parentId", "")
            doc.setdefault("role", "")
            doc.setdefault("start", 0.0)
            doc.setdefault("durationMs", 0.0)
            if _buffer.maxlen != buffer_size():
                _buffer = deque(_buffer, maxlen=buffer_size())
            _buffer.append(doc)
            have.add(doc["spanId"])
            added += 1
    return added


def spans_for(trace_id: str) -> "list[dict]":
    with _buffer_lock:
        return [dict(d) for d in _buffer if d["traceId"] == trace_id]


def recent_spans(limit: int = 200) -> "list[dict]":
    with _buffer_lock:
        docs = list(_buffer)
    return [dict(d) for d in docs[-max(1, limit):]]


def reset_buffer() -> None:
    """Tests only: empty the ring buffer."""
    with _buffer_lock:
        _buffer.clear()
