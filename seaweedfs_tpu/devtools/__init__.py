"""Project-native static analysis + runtime race tooling.

`weed analyze` analog: an AST rule engine (analyze.py) with rules tuned
to this codebase's real failure modes (rules.py, SWFS001..SWFS006 —
see RULES.md), and a runtime lock-order detector (lockgraph.py) that
turns the proc-cluster tests into a deadlock harness.

The engine is self-contained stdlib Python: no third-party linter is
required (or available) in the container.
"""

from .analyze import Finding, run_paths  # noqa: F401
