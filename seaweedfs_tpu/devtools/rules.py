"""SWFS001..SWFS006 — rules tuned to this codebase's failure modes.

Rationale and examples for every rule live in devtools/RULES.md; each
rule's docstring here carries only the detection contract.
"""

from __future__ import annotations

import ast
import os
import re
import struct

from .analyze import FileContext, Rule

_MUTATOR_METHODS = {
    "append", "extend", "insert", "add", "remove", "discard", "pop",
    "popitem", "clear", "update", "setdefault", "appendleft", "popleft",
}

_LOCK_FACTORIES = {"Lock", "RLock"}


def _self_attr(node: ast.AST) -> "str | None":
    """'x' for `self.x`, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name of a call target ('time.sleep', 'open')."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Call):
        parts.append(_dotted(node.func) + "()")
    return ".".join(reversed(parts))


class LockDisciplineRule(Rule):
    """SWFS001: a class that guards an attribute with `with self.<lock>`
    somewhere must guard EVERY mutation of that attribute.  Mutations of
    lock-guarded attrs outside any lock block (and outside __init__) are
    flagged; helpers named `*_locked` or whose docstring says the
    caller holds the lock are skipped."""

    id = "SWFS001"
    severity = "error"
    title = "lock-guarded attribute mutated without the lock"

    def check(self, ctx: FileContext):
        for cls in ast.walk(ctx.tree):
            if isinstance(cls, ast.ClassDef):
                yield from self._check_class(ctx, cls)

    def _lock_attrs(self, cls: ast.ClassDef) -> set:
        locks = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                fn = node.value.func
                if isinstance(fn, ast.Attribute) and \
                        fn.attr in _LOCK_FACTORIES:
                    for t in node.targets:
                        attr = _self_attr(t)
                        if attr:
                            locks.add(attr)
        return locks

    def _check_class(self, ctx: FileContext, cls: ast.ClassDef):
        locks = self._lock_attrs(cls)
        if not locks:
            return
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        guarded: set[str] = set()
        unguarded: list[tuple[str, ast.AST]] = []
        for m in methods:
            if m.name == "__init__":
                continue
            doc = (ast.get_docstring(m) or "").lower()
            caller_holds = m.name.endswith("_locked") \
                or "caller holds" in doc or "lock held" in doc \
                or "holds the lock" in doc
            for attr, node, under in self._mutations(m, locks):
                if attr in locks:
                    continue
                if under or caller_holds:
                    guarded.add(attr)
                else:
                    unguarded.append((attr, node))
        for attr, node in unguarded:
            if attr in guarded:
                yield self.finding(
                    ctx, node,
                    f"{cls.name}.{attr} is mutated under the lock "
                    f"elsewhere but written here without `with "
                    f"self.{sorted(locks)[0]}`")

    def _mutations(self, fn: ast.AST, locks: set):
        """Yield (attr, node, under_lock) for every self.<attr> mutation
        in fn, tracking `with self.<lock>:` nesting."""

        def walk(node: ast.AST, under: bool):
            if isinstance(node, ast.With):
                has_lock = any(
                    _self_attr(item.context_expr) in locks
                    for item in node.items)
                for child in node.body:
                    yield from walk(child, under or has_lock)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                return  # nested defs: closure timing is unknowable here
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    attr = _self_attr(t)
                    if attr:
                        yield attr, node, under
                    elif isinstance(t, ast.Subscript):
                        attr = _self_attr(t.value)
                        if attr:
                            yield attr, node, under
                    elif isinstance(t, (ast.Tuple, ast.List)):
                        for elt in t.elts:
                            a = _self_attr(elt)
                            if a:
                                yield a, node, under
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATOR_METHODS:
                attr = _self_attr(node.func.value)
                if attr:
                    yield attr, node, under
            for child in ast.iter_child_nodes(node):
                yield from walk(child, under)

        yield from walk(fn, False)


class JitBlockingRule(Rule):
    """SWFS002: host-side blocking calls inside @jax.jit-decorated
    functions or Pallas kernels.  Blocking inside a traced function runs
    at TRACE time at best and deadlocks a compiled callback at worst;
    either way it does not do what the author meant."""

    id = "SWFS002"
    severity = "error"
    title = "blocking call inside a jit/pallas kernel"

    _BLOCKING_EXACT = {
        "time.sleep", "open", "input", "os.system", "socket.socket",
        "socket.create_connection", "http_bytes", "http_json",
    }
    _BLOCKING_PREFIX = ("subprocess.", "requests.", "urllib.request.")

    def check(self, ctx: FileContext):
        kernels = self._kernel_functions(ctx)
        for fn in kernels:
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = _dotted(node.func)
                blocking = (name in self._BLOCKING_EXACT or
                            name.startswith(self._BLOCKING_PREFIX) or
                            (isinstance(node.func, ast.Attribute) and
                             node.func.attr == "result" and
                             not node.args))
                if blocking:
                    yield self.finding(
                        ctx, node,
                        f"blocking call {name or '.result()'}() inside "
                        f"jit/pallas function {fn.name!r} — runs at "
                        f"trace time / stalls the accelerator stream")

    def _kernel_functions(self, ctx: FileContext) -> list:
        pallas_kernel_names: set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "pallas_call":
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name):
                        pallas_kernel_names.add(sub.id)
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if node.name in pallas_kernel_names:
                out.append(node)
                continue
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                names = [_dotted(target)]
                if isinstance(dec, ast.Call):
                    names += [_dotted(a) for a in dec.args]
                if any(n == "jit" or n.endswith(".jit") or
                       n.endswith("pallas_call") for n in names):
                    out.append(node)
                    break
        return out


class StructWidthRule(Rule):
    """SWFS003: struct format strings on the data plane.

    (a) formats without an explicit byte order ('>', '<', '!') use
    native size/alignment — on-disk/wire layouts silently change per
    platform (the shadow-writer alignment bug class);
    (b) a constant-width buffer slice passed to unpack must match
    calcsize(fmt) exactly."""

    id = "SWFS003"
    severity = "error"
    title = "struct format width/byte-order hazard"

    _FUNCS = {"pack", "unpack", "pack_into", "unpack_from", "calcsize"}

    def check(self, ctx: FileContext):
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and
                    isinstance(node.func, ast.Attribute) and
                    node.func.attr in self._FUNCS and
                    isinstance(node.func.value, ast.Name) and
                    node.func.value.id == "struct"):
                continue
            if not (node.args and
                    isinstance(node.args[0], ast.Constant) and
                    isinstance(node.args[0].value, str)):
                continue
            fmt = node.args[0].value
            try:
                width = struct.calcsize(fmt)
            except struct.error as e:
                yield self.finding(ctx, node,
                                   f"invalid struct format {fmt!r}: {e}")
                continue
            if not fmt or fmt[0] not in "<>!":
                yield self.finding(
                    ctx, node,
                    f"struct format {fmt!r} has no explicit byte order "
                    f"— native size/alignment is platform-dependent; "
                    f"on-disk formats here are big-endian ('>')")
                continue
            if node.func.attr == "unpack" and len(node.args) == 2:
                got = self._const_slice_width(node.args[1])
                if got is not None and got != width:
                    yield self.finding(
                        ctx, node,
                        f"struct.unpack({fmt!r}, ...) needs exactly "
                        f"{width} byte(s) but the slice provides {got}")

    @staticmethod
    def _const_slice_width(node: ast.AST) -> "int | None":
        """Width of buf[a:b] when a and b are non-negative int
        constants (a omitted = 0); None when not statically known."""
        if not (isinstance(node, ast.Subscript) and
                isinstance(node.slice, ast.Slice)):
            return None
        sl = node.slice
        if sl.step is not None:
            return None
        if sl.lower is None:
            lower = 0
        elif isinstance(sl.lower, ast.Constant) and \
                isinstance(sl.lower.value, int) and sl.lower.value >= 0:
            lower = sl.lower.value
        else:
            return None
        if isinstance(sl.upper, ast.Constant) and \
                isinstance(sl.upper.value, int) and sl.upper.value >= 0:
            upper = sl.upper.value
        else:
            return None
        return max(upper - lower, 0)


class SwallowedExceptionRule(Rule):
    """SWFS004: silently swallowed exceptions.  Flags (a) bare `except:`
    unless the body re-raises (it catches KeyboardInterrupt/SystemExit),
    and (b) `except Exception`/`except BaseException` whose body does
    nothing but pass/continue — data-plane corruption's favourite
    hiding place."""

    id = "SWFS004"
    severity = "error"
    title = "swallowed exception"

    def check(self, ctx: FileContext):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                if not any(isinstance(n, ast.Raise)
                           for n in ast.walk(node)):
                    yield self.finding(
                        ctx, node,
                        "bare `except:` swallows KeyboardInterrupt/"
                        "SystemExit — catch a concrete error type")
                continue
            if self._is_broad(node.type) and self._body_inert(node):
                yield self.finding(
                    ctx, node,
                    "broad exception silently swallowed — narrow the "
                    "type and/or log the failure")

    @staticmethod
    def _is_broad(t: ast.AST) -> bool:
        names = []
        if isinstance(t, ast.Tuple):
            names = [_dotted(e) for e in t.elts]
        else:
            names = [_dotted(t)]
        return any(n in ("Exception", "BaseException") for n in names)

    @staticmethod
    def _body_inert(node: ast.ExceptHandler) -> bool:
        for stmt in node.body:
            if isinstance(stmt, ast.Pass) or \
                    isinstance(stmt, ast.Continue) or \
                    (isinstance(stmt, ast.Expr) and
                     isinstance(stmt.value, ast.Constant)):
                continue
            return False
        return True


class UnclosedHandleRule(Rule):
    """SWFS005: file/socket opened without a context manager or a
    visible close.  Handles that escape (returned, passed to a call,
    stored on self or in a container) are the caller's problem and are
    not flagged."""

    id = "SWFS005"
    severity = "warning"
    title = "handle opened without with/close"

    _OPENERS = {"open", "socket.socket"}

    def check(self, ctx: FileContext):
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and
                    _dotted(node.func) in self._OPENERS):
                continue
            verdict = self._verdict(ctx, node)
            if verdict:
                yield self.finding(ctx, node, verdict)

    def _verdict(self, ctx: FileContext, call: ast.Call) -> "str | None":
        name = _dotted(call.func)
        parent = ctx.parent(call)
        if isinstance(parent, ast.withitem):
            return None
        if isinstance(parent, ast.Attribute):
            if parent.attr == "close":
                return None
            return (f"{name}(...).{parent.attr}() leaks the handle — "
                    f"use a `with` block")
        if isinstance(parent, ast.Expr):
            return f"{name}(...) result discarded — handle leaks"
        if isinstance(parent, (ast.Assign, ast.AnnAssign)):
            targets = parent.targets if isinstance(parent, ast.Assign) \
                else [parent.target]
            if len(targets) != 1 or not isinstance(targets[0], ast.Name):
                return None  # self.x / container slot: lifecycle-managed
            var = targets[0].id
            fn = next((a for a in ctx.ancestors(call)
                       if isinstance(a, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))), None)
            scope = fn if fn is not None else ctx.tree
            if self._name_is_handled(scope, var, parent):
                return None
            return (f"{name}(...) assigned to {var!r} but never closed, "
                    f"returned, stored, or passed on in this scope")
        return None  # escapes into a call/container/comprehension

    @staticmethod
    def _name_is_handled(scope: ast.AST, var: str,
                         assign: ast.AST) -> bool:
        for node in ast.walk(scope):
            if node is assign:
                continue
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) and \
                        f.attr == "close" and \
                        isinstance(f.value, ast.Name) and \
                        f.value.id == var:
                    return True
                for arg in list(node.args) + \
                        [kw.value for kw in node.keywords]:
                    if isinstance(arg, ast.Name) and arg.id == var:
                        return True
            elif isinstance(node, ast.withitem):
                if isinstance(node.context_expr, ast.Name) and \
                        node.context_expr.id == var:
                    return True
            elif isinstance(node, ast.Return):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name) and sub.id == var:
                        return True
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                value = node.value
                for sub in ast.walk(value) if value else []:
                    if isinstance(sub, ast.Name) and sub.id == var:
                        return True
            elif isinstance(node, (ast.Tuple, ast.List, ast.Dict,
                                   ast.Yield, ast.YieldFrom)):
                for sub in ast.iter_child_nodes(node):
                    if isinstance(sub, ast.Name) and sub.id == var:
                        return True
        return False


class WallClockRule(Rule):
    """SWFS006: wall-clock reads in replay-deterministic paths.  The
    raft log and .idx replay must produce identical state on every
    replay; `time.time()` there bakes the replay wall time into state.
    Scope: the module list below plus any module whose first lines
    carry a `swfs: deterministic` marker."""

    id = "SWFS006"
    severity = "error"
    title = "wall clock used in a replay-deterministic path"

    DETERMINISTIC_SUFFIXES = (
        "seaweedfs_tpu/server/raft.py",
        "seaweedfs_tpu/storage/idx.py",
        "seaweedfs_tpu/storage/needle_map.py",
    )
    _CLOCKS = {"time.time", "time.time_ns", "datetime.now",
               "datetime.utcnow", "datetime.datetime.now",
               "datetime.datetime.utcnow", "datetime.date.today"}

    def _applies(self, ctx: FileContext) -> bool:
        if ctx.relpath.endswith(self.DETERMINISTIC_SUFFIXES):
            return True
        head = "\n".join(ctx.lines[:50])
        return "swfs: deterministic" in head

    def check(self, ctx: FileContext):
        if not self._applies(ctx):
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and \
                    _dotted(node.func) in self._CLOCKS:
                yield self.finding(
                    ctx, node,
                    f"{_dotted(node.func)}() in a replay-deterministic "
                    f"module — use time.monotonic() for intervals or "
                    f"carry timestamps in the replayed record")


class LeakedSpanRule(Rule):
    """SWFS007: a trace span opened without a context manager or a
    matching finish.  `tracing.start_span()` (and the `tracing.span()`
    context-manager form) set the context's current span; a span that
    is never finished leaves every later span in the handler thread
    parented under it AND never reaches the ring buffer — the trace
    silently loses its timing.  Flagged unless the call is a
    with-item, or its result visibly reaches `.finish()` / a `with`
    block / escapes the scope (returned, stored, passed on)."""

    id = "SWFS007"
    severity = "error"
    title = "trace span started without context manager or finish"

    _OPENERS_SUFFIX = ("start_span",)
    _OPENERS_EXACT = {"tracing.span", "tracing.start_span"}

    def _is_opener(self, name: str) -> bool:
        return name in self._OPENERS_EXACT or \
            name.rsplit(".", 1)[-1] in self._OPENERS_SUFFIX

    def check(self, ctx: FileContext):
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and
                    self._is_opener(_dotted(node.func))):
                continue
            verdict = self._verdict(ctx, node)
            if verdict:
                yield self.finding(ctx, node, verdict)

    def _verdict(self, ctx: FileContext, call: ast.Call) -> "str | None":
        name = _dotted(call.func)
        parent = ctx.parent(call)
        if isinstance(parent, ast.withitem):
            return None            # `with tracing.span(...) as sp:`
        if isinstance(parent, ast.Attribute):
            # `start_span(...).finish()` is pointless but not a leak;
            # any other immediate attribute use drops the handle
            if parent.attr in ("finish", "set", "set_error"):
                return None
            return (f"{name}(...).{parent.attr} discards the span — "
                    f"use `with` or keep it and call .finish()")
        if isinstance(parent, ast.Expr):
            return (f"{name}(...) result discarded — the span is "
                    f"never finished (use `with {name}(...)`)")
        if isinstance(parent, (ast.Assign, ast.AnnAssign)):
            targets = parent.targets if isinstance(parent, ast.Assign) \
                else [parent.target]
            if len(targets) != 1 or not isinstance(targets[0], ast.Name):
                return None        # self.x / container: lifecycle-managed
            var = targets[0].id
            fn = next((a for a in ctx.ancestors(call)
                       if isinstance(a, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))), None)
            scope = fn if fn is not None else ctx.tree
            if self._name_is_finished(scope, var, parent):
                return None
            return (f"{name}(...) assigned to {var!r} but never "
                    f"finished, used as a context manager, or passed "
                    f"on in this scope — the span leaks")
        return None                # escapes into a call/container

    @staticmethod
    def _name_is_finished(scope: ast.AST, var: str,
                          assign: ast.AST) -> bool:
        for node in ast.walk(scope):
            if node is assign:
                continue
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) and \
                        f.attr == "finish" and \
                        isinstance(f.value, ast.Name) and \
                        f.value.id == var:
                    return True
                for arg in list(node.args) + \
                        [kw.value for kw in node.keywords]:
                    if isinstance(arg, ast.Name) and arg.id == var:
                        return True
            elif isinstance(node, ast.withitem):
                if isinstance(node.context_expr, ast.Name) and \
                        node.context_expr.id == var:
                    return True
            elif isinstance(node, ast.Return):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name) and sub.id == var:
                        return True
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                value = node.value
                for sub in ast.walk(value) if value else []:
                    if isinstance(sub, ast.Name) and sub.id == var:
                        return True
        return False


class UnclosedShardStreamRule(Rule):
    """SWFS008: a ShardSink/ShardSource (or their fetcher/stats
    aggregates holding them) constructed without a context manager or
    a visible close.  These objects own sockets, fds, send/prefetch
    threads AND, for sinks, staged server-side temp files: one leaked
    RemoteShardSink keeps a `.scatter.<id>` temp pinned on its
    destination until the reaper, and a leaked fetcher strands its
    prefetch threads.  Same shape as SWFS007 for spans: flagged unless
    the constructor call is a with-item, or its result visibly reaches
    `.close()` (put it in a `finally`), a `with` block, or another
    owner (returned, stored on self/container, passed on)."""

    id = "SWFS008"
    severity = "error"
    title = "ShardSink/ShardSource not closed (with/close-in-finally)"

    _SUFFIXES = ("ShardSink", "ShardSource")
    _EXACT = {"MultiSourceFetcher"}

    def _is_opener(self, name: str) -> bool:
        last = name.rsplit(".", 1)[-1]
        return last.endswith(self._SUFFIXES) or last in self._EXACT

    def check(self, ctx: FileContext):
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and
                    self._is_opener(_dotted(node.func))):
                continue
            verdict = self._verdict(ctx, node)
            if verdict:
                yield self.finding(ctx, node, verdict)

    def _verdict(self, ctx: FileContext, call: ast.Call) -> "str | None":
        name = _dotted(call.func)
        parent = ctx.parent(call)
        if isinstance(parent, ast.withitem):
            return None            # `with LocalShardSink(...) as s:`
        if isinstance(parent, ast.Attribute):
            if parent.attr == "close":
                return None
            return (f"{name}(...).{parent.attr} drops the stream — "
                    f"use `with`, or keep it and close() in a finally")
        if isinstance(parent, ast.Expr):
            return (f"{name}(...) result discarded — its threads/fds/"
                    f"staged temps are never released")
        if isinstance(parent, (ast.Assign, ast.AnnAssign)):
            targets = parent.targets if isinstance(parent, ast.Assign) \
                else [parent.target]
            if len(targets) != 1 or not isinstance(targets[0], ast.Name):
                return None        # self.x / container: lifecycle-managed
            var = targets[0].id
            fn = next((a for a in ctx.ancestors(call)
                       if isinstance(a, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))), None)
            scope = fn if fn is not None else ctx.tree
            # reuse the handle-escape analysis: close()/with/returned/
            # stored/passed-on all transfer ownership
            if UnclosedHandleRule._name_is_handled(scope, var, parent):
                return None
            return (f"{name}(...) assigned to {var!r} but never "
                    f"closed, used as a context manager, or passed "
                    f"on in this scope — close() it in a finally")
        return None                # escapes into a call/container


class MissingTimeoutRule(Rule):
    """SWFS009: a network call site without an explicit timeout.

    Every helper in the client funnel (`http_json`, `http_bytes`,
    `http_download`, `http_upload`, `http_relay`,
    `http_stream_request`, `master_json`) *has* a default timeout, but
    a call site that relies on it is making an invisible latency
    decision: the 30s/600s defaults are tuned for bulk data moves, and
    a control-plane call that inherits them holds locks, worker slots,
    or retry budget for that long when a peer wedges.  The chaos
    suite's delay failpoints turn exactly this into test failures.
    Fix: pass `timeout=` explicitly (what should THIS call tolerate?),
    or `# noqa: SWFS009` / baseline a call site whose default is a
    considered choice."""

    id = "SWFS009"
    severity = "error"
    title = "network call without an explicit timeout"

    # zero-based positional index of each helper's `timeout` param —
    # a call passing it positionally is explicit too
    _FUNCS = {"http_json": 3, "http_bytes": 4, "http_download": 3,
              "http_upload": 4, "http_relay": 4,
              "http_stream_request": 4, "master_json": 4}

    def check(self, ctx: FileContext):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func).rsplit(".", 1)[-1]
            if name not in self._FUNCS:
                continue
            if any(kw.arg == "timeout" for kw in node.keywords):
                continue
            if any(kw.arg is None for kw in node.keywords):
                continue    # **kwargs may carry a timeout
            if len(node.args) > self._FUNCS[name]:
                continue    # timeout passed positionally
            yield self.finding(
                ctx, node,
                f"{name}(...) without an explicit timeout= — the "
                f"helper default is a bulk-transfer latency budget, "
                f"not a considered choice for this call site")


class MissingAdmissionRule(Rule):
    """SWFS010: a gateway role server wired up without the QoS
    admission middleware.

    A class whose listener carries BOTH the uniform request metrics
    (`self.http.metrics = ...`) and a catch-all data path
    (`self.http.fallback = ...`) is a tenant-facing gateway (the
    s3/filer/volume shape); registering its handlers without routing
    them through admission control (`qos.install(self.http, ...)` or
    a direct `self.http.admission = ...`) silently exempts that
    listener from the per-tenant QoS plane — a noisy tenant then
    bypasses its token bucket by picking the unguarded door.  Control
    planes without a fallback (master) and auxiliary listeners
    without role metrics (webdav, mq, kms) are out of scope."""

    id = "SWFS010"
    severity = "error"
    title = "gateway listener without QoS admission middleware"

    @staticmethod
    def _http_attr(node: ast.AST) -> "str | None":
        """'fallback' for `self.<anything>.fallback` where the owner
        chain ends at self.http (or any single http-ish attribute)."""
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Attribute) and \
                isinstance(node.value.value, ast.Name) and \
                node.value.value.id == "self":
            return node.attr
        return None

    @classmethod
    def _is_self_http(cls, node: ast.AST) -> bool:
        return isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and \
            node.value.id == "self"

    def check(self, ctx: FileContext):
        for cls_node in ast.walk(ctx.tree):
            if not isinstance(cls_node, ast.ClassDef):
                continue
            has_fallback = has_metrics = has_admission = False
            anchor = None
            for node in ast.walk(cls_node):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        attr = self._http_attr(t)
                        if attr == "fallback":
                            has_fallback = True
                            anchor = anchor or node
                        elif attr == "metrics":
                            has_metrics = True
                        elif attr == "admission":
                            has_admission = True
                elif isinstance(node, ast.Call):
                    name = _dotted(node.func)
                    if name.split(".")[-1] == "install" and \
                            "qos" in name and node.args and \
                            self._is_self_http(node.args[0]):
                        has_admission = True
            if has_fallback and has_metrics and not has_admission:
                yield self.finding(
                    ctx, anchor or cls_node,
                    f"{cls_node.name} wires a gateway listener "
                    f"(role metrics + fallback data path) without "
                    f"the QoS admission middleware — call "
                    f"qos.install(self.http, <role>) so its handlers "
                    f"pass through per-tenant admission")


class WallDurationRule(Rule):
    """SWFS011: `time.time()` arithmetic used to measure a duration.
    The wall clock steps under NTP — backwards (a measured interval
    goes negative, a TTL pins stale cache entries alive) or forwards
    (timeouts fire instantly, a fresh cache flushes on every lookup).
    Flagged: a subtraction whose operand is a direct `time.time()` /
    `time.time_ns()` call, or a local name bound to one in the same
    scope (the t1 - t0 pattern).  Durations belong on
    `time.monotonic()` / `time.perf_counter()`; wall timestamps are
    for RECORDS (needle ts, entry mtime), where cross-process
    comparisons need them — age-of-persisted-timestamp math is the
    legitimate remainder that lives in the baseline or under
    `# noqa: SWFS011`."""

    id = "SWFS011"
    severity = "error"
    title = "wall clock used to measure a duration"

    _WALL = {"time.time", "time.time_ns"}

    @staticmethod
    def _local_walk(scope: ast.AST):
        """Child nodes of `scope` without descending into nested
        function scopes (their own pass sees them — a name bound in
        the outer scope is not visible evidence for the inner one)."""
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            yield n
            stack.extend(ast.iter_child_nodes(n))

    def check(self, ctx: FileContext):
        scopes = [ctx.tree] + [
            n for n in ast.walk(ctx.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        seen: set = set()
        for scope in scopes:
            bound: set = set()
            for n in self._local_walk(scope):
                if isinstance(n, ast.Assign) and \
                        isinstance(n.value, ast.Call) and \
                        _dotted(n.value.func) in self._WALL:
                    bound.update(t.id for t in n.targets
                                 if isinstance(t, ast.Name))

            def wallish(x: ast.AST) -> bool:
                if isinstance(x, ast.Call) and \
                        _dotted(x.func) in self._WALL:
                    return True
                return isinstance(x, ast.Name) and x.id in bound

            for n in self._local_walk(scope):
                if not (isinstance(n, ast.BinOp) and
                        isinstance(n.op, ast.Sub)):
                    continue
                if not (wallish(n.left) or wallish(n.right)):
                    continue
                key = (n.lineno, n.col_offset)
                if key in seen:
                    continue
                seen.add(key)
                yield self.finding(
                    ctx, n,
                    "duration measured on the wall clock — an NTP "
                    "step skews or negates it; use time.monotonic() "
                    "(or perf_counter) for intervals")


class FlushUnderLockRule(Rule):
    """SWFS012: a blocking durability barrier — `<x>.flush()`,
    `os.fsync()`, `os.fdatasync()` — executed while holding a
    per-instance lock (`with <obj>.lock:` / `with <obj>._lock:`, or a
    `<obj>.lock.acquire()` region).  The barrier serializes every
    writer behind one kernel round-trip; group commit
    (util/group_commit.CommitBarrier) exists so concurrent writers
    buffer under the lock and share ONE flush outside it.  Exempt: the
    designated barrier helpers (functions named `_group_commit*` — the
    one place flush-under-lock is the contract), the group_commit
    module itself, and teardown/maintenance shapes (`close`, `stop`,
    `abort`, `__exit__`, `__del__`).  Slow-path barriers that are
    genuinely per-operation (compaction commit points, superblock
    rewrites) stay with `# noqa: SWFS012` and a reason."""

    id = "SWFS012"
    severity = "error"
    title = "blocking flush/fsync while holding a lock"

    _BARRIERS = {"os.fsync", "os.fdatasync"}
    _EXEMPT_FUNCS = {"close", "stop", "abort", "__exit__", "__del__"}
    _LOCK_ATTRS = {"lock", "_lock", "_io_lock"}

    def _is_lock_expr(self, node: ast.AST) -> bool:
        return isinstance(node, ast.Attribute) and \
            node.attr in self._LOCK_ATTRS

    def _barrier_call(self, node: ast.AST) -> "str | None":
        if not isinstance(node, ast.Call):
            return None
        name = _dotted(node.func)
        if name in self._BARRIERS:
            return name
        if name.endswith(".flush") and not node.args and \
                not node.keywords:
            return name
        return None

    @staticmethod
    def _body_walk(nodes):
        """Walk statements without descending into nested function
        definitions (their own visit sees them)."""
        stack = list(nodes)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            yield n
            stack.extend(ast.iter_child_nodes(n))

    def check(self, ctx: FileContext):
        if ctx.relpath.endswith("util/group_commit.py"):
            return
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            if fn.name in self._EXEMPT_FUNCS or \
                    fn.name.startswith("_group_commit"):
                continue
            yield from self._check_function(ctx, fn)

    def _check_function(self, ctx: FileContext, fn: ast.AST):
        # regions holding a lock: `with <x>.lock:` bodies, plus
        # everything after a bare `<x>.lock.acquire()` statement in
        # the same body (the acquire/try/finally-release shape)
        for node in self._body_walk(fn.body):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                if any(self._is_lock_expr(item.context_expr)
                       for item in node.items):
                    yield from self._flag_region(ctx, node.body)
            elif isinstance(node, ast.Expr) and \
                    isinstance(node.value, ast.Call):
                tgt = node.value.func
                if isinstance(tgt, ast.Attribute) and \
                        tgt.attr == "acquire" and \
                        self._is_lock_expr(tgt.value):
                    # the acquired region is the REST of the enclosing
                    # body (conservative: up to the function's end)
                    parent = ctx.parent(node)
                    body = getattr(parent, "body", [])
                    if node in body:
                        rest = body[body.index(node) + 1:]
                        yield from self._flag_region(ctx, rest)

    def _flag_region(self, ctx: FileContext, body):
        for n in self._body_walk(body):
            name = self._barrier_call(n)
            if name is None:
                continue
            yield self.finding(
                ctx, n,
                f"{name}() under a held lock serializes every writer "
                f"behind one kernel barrier — route it through a "
                f"group-commit helper (util/group_commit."
                f"CommitBarrier) or noqa with a reason")


class UnboundedBodyReadRule(Rule):
    """SWFS013: a full-body `f.read()` (no size argument) on a file
    handle opened in a DATA-PLANE module (`server/`, `filer/`, `s3/`,
    `mount/`, `util/chunk_cache.py`).  These trees assemble responses
    and caches: an unbounded read stages a whole file through Python
    bytes where the serving path should stream (`FileSlice` rides the
    dispatcher's sendfile(2); `Filer.open_read_stream` fetches chunk
    views lazily) or at least bound the read to what the protocol
    allows.  Genuinely bounded reads (sidecar files with format-fixed
    sizes, admin inventory endpoints that need the full buffer) stay
    with `# noqa: SWFS013` and a reason."""

    id = "SWFS013"
    severity = "error"
    title = "unbounded full-body read on a data-plane path"

    _TREES = ("seaweedfs_tpu/server/", "seaweedfs_tpu/filer/",
              "seaweedfs_tpu/s3/", "seaweedfs_tpu/mount/",
              "seaweedfs_tpu/util/chunk_cache.py")

    def check(self, ctx: FileContext):
        rel = ctx.relpath.replace("\\", "/")
        if not any(t in rel for t in self._TREES):
            return
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            yield from self._check_function(ctx, fn)

    @staticmethod
    def _opened_names(fn: ast.AST) -> "set[str]":
        """Names bound to `open(...)` results inside this function:
        `x = open(...)`, `with open(...) as x:`."""
        names: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    _dotted(node.value.func) in ("open", "io.open"):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        names.add(tgt.id)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if isinstance(item.context_expr, ast.Call) and \
                            _dotted(item.context_expr.func) in \
                            ("open", "io.open") and \
                            isinstance(item.optional_vars, ast.Name):
                        names.add(item.optional_vars.id)
        return names

    def _check_function(self, ctx: FileContext, fn: ast.AST):
        opened = self._opened_names(fn)
        if not opened:
            return
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call) or node.args or \
                    node.keywords:
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "read" and \
                    isinstance(f.value, ast.Name) and \
                    f.value.id in opened:
                yield self.finding(
                    ctx, node,
                    f"{f.value.id}.read() buffers the whole file "
                    f"through Python bytes on a data-plane path — "
                    f"stream it (FileSlice / open_read_stream) or "
                    f"bound the read, or noqa with a reason")


class AsyncBlockingCallRule(Rule):
    """SWFS014: a blocking call written directly inside an `async def`
    body.  The asyncio front (server/async_front.py) multiplexes a
    whole role's connections on ONE event loop — a single `time.sleep`,
    synchronous pooled-client hop (`http_bytes`/`http_json`/
    `master_json`/friends), `urllib.request.urlopen`, or bare `open()`
    in a coroutine stalls every connection of the role at once.
    Blocking work belongs on the executor
    (`loop.run_in_executor(pool, fn)`): calls inside nested `def`s and
    lambdas are NOT flagged, because that is exactly the executor
    hand-off shape.  A coroutine that must block anyway (none known)
    carries `# noqa: SWFS014` and a reason."""

    id = "SWFS014"
    severity = "error"
    title = "blocking call inside an async def"

    # fully-dotted spellings that block wherever they appear
    _FULL = {"time.sleep", "open", "io.open",
             "urllib.request.urlopen"}
    # the sync client funnel (httpd.py / operation.py), matched by
    # trailing name so module-qualified spellings are caught too
    _TAILS = {"http_bytes", "http_json", "master_json", "http_upload",
              "http_download", "http_relay", "http_stream_request",
              "_pooled_request", "_one_pooled_request"}

    @staticmethod
    def _direct_nodes(fn: ast.AST):
        """This function's own body, stopping at nested function /
        lambda scopes (their bodies run wherever they are CALLED —
        normally on the executor)."""
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.Lambda)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def check(self, ctx: FileContext):
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for node in self._direct_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                dotted = _dotted(node.func)
                tail = dotted.rsplit(".", 1)[-1]
                if dotted in self._FULL or tail in self._TAILS:
                    yield self.finding(
                        ctx, node,
                        f"{dotted}() blocks the event loop inside "
                        f"async def {fn.name} — hand it to the "
                        f"executor (loop.run_in_executor) or use the "
                        f"async equivalent")


class FilerHotPathCommitRule(Rule):
    """SWFS015: per-request store work on the filer hot path that the
    meta plane (filer/meta_plane.py) exists to amortize — (a) a
    DB-connection `commit()` (`self._db.commit()`, `conn.commit()`)
    outside the designated batch helpers, i.e. one store transaction
    per request instead of one per apply window; (b) an
    `Entry.to_json()` inside a store's `insert_entry`/`update_entry`,
    i.e. a SECOND per-request entry serialization after the one the
    WAL line already carries.  Exempt: the designated batch/teardown
    helpers (`apply_events`, `put_many`, `recover_sync`, `close`,
    `stop`, `__init__`, `commit` — MetaPlane.commit IS the
    single-serialization site — and `_group_commit*`/`_checkpoint*`
    prefixes).  The synchronous kill-switch path keeps its
    serialization under `# noqa: SWFS015` with a reason."""

    id = "SWFS015"
    severity = "error"
    title = "per-request serialization/commit on the filer hot path"

    _FILES = ("seaweedfs_tpu/filer/filer.py",
              "seaweedfs_tpu/filer/abstract_sql.py",
              "seaweedfs_tpu/filer/filer_store.py",
              "seaweedfs_tpu/filer/lsm_store.py",
              "seaweedfs_tpu/filer/meta_log.py",
              "seaweedfs_tpu/filer/meta_cache.py",
              "seaweedfs_tpu/filer/meta_plane.py",
              "seaweedfs_tpu/server/filer_server.py")
    _EXEMPT = {"apply_events", "put_many", "recover_sync", "close",
               "stop", "__init__", "commit"}
    _EXEMPT_PREFIXES = ("_group_commit", "_checkpoint")
    _SERIALIZING_FUNCS = {"insert_entry", "update_entry"}

    def _exempt(self, name: str) -> bool:
        return name in self._EXEMPT or \
            any(name.startswith(p) for p in self._EXEMPT_PREFIXES)

    @staticmethod
    def _own_nodes(fn: ast.AST):
        """The function's own body, stopping at nested defs (they get
        their own visit and their own exemption verdict)."""
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.Lambda)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def check(self, ctx: FileContext):
        rel = ctx.relpath.replace("\\", "/")
        if not any(rel.endswith(f) for f in self._FILES):
            return
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            if self._exempt(fn.name):
                continue
            for node in self._own_nodes(fn):
                if not isinstance(node, ast.Call) or \
                        not isinstance(node.func, ast.Attribute):
                    continue
                attr = node.func.attr
                if attr == "commit" and not node.args:
                    tail = _dotted(node.func.value).rsplit(".", 1)[-1]
                    if "db" in tail or "conn" in tail:
                        yield self.finding(
                            ctx, node,
                            f"{_dotted(node.func)}() commits one "
                            f"store transaction per request on the "
                            f"filer hot path — batch it through the "
                            f"meta plane's apply_events window (or "
                            f"noqa the synchronous kill-switch path "
                            f"with a reason)")
                elif attr == "to_json" and \
                        fn.name in self._SERIALIZING_FUNCS:
                    yield self.finding(
                        ctx, node,
                        f"{fn.name} re-serializes the entry per "
                        f"request — the meta plane's WAL line already "
                        f"carries these bytes; reuse them via "
                        f"apply_events (or noqa the synchronous "
                        f"kill-switch path with a reason)")


class BareTimeoutLiteralRule(Rule):
    """SWFS016: a bare numeric `timeout=` literal on a hot-path
    network call.

    The deadline plane (util/deadline, ISSUE 14) derives every
    request-path socket timeout from the REMAINING request budget:
    `timeout=deadline.io_timeout(default, site=...)` shrinks with the
    budget, fails fast when it is spent, and keeps the seed default
    for un-deadlined traffic.  A numeric literal at one of these call
    sites silently opts that hop out — a request with 50ms left can
    then park for the literal's full value, and the caller's 504 fires
    only after the work was done anyway.  Scope: the request-path
    client modules (`operation.py`, `wdclient.py`, `filer/filer.py`,
    `server/store_ec.py`) and the funnel helpers + lean plane client.
    Background threads that never carry a deadline (the master
    follower's snapshot poll) keep their deliberate fixed bound under
    `# noqa: SWFS016` with a reason."""

    id = "SWFS016"
    severity = "error"
    title = "bare numeric timeout on a hot-path network call"

    _FILES = ("seaweedfs_tpu/operation.py",
              "seaweedfs_tpu/wdclient.py",
              "seaweedfs_tpu/filer/filer.py",
              "seaweedfs_tpu/server/store_ec.py")
    # zero-based positional index of each helper's timeout param
    # (shared shape with SWFS009's table, plus the lean plane client)
    _FUNCS = {"http_json": 3, "http_bytes": 4, "http_download": 3,
              "http_upload": 4, "http_relay": 4,
              "http_stream_request": 4, "master_json": 4,
              "_plane_request": 4}

    @staticmethod
    def _numeric(node: ast.AST) -> bool:
        if isinstance(node, ast.Constant) and \
                isinstance(node.value, (int, float)) and \
                not isinstance(node.value, bool):
            return True
        # -5 / +5 parse as UnaryOp(Constant)
        return isinstance(node, ast.UnaryOp) and \
            isinstance(node.operand, ast.Constant) and \
            isinstance(node.operand.value, (int, float))

    def check(self, ctx: FileContext):
        rel = ctx.relpath.replace("\\", "/")
        if not any(rel.endswith(f) for f in self._FILES):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func).rsplit(".", 1)[-1]
            if name not in self._FUNCS:
                continue
            value = None
            for kw in node.keywords:
                if kw.arg == "timeout":
                    value = kw.value
                    break
            if value is None and len(node.args) > self._FUNCS[name]:
                value = node.args[self._FUNCS[name]]
            if value is None or not self._numeric(value):
                continue
            yield self.finding(
                ctx, value,
                f"{name}(...) with a bare numeric timeout on the "
                f"request path — derive it from the remaining budget "
                f"via util.deadline.io_timeout(default, site=...) so "
                f"a deadline-carrying request cannot out-wait its "
                f"caller (or noqa a background-thread site with a "
                f"reason)")


class DynamicMetricNameRule(Rule):
    """SWFS017: a metric name assembled at the mint site instead of
    written as a literal.

    Variable data belongs in LABELS, never in the metric NAME: a name
    interpolating a per-request value (a path, a tenant, a volume id)
    mints a new time series per distinct value, so the registry, every
    /metrics scrape, and every cluster.top parse grow without bound —
    and the family stops being queryable as one metric.  A label with
    the same value is still visible per-cell but shares ONE name the
    helpers (`prom_histogram`, `_counter_sum`) can aggregate, and the
    existing per-label cells are capped by the registry's cell
    accounting rather than silently minting new families.

    Flagged: the name argument of `counter_add` / `gauge_set` /
    `histogram_observe` that is an f-string with interpolation, a
    `+`/`%` string expression, or a `.format()` call — written
    directly, or via a scope-local name bound to one.  A name chosen
    from a closed literal set (a conditional of literals, a loop over
    a literal table) passes.  The documented exception is a name
    derived from a CODE-SITE constant — StageTrack's
    `<track>_stage_seconds` family, one name per `track()` call
    site — which stays under `# noqa: SWFS017` with the reason."""

    id = "SWFS017"
    severity = "error"
    title = "metric name built dynamically at the mint site"

    _METERS = {"counter_add", "gauge_set", "histogram_observe"}

    @staticmethod
    def _dynamic(node: ast.AST) -> bool:
        if isinstance(node, ast.JoinedStr):
            return any(isinstance(v, ast.FormattedValue)
                       for v in node.values)
        if isinstance(node, ast.BinOp) and \
                isinstance(node.op, (ast.Add, ast.Mod)):
            # the name argument is a str by contract, so arithmetic
            # here IS string assembly ("prefix_" + kind, "%s_total")
            return True
        return isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Attribute) and \
            node.func.attr == "format"

    def check(self, ctx: FileContext):
        scopes = [ctx.tree] + [
            n for n in ast.walk(ctx.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        seen: set = set()
        local_walk = WallDurationRule._local_walk
        for scope in scopes:
            bound: set = set()
            for n in local_walk(scope):
                if isinstance(n, ast.Assign) and self._dynamic(n.value):
                    bound.update(t.id for t in n.targets
                                 if isinstance(t, ast.Name))
            for n in local_walk(scope):
                if not isinstance(n, ast.Call):
                    continue
                name = _dotted(n.func).rsplit(".", 1)[-1]
                if name not in self._METERS:
                    continue
                arg = n.args[0] if n.args else next(
                    (kw.value for kw in n.keywords
                     if kw.arg == "name"), None)
                if arg is None:
                    continue
                if not (self._dynamic(arg) or
                        (isinstance(arg, ast.Name) and
                         arg.id in bound)):
                    continue
                key = (n.lineno, n.col_offset)
                if key in seen:
                    continue
                seen.add(key)
                yield self.finding(
                    ctx, n,
                    f"{name}(...) mints a dynamically-built metric "
                    f"name — per-request values in a NAME create one "
                    f"time series per value (unbounded cardinality); "
                    f"move the variable part into a label and keep "
                    f"the name a literal (or noqa a code-site-"
                    f"constant family with a reason)")


class UnguardedMetaLogAppendRule(Rule):
    """SWFS018: a Python `MetaLog.append`/`append_raw` call reachable
    from the filer's hot-path handlers without the meta-plane guard.

    The native meta plane (native/meta_plane.cc, ISSUE 17) only arms
    when `Filer.meta_plane` exists: armed, the C++ plane is the WAL
    appender for hot-path creates, and the Python side's only legal
    hot-path commit is `MetaPlane.commit` (whose appender half lives
    in filer/meta_plane.py and stays exempt).  A direct
    `meta_log.append(...)` in the filer front or server is therefore
    correct ONLY on the meta-plane-less fallback branch — anywhere
    else it would put a second, GIL-bound appender back on the armed
    hot path, with its own wid and its own barrier, silently undoing
    the plane's zero-Python contract.  Flagged: any `*.meta_log
    .append`/`.append_raw` call in the filer front/server modules not
    enclosed in an `if` whose test names `meta_plane` (the arming
    gate).  Replay/boot helpers that run before the plane exists keep
    their direct append under `# noqa: SWFS018` with a reason."""

    id = "SWFS018"
    severity = "error"
    title = "MetaLog append reachable from the armed filer hot path"

    _FILES = ("seaweedfs_tpu/filer/filer.py",
              "seaweedfs_tpu/server/filer_server.py")
    _APPENDS = {"append", "append_raw"}

    @staticmethod
    def _names_meta_plane(test: ast.AST) -> bool:
        for n in ast.walk(test):
            if isinstance(n, ast.Attribute) and \
                    n.attr == "meta_plane":
                return True
            if isinstance(n, ast.Name) and n.id == "meta_plane":
                return True
        return False

    def check(self, ctx: FileContext):
        rel = ctx.relpath.replace("\\", "/")
        if not any(rel.endswith(f) for f in self._FILES):
            return
        parents: dict = {}
        for parent in ast.walk(ctx.tree):
            for child in ast.iter_child_nodes(parent):
                parents[child] = parent
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or \
                    not isinstance(node.func, ast.Attribute):
                continue
            if node.func.attr not in self._APPENDS:
                continue
            if "meta_log" not in _dotted(node.func):
                continue
            cur: ast.AST = node
            guarded = False
            while cur in parents and not guarded:
                parent = parents[cur]
                if isinstance(parent, ast.If) and \
                        self._names_meta_plane(parent.test):
                    guarded = True
                    break
                # early-return guard style: a PRECEDING statement in
                # the same suite tested meta_plane and returned (`if
                # self.meta_plane is not None: return ...commit(...)`)
                # — the append after it is the fallback branch
                for field in ("body", "orelse", "finalbody"):
                    stmts = getattr(parent, field, None)
                    if isinstance(stmts, list) and cur in stmts:
                        guarded = any(
                            isinstance(prev, ast.If) and
                            self._names_meta_plane(prev.test)
                            for prev in stmts[:stmts.index(cur)])
                        break
                if isinstance(parent, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    break       # a guard outside the function is not
                cur = parent    # evidence about this call site
            if guarded:
                continue
            yield self.finding(
                ctx, node,
                f"{_dotted(node.func)}(...) appends to the metalog "
                f"outside the meta-plane guard — armed, the native "
                f"meta plane owns the hot-path WAL, so a direct "
                f"Python append belongs only on the `if meta_plane "
                f"is None` fallback branch (or noqa a boot/replay "
                f"helper with a reason)")


class PlaneLabelDriftRule(Rule):
    """SWFS019: a stage/fallback/stat label exported by a C++ plane
    with no matching literal in its Python drain driver.

    The planes name their flight-record stages, fallback reasons and
    stats in `const char* const` tables (kRecStageNames /
    kRecFallbackNames / kStatsNames); the Python drivers render those
    same labels into histograms, cluster.slow stage decompositions
    and cluster.top lines from their own literal tuples
    (RECORD_STAGES / RECORD_FALLBACKS / _STATS_KEYS).  The pairing is
    positional and stringly-typed across a language boundary no type
    checker sees, so a label added or renamed C-side with no matching
    Python literal silently misattributes every drained record.
    Flagged: any literal in a plane's C++ name table that appears
    nowhere as a string literal in the paired driver module.  Only
    the three driver modules are checked; checkouts without the
    native sources are skipped."""

    id = "SWFS019"
    severity = "error"
    title = "native-plane label missing from the Python drain table"

    _PAIRS = {
        "seaweedfs_tpu/server/meta_plane_native.py":
            "seaweedfs_tpu/native/meta_plane.cc",
        "seaweedfs_tpu/server/write_plane.py":
            "seaweedfs_tpu/native/write_plane.cc",
        "seaweedfs_tpu/server/read_plane.py":
            "seaweedfs_tpu/native/read_plane.cc",
        "seaweedfs_tpu/server/filer_read_plane_native.py":
            "seaweedfs_tpu/native/filer_read_plane.cc",
    }
    _TABLES = (("kRecStageNames", "RECORD_STAGES"),
               ("kRecFallbackNames", "RECORD_FALLBACKS"),
               ("kStatsNames", "_STATS_KEYS"))

    @staticmethod
    def _cc_labels(src: str, array: str) -> "list[str]":
        m = re.search(array + r"\[\]\s*=\s*\{(.*?)\}", src, re.S)
        return re.findall(r'"([^"]*)"', m.group(1)) if m else []

    def check(self, ctx: FileContext):
        rel = ctx.relpath.replace("\\", "/")
        cc_rel = next((cc for py, cc in self._PAIRS.items()
                       if rel.endswith(py)), None)
        if cc_rel is None:
            return
        from .analyze import repo_root
        try:
            with open(os.path.join(repo_root(), *cc_rel.split("/")),
                      encoding="utf-8") as f:
                cc_src = f.read()
        except OSError:
            return      # no native sources beside this checkout
        literals = {n.value for n in ast.walk(ctx.tree)
                    if isinstance(n, ast.Constant) and
                    isinstance(n.value, str)}
        anchors: dict = {}
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        anchors[t.id] = n
        default_anchor = ctx.tree.body[0] if ctx.tree.body else None
        for array, table in self._TABLES:
            for label in self._cc_labels(cc_src, array):
                if label in literals:
                    continue
                node = anchors.get(table, default_anchor)
                if node is None:
                    continue
                yield self.finding(
                    ctx, node,
                    f'{cc_rel} exports "{label}" in {array} but this '
                    f"driver has no matching literal — the {table} "
                    f"render/drain table is positional and stringly-"
                    f"typed across the ctypes boundary, so every "
                    f"drained record would carry a wrong or missing "
                    f"label in cluster.slow/cluster.top")


class UnguardedReadPathLookupRule(Rule):
    """SWFS020: a store lookup on the filer's hot-path GET handler
    with no read-plane fill fence captured first.

    The native filer read plane (native/filer_read_plane.cc, ISSUE
    19) keeps a C-side entry map that the Python front refills after
    its own store lookups (`warm_fill`).  A fill is only coherent if
    its generation token was captured BEFORE the store SELECT — a
    token taken after (or never) lets a fill land over an
    invalidation that raced the lookup, and the plane then serves
    pre-overwrite bytes.  So the contract on every GET-shaped handler
    in the filer front is a fixed statement order: `begin_fill()` (or
    an explicit `native_read` test) first, `find_entry(...)` after.
    Flagged: any `*.find_entry(...)` call inside a `_get*` handler of
    the filer server with no preceding statement that names
    `begin_fill` or `native_read`.  Handlers that can never feed the
    plane (mutation endpoints, list/stat surfaces) are out of scope
    by name; a deliberate unfenced probe takes `# noqa: SWFS020`
    with a reason."""

    id = "SWFS020"
    severity = "error"
    title = "filer GET-path store lookup without a read-plane fence"

    _FILES = ("seaweedfs_tpu/server/filer_server.py",)

    @staticmethod
    def _names_fence(node: ast.AST) -> bool:
        for n in ast.walk(node):
            if isinstance(n, ast.Attribute) and \
                    n.attr in ("begin_fill", "native_read"):
                return True
            if isinstance(n, ast.Name) and \
                    n.id in ("begin_fill", "native_read"):
                return True
        return False

    def check(self, ctx: FileContext):
        rel = ctx.relpath.replace("\\", "/")
        if not any(rel.endswith(f) for f in self._FILES):
            return
        parents: dict = {}
        for parent in ast.walk(ctx.tree):
            for child in ast.iter_child_nodes(parent):
                parents[child] = parent
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or \
                    not isinstance(node.func, ast.Attribute) or \
                    node.func.attr != "find_entry":
                continue
            # scope: only the GET-shaped handlers feed warm fills
            fn: "ast.AST | None" = node
            while fn in parents and not isinstance(
                    fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = parents[fn]
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)) or \
                    not fn.name.startswith("_get"):
                continue
            cur: ast.AST = node
            fenced = False
            while cur in parents and not fenced:
                parent = parents[cur]
                for field in ("body", "orelse", "finalbody"):
                    stmts = getattr(parent, field, None)
                    if isinstance(stmts, list) and cur in stmts:
                        fenced = any(
                            self._names_fence(prev)
                            for prev in stmts[:stmts.index(cur)])
                        break
                if parent is fn:
                    break       # the fence must sit inside the
                cur = parent    # handler, before the lookup
            if fenced:
                continue
            yield self.finding(
                ctx, node,
                f"{_dotted(node.func)}(...) runs the store lookup in "
                f"{fn.name}() with no read-plane fence before it — "
                f"capture the plane generation (begin_fill) or test "
                f"native_read first, or a warm fill landing after a "
                f"raced invalidation serves pre-overwrite bytes from "
                f"the C-side entry map")


class CompetingControllerRule(Rule):
    """SWFS021: runtime mutation of an autopilot-controlled knob
    outside the control registry.

    The SLO autopilot (autopilot.py, ISSUE 20) closes a feedback loop
    over a fixed set of module-global knobs: hedge ratio/floor,
    brownout factor, cache sizes, worker fleet.  Those knobs are
    single-writer by design — a second runtime writer (a debug
    handler poking `hedge.set_ratio`, a server start-up path writing
    the knob's env var) forms a second controller on the same plant,
    and the two fight: each one's "correction" is the other's
    disturbance, so the knob oscillates instead of settling.  The one
    mutation path is the registry: an `Actuator` registered on the
    autopilot, driven through `actuate()` (bounded, damped, logged).
    Flagged: (a) calls to a knob setter (`set_ratio`,
    `set_min_threshold_ms`, `set_brownout_factor`, `set_limit`,
    `set_mem_limit`, `set_capacity`) outside autopilot.py and the
    setter's own defining module; (b) writes to a knob env var
    (`os.environ[...] = / .setdefault / os.putenv`) anywhere but
    autopilot.py.  Exempt with `# noqa: SWFS021` and a reason —
    legitimate for reset-to-baseline paths (hedge.reset, qos.reset)
    and test rigs that deliberately misconfigure a knob."""

    id = "SWFS021"
    severity = "error"
    title = "autopilot-controlled knob mutated outside the registry"

    _REGISTRY = "seaweedfs_tpu/autopilot.py"
    # setter -> the module that defines it (internal delegation inside
    # the defining module is wiring, not a second controller)
    _SETTERS = {
        "set_ratio": "seaweedfs_tpu/util/hedge.py",
        "set_min_threshold_ms": "seaweedfs_tpu/util/hedge.py",
        "set_brownout_factor": "seaweedfs_tpu/qos.py",
        "set_limit": "seaweedfs_tpu/util/chunk_cache.py",
        "set_mem_limit": "seaweedfs_tpu/util/chunk_cache.py",
        "set_capacity": "seaweedfs_tpu/filer/meta_cache.py",
    }
    _ENVS = frozenset((
        "SEAWEEDFS_TPU_HEDGE_RATIO", "SEAWEEDFS_TPU_HEDGE_MIN_MS",
        "SEAWEEDFS_TPU_HEDGE_BURST", "SEAWEEDFS_TPU_BROWNOUT_FACTOR",
    ))

    @staticmethod
    def _env_key(node: ast.AST) -> "str | None":
        """The literal key of an `os.environ[...]` subscript."""
        if isinstance(node, ast.Subscript) and \
                _dotted(node.value) == "os.environ" and \
                isinstance(node.slice, ast.Constant) and \
                isinstance(node.slice.value, str):
            return node.slice.value
        return None

    def check(self, ctx: FileContext):
        rel = ctx.relpath.replace("\\", "/")
        if rel.endswith(self._REGISTRY):
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                name = node.func.attr \
                    if isinstance(node.func, ast.Attribute) \
                    else (node.func.id
                          if isinstance(node.func, ast.Name) else "")
                if name in self._SETTERS and \
                        not rel.endswith(self._SETTERS[name]):
                    yield self.finding(
                        ctx, node,
                        f"{_dotted(node.func)}(...) mutates an "
                        f"autopilot-controlled knob outside the "
                        f"control registry — a second runtime writer "
                        f"fights the control loop (each correction is "
                        f"the other's disturbance); register an "
                        f"Actuator on the autopilot and go through "
                        f"actuate() instead")
                    continue
                # os.environ.setdefault("KNOB", ...) / os.putenv
                if (isinstance(node.func, ast.Attribute) and
                        node.func.attr == "setdefault" and
                        _dotted(node.func.value) == "os.environ") or \
                        _dotted(node.func) == "os.putenv":
                    if node.args and \
                            isinstance(node.args[0], ast.Constant) and \
                            node.args[0].value in self._ENVS:
                        yield self.finding(
                            ctx, node,
                            f"writes knob env var "
                            f"{node.args[0].value} at runtime — the "
                            f"env is the knob's operator-set "
                            f"baseline; runtime control goes through "
                            f"the autopilot registry")
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets \
                    if isinstance(node, ast.Assign) else [node.target]
                for t in targets:
                    key = self._env_key(t)
                    if key in self._ENVS:
                        yield self.finding(
                            ctx, t,
                            f"writes knob env var {key} at runtime — "
                            f"the env is the knob's operator-set "
                            f"baseline; runtime control goes through "
                            f"the autopilot registry")


RULES = [
    LockDisciplineRule(),
    JitBlockingRule(),
    StructWidthRule(),
    SwallowedExceptionRule(),
    UnclosedHandleRule(),
    WallClockRule(),
    LeakedSpanRule(),
    UnclosedShardStreamRule(),
    MissingTimeoutRule(),
    MissingAdmissionRule(),
    WallDurationRule(),
    FlushUnderLockRule(),
    UnboundedBodyReadRule(),
    AsyncBlockingCallRule(),
    FilerHotPathCommitRule(),
    BareTimeoutLiteralRule(),
    DynamicMetricNameRule(),
    UnguardedMetaLogAppendRule(),
    PlaneLabelDriftRule(),
    UnguardedReadPathLookupRule(),
    CompetingControllerRule(),
]
