"""Runtime lock-order race detector (the dynamic half of `weed
analyze`).

`install()` replaces `threading.Lock`/`threading.RLock` with tracked
wrappers keyed by ALLOCATION SITE (file:line — every lock minted at one
site is one node, the right granularity for order analysis).  Only
locks allocated from seaweedfs_tpu code are tracked: a stdlib site
(queue.Queue's mutex, Condition's internal RLock) would alias many
unrelated instances onto one node and manufacture false cycles.  Each
acquisition records held-lock -> acquired-lock edges per thread; a new
edge that closes a cycle in the global graph is a potential-deadlock
violation recorded with both acquisition stacks.  While any tracked
lock is held, `time.sleep` and `socket.create_connection` record
hold-while-blocking violations (the lock convoy / jit-stall class).

Opt-in: set WEED_LOCKGRAPH=1 (and optionally WEED_LOCKGRAPH_OUT=path)
before process start; `python -m seaweedfs_tpu` calls
`maybe_instrument()` first thing, and the proc-cluster test framework
sets the flag for every server role so tier-1 runs double as a race
harness.  Violations are flushed to the report file the moment they
are found (servers die by SIGTERM/SIGKILL — atexit alone is not
enough).

Detection NEVER raises into application code: a detector that can
kill a volume server is worse than the deadlock it hunts.
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading
import time
import traceback

_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock
_ORIG_SLEEP = time.sleep

# sleeps shorter than this while holding a lock are tolerated (tight
# retry backoffs); longer ones starve every waiter for the duration
HOLD_SLEEP_THRESHOLD = 0.05


def _format_site(frame) -> str:
    parts = frame.f_code.co_filename.replace(os.sep, "/").split("/")
    return "/".join(parts[-2:]) + f":{frame.f_lineno}"


def _short_stack(limit: int = 12) -> list[str]:
    out = []
    for f in traceback.extract_stack()[:-2][-limit:]:
        out.append(f"{f.filename.split(os.sep)[-1]}:{f.lineno}:{f.name}")
    return out


class LockGraph:
    """Global acquisition-order graph + violation log."""

    def __init__(self, out_path: "str | None" = None):
        self._mu = _ORIG_LOCK()      # leaf lock: guards graph state
        self._local = threading.local()
        self.edges: dict[str, set] = {}
        self.edge_stacks: dict[tuple, list] = {}
        self.violations: list[dict] = []
        self._seen: set = set()
        self.out_path = out_path
        self.acquisitions = 0

    # -- per-thread held stack -------------------------------------------

    def held(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = []
            self._local.stack = st
        return st

    # -- events ----------------------------------------------------------

    def on_acquired(self, name: str) -> None:
        st = self.held()
        with self._mu:
            self.acquisitions += 1
            for h in st:
                if h == name:
                    # reentrant RLock, or a SIBLING instance from the
                    # same allocation site.  Site-level nodes cannot
                    # tell those apart, so instance-pair inversions
                    # inside one lock class are invisible to the
                    # cycle check — surface the nesting pattern
                    # itself so the report points at where an
                    # instance-ordering discipline must exist.
                    self._record_same_site_locked(name)
                    continue
                tgt = self.edges.setdefault(h, set())
                if name not in tgt:
                    tgt.add(name)
                    self.edge_stacks[(h, name)] = _short_stack()
                    self._check_cycle_locked(h, name)
        st.append(name)

    def _record_same_site_locked(self, name: str) -> None:
        key = ("same-site", name)
        if key in self._seen:
            return
        self._seen.add(key)
        self.violations.append({
            "kind": "same-site-nesting",
            "lock": name,
            "note": "nested acquisition of two locks from one "
                    "allocation site (or an RLock re-entry): "
                    "instance-pair AB/BA inversions here are NOT "
                    "covered by cycle detection — verify an "
                    "instance-ordering discipline",
            "stack": _short_stack(),
        })
        self._flush_locked()

    def on_released(self, name: str) -> None:
        st = self.held()
        for i in range(len(st) - 1, -1, -1):
            if st[i] == name:
                del st[i]
                break

    def on_blocking_call(self, what: str, detail: str) -> None:
        st = self.held()
        if not st:
            return
        with self._mu:
            key = ("block", what, tuple(st))
            if key in self._seen:
                return
            self._seen.add(key)
            self.violations.append({
                "kind": "hold-while-blocking",
                "call": what,
                "detail": detail,
                "held": list(st),
                "stack": _short_stack(),
            })
            self._flush_locked()

    # -- cycle detection --------------------------------------------------

    def _check_cycle_locked(self, src: str, dst: str) -> None:
        """Adding src->dst closed a cycle iff dst already reaches src."""
        path = self._path_locked(dst, src)
        if path is None:
            return
        cycle = path + [dst]          # dst ... src (-> dst)
        key = ("cycle", frozenset(cycle))
        if key in self._seen:
            return
        self._seen.add(key)
        stacks = {}
        hops = list(zip(cycle, cycle[1:] + cycle[:1]))
        for a, b in hops:
            if (a, b) in self.edge_stacks:
                stacks[f"{a} -> {b}"] = self.edge_stacks[(a, b)]
        self.violations.append({
            "kind": "lock-order-cycle",
            "cycle": cycle,
            "stacks": stacks,
        })
        self._flush_locked()

    def _path_locked(self, start: str, goal: str) -> "list | None":
        seen = {start}
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            if node == goal:
                return path
            for nxt in self.edges.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    # -- reporting --------------------------------------------------------

    def cycles(self) -> list:
        with self._mu:
            return [v for v in self.violations
                    if v["kind"] == "lock-order-cycle"]

    def _doc_locked(self) -> dict:
        """The report document — single definition for report() and
        the on-disk flush (edges as lists, matching the JSON shape a
        reader of the report file sees)."""
        return {
            "pid": os.getpid(),
            "acquisitions": self.acquisitions,
            "locks": sorted(set(self.edges)
                            | {d for s in self.edges.values()
                               for d in s}),
            "edges": sorted([a, b] for a, s in self.edges.items()
                            for b in s),
            "violations": list(self.violations),
        }

    def report(self) -> dict:
        with self._mu:
            return self._doc_locked()

    def flush(self) -> None:
        with self._mu:
            self._flush_locked()

    def _flush_locked(self) -> None:
        if not self.out_path:
            return
        try:
            tmp = f"{self.out_path}.{os.getpid()}.tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(self._doc_locked(), f, indent=1)
            os.replace(tmp, self.out_path)
        except OSError:
            pass                      # never raise into app code


class TrackedLock:
    """threading.Lock/RLock wrapper reporting to a LockGraph.  Also
    speaks the Condition protocol (_release_save/_acquire_restore/
    _is_owned) so `threading.Condition(tracked_lock)` keeps the held
    bookkeeping straight across wait()."""

    def __init__(self, graph: LockGraph, name: str, inner):
        self._graph = graph
        self.name = name
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._graph.on_acquired(self.name)
        return ok

    def release(self) -> None:
        self._inner.release()
        self._graph.on_released(self.name)

    def locked(self) -> bool:
        try:
            return self._inner.locked()
        except AttributeError:   # RLock pre-3.12 has no locked()
            return self.name in self._graph.held()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # -- Condition protocol ----------------------------------------------

    def _release_save(self):
        if hasattr(self._inner, "_release_save"):
            state = self._inner._release_save()
        else:
            self._inner.release()
            state = None
        st = self._graph.held()
        n = st.count(self.name)
        for _ in range(n):
            self._graph.on_released(self.name)
        return (state, n)

    def _acquire_restore(self, saved) -> None:
        state, n = saved
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        # re-held after wait(): push without edge recording — waking
        # from a cv wait is not an ordering decision by this code path
        self._graph.held().extend([self.name] * max(n, 1))

    def _is_owned(self) -> bool:
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        return self.name in self._graph.held()

    def _at_fork_reinit(self) -> None:
        self._inner._at_fork_reinit()

    def __getattr__(self, name):
        # stdlib internals poke at lock attributes we don't model
        # (e.g. os.register_at_fork handlers) — delegate them
        return getattr(self._inner, name)

    def __repr__(self) -> str:
        return f"<TrackedLock {self.name} {self._inner!r}>"


_graph: "LockGraph | None" = None


def graph() -> "LockGraph | None":
    return _graph


def _lock_factory(g: LockGraph, inner_factory):
    def factory():
        fr = sys._getframe(1)
        fn = fr.f_code.co_filename
        # track ONLY locks minted from this package's code.  Stdlib
        # allocation sites (queue.Queue's mutex, Condition's internal
        # RLock, logging) would each alias MANY unrelated instances
        # onto one graph node, manufacturing provably-false cycles
        # (two different queues bridging two app locks).
        if "seaweedfs_tpu" not in fn.replace(os.sep, "/"):
            return inner_factory()
        return TrackedLock(g, _format_site(fr), inner_factory())
    return factory


def _patched_sleep(g: LockGraph):
    def sleep(secs):
        if secs >= HOLD_SLEEP_THRESHOLD:
            g.on_blocking_call("time.sleep", f"{secs}s")
        return _ORIG_SLEEP(secs)
    return sleep


def install(out_path: "str | None" = None) -> LockGraph:
    """Patch lock factories process-wide; idempotent.  Returns the
    process LockGraph."""
    global _graph
    if _graph is not None:
        return _graph
    _graph = LockGraph(out_path)
    threading.Lock = _lock_factory(_graph, _ORIG_LOCK)
    threading.RLock = _lock_factory(_graph, _ORIG_RLOCK)
    time.sleep = _patched_sleep(_graph)

    import socket
    orig_create = socket.create_connection

    def create_connection(address, *a, **kw):
        _graph.on_blocking_call("socket.create_connection",
                                f"{address}")
        return orig_create(address, *a, **kw)

    socket.create_connection = create_connection
    atexit.register(_graph.flush)
    _graph.flush()          # report file exists even with 0 findings
    if out_path:
        # periodic flush: SIGTERM'd server roles skip atexit
        def flusher():
            while True:
                _ORIG_SLEEP(1.0)
                _graph.flush()
        t = threading.Thread(target=flusher, daemon=True,
                             name="lockgraph-flush")
        t.start()
    return _graph


def maybe_instrument() -> "LockGraph | None":
    """Honour the WEED_LOCKGRAPH env opt-in (CLI entry calls this
    before any server object builds its locks)."""
    if os.environ.get("WEED_LOCKGRAPH", "") not in ("1", "true", "on"):
        return None
    return install(os.environ.get("WEED_LOCKGRAPH_OUT") or None)
