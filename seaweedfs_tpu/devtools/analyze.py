"""Static-analysis engine: rule registry, noqa suppressions, baseline.

The shape mirrors how flake8-style tools work, collapsed to what this
repo needs:

* a Rule visits one file's AST (`FileContext`) and yields `Finding`s;
* `# noqa` / `# noqa: SWFS003` comments suppress findings on that line
  (codes must match; foreign codes like BLE001 do not suppress SWFS
  rules);
* a committed baseline (devtools/baseline.json) records fingerprints of
  accepted legacy findings so only NEW violations fail CI.  Fingerprints
  hash the rule id, the file's path, and the stripped source line (plus
  an occurrence index), so re-numbering lines does not invalidate the
  baseline but touching the offending code does.

Run via `python -m seaweedfs_tpu analyze [paths...]`.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
import sys
from dataclasses import dataclass, field

SEVERITIES = ("error", "warning")

_NOQA_RE = re.compile(r"#\s*noqa(?::\s*(?P<codes>[A-Z]+[A-Z0-9, ]*))?",
                      re.IGNORECASE)

_SUPPRESS_ALL = "*"


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def repo_root() -> str:
    """The directory holding the seaweedfs_tpu package — baseline paths
    are stored relative to it so analysis is cwd-independent."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


@dataclass
class Finding:
    rule: str
    severity: str
    path: str              # repo-relative when under the repo root
    line: int
    col: int
    message: str
    snippet: str = ""

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"

    def render(self) -> str:
        sev = self.severity.upper()
        out = f"{self.location()}: {sev} {self.rule}: {self.message}"
        if self.snippet:
            out += f"\n    {self.snippet}"
        return out

    def to_json(self) -> dict:
        return {"rule": self.rule, "severity": self.severity,
                "path": self.path, "line": self.line, "col": self.col,
                "message": self.message, "snippet": self.snippet}


class FileContext:
    """One parsed source file, shared by every rule: AST with parent
    links, source lines, and the per-line noqa suppression map."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self._parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent
        self.noqa: dict[int, set[str]] = {}
        for i, text in enumerate(self.lines, start=1):
            if "#" not in text or "noqa" not in text.lower():
                continue
            m = _NOQA_RE.search(text)
            if not m:
                continue
            codes = m.group("codes")
            if codes is None:
                self.noqa[i] = {_SUPPRESS_ALL}
            else:
                self.noqa[i] = {c.strip().upper()
                                for c in codes.split(",") if c.strip()}

    def parent(self, node: ast.AST) -> "ast.AST | None":
        return self._parents.get(node)

    def ancestors(self, node: ast.AST):
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def suppressed(self, rule_id: str, lineno: int) -> bool:
        codes = self.noqa.get(lineno)
        if not codes:
            return False
        return _SUPPRESS_ALL in codes or rule_id.upper() in codes


class Rule:
    """Base class: subclasses set id/severity/title and implement
    check(ctx) yielding Findings (path/snippet filled by the engine)."""

    id = "SWFS000"
    severity = "error"
    title = "abstract rule"

    def check(self, ctx: FileContext):
        raise NotImplementedError

    def finding(self, ctx: FileContext, node: ast.AST,
                message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(self.id, self.severity, ctx.relpath, line, col,
                       message, ctx.line_text(line))


# -- engine ---------------------------------------------------------------

def collect_files(targets: list[str]) -> list[str]:
    files: list[str] = []
    for t in targets:
        if os.path.isdir(t):
            for dirpath, dirnames, filenames in os.walk(t):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                files.extend(os.path.join(dirpath, f)
                             for f in sorted(filenames)
                             if f.endswith(".py"))
        elif t.endswith(".py"):
            files.append(t)
    return sorted(set(files))


def _relpath(path: str, root: str) -> str:
    ap = os.path.abspath(path)
    root = os.path.abspath(root)
    if ap.startswith(root + os.sep):
        return os.path.relpath(ap, root).replace(os.sep, "/")
    return ap.replace(os.sep, "/")


def run_paths(targets: list[str], rules=None, root: "str | None" = None
              ) -> "tuple[list[Finding], list[str]]":
    """Analyze files/dirs; returns (findings, parse_errors).  Findings
    are noqa-filtered but NOT baseline-filtered (that is a reporting
    decision, see partition_baseline)."""
    from . import rules as rules_mod
    active = list(rules) if rules is not None else list(rules_mod.RULES)
    root = root or repo_root()
    findings: list[Finding] = []
    errors: list[str] = []
    for path in collect_files(targets):
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            ctx = FileContext(path, _relpath(path, root), source)
        except (OSError, SyntaxError, ValueError) as e:
            errors.append(f"{path}: {e}")
            continue
        for rule in active:
            for fd in rule.check(ctx):
                if not ctx.suppressed(fd.rule, fd.line):
                    findings.append(fd)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, errors


# -- baseline -------------------------------------------------------------

def fingerprints(findings: list[Finding]) -> "list[tuple[Finding, str]]":
    """Stable fingerprint per finding: rule + path + stripped source
    line + occurrence index among identical triples (line-move proof,
    edit-sensitive)."""
    seen: dict[tuple, int] = {}
    out = []
    for f in findings:
        key = (f.rule, f.path, f.snippet)
        idx = seen.get(key, 0)
        seen[key] = idx + 1
        raw = f"{f.rule}|{f.path}|{f.snippet}|{idx}"
        out.append((f, hashlib.sha1(raw.encode()).hexdigest()[:16]))
    return out


def load_baseline(path: str) -> dict:
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except FileNotFoundError:
        return {}
    except (OSError, ValueError) as e:
        print(f"analyze: bad baseline {path}: {e}", file=sys.stderr)
        return {}
    return doc.get("fingerprints", {})


def save_baseline(path: str, findings: list[Finding]) -> int:
    fps = {}
    for f, fp in fingerprints(findings):
        fps[fp] = {"rule": f.rule, "path": f.path,
                   "snippet": f.snippet}
    doc = {"version": 1, "count": len(fps),
           "fingerprints": dict(sorted(fps.items()))}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return len(fps)


def partition_baseline(findings: list[Finding], baseline: dict
                       ) -> "tuple[list[Finding], list[Finding]]":
    """(new, baselined)."""
    new, old = [], []
    for f, fp in fingerprints(findings):
        (old if fp in baseline else new).append(f)
    return new, old


# -- CLI ------------------------------------------------------------------

def run_cli(paths: list[str], json_out: bool = False,
            baseline_path: str = "", write_baseline: bool = False,
            no_baseline: bool = False, rule_ids: str = "") -> int:
    from . import rules as rules_mod
    targets = paths or [os.path.join(repo_root(), "seaweedfs_tpu")]
    missing = [t for t in targets
               if not (os.path.isdir(t) or
                       (t.endswith(".py") and os.path.isfile(t)))]
    if missing:
        # a typo'd path must not read as "0 findings, all clean"
        print(f"analyze: no such file or directory: {missing}",
              file=sys.stderr)
        return 2
    active = None
    if rule_ids:
        want = {r.strip().upper() for r in rule_ids.split(",")
                if r.strip()}
        active = [r for r in rules_mod.RULES if r.id in want]
        unknown = want - {r.id for r in active}
        if unknown:
            print(f"analyze: unknown rule ids {sorted(unknown)}",
                  file=sys.stderr)
            return 2
    files = collect_files(targets)
    findings, errors = run_paths(files, rules=active)
    for e in errors:
        print(f"analyze: {e}", file=sys.stderr)

    bpath = baseline_path or default_baseline_path()
    if write_baseline:
        n = save_baseline(bpath, findings)
        print(f"analyze: wrote {n} baseline fingerprint(s) to {bpath}")
        return 0
    baseline = {} if no_baseline else load_baseline(bpath)
    new, old = partition_baseline(findings, baseline)

    if json_out:
        print(json.dumps({
            "files": len(files),
            "findings": [f.to_json() for f in new],
            "baselined": len(old),
            "errors": errors,
        }, indent=1))
    else:
        for f in new:
            print(f.render())
        n_err = sum(1 for f in new if f.severity == "error")
        n_warn = len(new) - n_err
        print(f"analyze: {n_err} error(s), {n_warn} warning(s)"
              + (f", {len(old)} baselined" if old else "")
              + (f", {len(errors)} unparsable" if errors else ""))
    return 1 if new or errors else 0
