"""s3.* shell family (reference: weed/shell/command_s3_bucket_quota*.go
+ the lifecycle enforcement pass):

    s3.bucket.quota          -bucket=b [-limitMB=N | -remove]
    s3.bucket.quota.enforce  flips over-quota buckets read-only (and
                             back) — the reference's
                             s3.bucket.quota.enforce
    s3.lifecycle.apply       one enforcement pass over every bucket
                             with a lifecycle configuration
"""

from __future__ import annotations

from ..filer.client import FilerClient
import urllib.parse

from ..server.httpd import http_bytes
from .commands import CommandEnv, _parse_flags, command


def _check_bucket_name(name: str) -> None:
    """S3 bucket-name charset (lowercase alnum, dots, dashes): also
    keeps URL metacharacters out of the filer paths these commands
    build."""
    import re
    if not name or not re.fullmatch(r"[a-z0-9][a-z0-9.\-]{1,62}",
                                    name):
        raise RuntimeError(
            f"bad bucket name {name!r} (3-63 chars, lowercase "
            "alnum/dot/dash)")

BUCKETS_ROOT = "/buckets"


def _client(env: CommandEnv) -> FilerClient:
    return FilerClient(env.require_filer())


def _bucket_usage(fc: FilerClient, path: str) -> int:
    """Recursive content bytes under a bucket (chunk extents)."""
    total = 0
    last = ""
    while True:
        batch = fc.list_directory(path, start_file=last, limit=500)
        if not batch:
            return total
        for e in batch:
            if e.is_directory:
                if not e.name.startswith("."):
                    total += _bucket_usage(fc, e.full_path)
            else:
                total += e.total_size()
        if len(batch) < 500:
            return total
        last = batch[-1].name


def _patch_extended(fc: FilerClient, path: str, patch: dict) -> None:
    # one shared client for /__meta__/patch_extended (also used by
    # the remote-storage gateway)
    from ..remote.remote_storage import _meta_patch_extended
    _meta_patch_extended(fc.filer, path, patch)


@command("s3.bucket.quota")
def s3_bucket_quota(env: CommandEnv, args: list[str]) -> str:
    flags = _parse_flags(args)
    bucket = flags.get("bucket", "")
    fc = _client(env)
    path = f"{BUCKETS_ROOT}/{bucket}"
    entry = fc.find_entry(path)
    if entry is None:
        return f"no such bucket {bucket!r}"
    if "remove" in flags:
        _patch_extended(fc, path, {"quotaBytes": "",
                                   "readOnly": ""})
        return f"quota removed from {bucket}"
    if "limitMB" not in flags:
        q = entry.extended.get("quotaBytes", "")
        used = _bucket_usage(fc, path)
        return (f"{bucket}: quota="
                f"{q or 'none'} used={used} "
                f"readOnly={entry.extended.get('readOnly', 'false')}")
    limit = int(float(flags["limitMB"]) * 1024 * 1024)
    _patch_extended(fc, path, {"quotaBytes": str(limit)})
    return f"quota on {bucket}: {limit} bytes"


@command("s3.bucket.quota.enforce")
def s3_bucket_quota_enforce(env: CommandEnv,
                            args: list[str]) -> str:
    fc = _client(env)
    lines = []
    for b in fc.list_directory(BUCKETS_ROOT, limit=10000):
        if not b.is_directory:
            continue
        quota = b.extended.get("quotaBytes", "")
        if not quota:
            continue
        used = _bucket_usage(fc, b.full_path)
        over = used > int(quota)
        was = b.extended.get("readOnly") == "true"
        if over != was:
            _patch_extended(fc, b.full_path,
                            {"readOnly": "true" if over else ""})
        lines.append(f"{b.name}: used={used}/{quota} "
                     f"{'READ-ONLY' if over else 'ok'}")
    return "\n".join(lines) or "no buckets carry quotas"


@command("s3.lifecycle.apply")
def s3_lifecycle_apply(env: CommandEnv, args: list[str]) -> str:
    from ..s3.lifecycle import (LifecycleError, apply_lifecycle,
                                parse_lifecycle)
    fc = _client(env)
    lines = []
    for b in fc.list_directory(BUCKETS_ROOT, limit=10000):
        if not b.is_directory:
            continue
        doc = b.extended.get("lifecycle", "")
        if not doc:
            continue
        try:
            rules = parse_lifecycle(doc.encode())
        except LifecycleError as e:
            lines.append(f"{b.name}: bad lifecycle config: {e}")
            continue
        deleted, aborted = apply_lifecycle(fc, b.full_path, rules)
        lines.append(f"{b.name}: expired {deleted} objects, "
                     f"aborted {aborted} uploads")
    return "\n".join(lines) or "no buckets carry lifecycle configs"


@command("s3.bucket.create")
def cmd_s3_bucket_create(env: CommandEnv, args: list[str]) -> str:
    """command_s3_bucket_create.go: a bucket is a directory under
    /buckets in the filer namespace."""
    opts = _parse_flags(args)
    name = opts.get("name", "")
    _check_bucket_name(name)
    st, body, _ = http_bytes(
        "POST", env.require_filer() +
        f"/buckets/{urllib.parse.quote(name)}/")
    if st >= 300:
        raise RuntimeError(f"create bucket: HTTP {st} {body[:120]!r}")
    return f"created bucket {name}"


@command("s3.bucket.delete")
def cmd_s3_bucket_delete(env: CommandEnv, args: list[str]) -> str:
    """command_s3_bucket_delete.go (-name=... [-force] — a non-empty
    bucket needs -force, matching the reference's guard)."""
    opts = _parse_flags(args)
    name = opts.get("name", "")
    _check_bucket_name(name)
    # existence via the metadata lookup: the directory LISTING answers
    # 200-with-empty for missing paths, so it cannot distinguish
    # "no such bucket" from "empty bucket"
    st, _, _ = http_bytes(
        "GET", env.require_filer() + "/__meta__/lookup?path=" +
        urllib.parse.quote(f"/buckets/{name}"))
    if st == 404:
        raise RuntimeError(f"no bucket {name}")
    q = urllib.parse.quote(name)
    st, body, _ = http_bytes(
        "GET", env.require_filer() + f"/buckets/{q}/?limit=1")
    import json as _json
    entries = _json.loads(body).get("entries", []) if st == 200 else []
    if entries and "force" not in opts:
        raise RuntimeError(
            f"bucket {name} is not empty; pass -force")
    st, body, _ = http_bytes(
        "DELETE", env.require_filer() + f"/buckets/{q}?recursive=true")
    if st >= 300:
        raise RuntimeError(f"delete bucket: HTTP {st}")
    return f"deleted bucket {name}"


@command("s3.bucket.list")
def cmd_s3_bucket_list(env: CommandEnv, args: list[str]) -> str:
    st, body, _ = http_bytes(
        "GET", env.require_filer() + "/buckets/?limit=10000")
    if st == 404:
        return "no buckets"
    import json as _json
    out = []
    for e in _json.loads(body).get("entries", []):
        if e.get("isDirectory"):
            out.append(e["fullPath"].rsplit("/", 1)[-1])
    return "\n".join(sorted(out)) or "no buckets"


@command("s3.circuitBreaker")
def cmd_s3_circuit_breaker(env: CommandEnv, args: list[str]) -> str:
    """command_s3_circuitbreaker.go: edit the admission-control
    config at /etc/s3/circuit_breaker.json (the gateway TTL-reloads
    it).  Usage mirrors the reference:

        s3.circuitBreaker -global -type=count -actions=Read,Write
                          -values=500,200 -apply
        s3.circuitBreaker -buckets=x,y -type=mb -actions=Write
                          -values=64 -apply
        s3.circuitBreaker -global -disable -apply
        s3.circuitBreaker -buckets=x -delete -apply
        s3.circuitBreaker -delete -apply          # clear everything

    Without -apply the resulting config is printed, not written."""
    import json as _json
    from ..s3.circuit_breaker import CONFIG_PATH, CircuitBreaker
    opts = _parse_flags(args)
    fc = _client(env)
    e = fc.find_entry(CONFIG_PATH)
    doc = {}
    if e is not None:
        raw = fc.read_file(CONFIG_PATH)
        doc = _json.loads(raw) if raw else {}
    is_global = "global" in opts
    buckets = [b for b in opts.get("buckets", "").split(",") if b]
    if "delete" in opts:
        if buckets:
            for b in buckets:
                doc.get("buckets", {}).pop(b, None)
        elif is_global:
            doc.pop("global", None)
        else:
            doc = {}
    elif "disable" in opts:
        targets = ([doc.setdefault("buckets", {}).setdefault(
            b, {"actions": {}}) for b in buckets] if buckets
            else [doc.setdefault("global", {"actions": {}})])
        for t in targets:
            t["enabled"] = False
    else:
        ltype = {"count": "Count", "mb": "MB"}.get(
            opts.get("type", "count").lower())
        if ltype is None:
            raise RuntimeError("-type must be count or mb")
        actions = [a for a in opts.get("actions", "").split(",") if a]
        values = [v for v in opts.get("values", "").split(",") if v]
        if not actions or len(actions) != len(values):
            return ("usage: s3.circuitBreaker [-global|-buckets=x,y] "
                    "-type=count|mb -actions=Read,Write "
                    "-values=N,M -apply")
        entries = {f"{a}:{ltype}": int(v)
                   for a, v in zip(actions, values)}
        targets = ([doc.setdefault("buckets", {}).setdefault(
            b, {"enabled": True, "actions": {}}) for b in buckets]
            if buckets
            else [doc.setdefault("global",
                                 {"enabled": True, "actions": {}})])
        for t in targets:
            t["enabled"] = True
            t.setdefault("actions", {}).update(entries)
    CircuitBreaker().load(doc)        # validate before write/print
    rendered = _json.dumps(doc, indent=1)
    if "apply" not in opts:
        return rendered + "\n(dry run; add -apply to write)"
    fc.write_file(CONFIG_PATH, rendered.encode(),
                  mime="application/json")
    return f"applied:\n{rendered}"


# -- S3 Tables (command_s3tables_*.go) ------------------------------------

def _s3tables_store(env: CommandEnv):
    from ..s3.s3tables import S3TablesStore
    return S3TablesStore(_client(env))


@command("s3tables.bucket")
def cmd_s3tables_bucket(env: CommandEnv, args: list[str]) -> str:
    """command_s3tables_bucket.go: manage table buckets.

        s3tables.bucket -create -name=B [-tags=k1=v1,k2=v2]
        s3tables.bucket -list [-prefix=P]
        s3tables.bucket -get -name=B
        s3tables.bucket -delete -name=B
        s3tables.bucket -put-policy -name=B -file=policy.json
        s3tables.bucket -get-policy -name=B
        s3tables.bucket -delete-policy -name=B"""
    import json as _json
    from ..s3.s3tables import S3TablesError
    opts = _parse_flags(args)
    st = _s3tables_store(env)
    name = opts.get("name", "")
    try:
        if "create" in opts:
            tags = dict(kv.split("=", 1) for kv in
                        opts.get("tags", "").split(",") if "=" in kv)
            r = st.create_table_bucket(name, tags=tags or None)
            return _json.dumps(r, indent=1)
        if "list" in opts:
            r = st.list_table_buckets(opts.get("prefix", ""),
                                      opts.get("continuation", ""),
                                      int(opts.get("limit", 0)))
            return _json.dumps(r, indent=1)
        if "get" in opts:
            return _json.dumps(st.get_table_bucket(name), indent=1)
        if "delete-policy" in opts:
            st.delete_policy(bucket_arn_=name)
            return f"deleted policy of {name}"
        if "delete" in opts:
            st.delete_table_bucket(name)
            return f"deleted table bucket {name}"
        if "put-policy" in opts:
            with open(opts["file"]) as f:
                st.put_policy(f.read(), bucket_arn_=name)
            return f"policy applied to {name}"
        if "get-policy" in opts:
            return st.get_policy(bucket_arn_=name)["resourcePolicy"]
    except S3TablesError as e:
        raise RuntimeError(f"{e.code}: {e.message}")
    return ("usage: s3tables.bucket -create|-list|-get|-delete|"
            "-put-policy|-get-policy|-delete-policy -name=B")


@command("s3tables.namespace")
def cmd_s3tables_namespace(env: CommandEnv, args: list[str]) -> str:
    """command_s3tables_namespace.go: namespaces inside a table
    bucket (-bucket=B -create|-list|-get|-delete [-name=NS])."""
    import json as _json
    from ..s3.s3tables import S3TablesError
    opts = _parse_flags(args)
    st = _s3tables_store(env)
    bucket, ns = opts.get("bucket", ""), opts.get("name", "")
    try:
        if "create" in opts:
            return _json.dumps(st.create_namespace(bucket, [ns]),
                               indent=1)
        if "list" in opts:
            return _json.dumps(
                st.list_namespaces(bucket, opts.get("prefix", "")),
                indent=1)
        if "get" in opts:
            return _json.dumps(st.get_namespace(bucket, [ns]),
                               indent=1)
        if "delete" in opts:
            st.delete_namespace(bucket, [ns])
            return f"deleted namespace {ns}"
    except S3TablesError as e:
        raise RuntimeError(f"{e.code}: {e.message}")
    return ("usage: s3tables.namespace -bucket=B "
            "-create|-list|-get|-delete [-name=NS]")


@command("s3tables.table")
def cmd_s3tables_table(env: CommandEnv, args: list[str]) -> str:
    """command_s3tables_table.go: tables inside a namespace
    (-bucket=B -namespace=NS -create|-list|-get|-delete|-update
    [-name=T] [-metadataFile=m.json] [-versionToken=V])."""
    import json as _json
    from ..s3.s3tables import S3TablesError
    opts = _parse_flags(args)
    st = _s3tables_store(env)
    bucket = opts.get("bucket", "")
    ns = [opts["namespace"]] if opts.get("namespace") else []
    name = opts.get("name", "")
    meta = None
    if opts.get("metadataFile"):
        with open(opts["metadataFile"]) as f:
            meta = _json.load(f)
    try:
        if "create" in opts:
            return _json.dumps(
                st.create_table(bucket, ns, name, metadata=meta),
                indent=1)
        if "list" in opts:
            return _json.dumps(
                st.list_tables(bucket, ns or None,
                               opts.get("prefix", "")), indent=1)
        if "get" in opts:
            return _json.dumps(st.get_table(bucket, ns, name),
                               indent=1)
        if "update" in opts:
            return _json.dumps(st.update_table(
                bucket, ns, name, opts.get("versionToken", ""),
                meta), indent=1)
        if "delete" in opts:
            st.delete_table(bucket, ns, name,
                            opts.get("versionToken", ""))
            return f"deleted table {name}"
    except S3TablesError as e:
        raise RuntimeError(f"{e.code}: {e.message}")
    return ("usage: s3tables.table -bucket=B -namespace=NS "
            "-create|-list|-get|-update|-delete [-name=T]")


@command("s3tables.tag")
def cmd_s3tables_tag(env: CommandEnv, args: list[str]) -> str:
    """command_s3tables_tag.go: tag table buckets/tables by ARN or
    bucket name (-resource=ARN -set=k=v,... | -list | -unset=k1,k2)."""
    import json as _json
    from ..s3.s3tables import S3TablesError
    opts = _parse_flags(args)
    st = _s3tables_store(env)
    arn = opts.get("resource", "")
    try:
        if opts.get("set"):
            tags = dict(kv.split("=", 1) for kv in
                        opts["set"].split(",") if "=" in kv)
            st.tag_resource(arn, tags)
            return f"tagged {arn}"
        if opts.get("unset"):
            st.untag_resource(arn, opts["unset"].split(","))
            return f"untagged {arn}"
        if "list" in opts:
            return _json.dumps(st.list_tags(arn), indent=1)
    except S3TablesError as e:
        raise RuntimeError(f"{e.code}: {e.message}")
    return "usage: s3tables.tag -resource=ARN -set=k=v|-unset=k|-list"
