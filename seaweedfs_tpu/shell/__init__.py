"""Interactive shell / command layer (weed/shell): cluster-lock-gated
maintenance commands driving master + volume servers."""

from .commands import CommandEnv, COMMANDS, run_command  # noqa: F401
from . import fs_commands  # noqa: F401  (registers fs.* + repair cmds)
from . import remote_commands  # noqa: F401  (registers remote.*)
from . import s3_commands  # noqa: F401  (registers s3.*)
from . import admin_commands  # noqa: F401  (registers volume/cluster/mq admin)
from . import s3_iam_commands  # noqa: F401  (registers s3 identity admin)
