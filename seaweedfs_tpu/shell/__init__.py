"""Interactive shell / command layer (weed/shell): cluster-lock-gated
maintenance commands driving master + volume servers."""

from .commands import CommandEnv, COMMANDS, run_command  # noqa: F401
