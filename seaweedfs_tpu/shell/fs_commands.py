"""Filer (`fs.*`) and repair-plane shell commands.

fs.* family (weed/shell/command_fs_*.go): operate on a filer's
namespace from the admin shell — ls/cat/rm/meta/mkdir/du.  The filer
address comes from the shell's -filer flag or `fs.configure`.

Repair plane:
  volume.fsck        (weed/shell/command_volume_fsck.go) — cross-
                     reference filer chunk fids against volume needles:
                     report (optionally purge) orphan needles no filer
                     entry references, and missing fids filer entries
                     still reference.
  volume.check.disk  (weed/shell/command_volume_check_disk.go) — diff
                     replica needle inventories pairwise and copy
                     missing needles from healthy replicas.
  ec.balance -proportional
                     (weed/shell/ec_proportional_rebalance.go) — spread
                     EC shards proportionally to free capacity instead
                     of evenly.
"""

from __future__ import annotations

import json
import urllib.parse

from ..server.httpd import http_bytes, http_json
from .commands import (CommandEnv, _all_node_urls, _ec_shard_locations,
                       _ec_volumes, _move_shard, _must, _parse_flags,
                       _volumes_by_id, command)


def _filer_get(env: CommandEnv, path: str, query: str = ""):
    url = env.require_filer() + urllib.parse.quote(path)
    if query:
        url += "?" + query
    return http_bytes("GET", url)


# --- fs.* family ---------------------------------------------------------

@command("fs.configure")
def cmd_fs_configure(env: CommandEnv, args: list[str]) -> str:
    opts = _parse_flags(args)
    if "filer" in opts:
        env.filer = opts["filer"]
    return f"filer = {env.filer or '(unset)'}"


def _list_dir(env: CommandEnv, path: str) -> list[dict]:
    """Full listing with lastFileName pagination — silent truncation
    here would make fsck classify unseen files' needles as orphans."""
    out: list[dict] = []
    last = ""
    while True:
        st, body, _ = _filer_get(
            env, path.rstrip("/") + "/",
            "limit=1000&lastFileName=" + urllib.parse.quote(last))
        if st != 200:
            raise RuntimeError(f"list {path}: HTTP {st}")
        batch = json.loads(body).get("entries", [])
        out.extend(batch)
        if len(batch) < 1000:
            return out
        last = batch[-1]["fullPath"].rsplit("/", 1)[-1]


@command("fs.ls")
def cmd_fs_ls(env: CommandEnv, args: list[str]) -> str:
    """command_fs_ls.go: list a directory (-l for mode/size/mtime)."""
    opts = _parse_flags(args)
    paths = [a for a in args if not a.startswith("-")] or ["/"]
    out = []
    for path in paths:
        for e in _list_dir(env, path):
            name = e["fullPath"].rsplit("/", 1)[-1]
            if e.get("isDirectory"):
                name += "/"
            if "l" in opts:
                attrs = e.get("attributes", {})
                size = sum(c.get("size", 0)
                           for c in e.get("chunks", []))
                out.append(f"{attrs.get('mode', 0):>6o} "
                           f"{size:>12} {name}")
            else:
                out.append(name)
    return "\n".join(out)


@command("fs.cat")
def cmd_fs_cat(env: CommandEnv, args: list[str]) -> str:
    """command_fs_cat.go."""
    path = next(a for a in args if not a.startswith("-"))
    st, body, _ = _filer_get(env, path)
    if st != 200:
        raise RuntimeError(f"cat {path}: HTTP {st}")
    return body.decode(errors="replace")


@command("fs.meta")
def cmd_fs_meta(env: CommandEnv, args: list[str]) -> str:
    """command_fs_meta_cat.go: raw entry metadata incl. chunk fids."""
    path = next(a for a in args if not a.startswith("-"))
    st, body, _ = http_bytes(
        "GET", f"{env.require_filer()}/__meta__/lookup?path="
        f"{urllib.parse.quote(path)}")
    if st != 200:
        raise RuntimeError(f"meta {path}: HTTP {st}")
    return json.dumps(json.loads(body), indent=2)


@command("fs.rm")
def cmd_fs_rm(env: CommandEnv, args: list[str]) -> str:
    """command_fs_rm.go (-r recursive)."""
    opts = _parse_flags(args)
    targets = [a for a in args if not a.startswith("-")]
    removed = []
    for path in targets:
        rec = "?recursive=true" if "r" in opts else ""
        st, body, _ = http_bytes(
            "DELETE",
            env.require_filer() + urllib.parse.quote(path) + rec)
        if st not in (204, 200):
            raise RuntimeError(
                f"rm {path}: HTTP {st} {body[:200]!r}")
        removed.append(path)
    return f"removed: {removed}"


@command("fs.mkdir")
def cmd_fs_mkdir(env: CommandEnv, args: list[str]) -> str:
    path = next(a for a in args if not a.startswith("-"))
    st, _, _ = http_bytes(
        "PUT", env.require_filer() + urllib.parse.quote(
            path.rstrip("/") + "/"))
    if st not in (200, 201):
        raise RuntimeError(f"mkdir {path}: HTTP {st}")
    return f"created {path}"


@command("fs.du")
def cmd_fs_du(env: CommandEnv, args: list[str]) -> str:
    """command_fs_du.go: recursive size of a subtree."""
    path = (next((a for a in args if not a.startswith("-")), "/"))

    def du(p: str) -> "tuple[int, int]":
        nbytes = nfiles = 0
        for e in _list_dir(env, p):
            if e.get("isDirectory"):
                b, f = du(e["fullPath"])
                nbytes += b
                nfiles += f
            else:
                nbytes += sum(c.get("size", 0)
                              for c in e.get("chunks", []))
                nfiles += 1
        return nbytes, nfiles

    nbytes, nfiles = du(path)
    return f"{nbytes} bytes, {nfiles} files under {path}"


# --- volume.fsck (command_volume_fsck.go) --------------------------------

def _collect_filer_fids(env: CommandEnv, path: str = "/"
                        ) -> "set[str]":
    fids: set[str] = set()
    for e in _list_dir(env, path):
        if e.get("isDirectory"):
            fids |= _collect_filer_fids(env, e["fullPath"])
        else:
            for c in e.get("chunks", []):
                fid = c.get("fileId") or c.get("fid", "")
                if fid:
                    fids.add(fid)
    return fids


def _needle_is_recent(url: str, vid: int, key: int,
                      cutoff_s: float) -> bool:
    """True if the needle was appended/modified within cutoff_s (or we
    cannot tell — err on the side of NOT purging)."""
    import struct
    import time as _time

    from ..storage.needle import Needle
    st, raw, hdrs = http_bytes(
        "GET", f"{url}/admin/needle_raw?volumeId={vid}&key={key}")
    if st != 200 or len(raw) < 16:
        return True
    try:
        version = int(hdrs.get("X-Needle-Version", 2))
        n = Needle.parse_header(raw[:16])
        n.parse_body(raw[16:], version, check_crc=False)
    except (ValueError, struct.error):
        return True
    now = _time.time()
    if n.append_at_ns:
        return now - n.append_at_ns / 1e9 < cutoff_s
    if n.last_modified:
        return now - n.last_modified < cutoff_s
    return True


def _volume_live_keys(url: str, vid: int) -> "dict[int, int]":
    r = http_json("GET", f"{url}/admin/volume_index?volumeId={vid}")
    if "error" in r:
        raise RuntimeError(f"volume_index {vid}@{url}: {r['error']}")
    return {int(k): int(s) for k, s in r["entries"]}


@command("volume.fsck")
def cmd_volume_fsck(env: CommandEnv, args: list[str]) -> str:
    """Cross-reference filer chunks against volume needles.

    Orphans (needle exists, no filer reference) are reported; pass
    -reallyDeleteFromVolume to purge them (the reference's flag name).
    Missing fids (filer references a needle that is gone) are always
    reported — they mean data loss upstream."""
    opts = _parse_flags(args)
    purge = "reallyDeleteFromVolume" in opts
    cutoff_s = float(opts.get("cutoffSeconds", 60))
    if purge:
        env.confirm_is_locked()
    referenced = _collect_filer_fids(env)
    ref_keys: dict[int, set[int]] = {}
    for fid in referenced:
        try:
            vid_s, rest = fid.split(",", 1)
            key = int(rest[:-8], 16)  # strip 8 cookie hex chars
            ref_keys.setdefault(int(vid_s), set()).add(key)
        except (ValueError, IndexError):
            continue
    orphans: list[str] = []
    missing: list[str] = []
    purged = 0
    skipped_recent = 0
    volumes = _volumes_by_id(env)
    for vid, urls in sorted(volumes.items()):
        live = _volume_live_keys(urls[0], vid)
        refs = ref_keys.get(vid, set())
        for key in sorted(set(live) - refs):
            orphans.append(f"{vid},{key:x}")
            if purge:
                if _needle_is_recent(urls[0], vid, key, cutoff_s):
                    # an in-flight upload writes its chunks BEFORE the
                    # filer entry exists; purging a fresh needle would
                    # destroy it (the reference's -cutoffTimeAgo guard,
                    # command_volume_fsck.go)
                    skipped_recent += 1
                    continue
                for url in urls:
                    http_json("POST", f"{url}/admin/delete_needle",
                              {"volumeId": vid, "key": key})
                purged += 1
        for key in sorted(refs - set(live)):
            missing.append(f"{vid},{key:x}")
    lines = [f"volumes checked: {len(volumes)}",
             f"filer-referenced fids: {len(referenced)}",
             f"orphan needles (no filer reference): {len(orphans)}"]
    if orphans:
        lines.append("  " + " ".join(orphans[:20]) +
                     (" ..." if len(orphans) > 20 else ""))
    if purge:
        lines.append(f"purged: {purged} "
                     f"(skipped {skipped_recent} newer than "
                     f"{cutoff_s:.0f}s)")
    lines.append(f"MISSING needles (filer references broken): "
                 f"{len(missing)}")
    if missing:
        lines.append("  " + " ".join(missing[:20]) +
                     (" ..." if len(missing) > 20 else ""))
    return "\n".join(lines)


# --- volume.check.disk (command_volume_check_disk.go) --------------------

@command("volume.check.disk")
def cmd_volume_check_disk(env: CommandEnv, args: list[str]) -> str:
    """Pairwise-sync replicas of each volume: needles present on one
    replica but absent on another are copied over as raw records."""
    env.confirm_is_locked()
    opts = _parse_flags(args)
    target = int(opts["volumeId"]) if "volumeId" in opts else None
    out = []
    for vid, urls in sorted(_volumes_by_id(env).items()):
        if target is not None and vid != target:
            continue
        if len(urls) < 2:
            continue
        inv = {url: _volume_live_keys(url, vid) for url in urls}
        union: set[int] = set()
        for keys in inv.values():
            union |= set(keys)
        fixed = 0
        for url in urls:
            lacking = union - set(inv[url])
            for key in sorted(lacking):
                donor = next(u for u in urls if key in inv[u])
                st, raw, hdrs = http_bytes(
                    "GET", f"{donor}/admin/needle_raw?volumeId={vid}"
                    f"&key={key}")
                if st != 200:
                    raise RuntimeError(
                        f"read needle {vid},{key:x} from {donor}: {st}")
                version = hdrs.get("X-Needle-Version", "")
                st, body, _ = http_bytes(
                    "POST", f"{url}/admin/write_needle_raw?volumeId="
                    f"{vid}&version={version}", raw)
                if st != 200:
                    raise RuntimeError(
                        f"write needle {vid},{key:x} to {url}: {st} "
                        f"{body[:200]!r}")
                fixed += 1
        out.append(f"volume {vid}: {len(urls)} replicas, "
                   f"{fixed} needles synced")
    return "\n".join(out) if out else "no replicated volumes"


# --- volume tiering (shell/command_volume_tier_move.go) ------------------

@command("volume.tier.move")
def cmd_volume_tier_move(env: CommandEnv, args: list[str]) -> str:
    """Move a volume's .dat to an S3-compatible backend; needle reads
    become ranged GETs against the backend (storage/volume_tier.go +
    backend/s3_backend).  Every replica location is converted."""
    env.confirm_is_locked()
    opts = _parse_flags(args)
    vid = int(opts["volumeId"])
    if "endpoint" not in opts:
        raise RuntimeError("volume.tier.move needs -endpoint=host:port "
                           "(an S3-compatible API, e.g. our own "
                           "gateway)")
    body = {"volumeId": vid,
            "endpoint": opts["endpoint"],
            "bucket": opts.get("bucket", "tier"),
            "accessKey": opts.get("accessKey", ""),
            "secretKey": opts.get("secretKey", ""),
            "backendId": opts.get("backendId", "default")}
    urls = [l["url"] for l in env.volume_locations(vid)]
    if not urls:
        raise RuntimeError(f"volume {vid} has no locations")
    out = []
    for url in urls:
        r = http_json("POST", f"{url}/admin/tier_move", body)
        if r.get("error"):
            raise RuntimeError(f"tier_move on {url}: {r['error']}")
        out.append(f"{url}: -> s3://{body['bucket']}/"
                   f"{r.get('key', '?')} ({r.get('fileSize', '?')}B)")
    return "\n".join(out)


@command("volume.tier.fetch")
def cmd_volume_tier_fetch(env: CommandEnv, args: list[str]) -> str:
    """Bring a tiered volume's .dat back to local disk."""
    env.confirm_is_locked()
    opts = _parse_flags(args)
    vid = int(opts["volumeId"])
    urls = [l["url"] for l in env.volume_locations(vid)]
    out = []
    for i, url in enumerate(urls):
        # only the LAST replica may delete the remote object, or the
        # remaining replicas have nothing left to download
        r = http_json("POST", f"{url}/admin/tier_fetch",
                      {"volumeId": vid,
                       "deleteRemote": i == len(urls) - 1})
        if r.get("error"):
            raise RuntimeError(f"tier_fetch on {url}: {r['error']}")
        out.append(f"{url}: fetched ({r.get('fileSize', '?')}B)")
    return "\n".join(out)


# --- ec proportional rebalance (ec_proportional_rebalance.go) ------------

@command("ec.rebalance.proportional")
def cmd_ec_rebalance_proportional(env: CommandEnv,
                                  args: list[str]) -> str:
    """Spread EC shards proportionally to each node's free volume
    capacity: nodes with more headroom carry more shards (the
    reference's proportional strategy, vs ec.balance's even spread)."""
    env.confirm_is_locked()
    opts = _parse_flags(args)
    collection = opts.get("collection", "")
    vl = env.volume_list()
    capacity: dict[str, int] = {}
    used: dict[str, int] = {}
    for dc in vl.get("dataCenters", {}).values():
        for rack in dc.get("racks", {}).values():
            for node in rack.get("nodes", []):
                url = node["url"]
                capacity[url] = int(node.get("maxVolumeCount", 8))
                used[url] = len(node.get("volumes", []))
    for url in _all_node_urls(env):
        capacity.setdefault(url, 8)
        used.setdefault(url, 0)
    free = {u: max(1, capacity[u] - used[u]) for u in capacity}
    total_free = sum(free.values())

    moved = 0
    for vid in _ec_volumes(env):
        locs = _ec_shard_locations(env, vid)
        n = sum(len(sids) for sids in locs.values())
        # proportional targets, largest-remainder rounding
        quota = {u: n * free[u] / total_free for u in free}
        tgt = {u: int(quota[u]) for u in quota}
        for u in sorted(quota, key=lambda u: quota[u] - tgt[u],
                        reverse=True):
            if sum(tgt.values()) >= n:
                break
            tgt[u] += 1
        have = {u: len(locs.get(u, [])) for u in free}
        for donor in sorted(free, key=lambda u: tgt[u] - have[u]):
            while have[donor] > tgt[donor] and locs.get(donor):
                recv = min((u for u in free if have[u] < tgt[u]),
                           key=lambda u: have[u] - tgt[u],
                           default=None)
                if recv is None:
                    break
                sid = locs[donor][-1]
                _move_shard(env, vid, collection, sid, donor, recv)
                locs[donor].remove(sid)
                locs.setdefault(recv, []).append(sid)
                have[donor] -= 1
                have[recv] += 1
                moved += 1
    return (f"proportionally rebalanced: moved {moved} shards; "
            f"capacity " +
            json.dumps({u: f"{used[u]}/{capacity[u]}"
                        for u in sorted(capacity)}))


@command("fs.mv")
def cmd_fs_mv(env: CommandEnv, args: list[str]) -> str:
    """command_fs_mv.go: rename/move within the filer namespace via
    the atomic rename RPC (filer.proto AtomicRenameEntry)."""
    paths = [a for a in args if not a.startswith("-")]
    if len(paths) != 2:
        raise RuntimeError("usage: fs.mv <source> <destination>")
    src, dst = paths
    r = http_json("POST", env.require_filer() + "/__meta__/rename",
                  {"oldPath": src, "newPath": dst})
    if "error" in r:
        raise RuntimeError(f"fs.mv: {r['error']}")
    return f"moved {src} -> {dst}"


@command("fs.tree")
def cmd_fs_tree(env: CommandEnv, args: list[str]) -> str:
    """command_fs_tree.go: recursive listing as an indented tree."""
    paths = [a for a in args if not a.startswith("-")] or ["/"]
    root = paths[0]
    lines: list[str] = [root]
    dirs = files = 0

    def walk(path: str, depth: int) -> None:
        nonlocal dirs, files
        for e in _list_dir(env, path):
            name = e["fullPath"].rsplit("/", 1)[-1]
            is_dir = e.get("isDirectory")
            lines.append("  " * (depth + 1) +
                         (name + "/" if is_dir else name))
            if is_dir:
                dirs += 1
                walk(e["fullPath"], depth + 1)
            else:
                files += 1

    walk(root.rstrip("/") or "/", 0)
    lines.append(f"{dirs} directories, {files} files")
    return "\n".join(lines)


# --- round-5 fs breadth (command_fs_cd.go, _pwd, _meta_save/_load/_cat,
#     _verify, _log) ------------------------------------------------------

def _resolve(env: CommandEnv, path: str) -> str:
    """Resolve against the shell's working directory (fs.cd),
    collapsing ./.. segments so `fs.cd ..` navigates up."""
    import posixpath
    cwd = getattr(env, "cwd", "/")
    if not path:
        return cwd
    if not path.startswith("/"):
        path = cwd.rstrip("/") + "/" + path
    return posixpath.normpath(path) or "/"


@command("fs.pwd")
def cmd_fs_pwd(env: CommandEnv, args: list[str]) -> str:
    """command_fs_pwd.go."""
    return getattr(env, "cwd", "/")


@command("fs.cd")
def cmd_fs_cd(env: CommandEnv, args: list[str]) -> str:
    """command_fs_cd.go: change the shell's filer working directory
    (relative fs.* paths resolve against it)."""
    target = _resolve(env, args[0] if args else "/")
    if target != "/":
        st, body, _ = _filer_get(
            env, "/__meta__/lookup",
            "path=" + urllib.parse.quote(target.rstrip("/")))
        if st != 200 or not json.loads(body).get("isDirectory"):
            raise RuntimeError(f"{target}: not a directory")
    env.cwd = target if target.startswith("/") else "/" + target
    return env.cwd


def _walk_entries(env: CommandEnv, directory: str):
    """Depth-first full-entry walk via the PAGINATED filer listing
    (_list_dir) — a flat limit would silently truncate large
    directories, making fs.meta.save backups and fs.verify sweeps
    incomplete without saying so."""
    for e in _list_dir(env, directory):
        yield e
        if e.get("isDirectory"):
            yield from _walk_entries(env, e["fullPath"])


@command("fs.meta.save")
def cmd_fs_meta_save(env: CommandEnv, args: list[str]) -> str:
    """command_fs_meta_save.go (-o=meta.jsonl [dir]): serialize the
    filer metadata tree (entries incl. chunk lists) to a local file
    for backup/migration."""
    opts = _parse_flags(args)
    out_path = opts.get("o", "filer-meta.jsonl")
    root = _resolve(env, next((a for a in args
                               if not a.startswith("-")), "/"))
    n = 0
    with open(out_path, "w") as f:
        for e in _walk_entries(env, root):
            f.write(json.dumps(e) + "\n")
            n += 1
    return f"saved {n} entries under {root} to {out_path}"


@command("fs.meta.load")
def cmd_fs_meta_load(env: CommandEnv, args: list[str]) -> str:
    """command_fs_meta_load.go (meta.jsonl): restore entries saved by
    fs.meta.save (full entries incl. chunk refs — the data itself must
    still live on the volume servers)."""
    src = next((a for a in args if not a.startswith("-")), "")
    if not src:
        return "usage: fs.meta.load <meta.jsonl>"
    filer = env.require_filer()
    n = 0
    with open(src) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            entry = json.loads(line)
            _must(http_json("POST", f"{filer}/__meta__/put_entry",
                            entry), f"load {entry.get('fullPath')}")
            n += 1
    return f"loaded {n} entries from {src}"


@command("fs.meta.cat")
def cmd_fs_meta_cat(env: CommandEnv, args: list[str]) -> str:
    """command_fs_meta_cat.go: the raw stored entry (attributes +
    chunk list) of one path."""
    path = _resolve(env, args[0] if args else "")
    st, body, _ = _filer_get(env, "/__meta__/lookup",
                             "path=" + urllib.parse.quote(path))
    if st != 200:
        raise RuntimeError(f"{path}: {st}")
    return json.dumps(json.loads(body), indent=1)


@command("fs.verify")
def cmd_fs_verify(env: CommandEnv, args: list[str]) -> str:
    """command_fs_verify.go ([dir]): every chunk fid of every file
    under dir must be readable on some volume server."""
    from .. import operation
    root = _resolve(env, args[0] if args else "/")
    files = chunks = broken = 0
    problems: list[str] = []
    for e in _walk_entries(env, root):
        if e.get("isDirectory"):
            continue
        files += 1
        for c in e.get("chunks", []):
            chunks += 1
            fid = c.get("fileId", "")
            try:
                vid = int(fid.split(",")[0])
                locs = operation.lookup(env.master, vid,
                                        use_cache=False)
                if not locs:
                    raise LookupError("no locations")
                # readable on SOME replica is the contract — a single
                # down server must not flag healthy data as broken
                errs = []
                for loc in locs:
                    try:
                        st, _, _ = http_bytes(
                            "HEAD", f"{loc['url']}/{fid}")
                    except OSError as oe:
                        errs.append(f"{loc['url']}: {oe}")
                        continue
                    if st == 200:
                        break
                    errs.append(f"{loc['url']}: HTTP {st}")
                else:
                    raise LookupError("; ".join(errs))
            except (OSError, LookupError, ValueError) as ex:
                broken += 1
                if len(problems) < 20:
                    problems.append(f"{e['fullPath']}: {fid}: {ex}")
    lines = [f"verified {files} files / {chunks} chunks under {root}: "
             f"{broken} broken"]
    lines += problems
    return "\n".join(lines)


@command("fs.log")
def cmd_fs_log(env: CommandEnv, args: list[str]) -> str:
    """command_fs_log.go analog: recent filer metadata log events
    (-n=20)."""
    opts = _parse_flags(args)
    n = int(opts.get("n", 20))
    st, body, _ = _filer_get(env, "/__meta__/events", "sinceNs=0")
    if st != 200:
        raise RuntimeError(f"meta events: {st}")
    events = json.loads(body).get("events", [])[-n:]
    lines = []
    for ev in events:
        path = ((ev.get("newEntry") or ev.get("oldEntry") or
                 {}).get("fullPath", "?"))
        lines.append(f"{ev.get('tsNs', 0)} {ev.get('op', '?'):7s} "
                     f"{path}")
    return "\n".join(lines) or "(no events)"


@command("fs.meta.notify")
def cmd_fs_meta_notify(env: CommandEnv, args: list[str]) -> str:
    """command_fs_meta_notify.go ([dir]): re-emit every entry under
    dir as a fresh metadata event (re-seeds filer.sync / notification
    consumers after they lost their position)."""
    filer = env.require_filer()
    root = _resolve(env, args[0] if args else "/")
    n = 0
    for e in _walk_entries(env, root):
        _must(http_json("POST", f"{filer}/__meta__/put_entry", e),
              f"notify {e.get('fullPath')}")
        n += 1
    return f"re-emitted {n} entries under {root} into the meta log"


# --- chunk relocation (command_fs_merge_volumes.go /
# command_fs_meta_change_volume_id.go) ------------------------------------

def _chunk_vid(fid: str) -> int:
    return int(fid.split(",", 1)[0])


@command("fs.meta.change.volume.id")
def cmd_fs_meta_change_volume_id(env: CommandEnv,
                                 args: list[str]) -> str:
    """command_fs_meta_change_volume_id.go: rewrite volume ids inside
    chunk fids in filer METADATA only (after an out-of-band volume
    move/renumber).

        fs.meta.change.volume.id -dir=/p -fromVolumeId=x
                                 -toVolumeId=y -apply
        fs.meta.change.volume.id -dir=/p -mapping=map.txt -apply

    mapping file lines: `1 => 2`.  Without -apply: dry run."""
    opts = _parse_flags(args)
    mapping: dict[int, int] = {}
    if opts.get("mapping"):
        with open(opts["mapping"]) as f:
            for line in f:
                line = line.strip()
                if not line or "=>" not in line:
                    continue
                a, b = line.split("=>", 1)
                mapping[int(a.strip())] = int(b.strip())
    elif "fromVolumeId" in opts and "toVolumeId" in opts:
        mapping[int(opts["fromVolumeId"])] = int(opts["toVolumeId"])
    if not mapping:
        return ("usage: fs.meta.change.volume.id -dir=/p "
                "(-fromVolumeId=x -toVolumeId=y | -mapping=f) "
                "[-apply]")
    root = _resolve(env, opts.get("dir", "/"))
    apply = "apply" in opts
    filer = env.require_filer()
    changed = files = 0
    for e in _walk_entries(env, root):
        chunks = e.get("chunks") or []
        touched = False
        for c in chunks:
            vid = _chunk_vid(c["fileId"])
            if vid in mapping:
                c["fileId"] = \
                    f"{mapping[vid]}," + c["fileId"].split(",", 1)[1]
                touched = True
                changed += 1
        if touched:
            files += 1
            if apply:
                _must(http_json("POST",
                                f"{filer}/__meta__/put_entry", e),
                      f"update {e['fullPath']}")
    verb = "changed" if apply else "would change"
    return (f"{verb} {changed} chunk refs in {files} files under "
            f"{root} ({', '.join(f'{a}=>{b}' for a, b in sorted(mapping.items()))})"
            + ("" if apply else "; add -apply to write"))


@command("fs.merge.volumes")
def cmd_fs_merge_volumes(env: CommandEnv, args: list[str]) -> str:
    """command_fs_merge_volumes.go: RELOCATE chunk data out of
    lighter volumes into a target volume so vacuum can reclaim the
    emptied ones.

        fs.merge.volumes -fromVolumeId=x -toVolumeId=y [-dir=/]
                         [-apply]

    For every file chunk on the source volume: read the bytes, write
    them to the SAME needle key on the target volume, update the
    entry's chunk fid, then delete the source needle.  Needle keys
    are cluster-unique (master sequence), so no collision on the
    target."""
    from .. import operation
    opts = _parse_flags(args)
    if "fromVolumeId" not in opts or "toVolumeId" not in opts:
        return ("usage: fs.merge.volumes -fromVolumeId=x "
                "-toVolumeId=y [-dir=/] [-apply]")
    src_vid = int(opts["fromVolumeId"])
    dst_vid = int(opts["toVolumeId"])
    if src_vid == dst_vid:
        raise RuntimeError("from and to volume are the same")
    apply = "apply" in opts
    root = _resolve(env, opts.get("dir", "/"))
    filer = env.require_filer()
    dst_locs = env.volume_locations(dst_vid)
    if not dst_locs:
        raise RuntimeError(f"target volume {dst_vid} not found")
    moved = bytes_moved = files = 0
    for e in _walk_entries(env, root):
        chunks = e.get("chunks") or []
        todo = [c for c in chunks
                if _chunk_vid(c["fileId"]) == src_vid]
        if not todo:
            continue
        files += 1
        if not apply:
            moved += len(todo)
            bytes_moved += sum(c.get("size", 0) for c in todo)
            continue
        old_fids = []
        for c in todo:
            data = operation.read(env.master, c["fileId"])
            rest = c["fileId"].split(",", 1)[1]
            new_fid = f"{dst_vid},{rest}"
            operation.upload(dst_locs[0]["url"], new_fid, data)
            old_fids.append(c["fileId"])
            c["fileId"] = new_fid
            moved += 1
            bytes_moved += len(data)
        _must(http_json("POST", f"{filer}/__meta__/put_entry", e),
              f"update {e['fullPath']}")
        # source needles die only AFTER the metadata points at the
        # new home — a crash in between leaves both copies (safe)
        for fid in old_fids:
            try:
                operation.delete(env.master, fid)
            except (OSError, RuntimeError):
                pass    # vacuum will reclaim
    verb = "moved" if apply else "would move"
    return (f"{verb} {moved} chunks ({bytes_moved} bytes) in {files} "
            f"files from volume {src_vid} to {dst_vid}"
            + ("" if apply else "; add -apply to execute"))


@command("volume.tier.compact")
def cmd_volume_tier_compact(env: CommandEnv, args: list[str]) -> str:
    """command_volume_tier_compact.go: reclaim remote-tier space —
    fetch the tiered .dat back, vacuum out deleted needles, upload
    the compacted copy to the same backend key.

        volume.tier.compact -volumeId=N [-endpoint=.. -bucket=..
                            -accessKey=.. -secretKey=..]
        volume.tier.compact [-collection=C] [-garbageThreshold=0.3]

    Backend flags are optional when the server still holds the
    backend registration from the original volume.tier.move."""
    env.confirm_is_locked()
    from .commands import _volumes_by_id
    opts = _parse_flags(args)
    threshold = float(opts.get("garbageThreshold", 0.3))
    move_body = {"backendId": opts.get("backendId", "default")}
    for k in ("endpoint", "bucket", "accessKey", "secretKey"):
        if opts.get(k):
            move_body[k] = opts[k]
    if "volumeId" in opts:
        vids = [int(opts["volumeId"])]
    else:
        vl = env.volume_list()
        collection = opts.get("collection", "")
        vids = []
        from ..topology import iter_volume_list_volumes
        for _node, v in iter_volume_list_volumes(vl):
            if not v.get("remoteTiered"):
                continue
            if collection and v.get("collection") != collection:
                continue
            size = max(v.get("size", 0), 1)
            if v.get("deletedByteCount", 0) / size >= threshold:
                vids.append(v["id"])
        vids = sorted(set(vids))
    if not vids:
        return "no remote volumes above the garbage threshold"
    out = []
    for vid in vids:
        urls = _volumes_by_id(env).get(vid) or \
            [l["url"] for l in env.volume_locations(vid)]
        for url in urls:
            r = http_json("POST", f"{url}/admin/tier_fetch",
                          {"volumeId": vid, "deleteRemote": False})
            if r.get("error"):
                raise RuntimeError(f"tier_fetch on {url}: "
                                   f"{r['error']}")
            if r.get("alreadyLocal"):
                # NOT a tiered volume: a "reclaim remote space"
                # command must never convert a local volume to
                # remote-tiered as a side effect
                raise RuntimeError(
                    f"volume {vid} on {url} is not remote-tiered; "
                    "use volume.vacuum for local volumes")
            before = r.get("fileSize", 0)
            # re-upload to the backend the volume CAME from unless
            # the operator overrode it — tier_fetch just cleared the
            # .vif binding, so "default" here would silently re-home
            # the volume (and orphan the original object)
            body = dict(move_body, volumeId=vid)
            if "backendId" not in opts and r.get("backendId"):
                body["backendId"] = r["backendId"]
            r2 = http_json("POST", f"{url}/admin/vacuum",
                           {"volumeId": vid})
            if r2.get("error"):
                raise RuntimeError(f"vacuum on {url}: {r2['error']}")
            r = http_json("POST", f"{url}/admin/tier_move", body)
            if r.get("error"):
                raise RuntimeError(f"tier_move on {url}: "
                                   f"{r['error']}")
            after = r.get("fileSize", 0)
            out.append(f"volume {vid} on {url}: {before} -> "
                       f"{after} bytes remote")
    return "\n".join(out)
