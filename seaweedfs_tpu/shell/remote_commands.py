"""remote.* shell family (weed/shell/command_remote_*.go): configure
foreign object stores, mount them into the filer namespace, cache /
uncache content, re-pull metadata.

    remote.configure -name=cloud1 -type=s3 -endpoint=host:port \\
                     -accessKey=... -secretKey=...
    remote.mount     -dir=/buckets/b -remote=cloud1/bucket[/prefix]
    remote.meta.sync -dir=/buckets/b
    remote.cache     -dir=/buckets/b [-include=path]
    remote.uncache   -dir=/buckets/b [-include=path]
    remote.unmount   -dir=/buckets/b
"""

from __future__ import annotations

import json
import urllib.parse

from ..remote import (cache_path, load_conf, load_mounts,
                      mount_remote, save_conf, save_mounts,
                      uncache_path)
from ..server.httpd import http_bytes, http_json
from .commands import CommandEnv, _must, _parse_flags, command


def _filer(env: CommandEnv) -> str:
    return env.require_filer()


@command("remote.configure")
def remote_configure(env: CommandEnv, args: list[str]) -> str:
    flags = _parse_flags(args)
    name = flags.get("name", "")
    if not name:
        # list configured remotes
        st, body, _ = http_bytes(
            "GET", f"{_filer(env)}/etc/remote/?limit=1000")
        if st != 200:
            return "no remotes configured"
        # each remote may have TWO files: <name>.conf (JSON) and the
        # reference-wire twin <name>.remote.conf — one listing entry
        names = [e["fullPath"].rsplit("/", 1)[-1]
                 .removesuffix(".conf")
                 for e in json.loads(body).get("entries", [])
                 if e["fullPath"].endswith(".conf") and
                 not e["fullPath"].endswith(".remote.conf")]
        return "\n".join(names) or "no remotes configured"
    if flags.get("type", "s3") != "s3":
        return f"unsupported remote type {flags.get('type')!r}"
    save_conf(_filer(env), name, {
        "type": "s3",
        "endpoint": flags.get("endpoint", ""),
        "accessKey": flags.get("accessKey", ""),
        "secretKey": flags.get("secretKey", ""),
    })
    return f"saved remote {name}"


def _split_remote(spec: str) -> "tuple[str, str, str]":
    """cloud1/bucket[/prefix...] -> (conf, bucket, prefix)."""
    parts = spec.strip("/").split("/", 2)
    if len(parts) < 2:
        raise ValueError(
            "remote must be <name>/<bucket>[/<prefix>]")
    return parts[0], parts[1], parts[2] if len(parts) > 2 else ""


@command("remote.mount")
def remote_mount(env: CommandEnv, args: list[str]) -> str:
    flags = _parse_flags(args)
    directory = flags.get("dir", "")
    spec = flags.get("remote", "")
    if not directory or not spec:
        mounts = load_mounts(_filer(env))
        return "\n".join(
            f"{d} -> {m['conf']}/{m['bucket']}/{m.get('keyPrefix', '')}"
            for d, m in sorted(mounts.items())) or "no mounts"
    conf, bucket, prefix = _split_remote(spec)
    n = mount_remote(_filer(env), directory, conf, bucket, prefix)
    return f"mounted {spec} at {directory} ({n} entries)"


@command("remote.meta.sync")
def remote_meta_sync(env: CommandEnv, args: list[str]) -> str:
    flags = _parse_flags(args)
    directory = flags.get("dir", "").rstrip("/")
    mounts = load_mounts(_filer(env))
    if directory not in mounts:
        return f"{directory} is not remote-mounted"
    m = mounts[directory]
    n = mount_remote(_filer(env), directory, m["conf"], m["bucket"],
                     m.get("keyPrefix", ""))
    return f"meta re-synced: {n} entries"


def _walk(filer: str, directory: str):
    last = ""
    while True:
        st, body, _ = http_bytes(
            "GET", filer + urllib.parse.quote(
                directory.rstrip("/") + "/") +
            f"?limit=500&lastFileName={urllib.parse.quote(last)}")
        if st != 200:
            return
        batch = json.loads(body).get("entries", [])
        for e in batch:
            if e.get("isDirectory"):
                yield from _walk(filer, e["fullPath"])
            else:
                yield e
        if len(batch) < 500:
            return
        last = batch[-1]["fullPath"].rsplit("/", 1)[-1]


@command("remote.cache")
def remote_cache(env: CommandEnv, args: list[str]) -> str:
    from ..remote import remote_for_path
    flags = _parse_flags(args)
    directory = flags.get("dir", "").rstrip("/")
    include = flags.get("include", "")
    # resolve the mount ONCE: per-file resolution would re-fetch the
    # mount table + conf for every entry
    located = remote_for_path(_filer(env), directory)
    if located is None:
        return f"{directory} is not under a remote mount"
    client, base_key = located
    total = files = 0
    for e in _walk(_filer(env), directory):
        if include and include not in e["fullPath"]:
            continue
        if e.get("extended", {}).get("remote") and not e.get("chunks"):
            rel = e["fullPath"][len(directory):].lstrip("/")
            key = (base_key.rstrip("/") + "/" + rel).lstrip("/") \
                if base_key else rel
            total += cache_path(_filer(env), e["fullPath"],
                                located=(client, key))
            files += 1
    return f"cached {files} files, {total} bytes"


@command("remote.uncache")
def remote_uncache(env: CommandEnv, args: list[str]) -> str:
    flags = _parse_flags(args)
    directory = flags.get("dir", "")
    include = flags.get("include", "")
    files = 0
    for e in _walk(_filer(env), directory):
        if include and include not in e["fullPath"]:
            continue
        if e.get("extended", {}).get("remote") and e.get("chunks"):
            uncache_path(_filer(env), e["fullPath"])
            files += 1
    return f"uncached {files} files"


@command("remote.unmount")
def remote_unmount(env: CommandEnv, args: list[str]) -> str:
    flags = _parse_flags(args)
    directory = flags.get("dir", "").rstrip("/")
    mounts = load_mounts(_filer(env))
    if directory not in mounts:
        return f"{directory} is not remote-mounted"
    del mounts[directory]
    save_mounts(_filer(env), mounts)
    return (f"unmounted {directory} (entries left in place; "
            f"remove with fs.rm if unwanted)")


@command("remote.mount.buckets")
def remote_mount_buckets(env: CommandEnv, args: list[str]) -> str:
    """command_remote_mount_buckets.go (-remote=conf): list the remote
    storage's buckets and mount each under /buckets/<name>."""
    flags = _parse_flags(args)
    conf_name = flags.get("remote", "")
    if not conf_name:
        return "usage: remote.mount.buckets -remote=conf " \
               "[-bucketPattern=sub]"
    pattern = flags.get("bucketPattern", "")
    filer = _filer(env)
    conf = load_conf(filer, conf_name)
    from ..remote.remote_storage import S3RemoteStorage
    client = S3RemoteStorage.from_conf(conf)
    mounted = []
    for bucket in client.list_buckets():
        if pattern and pattern not in bucket:
            continue
        n = mount_remote(filer, f"/buckets/{bucket}", conf_name,
                         bucket, "")
        mounted.append(f"/buckets/{bucket} ({n} entries)")
    return "\n".join(mounted) or "no matching buckets on the remote"


@command("remote.copy.local")
def remote_copy_local(env: CommandEnv, args: list[str]) -> str:
    """command_remote_copy_local.go: push LOCAL-only files under a
    remote mount up to the remote storage (recovery path when the
    filer log was lost or files predate the mount).

        remote.copy.local -dir=/xxx [-include=sub] [-exclude=sub]
                          [-dryRun] [-forceUpdate]

    A file is copied when the remote object is missing (or on
    -forceUpdate when its md5 differs); local metadata then carries
    the remote stat so filer.remote.sync stays idempotent."""
    import hashlib
    from ..remote import remote_for_path
    flags = _parse_flags(args)
    directory = flags.get("dir", "").rstrip("/")
    if not directory:
        return ("usage: remote.copy.local -dir=/mounted "
                "[-include=s] [-exclude=s] [-dryRun] [-forceUpdate]")
    include = flags.get("include", "")
    exclude = flags.get("exclude", "")
    dry = flags.get("dryRun", "").lower() == "true"
    force = flags.get("forceUpdate", "").lower() == "true"
    located = remote_for_path(_filer(env), directory)
    if located is None:
        return f"{directory} is not under a remote mount"
    client, base_key = located
    filer = _filer(env)
    copied = skipped = 0
    lines = []
    for e in _walk(filer, directory):
        path = e["fullPath"]
        if include and include not in path:
            continue
        if exclude and exclude in path:
            continue
        if not e.get("chunks"):
            continue            # remote-only stub, nothing local
        rel = path[len(directory):].lstrip("/")
        key = (base_key.rstrip("/") + "/" + rel).lstrip("/") \
            if base_key else rel
        # stat FIRST: on a mostly-synced mount the common case is
        # "already there" — downloading every body just to discard it
        # would cost a full dataset read per run
        stat = client.stat(key)
        if stat is not None and not force:
            skipped += 1
            continue
        st, body, _ = http_bytes(
            "GET", filer + urllib.parse.quote(path))
        if st != 200:
            continue
        etag = hashlib.md5(body).hexdigest()
        if stat is not None and stat.get("etag") == etag:
            skipped += 1        # force, but content identical
            continue
        if dry:
            lines.append(f"would copy {path} -> {key} ({len(body)}B)")
            copied += 1
            continue
        client.write(key, body)
        # record the remote stat on the entry so sync/uncache treat
        # it as materialized-remote from now on — the SAME marker
        # shape _remote_marker() builds, because mount_remote's meta
        # sync compares markers by string equality and a mismatched
        # shape would make it evict the local copy as "changed"
        from ..remote.remote_storage import _remote_marker
        _must(http_json(
            "POST", f"{filer}/__meta__/patch_extended",
            {"path": path,
             "extended": {"remote": _remote_marker(len(body),
                                                   etag)}}),
            f"mark {path}")
        copied += 1
    verb = "would copy" if dry else "copied"
    head = f"{verb} {copied} files, {skipped} already on remote"
    return head + ("\n" + "\n".join(lines[:50]) if lines else "")


@command("mount.configure")
def mount_configure(env: CommandEnv, args: list[str]) -> str:
    """command_mount_configure.go: adjust a RUNNING mount through its
    local control API (mount.proto SeaweedMount.Configure; the
    reference dials a unix socket derived from -dir, ours is the
    gRPC port the mount printed at startup).

        mount.configure -port=PORT -collectionCapacity=BYTES"""
    flags = _parse_flags(args)
    if "port" not in flags:
        return ("usage: mount.configure -port=GRPC_PORT "
                "-collectionCapacity=BYTES (0 lifts the quota)")
    capacity = int(flags.get("collectionCapacity", 0))
    try:
        import grpc
        from ..pb import mount_pb2 as mpb
        from ..pb.rpc import Stub
        from ..pb.mount_service import MOUNT_METHODS, MOUNT_SERVICE
    except ImportError:
        raise RuntimeError("grpcio not available in this environment")
    channel = grpc.insecure_channel(f"127.0.0.1:{flags['port']}")
    try:
        stub = Stub(channel, MOUNT_SERVICE, MOUNT_METHODS)
        stub.Configure(mpb.ConfigureRequest(
            collection_capacity=capacity))
    finally:
        channel.close()
    return (f"mount on :{flags['port']}: collectionCapacity="
            f"{capacity or 'unlimited'}")
