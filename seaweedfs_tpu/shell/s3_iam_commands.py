"""S3 identity/credential admin shell commands
(weed/shell/command_s3_user*.go, command_s3_accesskey*.go,
command_s3_group*.go, command_s3_policy*.go, command_s3_anonymous*.go,
command_s3_configure.go, command_s3_clean_uploads.go).

All of them operate on the shared IdentityStore JSON config
(iam/identity.py) — the same file the S3 gateway and IAM API watch by
mtime, so shell changes propagate live, the way the reference
propagates credential config through the filer
(credential/propagating_store.go)."""

from __future__ import annotations

import json
import secrets
import time
import urllib.parse

from ..iam.identity import Credential, Identity, IdentityStore
from ..server.httpd import http_bytes, http_json
from .commands import CommandEnv, _must, _parse_flags, command


def _store(env: CommandEnv, opts: dict) -> IdentityStore:
    path = opts.get("config") or getattr(env, "iam_config", "")
    if not path:
        raise RuntimeError(
            "no identities config; pass -config=/path/to/s3.json "
            "(the file the s3/iam gateways were started with)")
    env.iam_config = path
    return IdentityStore(path)


def _fmt_identity(i: Identity, verbose: bool = False) -> str:
    keys = ", ".join(c.access_key + ("" if c.status == "Active"
                                     else " (inactive)")
                     for c in i.credentials) or "-"
    line = (f"{i.name:24s} actions={len(i.actions)} keys=[{keys}]"
            + (" DISABLED" if getattr(i, 'disabled', False) else ""))
    if verbose:
        line += "\n  actions: " + (", ".join(i.actions) or "-")
    return line


# -- users ----------------------------------------------------------------

@command("s3.user.create")
def cmd_s3_user_create(env: CommandEnv, args: list[str]) -> str:
    """command_s3_user_create.go (-user=NAME [-actions=a,b]
    [-config=...]): creates the identity with a fresh access key."""
    opts = _parse_flags(args)
    name = opts.get("user", "")
    if not name:
        return "usage: s3.user.create -user=NAME [-actions=Read:bucket]"
    store = _store(env, opts)
    if store.get(name) is not None:
        raise RuntimeError(f"user {name!r} already exists")
    actions = [a for a in opts.get("actions", "").split(",") if a]
    cred = Credential(access_key=secrets.token_hex(8).upper(),
                      secret_key=secrets.token_urlsafe(24))
    store.put(Identity(name, actions=actions, credentials=[cred]))
    return (f"created {name}\naccessKey: {cred.access_key}\n"
            f"secretKey: {cred.secret_key}")


@command("s3.user.delete")
def cmd_s3_user_delete(env: CommandEnv, args: list[str]) -> str:
    """command_s3_user_delete.go (-user=NAME)."""
    opts = _parse_flags(args)
    name = opts.get("user", "")
    store = _store(env, opts)
    if store.get(name) is None:
        raise RuntimeError(f"no such user {name!r}")
    store.delete(name)
    return f"deleted {name}"


@command("s3.user.list")
def cmd_s3_user_list(env: CommandEnv, args: list[str]) -> str:
    """command_s3_user_list.go."""
    store = _store(env, _parse_flags(args))
    out = [_fmt_identity(i) for i in sorted(store, key=lambda i: i.name)]
    return "\n".join(out) or "(no identities)"


@command("s3.user.show")
def cmd_s3_user_show(env: CommandEnv, args: list[str]) -> str:
    """command_s3_user_show.go (-user=NAME)."""
    opts = _parse_flags(args)
    i = _store(env, opts).get(opts.get("user", ""))
    if i is None:
        raise RuntimeError(f"no such user {opts.get('user')!r}")
    return _fmt_identity(i, verbose=True)


def _set_disabled(env, args, disabled: bool) -> str:
    opts = _parse_flags(args)
    store = _store(env, opts)
    i = store.get(opts.get("user", ""))
    if i is None:
        raise RuntimeError(f"no such user {opts.get('user')!r}")
    i.disabled = disabled
    store.put(i)
    return f"{'disabled' if disabled else 'enabled'} {i.name}"


@command("s3.user.disable")
def cmd_s3_user_disable(env: CommandEnv, args: list[str]) -> str:
    """command_s3_user_disable.go: auth refuses a disabled identity's
    keys without deleting its config."""
    return _set_disabled(env, args, True)


@command("s3.user.enable")
def cmd_s3_user_enable(env: CommandEnv, args: list[str]) -> str:
    """command_s3_user_enable.go."""
    return _set_disabled(env, args, False)


# -- access keys ----------------------------------------------------------

@command("s3.accesskey.create")
def cmd_s3_accesskey_create(env: CommandEnv, args: list[str]) -> str:
    """command_s3_accesskey_create.go (-user=NAME): mints an extra key
    pair for key rotation."""
    opts = _parse_flags(args)
    store = _store(env, opts)
    i = store.get(opts.get("user", ""))
    if i is None:
        raise RuntimeError(f"no such user {opts.get('user')!r}")
    cred = Credential(access_key=secrets.token_hex(8).upper(),
                      secret_key=secrets.token_urlsafe(24))
    i.credentials.append(cred)
    store.put(i)
    return f"accessKey: {cred.access_key}\nsecretKey: {cred.secret_key}"


@command("s3.accesskey.delete")
def cmd_s3_accesskey_delete(env: CommandEnv, args: list[str]) -> str:
    """command_s3_accesskey_delete.go (-user=NAME -accessKey=K)."""
    opts = _parse_flags(args)
    store = _store(env, opts)
    i = store.get(opts.get("user", ""))
    if i is None:
        raise RuntimeError(f"no such user {opts.get('user')!r}")
    key = opts.get("accessKey", "")
    before = len(i.credentials)
    i.credentials = [c for c in i.credentials if c.access_key != key]
    if len(i.credentials) == before:
        raise RuntimeError(f"user {i.name} has no key {key!r}")
    store.put(i)
    return f"deleted key {key} of {i.name}"


@command("s3.accesskey.list")
def cmd_s3_accesskey_list(env: CommandEnv, args: list[str]) -> str:
    """command_s3_accesskey_list.go: every key -> identity mapping."""
    store = _store(env, _parse_flags(args))
    lines = []
    for i in sorted(store, key=lambda i: i.name):
        for c in i.credentials:
            lines.append(f"{c.access_key:20s} {i.name:20s} {c.status}")
    return "\n".join(lines) or "(no access keys)"


# -- action grants (the reference's policy attach surface) ---------------

@command("s3.policy.attach")
def cmd_s3_policy_attach(env: CommandEnv, args: list[str]) -> str:
    """command_s3_policy.go attach (-user=NAME -actions=a,b): grants
    identity actions (Read/Write/List/Tagging/Admin[:bucket])."""
    opts = _parse_flags(args)
    store = _store(env, opts)
    i = store.get(opts.get("user", ""))
    if i is None:
        raise RuntimeError(f"no such user {opts.get('user')!r}")
    new = [a for a in opts.get("actions", "").split(",") if a]
    if not new:
        return "usage: s3.policy.attach -user=NAME -actions=Read:bucket"
    i.actions = sorted(set(i.actions) | set(new))
    # operator grants are static: IAM policy recomputation must not
    # strip them (identity.py static_actions contract)
    i.static_actions = sorted(set(i.static_actions) | set(new))
    store.put(i)
    return f"{i.name} actions: {', '.join(i.actions)}"


@command("s3.policy.detach")
def cmd_s3_policy_detach(env: CommandEnv, args: list[str]) -> str:
    """command_s3_policy.go detach."""
    opts = _parse_flags(args)
    store = _store(env, opts)
    i = store.get(opts.get("user", ""))
    if i is None:
        raise RuntimeError(f"no such user {opts.get('user')!r}")
    drop = set(a for a in opts.get("actions", "").split(",") if a)
    i.actions = [a for a in i.actions if a not in drop]
    i.static_actions = [a for a in i.static_actions if a not in drop]
    store.put(i)
    return f"{i.name} actions: {', '.join(i.actions) or '-'}"


# -- anonymous access -----------------------------------------------------

@command("s3.anonymous.get")
def cmd_s3_anonymous_get(env: CommandEnv, args: list[str]) -> str:
    """command_s3_anonymous.go: show what unauthenticated requests may
    do (the identity literally named "anonymous")."""
    store = _store(env, _parse_flags(args))
    anon = store.get("anonymous")
    if anon is None:
        return "anonymous access: none"
    return "anonymous actions: " + (", ".join(anon.actions) or "-")


@command("s3.anonymous.set")
def cmd_s3_anonymous_set(env: CommandEnv, args: list[str]) -> str:
    """Grant/replace anonymous actions (-actions=Read:public,...);
    empty -actions removes anonymous access."""
    opts = _parse_flags(args)
    store = _store(env, opts)
    actions = [a for a in opts.get("actions", "").split(",") if a]
    if not actions:
        store.delete("anonymous")
        return "anonymous access removed"
    store.put(Identity("anonymous", actions=actions))
    return "anonymous actions: " + ", ".join(actions)


@command("s3.anonymous.list")
def cmd_s3_anonymous_list(env: CommandEnv, args: list[str]) -> str:
    """Buckets anonymously readable under the current grants."""
    store = _store(env, _parse_flags(args))
    anon = store.get("anonymous")
    if anon is None:
        return "(no anonymous access)"
    buckets = sorted({a.split(":", 1)[1] for a in anon.actions
                      if ":" in a} |
                     ({"*"} if any(":" not in a for a in anon.actions)
                      else set()))
    return "\n".join(buckets) or "(no anonymous access)"


# -- config ---------------------------------------------------------------

@command("s3.config.show")
def cmd_s3_config_show(env: CommandEnv, args: list[str]) -> str:
    """command_s3_configure.go read side: dump the identities JSON."""
    store = _store(env, _parse_flags(args))
    return json.dumps(store.to_json(), indent=1)


@command("s3.configure")
def cmd_s3_configure(env: CommandEnv, args: list[str]) -> str:
    """command_s3_configure.go: point the shell at an identities
    config (-config=...) and optionally apply a raw identity JSON
    (-applyJson='{"name": ...}')."""
    opts = _parse_flags(args)
    store = _store(env, opts)
    raw = opts.get("applyJson", "")
    if raw:
        d = json.loads(raw)
        store.put(Identity.from_json(d))
        return f"applied identity {d.get('name')}"
    return f"using identities config {store.path} " \
           f"({sum(1 for _ in store)} identities)"


# -- multipart hygiene ----------------------------------------------------

@command("s3.clean.uploads")
def cmd_s3_clean_uploads(env: CommandEnv, args: list[str]) -> str:
    """command_s3_clean_uploads.go (-timeAgo=24h): purge aged
    multipart-upload scratch dirs under the filer's /.uploads."""
    opts = _parse_flags(args)
    spec = opts.get("timeAgo", "24h")
    mult = {"s": 1, "m": 60, "h": 3600, "d": 86400}
    try:
        age = float(spec[:-1]) * mult[spec[-1]] \
            if spec[-1] in mult else float(spec)
    except ValueError:
        raise RuntimeError(f"bad -timeAgo {spec!r} (Ns/Nm/Nh/Nd)")
    filer = env.require_filer()
    # multipart scratch lives PER BUCKET: /buckets/<b>/.uploads/<id>
    # (s3_server.py UPLOADS_DIR under _bucket_path)
    st, body, _ = http_bytes("GET", f"{filer}/buckets/?limit=1000")
    if st == 404:
        return "purged 0 multipart uploads"
    buckets = [e["fullPath"].rsplit("/", 1)[-1]
               for e in json.loads(body).get("entries", [])
               if e.get("isDirectory")]
    # entry mtimes are cross-process wall timestamps written by the
    # filer — the wall clock is the only shared clock
    cutoff = time.time() - age  # noqa: SWFS011
    purged = 0
    for bucket in buckets:
        st, body, _ = http_bytes(
            "GET", f"{filer}/buckets/"
                   f"{urllib.parse.quote(bucket)}/.uploads/"
                   f"?limit=1000")
        if st != 200:
            continue
        for e in json.loads(body).get("entries", []):
            mtime = e.get("attributes", {}).get("mtime", 0)
            if mtime and mtime < cutoff:
                _must(http_json(
                    "DELETE",
                    f"{filer}{urllib.parse.quote(e['fullPath'])}"
                    f"?recursive=true"), f"purge {e['fullPath']}")
                purged += 1
    return f"purged {purged} multipart uploads older than {spec}"


# -- bucket administration (command_s3_bucket_*.go) -----------------------

def _bucket_entry(env: CommandEnv, bucket: str) -> dict:
    filer = env.require_filer()
    st, body, _ = http_bytes(
        "GET", f"{filer}/__meta__/lookup?path=" +
        urllib.parse.quote(f"/buckets/{bucket}"))
    if st != 200:
        raise RuntimeError(f"no bucket {bucket!r} ({st})")
    return json.loads(body)


def _patch_bucket(env: CommandEnv, bucket: str, extended: dict) -> None:
    filer = env.require_filer()
    _bucket_entry(env, bucket)  # existence check
    _must(http_json("POST", f"{filer}/__meta__/patch_extended",
                    {"path": f"/buckets/{bucket}",
                     "extended": extended}),
          f"update bucket {bucket}")


@command("s3.bucket.versioning")
def cmd_s3_bucket_versioning(env: CommandEnv, args: list[str]) -> str:
    """command_s3_bucket_versioning.go (-bucket=B
    [-status=Enabled|Suspended]): read or set the bucket versioning
    state the gateway enforces (stored on the bucket entry, the same
    place PutBucketVersioning writes)."""
    opts = _parse_flags(args)
    bucket = opts.get("bucket", "")
    if not bucket:
        return "usage: s3.bucket.versioning -bucket=B [-status=Enabled]"
    status = opts.get("status", "")
    if status:
        if status not in ("Enabled", "Suspended"):
            raise RuntimeError("status must be Enabled or Suspended")
        _patch_bucket(env, bucket, {"versioning": status})
        return f"{bucket}: versioning {status}"
    e = _bucket_entry(env, bucket)
    return f"{bucket}: versioning " \
           f"{e.get('extended', {}).get('versioning') or 'unset'}"


@command("s3.bucket.owner")
def cmd_s3_bucket_owner(env: CommandEnv, args: list[str]) -> str:
    """command_s3_bucket_owner.go analog (-bucket=B [-owner=ID]):
    read/set the owning account id recorded on the bucket entry (the
    gateway's ACL owner checks read it)."""
    opts = _parse_flags(args)
    bucket = opts.get("bucket", "")
    if not bucket:
        return "usage: s3.bucket.owner -bucket=B [-owner=accountId]"
    owner = opts.get("owner", "")
    if owner:
        _patch_bucket(env, bucket, {"x-amz-owner-id": owner})
        return f"{bucket}: owner {owner}"
    e = _bucket_entry(env, bucket)
    return f"{bucket}: owner " \
           f"{e.get('extended', {}).get('x-amz-owner-id') or 'unset'}"


@command("s3.user.provision")
def cmd_s3_user_provision(env: CommandEnv, args: list[str]) -> str:
    """command_s3_user_provision.go shape: one-shot onboarding —
    create the user (if absent), a bucket named for it (if absent),
    and grant the user full access to that bucket."""
    opts = _parse_flags(args)
    name = opts.get("user", "")
    if not name:
        return "usage: s3.user.provision -user=NAME [-bucket=B]"
    bucket = opts.get("bucket", name)
    store = _store(env, opts)
    created_user = False
    i = store.get(name)
    key_note = ""
    if i is None:
        cred = Credential(access_key=secrets.token_hex(8).upper(),
                          secret_key=secrets.token_urlsafe(24))
        i = Identity(name, credentials=[cred])
        created_user = True
        key_note = (f"\naccessKey: {cred.access_key}"
                    f"\nsecretKey: {cred.secret_key}")
    grants = {f"Read:{bucket}", f"Write:{bucket}", f"List:{bucket}",
              f"Tagging:{bucket}"}
    i.actions = sorted(set(i.actions) | grants)
    i.static_actions = sorted(set(i.static_actions) | grants)
    store.put(i)
    filer = env.require_filer()
    st, _, _ = http_bytes(
        "HEAD", f"{filer}/buckets/{urllib.parse.quote(bucket)}")
    created_bucket = False
    if st != 200:
        _must(http_json("POST", f"{filer}/__meta__/create",
                        {"path": f"/buckets/{bucket}",
                         "isDirectory": True}),
              f"create bucket {bucket}")
        created_bucket = True
    return (f"{'created' if created_user else 'updated'} user {name}; "
            f"{'created' if created_bucket else 'kept'} bucket "
            f"{bucket}; granted {', '.join(sorted(grants))}"
            + key_note)


# -- groups (command_s3_group_*.go; iam.proto Group) ----------------------

@command("s3.group.create")
def cmd_s3_group_create(env: CommandEnv, args: list[str]) -> str:
    """command_s3_group_create.go (-name=G [-policies=p1,p2]): a new
    (normally empty) group; members inherit the coarse translation of
    every attached managed policy (identity.py group_actions)."""
    opts = _parse_flags(args)
    name = opts.get("name", "")
    if not name:
        return "usage: s3.group.create -name=GROUP [-policies=p1,p2]"
    store = _store(env, opts)
    if store.get_group(name) is not None:
        raise RuntimeError(f"group {name!r} already exists")
    policies = [p for p in opts.get("policies", "").split(",") if p]
    for p in policies:
        if store.get_policy(p) is None:
            raise RuntimeError(f"no managed policy {p!r} "
                               "(create it with s3.policy first)")
    store.put_group(name, {"name": name, "members": [],
                           "policyNames": policies,
                           "disabled": False})
    return f"created group {name}"


@command("s3.group.delete")
def cmd_s3_group_delete(env: CommandEnv, args: list[str]) -> str:
    """command_s3_group_delete.go: removing a group revokes its
    policy grants from every member at once."""
    opts = _parse_flags(args)
    name = opts.get("name", "")
    store = _store(env, opts)
    if store.get_group(name) is None:
        raise RuntimeError(f"no such group {name!r}")
    store.delete_group(name)
    return f"deleted group {name}"


@command("s3.group.list")
def cmd_s3_group_list(env: CommandEnv, args: list[str]) -> str:
    """command_s3_group_list.go."""
    store = _store(env, _parse_flags(args))
    lines = []
    for name, g in sorted(store.list_groups().items()):
        lines.append(f"{name:24s} members={len(g.get('members', []))} "
                     f"policies=[{','.join(g.get('policyNames', []))}]"
                     + (" DISABLED" if g.get("disabled") else ""))
    return "\n".join(lines) or "(no groups)"


@command("s3.group.show")
def cmd_s3_group_show(env: CommandEnv, args: list[str]) -> str:
    """command_s3_group_show.go: full group document."""
    opts = _parse_flags(args)
    store = _store(env, opts)
    g = store.get_group(opts.get("name", ""))
    if g is None:
        raise RuntimeError(f"no such group {opts.get('name')!r}")
    return json.dumps(g, indent=1)


@command("s3.group.add.user")
def cmd_s3_group_add_user(env: CommandEnv, args: list[str]) -> str:
    """command_s3_group_add_user.go (-name=G -user=U): membership
    takes effect on the user's next request (grants are recomputed
    inside put_group)."""
    opts = _parse_flags(args)
    store = _store(env, opts)
    g = store.get_group(opts.get("name", ""))
    if g is None:
        raise RuntimeError(f"no such group {opts.get('name')!r}")
    user = opts.get("user", "")
    if store.get(user) is None:
        raise RuntimeError(f"no such user {user!r}")
    if user in g.get("members", []):
        return f"{user} already in {g['name']}"
    g.setdefault("members", []).append(user)
    store.put_group(g["name"], g)
    return f"added {user} to {g['name']}"


@command("s3.group.remove.user")
def cmd_s3_group_remove_user(env: CommandEnv, args: list[str]) -> str:
    """command_s3_group_remove_user.go."""
    opts = _parse_flags(args)
    store = _store(env, opts)
    g = store.get_group(opts.get("name", ""))
    if g is None:
        raise RuntimeError(f"no such group {opts.get('name')!r}")
    user = opts.get("user", "")
    if user not in g.get("members", []):
        raise RuntimeError(f"{user!r} not in {g['name']}")
    g["members"] = [m for m in g["members"] if m != user]
    store.put_group(g["name"], g)
    return f"removed {user} from {g['name']}"


# -- managed policies (command_s3_policy.go; iam.proto Policy) ------------

@command("s3.policy")
def cmd_s3_policy(env: CommandEnv, args: list[str]) -> str:
    """command_s3_policy.go: manage MANAGED policy documents
    (-list | -name=P [-content=JSON | -file=path | -delete]).
    Attach them to groups (s3.group.create -policies=...); per-user
    coarse grants stay on s3.policy.attach/detach."""
    opts = _parse_flags(args)
    store = _store(env, opts)
    if "list" in opts:
        pols = store.list_policies()
        return "\n".join(sorted(pols)) or "(no managed policies)"
    name = opts.get("name", "")
    if not name:
        return ("usage: s3.policy -list | "
                "-name=P [-content=JSON|-file=F|-delete]")
    if "delete" in opts:
        if store.get_policy(name) is None:
            raise RuntimeError(f"no such policy {name!r}")
        store.delete_policy(name)
        return f"deleted policy {name}"
    content = opts.get("content", "")
    if opts.get("file"):
        with open(opts["file"]) as f:
            content = f.read()
    if content:
        from ..iam.iamapi import policy_to_actions
        policy_to_actions(content)       # validate before storing
        store.put_policy(name, content)
        return f"stored policy {name}"
    doc = store.get_policy(name)
    if doc is None:
        raise RuntimeError(f"no such policy {name!r}")
    return doc


# -- service accounts (command_s3_serviceaccount_*.go) --------------------

@command("s3.serviceaccount.create")
def cmd_s3_sa_create(env: CommandEnv, args: list[str]) -> str:
    """command_s3_serviceaccount_create.go (-user=PARENT
    [-description=..] [-actions=a,b] [-expiry=24h]): application
    credentials parented to a user.  -actions must be a subset the
    parent could itself perform; empty inherits the parent's grants
    (including future changes)."""
    opts = _parse_flags(args)
    store = _store(env, opts)
    parent = store.get(opts.get("user", ""))
    if parent is None:
        raise RuntimeError(f"no such user {opts.get('user')!r}")
    actions = [a for a in opts.get("actions", "").split(",") if a]
    for a in actions:
        act, _, scope = a.partition(":")
        bucket, _, key = scope.partition("/")
        if not parent.can_do(act, bucket, key):
            raise RuntimeError(
                f"parent {parent.name} cannot {a!r}; a service "
                "account cannot exceed its parent")
    expiration = 0
    spec = opts.get("expiry", "")
    if spec:
        mult = {"s": 1, "m": 60, "h": 3600, "d": 86400}
        try:
            secs = float(spec[:-1]) * mult[spec[-1]] \
                if spec[-1] in mult else float(spec)
        except ValueError:
            raise RuntimeError(f"bad -expiry {spec!r} (Ns/Nm/Nh/Nd)")
        expiration = int(time.time() + secs)
    sa_id = "sa-" + secrets.token_hex(6)
    cred = Credential(access_key=secrets.token_hex(8).upper(),
                      secret_key=secrets.token_urlsafe(24))
    store.put_service_account({
        "id": sa_id, "parentUser": parent.name,
        "description": opts.get("description", ""),
        "credential": cred.to_json(), "actions": actions,
        "expiration": expiration, "disabled": False,
        "createdAt": int(time.time()), "createdBy": "shell"})
    return (f"id: {sa_id}\naccessKey: {cred.access_key}\n"
            f"secretKey: {cred.secret_key}")


@command("s3.serviceaccount.delete")
def cmd_s3_sa_delete(env: CommandEnv, args: list[str]) -> str:
    """command_s3_serviceaccount_delete.go (-id=sa-xxx)."""
    opts = _parse_flags(args)
    store = _store(env, opts)
    sa_id = opts.get("id", "")
    if store.get_service_account(sa_id) is None:
        raise RuntimeError(f"no such service account {sa_id!r}")
    store.delete_service_account(sa_id)
    return f"deleted service account {sa_id}"


@command("s3.serviceaccount.list")
def cmd_s3_sa_list(env: CommandEnv, args: list[str]) -> str:
    """command_s3_serviceaccount_list.go ([-user=PARENT])."""
    opts = _parse_flags(args)
    store = _store(env, opts)
    lines = []
    for sa in sorted(store.list_service_accounts(opts.get("user", "")),
                     key=lambda s: s["id"]):
        exp = sa.get("expiration", 0)
        state = ("DISABLED" if sa.get("disabled") else
                 "EXPIRED" if exp and exp < time.time() else "active")
        lines.append(
            f"{sa['id']:20s} parent={sa.get('parentUser', ''):16s} "
            f"key={sa.get('credential', {}).get('accessKey', '-')} "
            f"{state}")
    return "\n".join(lines) or "(no service accounts)"


@command("s3.serviceaccount.show")
def cmd_s3_sa_show(env: CommandEnv, args: list[str]) -> str:
    """command_s3_serviceaccount_show.go (-id=sa-xxx): full document
    minus the secret key."""
    opts = _parse_flags(args)
    store = _store(env, opts)
    sa = store.get_service_account(opts.get("id", ""))
    if sa is None:
        raise RuntimeError(f"no such service account {opts.get('id')!r}")
    redacted = dict(sa)
    if redacted.get("credential"):
        redacted["credential"] = {
            **redacted["credential"], "secretKey": "<redacted>"}
    return json.dumps(redacted, indent=1)


# -- key rotation + config portability ------------------------------------

@command("s3.accesskey.rotate")
def cmd_s3_accesskey_rotate(env: CommandEnv, args: list[str]) -> str:
    """command_s3_accesskey_rotate.go (-user=U -accessKey=OLD):
    mint-new-then-delete-old in one step; the brief both-valid window
    the reference documents does not exist here because the swap is
    a single store.put."""
    opts = _parse_flags(args)
    store = _store(env, opts)
    i = store.get(opts.get("user", ""))
    if i is None:
        raise RuntimeError(f"no such user {opts.get('user')!r}")
    old = opts.get("accessKey", "")
    if old and all(c.access_key != old for c in i.credentials):
        raise RuntimeError(f"user {i.name} has no key {old!r}")
    if not old:
        if len(i.credentials) != 1:
            raise RuntimeError(
                f"user {i.name} has {len(i.credentials)} keys; "
                "pass -accessKey=OLD to pick one")
        old = i.credentials[0].access_key
    cred = Credential(access_key=secrets.token_hex(8).upper(),
                      secret_key=secrets.token_urlsafe(24))
    i.credentials = [c for c in i.credentials
                     if c.access_key != old] + [cred]
    store.put(i)
    return (f"rotated {old} -> {cred.access_key}\n"
            f"secretKey: {cred.secret_key}")


@command("s3.iam.export")
def cmd_s3_iam_export(env: CommandEnv, args: list[str]) -> str:
    """command_s3_iam_export.go (-file=out.json): portable dump of
    the whole identity/policy/group/service-account config."""
    opts = _parse_flags(args)
    store = _store(env, opts)
    doc = json.dumps(store.to_json(), indent=1)
    out = opts.get("file", "")
    if out:
        with open(out, "w") as f:
            f.write(doc)
        return f"exported {out}"
    return doc


@command("s3.iam.import")
def cmd_s3_iam_import(env: CommandEnv, args: list[str]) -> str:
    """command_s3_iam_import.go (-file=in.json [-merge]): load a
    previously exported config.  Default REPLACES the store; -merge
    keeps existing entries not present in the file."""
    opts = _parse_flags(args)
    store = _store(env, opts)
    src = opts.get("file", "")
    if not src:
        return "usage: s3.iam.import -file=dump.json [-merge]"
    with open(src) as f:
        doc = json.load(f)
    if "merge" in opts:
        merged = store.to_json()
        have = {i["name"] for i in merged["identities"]}
        merged["identities"].extend(
            i for i in doc.get("identities", [])
            if i["name"] not in have)
        for k in ("policies", "groups"):
            merged[k] = {**doc.get(k, {}), **merged.get(k, {})}
        have_sa = {s["id"] for s in merged.get("serviceAccounts", [])}
        merged.setdefault("serviceAccounts", []).extend(
            s for s in doc.get("serviceAccounts", [])
            if s["id"] not in have_sa)
        doc = merged
    store.load_json(doc)
    store.save()
    n = len(doc.get("identities", []))
    return f"imported {n} identities from {src}"


# -- per-bucket access + object lock (command_s3_bucket_*.go) -------------

@command("s3.bucket.access")
def cmd_s3_bucket_access(env: CommandEnv, args: list[str]) -> str:
    """command_s3_bucket_access.go (-name=B -user=U
    [-access=Read,List|none]): view or replace a user's
    bucket-scoped grants; the user is auto-created, and "none"
    strips every grant scoped to the bucket."""
    opts = _parse_flags(args)
    bucket = opts.get("name", "")
    user = opts.get("user", "")
    if not bucket or not user:
        return ("usage: s3.bucket.access -name=B -user=U "
                "[-access=Read,List|none]")
    store = _store(env, opts)
    i = store.get(user)
    spec = opts.get("access", "")

    def _on_bucket(a: str) -> bool:
        # both whole-bucket ("Read:b") and path-scoped
        # ("Read:b/prefix") grants target this bucket — -access=none
        # must strip BOTH or revocation silently leaves path access
        _, _, scope = a.partition(":")
        return scope == bucket or scope.startswith(bucket + "/")

    if not spec:
        if i is None:
            return f"{user}: no access to {bucket}"
        scoped = [a for a in i.granted_actions()
                  if ":" in a and _on_bucket(a)]
        return f"{user} on {bucket}: " + (", ".join(scoped) or "none")
    if i is None:
        i = Identity(user, credentials=[Credential(
            access_key=secrets.token_hex(8).upper(),
            secret_key=secrets.token_urlsafe(24))])
    keep = [a for a in i.actions if ":" not in a or not _on_bucket(a)]
    keep_static = [a for a in i.static_actions
                   if ":" not in a or not _on_bucket(a)]
    if spec.lower() != "none":
        allowed = {"Read", "Write", "List", "Tagging", "Admin"}
        new = []
        for a in spec.split(","):
            if a and a not in allowed:
                raise RuntimeError(f"unknown action {a!r} "
                                   f"(use {'/'.join(sorted(allowed))})")
            if a:
                new.append(f"{a}:{bucket}")
        keep = sorted(set(keep) | set(new))
        keep_static = sorted(set(keep_static) | set(new))
    i.actions, i.static_actions = keep, keep_static
    store.put(i)
    i = store.get(user)          # re-read: group grants recomputed
    scoped = [a for a in i.actions if ":" in a and _on_bucket(a)]
    out = f"{user} on {bucket}: " + (", ".join(scoped) or "none")
    inherited = [a for a in i.group_actions
                 if ":" in a and _on_bucket(a)]
    if inherited:
        # stripping per-user actions cannot revoke group-inherited
        # grants — saying "none" while access survives would mislead
        # the operator into believing access was revoked
        out += (f"\nWARNING: still inherited via groups: "
                f"{', '.join(inherited)} (edit the group or its "
                "policies to revoke)")
    return out


@command("s3.bucket.lock")
def cmd_s3_bucket_lock(env: CommandEnv, args: list[str]) -> str:
    """command_s3_bucket_lock.go (-name=B [-enable]): view or enable
    WORM Object Lock.  Enabling turns versioning on (a lock
    prerequisite) and is irreversible, matching AWS semantics."""
    opts = _parse_flags(args)
    bucket = opts.get("name", "")
    if not bucket:
        return "usage: s3.bucket.lock -name=B [-enable]"
    e = _bucket_entry(env, bucket)
    state = e.get("extended", {}).get("objectLock") or "Disabled"
    if "enable" not in opts:
        return f"{bucket}: object lock {state}"
    if state == "Enabled":
        return f"{bucket}: object lock already Enabled"
    _patch_bucket(env, bucket, {"versioning": "Enabled",
                                "objectLock": "Enabled"})
    return f"{bucket}: object lock Enabled (versioning Enabled)"
