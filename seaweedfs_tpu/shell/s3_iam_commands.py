"""S3 identity/credential admin shell commands
(weed/shell/command_s3_user*.go, command_s3_accesskey*.go,
command_s3_group*.go, command_s3_policy*.go, command_s3_anonymous*.go,
command_s3_configure.go, command_s3_clean_uploads.go).

All of them operate on the shared IdentityStore JSON config
(iam/identity.py) — the same file the S3 gateway and IAM API watch by
mtime, so shell changes propagate live, the way the reference
propagates credential config through the filer
(credential/propagating_store.go)."""

from __future__ import annotations

import json
import secrets
import time
import urllib.parse

from ..iam.identity import Credential, Identity, IdentityStore
from ..server.httpd import http_bytes, http_json
from .commands import CommandEnv, _must, _parse_flags, command


def _store(env: CommandEnv, opts: dict) -> IdentityStore:
    path = opts.get("config") or getattr(env, "iam_config", "")
    if not path:
        raise RuntimeError(
            "no identities config; pass -config=/path/to/s3.json "
            "(the file the s3/iam gateways were started with)")
    env.iam_config = path
    return IdentityStore(path)


def _fmt_identity(i: Identity, verbose: bool = False) -> str:
    keys = ", ".join(c.access_key + ("" if c.status == "Active"
                                     else " (inactive)")
                     for c in i.credentials) or "-"
    line = (f"{i.name:24s} actions={len(i.actions)} keys=[{keys}]"
            + (" DISABLED" if getattr(i, 'disabled', False) else ""))
    if verbose:
        line += "\n  actions: " + (", ".join(i.actions) or "-")
    return line


# -- users ----------------------------------------------------------------

@command("s3.user.create")
def cmd_s3_user_create(env: CommandEnv, args: list[str]) -> str:
    """command_s3_user_create.go (-user=NAME [-actions=a,b]
    [-config=...]): creates the identity with a fresh access key."""
    opts = _parse_flags(args)
    name = opts.get("user", "")
    if not name:
        return "usage: s3.user.create -user=NAME [-actions=Read:bucket]"
    store = _store(env, opts)
    if store.get(name) is not None:
        raise RuntimeError(f"user {name!r} already exists")
    actions = [a for a in opts.get("actions", "").split(",") if a]
    cred = Credential(access_key=secrets.token_hex(8).upper(),
                      secret_key=secrets.token_urlsafe(24))
    store.put(Identity(name, actions=actions, credentials=[cred]))
    return (f"created {name}\naccessKey: {cred.access_key}\n"
            f"secretKey: {cred.secret_key}")


@command("s3.user.delete")
def cmd_s3_user_delete(env: CommandEnv, args: list[str]) -> str:
    """command_s3_user_delete.go (-user=NAME)."""
    opts = _parse_flags(args)
    name = opts.get("user", "")
    store = _store(env, opts)
    if store.get(name) is None:
        raise RuntimeError(f"no such user {name!r}")
    store.delete(name)
    return f"deleted {name}"


@command("s3.user.list")
def cmd_s3_user_list(env: CommandEnv, args: list[str]) -> str:
    """command_s3_user_list.go."""
    store = _store(env, _parse_flags(args))
    out = [_fmt_identity(i) for i in sorted(store, key=lambda i: i.name)]
    return "\n".join(out) or "(no identities)"


@command("s3.user.show")
def cmd_s3_user_show(env: CommandEnv, args: list[str]) -> str:
    """command_s3_user_show.go (-user=NAME)."""
    opts = _parse_flags(args)
    i = _store(env, opts).get(opts.get("user", ""))
    if i is None:
        raise RuntimeError(f"no such user {opts.get('user')!r}")
    return _fmt_identity(i, verbose=True)


def _set_disabled(env, args, disabled: bool) -> str:
    opts = _parse_flags(args)
    store = _store(env, opts)
    i = store.get(opts.get("user", ""))
    if i is None:
        raise RuntimeError(f"no such user {opts.get('user')!r}")
    i.disabled = disabled
    store.put(i)
    return f"{'disabled' if disabled else 'enabled'} {i.name}"


@command("s3.user.disable")
def cmd_s3_user_disable(env: CommandEnv, args: list[str]) -> str:
    """command_s3_user_disable.go: auth refuses a disabled identity's
    keys without deleting its config."""
    return _set_disabled(env, args, True)


@command("s3.user.enable")
def cmd_s3_user_enable(env: CommandEnv, args: list[str]) -> str:
    """command_s3_user_enable.go."""
    return _set_disabled(env, args, False)


# -- access keys ----------------------------------------------------------

@command("s3.accesskey.create")
def cmd_s3_accesskey_create(env: CommandEnv, args: list[str]) -> str:
    """command_s3_accesskey_create.go (-user=NAME): mints an extra key
    pair for key rotation."""
    opts = _parse_flags(args)
    store = _store(env, opts)
    i = store.get(opts.get("user", ""))
    if i is None:
        raise RuntimeError(f"no such user {opts.get('user')!r}")
    cred = Credential(access_key=secrets.token_hex(8).upper(),
                      secret_key=secrets.token_urlsafe(24))
    i.credentials.append(cred)
    store.put(i)
    return f"accessKey: {cred.access_key}\nsecretKey: {cred.secret_key}"


@command("s3.accesskey.delete")
def cmd_s3_accesskey_delete(env: CommandEnv, args: list[str]) -> str:
    """command_s3_accesskey_delete.go (-user=NAME -accessKey=K)."""
    opts = _parse_flags(args)
    store = _store(env, opts)
    i = store.get(opts.get("user", ""))
    if i is None:
        raise RuntimeError(f"no such user {opts.get('user')!r}")
    key = opts.get("accessKey", "")
    before = len(i.credentials)
    i.credentials = [c for c in i.credentials if c.access_key != key]
    if len(i.credentials) == before:
        raise RuntimeError(f"user {i.name} has no key {key!r}")
    store.put(i)
    return f"deleted key {key} of {i.name}"


@command("s3.accesskey.list")
def cmd_s3_accesskey_list(env: CommandEnv, args: list[str]) -> str:
    """command_s3_accesskey_list.go: every key -> identity mapping."""
    store = _store(env, _parse_flags(args))
    lines = []
    for i in sorted(store, key=lambda i: i.name):
        for c in i.credentials:
            lines.append(f"{c.access_key:20s} {i.name:20s} {c.status}")
    return "\n".join(lines) or "(no access keys)"


# -- action grants (the reference's policy attach surface) ---------------

@command("s3.policy.attach")
def cmd_s3_policy_attach(env: CommandEnv, args: list[str]) -> str:
    """command_s3_policy.go attach (-user=NAME -actions=a,b): grants
    identity actions (Read/Write/List/Tagging/Admin[:bucket])."""
    opts = _parse_flags(args)
    store = _store(env, opts)
    i = store.get(opts.get("user", ""))
    if i is None:
        raise RuntimeError(f"no such user {opts.get('user')!r}")
    new = [a for a in opts.get("actions", "").split(",") if a]
    if not new:
        return "usage: s3.policy.attach -user=NAME -actions=Read:bucket"
    i.actions = sorted(set(i.actions) | set(new))
    # operator grants are static: IAM policy recomputation must not
    # strip them (identity.py static_actions contract)
    i.static_actions = sorted(set(i.static_actions) | set(new))
    store.put(i)
    return f"{i.name} actions: {', '.join(i.actions)}"


@command("s3.policy.detach")
def cmd_s3_policy_detach(env: CommandEnv, args: list[str]) -> str:
    """command_s3_policy.go detach."""
    opts = _parse_flags(args)
    store = _store(env, opts)
    i = store.get(opts.get("user", ""))
    if i is None:
        raise RuntimeError(f"no such user {opts.get('user')!r}")
    drop = set(a for a in opts.get("actions", "").split(",") if a)
    i.actions = [a for a in i.actions if a not in drop]
    i.static_actions = [a for a in i.static_actions if a not in drop]
    store.put(i)
    return f"{i.name} actions: {', '.join(i.actions) or '-'}"


# -- anonymous access -----------------------------------------------------

@command("s3.anonymous.get")
def cmd_s3_anonymous_get(env: CommandEnv, args: list[str]) -> str:
    """command_s3_anonymous.go: show what unauthenticated requests may
    do (the identity literally named "anonymous")."""
    store = _store(env, _parse_flags(args))
    anon = store.get("anonymous")
    if anon is None:
        return "anonymous access: none"
    return "anonymous actions: " + (", ".join(anon.actions) or "-")


@command("s3.anonymous.set")
def cmd_s3_anonymous_set(env: CommandEnv, args: list[str]) -> str:
    """Grant/replace anonymous actions (-actions=Read:public,...);
    empty -actions removes anonymous access."""
    opts = _parse_flags(args)
    store = _store(env, opts)
    actions = [a for a in opts.get("actions", "").split(",") if a]
    if not actions:
        store.delete("anonymous")
        return "anonymous access removed"
    store.put(Identity("anonymous", actions=actions))
    return "anonymous actions: " + ", ".join(actions)


@command("s3.anonymous.list")
def cmd_s3_anonymous_list(env: CommandEnv, args: list[str]) -> str:
    """Buckets anonymously readable under the current grants."""
    store = _store(env, _parse_flags(args))
    anon = store.get("anonymous")
    if anon is None:
        return "(no anonymous access)"
    buckets = sorted({a.split(":", 1)[1] for a in anon.actions
                      if ":" in a} |
                     ({"*"} if any(":" not in a for a in anon.actions)
                      else set()))
    return "\n".join(buckets) or "(no anonymous access)"


# -- config ---------------------------------------------------------------

@command("s3.config.show")
def cmd_s3_config_show(env: CommandEnv, args: list[str]) -> str:
    """command_s3_configure.go read side: dump the identities JSON."""
    store = _store(env, _parse_flags(args))
    return json.dumps(store.to_json(), indent=1)


@command("s3.configure")
def cmd_s3_configure(env: CommandEnv, args: list[str]) -> str:
    """command_s3_configure.go: point the shell at an identities
    config (-config=...) and optionally apply a raw identity JSON
    (-applyJson='{"name": ...}')."""
    opts = _parse_flags(args)
    store = _store(env, opts)
    raw = opts.get("applyJson", "")
    if raw:
        d = json.loads(raw)
        store.put(Identity.from_json(d))
        return f"applied identity {d.get('name')}"
    return f"using identities config {store.path} " \
           f"({sum(1 for _ in store)} identities)"


# -- multipart hygiene ----------------------------------------------------

@command("s3.clean.uploads")
def cmd_s3_clean_uploads(env: CommandEnv, args: list[str]) -> str:
    """command_s3_clean_uploads.go (-timeAgo=24h): purge aged
    multipart-upload scratch dirs under the filer's /.uploads."""
    opts = _parse_flags(args)
    spec = opts.get("timeAgo", "24h")
    mult = {"s": 1, "m": 60, "h": 3600, "d": 86400}
    try:
        age = float(spec[:-1]) * mult[spec[-1]] \
            if spec[-1] in mult else float(spec)
    except ValueError:
        raise RuntimeError(f"bad -timeAgo {spec!r} (Ns/Nm/Nh/Nd)")
    filer = env.require_filer()
    # multipart scratch lives PER BUCKET: /buckets/<b>/.uploads/<id>
    # (s3_server.py UPLOADS_DIR under _bucket_path)
    st, body, _ = http_bytes("GET", f"{filer}/buckets/?limit=1000")
    if st == 404:
        return "purged 0 multipart uploads"
    buckets = [e["fullPath"].rsplit("/", 1)[-1]
               for e in json.loads(body).get("entries", [])
               if e.get("isDirectory")]
    cutoff = time.time() - age
    purged = 0
    for bucket in buckets:
        st, body, _ = http_bytes(
            "GET", f"{filer}/buckets/"
                   f"{urllib.parse.quote(bucket)}/.uploads/"
                   f"?limit=1000")
        if st != 200:
            continue
        for e in json.loads(body).get("entries", []):
            mtime = e.get("attributes", {}).get("mtime", 0)
            if mtime and mtime < cutoff:
                _must(http_json(
                    "DELETE",
                    f"{filer}{urllib.parse.quote(e['fullPath'])}"
                    f"?recursive=true"), f"purge {e['fullPath']}")
                purged += 1
    return f"purged {purged} multipart uploads older than {spec}"


# -- bucket administration (command_s3_bucket_*.go) -----------------------

def _bucket_entry(env: CommandEnv, bucket: str) -> dict:
    filer = env.require_filer()
    st, body, _ = http_bytes(
        "GET", f"{filer}/__meta__/lookup?path=" +
        urllib.parse.quote(f"/buckets/{bucket}"))
    if st != 200:
        raise RuntimeError(f"no bucket {bucket!r} ({st})")
    return json.loads(body)


def _patch_bucket(env: CommandEnv, bucket: str, extended: dict) -> None:
    filer = env.require_filer()
    _bucket_entry(env, bucket)  # existence check
    _must(http_json("POST", f"{filer}/__meta__/patch_extended",
                    {"path": f"/buckets/{bucket}",
                     "extended": extended}),
          f"update bucket {bucket}")


@command("s3.bucket.versioning")
def cmd_s3_bucket_versioning(env: CommandEnv, args: list[str]) -> str:
    """command_s3_bucket_versioning.go (-bucket=B
    [-status=Enabled|Suspended]): read or set the bucket versioning
    state the gateway enforces (stored on the bucket entry, the same
    place PutBucketVersioning writes)."""
    opts = _parse_flags(args)
    bucket = opts.get("bucket", "")
    if not bucket:
        return "usage: s3.bucket.versioning -bucket=B [-status=Enabled]"
    status = opts.get("status", "")
    if status:
        if status not in ("Enabled", "Suspended"):
            raise RuntimeError("status must be Enabled or Suspended")
        _patch_bucket(env, bucket, {"versioning": status})
        return f"{bucket}: versioning {status}"
    e = _bucket_entry(env, bucket)
    return f"{bucket}: versioning " \
           f"{e.get('extended', {}).get('versioning') or 'unset'}"


@command("s3.bucket.owner")
def cmd_s3_bucket_owner(env: CommandEnv, args: list[str]) -> str:
    """command_s3_bucket_owner.go analog (-bucket=B [-owner=ID]):
    read/set the owning account id recorded on the bucket entry (the
    gateway's ACL owner checks read it)."""
    opts = _parse_flags(args)
    bucket = opts.get("bucket", "")
    if not bucket:
        return "usage: s3.bucket.owner -bucket=B [-owner=accountId]"
    owner = opts.get("owner", "")
    if owner:
        _patch_bucket(env, bucket, {"x-amz-owner-id": owner})
        return f"{bucket}: owner {owner}"
    e = _bucket_entry(env, bucket)
    return f"{bucket}: owner " \
           f"{e.get('extended', {}).get('x-amz-owner-id') or 'unset'}"


@command("s3.user.provision")
def cmd_s3_user_provision(env: CommandEnv, args: list[str]) -> str:
    """command_s3_user_provision.go shape: one-shot onboarding —
    create the user (if absent), a bucket named for it (if absent),
    and grant the user full access to that bucket."""
    opts = _parse_flags(args)
    name = opts.get("user", "")
    if not name:
        return "usage: s3.user.provision -user=NAME [-bucket=B]"
    bucket = opts.get("bucket", name)
    store = _store(env, opts)
    created_user = False
    i = store.get(name)
    key_note = ""
    if i is None:
        cred = Credential(access_key=secrets.token_hex(8).upper(),
                          secret_key=secrets.token_urlsafe(24))
        i = Identity(name, credentials=[cred])
        created_user = True
        key_note = (f"\naccessKey: {cred.access_key}"
                    f"\nsecretKey: {cred.secret_key}")
    grants = {f"Read:{bucket}", f"Write:{bucket}", f"List:{bucket}",
              f"Tagging:{bucket}"}
    i.actions = sorted(set(i.actions) | grants)
    i.static_actions = sorted(set(i.static_actions) | grants)
    store.put(i)
    filer = env.require_filer()
    st, _, _ = http_bytes(
        "HEAD", f"{filer}/buckets/{urllib.parse.quote(bucket)}")
    created_bucket = False
    if st != 200:
        _must(http_json("POST", f"{filer}/__meta__/create",
                        {"path": f"/buckets/{bucket}",
                         "isDirectory": True}),
              f"create bucket {bucket}")
        created_bucket = True
    return (f"{'created' if created_user else 'updated'} user {name}; "
            f"{'created' if created_bucket else 'kept'} bucket "
            f"{bucket}; granted {', '.join(sorted(grants))}"
            + key_note)
