"""Shell commands (weed/shell/command_*.go).

Implemented commands (north-star set, SURVEY §3.3):
  volume.list, volume.vacuum, volume.delete, volume.mount, volume.unmount
  ec.encode, ec.decode, ec.rebuild, ec.balance
  lock, unlock, cluster.check

Commands run against a CommandEnv holding the master address and the
cluster admin lock token (shell/command_lock_unlock.go semantics:
mutating commands require the lock).
"""

from __future__ import annotations

import json
import time
from concurrent.futures import ThreadPoolExecutor

from ..operation import master_json
from ..server.httpd import http_bytes, http_json
from ..storage.erasure_coding.ec_context import to_ext

COMMANDS: dict[str, "callable"] = {}


def command(name):
    def reg(fn):
        COMMANDS[name] = fn
        fn.command_name = name
        return fn
    return reg


class CommandEnv:
    def __init__(self, master: str, filer: str = ""):
        self.master = master
        self.filer = filer  # host:port for the fs.* family
        self.admin_token: int | None = None

    def require_filer(self) -> str:
        if not self.filer:
            raise RuntimeError(
                "no filer configured; start the shell with -filer or "
                "run `fs.configure -filer=host:port`")
        return self.filer

    # -- admin lock (command_lock_unlock.go) ------------------------------

    def lock(self) -> None:
        r = master_json(self.master, "POST", "/cluster/lease_admin_token",
                      {"previousToken": self.admin_token or 0,
                       "lockName": "admin"}, timeout=30)
        if "token" not in r:
            raise RuntimeError(f"cannot acquire cluster lock: {r}")
        self.admin_token = r["token"]

    def unlock(self) -> None:
        master_json(self.master, "POST", "/cluster/release_admin_token",
                  {"previousToken": self.admin_token or 0}, timeout=30)
        self.admin_token = None

    def confirm_is_locked(self) -> None:
        """command_ec_encode.go:104 confirmIsLocked equivalent."""
        if self.admin_token is None:
            raise RuntimeError(
                "lock is lost, or it is not locked; run `lock` first")

    def volume_list(self) -> dict:
        return master_json(self.master, "GET", "/vol/list", timeout=30)

    def volume_locations(self, vid: int) -> list[dict]:
        r = master_json(self.master, "GET", f"/dir/lookup?volumeId={vid}",
                timeout=30)
        return r.get("locations", [])


# --- basic commands ------------------------------------------------------

@command("lock")
def cmd_lock(env: CommandEnv, args: list[str]) -> str:
    env.lock()
    return "locked"


@command("unlock")
def cmd_unlock(env: CommandEnv, args: list[str]) -> str:
    env.unlock()
    return "unlocked"


@command("volume.list")
def cmd_volume_list(env: CommandEnv, args: list[str]) -> str:
    """shell/command_volume_list.go."""
    return json.dumps(env.volume_list(), indent=2)


@command("cluster.check")
def cmd_cluster_check(env: CommandEnv, args: list[str]) -> str:
    r = master_json(env.master, "GET", "/cluster/status", timeout=30)
    return json.dumps(r, indent=2)


@command("volume.vacuum")
def cmd_volume_vacuum(env: CommandEnv, args: list[str]) -> str:
    """shell/command_volume_vacuum.go: compact all (or one) volume."""
    env.confirm_is_locked()
    opts = _parse_flags(args)
    target_vid = int(opts["volumeId"]) if "volumeId" in opts else None
    done = []
    for vid, urls in _volumes_by_id(env).items():
        if target_vid is not None and vid != target_vid:
            continue
        for url in urls:
            http_json("POST", f"{url}/admin/vacuum", {"volumeId": vid},
                timeout=30)
        done.append(vid)
    return f"vacuumed volumes: {sorted(done)}"


# --- EC commands (the north-star pipeline, command_ec_encode.go:86) ------

@command("ec.encode")
def cmd_ec_encode(env: CommandEnv, args: list[str]) -> str:
    """shell/command_ec_encode.go:86 Do, placement-first.

    Default `-mode=scatter`: plan every shard's destination up front
    (the same rack-spread + placement-score rules ec.balance enforces),
    then have the source server stream each shard's GF-pipeline windows
    DIRECTLY to its destination over one long chunked
    `/admin/ec/shard_write` stream — shards bound elsewhere never touch
    the source's disks and no balance re-copy round follows (the 1.4x
    source write amplification collapses to the sidecars, ~0.07x).
    `-mode=local` keeps the seed shape — generate all shards on the
    source, mount, then balance-move them off — and is the A/B
    baseline bench.py measures against
    (SEAWEEDFS_TPU_EC_ENCODE_MODE overrides the default)."""
    env.confirm_is_locked()
    opts = _parse_flags(args)
    import os as _os
    mode = opts.get("mode", _os.environ.get(
        "SEAWEEDFS_TPU_EC_ENCODE_MODE", "scatter"))
    if mode not in ("scatter", "local"):
        return f"unknown -mode={mode}; use scatter or local"
    data_shards = int(opts.get("dataShards", 10))
    parity_shards = int(opts.get("parityShards", 4))
    vids = _select_volumes(env, opts)
    if not vids:
        return "no volumes qualify for ec encoding"
    out = []
    for vid in vids:
        out.append(_do_ec_encode(env, vid, data_shards, parity_shards,
                                 opts, mode))
    return "\n".join(out)


def _do_ec_encode(env: CommandEnv, vid: int, data_shards: int,
                  parity_shards: int, opts: dict,
                  mode: str = "scatter") -> str:
    # pre-collect locations before mutating (race fix,
    # command_ec_encode.go:160-166)
    locations = env.volume_locations(vid)
    if not locations:
        raise RuntimeError(f"volume {vid} has no locations")
    collection = opts.get("collection", "")
    if collection == "ALL":
        # "ALL" is a volume-SELECTION sentinel (the empty collection),
        # never a real collection name — passing it through would make
        # generate/mount address nonexistent "ALL_<vid>" files
        collection = ""
    total = data_shards + parity_shards
    source = locations[0]["url"]
    # 1. mark all replicas readonly (:250) — and UNWIND on any later
    # failure: a failed generate/mount must not strand the volume
    # readonly forever (it is still the only copy of the data)
    marked: list[str] = []
    try:
        for loc in locations:
            _must(http_json("POST",
                            f"{loc['url']}/admin/set_readonly",
                            {"volumeId": vid, "readOnly": True}, timeout=30),
                  f"set readonly on {loc['url']}")
            marked.append(loc["url"])
        if mode == "scatter":
            # 2s. placement FIRST (the scores/rack rules balance would
            # apply after the fact), then one scatter generate: the
            # source streams every shard to its final destination and
            # mounts it there — no local mount, no balance round.
            # Failure handling: a generate that dies on specific
            # destinations reports them (failedDests) and the stripe
            # is RE-PLANNED around them — up to twice — before giving
            # up; a re-plan with no remaining candidates falls back to
            # `-mode=local` (encode still completes, balance spreads
            # later) instead of failing the job.  The planner also
            # skips peers whose circuit breaker is open.
            exclude: set = set()
            replans = 0
            while True:
                try:
                    placement = _plan_ec_placement(env, vid, total,
                                                   exclude=exclude)
                except RuntimeError:
                    if not exclude:
                        raise  # nothing excluded: a real planning error
                    # nowhere left to scatter after exclusions: local
                    # mode still completes the encode on the source
                    return _do_ec_encode(env, vid, data_shards,
                                         parity_shards, opts, "local")
                r = http_json("POST", f"{source}/admin/ec/generate", {
                    "volumeId": vid, "collection": collection,
                    "dataShards": data_shards,
                    "parityShards": parity_shards,
                    "replan": replans,
                    "placement": {str(s): u
                                  for s, u in placement.items()}},
                    timeout=600.0)
                if "error" not in r:
                    break
                failed = [d for d in r.get("failedDests", [])
                          if d != source]
                if replans >= 2 or not failed:
                    _must(r, f"scatter generate on {source}")
                dropped = set(failed) - exclude
                if not dropped:
                    _must(r, f"scatter generate on {source}")
                exclude |= dropped
                replans += 1
            moved = 0
        else:
            # 2. generate EC shards on the first replica (:359)
            _must(http_json("POST", f"{source}/admin/ec/generate", {
                "volumeId": vid, "collection": collection,
                "dataShards": data_shards,
                "parityShards": parity_shards}, timeout=600.0),
                f"generate on {source}")
            # 3. mount all shards on source (:314) — a silent mount
            # failure here would let step 5 delete the originals with
            # the EC copy unregistered (data loss)
            _must(http_json("POST", f"{source}/admin/ec/mount", {
                "volumeId": vid, "collection": collection,
                "shardIds": list(range(total))}, timeout=30),
                f"mount ec shards on {source}")
            # 4. spread shards across servers (EcBalance, :199)
            moved = _balance_ec_volume(env, vid, collection, total)
            r = {}
    except BaseException:
        # restore read-write on every replica we froze, then surface
        # the ORIGINAL error (scatter/generate handlers already tore
        # down their own partial state)
        for url in marked:
            try:
                http_json("POST", f"{url}/admin/set_readonly",
                          {"volumeId": vid, "readOnly": False}, timeout=30)
            except OSError:
                pass
        raise
    # 5. delete original volume replicas (:329) — only now, with every
    # shard mounted at its destination
    for loc in locations:
        http_json("POST", f"{loc['url']}/admin/delete_volume",
                  {"volumeId": vid}, timeout=30)
    if mode == "scatter":
        tele = r.get("telemetry") or {}
        dests = len(set((r.get("placement") or {}).values())) or 1
        msg = (f"volume {vid}: scatter-encoded {total} shards from "
               f"{source} to {dests} destinations, deleted originals")
        if tele:
            msg += (f" [{tele['bytesScatteredTotal'] >> 20}MB "
                    f"scattered, {tele['localWriteBytes'] >> 20}MB "
                    f"local, {tele['volumeGbps']} GB/s volume-rate, "
                    f"window p95 {tele['windowP95Ms']}ms]")
        return msg
    return (f"volume {vid}: encoded {total} shards on {source}, "
            f"moved {moved} shards, deleted originals")


def _plan_ec_placement(env: CommandEnv, vid: int, total: int,
                       exclude: "frozenset | set" = frozenset()
                       ) -> "dict[int, str]":
    """Placement-first shard->server plan, applying the same rules
    `_balance_ec_volume` would enforce AFTER the fact: spread across
    racks toward ceil(total/racks) per rack, even out per-server
    counts within a rack, and break ties by placement score
    (diskDistributionScore role — anti-correlation with this volume's
    shards weighs heaviest).  Computing this BEFORE encode is what
    lets scatter stream every shard to its final home in one hop.

    Robustness: nodes in `exclude` (destinations a previous attempt
    watched fail) and nodes whose circuit breaker is OPEN in this
    process's health map (util/retry) are never chosen — a tripped
    destination is planned around, not rediscovered the hard way
    mid-stripe."""
    from ..util import retry as _retry
    nodes = _all_node_urls(env)
    nodes = [n for n in nodes
             if n not in exclude and _retry.peer_available(n)]
    if not nodes:
        raise RuntimeError("no alive volume servers to place shards")
    vl = env.volume_list()   # one topology fetch for both helpers
    rack_of = _rack_of_nodes(env, vl)
    score = _ec_placement_scores(env, vid, vl)
    racks = sorted({rack_of.get(n, "?") for n in nodes})
    per_rack_cap = max(1, -(-total // len(racks)))  # ceil
    rack_load: dict[str, int] = {r: 0 for r in racks}
    node_load: dict[str, int] = {n: 0 for n in nodes}
    placement: dict[int, str] = {}
    for sid in range(total):
        open_racks = [r for r in racks if rack_load[r] < per_rack_cap]
        if not open_racks:
            open_racks = racks  # more shards than rack capacity: wrap
        rack = min(open_racks, key=lambda r: rack_load[r])
        members = [n for n in nodes if rack_of.get(n, "?") == rack]
        dst = min(members, key=lambda n: (node_load[n],
                                          score.get(n, 0)))
        placement[sid] = dst
        rack_load[rack] += 1
        node_load[dst] += 1
    return placement


def _rack_of_nodes(env: CommandEnv, vl: "dict | None" = None
                   ) -> dict[str, str]:
    """url -> "dc/rack" from the topology tree."""
    vl = vl if vl is not None else env.volume_list()
    out: dict[str, str] = {}
    for dc_name, dc in vl.get("dataCenters", {}).items():
        for rack_name, rack in dc.get("racks", {}).items():
            for node in rack.get("nodes", []):
                out[node["url"]] = f"{dc_name}/{rack_name}"
    return out


def _ec_placement_scores(env: CommandEnv, vid: int,
                         vl: "dict | None" = None) -> dict[str, int]:
    """Per-node placement score, LOWER is better
    (command_ec_common.go:1380 diskDistributionScore + :1441 pick):
    shards of THIS volume weigh 100 (anti-correlation — losing one
    node must not take multiple shards of a stripe), total EC shards
    weigh 10 (overall spread), free volume slots subtract (headroom
    attracts placements)."""
    from ..topology import iter_volume_list_ec_shards
    vl = vl if vl is not None else env.volume_list()
    scores: dict[str, int] = {}
    headroom: dict[str, int] = {}
    for dc in vl.get("dataCenters", {}).values():
        for rack in dc.get("racks", {}).values():
            for node in rack.get("nodes", []):
                headroom[node["url"]] = \
                    int(node.get("maxVolumeCount", 8)) - \
                    len(node.get("volumes", []))
                scores[node["url"]] = 0
    for node, e in iter_volume_list_ec_shards(vl):
        cnt = bin(int(e.get("ecIndexBits", 0))).count("1")
        url = node["url"]
        scores[url] = scores.get(url, 0) + cnt * 10
        if e.get("volumeId", e.get("id")) == vid:
            scores[url] += cnt * 100
    return {u: s - headroom.get(u, 0) for u, s in scores.items()}


def _balance_ec_volume(env: CommandEnv, vid: int, collection: str,
                       total: int) -> int:
    """The balance algorithm of command_ec_common.go:59-124:
    (1) dedupe shard copies, (2) spread shards across racks toward
    total/numRacks per rack, (3) even out per-server counts within each
    rack.  Destination picks among equally-loaded candidates break
    ties by placement score (diskDistributionScore role)."""
    shard_locs = _ec_shard_locations(env, vid)
    nodes = _all_node_urls(env)
    if not nodes:
        return 0
    rack_of = _rack_of_nodes(env)
    score = _ec_placement_scores(env, vid)
    moved = 0

    # (1) dedupe: keep first copy of each shard
    owner: dict[int, str] = {}
    for url, sids in sorted(shard_locs.items()):
        for sid in sids:
            if sid in owner:
                _delete_shards(url, vid, collection, [sid])
                moved += 1
            else:
                owner[sid] = url

    def load_by_url() -> dict[str, list[int]]:
        load = {n: [] for n in nodes}
        for sid, url in owner.items():
            load.setdefault(url, []).append(sid)
        return load

    def move(sid: int, src: str, dst: str):
        nonlocal moved
        _move_shard(env, vid, collection, sid, src, dst)
        owner[sid] = dst
        moved += 1

    # (2) across racks: doBalanceEcShardsAcrossRacks.  Only racks with
    # an alive member can receive (shards may sit on dead nodes whose
    # rack has no live servers).
    racks = sorted({rack_of.get(n, "?") for n in nodes})
    avg_per_rack = max(1, -(-total // max(len(racks), 1)))  # ceil
    def rack_load() -> dict[str, list[int]]:
        rl: dict[str, list[int]] = {r: [] for r in racks}
        for sid, url in owner.items():
            rl.setdefault(rack_of.get(url, "?"), []).append(sid)
        return rl
    rl = rack_load()
    for rack in sorted(rl, key=lambda r: -len(rl[r])):
        while len(rl[rack]) > avg_per_rack:
            receivable = [r for r in rl if r != rack and
                          any(rack_of.get(n, "?") == r for n in nodes)]
            if not receivable:
                break
            dest_rack = min(receivable, key=lambda r: len(rl[r]))
            if len(rl[dest_rack]) + 1 > avg_per_rack:
                break
            load = load_by_url()
            dest_candidates = [n for n in nodes
                               if rack_of.get(n, "?") == dest_rack]
            dst = min(dest_candidates,
                      key=lambda n: (len(load[n]),
                                     score.get(n, 0)))
            sid = rl[rack][-1]
            move(sid, owner[sid], dst)
            rl = rack_load()

    # (3) within racks: doBalanceEcShardsWithinOneRack
    for rack in racks:
        members = [n for n in nodes if rack_of.get(n, "?") == rack]
        if not members:
            continue
        load = load_by_url()
        rack_shards = [sid for sid, url in owner.items()
                       if url in members]
        avg = max(1, -(-len(rack_shards) // len(members)))
        for donor in sorted(members, key=lambda n: -len(load[n])):
            while len(load[donor]) > avg:
                recv = min(members,
                           key=lambda n: (len(load[n]),
                                          score.get(n, 0)))
                if recv == donor or len(load[recv]) + 1 > avg:
                    break
                sid = load[donor][-1]
                move(sid, donor, recv)
                load[donor].remove(sid)
                load[recv].append(sid)
    return moved


def _move_shard(env: CommandEnv, vid: int, collection: str, sid: int,
                source: str, dest: str) -> None:
    """command_ec_common.go:336 oneServerCopyAndMountEcShardsFromSource:
    copy (+ecx/ecj/vif), mount on dest, delete+unmount on source.

    The copy legs are pipelined through `httpd.http_relay` (the shape
    PR 2 gave `_copy_volume_files`): each file streams chunk-by-chunk
    from source to dest with the push starting at the first downloaded
    chunk, instead of the dest's download-then-upload
    `/admin/ec/copy` staging pass.  The shard file and `.ecx` are
    required; `.ecj`/`.vif` ride along when present (the journal
    legitimately may not exist)."""
    from ..server.httpd import http_relay
    for ext in (to_ext(sid), ".ecx", ".ecj", ".vif"):
        src_status, dst_status, body = http_relay(
            f"{source}/admin/volume_file?volumeId={vid}"
            f"&collection={collection}&ext={ext}",
            "POST", f"{dest}/admin/receive_file?volumeId={vid}"
            f"&collection={collection}&ext={ext}", timeout=600)
        if src_status != 200:
            if ext in (".ecj", ".vif"):
                continue
            raise RuntimeError(
                f"move shard {vid}.{sid}: pull {ext} from {source}: "
                f"{src_status}")
        if dst_status != 200:
            raise RuntimeError(
                f"move shard {vid}.{sid}: push {ext} to {dest}: "
                f"{dst_status} {body[:200]!r}")
    _must(http_json("POST", f"{dest}/admin/ec/mount",
                    {"volumeId": vid, "collection": collection,
                     "shardIds": [sid]}, timeout=30),
          f"mount shard {vid}.{sid} on {dest}")
    _delete_shards(source, vid, collection, [sid])


def _delete_shards(url: str, vid: int, collection: str,
                   sids: list[int]) -> None:
    """The server refreshes its mounted shard set + heartbeat itself."""
    http_json("POST", f"{url}/admin/ec/delete_shards",
              {"volumeId": vid, "collection": collection,
               "shardIds": sids}, timeout=30)


@command("ec.decode")
def cmd_ec_decode(env: CommandEnv, args: list[str]) -> str:
    """shell/command_ec_decode.go:64: collect all shards onto one server,
    decode back to a normal volume, drop shards elsewhere."""
    env.confirm_is_locked()
    opts = _parse_flags(args)
    vid = int(opts["volumeId"])
    collection = opts.get("collection", "")
    shard_locs = _ec_shard_locations(env, vid)
    if not shard_locs:
        return f"volume {vid} has no ec shards"
    # choose the server with the most shards as decode target
    target = max(shard_locs, key=lambda u: len(shard_locs[u]))
    have = set(shard_locs[target])
    for url, sids in shard_locs.items():
        if url == target:
            continue
        need = [s for s in sids if s not in have]
        if need:
            http_json("POST", f"{target}/admin/ec/copy", {
                "volumeId": vid, "collection": collection,
                "shardIds": need, "sourceDataNode": url,
                "copyEcxFile": False, "copyEcjFile": True,
                "copyVifFile": False}, timeout=30)
            have.update(need)
    r = http_json("POST", f"{target}/admin/ec/to_volume",
                  {"volumeId": vid, "collection": collection},
                  timeout=600.0)
    if "error" in r:
        raise RuntimeError(f"decode: {r['error']}")
    # remove shards from all other servers — AND the decode target's
    # own shard files: stale `.ecNN` files left on its disks would be
    # re-registered by the next encode's mount scan (duplicate shard
    # locations) and mistaken for survivors by rebuild discovery
    for url, sids in shard_locs.items():
        if url != target:
            _delete_shards(url, vid, collection, sids)
    _delete_shards(target, vid, collection, sorted(have))
    return f"volume {vid}: decoded to normal volume on {target}"


@command("ec.rebuild")
def cmd_ec_rebuild(env: CommandEnv, args: list[str]) -> str:
    """shell/command_ec_rebuild.go:83: for each ec volume missing
    shards, rebuild on the node holding the most survivors, re-spread.

    Default `-mode=stream`: the rebuilder streams remote survivors in
    slice windows straight into the GF pipeline (no whole-shard
    pre-copies).  `-mode=copy` keeps the legacy collect-then-rebuild
    (every remote survivor pulled in full via /admin/ec/copy first) —
    the A/B baseline bench.py measures against."""
    env.confirm_is_locked()
    opts = _parse_flags(args)
    import os as _os
    mode = opts.get("mode", _os.environ.get(
        "SEAWEEDFS_TPU_EC_REBUILD_MODE", "stream"))
    if mode not in ("stream", "copy"):
        return f"unknown -mode={mode}; use stream or copy"
    vids = ([int(opts["volumeId"])] if "volumeId" in opts
            else list(_ec_volumes(env)))
    out = []
    for vid in vids:
        out.append(_rebuild_one(env, vid, opts.get("collection", ""),
                                mode))
    return "\n".join(out) if out else "no ec volumes"


def _rebuild_one(env: CommandEnv, vid: int, collection: str,
                 mode: str = "stream") -> str:
    shard_locs = _ec_shard_locations(env, vid)
    present = sorted({s for sids in shard_locs.values() for s in sids})
    info = None
    for url in shard_locs:
        r = http_json("GET", f"{url}/admin/ec/info?volumeId={vid}", timeout=30)
        if "error" not in r:
            info = r
            break
    if info is None:
        return f"volume {vid}: no reachable shards"
    total = info["dataShards"] + info["parityShards"]
    missing = [s for s in range(total) if s not in present]
    if not missing:
        return f"volume {vid}: all {total} shards present"
    # rebuilder = node with most shards (fewest bytes left to ingest)
    rebuilder = max(shard_locs, key=lambda u: len(shard_locs[u]))
    if mode == "copy":
        # legacy collect-then-rebuild: pull survivors the rebuilder
        # lacks, in full, one source at a time.  Sidecars
        # (.ecx/.ecj/.vif) ride along ONCE with the first shard copy —
        # they are identical on every source, so re-pulling them per
        # source was pure waste.
        have = set(shard_locs[rebuilder])
        sidecars_pending = True
        for url, sids in shard_locs.items():
            if url == rebuilder:
                continue
            need = [s for s in sids if s not in have]
            if need:
                http_json("POST", f"{rebuilder}/admin/ec/copy", {
                    "volumeId": vid, "collection": collection,
                    "shardIds": need, "sourceDataNode": url,
                    "copyEcxFile": sidecars_pending,
                    "copyEcjFile": sidecars_pending,
                    "copyVifFile": sidecars_pending}, timeout=30)
                sidecars_pending = False
                have.update(need)
        r = http_json("POST", f"{rebuilder}/admin/ec/rebuild",
                      {"volumeId": vid, "collection": collection,
                       "mode": "local"}, timeout=30)
    else:
        # streaming: hand the rebuilder every survivor's locations and
        # let it range-read slices off its peers — zero /admin/ec/copy
        # traffic, no survivor files staged on the rebuilder's disks
        from ..topology import shard_ids_to_urls
        shard_locations = shard_ids_to_urls(shard_locs)
        r = http_json("POST", f"{rebuilder}/admin/ec/rebuild",
                      {"volumeId": vid, "collection": collection,
                       "mode": "stream",
                       "shardLocations": shard_locations,
                       "dataShards": info["dataShards"],
                       "parityShards": info["parityShards"]},
                      timeout=600.0)
    if "error" in r:
        raise RuntimeError(f"rebuild: {r['error']}")
    http_json("POST", f"{rebuilder}/admin/ec/mount",
              {"volumeId": vid, "collection": collection,
               "shardIds": r["rebuiltShardIds"]}, timeout=30)
    moved = _balance_ec_volume(env, vid, collection, total)
    msg = (f"volume {vid}: rebuilt shards {r['rebuiltShardIds']} on "
           f"{rebuilder}, rebalanced {moved}")
    tele = r.get("telemetry")
    if tele:
        msg += (f" [streamed {tele['bytesFetchedTotal'] >> 20}MB "
                f"from {len(tele['bytesFetchedBySource'])} sources, "
                f"{tele['volumeGbps']} GB/s volume-rate, "
                f"slice p95 {tele['sliceP95Ms']}ms]")
    return msg


@command("ec.balance")
def cmd_ec_balance(env: CommandEnv, args: list[str]) -> str:
    """shell/command_ec_balance.go."""
    env.confirm_is_locked()
    opts = _parse_flags(args)
    collection = opts.get("collection", "")
    out = []
    for vid in _ec_volumes(env):
        info = None
        for url in _ec_shard_locations(env, vid):
            r = http_json("GET", f"{url}/admin/ec/info?volumeId={vid}",
                    timeout=30)
            if "error" not in r:
                info = r
                break
        total = (info["dataShards"] + info["parityShards"]) if info else 14
        moved = _balance_ec_volume(env, vid, collection, total)
        out.append(f"volume {vid}: moved {moved} shards")
    return "\n".join(out) if out else "no ec volumes"


def _copy_volume_files(env: CommandEnv, vid: int, collection: str,
                       src: str, dst: str) -> None:
    """Pull .dat/.idx/.vif from src and push to dst (the CopyFile /
    ReceiveFile pattern, volume_server.proto:69-101).  The two legs are
    pipelined through http_relay — the push to dst starts at the first
    downloaded chunk instead of after a full stage-to-temp-file pass —
    while RAM stays bounded by one 4MB chunk, so the shell never
    buffers a 30GB .dat any more than the worker may."""
    from ..server.httpd import http_relay
    for ext in (".dat", ".idx", ".vif"):
        src_status, dst_status, body = http_relay(
            f"{src}/admin/volume_file?volumeId={vid}"
            f"&collection={collection}&ext={ext}",
            "POST", f"{dst}/admin/receive_file?volumeId={vid}"
            f"&collection={collection}&ext={ext}", timeout=600)
        if src_status != 200:
            if ext == ".vif":
                continue
            raise RuntimeError(f"copy {ext} from {src}: {src_status}")
        if dst_status != 200:
            raise RuntimeError(
                f"push {ext} to {dst}: {dst_status} {body[:200]!r}")


def _move_volume(env: CommandEnv, vid: int, collection: str,
                 src: str, dst: str, delete_source: bool = True) -> None:
    """shell/command_volume_move.go pipeline: freeze, copy, mount,
    delete source."""
    _must(http_json("POST", f"{src}/admin/set_readonly",
                    {"volumeId": vid, "readOnly": True}, timeout=30),
          f"set readonly on {src}")
    _copy_volume_files(env, vid, collection, src, dst)
    _must(http_json("POST", f"{dst}/admin/mount_volume",
                    {"volumeId": vid, "collection": collection}, timeout=30),
          f"mount on {dst}")
    if delete_source:
        _must(http_json("POST", f"{src}/admin/delete_volume",
                        {"volumeId": vid}, timeout=30),
              f"delete source on {src}")
    else:
        _must(http_json("POST", f"{src}/admin/set_readonly",
                        {"volumeId": vid, "readOnly": False}, timeout=30),
              f"clear readonly on {src}")


@command("volume.balance")
def cmd_volume_balance(env: CommandEnv, args: list[str]) -> str:
    """shell/command_volume_balance.go: even out volume counts across
    servers by moving volumes from the fullest to the emptiest."""
    env.confirm_is_locked()
    from ..topology import iter_volume_list_volumes
    vl = env.volume_list()
    per_node: dict[str, list[dict]] = {}
    for node, v in iter_volume_list_volumes(vl):
        per_node.setdefault(node["url"], []).append(v)
    for url in _all_node_urls(env):
        per_node.setdefault(url, [])
    if not per_node:
        return "no volume servers"
    total = sum(len(v) for v in per_node.values())
    avg = max(1, -(-total // len(per_node)))
    moved = 0
    for donor in sorted(per_node, key=lambda u: -len(per_node[u])):
        while len(per_node[donor]) > avg:
            recv = min(per_node, key=lambda u: len(per_node[u]))
            if recv == donor or len(per_node[recv]) + 1 > avg:
                break
            donor_vids = {v["id"] for v in per_node[donor]}
            recv_vids = {v["id"] for v in per_node[recv]}
            movable = [v for v in per_node[donor]
                       if v["id"] not in recv_vids]
            if not movable:
                break
            v = movable[-1]
            _move_volume(env, v["id"], v.get("collection", ""),
                         donor, recv)
            per_node[donor].remove(v)
            per_node[recv].append(v)
            moved += 1
    return f"moved {moved} volumes"


@command("volume.fix.replication")
def cmd_volume_fix_replication(env: CommandEnv, args: list[str]) -> str:
    """shell/command_volume_fix_replication.go: re-create missing
    replicas for under-replicated volumes."""
    env.confirm_is_locked()
    from ..storage.replica_placement import ReplicaPlacement
    from ..topology import iter_volume_list_volumes
    vl = env.volume_list()
    locations: dict[int, list[str]] = {}
    meta: dict[int, dict] = {}
    for node, v in iter_volume_list_volumes(vl):
        locations.setdefault(v["id"], []).append(node["url"])
        meta[v["id"]] = v
    nodes = _all_node_urls(env)
    fixed = []
    for vid, locs in sorted(locations.items()):
        v = meta[vid]
        want = ReplicaPlacement.from_byte(
            v.get("replicaPlacement", 0)).copy_count()
        missing = want - len(locs)
        if missing <= 0:
            continue
        candidates = [n for n in nodes if n not in locs]
        for dst in candidates[:missing]:
            _copy_volume_files(env, vid, v.get("collection", ""),
                               locs[0], dst)
            _must(http_json("POST", f"{dst}/admin/mount_volume",
                            {"volumeId": vid,
                             "collection": v.get("collection", "")},
                      timeout=30),
                  f"mount on {dst}")
            fixed.append(f"{vid}->{dst}")
    return f"fixed replicas: {fixed}" if fixed else \
        "all volumes sufficiently replicated"


@command("ec.scrub")
def cmd_ec_scrub(env: CommandEnv, args: list[str]) -> str:
    """shell/command_ec_scrub.go:31 — modes index/local (:52)."""
    opts = _parse_flags(args)
    mode = opts.get("mode", "local")
    out = []
    for vid in _ec_volumes(env):
        for url in _ec_shard_locations(env, vid):
            r = http_json("POST", f"{url}/admin/ec/scrub",
                          {"volumeId": vid, "mode": mode}, timeout=30)
            if r.get("error"):
                out.append(f"volume {vid} @ {url}: ERROR {r['error']}")
            else:
                status = "ok" if not r["errors"] else \
                    f"{len(r['errors'])} errors, broken shards " \
                    f"{r['brokenShards']}"
                out.append(f"volume {vid} @ {url}: checked "
                           f"{r['checked']} entries, {status}")
    return "\n".join(out) if out else "no ec volumes"


# --- distributed tracing (tracing.py; the operator's flame view) ---------

def _cluster_debug_nodes(env: CommandEnv) -> list[str]:
    """Every node that may hold spans of a trace: master(s), every
    volume server, and the filer when the shell knows one."""
    r = master_json(env.master, "GET", "/cluster/status", timeout=30)
    nodes = [env.master]
    nodes += [p for p in r.get("peers", []) if p not in nodes]
    nodes += r.get("dataNodes", [])
    if env.filer and env.filer not in nodes:
        nodes.append(env.filer)
    return nodes


def collect_trace(env: CommandEnv, request_id: str,
                  extra_nodes: "list[str] | None" = None
                  ) -> "list[dict]":
    """Fan /debug/traces?request_id= out to every cluster node and
    merge the spans (deduped by span id; an unreachable node
    contributes nothing rather than failing the whole view).

    Runs under a FRESH request id: a shell context still carrying the
    queried id would otherwise trace its own topology/debug calls
    into the very trace it is rendering."""
    from ..util.request_id import (new_request_id, reset_request_id,
                                   set_request_id)
    token = set_request_id(new_request_id())
    try:
        nodes = _cluster_debug_nodes(env)
    finally:
        reset_request_id(token)
    for n in extra_nodes or []:
        if n not in nodes:
            nodes.append(n)

    def fetch(url: str) -> list:
        try:
            r = http_json(
                "GET", f"{url}/debug/traces?request_id={request_id}",
                timeout=10)
        except OSError:
            return []
        spans = r.get("spans", []) if isinstance(r, dict) else []
        for s in spans:
            s["node"] = url
        return spans

    merged: dict[str, dict] = {}
    with ThreadPoolExecutor(max_workers=min(8, len(nodes))) as ex:
        for spans in ex.map(fetch, nodes):
            for s in spans:
                merged.setdefault(s["spanId"], s)
    return sorted(merged.values(), key=lambda s: s["start"])


def render_trace(spans: "list[dict]") -> str:
    """Time-aligned tree: children indent under their parent, each
    line shows offset from the trace's first span, duration, role@node
    and attrs — one request id becomes a cross-node flame view."""
    if not spans:
        return "no spans found (buffer rolled over, or wrong id?)"
    t0 = min(s["start"] for s in spans)
    by_parent: dict[str, list] = {}
    ids = {s["spanId"] for s in spans}
    for s in spans:
        parent = s.get("parentId") or ""
        if parent not in ids:
            parent = ""          # orphan (parent not collected): root
        by_parent.setdefault(parent, []).append(s)
    lines = [f"trace {spans[0]['traceId']}: {len(spans)} span(s), "
             f"{len({s.get('role') or '?' for s in spans})} role(s)"]

    def walk(parent: str, depth: int) -> None:
        for s in sorted(by_parent.get(parent, []),
                        key=lambda x: x["start"]):
            off = (s["start"] - t0) * 1e3
            attrs = s.get("attrs") or {}
            extra = " ".join(f"{k}={v}" for k, v in attrs.items())
            mark = " ERROR" if s.get("error") else ""
            lines.append(
                f"{'  ' * depth}+{off:8.1f}ms {s['name']}  "
                f"[{s.get('role') or '?'}@{s.get('node', '?')}] "
                f"{s['durationMs']}ms{mark}"
                + (f"  {extra}" if extra else ""))
            walk(s["spanId"], depth + 1)

    walk("", 0)
    return "\n".join(lines)


def collect_peer_health(env: CommandEnv,
                        extra_nodes: "list[str] | None" = None
                        ) -> "list[str]":
    """Every node's /debug/health (util/retry breaker map + budget),
    rendered one line per non-closed peer — the view that makes a
    chaos run debuggable from the shell: which node has stopped
    talking to which peer, and why."""
    try:
        nodes = _cluster_debug_nodes(env)
    except OSError:
        nodes = [env.master]
    for n in extra_nodes or []:
        if n not in nodes:
            nodes.append(n)

    def fetch(url: str):
        # best-effort probe: keep the budget per node tight — this
        # runs mid-incident, when a wedged node would otherwise stall
        # the whole shell command for its full timeout x retries
        try:
            r = http_json("GET", f"{url}/debug/health", timeout=3)
        except OSError:
            return url, None
        return url, r if isinstance(r, dict) else None

    lines: list[str] = []
    with ThreadPoolExecutor(max_workers=min(8, len(nodes))) as ex:
        for url, r in ex.map(fetch, nodes):
            if not r:
                continue
            for peer, h in (r.get("peers") or {}).items():
                if h.get("state") == "closed" and not h.get("trips"):
                    continue
                lines.append(
                    f"  {url}: peer {peer} {h.get('state')} "
                    f"(consecutive failures "
                    f"{h.get('consecutiveFailures', 0)}, trips "
                    f"{h.get('trips', 0)})"
                    + (f" last: {h['lastError']}"
                       if h.get("lastError") else ""))
    return lines


@command("trace.show")
def cmd_trace_show(env: CommandEnv, args: list[str]) -> str:
    """Assemble one request's spans from every cluster node's
    /debug/traces ring buffer and render the time-aligned tree —
    turns a request id from a log line into a cross-node flame view
    (tracing.py; the operator entry point of the tracing plane).
    `-nodes=host:port[,...]` queries extra debug planes the topology
    doesn't know — e.g. the admin server, which holds ingested worker
    job spans.  When the trace shows failure activity (retry.* or
    error spans) — or always with `-health` — a "peer health" section
    is appended from every node's /debug/health, so retry stalls in
    the tree line up with the breaker that caused them; a clean trace
    skips that second cluster-wide fan-out (mid-incident, wedged
    nodes make every extra probe a stall)."""
    rids = [a for a in args if not a.startswith("-")]
    opts = _parse_flags(args)
    extra = [n.strip() for n in opts.get("nodes", "").split(",")
             if n.strip()]
    if not rids:
        return "usage: trace.show <request_id> [-nodes=host:port,...]" \
               " [-health]"
    traces = [collect_trace(env, rid, extra_nodes=extra)
              for rid in rids]
    out = [render_trace(spans) for spans in traces]
    want_health = "health" in opts or any(
        str(s.get("name", "")).startswith("retry.") or s.get("error")
        for spans in traces for s in spans)
    if want_health:
        health = collect_peer_health(env, extra_nodes=extra)
        if health:
            out.append("peer health (non-closed breakers):")
            out.extend(health)
        else:
            out.append("peer health: all breakers closed")
    return "\n".join(out)


@command("qos.status")
def cmd_qos_status(env: CommandEnv, args: list[str]) -> str:
    """Cluster-wide QoS view (qos.py): every node's /debug/qos —
    admission config, per-tenant in-flight bytes, and the EC feedback
    throttle's pace/p99.  `-nodes=host:port,...` adds listeners the
    topology doesn't know (e.g. a standalone S3 gateway)."""
    opts = _parse_flags(args)
    try:
        nodes = _cluster_debug_nodes(env)
    except OSError:
        nodes = [env.master]
    for n in (opts.get("nodes", "") or "").split(","):
        n = n.strip()
        if n and n not in nodes:
            nodes.append(n)
    out = []
    for url in nodes:
        try:
            r = http_json("GET", f"{url}/debug/qos", timeout=3)
        except OSError:
            out.append(f"{url}: unreachable")
            continue
        if not isinstance(r, dict) or "config" not in r:
            out.append(f"{url}: {r.get('error', 'no qos plane')}"
                       if isinstance(r, dict) else f"{url}: ?")
            continue
        cfg = r["config"]
        th = r.get("throttle", {})
        tenants = cfg.get("tenants", {})
        out.append(
            f"{url}: enabled={cfg.get('enabled')} "
            f"tenants={len(tenants)} "
            f"slo_p99={cfg.get('sloP99Ms', 0):.0f}ms "
            f"pace={th.get('paceMs', 0):.0f}ms "
            f"p99={th.get('lastP99Ms', 0):.1f}ms")
        for t, lim in sorted(tenants.items()):
            inflight = r.get("inflightBytes", {}).get(t, 0)
            out.append(f"  {t}: rps={lim.get('rps')} "
                       f"burst={lim.get('burst')} "
                       f"inflight_mb={lim.get('inflightMb')} "
                       f"(in flight now: {inflight}B)")
    return "\n".join(out)


@command("qos.set")
def cmd_qos_set(env: CommandEnv, args: list[str]) -> str:
    """Push one tenant's limits (or the default, tenant `*`) to every
    node's runtime QoS lever: `qos.set -tenant=AK -rps=10 [-burst=20]
    [-inflightMb=8]` — or `-sloP99Ms=200` to retune the EC throttle,
    `-clear` to reset the whole plane."""
    opts = _parse_flags(args)
    body: dict = {}
    if "clear" in opts:
        body["clear"] = True
    if "tenant" in opts:
        body["tenant"] = opts["tenant"]
        for k in ("rps", "burst", "inflightMb"):
            if k in opts:
                body[k] = float(opts[k])
    if "sloP99Ms" in opts:
        body["sloP99Ms"] = float(opts["sloP99Ms"])
    if not body:
        return ("usage: qos.set -tenant=<access-key|*> -rps=N "
                "[-burst=N] [-inflightMb=N] | -sloP99Ms=N | -clear")
    try:
        nodes = _cluster_debug_nodes(env)
    except OSError:
        nodes = [env.master]
    ok, failed = 0, []
    for url in nodes:
        try:
            r = http_json("POST", f"{url}/debug/qos", body, timeout=5)
            if isinstance(r, dict) and "error" in r:
                failed.append(f"{url}: {r['error']}")
            else:
                ok += 1
        except OSError as e:
            failed.append(f"{url}: {e}")
    out = [f"qos updated on {ok}/{len(nodes)} nodes"]
    out.extend(failed)
    return "\n".join(out)


_ROLE_NAMESPACES = ("master", "volume_server", "filer", "s3")


def _top_nodes(env: CommandEnv, opts: dict) -> "list[str]":
    """Fan-out target list: the topology's debug planes plus any
    `-nodes=` extras (a standalone S3 gateway, the admin server)."""
    try:
        nodes = _cluster_debug_nodes(env)
    except OSError:
        nodes = [env.master]
    for n in (opts.get("nodes", "") or "").split(","):
        n = n.strip()
        if n and n not in nodes:
            nodes.append(n)
    return nodes


def _fetch_metrics(url: str) -> "dict[str, list] | None":
    """One node's /metrics, parsed (profiling.parse_prom_text);
    None when unreachable."""
    from .. import profiling
    try:
        st, body, _ = http_bytes("GET", f"{url}/metrics", timeout=3)
    except OSError:
        return None
    if st >= 300:
        return None
    return profiling.parse_prom_text(body.decode("utf-8", "replace"))


def _node_role(metrics: "dict[str, list]") -> str:
    """Which role registry this listener renders (each role's Metrics
    namespace prefixes its request_seconds histogram)."""
    for ns in _ROLE_NAMESPACES:
        if f"{ns}_request_seconds_count" in metrics:
            return ns
    return "?"


def _gauge(metrics: "dict[str, list]", name: str,
           match: "dict | None" = None) -> "float | None":
    match = match or {}
    for labels, value in metrics.get(name, []):
        if all(labels.get(k) == v for k, v in match.items()):
            return value
    return None


def _counter_sum(metrics: "dict[str, list]", name: str,
                 match: "dict | None" = None) -> float:
    match = match or {}
    return sum(v for l, v in metrics.get(name, [])
               if all(l.get(k) == mv for k, mv in match.items()))


def _read_cache_report(before: "dict[str, list]",
                       after: "dict[str, list]") -> str:
    """Per-cache hot-read-cache view over the sampling window: hit
    ratio + bytes served from cache (util/chunk_cache meters on the
    shared registry).  Empty when no instrumented cache was touched."""
    caches = {l.get("cache", "") for name in
              ("seaweedfs_tpu_read_cache_hits_total",
               "seaweedfs_tpu_read_cache_misses_total")
              for l, _v in after.get(name, [])}
    parts = []
    for c in sorted(caches):
        hits = _counter_sum(
            after, "seaweedfs_tpu_read_cache_hits_total",
            {"cache": c}) - _counter_sum(
            before, "seaweedfs_tpu_read_cache_hits_total",
            {"cache": c})
        misses = _counter_sum(
            after, "seaweedfs_tpu_read_cache_misses_total",
            {"cache": c}) - _counter_sum(
            before, "seaweedfs_tpu_read_cache_misses_total",
            {"cache": c})
        if hits + misses <= 0:
            continue
        served = _counter_sum(
            after, "seaweedfs_tpu_read_cache_bytes_served_total",
            {"cache": c}) - _counter_sum(
            before, "seaweedfs_tpu_read_cache_bytes_served_total",
            {"cache": c})
        parts.append(f"{c} {hits / (hits + misses) * 100:.0f}% "
                     f"({served / (1 << 20):.1f}MB served)")
    if not parts:
        return ""
    return "read-cache: " + "  ".join(parts)


def _stage_report(before: "dict[str, list]", after: "dict[str, list]",
                  ns: str) -> str:
    """Per-stage share of write-path wall time over the sampling
    window, from the write_stage_seconds decomposition (profiling.py),
    with each stage's cpu/wall mean beside it (write_stage_cpu_seconds
    — ISSUE 15): `upload 45% cpu=0.12/1.30ms` reads "45% of write
    wall, of which each call burned 0.12ms CPU out of 1.30ms wall —
    the other 1.18ms was GIL/lock/IO wait".  Empty string when no
    write landed in the window."""
    from .. import profiling
    name = f"{ns}_write_stage_seconds"
    cpu_name = f"{ns}_write_stage_cpu_seconds"
    stages: dict[str, tuple] = {}
    total = 0.0
    seen = {l.get("stage", "") for l, _v in
            after.get(f"{name}_count", [])}
    for stage in sorted(seen):
        h = profiling.histogram_delta(
            profiling.prom_histogram(after, name, {"stage": stage}),
            profiling.prom_histogram(before, name, {"stage": stage}))
        if not h or h["count"] <= 0:
            continue
        c = profiling.histogram_delta(
            profiling.prom_histogram(after, cpu_name,
                                     {"stage": stage}),
            profiling.prom_histogram(before, cpu_name,
                                     {"stage": stage}))
        cpu_mean = (c["sum"] / c["count"]) if c and c["count"] else None
        if stage == "total":
            total = h["sum"]
        else:
            stages[stage] = (h["sum"], h["sum"] / h["count"], cpu_mean)
    if not stages or total <= 0:
        return ""
    parts = []
    for s, (secs, wall_mean, cpu_mean) in sorted(
            stages.items(), key=lambda kv: -kv[1][0]):
        p = f"{s} {secs / total * 100.0:.0f}%"
        if cpu_mean is not None:
            p += (f" cpu={cpu_mean * 1e3:.2f}/"
                  f"{wall_mean * 1e3:.2f}ms")
        parts.append(p)
    return "write stages: " + " ".join(parts)


def _cpu_report(before: "dict[str, list]", after: "dict[str, list]",
                ns: str, req: "dict | None", window: float) -> str:
    """The node's cost-attribution line (ISSUE 15): mean CPU vs wall
    per request from request_cpu_seconds/request_seconds, the
    scheduler-probe gil_wait_ratio, and the /proc process-TREE CPU
    burn + RSS (pre-fork workers and native plane children included).
    Empty when the window saw no requests and no tree gauges."""
    from .. import profiling
    parts = []
    c = profiling.histogram_delta(
        profiling.prom_histogram(after, f"{ns}_request_cpu_seconds"),
        profiling.prom_histogram(before, f"{ns}_request_cpu_seconds"))
    if c and c["count"] > 0 and req and req["count"] > 0:
        cpu_ms = c["sum"] / c["count"] * 1e3
        wall_ms = req["sum"] / req["count"] * 1e3
        if wall_ms > 0:
            parts.append(
                f"{cpu_ms:.2f}ms cpu of {wall_ms:.2f}ms wall/req "
                f"(wait {max(1.0 - cpu_ms / wall_ms, 0.0) * 100:.0f}%)")
    gil = _gauge(after, "seaweedfs_tpu_gil_wait_ratio")
    if gil is not None:
        parts.append(f"gil-wait={gil * 100:.0f}%")
    tree_a = _gauge(after, "seaweedfs_tpu_process_tree_cpu_seconds")
    tree_b = _gauge(before, "seaweedfs_tpu_process_tree_cpu_seconds")
    if tree_a is not None and tree_b is not None and window > 0:
        burn = max(tree_a - tree_b, 0.0) / window
        procs = _gauge(after, "seaweedfs_tpu_process_tree_procs") or 1
        rss = _gauge(after, "seaweedfs_tpu_process_tree_rss_bytes") \
            or 0.0
        parts.append(f"tree={burn:.2f} cores/{procs:.0f} procs "
                     f"rss={rss / (1 << 20):.0f}MB")
    if not parts:
        return ""
    return "cpu: " + "  ".join(parts)


def _group_commit_report(before: "dict[str, list]",
                         after: "dict[str, list]") -> str:
    """Per-site group-commit view over the sampling window: mean
    batch (writers covered per shared durability barrier) and
    barrier-wait p99, from the util/group_commit metrics on the
    shared process registry.  Empty when no barrier fired."""
    from .. import profiling
    batch = "seaweedfs_tpu_group_commit_batch_size"
    wait = "seaweedfs_tpu_group_commit_wait_seconds"
    sites = {l.get("site", "") for l, _v in
             after.get(f"{batch}_count", [])}
    parts = []
    for site in sorted(sites):
        h = profiling.histogram_delta(
            profiling.prom_histogram(after, batch, {"site": site}),
            profiling.prom_histogram(before, batch, {"site": site}))
        if not h or h["count"] <= 0:
            continue
        w = profiling.histogram_delta(
            profiling.prom_histogram(after, wait, {"site": site}),
            profiling.prom_histogram(before, wait, {"site": site}))
        p99 = profiling.histogram_quantile(w, 0.99) if w else 0.0
        parts.append(f"{site} batch={h['sum'] / h['count']:.1f} "
                     f"wait-p99={p99 * 1e3:.2f}ms")
    if not parts:
        return ""
    return "group-commit: " + "  ".join(parts)


def _native_plane_report(before: "dict[str, list]",
                         after: "dict[str, list]") -> str:
    """Native read/write/meta plane view over the sampling window:
    acks and fallbacks per plane plus the native ack-latency p99 (C++
    atomics rendered by the volume server's and filer's /metrics).
    Empty when the node runs no native plane."""
    from .. import profiling
    parts = []
    wname = "volume_server_write_plane_ack_seconds"
    wr = _counter_sum(
        after, "volume_server_write_plane_requests_total") - \
        _counter_sum(before, "volume_server_write_plane_requests_total")
    wf = _counter_sum(
        after, "volume_server_write_plane_fallbacks_total") - \
        _counter_sum(before,
                     "volume_server_write_plane_fallbacks_total")
    if f"{wname}_count" in after:
        h = profiling.histogram_delta(
            profiling.prom_histogram(after, wname),
            profiling.prom_histogram(before, wname))
        p99 = profiling.histogram_quantile(h, 0.99) \
            if h and h.get("count") else 0.0
        parts.append(f"write {wr:.0f} acked/{wf:.0f} fallback"
                     f" ack-p99={p99 * 1e3:.2f}ms")
    rr = _counter_sum(
        after, "volume_server_read_plane_requests_total") - \
        _counter_sum(before,
                     "volume_server_read_plane_requests_total")
    rf = _counter_sum(
        after, "volume_server_read_plane_fallbacks_total") - \
        _counter_sum(before,
                     "volume_server_read_plane_fallbacks_total")
    if "volume_server_read_plane_requests_total" in after:
        parts.append(f"read {rr:.0f} served/{rf:.0f} fallback")
    # the filer's native READ plane (ISSUE 19): warm GETs served with
    # zero Python, coherence misses surfaced beside the fallbacks
    fr = _counter_sum(
        after, "filer_read_plane_native_requests_total") - \
        _counter_sum(before, "filer_read_plane_native_requests_total")
    ff = _counter_sum(
        after, "filer_read_plane_native_fallbacks_total") - \
        _counter_sum(before,
                     "filer_read_plane_native_fallbacks_total")
    if "filer_read_plane_native_requests_total" in after:
        fstale = _counter_sum(
            after, "filer_read_plane_native_stale_misses_total") - \
            _counter_sum(before,
                         "filer_read_plane_native_stale_misses_total")
        seg = f"filer-read {fr:.0f} served/{ff:.0f} fallback"
        if fstale > 0:
            seg += f" stale={fstale:.0f}"
        parts.append(seg)
    # the filer's native META plane (ISSUE 17): creates acked with
    # zero Python, plus its ack-latency p99 and mean WAL batch
    mname = "filer_meta_plane_native_ack_seconds"
    mr = _counter_sum(
        after, "filer_meta_plane_native_requests_total") - \
        _counter_sum(before, "filer_meta_plane_native_requests_total")
    mf = _counter_sum(
        after, "filer_meta_plane_native_fallbacks_total") - \
        _counter_sum(before,
                     "filer_meta_plane_native_fallbacks_total")
    if f"{mname}_count" in after:
        h = profiling.histogram_delta(
            profiling.prom_histogram(after, mname),
            profiling.prom_histogram(before, mname))
        p99 = profiling.histogram_quantile(h, 0.99) \
            if h and h.get("count") else 0.0
        batches = _counter_sum(
            after, "filer_meta_plane_native_wal_batches_total") - \
            _counter_sum(before,
                         "filer_meta_plane_native_wal_batches_total")
        lines = _counter_sum(
            after, "filer_meta_plane_native_wal_lines_total") - \
            _counter_sum(before,
                         "filer_meta_plane_native_wal_lines_total")
        seg = (f"meta {mr:.0f} acked/{mf:.0f} fallback"
               f" ack-p99={p99 * 1e3:.2f}ms")
        if batches > 0:
            seg += f" wal-batch={lines / batches:.1f}"
        parts.append(seg)
    # per-stage tails from the drained flight records (ISSUE 18): the
    # plane_stage_seconds family is fed by the Python drainer, so each
    # plane's stage decomposition shows up windowed, like every other
    # cluster.top figure
    sname = "seaweedfs_tpu_plane_stage_seconds"
    planes = sorted({l.get("plane", "") for l, _v in
                     after.get(f"{sname}_count", []) if l.get("plane")})
    from ..server.filer_read_plane_native import (
        RECORD_STAGES as _FILER_READ_STAGES)
    from ..server.meta_plane_native import (
        RECORD_STAGES as _META_STAGES)
    from ..server.read_plane import RECORD_STAGES as _READ_STAGES
    from ..server.write_plane import RECORD_STAGES as _WRITE_STAGES
    stage_order = {"meta": _META_STAGES, "write": _WRITE_STAGES,
                   "read": _READ_STAGES,
                   "filer_read": _FILER_READ_STAGES}
    for plane in planes:
        segs = []
        for stg in stage_order.get(plane, ()):
            h = profiling.histogram_delta(
                profiling.prom_histogram(
                    after, sname, {"plane": plane, "stage": stg}),
                profiling.prom_histogram(
                    before, sname, {"plane": plane, "stage": stg}))
            if h and h.get("count"):
                p99 = profiling.histogram_quantile(h, 0.99)
                segs.append(f"{stg}-p99={p99 * 1e3:.2f}ms")
        dropped = _counter_sum(
            after, "seaweedfs_tpu_plane_ring_dropped_total",
            {"plane": plane}) - _counter_sum(
            before, "seaweedfs_tpu_plane_ring_dropped_total",
            {"plane": plane})
        if dropped > 0:
            segs.append(f"ring-dropped={dropped:.0f}")
        if segs:
            parts.append(f"{plane}-stages " + " ".join(segs))
    if not parts:
        return ""
    return "native-planes: " + "  ".join(parts)


def _autopilot_report(before: "dict[str, list]",
                      after: "dict[str, list]") -> str:
    """SLO-autopilot view (autopilot.py, ISSUE 20): loop state, the
    knobs it currently holds, and any actuation that landed in the
    sampling window with its direction.  Empty for a role that runs
    no loop; "off" is explicit — an operator must be able to see a
    killed controller at a glance."""
    enabled = _gauge(after, "seaweedfs_tpu_autopilot_enabled")
    if enabled is None:
        return ""
    knobs = " ".join(
        f"{l.get('knob', '?')}={v:.4g}"
        for l, v in sorted(after.get(
            "seaweedfs_tpu_autopilot_knob", []),
            key=lambda kv: kv[0].get("knob", "")))
    line = "autopilot: " + ("on" if enabled else "off")
    if knobs:
        line += "  " + knobs
    moved = []
    for l, v in after.get("seaweedfs_tpu_autopilot_actions_total",
                          []):
        d = v - _counter_sum(
            before, "seaweedfs_tpu_autopilot_actions_total",
            {"knob": l.get("knob", ""),
             "direction": l.get("direction", "")})
        if d > 0:
            arrow = {"up": "^", "down": "v"}.get(
                l.get("direction", ""), l.get("direction", ""))
            moved.append(f"{l.get('knob', '?')}{arrow}x{d:.0f}")
    if moved:
        line += "  moved: " + " ".join(sorted(moved))
    return line


def _deadline_report(before: "dict[str, list]",
                     after: "dict[str, list]") -> str:
    """Deadline-plane view over the sampling window: budgets refused
    (per fail-fast site) and hedged replica reads issued/won
    (util/deadline + util/hedge meter on the shared registry).  Empty
    when the window saw neither — the common healthy state."""
    exceeded = _counter_sum(
        after, "seaweedfs_tpu_deadline_exceeded_total") - \
        _counter_sum(before, "seaweedfs_tpu_deadline_exceeded_total")
    issued = _counter_sum(
        after, "seaweedfs_tpu_hedges_issued_total") - \
        _counter_sum(before, "seaweedfs_tpu_hedges_issued_total")
    won = _counter_sum(
        after, "seaweedfs_tpu_hedges_won_total") - \
        _counter_sum(before, "seaweedfs_tpu_hedges_won_total")
    parts = []
    if exceeded > 0:
        sites = {l.get("site", "") for l, _v in after.get(
            "seaweedfs_tpu_deadline_exceeded_total", [])}
        worst = []
        for s in sorted(sites):
            d = _counter_sum(
                after, "seaweedfs_tpu_deadline_exceeded_total",
                {"site": s}) - _counter_sum(
                before, "seaweedfs_tpu_deadline_exceeded_total",
                {"site": s})
            if d > 0:
                worst.append((d, s))
        worst.sort(reverse=True)
        top = " ".join(f"{s}={d:.0f}" for d, s in worst[:3])
        parts.append(f"exceeded={exceeded:.0f} ({top})")
    if issued > 0:
        parts.append(f"hedges={issued:.0f} issued/{won:.0f} won")
    if not parts:
        return ""
    return "deadline: " + "  ".join(parts)


@command("cluster.top")
def cmd_cluster_top(env: CommandEnv, args: list[str]) -> str:
    """Live one-screen cluster view: every node's /metrics sampled
    twice `-interval=N` seconds apart (default 2), the delta rendered
    as per-role req/s, windowed p99, in-flight requests, pooled-client
    connection reuse, breaker/QoS state, device telemetry where the
    node has touched a TPU, the write-path stage decomposition and
    group-commit batching (mean batch size, barrier-wait p99) when
    writes landed in the window, and the top profiler stacks on any
    node whose sampler is armed.  The operator's answer to "what is
    this cluster doing RIGHT NOW"."""
    from .. import profiling
    opts = _parse_flags(args)
    try:
        window = max(0.2, float(opts.get("interval", 2.0)))
    except ValueError:
        return "bad -interval"
    nodes = _top_nodes(env, opts)

    with ThreadPoolExecutor(max_workers=min(8, len(nodes))) as ex:
        before = dict(zip(nodes, ex.map(_fetch_metrics, nodes)))
        time.sleep(window)
        after = dict(zip(nodes, ex.map(_fetch_metrics, nodes)))

    out = [f"cluster.top — {len(nodes)} nodes, "
           f"{window:.1f}s window"]
    for url in nodes:
        b, a = before.get(url), after.get(url)
        if a is None:
            out.append(f"{url}: unreachable")
            continue
        if b is None:
            # no baseline sample: rendering cumulative-since-boot
            # counters as this window's delta would show a day-old
            # node at absurd req/s
            out.append(f"{url}: no baseline sample this window")
            continue
        try:
            out.extend(_render_node_top(url, b, a, window))
        except Exception as e:  # noqa: BLE001 — one node's partial or
            # malformed mid-interval scrape must cost that node a
            # note, never the whole cluster view
            out.append(f"{url}: render failed: {e}")
    return "\n".join(out)


def _render_node_top(url: str, b: "dict[str, list]",
                     a: "dict[str, list]",
                     window: float) -> "list[str]":
    """One node's cluster.top block, split out so the caller can
    contain a render failure (a node restarting mid-interval hands
    back truncated metrics; a role skew hands back unexpected label
    shapes) to that node's line."""
    from .. import profiling
    out: list[str] = []
    ns = _node_role(a)
    req = profiling.histogram_delta(
        profiling.prom_histogram(a, f"{ns}_request_seconds"),
        profiling.prom_histogram(b, f"{ns}_request_seconds"))
    rate = (req["count"] / window) if req else 0.0
    p99 = profiling.histogram_quantile(req, 0.99) if req else 0.0
    inflight = _gauge(a, f"{ns}_requests_in_flight") or 0
    line = (f"{url} [{ns}] {rate:7.1f} req/s  "
            f"p99={p99 * 1e3:7.1f}ms  in-flight={inflight:.0f}")
    reused = _counter_sum(
        a, "seaweedfs_tpu_pool_connections_reused_total")
    opened = _counter_sum(
        a, "seaweedfs_tpu_pool_connections_opened_total")
    if reused + opened > 0:
        line += (f"  pool-reuse={reused / (reused + opened) * 100:.0f}%"
                 f" ({opened:.0f} dials)")
    open_breakers = sum(
        1 for _l, v in a.get("seaweedfs_tpu_peer_breaker_state", [])
        if v != 0)
    if open_breakers:
        line += f"  breakers:{open_breakers} non-closed"
    pace = _gauge(a, "seaweedfs_tpu_qos_ec_pace_ms")
    if pace:
        line += f"  ec-pace={pace:.0f}ms"
    rejected = _counter_sum(a, "seaweedfs_tpu_qos_rejected_total") \
        - _counter_sum(b, "seaweedfs_tpu_qos_rejected_total")
    if rejected > 0:
        line += f"  qos-rejected={rejected:.0f}"
    out.append(line)
    kern = _gauge(a, "seaweedfs_tpu_device_kernel_last_ms",
                  {"kernel": "gf_apply_matrix"})
    if kern is not None:
        h2d = _gauge(a, "seaweedfs_tpu_device_h2d_gbps") or 0.0
        d2h = _gauge(a, "seaweedfs_tpu_device_d2h_gbps") or 0.0
        line = (f"  device: kernel={kern:.2f}ms "
                f"h2d={h2d:.2f}GB/s d2h={d2h:.2f}GB/s")
        # windowed staging figures (ops.staging): window count
        # since the previous sample + how overlapped the last
        # launch's h2d/d2h planes actually ran
        ov = _gauge(a, "seaweedfs_tpu_device_h2d_overlap_fraction",
                    {"op": "encode"})
        if ov is None:  # rebuild-only workload stages too
            ov = _gauge(a,
                        "seaweedfs_tpu_device_h2d_overlap_fraction",
                        {"op": "rebuild"})
        wins = _counter_sum(
            a, "seaweedfs_tpu_device_staged_windows_total") - \
            _counter_sum(
                b, "seaweedfs_tpu_device_staged_windows_total")
        if ov is not None:
            line += f"  overlap={ov * 100:.0f}%"
        if wins > 0:
            line += f"  windows={wins:.0f}"
        out.append(line)
    cpu = _cpu_report(b, a, ns, req, window)
    if cpu:
        out.append("  " + cpu)
    cache_line = _read_cache_report(b, a)
    degraded = _counter_sum(
        a, "seaweedfs_tpu_ec_degraded_reads_total") - \
        _counter_sum(b, "seaweedfs_tpu_ec_degraded_reads_total")
    if degraded > 0:
        cache_line += ("  " if cache_line else "") + \
            f"degraded-reads={degraded:.0f}"
    if cache_line:
        out.append("  " + cache_line)
    stages = _stage_report(b, a, ns)
    if stages:
        out.append("  " + stages)
    planes = _native_plane_report(b, a)
    if planes:
        out.append("  " + planes)
    gc = _group_commit_report(b, a)
    if gc:
        out.append("  " + gc)
    dl = _deadline_report(b, a)
    if dl:
        out.append("  " + dl)
    ap = _autopilot_report(b, a)
    if ap:
        out.append("  " + ap)
    try:
        prof = http_json("GET", f"{url}/debug/pprof?top=3",
                         timeout=3)
    except OSError:
        prof = None
    if isinstance(prof, dict) and prof.get("stacks"):
        total = max(1, prof["stacks"])
        for stack, n in sorted(prof.get("folded", {}).items(),
                               key=lambda kv: -kv[1]):
            leaf = stack.rsplit(";", 2)[-2:]
            out.append(f"  prof {n / total * 100:4.1f}% "
                       f"{';'.join(leaf)}")
    return out


def _render_slow_hop(url: str, rec: dict) -> "list[str]":
    """One flight record as an indented hop block: the wall/cpu/wait
    split, the deadline budget+verdict, the stage decomposition
    (wall/cpu per stage) and the hedge/QoS/plane flight notes."""
    wall = rec.get("wallMs", 0.0)
    cpu = rec.get("cpuMs")     # absent = request didn't draw the
    # CPU-attribution sample (SEAWEEDFS_TPU_CPU_SAMPLE): wall only,
    # never a fake 0ms cpu
    head = (f"  {rec.get('role', '?')}@{url}: "
            f"{rec.get('method', '?')} {rec.get('path', '?')} "
            f"status={rec.get('status', 0)}")
    if wall > 0 and cpu is not None:
        wait = rec.get("waitMs", max(wall - cpu, 0.0))
        line = (f"{head} {wall:.1f}ms wall / {cpu:.2f}ms cpu "
                f"(wait {wait / wall * 100:.0f}%)")
    elif wall > 0:
        line = f"{head} {wall:.1f}ms wall (cpu unsampled)"
    else:
        line = head
    dl = rec.get("deadline")
    if dl:
        line += (f"  deadline={dl.get('budgetMs', 0)}ms"
                 f"->{dl.get('remainingMs', 0)}ms left")
    if rec.get("verdict") not in (None, "slow"):
        line += f"  verdict={rec['verdict']}"
    out = [line]
    stages = (rec.get("stages") or {}).get("stages") or {}
    if stages:
        with_cpu = any("cpuMs" in d for d in stages.values())
        parts = [(f"{s} {d.get('wallMs', 0):.1f}/"
                  f"{d.get('cpuMs', 0):.2f}ms" if "cpuMs" in d else
                  f"{s} {d.get('wallMs', 0):.1f}ms")
                 for s, d in sorted(stages.items(),
                                    key=lambda kv:
                                    -kv[1].get("wallMs", 0))]
        out.append(("    stages (wall/cpu): " if with_cpu else
                    "    stages (wall): ") + " ".join(parts))
    notes = dict(rec.get("notes") or {})
    notes.update((rec.get("stages") or {}).get("notes") or {})
    if notes:
        out.append("    notes: " + " ".join(
            f"{k}={json.dumps(v, separators=(',', ':'))}"
            if isinstance(v, (dict, list)) else f"{k}={v}"
            for k, v in sorted(notes.items())))
    return out


@command("cluster.slow")
def cmd_cluster_slow(env: CommandEnv, args: list[str]) -> str:
    """The cluster's tail, after the fact: every node's flight
    recorder ring (/debug/slow, profiling.FlightRecorder) fanned out,
    merged by trace id, and rendered as the top-N slowest END-TO-END
    requests — one block per request with each hop's wall/cpu/wait
    split, stage decomposition, deadline budget+verdict and
    hedge/QoS/native-plane notes, then the merged cross-role span
    tree, time-aligned like trace.show.  `-top=N` blocks (default 5),
    `-verdict=slow|error|deadline|shed` filters on any hop's verdict,
    `-nodes=host:port,...` adds listeners the topology doesn't know,
    `-clear` empties every ring instead (chaos runs reset between
    scenarios).  A node whose scrape fails mid-fan-out is noted and
    skipped — mid-incident is exactly when one wedged node must not
    take the whole view down."""
    opts = _parse_flags(args)
    try:
        top = max(1, int(opts.get("top", 5)))
    except ValueError:
        return "bad -top"
    want = opts.get("verdict", "")
    nodes = _top_nodes(env, opts)

    if "clear" in opts:
        def clear(url: str) -> "tuple[str, bool]":
            try:
                r = http_json("POST", f"{url}/debug/slow",
                              {"clear": True}, timeout=5)
                return url, isinstance(r, dict) and "error" not in r
            except OSError:
                return url, False
        with ThreadPoolExecutor(max_workers=min(8, len(nodes))) as ex:
            results = dict(ex.map(clear, nodes))
        ok = sum(1 for v in results.values() if v)
        out = [f"cluster.slow — cleared {ok}/{len(nodes)} rings"]
        out.extend(f"  {u}: unreachable" for u, v in results.items()
                   if not v)
        return "\n".join(out)

    def fetch(url: str) -> "tuple[str, dict | None]":
        try:
            r = http_json("GET", f"{url}/debug/slow", timeout=5)
        except OSError:
            return url, None
        return url, r if isinstance(r, dict) and "records" in r \
            else None

    with ThreadPoolExecutor(max_workers=min(8, len(nodes))) as ex:
        snaps = dict(ex.map(fetch, nodes))

    # merge by trace id: the same end-to-end request appears in each
    # hop's ring under one id; records with no id stand alone
    groups: "dict[str, list[tuple[str, dict]]]" = {}
    captured = 0
    skipped: list[str] = []
    loose = 0
    seen_recs: "set[str]" = set()
    for url in nodes:
        snap = snaps.get(url)
        if snap is None:
            skipped.append(f"  {url}: scrape failed, skipped")
            continue
        for rec in snap.get("records", []):
            if not isinstance(rec, dict):
                continue
            # one recorder answering under two addresses (a node
            # listed both by the topology and -nodes=, or an
            # in-process multi-role rig sharing one ring) must not
            # double every hop of every request it captured
            fp = json.dumps(rec, sort_keys=True,
                            separators=(",", ":"))
            if fp in seen_recs:
                continue
            seen_recs.add(fp)
            captured += 1
            tid = rec.get("traceId") or ""
            if not tid:
                loose += 1
                tid = f"(no-trace-{loose})"
            groups.setdefault(tid, []).append((url, rec))
    if want:
        groups = {tid: hops for tid, hops in groups.items()
                  if any(r.get("verdict") == want for _u, r in hops)}

    # end-to-end wall = the slowest hop's wall (the edge's record
    # covers its downstream hops); rank the groups by it
    def group_wall(hops: "list[tuple[str, dict]]") -> float:
        return max(r.get("wallMs", 0.0) for _u, r in hops)

    ranked = sorted(groups.items(), key=lambda kv: -group_wall(kv[1]))
    out = [f"cluster.slow — {captured} records on "
           f"{sum(1 for u in nodes if snaps.get(u) is not None)}"
           f"/{len(nodes)} nodes, "
           f"{len(groups)} distinct requests"
           + (f" (verdict={want})" if want else "")
           + f", top {min(top, len(ranked))}"]
    out.extend(skipped)
    for tid, hops in ranked[:top]:
        # a hop with a terminal verdict names the incident better
        # than "slow"; surface the worst one in the header
        verdicts = {r.get("verdict", "slow") for _u, r in hops}
        headline = next((v for v in ("deadline", "error", "shed")
                         if v in verdicts), "slow")
        out.append(f"{group_wall(hops):9.1f}ms  trace={tid}  "
                   f"verdict={headline}  {len(hops)} hop(s)")
        spans: "dict[str, dict]" = {}
        for url, rec in sorted(hops,
                               key=lambda ur: -ur[1].get("wallMs", 0)):
            try:
                out.extend(_render_slow_hop(url, rec))
            except Exception as e:  # noqa: BLE001 — one malformed
                # record must not hide the rest of the request
                out.append(f"  {url}: record render failed: {e}")
            for s in rec.get("spans") or []:
                if isinstance(s, dict) and s.get("spanId"):
                    s.setdefault("node", url)
                    spans.setdefault(s["spanId"], s)
        if spans:
            tree = render_trace(
                sorted(spans.values(), key=lambda s: s["start"]))
            out.extend("  " + t for t in tree.splitlines())
    if len(out) == 1 + len(skipped):
        out.append("  (no records — rings empty or filtered out)")
    return "\n".join(out)


@command("cluster.profile")
def cmd_cluster_profile(env: CommandEnv, args: list[str]) -> str:
    """Arm the sampling profiler on every node, wait
    `-duration=N` seconds (default 10), disarm, and merge the folded
    stacks into one cluster-wide flame view (`-hz=N` sampling rate,
    `-top=N` lines shown, `-out=FILE` writes the full merged
    collapsed-stack file for flamegraph.pl).  A node whose sampler
    was already armed keeps its window but is still collected and
    disarmed — two operators profiling at once merge, not clobber."""
    from .. import profiling
    opts = _parse_flags(args)
    try:
        duration = max(0.2, float(opts.get("duration", 10.0)))
        hz = float(opts.get("hz", 100.0))
        top = int(opts.get("top", 25))
    except ValueError:
        return "bad -duration/-hz/-top"
    nodes = _top_nodes(env, opts)

    def arm(url: str) -> "tuple[str, bool]":
        try:
            r = http_json("POST", f"{url}/debug/pprof",
                          {"action": "start", "hz": hz}, timeout=5)
            return url, isinstance(r, dict) and "error" not in r
        except OSError:
            return url, False

    def disarm(url: str) -> "tuple[str, dict | None]":
        try:
            r = http_json("POST", f"{url}/debug/pprof",
                          {"action": "stop"}, timeout=10)
            return url, r if isinstance(r, dict) else None
        except OSError:
            return url, None

    with ThreadPoolExecutor(max_workers=min(8, len(nodes))) as ex:
        armed = dict(ex.map(arm, nodes))
        time.sleep(duration)
        snaps = dict(ex.map(disarm, nodes))

    tables, per_node = [], []
    for url in nodes:
        snap = snaps.get(url)
        if snap is None:
            per_node.append(f"  {url}: unreachable"
                            if not armed.get(url) else
                            f"  {url}: armed but no snapshot")
            continue
        tables.append(snap.get("folded") or {})
        per_node.append(
            f"  {url}: {snap.get('samples', 0)} passes, "
            f"{snap.get('stacks', 0)} stacks, "
            f"overhead={snap.get('overhead', 0.0) * 100:.2f}%")
    merged = profiling.merge_folded(tables)
    total = sum(merged.values()) or 1
    out = [f"cluster.profile — {duration:.1f}s @ {hz:.0f}Hz, "
           f"{len(tables)}/{len(nodes)} nodes, "
           f"{len(merged)} distinct stacks"]
    out.extend(per_node)
    if "out" in opts:
        with open(opts["out"], "w", encoding="utf-8") as f:
            for stack, n in sorted(merged.items(),
                                   key=lambda kv: -kv[1]):
                f.write(f"{stack} {n}\n")
        out.append(f"full collapsed-stack file: {opts['out']} "
                   f"(flamegraph.pl input)")
    for stack, n in sorted(merged.items(),
                           key=lambda kv: -kv[1])[:top]:
        frames = stack.split(";")
        tail = ";".join(frames[-3:]) if len(frames) > 3 else stack
        out.append(f"{n:6d} {n / total * 100:4.1f}%  {tail}")
    return "\n".join(out)


@command("volume.scrub")
def cmd_volume_scrub(env: CommandEnv, args: list[str]) -> str:
    """CRC-verify every needle of every (or one) volume
    (volume.fsck-style integrity pass)."""
    opts = _parse_flags(args)
    target = int(opts["volumeId"]) if "volumeId" in opts else None
    out = []
    for vid, urls in sorted(_volumes_by_id(env).items()):
        if target is not None and vid != target:
            continue
        for url in urls:
            r = http_json("POST", f"{url}/admin/scrub",
                          {"volumeId": vid}, timeout=30)
            if r.get("error"):
                out.append(f"volume {vid} @ {url}: ERROR {r['error']}")
            else:
                status = "ok" if not r["errors"] else r["errors"][:3]
                out.append(f"volume {vid} @ {url}: checked "
                           f"{r['checked']}, {status}")
    return "\n".join(out) if out else "no volumes"


# --- helpers -------------------------------------------------------------

def _must(r: dict, what: str) -> dict:
    if isinstance(r, dict) and r.get("error"):
        raise RuntimeError(f"{what}: {r['error']}")
    return r


def _parse_flags(args: list[str]) -> dict:
    """-volumeId=3 -collection=x style flags."""
    out = {}
    for a in args:
        if a.startswith("-") and "=" in a:
            k, v = a[1:].split("=", 1)
            out[k] = v
        elif a.startswith("-"):
            out[a[1:]] = "true"
    return out


def _volumes_by_id(env: CommandEnv) -> dict[int, list[str]]:
    from ..topology import iter_volume_list_volumes
    out: dict[int, list[str]] = {}
    for node, v in iter_volume_list_volumes(env.volume_list()):
        out.setdefault(v["id"], []).append(node["url"])
    return out


def _ec_volumes(env: CommandEnv) -> dict[int, None]:
    from ..topology import iter_volume_list_ec_shards
    out: dict[int, None] = {}
    for _node, e in iter_volume_list_ec_shards(env.volume_list()):
        out[e["volumeId"]] = None
    return out


def _ec_shard_locations(env: CommandEnv, vid: int) -> dict[str, list[int]]:
    from ..topology import fetch_ec_shard_locations
    return fetch_ec_shard_locations(env.master, vid)


def _all_node_urls(env: CommandEnv) -> list[str]:
    r = master_json(env.master, "GET", "/cluster/status", timeout=30)
    return r.get("dataNodes", [])


def _select_volumes(env: CommandEnv, opts: dict) -> list[int]:
    """command_ec_encode.go:375 collectVolumeIdsForEcEncode (simplified:
    explicit -volumeId, or all volumes of -collection)."""
    if "volumeId" in opts:
        return [int(opts["volumeId"])]
    collection = opts.get("collection")
    if collection is None:
        return []
    from ..topology import iter_volume_list_volumes
    vids = []
    for _node, v in iter_volume_list_volumes(env.volume_list()):
        if v.get("collection", "") == (
                "" if collection == "ALL" else collection):
            vids.append(v["id"])
    return sorted(set(vids))


def run_command(env: CommandEnv, line: str) -> str:
    parts = line.split()
    if not parts:
        return ""
    name, args = parts[0], parts[1:]
    fn = COMMANDS.get(name)
    if fn is None:
        raise ValueError(
            f"unknown command {name!r}; known: {sorted(COMMANDS)}")
    # shell ingress of the deadline plane (util/deadline): with
    # SEAWEEDFS_TPU_DEADLINE_DEFAULT_MS configured every command runs
    # under a budget that its outbound hops forward and derive their
    # timeouts from — a wedged peer fails an operator's command fast
    # instead of parking the shell.  Unconfigured: nothing is bound.
    from ..util import deadline as _dl
    budget = _dl.default_budget()
    if budget > 0:
        with _dl.scope(budget):
            return fn(env, args)
    return fn(env, args)


def _volume_meta(env: CommandEnv, vid: int) -> "dict | None":
    """Collection etc. from the master volume list (the lookup
    endpoint returns urls only)."""
    from ..topology import iter_volume_list_volumes
    for _node, v in iter_volume_list_volumes(env.volume_list()):
        if v["id"] == vid:
            return v
    return None


@command("volume.copy")
def cmd_volume_copy(env: CommandEnv, args: list[str]) -> str:
    """shell/command_volume_copy.go: replicate one volume to a target
    server — freeze-copy-mount via the shared _move_volume pipeline
    (unfenced copies of live volumes tear .dat/.idx)."""
    env.confirm_is_locked()
    opts = _parse_flags(args)
    vid = int(opts["volumeId"])
    dst = opts["target"]
    locs = env.volume_locations(vid)
    if not locs:
        return f"volume {vid} not found"
    src = opts.get("source", locs[0]["url"])
    meta = _volume_meta(env, vid) or {}
    if any(loc["url"] == dst for loc in locs):
        return f"volume {vid} already on {dst}"
    _move_volume(env, vid, meta.get("collection", ""), src, dst,
                 delete_source=False)
    return f"copied volume {vid}: {src} -> {dst}"


@command("volume.move")
def cmd_volume_move(env: CommandEnv, args: list[str]) -> str:
    """shell/command_volume_move.go: freeze, copy to target, mount,
    delete at the source (the shared _move_volume pipeline — data is
    readable at every step)."""
    env.confirm_is_locked()
    opts = _parse_flags(args)
    vid = int(opts["volumeId"])
    src = opts["source"]
    dst = opts["target"]
    if src == dst:
        return "source and target are the same server"
    locs = env.volume_locations(vid)
    if not any(loc["url"] == src for loc in locs):
        return f"volume {vid} is not on {src}"
    meta = _volume_meta(env, vid) or {}
    collection = meta.get("collection", "")
    if any(loc["url"] == dst for loc in locs):
        # target already holds a replica: deleting src would still
        # need its copy verified — just drop the source replica
        _must(http_json("POST", f"{src}/admin/delete_volume",
                        {"volumeId": vid,
                         "collection": collection}, timeout=30),
              f"delete on {src}")
    else:
        _move_volume(env, vid, collection, src, dst,
                     delete_source=True)
    return f"moved volume {vid}: {src} -> {dst}"


@command("volume.grow")
def cmd_volume_grow(env: CommandEnv, args: list[str]) -> str:
    """shell/command_volume_grow.go / master VolumeGrow."""
    env.confirm_is_locked()
    opts = _parse_flags(args)
    r = master_json(env.master, "POST", "/vol/grow", {
        "collection": opts.get("collection", ""),
        "replication": opts.get("replication", ""),
        "count": int(opts.get("count", 1))}, timeout=30)
    if "volumeIds" not in r:
        return f"grow failed: {r}"
    return f"grew volumes: {r['volumeIds']}"


@command("collection.list")
def cmd_collection_list(env: CommandEnv, args: list[str]) -> str:
    """shell/command_collection_list.go: collections + volume counts
    from the master's volume list."""
    from ..topology import iter_volume_list_volumes
    vols: dict[str, set] = {}
    for _node, v in iter_volume_list_volumes(env.volume_list()):
        # count DISTINCT volumes, not replica pairs
        vols.setdefault(v.get("collection", ""), set()).add(v["id"])
    return "\n".join(
        f"{name or '(default)'}: {len(ids)} volumes"
        for name, ids in sorted(vols.items())) or "no volumes"


@command("collection.delete")
def cmd_collection_delete(env: CommandEnv, args: list[str]) -> str:
    """shell/command_collection_delete.go: delete every volume of a
    collection on every server (requires the lock + an explicit
    -force)."""
    env.confirm_is_locked()
    opts = _parse_flags(args)
    name = opts.get("collection", "")
    if not name:
        return "need -collection=<name>"
    if "force" not in opts:
        return ("this deletes EVERY volume of the collection; "
                "re-run with -force")
    from ..topology import iter_volume_list_volumes
    deleted = []
    vl = env.volume_list()
    for node, v in list(iter_volume_list_volumes(vl)):
        if v.get("collection", "") != name:
            continue
        _must(http_json("POST", f"{node['url']}/admin/delete_volume",
                        {"volumeId": v["id"],
                         "collection": name}, timeout=30),
              f"delete {v['id']} on {node['url']}")
        deleted.append(v["id"])
    # EC volumes of the collection too (the Go analog deletes both)
    ec_deleted = []
    for dc in vl.get("dataCenters", {}).values():
        for rack in dc.get("racks", {}).values():
            for node in rack.get("nodes", []):
                for e in node.get("ecShards", []):
                    if e.get("collection", "") != name:
                        continue
                    shard_ids = [i for i in range(32)
                                 if e.get("shardBits", 0) >> i & 1]
                    _must(http_json(
                        "POST",
                        f"{node['url']}/admin/ec/delete_shards",
                        {"volumeId": e["volumeId"],
                         "collection": name,
                         "shardIds": shard_ids}, timeout=30),
                        f"delete ec {e['volumeId']} on "
                        f"{node['url']}")
                    ec_deleted.append(e["volumeId"])
    out = f"deleted collection {name!r}: volumes {sorted(set(deleted))}"
    if ec_deleted:
        out += f", ec volumes {sorted(set(ec_deleted))}"
    return out


@command("volume.merge")
def cmd_volume_merge(env: CommandEnv, args: list[str]) -> str:
    """shell/command_volume_merge.go (-volumeId=N): merge DIVERGED
    replicas in append-timestamp order into one copy, then replace
    every replica with it.

    1) mark all replicas readonly (remembering prior state)
    2) merge on the first replica, pulling peers' .dat files
       (AppendAtNs-ordered union, newest write/tombstone wins)
    3) re-copy the merged volume over the other replicas
    4) restore writable state"""
    env.confirm_is_locked()
    opts = _parse_flags(args)
    if "volumeId" not in opts:
        return "usage: volume.merge -volumeId=N"
    vid = int(opts["volumeId"])
    urls = _volumes_by_id(env).get(vid)
    if not urls:
        raise RuntimeError(f"volume {vid} not found")
    meta = _volume_meta(env, vid) or {}
    collection = meta.get("collection", "")
    was_writable = not meta.get("readOnly", False)
    primary, others = urls[0], urls[1:]
    for url in urls:
        _must(http_json("POST", f"{url}/admin/set_readonly",
                        {"volumeId": vid, "readOnly": True}, timeout=30),
              f"set readonly on {url}")
    try:
        r = _must(http_json(
            "POST", f"{primary}/admin/volume/merge",
            {"volumeId": vid, "collection": collection,
             "peers": others}, timeout=30), f"merge on {primary}")
        # replace the other replicas with the merged copy
        for url in others:
            _must(http_json("POST", f"{url}/admin/delete_volume",
                            {"volumeId": vid}, timeout=30),
                  f"drop stale replica on {url}")
            _copy_volume_files(env, vid, collection, primary, url)
            _must(http_json("POST", f"{url}/admin/mount_volume",
                            {"volumeId": vid,
                             "collection": collection}, timeout=30),
                  f"mount merged on {url}")
            _must(http_json("POST", f"{url}/admin/set_readonly",
                            {"volumeId": vid, "readOnly": True}, timeout=30),
                  f"re-freeze merged on {url}")
    finally:
        if was_writable:
            for url in urls:
                try:
                    http_json("POST", f"{url}/admin/set_readonly",
                              {"volumeId": vid, "readOnly": False}, timeout=30)
                except OSError:
                    pass
    return (f"volume {vid}: merged {len(urls)} replicas "
            f"({r['mergedNeedles']} live needles, "
            f"{r['datBytes']} bytes) on {primary}; "
            f"replaced {len(others)} peer copies")
