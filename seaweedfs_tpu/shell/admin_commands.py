"""Volume/cluster/MQ admin shell commands — the operator-surface
breadth pass (weed/shell/command_volume_mount.go, _volume_delete.go,
_volume_configure_replication.go, _volume_mark.go,
_volume_server_evacuate.go, _cluster_ps.go, _mq_topic_*.go)."""

from __future__ import annotations

import json

from ..operation import master_json
from ..server.httpd import http_json
from .commands import (CommandEnv, _all_node_urls, _move_shard,
                       _move_volume, _must, _parse_flags, command)


def _flag_true(opts: dict, name: str) -> bool:
    """Go-style boolean flags: presence is true, but an explicit
    -name=false|0|no is false."""
    if name not in opts:
        return False
    return str(opts[name]).lower() not in ("false", "0", "no")


def _vid_locations(env: CommandEnv, vid: int) -> "list[str]":
    return [l["url"] for l in env.volume_locations(vid)]


def _one_location(env: CommandEnv, opts: dict, vid: int) -> str:
    node = opts.get("node", "")
    locs = _vid_locations(env, vid)
    if node:
        if locs and node not in locs:
            raise RuntimeError(
                f"volume {vid} is not on {node} (it is on {locs})")
        return node
    if not locs:
        raise RuntimeError(f"volume {vid} has no locations")
    return locs[0]


@command("volume.mount")
def cmd_volume_mount(env: CommandEnv, args: list[str]) -> str:
    """command_volume_mount.go: mount an unmounted volume on a server
    (-volumeId=N -node=host:port).  -node is REQUIRED: the master
    forgets an unmounted volume within one heartbeat pulse, so there
    is no reliable location to infer."""
    env.confirm_is_locked()
    opts = _parse_flags(args)
    vid = int(opts["volumeId"])
    node = opts.get("node", "")
    if not node:
        raise RuntimeError("volume.mount requires -node=host:port "
                           "(the master does not track unmounted "
                           "volumes)")
    _must(http_json("POST", f"{node}/admin/mount_volume",
                    {"volumeId": vid,
                     "collection": opts.get("collection", "")}, timeout=30),
          f"mount volume {vid} on {node}")
    return f"mounted volume {vid} on {node}"


@command("volume.unmount")
def cmd_volume_unmount(env: CommandEnv, args: list[str]) -> str:
    """command_volume_unmount.go (-volumeId=N [-node=...])."""
    env.confirm_is_locked()
    opts = _parse_flags(args)
    vid = int(opts["volumeId"])
    node = _one_location(env, opts, vid)
    _must(http_json("POST", f"{node}/admin/unmount_volume",
                    {"volumeId": vid}, timeout=30),
          f"unmount volume {vid} on {node}")
    return f"unmounted volume {vid} on {node}"


@command("volume.delete")
def cmd_volume_delete(env: CommandEnv, args: list[str]) -> str:
    """command_volume_delete.go: delete a volume from every holder
    (-volumeId=N)."""
    env.confirm_is_locked()
    opts = _parse_flags(args)
    vid = int(opts["volumeId"])
    locs = _vid_locations(env, vid)
    if not locs:
        return f"volume {vid} has no locations"
    for url in locs:
        _must(http_json("POST", f"{url}/admin/delete_volume",
                        {"volumeId": vid}, timeout=30),
              f"delete volume {vid} on {url}")
    return f"deleted volume {vid} from {len(locs)} servers"


@command("volume.delete.empty")
def cmd_volume_delete_empty(env: CommandEnv, args: list[str]) -> str:
    """command_volume_delete_empty.go: delete volumes holding no live
    files (optionally -collection=...)."""
    env.confirm_is_locked()
    from ..topology import iter_volume_list_volumes
    opts = _parse_flags(args)
    collection = opts.get("collection")
    seen: dict[int, dict] = {}
    for _n, v in iter_volume_list_volumes(env.volume_list()):
        seen[v["id"]] = v
    deleted = []
    for vid, v in sorted(seen.items()):
        if collection is not None and \
                v.get("collection", "") != collection:
            continue
        if v.get("fileCount", 0) - v.get("deleteCount", 0) > 0:
            continue
        locs = _vid_locations(env, vid)
        # Quiet-period guard against the check-then-delete race: mark
        # the volume readonly FIRST (blocking new writes), then ask
        # every holder for its actual needle inventory; only a volume
        # that is verifiably empty while unwritable is deleted.  A
        # write that slipped in before the readonly mark is seen by
        # the inventory check; one after it is rejected at the server.
        # Volumes the OPERATOR already froze stay frozen on the
        # not-empty path — only our own quiet-period mark is undone.
        was_readonly = bool(v.get("readOnly", False))
        for url in locs:
            http_json("POST", f"{url}/admin/set_readonly",
                      {"volumeId": vid, "readOnly": True}, timeout=30)
        live_anywhere = False
        for url in locs:
            r = http_json("GET",
                          f"{url}/admin/volume_index?volumeId={vid}",
                    timeout=30)
            if r.get("error") or r.get("entries"):
                live_anywhere = True
                break
        if live_anywhere:
            if not was_readonly:
                for url in locs:  # undo OUR mark only
                    http_json("POST", f"{url}/admin/set_readonly",
                              {"volumeId": vid, "readOnly": False}, timeout=30)
            continue
        for url in locs:
            http_json("POST", f"{url}/admin/delete_volume",
                      {"volumeId": vid}, timeout=30)
        deleted.append(vid)
    return f"deleted {len(deleted)} empty volumes: {deleted}" \
        if deleted else "no empty volumes"


@command("volume.mark")
def cmd_volume_mark(env: CommandEnv, args: list[str]) -> str:
    """command_volume_mark.go: -volumeId=N -readonly|-writable on
    every holder."""
    env.confirm_is_locked()
    opts = _parse_flags(args)
    vid = int(opts["volumeId"])
    if _flag_true(opts, "readonly"):
        ro = True
    elif _flag_true(opts, "writable") or \
            ("readonly" in opts and not _flag_true(opts, "readonly")):
        ro = False
    else:
        raise RuntimeError("pass -readonly or -writable")
    locs = _vid_locations(env, vid)
    for url in locs:
        _must(http_json("POST", f"{url}/admin/set_readonly",
                        {"volumeId": vid, "readOnly": ro}, timeout=30),
              f"mark volume {vid} on {url}")
    state = "readonly" if ro else "writable"
    return f"marked volume {vid} {state} on {len(locs)} servers"


@command("volume.configure.replication")
def cmd_volume_configure_replication(env: CommandEnv,
                                     args: list[str]) -> str:
    """command_volume_configure_replication.go: rewrite a volume's
    replica placement (-volumeId=N -replication=XYZ) on every
    holder."""
    env.confirm_is_locked()
    opts = _parse_flags(args)
    vid = int(opts["volumeId"])
    replication = str(opts["replication"])
    if len(replication) != 3 or not replication.isdigit():
        raise RuntimeError("-replication must be 3 digits (e.g. 001)")
    locs = _vid_locations(env, vid)
    if not locs:
        return f"volume {vid} has no locations"
    for url in locs:
        _must(http_json("POST", f"{url}/admin/configure_volume",
                        {"volumeId": vid,
                         "replication": replication}, timeout=30),
              f"configure volume {vid} on {url}")
    return (f"volume {vid} replication set to {replication} on "
            f"{len(locs)} servers")


@command("volume.server.evacuate")
def cmd_volume_server_evacuate(env: CommandEnv,
                               args: list[str]) -> str:
    """command_volume_server_evacuate.go: move every volume AND every
    EC shard off a server (-node=host:port) onto the others.
    Replicated volumes keep their copy count: the victim's copy is
    moved to a server that doesn't already hold the volume (never just
    deleted — that would leave it under-replicated)."""
    env.confirm_is_locked()
    from ..topology import (iter_volume_list_ec_shards,
                            iter_volume_list_volumes)
    opts = _parse_flags(args)
    node = opts["node"]
    others = [u for u in _all_node_urls(env) if u != node]
    if not others:
        return "no other servers to evacuate to"
    vl = env.volume_list()
    victims = []
    ec_victims = []
    per_target: dict[str, int] = {u: 0 for u in others}
    for n, v in iter_volume_list_volumes(vl):
        if n["url"] == node:
            victims.append(v)
        else:
            per_target[n["url"]] = per_target.get(n["url"], 0) + 1
    for n, e in iter_volume_list_ec_shards(vl):
        if n["url"] == node:
            ec_victims.append(e)
    moved = 0
    skipped = []
    for v in victims:
        holders = set(_vid_locations(env, v["id"]))
        candidates = [u for u in others if u not in holders]
        if not candidates:
            skipped.append(v["id"])
            continue
        target = min(candidates, key=lambda u: per_target[u])
        _move_volume(env, v["id"], v.get("collection", ""), node,
                     target)
        per_target[target] += 1
        moved += 1
    ec_moved = 0
    from .commands import _ec_shard_locations
    for e in ec_victims:
        vid = e.get("volumeId", e.get("id"))
        bits = e.get("shardBits", 0)
        sids = [s for s in range(32) if bits & (1 << s)]
        for sid in sids:
            holders = _ec_shard_locations(env, vid)
            target = min(others,
                         key=lambda u: len(holders.get(u, [])))
            _move_shard(env, vid, e.get("collection", ""), sid, node,
                        target)
            ec_moved += 1
    out = f"evacuated {moved} volumes, {ec_moved} ec shards off {node}"
    if skipped:
        out += (f"; NOT moved (every other server already holds a "
                f"replica): volumes {skipped}")
    return out


# -- cluster ---------------------------------------------------------

@command("cluster.ps")
def cmd_cluster_ps(env: CommandEnv, args: list[str]) -> str:
    """command_cluster_ps.go: list cluster processes (masters +
    volume servers, with volume counts)."""
    from ..topology import iter_volume_list_volumes
    st = master_json(env.master, "GET", "/cluster/status", timeout=30)
    counts: dict[str, int] = {}
    for n, _v in iter_volume_list_volumes(env.volume_list()):
        counts[n["url"]] = counts.get(n["url"], 0) + 1
    lines = [f"master {st.get('leader', '?')} leader "
             f"(term {st.get('term', '?')})"]
    for peer in st.get("peers", []):
        if peer != st.get("leader"):
            lines.append(f"master {peer} follower")
    for url in st.get("dataNodes", []):
        lines.append(f"volume {url} ({counts.get(url, 0)} volumes)")
    return "\n".join(lines)


@command("cluster.status")
def cmd_cluster_status(env: CommandEnv, args: list[str]) -> str:
    """Raw cluster status JSON (command_cluster_status.go)."""
    return json.dumps(
        master_json(env.master, "GET", "/cluster/status",
            timeout=30), indent=2)


# -- mq.topic.* (command_mq_topic_*.go) ------------------------------

def _broker(env: CommandEnv, opts: dict) -> str:
    b = opts.get("broker", "")
    if not b:
        raise RuntimeError("pass -broker=host:port")
    return b


@command("mq.topic.list")
def cmd_mq_topic_list(env: CommandEnv, args: list[str]) -> str:
    opts = _parse_flags(args)
    ns = opts.get("namespace", "default")
    r = _must(http_json(
        "GET", f"{_broker(env, opts)}/topics/list?namespace={ns}", timeout=30),
        "list topics")
    topics = r.get("topics", [])
    return "\n".join(f"{ns}.{t}" for t in topics) or "no topics"


@command("mq.topic.configure")
def cmd_mq_topic_configure(env: CommandEnv, args: list[str]) -> str:
    opts = _parse_flags(args)
    r = _must(http_json(
        "POST", f"{_broker(env, opts)}/topics/configure",
        {"namespace": opts["namespace"], "topic": opts["topic"],
         "partitionCount": int(opts.get("partitionCount", 4))}, timeout=30),
        "configure topic")
    return (f"topic {opts['namespace']}.{opts['topic']}: "
            f"{len(r.get('partitions', []))} partitions")


@command("mq.topic.desc")
def cmd_mq_topic_desc(env: CommandEnv, args: list[str]) -> str:
    opts = _parse_flags(args)
    broker = _broker(env, opts)
    r = _must(http_json(
        "GET", f"{broker}/topics/lookup?namespace="
        f"{opts['namespace']}&topic={opts['topic']}",
                  timeout=30), "lookup topic")
    lines = []
    for a in r.get("assignments", []):
        p = a["partition"]
        lines.append(f"partition [{p['rangeStart']},{p['rangeStop']}) "
                     f"-> {a.get('broker', '?')}")
    sch = http_json("GET", f"{broker}/topics/schema?namespace="
                    f"{opts['namespace']}&topic={opts['topic']}", timeout=30)
    if "recordType" in sch:
        lines.append(f"schema rev {sch['revision']}: "
                     + json.dumps(sch["recordType"]))
    return "\n".join(lines)


@command("mq.topic.compact")
def cmd_mq_topic_compact(env: CommandEnv, args: list[str]) -> str:
    """command_mq_topic_compact.go: fold cold log segments into
    parquet."""
    opts = _parse_flags(args)
    r = _must(http_json(
        "POST", f"{_broker(env, opts)}/topics/compact",
        {"namespace": opts["namespace"], "topic": opts["topic"],
         "force": True,
         "keepRecent": int(opts.get("keepRecent", 1))}, timeout=30),
        "compact topic")
    done = sum(x.get("compacted", 0) for x in r.get("results", []))
    rows = sum(x.get("rows", 0) for x in r.get("results", []))
    return f"compacted {done} segments ({rows} rows) into parquet"


@command("sleep")
def cmd_sleep(env: CommandEnv, args: list[str]) -> str:
    """command_sleep.go — for scripted `;` sequences."""
    import time
    time.sleep(float(args[0]) if args else 1.0)
    return ""


# -- raft membership (shell/command_cluster_raft_*.go) ---------------------

@command("cluster.raft.ps")
def cmd_cluster_raft_ps(env: CommandEnv, args: list[str]) -> str:
    """command_cluster_raft_ps.go RaftListClusterServers: membership +
    replication state of the master raft group."""
    st = master_json(env.master, "GET", "/cluster/status", timeout=30)
    raft = st.get("raft", {})
    lines = [f"leader: {st.get('leader')}  term: {st.get('term')}  "
             f"topologyId: {st.get('topologyId')}"]
    for p in st.get("peers", []):
        mark = "*" if p == st.get("leader") else " "
        lines.append(f"  {mark} {p}")
    lines.append(f"log: commit={raft.get('commitIndex')} "
                 f"applied={raft.get('appliedIndex')} "
                 f"last={raft.get('lastLogIndex')} "
                 f"snapshot={raft.get('snapshotIndex')} "
                 f"persistent={raft.get('persistent')}")
    return "\n".join(lines)


@command("cluster.raft.add")
def cmd_cluster_raft_add(env: CommandEnv, args: list[str]) -> str:
    """command_cluster_raft_add.go RaftAddServer (-server=host:port):
    adds a master to the replicated membership."""
    opts = _parse_flags(args)
    server = opts.get("server", "")
    if not server:
        return "usage: cluster.raft.add -server=host:port"
    r = master_json(env.master, "POST", "/cluster/raft/config",
                    {"add": [server]}, timeout=30)
    _must(r, f"add raft server {server}")
    return f"members: {', '.join(r['peers'])}"


@command("cluster.raft.remove")
def cmd_cluster_raft_remove(env: CommandEnv, args: list[str]) -> str:
    """command_cluster_raft_remove.go RaftRemoveServer
    (-server=host:port)."""
    opts = _parse_flags(args)
    server = opts.get("server", "")
    if not server:
        return "usage: cluster.raft.remove -server=host:port"
    r = master_json(env.master, "POST", "/cluster/raft/config",
                    {"remove": [server]}, timeout=30)
    _must(r, f"remove raft server {server}")
    return f"members: {', '.join(r['peers'])}"


# -- round-5 breadth: volume server lifecycle, replica verification,
#    vacuum gates, tier aliases, mq balance/truncate ----------------------

@command("volume.server.state")
def cmd_volume_server_state(env: CommandEnv, args: list[str]) -> str:
    """command_volume_server_status.go (-node=host:port): one server's
    live /status view."""
    opts = _parse_flags(args)
    node = opts.get("node", "")
    if not node:
        return "usage: volume.server.state -node=host:port"
    st = http_json("GET", f"{node}/status", timeout=30)
    _must(st, f"status of {node}")
    vols = st.get("volumes", [])
    ecs = st.get("ecShards", [])
    lines = [f"{node}: version {st.get('version', '?')}, "
             f"{len(vols)}/{st.get('maxVolumeCount', '?')} volumes, "
             f"{len(ecs)} ec volumes, "
             f"maxFileKey {st.get('maxFileKey', 0)}, "
             f"readPlanePort {st.get('readPlanePort', 0)}"]
    for v in vols:
        lines.append(f"  vol {v['id']:6d} {v.get('collection', ''):12s}"
                     f" {v.get('size', 0):>12d}B"
                     f" files={v.get('fileCount', 0)}"
                     f"{' RO' if v.get('readOnly') else ''}")
    return "\n".join(lines)


@command("volume.server.leave")
def cmd_volume_server_leave(env: CommandEnv, args: list[str]) -> str:
    """command_volume_server_leave.go (-node=host:port): the server
    stops heartbeating and the master forgets it after its pulse
    timeout.  Evacuate first (volume.server.evacuate) — volumes on a
    left server are no longer assignable or discoverable."""
    env.confirm_is_locked()
    opts = _parse_flags(args)
    node = opts.get("node", "")
    if not node:
        return "usage: volume.server.leave -node=host:port"
    _must(http_json("POST", f"{node}/admin/leave", {}, timeout=30),
          f"leave {node}")
    return f"{node} left the cluster (master forgets it within its " \
           f"pulse timeout)"


@command("volume.vacuum.disable")
def cmd_volume_vacuum_disable(env: CommandEnv, args: list[str]) -> str:
    """command_volume_vacuum_disable.go: gate vacuum during delicate
    maintenance (every node unless -node=)."""
    opts = _parse_flags(args)
    nodes = [opts["node"]] if opts.get("node") \
        else _all_node_urls(env)
    for n in nodes:
        _must(http_json("POST", f"{n}/admin/vacuum_toggle",
                        {"enabled": False},
                  timeout=30), f"disable vacuum on {n}")
    return f"vacuum disabled on {len(nodes)} server(s)"


@command("volume.vacuum.enable")
def cmd_volume_vacuum_enable(env: CommandEnv, args: list[str]) -> str:
    """command_volume_vacuum_enable.go."""
    opts = _parse_flags(args)
    nodes = [opts["node"]] if opts.get("node") \
        else _all_node_urls(env)
    for n in nodes:
        _must(http_json("POST", f"{n}/admin/vacuum_toggle",
                        {"enabled": True},
                  timeout=30), f"enable vacuum on {n}")
    return f"vacuum enabled on {len(nodes)} server(s)"


@command("volume.replica.check")
def cmd_volume_replica_check(env: CommandEnv, args: list[str]) -> str:
    """command_volume_check_disk.go's replica-divergence angle: compare
    every replicated volume's fileCount/deleteCount/size ACROSS its
    replicas via each server's live /status (the master view is
    aggregated and can hide divergence)."""
    per_server: dict[str, dict[int, dict]] = {}
    for url in _all_node_urls(env):
        st = http_json("GET", f"{url}/status", timeout=30)
        if st.get("error"):
            continue
        per_server[url] = {v["id"]: v for v in st.get("volumes", [])}
    by_vid: dict[int, list] = {}
    for url, vols in per_server.items():
        for vid, v in vols.items():
            by_vid.setdefault(vid, []).append((url, v))
    divergent = []
    for vid, replicas in sorted(by_vid.items()):
        if len(replicas) < 2:
            continue
        sigs = {(v.get("fileCount", 0), v.get("deleteCount", 0),
                 v.get("size", 0)) for _u, v in replicas}
        if len(sigs) > 1:
            detail = "; ".join(
                f"{u}: files={v.get('fileCount', 0)} "
                f"deletes={v.get('deleteCount', 0)} "
                f"size={v.get('size', 0)}" for u, v in replicas)
            divergent.append(f"volume {vid} DIVERGES: {detail}")
    checked = sum(1 for r in by_vid.values() if len(r) > 1)
    return "\n".join([f"checked {checked} replicated volumes: "
                      f"{len(divergent)} divergent"] + divergent)


@command("volume.tier.upload")
def cmd_volume_tier_upload(env: CommandEnv, args: list[str]) -> str:
    """command_volume_tier_upload.go: the reference's name for moving
    a volume's .dat to an S3-compatible tier backend (same engine as
    volume.tier.move; dest flags follow the reference)."""
    from .fs_commands import cmd_volume_tier_move
    return cmd_volume_tier_move(env, args)


@command("volume.tier.download")
def cmd_volume_tier_download(env: CommandEnv, args: list[str]) -> str:
    """command_volume_tier_download.go: bring a tiered volume's .dat
    back to local disk (same engine as volume.tier.fetch)."""
    from .fs_commands import cmd_volume_tier_fetch
    return cmd_volume_tier_fetch(env, args)


@command("cluster.raft.leader.transfer")
def cmd_cluster_raft_leader_transfer(env: CommandEnv,
                                     args: list[str]) -> str:
    """command_cluster_raft_leader_transfer.go ([-target=URL]): the
    leader pushes a final heartbeat, nudges its most-caught-up peer
    (or -target) with TimeoutNow, and steps down — handover in one
    round trip instead of an election timeout."""
    from ..operation import master_json
    opts = _parse_flags(args)
    r = master_json(env.master, "POST", "/cluster/raft/transfer",
                    {"target": opts.get("target", "")}, timeout=30)
    _must(r, "leader transfer")
    return "leadership transferred (TimeoutNow nudge sent to the " \
           "successor)"


@command("mq.balance")
def cmd_mq_balance(env: CommandEnv, args: list[str]) -> str:
    """command_mq_balance.go (-broker=host:port): rebalance every
    topic's partition ownership round-robin across live brokers."""
    opts = _parse_flags(args)
    r = _must(http_json("POST", f"{_broker(env, opts)}/topics/balance",
                        {}, timeout=30), "mq balance")
    return (f"balanced {r.get('topics', 0)} topics across "
            f"{len(r.get('brokers', []))} brokers; moved "
            f"{r.get('movedPartitions', 0)} partitions")


@command("mq.topic.truncate")
def cmd_mq_topic_truncate(env: CommandEnv, args: list[str]) -> str:
    """mq.topic.truncate (-broker= -namespace= -topic=): drop a
    topic's stored messages, keeping its configuration."""
    env.confirm_is_locked()
    opts = _parse_flags(args)
    r = _must(http_json(
        "POST", f"{_broker(env, opts)}/topics/truncate",
        {"namespace": opts["namespace"], "topic": opts["topic"]}, timeout=30),
        "truncate topic")
    return (f"truncated {r.get('truncated', 0)} partitions of "
            f"{opts['namespace']}.{opts['topic']}")
