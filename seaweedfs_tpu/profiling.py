"""Performance-observability plane: where do the microseconds go.

PR 3 (tracing) answers "what did THIS request do"; the metrics plane
answers "how many / how slow on average".  Neither can answer the two
questions the headline ROADMAP gaps turn on — "what is this process
doing RIGHT NOW" (the 50x write-path gap is pure host-side overhead,
arXiv:1709.05365 §5) and "which STAGE of the hot path eats the time"
(the TPU arm's numbers were only reachable with device-level telemetry,
arXiv:2112.09017).  This module is the instrument panel both
questions read from:

1. `Sampler` — an in-process sampling wall-clock profiler.  A daemon
   thread snapshots `sys._current_frames()` at a configured rate and
   folds each thread's stack into collapsed-stack lines
   (``frame;frame;frame count`` — the flamegraph.pl input format).
   Off by default; armed per process via ``POST /debug/pprof`` (see
   server/debug.py) or at boot with ``SEAWEEDFS_TPU_PROFILE_HZ``.
   Overhead is bounded by construction: the sampler measures its own
   per-pass cost and stretches its sleep so sampling never exceeds
   ``MAX_OVERHEAD`` of one core, frame labels are cached per code
   object, and the folded table is capped (overflow counted, never
   unbounded).

2. `StageTrack` + `stage()` — write-path latency decomposition.  A
   role server opens a track around its hot handler
   (``with profiling.track("write", role=..., metrics=...)``); code
   anywhere down the synchronous call chain wraps its stages in
   ``with profiling.stage("append")`` — a contextvar carries the
   active track, so storage/volume.py needs no API change to report
   into the volume server's registry.  On finish the track observes
   one ``write_stage_seconds{stage}`` histogram cell per stage (plus
   ``stage="total"``) into the role's metrics and emits sibling trace
   spans, so `trace.show` renders the same breakdown per request.
   When no track is active, `stage()` is a shared no-op context
   manager: one contextvar read on the hot path.

3. Device telemetry — `device_note` (h2d/d2h staging throughput),
   `kernel_note` (per-encode kernel wall-ms), and
   `sample_device_memory` (jax backend memory stats), all recorded
   into stats.PROCESS so every role's /metrics carries them.  jax is
   only imported inside `sample_device_memory`, guarded — the module
   must be importable on roles that never touch a device.

4. Prometheus-text helpers (`parse_prom_text`, `prom_histogram`,
   `histogram_quantile`) and `merge_folded` — the client half of the
   plane, shared by `weed shell cluster.top` / `cluster.profile` and
   `bench.py write_path`.

5. Cost attribution (ISSUE 15): every `stage()` window additionally
   samples `time.thread_time_ns()` at its boundaries, so each stage
   reports CPU beside wall into `<name>_stage_cpu_seconds{stage}` —
   `wall − cpu` per stage IS the GIL/lock/syscall wait, measured
   instead of inferred.  The per-thread clock makes the `use_track()`
   re-bind exact: a stage timed on a limiter-pool/hedge/chunk-upload
   thread charges THAT thread's CPU to the request.  A per-role
   scheduler-delay probe (`SchedProbe`: a daemon thread timing short
   sleeps against their deadline) exports `gil_wait_ratio` — how late
   a runnable thread typically gets the interpreter back.

6. Flight recorder (ISSUE 15): `FlightRecorder`, a bounded per-role
   ring of COMPLETE records for the requests worth keeping — slower
   than the self-tracked p95 threshold (util/hedge.LatencyTracker,
   the same ring-quantile the hedge threshold and brownout median run
   on), errored, deadline-exceeded, or QoS/brownout-shed.  A record
   carries the trace span tree, per-stage wall+cpu, the deadline
   budget at ingress and its verdict, and the hedge/QoS/breaker/
   native-plane flight notes (`flight_note`).  Served at
   `GET /debug/slow` on every role; `weed shell cluster.slow` fans
   out, merges by trace id, and renders cross-role trees.  Head
   sampling almost never contains the slow request you care about —
   tail-sampling by construction always does.

Knobs:
  SEAWEEDFS_TPU_PROFILE_HZ       sampling rate; 0 (default) = off
  SEAWEEDFS_TPU_PROFILE_STACKS   distinct folded stacks kept (2048)
  SEAWEEDFS_TPU_STAGE_TIMERS     "0" disables stage tracks entirely
  SEAWEEDFS_TPU_CPU_SAMPLE       every Nth budget-less request pays
                                 the thread-CPU clock (16); deadline-
                                 carrying requests always do; 0 never
  SEAWEEDFS_TPU_FLIGHT_RECORDER  "0" disables the flight recorder
  SEAWEEDFS_TPU_SLOW_RING        records kept per process (64)
  SEAWEEDFS_TPU_SLOW_MIN_MS      slow-capture threshold floor (25)
  SEAWEEDFS_TPU_SLOW_CAPTURE_PER_S  threshold-capture rate cap (20)
  SEAWEEDFS_TPU_SCHED_PROBE      "0" disables the scheduler probe
  SEAWEEDFS_TPU_SCHED_PROBE_MS   probe sleep window (50)
"""

from __future__ import annotations

import contextvars
import itertools
import os
import sys
import threading
import time

# finer than stats.DEFAULT_BUCKETS: needle appends and index updates
# live in the 50us-5ms range the request-latency buckets can't resolve
STAGE_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5)

# the sampler refuses to spend more than this fraction of one core on
# itself: when a pass over every thread costs more than
# MAX_OVERHEAD * interval, the next sleep stretches to compensate
MAX_OVERHEAD = 0.10


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def default_hz() -> float:
    """SEAWEEDFS_TPU_PROFILE_HZ: sampling rate when the profiler is
    armed without an explicit rate; 0 (the default) keeps it off."""
    return max(0.0, _env_float("SEAWEEDFS_TPU_PROFILE_HZ", 0.0))


def max_stacks() -> int:
    """SEAWEEDFS_TPU_PROFILE_STACKS: bound on distinct folded stacks
    kept per process (overflow is counted, not stored)."""
    return max(64, _env_int("SEAWEEDFS_TPU_PROFILE_STACKS", 2048))


# runtime disarm lever (POST /debug/attribution): force-disarm in
# THIS process until restored — a live kill switch that needs no
# restart, and the bench's within-cluster A/B toggle (separate
# clusters can't resolve a ~1% cost under arm-to-arm boot noise).
# Scope "all" = the whole plane including the PR 7 wall-stage
# decomposition; scope "plane" = only the ISSUE 15 additions (CPU
# clocks, flight recorder) — the shape the bench's armed-vs-off
# acceptance compares, since wall tracks predate the plane and were
# paid for in every shipped number.
_attr_disarmed: "str | None" = None


def set_attribution_disarmed(disarmed: bool,
                             scope: str = "all") -> None:
    global _attr_disarmed
    _attr_disarmed = (scope if scope in ("all", "plane") else "all") \
        if disarmed else None


def attribution_disarmed() -> "str | None":
    return _attr_disarmed


def stage_timers_enabled() -> bool:
    """SEAWEEDFS_TPU_STAGE_TIMERS=0 turns the write-path stage
    decomposition off (the track() call becomes a no-op)."""
    if _attr_disarmed == "all":
        return False
    return os.environ.get("SEAWEEDFS_TPU_STAGE_TIMERS", "1") != "0"


# -- sampling profiler ----------------------------------------------------

class Sampler:
    """Thread-based statistical wall-clock profiler.

    Signal-based sampling (ITIMER_PROF) only interrupts the main
    thread; every role server does its real work on handler/pipeline
    threads, so a dedicated sampler thread walking
    `sys._current_frames()` is the only design that sees the hot
    paths.  Each pass folds every thread's stack root-first into
    `file.py:func;file.py:func;...` and counts it — the collapsed
    stack format any flamegraph renderer takes as-is."""

    MAX_DEPTH = 48

    def __init__(self):
        self._lock = threading.Lock()
        self._folded: dict[str, int] = {}
        self._label_cache: dict[object, str] = {}
        self._thread: "threading.Thread | None" = None
        self._stop = threading.Event()
        self.hz = 0.0
        self.samples = 0            # sampling passes completed
        self.stacks = 0             # thread stacks recorded
        self.dropped = 0            # stacks lost to the table cap
        self.self_seconds = 0.0     # time spent inside sampling passes
        self.started_wall = 0.0
        self._started_mono = 0.0
        self._stopped_elapsed = 0.0

    # -- control ---------------------------------------------------------

    def start(self, hz: "float | None" = None) -> bool:
        """Arm the sampler at `hz` (default: the env knob, else 100).
        Returns False when already running (the running profile is
        left untouched — two operators arming cluster-wide must not
        reset each other's windows)."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return False
            rate = hz if hz and hz > 0 else (default_hz() or 100.0)
            self.hz = min(float(rate), 1000.0)
            self._folded.clear()
            self.samples = self.stacks = self.dropped = 0
            self.self_seconds = 0.0
            self.started_wall = time.time()
            self._started_mono = time.monotonic()
            self._stopped_elapsed = 0.0
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="weed-profiler", daemon=True)
            self._thread.start()
            return True

    def stop(self) -> None:
        with self._lock:
            t = self._thread
            if t is None:
                return
            self._stop.set()
        t.join(timeout=5.0)
        with self._lock:
            if self._thread is t:
                self._stopped_elapsed = \
                    time.monotonic() - self._started_mono
                self._thread = None

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def reset(self) -> None:
        # _label_cache deliberately not cleared here: it is written
        # lock-free by the sampler thread (its only writer — start()
        # joins the old thread before spawning a new one) and bounded
        # by MAX_LABELS in _frame_label, so touching it from a
        # handler thread would be the race, not the hygiene
        with self._lock:
            self._folded.clear()
            self.samples = self.stacks = self.dropped = 0
            self.self_seconds = 0.0

    # -- sampling loop ---------------------------------------------------

    # code objects are cache keys (strong refs): bound the cache so a
    # long-armed process that mints code dynamically (jax jit) cannot
    # pin an unbounded set of them
    MAX_LABELS = 32768

    def _frame_label(self, code) -> str:
        label = self._label_cache.get(code)
        if label is None:
            if len(self._label_cache) >= self.MAX_LABELS:
                self._label_cache.clear()
            label = (f"{code.co_filename.rsplit('/', 1)[-1]}"
                     f":{code.co_name}")
            self._label_cache[code] = label
        return label

    def _run(self) -> None:
        me = threading.get_ident()
        interval = 1.0 / self.hz
        cap = max_stacks()
        while not self._stop.is_set():
            t0 = time.perf_counter()
            try:
                frames = sys._current_frames()
            except RuntimeError:   # pragma: no cover — interp teardown
                break
            new_folded = []
            for tid, frame in frames.items():
                if tid == me:
                    continue
                parts = []
                f = frame
                while f is not None and len(parts) < self.MAX_DEPTH:
                    parts.append(self._frame_label(f.f_code))
                    f = f.f_back
                new_folded.append(";".join(reversed(parts)))
            with self._lock:
                for stack in new_folded:
                    n = self._folded.get(stack)
                    if n is not None:
                        self._folded[stack] = n + 1
                        self.stacks += 1
                    elif len(self._folded) < cap:
                        self._folded[stack] = 1
                        self.stacks += 1
                    else:
                        self.dropped += 1
                self.samples += 1
                cost = time.perf_counter() - t0
                self.self_seconds += cost
            # overhead bound: never let sampling cost exceed
            # MAX_OVERHEAD of one core — a pass that took longer than
            # its budget buys proportionally more sleep
            self._stop.wait(max(interval, cost / MAX_OVERHEAD))

    # -- output ----------------------------------------------------------

    def snapshot(self, top: int = 0) -> dict:
        """JSON-able state + folded table (all stacks, or the `top` N
        by count)."""
        with self._lock:
            elapsed = (time.monotonic() - self._started_mono) \
                if self.running else self._stopped_elapsed
            folded = dict(self._folded)
            doc = {
                "running": self.running,
                "hz": self.hz,
                "samples": self.samples,
                "stacks": self.stacks,
                "droppedStacks": self.dropped,
                "startedAt": self.started_wall,
                "elapsedSeconds": round(elapsed, 3),
                "selfSeconds": round(self.self_seconds, 4),
                "overhead": round(self.self_seconds / elapsed, 4)
                if elapsed > 0 else 0.0,
            }
        if top and top > 0:
            folded = dict(sorted(folded.items(),
                                 key=lambda kv: -kv[1])[:top])
        doc["folded"] = folded
        return doc

    def collapsed(self) -> str:
        """`stack count` lines, most-sampled first — pipe straight
        into flamegraph.pl."""
        with self._lock:
            items = sorted(self._folded.items(), key=lambda kv: -kv[1])
        return "\n".join(f"{stack} {n}" for stack, n in items) + \
            ("\n" if items else "")


_sampler = Sampler()
_autostart_done = False


def sampler() -> Sampler:
    return _sampler


def maybe_autostart() -> None:
    """Boot-time arming: when SEAWEEDFS_TPU_PROFILE_HZ is set > 0 the
    process profiles from startup (once per process — every role's
    install_debug_routes calls this)."""
    global _autostart_done
    if _autostart_done:
        return
    _autostart_done = True
    if default_hz() > 0:
        _sampler.start(default_hz())


def merge_folded(tables: "list[dict]") -> "dict[str, int]":
    """Sum folded-stack tables (cluster.profile merges every node's
    snapshot into one cluster-wide flame view)."""
    out: dict[str, int] = {}
    for t in tables:
        for stack, n in (t or {}).items():
            try:
                out[stack] = out.get(stack, 0) + int(n)
            except (TypeError, ValueError):
                continue
    return out


# -- write-path stage decomposition ---------------------------------------

_track_var: contextvars.ContextVar["StageTrack | None"] = \
    contextvars.ContextVar("weed_stage_track", default=None)

# the finished track's summary, left for the server front's flight
# recorder (finish() runs inside the handler, the capture in the
# front's finally — same thread, so a plain contextvar bridges them)
_last_summary_var: contextvars.ContextVar["dict | None"] = \
    contextvars.ContextVar("weed_last_track_summary", default=None)

# per-request flight notes for requests that carry no stage track
# (reads): armed by the fronts at ingress, read back at capture
_notes_var: contextvars.ContextVar["dict | None"] = \
    contextvars.ContextVar("weed_flight_notes", default=None)


def cpu_sample_every() -> int:
    """SEAWEEDFS_TPU_CPU_SAMPLE: every Nth budget-less request pays
    the thread-CPU clock (default 16); deadline-carrying requests are
    ALWAYS attributed.  On sandboxed kernels CLOCK_THREAD_CPUTIME_ID
    is a trapped syscall (~5us/call measured here, not vDSO), and a
    stage-tracked write makes ~12 of them — unsampled, that alone is
    ~8% of a GIL-saturated role.  Sampling keeps every histogram
    MEAN exact (cpu/req, per-stage cpu) while the requests the
    deadline/hedge planes act on — and the flight recorder explains —
    keep their exact per-request split.  0 disables attribution
    entirely (the bench twin's knob)."""
    if _attr_disarmed:
        return 0
    return _env_int("SEAWEEDFS_TPU_CPU_SAMPLE", 16)


# SEPARATE counters for the two draw sites: a request advances the
# front counter once and (when tracked) the track counter once — one
# shared counter would advance by 2 per request and `(2r+1) % k` can
# never hit 0 for even k, i.e. tracks would NEVER draw the sample
_front_tick = itertools.count()
_track_tick = itertools.count()


def cpu_attr_tick() -> bool:
    """The budget-less sampling decision alone (callers that already
    know the deadline state, i.e. the server fronts)."""
    k = cpu_sample_every()
    if k <= 0:
        return False
    return next(_front_tick) % k == 0


def cpu_attr_front(deadline_armed: bool) -> bool:
    """The server fronts' sampling decision.  The k<=0 kill switch
    (SEAWEEDFS_TPU_CPU_SAMPLE=0 / the /debug/attribution disarm
    lever) gates EVERYTHING, deadline-carrying requests included — a
    deadline-default cluster must not pay the trapped clock syscall
    per request under a knob documented as '0 = never'."""
    k = cpu_sample_every()
    if k <= 0:
        return False
    if deadline_armed:
        return True
    return next(_front_tick) % k == 0


def cpu_attr_now() -> bool:
    """Should THIS request pay the thread-CPU clock?  Deadline-
    carrying requests always do; budget-less ones every Nth."""
    k = cpu_sample_every()
    if k <= 0:
        return False
    from .util import deadline as _dl
    if _dl.get() is not None:
        return True
    return next(_track_tick) % k == 0


def take_last_summary() -> "dict | None":
    """The most recent StageTrack summary finished on this context,
    cleared on read (reused handler threads must not attribute the
    previous request's decomposition to this one)."""
    s = _last_summary_var.get()
    if s is not None:
        _last_summary_var.set(None)
    return s


def arm_flight_notes() -> None:
    """Front-ingress arming: give this request a notes dict so
    flight_note() calls down the handler chain have somewhere to land
    even without a stage track."""
    _notes_var.set({})


def take_flight_notes() -> "dict | None":
    d = _notes_var.get()
    if d is not None:
        _notes_var.set(None)
    return d or None


def flight_note(key: str, value) -> None:
    """Attach one fact about the CURRENT request for the flight
    recorder (hedge issued/won, native-plane handoff, QoS verdict,
    degraded EC read...).  Prefers the active stage track (which
    follows use_track() onto pool threads); falls back to the
    front-armed notes dict; a no-op — two contextvar reads — when
    neither is armed (un-instrumented callers, background threads)."""
    trk = _track_var.get()
    if trk is not None:
        trk.note(key, value)
        return
    d = _notes_var.get()
    if d is not None:
        d[key] = value


class StageTrack:
    """Per-request stage accumulator.  Thread-safe: the filer funnel
    records assign/upload stages from limiter pool threads into the
    handler thread's track (see use_track).

    Each stage carries wall AND thread-CPU seconds (_StageCtx samples
    `time.thread_time()` at both boundaries, on whichever thread the
    stage actually ran): `finish()` emits `<name>_stage_cpu_seconds`
    beside the wall histograms, so `wall − cpu` per stage exposes the
    GIL/lock/syscall wait directly.  The track total's CPU is the
    OWNER thread's thread-time delta plus the CPU the stages burned on
    foreign (pool) threads — the request's whole CPU bill, not just
    the instrumented windows."""

    __slots__ = ("name", "role", "metrics", "stages", "notes", "_lock",
                 "_t0", "_owner", "_cpu0", "_cpu_on", "trace_ctx")

    def __init__(self, name: str, role: str = "", metrics=None):
        self.name = name
        self.role = role
        self.metrics = metrics
        # stage -> [wall seconds, calls, first-call wall time,
        #           cpu seconds, foreign-thread cpu seconds]
        self.stages: dict[str, list] = {}
        self.notes: "dict | None" = None
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._owner = threading.get_ident()
        # sampled CPU attribution (cpu_attr_now): the thread-CPU
        # clock is a trapped syscall on sandboxed kernels, so only
        # deadline-carrying and every-Nth budget-less tracks pay it;
        # wall is always measured
        self._cpu_on = cpu_attr_now()
        self._cpu0 = time.thread_time() if self._cpu_on else 0.0
        from . import tracing
        self.trace_ctx = tracing.current_ids()

    def add(self, stage: str, seconds: float,
            cpu_seconds: float = 0.0) -> None:
        foreign = threading.get_ident() != self._owner
        with self._lock:
            rec = self.stages.get(stage)
            if rec is None:
                # span-start RECORD, deliberately wall (trace spans
                # carry wall starts); the duration itself came off
                # perf_counter in _StageCtx
                self.stages[stage] = [
                    seconds, 1, time.time() - seconds,  # noqa: SWFS011
                    cpu_seconds, cpu_seconds if foreign else 0.0]
            else:
                rec[0] += seconds
                rec[1] += 1
                rec[3] += cpu_seconds
                if foreign:
                    rec[4] += cpu_seconds

    def note(self, key: str, value) -> None:
        """Attach one flight-recorder note to this request (hedge
        verdicts, native-plane handoffs, QoS outcomes — see
        flight_note)."""
        with self._lock:
            if self.notes is None:
                self.notes = {}
            self.notes[key] = value

    def finish(self) -> float:
        """Observe one histogram cell per stage (plus stage="total")
        for wall AND cpu, emit sibling stage spans under the span that
        was active at track start, and stash the finished summary for
        the front's flight recorder (take_last_summary).  Returns the
        track's total seconds."""
        total = time.perf_counter() - self._t0
        # the owner thread's CPU covers everything it ran between
        # track start and finish (instrumented or not); stages that
        # ran on OTHER threads contribute their own thread-time on top
        cpu_on = self._cpu_on
        own_cpu = (time.thread_time() - self._cpu0) \
            if cpu_on and threading.get_ident() == self._owner else 0.0
        with self._lock:
            stages = {k: list(v) for k, v in self.stages.items()}
            notes = dict(self.notes) if self.notes else None
        total_cpu = own_cpu + sum(rec[4] for rec in stages.values())
        hist = f"{self.name}_stage_seconds"
        cpu_hist = f"{self.name}_stage_cpu_seconds"
        if self.metrics is not None:
            # pre-resolved observers (stats.Metrics.observer, ROADMAP
            # 1d), memoized on the registry: StageTracks are
            # per-request, so the memo must outlive them; track names
            # are code-site constants ("write"), never request-
            # derived, so cardinality stays bounded by the set of
            # track() call sites x their stage names
            memo = self.metrics.obs_memo
            for stage, rec in list(stages.items()) + [("total", None)]:
                if rec is None:
                    secs, cpu = total, total_cpu
                else:
                    secs, cpu = rec[0], rec[3]
                obs = memo.get((hist, stage))
                if obs is None:
                    obs = memo[(hist, stage)] = self.metrics.observer(
                        # noqa: SWFS017 — code-site constant, above
                        hist, buckets=STAGE_BUCKETS,
                        help_text=f"per-request {self.name}-path "
                                  f"stage decomposition", stage=stage)
                obs(secs)
                if cpu_on:
                    cobs = memo.get((cpu_hist, stage))
                    if cobs is None:
                        cobs = memo[(cpu_hist, stage)] = \
                            self.metrics.observer(
                                # noqa: SWFS017 — as above
                                cpu_hist, buckets=STAGE_BUCKETS,
                                help_text=f"per-request {self.name}-"
                                          f"path stage CPU (thread_"
                                          f"time, sampled — see SEA"
                                          f"WEEDFS_TPU_CPU_SAMPLE); "
                                          f"wall minus this is GIL/"
                                          f"lock/syscall wait",
                                stage=stage)
                    cobs(cpu)
        if self.trace_ctx and stages:
            from . import tracing
            role = self.role or self.trace_ctx[2]
            specs = []
            for stage, rec in stages.items():
                secs, calls, wall0, cpu = rec[0], rec[1], rec[2], rec[3]
                attrs = {"cpuMs": round(cpu * 1e3, 3)} if cpu_on \
                    else {}
                if calls > 1:
                    attrs["calls"] = calls
                specs.append({
                    "name": f"{self.name}.{stage}",
                    "start": wall0, "duration": secs, "role": role,
                    "parent": self.trace_ctx[1],
                    "trace_id": self.trace_ctx[0], "attrs": attrs})
            # one batch: the tracer's knob env-reads are per CALL,
            # not per span (they were 3 env lookups x N stages here)
            tracing.emit_span_batch(specs)
        # leave the finished decomposition where the server front can
        # pick it up for a flight-recorder capture (same thread for
        # both fronts: threaded dispatch / the asyncio pool worker).
        # An unsampled track reports wall only — cpuMs keys are
        # ABSENT, never zero, so a render can't mistake "not
        # measured" for "no CPU"
        summary = {
            "totalMs": round(total * 1e3, 3),
            "cpuSampled": cpu_on,
            "stages": {
                s: dict({"wallMs": round(rec[0] * 1e3, 3),
                         "calls": rec[1]},
                        **({"cpuMs": round(rec[3] * 1e3, 3)}
                           if cpu_on else {}))
                for s, rec in stages.items()},
        }
        if cpu_on:
            summary["cpuMs"] = round(total_cpu * 1e3, 3)
        if notes:
            summary["notes"] = notes
        _last_summary_var.set(summary)
        return total


class _TrackCtx:
    """`with profiling.track(...)`: create + activate + finish."""

    __slots__ = ("_trk", "_token")

    def __init__(self, name: str, role: str, metrics):
        self._trk = StageTrack(name, role=role, metrics=metrics) \
            if stage_timers_enabled() else None
        self._token = None

    def __enter__(self) -> "StageTrack | None":
        if self._trk is not None:
            self._token = _track_var.set(self._trk)
        return self._trk

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._trk is None:
            return
        try:
            _track_var.reset(self._token)
        except ValueError:      # pragma: no cover — cross-context exit
            pass
        self._trk.finish()


def track(name: str, role: str = "", metrics=None) -> _TrackCtx:
    """Open a stage track for the current request and make it the
    context's active track; finished (histograms observed, spans
    emitted) on exit.  Yields None when stage timers are disabled."""
    return _TrackCtx(name, role, metrics)


def current_track() -> "StageTrack | None":
    return _track_var.get()


class _UseTrack:
    """Re-bind an existing track on ANOTHER thread (contextvars do not
    follow threading.Thread): the filer captures its track before
    handing upload work to the limiter pool, and each pool task wraps
    itself in use_track so operation.assign/upload's stage() calls
    find it."""

    __slots__ = ("_trk", "_token")

    def __init__(self, trk: "StageTrack | None"):
        self._trk = trk
        self._token = None

    def __enter__(self):
        if self._trk is not None:
            self._token = _track_var.set(self._trk)
        return self._trk

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._token is not None:
            try:
                _track_var.reset(self._token)
            except ValueError:  # pragma: no cover
                pass


def use_track(trk: "StageTrack | None") -> _UseTrack:
    return _UseTrack(trk)


class _StageCtx:
    __slots__ = ("_trk", "_name", "_t0", "_c0")

    def __init__(self, trk: "StageTrack", name: str):
        self._trk = trk
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        # per-THREAD cpu clock: sampled on whichever thread runs the
        # stage, so the use_track() re-bind charges pool-thread CPU to
        # the request exactly — but only when the track drew the CPU
        # attribution sample (the clock is a trapped syscall on
        # sandboxed kernels; see cpu_sample_every)
        self._c0 = time.thread_time() if self._trk._cpu_on else 0.0
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self._trk.add(self._name, time.perf_counter() - self._t0,
                      (time.thread_time() - self._c0)
                      if self._trk._cpu_on else 0.0)


class _NoopStage:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        pass


_NOOP = _NoopStage()


def stage(name: str):
    """Time one stage of the active track; a shared no-op (one
    contextvar read) when no track is active — safe on any hot path."""
    trk = _track_var.get()
    if trk is None:
        return _NOOP
    return _StageCtx(trk, name)


# -- flight recorder (tail-sampled slow-request capture) ------------------

def recorder_enabled() -> bool:
    """SEAWEEDFS_TPU_FLIGHT_RECORDER=0 disarms capture entirely (the
    fronts then skip note arming and the per-request observe); the
    /debug/attribution runtime lever disarms it the same way."""
    if _attr_disarmed:
        return False
    return os.environ.get("SEAWEEDFS_TPU_FLIGHT_RECORDER", "1") \
        not in ("0", "false")


def ring_size() -> int:
    """SEAWEEDFS_TPU_SLOW_RING: flight records kept per process."""
    return max(8, _env_int("SEAWEEDFS_TPU_SLOW_RING", 64))


def slow_floor_s() -> float:
    """SEAWEEDFS_TPU_SLOW_MIN_MS: the slow-capture threshold never
    drops below this — a uniformly-fast role must not spend captures
    on its own p95 noise."""
    return max(0.0, _env_float("SEAWEEDFS_TPU_SLOW_MIN_MS", 25.0)) / 1e3


def capture_rate() -> float:
    """SEAWEEDFS_TPU_SLOW_CAPTURE_PER_S: ceiling on threshold-only
    captures (error/deadline/shed verdicts are never rate-limited —
    they are rare and precious).  Each capture walks the trace ring
    for its span tree, so an unbounded rate would tax exactly the
    overloaded state the recorder exists to explain."""
    return max(1.0, _env_float("SEAWEEDFS_TPU_SLOW_CAPTURE_PER_S",
                               20.0))


class FlightRecorder:
    """Bounded ring of complete slow/error-request records.

    Always-on and self-limiting: every request's wall feeds a
    LatencyTracker (util/hedge — the same ring-quantile the hedge
    threshold and brownout median run on) and only requests beyond
    max(p95, SLOW_MIN_MS) — or with a non-ok verdict — are captured,
    so by construction ~1-in-20 requests pays the capture cost and the
    ring always holds the tail that head-sampled tracing misses."""

    def __init__(self, size: "int | None" = None):
        from .util.hedge import LatencyTracker
        import collections
        self._lock = threading.Lock()
        self._ring = collections.deque(
            maxlen=size if size else ring_size())
        self._tracker = LatencyTracker(size=128, min_samples=32)
        self._notes_since_quantile = 0
        self._threshold: "float | None" = None
        self._rate_window_start = 0.0
        self._rate_window_count = 0
        # injectable for tests: a real-time 1 s window can roll over
        # mid-assertion on a degraded box; pinning the clock makes the
        # rate-cap behavior deterministic
        self._now = time.monotonic
        self.captured = 0
        self.dropped_rate_limited = 0

    def threshold(self) -> "float | None":
        """Current slow-capture threshold in seconds; None while the
        tracker is still warming up (no threshold captures yet —
        error/deadline/shed still capture)."""
        with self._lock:
            return self._threshold

    def _note_wall(self, wall_s: float) -> None:
        self._tracker.note(wall_s)
        with self._lock:
            self._notes_since_quantile += 1
            if self._threshold is None or \
                    self._notes_since_quantile >= 32:
                # the quantile sorts 128 floats — recompute every 32
                # requests, not every request
                self._notes_since_quantile = 0
                p95 = self._tracker.quantile(0.95)
                self._threshold = None if p95 is None else \
                    max(p95, slow_floor_s())

    def note_walls(self, walls: "list[float]") -> None:
        """Bulk _note_wall for the native-plane record drain: train
        the slow threshold on a whole batch with one tracker lock
        round and at most one quantile refresh."""
        if not walls:
            return
        self._tracker.note_many(walls)
        with self._lock:
            self._notes_since_quantile += len(walls)
            if self._threshold is None or \
                    self._notes_since_quantile >= 32:
                self._notes_since_quantile = 0
                p95 = self._tracker.quantile(0.95)
                self._threshold = None if p95 is None else \
                    max(p95, slow_floor_s())

    def _rate_ok(self) -> bool:
        """Token check for threshold-only captures (caller holds no
        lock): a 1-second window capped at capture_rate()."""
        now = self._now()
        with self._lock:
            if now - self._rate_window_start >= 1.0:
                self._rate_window_start = now
                self._rate_window_count = 0
            if self._rate_window_count >= capture_rate():
                self.dropped_rate_limited += 1
                return False
            self._rate_window_count += 1
            return True

    def observe(self, role: str, method: str, path: str, status: int,
                wall_s: float, cpu_s: "float | None" = None,
                verdict: str = "ok", trace_id: str = "",
                deadline: "dict | None" = None,
                stages: "dict | None" = None,
                notes: "dict | None" = None) -> "dict | None":
        """Feed one finished request; returns the captured record (or
        None).  `stages` is a StageTrack summary (take_last_summary),
        `deadline` the {budgetMs, remainingMs} doc from the front,
        `notes` the flight_note dict.  `cpu_s` is None when the
        request didn't draw the CPU-attribution sample (see
        cpu_sample_every) — the record then reports wall only, with
        the cpuMs/waitMs keys ABSENT rather than zero."""
        self._note_wall(wall_s)
        slow = self._threshold is not None and wall_s >= self._threshold
        if verdict == "ok" and status >= 500:
            verdict = "error"
        if verdict == "ok":
            if not slow:
                return None
            if not self._rate_ok():
                return None
            verdict = "slow"
        rec = {
            "ts": time.time(),
            "role": role,
            "method": method,
            "path": path,
            "status": status,
            "verdict": verdict,
            "wallMs": round(wall_s * 1e3, 3),
            "traceId": trace_id,
        }
        if cpu_s is not None:
            rec["cpuMs"] = round(cpu_s * 1e3, 3)
            rec["waitMs"] = round(max(wall_s - cpu_s, 0.0) * 1e3, 3)
        if deadline:
            rec["deadline"] = deadline
        if stages:
            rec["stages"] = stages
        if notes:
            rec["notes"] = notes
        if trace_id:
            # the span tree AS OF capture time: the server span and
            # the track's stage spans are already in the ring (the
            # fronts capture after sp.finish()); downstream hops'
            # spans live in THEIR processes' rings and cluster.slow
            # merges them by trace id
            from . import tracing
            spans = tracing.spans_for(trace_id)
            if spans:
                rec["spans"] = spans
        with self._lock:
            self._ring.append(rec)
            self.captured += 1
        _process_metrics().counter_add(
            "flight_records_total", 1.0,
            help_text="requests captured by the flight recorder",
            verdict=verdict)
        return rec

    def snapshot(self) -> dict:
        with self._lock:
            thr = self._threshold
            return {
                "records": [dict(r) for r in self._ring],
                "captured": self.captured,
                "droppedRateLimited": self.dropped_rate_limited,
                "thresholdMs": round(thr * 1e3, 3)
                if thr is not None else None,
                "ringSize": self._ring.maxlen,
            }

    def reset(self) -> None:
        """Tests only: forget records and latency history."""
        with self._lock:
            self._ring.clear()
            self.captured = 0
            self.dropped_rate_limited = 0
            self._threshold = None
            self._notes_since_quantile = 0
            self._rate_window_count = 0
        self._tracker.reset()


_recorder: "FlightRecorder | None" = None
_recorder_lock = threading.Lock()


def flight_recorder() -> FlightRecorder:
    global _recorder
    r = _recorder
    if r is None:
        with _recorder_lock:
            r = _recorder
            if r is None:
                r = _recorder = FlightRecorder()
    return r


# -- native-plane flight deck (ISSUE 18) ----------------------------------
#
# The C++ planes record every request into a lock-free ring (PlaneRec
# in the .cc files / native.PlaneRecord on this side); the drainer
# threads in server/meta_plane_native.py and server/volume_server.py
# pull the rings on a tick + at /debug/slow scrape time and feed each
# record through a PlaneRecordSink — LatencyTracker training, stage
# tail histograms, synthesized trace spans, FlightRecorder captures.
# Python stays off the request path: the plane never waits on the
# drain, and a dead drainer only costs observability.

_plane_drain_disarmed = False


def set_plane_drain_disarmed(disarmed: bool) -> None:
    """Runtime kill switch (POST /debug/attribution scope "drain",
    and the bench's within-cluster drain-on/off A/B lever)."""
    global _plane_drain_disarmed
    _plane_drain_disarmed = bool(disarmed)


def plane_drain_enabled() -> bool:
    """SEAWEEDFS_TPU_PLANE_DRAIN=0 disarms the plane-record drain
    entirely (records still accumulate C-side and fall off the ring);
    the runtime lever disarms it the same way."""
    if _plane_drain_disarmed:
        return False
    return os.environ.get("SEAWEEDFS_TPU_PLANE_DRAIN", "1") \
        not in ("0", "false")


def plane_drain_interval_s() -> float:
    """SEAWEEDFS_TPU_PLANE_DRAIN_MS: drainer tick (how stale the
    Python view of the plane rings may go between scrapes)."""
    return max(10.0,
               _env_float("SEAWEEDFS_TPU_PLANE_DRAIN_MS", 200.0)) / 1e3


# scrape-time hooks: /debug/slow runs these before snapshotting so a
# just-finished plane request is drained into the recorder the scrape
# is about to read, instead of waiting out the drainer tick
_scrape_hooks: "list" = []
_scrape_hooks_lock = threading.Lock()


def register_scrape_hook(fn) -> None:
    with _scrape_hooks_lock:
        if fn not in _scrape_hooks:
            _scrape_hooks.append(fn)


def unregister_scrape_hook(fn) -> None:
    with _scrape_hooks_lock:
        try:
            _scrape_hooks.remove(fn)
        except ValueError:
            pass


def run_scrape_hooks() -> None:
    with _scrape_hooks_lock:
        hooks = list(_scrape_hooks)
    for fn in hooks:
        try:
            fn()
        except Exception:  # noqa: SWFS004 — a hook must never 500 a
            pass           # scrape


_PLANE_STAGE_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025,
                        0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                        1.0, 2.5)


class PlaneRecordSink:
    """Fan one plane's drained flight records into the Python
    observability planes.

    Per record: the wall (sum of stage ns) trains `tracker` (the
    hedge/brownout/capture LatencyTracker for the role) and the
    per-stage tail histograms; every record feeds
    FlightRecorder.observe so plane traffic trains the slow
    threshold; a span tree is synthesized (tracing.emit_plane_hop)
    only for records that can stitch or will be captured — client-rid
    records, errors, and records at/over the current slow threshold —
    so the lean all-minted-rid bench drain stays allocation-cheap."""

    def __init__(self, role: str, plane: str, method: str,
                 stage_names: "tuple[str, ...]",
                 fallback_names: "tuple[str, ...]",
                 tracker=None, metrics=None):
        from . import native as _native
        self.role = role
        self.plane = plane
        self.method = method
        self.stage_names = stage_names
        self.fallback_names = fallback_names
        self.tracker = tracker
        self.metrics = metrics if metrics is not None \
            else _process_metrics()
        self._client_rid_flag = _native.PLANE_RECORD_CLIENT_RID
        self._minted_rid_flag = _native.PLANE_RECORD_MINTED_UPSTREAM
        self._stage_obs = [
            self.metrics.observer(
                "plane_stage_seconds", _PLANE_STAGE_BUCKETS,
                help_text="native-plane per-request stage latency "
                          "(drained from the C++ flight ring)",
                plane=plane, stage=s)
            for s in stage_names]
        self._stage_batch_obs = [
            self.metrics.batch_observer(
                "plane_stage_seconds", _PLANE_STAGE_BUCKETS,
                plane=plane, stage=s)
            for s in stage_names]
        self.records = 0
        self.captures = 0

    def _observe_one(self, fr, rid: str, start_s: float,
                     stage_s: "list[float]", wall: float, status: int,
                     fb: int, flags: int, nbytes: int,
                     deadline_ms: int) -> None:
        """The interesting-record path: span synthesis + the
        FlightRecorder capture decision.  Only stitchable (client
        rid), error, and at/over-threshold records reach here — the
        lean minted-rid bulk must never pay these allocations."""
        fb_name = self.fallback_names[fb] \
            if 0 <= fb < len(self.fallback_names) else "?"
        error = status >= 500
        thr = fr.threshold()
        # a forwarded plane-minted rid is not a client trace: it only
        # earns spans when the record is independently interesting
        # (and then the rid still stitches the cross-role tree)
        stitchable = bool(flags & self._client_rid_flag) and \
            not (flags & self._minted_rid_flag)
        if stitchable or error or (thr is not None and wall >= thr):
            from . import tracing
            tracing.emit_plane_hop(
                f"{self.method} [{self.plane}-plane]", self.role,
                rid, start_s, wall,
                list(zip(self.stage_names, stage_s)),
                attrs={"status": status, "bytes": nbytes,
                       "fallback": fb_name},
                error=error)
        notes = {"plane": self.plane, "bytes": nbytes}
        if fb_name != "none":
            notes["fallback"] = fb_name
        deadline = None
        if deadline_ms >= 0:
            deadline = {"remainingMs": int(deadline_ms)}
        # StageTrack-summary shape: _render_slow_hop reads
        # rec["stages"]["stages"]
        stages = {"track": f"{self.plane}_plane",
                  "wallMs": round(wall * 1e3, 3),
                  "stages": {s: {"wallMs": round(v * 1e3, 3)}
                             for s, v in zip(self.stage_names,
                                             stage_s)
                             if v > 0.0}}
        if fr.observe(self.role, self.method,
                      f"[{self.plane}-plane]", status, wall,
                      verdict="error" if error else "ok",
                      trace_id=rid, deadline=deadline,
                      stages=stages, notes=notes) is not None:
            self.captures += 1

    def feed(self, records) -> int:
        """Consume one drained batch (native.PlaneRecord instances);
        returns how many were fed."""
        n = 0
        fr = flight_recorder()
        rec_on = recorder_enabled()
        thr = fr.threshold()
        for rec in records:
            n += 1
            stage_s = [ns / 1e9 for ns in rec.stage_ns]
            wall = sum(stage_s)
            for obs, s in zip(self._stage_obs, stage_s):
                if s > 0.0:
                    obs(s)
            if self.tracker is not None:
                self.tracker.note(wall)
            if not rec_on:
                continue
            status = int(rec.status)
            flags = int(rec.flags)
            stitch = (flags & self._client_rid_flag) and \
                not (flags & self._minted_rid_flag)
            if status < 500 and not stitch and \
                    (thr is None or wall < thr):
                # the lean bulk: train the slow threshold, skip the
                # rid decode and record-dict allocations entirely
                fr._note_wall(wall)
                continue
            self._observe_one(
                fr, rec.rid.decode("ascii", "replace"),
                rec.start_unix_ns / 1e9, stage_s, wall, status,
                int(rec.fallback), int(rec.flags), int(rec.bytes),
                int(rec.deadline_ms))
        self.records += n
        if n:
            self.metrics.counter_add(
                "plane_records_total", float(n),
                help_text="flight records drained from the native "
                          "plane rings", plane=self.plane)
        return n

    def feed_buffer(self, buf, n: int) -> int:
        """Vectorized drain hot path over the reused ctypes batch
        buffer (native.drain_plane_records hands it straight here).
        Per-record Python fan-out measured ~30% of this box's one
        core at a few thousand plane req/s; the numpy path pays one
        array view, one bincount per stage histogram, and one lock
        round per shared structure, touching Python objects only for
        the rare stitchable/error/slow records."""
        if n <= 0:
            return 0
        try:
            import numpy as np
        except ImportError:  # pragma: no cover — numpy ships here
            return self.feed(buf[i] for i in range(n))
        from . import native as _native
        arr = np.frombuffer(buf, dtype=_native.plane_record_dtype(),
                            count=n)
        stage_s = arr["stage_ns"] / 1e9      # (n, nstages) float64
        wall = stage_s.sum(axis=1)
        for i, obs_b in enumerate(self._stage_batch_obs):
            col = stage_s[:, i]
            obs_b(col[col > 0.0])
        if self.tracker is not None:
            self.tracker.note_many(wall.tolist())
        self.records += n
        self.metrics.counter_add(
            "plane_records_total", float(n),
            help_text="flight records drained from the native "
                      "plane rings", plane=self.plane)
        fr = flight_recorder()
        if not recorder_enabled():
            return n
        thr = fr.threshold()
        fl = arr["flags"]
        stitch = ((fl & self._client_rid_flag) != 0) & \
            ((fl & self._minted_rid_flag) == 0)
        mask = (arr["status"] >= 500) | stitch
        if thr is not None:
            mask = mask | (wall >= thr)
        fr.note_walls(wall[~mask].tolist())
        for i in np.nonzero(mask)[0].tolist():
            self._observe_one(
                fr,
                bytes(arr["rid"][i]).split(b"\0", 1)[0].decode(
                    "ascii", "replace"),
                float(arr["start_unix_ns"][i]) / 1e9,
                [float(x) for x in stage_s[i]], float(wall[i]),
                int(arr["status"][i]), int(arr["fallback"][i]),
                int(arr["flags"][i]), int(arr["bytes"][i]),
                int(arr["deadline_ms"][i]))
        return n

    def note_dropped(self, total_dropped: int, last_seen: int) -> int:
        """Publish the ring's monotonic dropped count as a counter
        delta; returns the new last-seen value for the caller to
        carry."""
        delta = total_dropped - last_seen
        if delta > 0:
            self.metrics.counter_add(
                "plane_ring_dropped_total", float(delta),
                help_text="flight records overwritten in the native "
                          "ring before the drainer reached them",
                plane=self.plane)
        return max(total_dropped, last_seen)


class PlaneRecordDrainer:
    """Consumer side of one plane's flight ring: a tick thread
    (SEAWEEDFS_TPU_PLANE_DRAIN_MS) plus on-demand pulls at
    /debug/slow scrape time, serialized by a lock — the C ring is
    single-consumer, so every pull path must go through drain_now.

    `drain_fn(sink) -> int` runs one native drain pass (the wrapper
    method, which no-ops after the plane stopped); `dropped_fn()`
    reads the ring's monotonic drop counter."""

    def __init__(self, sink: PlaneRecordSink, drain_fn, dropped_fn):
        self.sink = sink
        self._drain_fn = drain_fn
        self._dropped_fn = dropped_fn
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._dropped_seen = 0
        self._thread: "threading.Thread | None" = None

    def start(self) -> "PlaneRecordDrainer":
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"weed-plane-drain-{self.sink.plane}")
        self._thread.start()
        register_scrape_hook(self.drain_now)
        return self

    def drain_now(self) -> int:
        """One drain pass; safe from any thread, any time (including
        after stop — the wrapper's drain_fn checks its handle)."""
        if not plane_drain_enabled():
            return 0
        with self._lock:
            n = self._drain_fn(self.sink)
            self._dropped_seen = self.sink.note_dropped(
                int(self._dropped_fn()), self._dropped_seen)
            return n

    def _run(self) -> None:
        while not self._stop.wait(plane_drain_interval_s()):
            try:
                self.drain_now()
            except Exception:  # noqa: SWFS004 — a drain failure
                pass           # costs observability, never the drainer

    def stop(self) -> None:
        """Join the tick thread BEFORE the native server stops: the
        drain callable dereferences the plane handle."""
        unregister_scrape_hook(self.drain_now)
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        try:
            self.drain_now()   # final pass: nothing left un-drained
        except Exception:      # noqa: SWFS004
            pass


# -- scheduler-delay probe -------------------------------------------------

class SchedProbe:
    """Daemon thread timing short Event.wait sleeps against their
    deadline: the overshoot is how long a runnable thread waited for
    the scheduler AND the GIL after its wakeup — the direct signal for
    'this role is GIL-convoyed', independent of any request being
    instrumented.  Exported as the `gil_wait_ratio` gauge (EWMA of
    overshoot/interval; 0 idle .. ~1 means wakeups routinely wait a
    whole extra interval)."""

    def __init__(self, interval_s: "float | None" = None):
        self.interval = interval_s if interval_s else max(
            0.005, _env_float("SEAWEEDFS_TPU_SCHED_PROBE_MS", 50.0)
            / 1e3)
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None
        self.ratio = 0.0
        self.ticks = 0

    def start(self) -> "SchedProbe":
        self._thread = threading.Thread(
            target=self._run, name="weed-sched-probe", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        m = _process_metrics()
        ewma = 0.0
        while True:
            t0 = time.monotonic()
            if self._stop.wait(self.interval):
                return
            overshoot = max(
                0.0, (time.monotonic() - t0) - self.interval)
            ewma = 0.9 * ewma + 0.1 * (overshoot / self.interval)
            self.ratio = ewma
            self.ticks += 1
            if self.ticks == 1 or self.ticks % 10 == 0:
                # first tick immediately (a scrape right after boot
                # must see the gauge), then ~2 writes/second at the
                # default interval
                m.gauge_set(
                    "gil_wait_ratio", round(ewma, 4),
                    help_text="EWMA of scheduler-probe sleep overshoot"
                              " / interval: how late runnable threads "
                              "get the GIL back (0 idle, ~1 = a whole "
                              "extra interval per wakeup)")


_sched_probe: "SchedProbe | None" = None


def sched_probe_enabled() -> bool:
    return os.environ.get("SEAWEEDFS_TPU_SCHED_PROBE", "1") \
        not in ("0", "false")


def maybe_start_sched_probe() -> "SchedProbe | None":
    """Once per process (every role's install_debug_routes calls
    this, like maybe_autostart)."""
    global _sched_probe
    if _sched_probe is not None or not sched_probe_enabled():
        return _sched_probe
    _sched_probe = SchedProbe().start()
    return _sched_probe


# -- device telemetry (the TPU path's instrument cluster) -----------------

def _process_metrics():
    from . import stats
    return stats.PROCESS


def device_note(direction: str, nbytes: int,
                seconds: "float | None") -> None:
    """Record one host<->device staging window (direction "h2d" or
    "d2h"): cumulative bytes, a latency histogram, and a last-window
    throughput gauge — the number ROADMAP item 2's double-buffered
    staging work will watch.  seconds=None records bytes only: an
    async backend's enqueue wall is not a transfer wall, and a bogus
    gauge is worse than none (rs_jax._staged_h2d's fencing policy)."""
    m = _process_metrics()
    m.counter_add("device_transfer_bytes_total", float(nbytes),
                  help_text="host<->device staging bytes", dir=direction)
    if seconds is None:
        return
    m.histogram_observe("device_transfer_seconds", seconds,
                        help_text="host<->device staging window "
                                  "latency", dir=direction)
    if seconds > 0:
        # literal mint names (SWFS017): the direction set is closed
        gauge = "device_h2d_gbps" if direction == "h2d" \
            else "device_d2h_gbps"
        m.gauge_set(gauge, nbytes / seconds / 1e9,
                    help_text="last staging window throughput")


def overlap_note(fraction: float, windows: int,
                 op: str = "encode") -> None:
    """Record one windowed staging launch's h2d/d2h overlap fraction
    (ops.staging: 0 = the staging and consume planes ran serially,
    1 = the wall equalled the slower plane alone) plus the window
    count — the figure that says whether the double-buffered pipeline
    actually pipelined."""
    m = _process_metrics()
    m.gauge_set("device_h2d_overlap_fraction", fraction,
                help_text="last windowed launch's h2d/d2h overlap "
                          "fraction (0 serial .. 1 fully overlapped)",
                op=op)
    m.counter_add("device_staged_windows_total", float(windows),
                  help_text="h2d staging windows launched", op=op)


def kernel_note(kernel: str, seconds: float, nbytes: int = 0) -> None:
    """Record one device kernel dispatch-to-materialize window."""
    m = _process_metrics()
    m.histogram_observe("device_kernel_seconds", seconds,
                        help_text="device kernel wall time per launch",
                        kernel=kernel)
    m.gauge_set("device_kernel_last_ms", seconds * 1e3, kernel=kernel)
    if nbytes:
        m.counter_add("device_kernel_bytes_total", float(nbytes),
                      kernel=kernel)


def sample_device_memory() -> "dict[str, dict]":
    """Gauge each jax device's memory stats (bytes_in_use / peak /
    limit where the backend reports them).  Returns {device: stats};
    empty (and silent) when jax is absent, uninitialized, or the
    backend has no memory_stats — CPU test meshes must not pay for or
    fail on a TPU-only surface."""
    out: dict[str, dict] = {}
    try:
        import jax
        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — telemetry must never raise
        return out
    m = _process_metrics()
    for d in devices:
        try:
            ms = d.memory_stats()
        except Exception:  # noqa: BLE001
            ms = None
        if not ms:
            continue
        label = f"{d.platform}:{d.id}"
        out[label] = dict(ms)
        for key, gauge in (("bytes_in_use", "device_memory_bytes_in_use"),
                           ("peak_bytes_in_use",
                            "device_memory_peak_bytes"),
                           ("bytes_limit", "device_memory_bytes_limit")):
            if key in ms:
                m.gauge_set(gauge, float(ms[key]),
                            help_text="jax device memory stats",
                            device=label)
    return out


# -- Prometheus text-format client helpers --------------------------------

_LABEL_ESCAPES = {"n": "\n", "\\": "\\", '"': '"'}


def _unescape_label(v: str) -> str:
    """Single left-to-right pass — sequential str.replace decodes
    `\\\\n` (escaped backslash + literal n) wrongly because the \\n
    replacement consumes the second backslash of the pair."""
    if "\\" not in v:
        return v
    out: list = []
    i = 0
    while i < len(v):
        c = v[i]
        if c == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            out.append(_LABEL_ESCAPES.get(nxt, c + nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def parse_prom_text(text: str) -> "dict[str, list]":
    """Parse Prometheus exposition text into
    {metric_name: [(labels_dict, value), ...]} — the client half of
    stats.Metrics.render, for cluster.top and bench.py write_path to
    read any node's /metrics without a dependency."""
    out: dict[str, list] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            head, val = line.rsplit(" ", 1)
            value = float(val)
        except ValueError:
            continue
        labels: dict[str, str] = {}
        name = head
        if "{" in head and head.endswith("}"):
            name, _, rest = head.partition("{")
            body = rest[:-1]
            # split on commas outside quotes; values may hold escaped
            # quotes (stats.escape_label_value)
            parts, cur, quoted, escaped = [], "", False, False
            for ch in body:
                if escaped:
                    cur += ch
                    escaped = False
                elif ch == "\\":
                    cur += ch
                    escaped = True
                elif ch == '"':
                    quoted = not quoted
                    cur += ch
                elif ch == "," and not quoted:
                    parts.append(cur)
                    cur = ""
                else:
                    cur += ch
            if cur:
                parts.append(cur)
            for p in parts:
                k, _, v = p.partition("=")
                v = v.strip()
                if v.startswith('"') and v.endswith('"'):
                    v = _unescape_label(v[1:-1])
                labels[k.strip()] = v
        out.setdefault(name, []).append((labels, value))
    return out


def prom_histogram(metrics: "dict[str, list]", name: str,
                   match: "dict | None" = None) -> "dict | None":
    """Reassemble one histogram from parsed exposition text, merged
    across every label set whose labels include `match`.  Returns
    {"buckets": [...], "counts": [...(per-bucket, non-cumulative)...],
    "sum": s, "count": n} or None."""
    match = match or {}

    def ok(labels: dict) -> bool:
        return all(labels.get(k) == v for k, v in match.items())

    by_le: dict[float, float] = {}
    total_sum = 0.0
    total_count = 0.0
    seen = False
    for labels, value in metrics.get(f"{name}_bucket", []):
        if not ok(labels) or "le" not in labels:
            continue
        le = float("inf") if labels["le"] in ("+Inf", "inf") \
            else float(labels["le"])
        by_le[le] = by_le.get(le, 0.0) + value
        seen = True
    for labels, value in metrics.get(f"{name}_sum", []):
        if ok(labels):
            total_sum += value
            seen = True
    for labels, value in metrics.get(f"{name}_count", []):
        if ok(labels):
            total_count += value
    if not seen:
        return None
    les = sorted(le for le in by_le if le != float("inf"))
    cum = [by_le[le] for le in les] + \
        [by_le.get(float("inf"), total_count)]
    counts = [cum[0]] + [cum[i] - cum[i - 1]
                         for i in range(1, len(cum))]
    return {"buckets": les, "counts": counts,
            "sum": total_sum, "count": total_count}


def histogram_delta(after: "dict | None", before: "dict | None"
                    ) -> "dict | None":
    """after - before for two prom_histogram snapshots (the windowed
    view cluster.top and the bench need: counters are cumulative, the
    last N seconds are a subtraction)."""
    if after is None:
        return None
    if before is None or before.get("buckets") != after.get("buckets"):
        return dict(after)
    return {
        "buckets": list(after["buckets"]),
        "counts": [a - b for a, b in zip(after["counts"],
                                         before["counts"])],
        "sum": after["sum"] - before["sum"],
        "count": after["count"] - before["count"],
    }


def histogram_quantile(hist: "dict | None", q: float) -> float:
    """Linear-interpolated quantile over {buckets, counts} (the
    Prometheus histogram_quantile estimate).  0.0 for empty input."""
    if not hist or hist.get("count", 0) <= 0:
        return 0.0
    target = hist["count"] * min(max(q, 0.0), 1.0)
    cum = 0.0
    lo = 0.0
    for le, n in zip(hist["buckets"] + [float("inf")], hist["counts"]):
        if n <= 0:
            lo = le if le != float("inf") else lo
            continue
        if cum + n >= target:
            if le == float("inf"):
                return lo       # open upper bucket: clamp to its floor
            frac = (target - cum) / n
            return lo + (le - lo) * frac
        cum += n
        lo = le
    return lo if lo != float("inf") else 0.0
