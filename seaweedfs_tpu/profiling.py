"""Performance-observability plane: where do the microseconds go.

PR 3 (tracing) answers "what did THIS request do"; the metrics plane
answers "how many / how slow on average".  Neither can answer the two
questions the headline ROADMAP gaps turn on — "what is this process
doing RIGHT NOW" (the 50x write-path gap is pure host-side overhead,
arXiv:1709.05365 §5) and "which STAGE of the hot path eats the time"
(the TPU arm's numbers were only reachable with device-level telemetry,
arXiv:2112.09017).  This module is the instrument panel both
questions read from:

1. `Sampler` — an in-process sampling wall-clock profiler.  A daemon
   thread snapshots `sys._current_frames()` at a configured rate and
   folds each thread's stack into collapsed-stack lines
   (``frame;frame;frame count`` — the flamegraph.pl input format).
   Off by default; armed per process via ``POST /debug/pprof`` (see
   server/debug.py) or at boot with ``SEAWEEDFS_TPU_PROFILE_HZ``.
   Overhead is bounded by construction: the sampler measures its own
   per-pass cost and stretches its sleep so sampling never exceeds
   ``MAX_OVERHEAD`` of one core, frame labels are cached per code
   object, and the folded table is capped (overflow counted, never
   unbounded).

2. `StageTrack` + `stage()` — write-path latency decomposition.  A
   role server opens a track around its hot handler
   (``with profiling.track("write", role=..., metrics=...)``); code
   anywhere down the synchronous call chain wraps its stages in
   ``with profiling.stage("append")`` — a contextvar carries the
   active track, so storage/volume.py needs no API change to report
   into the volume server's registry.  On finish the track observes
   one ``write_stage_seconds{stage}`` histogram cell per stage (plus
   ``stage="total"``) into the role's metrics and emits sibling trace
   spans, so `trace.show` renders the same breakdown per request.
   When no track is active, `stage()` is a shared no-op context
   manager: one contextvar read on the hot path.

3. Device telemetry — `device_note` (h2d/d2h staging throughput),
   `kernel_note` (per-encode kernel wall-ms), and
   `sample_device_memory` (jax backend memory stats), all recorded
   into stats.PROCESS so every role's /metrics carries them.  jax is
   only imported inside `sample_device_memory`, guarded — the module
   must be importable on roles that never touch a device.

4. Prometheus-text helpers (`parse_prom_text`, `prom_histogram`,
   `histogram_quantile`) and `merge_folded` — the client half of the
   plane, shared by `weed shell cluster.top` / `cluster.profile` and
   `bench.py write_path`.

Knobs:
  SEAWEEDFS_TPU_PROFILE_HZ       sampling rate; 0 (default) = off
  SEAWEEDFS_TPU_PROFILE_STACKS   distinct folded stacks kept (2048)
  SEAWEEDFS_TPU_STAGE_TIMERS     "0" disables stage tracks entirely
"""

from __future__ import annotations

import contextvars
import os
import sys
import threading
import time

# finer than stats.DEFAULT_BUCKETS: needle appends and index updates
# live in the 50us-5ms range the request-latency buckets can't resolve
STAGE_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5)

# the sampler refuses to spend more than this fraction of one core on
# itself: when a pass over every thread costs more than
# MAX_OVERHEAD * interval, the next sleep stretches to compensate
MAX_OVERHEAD = 0.10


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def default_hz() -> float:
    """SEAWEEDFS_TPU_PROFILE_HZ: sampling rate when the profiler is
    armed without an explicit rate; 0 (the default) keeps it off."""
    return max(0.0, _env_float("SEAWEEDFS_TPU_PROFILE_HZ", 0.0))


def max_stacks() -> int:
    """SEAWEEDFS_TPU_PROFILE_STACKS: bound on distinct folded stacks
    kept per process (overflow is counted, not stored)."""
    return max(64, _env_int("SEAWEEDFS_TPU_PROFILE_STACKS", 2048))


def stage_timers_enabled() -> bool:
    """SEAWEEDFS_TPU_STAGE_TIMERS=0 turns the write-path stage
    decomposition off (the track() call becomes a no-op)."""
    return os.environ.get("SEAWEEDFS_TPU_STAGE_TIMERS", "1") != "0"


# -- sampling profiler ----------------------------------------------------

class Sampler:
    """Thread-based statistical wall-clock profiler.

    Signal-based sampling (ITIMER_PROF) only interrupts the main
    thread; every role server does its real work on handler/pipeline
    threads, so a dedicated sampler thread walking
    `sys._current_frames()` is the only design that sees the hot
    paths.  Each pass folds every thread's stack root-first into
    `file.py:func;file.py:func;...` and counts it — the collapsed
    stack format any flamegraph renderer takes as-is."""

    MAX_DEPTH = 48

    def __init__(self):
        self._lock = threading.Lock()
        self._folded: dict[str, int] = {}
        self._label_cache: dict[object, str] = {}
        self._thread: "threading.Thread | None" = None
        self._stop = threading.Event()
        self.hz = 0.0
        self.samples = 0            # sampling passes completed
        self.stacks = 0             # thread stacks recorded
        self.dropped = 0            # stacks lost to the table cap
        self.self_seconds = 0.0     # time spent inside sampling passes
        self.started_wall = 0.0
        self._started_mono = 0.0
        self._stopped_elapsed = 0.0

    # -- control ---------------------------------------------------------

    def start(self, hz: "float | None" = None) -> bool:
        """Arm the sampler at `hz` (default: the env knob, else 100).
        Returns False when already running (the running profile is
        left untouched — two operators arming cluster-wide must not
        reset each other's windows)."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return False
            rate = hz if hz and hz > 0 else (default_hz() or 100.0)
            self.hz = min(float(rate), 1000.0)
            self._folded.clear()
            self.samples = self.stacks = self.dropped = 0
            self.self_seconds = 0.0
            self.started_wall = time.time()
            self._started_mono = time.monotonic()
            self._stopped_elapsed = 0.0
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="weed-profiler", daemon=True)
            self._thread.start()
            return True

    def stop(self) -> None:
        with self._lock:
            t = self._thread
            if t is None:
                return
            self._stop.set()
        t.join(timeout=5.0)
        with self._lock:
            if self._thread is t:
                self._stopped_elapsed = \
                    time.monotonic() - self._started_mono
                self._thread = None

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def reset(self) -> None:
        # _label_cache deliberately not cleared here: it is written
        # lock-free by the sampler thread (its only writer — start()
        # joins the old thread before spawning a new one) and bounded
        # by MAX_LABELS in _frame_label, so touching it from a
        # handler thread would be the race, not the hygiene
        with self._lock:
            self._folded.clear()
            self.samples = self.stacks = self.dropped = 0
            self.self_seconds = 0.0

    # -- sampling loop ---------------------------------------------------

    # code objects are cache keys (strong refs): bound the cache so a
    # long-armed process that mints code dynamically (jax jit) cannot
    # pin an unbounded set of them
    MAX_LABELS = 32768

    def _frame_label(self, code) -> str:
        label = self._label_cache.get(code)
        if label is None:
            if len(self._label_cache) >= self.MAX_LABELS:
                self._label_cache.clear()
            label = (f"{code.co_filename.rsplit('/', 1)[-1]}"
                     f":{code.co_name}")
            self._label_cache[code] = label
        return label

    def _run(self) -> None:
        me = threading.get_ident()
        interval = 1.0 / self.hz
        cap = max_stacks()
        while not self._stop.is_set():
            t0 = time.perf_counter()
            try:
                frames = sys._current_frames()
            except RuntimeError:   # pragma: no cover — interp teardown
                break
            new_folded = []
            for tid, frame in frames.items():
                if tid == me:
                    continue
                parts = []
                f = frame
                while f is not None and len(parts) < self.MAX_DEPTH:
                    parts.append(self._frame_label(f.f_code))
                    f = f.f_back
                new_folded.append(";".join(reversed(parts)))
            with self._lock:
                for stack in new_folded:
                    n = self._folded.get(stack)
                    if n is not None:
                        self._folded[stack] = n + 1
                        self.stacks += 1
                    elif len(self._folded) < cap:
                        self._folded[stack] = 1
                        self.stacks += 1
                    else:
                        self.dropped += 1
                self.samples += 1
                cost = time.perf_counter() - t0
                self.self_seconds += cost
            # overhead bound: never let sampling cost exceed
            # MAX_OVERHEAD of one core — a pass that took longer than
            # its budget buys proportionally more sleep
            self._stop.wait(max(interval, cost / MAX_OVERHEAD))

    # -- output ----------------------------------------------------------

    def snapshot(self, top: int = 0) -> dict:
        """JSON-able state + folded table (all stacks, or the `top` N
        by count)."""
        with self._lock:
            elapsed = (time.monotonic() - self._started_mono) \
                if self.running else self._stopped_elapsed
            folded = dict(self._folded)
            doc = {
                "running": self.running,
                "hz": self.hz,
                "samples": self.samples,
                "stacks": self.stacks,
                "droppedStacks": self.dropped,
                "startedAt": self.started_wall,
                "elapsedSeconds": round(elapsed, 3),
                "selfSeconds": round(self.self_seconds, 4),
                "overhead": round(self.self_seconds / elapsed, 4)
                if elapsed > 0 else 0.0,
            }
        if top and top > 0:
            folded = dict(sorted(folded.items(),
                                 key=lambda kv: -kv[1])[:top])
        doc["folded"] = folded
        return doc

    def collapsed(self) -> str:
        """`stack count` lines, most-sampled first — pipe straight
        into flamegraph.pl."""
        with self._lock:
            items = sorted(self._folded.items(), key=lambda kv: -kv[1])
        return "\n".join(f"{stack} {n}" for stack, n in items) + \
            ("\n" if items else "")


_sampler = Sampler()
_autostart_done = False


def sampler() -> Sampler:
    return _sampler


def maybe_autostart() -> None:
    """Boot-time arming: when SEAWEEDFS_TPU_PROFILE_HZ is set > 0 the
    process profiles from startup (once per process — every role's
    install_debug_routes calls this)."""
    global _autostart_done
    if _autostart_done:
        return
    _autostart_done = True
    if default_hz() > 0:
        _sampler.start(default_hz())


def merge_folded(tables: "list[dict]") -> "dict[str, int]":
    """Sum folded-stack tables (cluster.profile merges every node's
    snapshot into one cluster-wide flame view)."""
    out: dict[str, int] = {}
    for t in tables:
        for stack, n in (t or {}).items():
            try:
                out[stack] = out.get(stack, 0) + int(n)
            except (TypeError, ValueError):
                continue
    return out


# -- write-path stage decomposition ---------------------------------------

_track_var: contextvars.ContextVar["StageTrack | None"] = \
    contextvars.ContextVar("weed_stage_track", default=None)


class StageTrack:
    """Per-request stage accumulator.  Thread-safe: the filer funnel
    records assign/upload stages from limiter pool threads into the
    handler thread's track (see use_track)."""

    __slots__ = ("name", "role", "metrics", "stages", "_lock",
                 "_t0", "trace_ctx")

    def __init__(self, name: str, role: str = "", metrics=None):
        self.name = name
        self.role = role
        self.metrics = metrics
        # stage -> [cumulative seconds, calls, first-call wall time]
        self.stages: dict[str, list] = {}
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        from . import tracing
        self.trace_ctx = tracing.current_ids()

    def add(self, stage: str, seconds: float) -> None:
        with self._lock:
            rec = self.stages.get(stage)
            if rec is None:
                # span-start RECORD, deliberately wall (trace spans
                # carry wall starts); the duration itself came off
                # perf_counter in _StageCtx
                self.stages[stage] = [
                    seconds, 1, time.time() - seconds]  # noqa: SWFS011
            else:
                rec[0] += seconds
                rec[1] += 1

    def finish(self) -> float:
        """Observe one histogram cell per stage (plus stage="total")
        and emit sibling stage spans under the span that was active at
        track start.  Returns the track's total seconds."""
        total = time.perf_counter() - self._t0
        with self._lock:
            stages = {k: list(v) for k, v in self.stages.items()}
        hist = f"{self.name}_stage_seconds"
        if self.metrics is not None:
            for stage, (secs, _calls, _w0) in stages.items():
                self.metrics.histogram_observe(
                    hist, secs, buckets=STAGE_BUCKETS,
                    help_text=f"per-request {self.name}-path stage "
                              f"decomposition", stage=stage)
            self.metrics.histogram_observe(
                hist, total, buckets=STAGE_BUCKETS, stage="total")
        if self.trace_ctx and stages:
            from . import tracing
            for stage, (secs, calls, wall0) in stages.items():
                tracing.emit_span(
                    f"{self.name}.{stage}", wall0, secs,
                    role=self.role or
                    (self.trace_ctx[2] if self.trace_ctx else ""),
                    parent=self.trace_ctx[1],
                    trace_id=self.trace_ctx[0],
                    attrs={"calls": calls} if calls > 1 else None)
        return total


class _TrackCtx:
    """`with profiling.track(...)`: create + activate + finish."""

    __slots__ = ("_trk", "_token")

    def __init__(self, name: str, role: str, metrics):
        self._trk = StageTrack(name, role=role, metrics=metrics) \
            if stage_timers_enabled() else None
        self._token = None

    def __enter__(self) -> "StageTrack | None":
        if self._trk is not None:
            self._token = _track_var.set(self._trk)
        return self._trk

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._trk is None:
            return
        try:
            _track_var.reset(self._token)
        except ValueError:      # pragma: no cover — cross-context exit
            pass
        self._trk.finish()


def track(name: str, role: str = "", metrics=None) -> _TrackCtx:
    """Open a stage track for the current request and make it the
    context's active track; finished (histograms observed, spans
    emitted) on exit.  Yields None when stage timers are disabled."""
    return _TrackCtx(name, role, metrics)


def current_track() -> "StageTrack | None":
    return _track_var.get()


class _UseTrack:
    """Re-bind an existing track on ANOTHER thread (contextvars do not
    follow threading.Thread): the filer captures its track before
    handing upload work to the limiter pool, and each pool task wraps
    itself in use_track so operation.assign/upload's stage() calls
    find it."""

    __slots__ = ("_trk", "_token")

    def __init__(self, trk: "StageTrack | None"):
        self._trk = trk
        self._token = None

    def __enter__(self):
        if self._trk is not None:
            self._token = _track_var.set(self._trk)
        return self._trk

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._token is not None:
            try:
                _track_var.reset(self._token)
            except ValueError:  # pragma: no cover
                pass


def use_track(trk: "StageTrack | None") -> _UseTrack:
    return _UseTrack(trk)


class _StageCtx:
    __slots__ = ("_trk", "_name", "_t0")

    def __init__(self, trk: "StageTrack", name: str):
        self._trk = trk
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self._trk.add(self._name, time.perf_counter() - self._t0)


class _NoopStage:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        pass


_NOOP = _NoopStage()


def stage(name: str):
    """Time one stage of the active track; a shared no-op (one
    contextvar read) when no track is active — safe on any hot path."""
    trk = _track_var.get()
    if trk is None:
        return _NOOP
    return _StageCtx(trk, name)


# -- device telemetry (the TPU path's instrument cluster) -----------------

def _process_metrics():
    from . import stats
    return stats.PROCESS


def device_note(direction: str, nbytes: int,
                seconds: "float | None") -> None:
    """Record one host<->device staging window (direction "h2d" or
    "d2h"): cumulative bytes, a latency histogram, and a last-window
    throughput gauge — the number ROADMAP item 2's double-buffered
    staging work will watch.  seconds=None records bytes only: an
    async backend's enqueue wall is not a transfer wall, and a bogus
    gauge is worse than none (rs_jax._staged_h2d's fencing policy)."""
    m = _process_metrics()
    m.counter_add("device_transfer_bytes_total", float(nbytes),
                  help_text="host<->device staging bytes", dir=direction)
    if seconds is None:
        return
    m.histogram_observe("device_transfer_seconds", seconds,
                        help_text="host<->device staging window "
                                  "latency", dir=direction)
    if seconds > 0:
        m.gauge_set(f"device_{direction}_gbps", nbytes / seconds / 1e9,
                    help_text="last staging window throughput")


def overlap_note(fraction: float, windows: int,
                 op: str = "encode") -> None:
    """Record one windowed staging launch's h2d/d2h overlap fraction
    (ops.staging: 0 = the staging and consume planes ran serially,
    1 = the wall equalled the slower plane alone) plus the window
    count — the figure that says whether the double-buffered pipeline
    actually pipelined."""
    m = _process_metrics()
    m.gauge_set("device_h2d_overlap_fraction", fraction,
                help_text="last windowed launch's h2d/d2h overlap "
                          "fraction (0 serial .. 1 fully overlapped)",
                op=op)
    m.counter_add("device_staged_windows_total", float(windows),
                  help_text="h2d staging windows launched", op=op)


def kernel_note(kernel: str, seconds: float, nbytes: int = 0) -> None:
    """Record one device kernel dispatch-to-materialize window."""
    m = _process_metrics()
    m.histogram_observe("device_kernel_seconds", seconds,
                        help_text="device kernel wall time per launch",
                        kernel=kernel)
    m.gauge_set("device_kernel_last_ms", seconds * 1e3, kernel=kernel)
    if nbytes:
        m.counter_add("device_kernel_bytes_total", float(nbytes),
                      kernel=kernel)


def sample_device_memory() -> "dict[str, dict]":
    """Gauge each jax device's memory stats (bytes_in_use / peak /
    limit where the backend reports them).  Returns {device: stats};
    empty (and silent) when jax is absent, uninitialized, or the
    backend has no memory_stats — CPU test meshes must not pay for or
    fail on a TPU-only surface."""
    out: dict[str, dict] = {}
    try:
        import jax
        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — telemetry must never raise
        return out
    m = _process_metrics()
    for d in devices:
        try:
            ms = d.memory_stats()
        except Exception:  # noqa: BLE001
            ms = None
        if not ms:
            continue
        label = f"{d.platform}:{d.id}"
        out[label] = dict(ms)
        for key, gauge in (("bytes_in_use", "device_memory_bytes_in_use"),
                           ("peak_bytes_in_use",
                            "device_memory_peak_bytes"),
                           ("bytes_limit", "device_memory_bytes_limit")):
            if key in ms:
                m.gauge_set(gauge, float(ms[key]),
                            help_text="jax device memory stats",
                            device=label)
    return out


# -- Prometheus text-format client helpers --------------------------------

_LABEL_ESCAPES = {"n": "\n", "\\": "\\", '"': '"'}


def _unescape_label(v: str) -> str:
    """Single left-to-right pass — sequential str.replace decodes
    `\\\\n` (escaped backslash + literal n) wrongly because the \\n
    replacement consumes the second backslash of the pair."""
    if "\\" not in v:
        return v
    out: list = []
    i = 0
    while i < len(v):
        c = v[i]
        if c == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            out.append(_LABEL_ESCAPES.get(nxt, c + nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def parse_prom_text(text: str) -> "dict[str, list]":
    """Parse Prometheus exposition text into
    {metric_name: [(labels_dict, value), ...]} — the client half of
    stats.Metrics.render, for cluster.top and bench.py write_path to
    read any node's /metrics without a dependency."""
    out: dict[str, list] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            head, val = line.rsplit(" ", 1)
            value = float(val)
        except ValueError:
            continue
        labels: dict[str, str] = {}
        name = head
        if "{" in head and head.endswith("}"):
            name, _, rest = head.partition("{")
            body = rest[:-1]
            # split on commas outside quotes; values may hold escaped
            # quotes (stats.escape_label_value)
            parts, cur, quoted, escaped = [], "", False, False
            for ch in body:
                if escaped:
                    cur += ch
                    escaped = False
                elif ch == "\\":
                    cur += ch
                    escaped = True
                elif ch == '"':
                    quoted = not quoted
                    cur += ch
                elif ch == "," and not quoted:
                    parts.append(cur)
                    cur = ""
                else:
                    cur += ch
            if cur:
                parts.append(cur)
            for p in parts:
                k, _, v = p.partition("=")
                v = v.strip()
                if v.startswith('"') and v.endswith('"'):
                    v = _unescape_label(v[1:-1])
                labels[k.strip()] = v
        out.setdefault(name, []).append((labels, value))
    return out


def prom_histogram(metrics: "dict[str, list]", name: str,
                   match: "dict | None" = None) -> "dict | None":
    """Reassemble one histogram from parsed exposition text, merged
    across every label set whose labels include `match`.  Returns
    {"buckets": [...], "counts": [...(per-bucket, non-cumulative)...],
    "sum": s, "count": n} or None."""
    match = match or {}

    def ok(labels: dict) -> bool:
        return all(labels.get(k) == v for k, v in match.items())

    by_le: dict[float, float] = {}
    total_sum = 0.0
    total_count = 0.0
    seen = False
    for labels, value in metrics.get(f"{name}_bucket", []):
        if not ok(labels) or "le" not in labels:
            continue
        le = float("inf") if labels["le"] in ("+Inf", "inf") \
            else float(labels["le"])
        by_le[le] = by_le.get(le, 0.0) + value
        seen = True
    for labels, value in metrics.get(f"{name}_sum", []):
        if ok(labels):
            total_sum += value
            seen = True
    for labels, value in metrics.get(f"{name}_count", []):
        if ok(labels):
            total_count += value
    if not seen:
        return None
    les = sorted(le for le in by_le if le != float("inf"))
    cum = [by_le[le] for le in les] + \
        [by_le.get(float("inf"), total_count)]
    counts = [cum[0]] + [cum[i] - cum[i - 1]
                         for i in range(1, len(cum))]
    return {"buckets": les, "counts": counts,
            "sum": total_sum, "count": total_count}


def histogram_delta(after: "dict | None", before: "dict | None"
                    ) -> "dict | None":
    """after - before for two prom_histogram snapshots (the windowed
    view cluster.top and the bench need: counters are cumulative, the
    last N seconds are a subtraction)."""
    if after is None:
        return None
    if before is None or before.get("buckets") != after.get("buckets"):
        return dict(after)
    return {
        "buckets": list(after["buckets"]),
        "counts": [a - b for a, b in zip(after["counts"],
                                         before["counts"])],
        "sum": after["sum"] - before["sum"],
        "count": after["count"] - before["count"],
    }


def histogram_quantile(hist: "dict | None", q: float) -> float:
    """Linear-interpolated quantile over {buckets, counts} (the
    Prometheus histogram_quantile estimate).  0.0 for empty input."""
    if not hist or hist.get("count", 0) <= 0:
        return 0.0
    target = hist["count"] * min(max(q, 0.0), 1.0)
    cum = 0.0
    lo = 0.0
    for le, n in zip(hist["buckets"] + [float("inf")], hist["counts"]):
        if n <= 0:
            lo = le if le != float("inf") else lo
            continue
        if cum + n >= target:
            if le == float("inf"):
                return lo       # open upper bucket: clamp to its floor
            frac = (target - cum) / n
            return lo + (le - lo) * frac
        cum += n
        lo = le
    return lo if lo != float("inf") else 0.0
