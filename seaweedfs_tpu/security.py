"""Security plane: HS256 JWTs for the data path, admin-plane auth, and
the process-global security configuration.

Mirrors weed/security/jwt.go:18 (GenJwtForVolumeServer: per-fid claims
signed by the master, verified by volume servers; separate write and
read keys with independent expiries) and weed/security/guard.go (Guard:
whitelist + JWT gate).  Like the reference — where security.toml is
loaded once into a process-global viper config
(util/config.go:34 LoadSecurityConfiguration) — the configuration here
is a module-level singleton that servers and the client SDK consult by
default; individual servers may override it for mixed-cluster tests.

JWT wire format is standard RFC 7519 HS256 (base64url(header).
base64url(claims).base64url(hmac-sha256)) so tokens interoperate with
any JWT tooling.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import ipaddress
import json
import re
import time
from dataclasses import dataclass, field


_SAFE_EXT = re.compile(r"^\.(dat|idx|vif|ecx|ecj|ec\d{2})$")
_SAFE_COLLECTION = re.compile(r"^[A-Za-z0-9_.-]*$")


def check_path_fields(collection: str, ext: str | None = None) -> None:
    """Both fields land in filesystem paths on volume servers — reject
    traversal before any path is built.  Shared by every server that
    accepts these fields from requests (volume admin plane, master
    assign/grow front door)."""
    if ext is not None and not _SAFE_EXT.match(ext):
        raise ValueError(f"unacceptable ext {ext!r}")
    if not _SAFE_COLLECTION.match(collection):
        raise ValueError(f"unacceptable collection {collection!r}")


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _b64url_decode(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


_HEADER = _b64url(json.dumps(
    {"alg": "HS256", "typ": "JWT"}, separators=(",", ":")).encode())


class JwtError(Exception):
    pass


def gen_jwt(key: str, claims: dict, expires_sec: int = 0) -> str:
    """Sign claims with HS256 (jwt.go GenJwtForVolumeServer shape:
    empty key -> empty token, exp only when expires_sec > 0)."""
    if not key:
        return ""
    claims = dict(claims)
    if expires_sec > 0:
        claims["exp"] = int(time.time()) + expires_sec
    payload = _b64url(json.dumps(claims, separators=(",", ":"),
                                 sort_keys=True).encode())
    signing_input = f"{_HEADER}.{payload}".encode()
    sig = hmac.new(key.encode(), signing_input, hashlib.sha256).digest()
    return f"{_HEADER}.{payload}.{_b64url(sig)}"


def decode_jwt(key: str, token: str) -> dict:
    """Verify signature + exp/nbf and return the claims
    (jwt.go DecodeJwt)."""
    parts = token.split(".")
    if len(parts) != 3:
        raise JwtError("malformed token")
    try:
        header = json.loads(_b64url_decode(parts[0]))
        claims = json.loads(_b64url_decode(parts[1]))
        sig = _b64url_decode(parts[2])
    except (ValueError, TypeError) as e:
        raise JwtError(f"undecodable token: {e}") from None
    if header.get("alg") != "HS256":
        raise JwtError("unknown token method")
    want = hmac.new(key.encode(), f"{parts[0]}.{parts[1]}".encode(),
                    hashlib.sha256).digest()
    if not hmac.compare_digest(sig, want):
        raise JwtError("bad signature")
    now = time.time()
    if "exp" in claims and now > float(claims["exp"]):
        raise JwtError("token expired")
    if "nbf" in claims and now < float(claims["nbf"]):
        raise JwtError("token not yet valid")
    return claims


def get_jwt(query: dict, headers: dict) -> str:
    """Extract a token from a request: ?jwt= then Authorization: Bearer
    (jwt.go GetJwt order; the cookie path is not mirrored — no browser
    UI on these servers)."""
    token = query.get("jwt", "")
    if not token:
        bearer = headers.get("Authorization", "")
        if bearer[:7].upper() == "BEARER ":
            token = bearer[7:]
    return token


@dataclass
class SecurityConfig:
    """The security.toml surface (command/scaffold/security.toml):
    [jwt.signing] gates volume writes, [jwt.signing.read] gates volume
    reads, admin_key gates the admin/maintenance plane (the guard's
    grpc/TLS role in this HTTP build), white_list bypasses all checks
    by source IP/CIDR."""

    volume_write_key: str = ""
    volume_write_expires_sec: int = 10
    volume_read_key: str = ""
    volume_read_expires_sec: int = 60
    admin_key: str = ""
    admin_expires_sec: int = 60
    white_list: list[str] = field(default_factory=list)
    # TLS/mTLS for the whole plane (weed/security/tls.go; [tls] in
    # security.toml).  When set, every HttpServer wraps its socket and
    # every client helper dials https with the cluster CA pinned.
    tls: "object | None" = None  # tls.TlsConfig

    # -- data-path tokens (per-fid claims, jwt.go SeaweedFileIdClaims) --

    def write_jwt(self, fid: str) -> str:
        return gen_jwt(self.volume_write_key, {"fid": fid},
                       self.volume_write_expires_sec)

    def read_jwt(self, fid: str) -> str:
        return gen_jwt(self.volume_read_key, {"fid": fid},
                       self.volume_read_expires_sec)

    def write_headers(self, fid: str) -> dict[str, str]:
        """Authorization header for a data-path write/delete on fid."""
        tok = self.write_jwt(fid)
        return {"Authorization": f"Bearer {tok}"} if tok else {}

    def check_fid_jwt(self, key: str, query: dict, headers: dict,
                      fid: str) -> str | None:
        """Returns an error string, or None when authorized."""
        if not key:
            return None
        token = get_jwt(query, headers)
        if not token:
            return "missing jwt"
        try:
            claims = decode_jwt(key, token)
        except JwtError as e:
            return str(e)
        # the claim restricts the token to one file id; an empty claim
        # fid is a wildcard the reference allows for chunked manifests
        if claims.get("fid", "") not in ("", fid):
            return f"jwt for {claims.get('fid')!r} used for {fid!r}"
        return None

    # -- admin plane -----------------------------------------------------

    def admin_jwt(self) -> str:
        return gen_jwt(self.admin_key, {"admin": True},
                       self.admin_expires_sec)

    def admin_headers(self) -> dict[str, str]:
        if not self.admin_key:
            return {}
        return {"Authorization": f"Bearer {self.admin_jwt()}"}

    def check_admin(self, query: dict, headers: dict,
                    remote_ip: str = "") -> str | None:
        """guard.go order: the whitelist is checked first; with a
        whitelist configured but no key, non-whitelisted IPs are
        REJECTED (the whitelist is a gate, not only a bypass)."""
        if not self.admin_key and not self.white_list:
            return None
        if self.white_list and remote_ip and \
                self.ip_whitelisted(remote_ip):
            return None
        if not self.admin_key:
            return f"ip {remote_ip} not in white list"
        token = get_jwt(query, headers)
        if not token:
            return "missing admin jwt"
        try:
            claims = decode_jwt(self.admin_key, token)
        except JwtError as e:
            return str(e)
        if not claims.get("admin"):
            return "not an admin token"
        return None

    # -- whitelist (guard.go checkWhiteList) ----------------------------

    def ip_whitelisted(self, ip: str) -> bool:
        if not self.white_list:
            return False
        for entry in self.white_list:
            if entry == ip:
                return True
            if "/" in entry:
                try:
                    if ipaddress.ip_address(ip) in \
                            ipaddress.ip_network(entry, strict=False):
                        return True
                except ValueError:
                    continue
        return False

    @property
    def enabled(self) -> bool:
        return bool(self.volume_write_key or self.volume_read_key or
                    self.admin_key or self.white_list)


# -- process-global config (util/config.go LoadSecurityConfiguration) ---

_config = SecurityConfig()


def configure(cfg: SecurityConfig | None) -> None:
    global _config
    _config = cfg or SecurityConfig()


def current() -> SecurityConfig:
    return _config


def load_security_toml(path: str) -> SecurityConfig:
    """Load the reference's security.toml layout
    (command/scaffold/security.toml: [jwt.signing].key,
    [jwt.signing.read].key, [access].white_list; admin_key is this
    build's HTTP analog of [grpc].ca-gated admin access)."""
    try:
        import tomllib
    except ModuleNotFoundError:      # py<3.11: the tomli backport
        import tomli as tomllib
    with open(path, "rb") as f:
        t = tomllib.load(f)
    jwt_t = t.get("jwt", {})
    signing = jwt_t.get("signing", {})
    read = signing.get("read", {})
    access = t.get("access", {})
    admin = t.get("admin", {})
    tls_t = t.get("tls", {})
    tls_cfg = None
    if tls_t:
        missing = [k for k in ("ca", "cert", "key")
                   if not tls_t.get(k)]
        if missing:
            # failing HERE names the security.toml key; failing later
            # would be an opaque OpenSSL error deep inside a request
            raise ValueError(
                f"security.toml [tls] requires ca/cert/key; "
                f"missing: {', '.join(missing)}")
        from .tls import TlsConfig
        tls_cfg = TlsConfig(
            ca_cert=tls_t["ca"],
            cert=tls_t["cert"],
            key=tls_t["key"],
            require_client_cert=bool(tls_t.get("mtls", False)))
    return SecurityConfig(
        tls=tls_cfg,
        volume_write_key=signing.get("key", ""),
        volume_write_expires_sec=int(
            signing.get("expires_after_seconds", 10)),
        volume_read_key=read.get("key", ""),
        volume_read_expires_sec=int(
            read.get("expires_after_seconds", 60)),
        # [admin] key is canonical; [access] admin_key is accepted
        # because an earlier scaffold template printed that spelling
        admin_key=admin.get("key", "") or access.get("admin_key", ""),
        admin_expires_sec=int(admin.get("expires_after_seconds", 60)),
        white_list=list(access.get("white_list", [])),
    )
