"""seaweedfs_tpu — a TPU-native distributed object store framework.

A ground-up rebuild of SeaweedFS's capability surface (master / volume /
filer / shell / worker roles, needle volume storage, replication, and
Reed-Solomon erasure coding) designed TPU-first: the compute-heavy path
(GF(2^8) erasure coding) runs as batched JAX/XLA kernels sharded over a
`jax.sharding.Mesh`, while the control plane and storage engine are
idiomatic Python/C++ rather than a port of the reference's Go.
"""

__version__ = "0.1.0"
