"""Native C++ components, built on demand.

The reference's native layer is Go-calling-SIMD-assembly + Rust
(SURVEY §2.6); ours is C++ compiled at first use (g++ is in the image;
pybind11 is not, so bindings go through ctypes).  The build artifact is
cached next to the sources keyed on source mtime.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "gf_rs.cc")
_SO = os.path.join(_DIR, "_build", "libgf_rs.so")
_lock = threading.Lock()
_lib = None
_tried = False




def _build_if_stale(src_path: str, out_path: str,
                    extra_flags: "list[str] | None" = None,
                    shared: bool = True,
                    try_march_native: bool = False,
                    deps: "list[str] | None" = None) -> "str | None":
    """Shared mtime-keyed g++ build (one implementation for all the
    native artifacts): makedirs, staleness check (source + any listed
    header deps), per-pid scratch so concurrent builders never publish
    half-written output, atomic publish.  None when the toolchain is
    unavailable."""
    try:
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        newest = os.path.getmtime(src_path)
        for dep in deps or ():
            try:
                newest = max(newest, os.path.getmtime(dep))
            except OSError:
                pass
        if os.path.exists(out_path) and \
                os.path.getmtime(out_path) >= newest:
            return out_path
        tmp = f"{out_path}.{os.getpid()}.tmp"
        base = ["g++", "-O2", "-std=c++17"]
        if shared:
            base += ["-shared", "-fPIC", "-pthread"]
        attempts = ([["-march=native"], []] if try_march_native
                    else [[]])
        for march in attempts:
            try:
                subprocess.run(
                    base + march + (extra_flags or []) +
                    [src_path, "-o", tmp],
                    check=True, capture_output=True, timeout=120)
                os.replace(tmp, out_path)
                return out_path
            except (OSError, subprocess.SubprocessError):
                continue
        return None
    except OSError:
        return None


def _build() -> str | None:
    return _build_if_stale(_SRC, _SO, extra_flags=["-O3"],
                           try_march_native=True)


def load() -> "ctypes.CDLL | None":
    """Build (if needed) + load the native library; None when no
    toolchain / no writable build dir / broken artifact — callers fall
    back to numpy/JAX and must never see an exception from here."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            so = _build()
            if so is None:
                return None
            lib = ctypes.CDLL(so)
            lib.gf_matrix_apply.argtypes = [
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_int,
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.c_size_t, ctypes.c_int]
            lib.gf_mul_slice_acc.argtypes = [
                ctypes.c_uint8, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_size_t]
            lib.gf_native_simd.restype = ctypes.c_int
        except OSError:
            return None
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


# -- shared plane flight-record wire format (ISSUE 18) -----------------


class PlaneRecord(ctypes.Structure):
    """One per-request flight record drained from a C++ plane ring
    (layout mirrors PlaneRec in meta_plane.cc / write_plane.cc /
    read_plane.cc — all three share the 112-byte shape; only the
    stage/fallback label tables differ per plane)."""

    _fields_ = [("rid", ctypes.c_char * 40),
                ("start_unix_ns", ctypes.c_uint64),
                ("stage_ns", ctypes.c_uint64 * 4),
                ("bytes", ctypes.c_uint64),
                ("deadline_ms", ctypes.c_int64),
                ("status", ctypes.c_int32),
                ("fallback", ctypes.c_int32),
                ("flags", ctypes.c_uint32),
                ("_pad", ctypes.c_uint32)]


PLANE_RECORD_CLIENT_RID = 0x1  # rid arrived on the wire (vs minted)
# the wire rid has the plane-minted shape ("mp00c0ffee-1"): it was
# forwarded by a sibling plane's upstream hop, not set by a client —
# the drain sink treats such records as lean unless independently
# interesting (error / over the slow threshold)
PLANE_RECORD_MINTED_UPSTREAM = 0x2

_PLANE_RECORD_DTYPE = None


def plane_record_dtype():
    """Numpy structured-dtype mirror of PlaneRecord, for the
    vectorized drain path (profiling.PlaneRecordSink.feed_buffer).
    Lazy: the wire format must not force numpy at module load."""
    global _PLANE_RECORD_DTYPE
    if _PLANE_RECORD_DTYPE is None:
        import numpy as np
        dt = np.dtype([
            ("rid", "S40"), ("start_unix_ns", "<u8"),
            ("stage_ns", "<u8", (4,)), ("bytes", "<u8"),
            ("deadline_ms", "<i8"), ("status", "<i4"),
            ("fallback", "<i4"), ("flags", "<u4"),
            ("_pad", "<u4")])
        if dt.itemsize != ctypes.sizeof(PlaneRecord):
            raise AssertionError(
                f"PlaneRecord dtype drift: {dt.itemsize} != "
                f"{ctypes.sizeof(PlaneRecord)}")
        _PLANE_RECORD_DTYPE = dt
    return _PLANE_RECORD_DTYPE


def _bind_record_drain(lib: "ctypes.CDLL", prefix: str) -> None:
    """Wire the {mp,wp,rp}_drain_records / _records_dropped pair."""
    drain = getattr(lib, f"{prefix}_drain_records")
    drain.argtypes = [ctypes.c_int, ctypes.POINTER(PlaneRecord),
                      ctypes.c_int]
    drain.restype = ctypes.c_int
    dropped = getattr(lib, f"{prefix}_records_dropped")
    dropped.argtypes = [ctypes.c_int]
    dropped.restype = ctypes.c_ulonglong


def drain_plane_records(lib: "ctypes.CDLL", prefix: str, handle: int,
                        sink=None, cap: int = 512):
    """Pull one plane's flight ring dry.  With `sink`, feed each
    batch through sink.feed and return the total count (the hot
    drainer path — the buffer is reused, never retained); without,
    return copied PlaneRecord instances (tests/inspection)."""
    drain = getattr(lib, f"{prefix}_drain_records")
    buf = (PlaneRecord * cap)()
    out: "list | None" = [] if sink is None else None
    feed_buffer = getattr(sink, "feed_buffer", None)
    total = 0
    while True:
        n = drain(handle, buf, cap)
        if n > 0:
            total += n
            if sink is None:
                out.extend(PlaneRecord.from_buffer_copy(buf[i])
                           for i in range(n))
            elif feed_buffer is not None:
                # vectorized hot path: the sink consumes the raw
                # buffer in one numpy pass before the next drain
                # call reuses it
                feed_buffer(buf, n)
            else:
                sink.feed(buf[i] for i in range(n))
        if n < cap:
            return out if sink is None else total


# -- read-plane library (read_plane.cc) --------------------------------

_RP_SRC = os.path.join(_DIR, "read_plane.cc")
_RP_SO = os.path.join(_DIR, "_build", "libread_plane.so")
_rp_lib = None
_rp_tried = False


def load_read_plane() -> "ctypes.CDLL | None":
    """Build (if needed) + load the native epoll read plane; None when
    unavailable — the volume server then serves reads from Python
    only."""
    global _rp_lib, _rp_tried
    with _lock:
        if _rp_lib is not None or _rp_tried:
            return _rp_lib
        _rp_tried = True
        try:
            if _build_if_stale(_RP_SRC, _RP_SO) is None:
                return None
            lib = ctypes.CDLL(_RP_SO)
            lib.rp_start.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                     ctypes.POINTER(ctypes.c_int)]
            lib.rp_start.restype = ctypes.c_int
            lib.rp_stop.argtypes = [ctypes.c_int]
            lib.rp_add_volume.argtypes = [ctypes.c_int, ctypes.c_uint,
                                          ctypes.c_char_p]
            lib.rp_add_volume.restype = ctypes.c_int
            lib.rp_remove_volume.argtypes = [ctypes.c_int,
                                             ctypes.c_uint]
            lib.rp_put.argtypes = [ctypes.c_int, ctypes.c_uint,
                                   ctypes.c_ulonglong, ctypes.c_uint,
                                   ctypes.c_ulonglong, ctypes.c_uint]
            lib.rp_put.restype = ctypes.c_int
            lib.rp_del.argtypes = [ctypes.c_int, ctypes.c_uint,
                                   ctypes.c_ulonglong]
            lib.rp_served.argtypes = [ctypes.c_int]
            lib.rp_served.restype = ctypes.c_ulonglong
            _bind_record_drain(lib, "rp")
        except (OSError, subprocess.SubprocessError):
            return None
        _rp_lib = lib
        return _rp_lib


# -- write-plane library (write_plane.cc) ------------------------------

_WP_SRC = os.path.join(_DIR, "write_plane.cc")
_WP_SO = os.path.join(_DIR, "_build", "libwrite_plane.so")
_wp_lib = None
_wp_tried = False


class WpEntry(ctypes.Structure):
    """One completed native append, drained back to the Python index
    (layout mirrors write_plane.cc WpEntry)."""

    _fields_ = [("key", ctypes.c_uint64),
                ("offset", ctypes.c_uint64),
                ("append_ns", ctypes.c_uint64),
                ("vid", ctypes.c_uint32),
                ("cookie", ctypes.c_uint32),
                ("size", ctypes.c_int32),
                ("data_len", ctypes.c_uint32)]


def load_write_plane() -> "ctypes.CDLL | None":
    """Build (if needed) + load the native epoll write plane; None
    when unavailable — the volume server then serves writes from
    Python only (the graceful-degradation contract the parity tests
    pin)."""
    global _wp_lib, _wp_tried
    with _lock:
        if _wp_lib is not None or _wp_tried:
            return _wp_lib
        _wp_tried = True
        try:
            if _build_if_stale(_WP_SRC, _WP_SO) is None:
                return None
            lib = ctypes.CDLL(_WP_SO)
            lib.wp_start.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                     ctypes.POINTER(ctypes.c_int)]
            lib.wp_start.restype = ctypes.c_int
            lib.wp_stop.argtypes = [ctypes.c_int]
            lib.wp_add_volume.argtypes = [
                ctypes.c_int, ctypes.c_uint, ctypes.c_char_p,
                ctypes.c_ulonglong, ctypes.c_ulonglong, ctypes.c_int]
            lib.wp_add_volume.restype = ctypes.c_int
            lib.wp_mark_keys.argtypes = [
                ctypes.c_int, ctypes.c_uint,
                ctypes.POINTER(ctypes.c_ulonglong), ctypes.c_int]
            lib.wp_mark_keys.restype = ctypes.c_int
            lib.wp_arm.argtypes = [ctypes.c_int, ctypes.c_uint]
            lib.wp_arm.restype = ctypes.c_int
            lib.wp_remove_volume.argtypes = [ctypes.c_int,
                                             ctypes.c_uint]
            lib.wp_append.argtypes = [
                ctypes.c_int, ctypes.c_uint, ctypes.c_ulonglong,
                ctypes.c_char_p, ctypes.c_ulonglong,
                ctypes.c_ulonglong]
            lib.wp_append.restype = ctypes.c_longlong
            lib.wp_drain.argtypes = [ctypes.c_int, ctypes.c_uint,
                                     ctypes.POINTER(WpEntry),
                                     ctypes.c_int]
            lib.wp_drain.restype = ctypes.c_int
            lib.wp_pending.argtypes = [ctypes.c_int, ctypes.c_uint]
            lib.wp_pending.restype = ctypes.c_int
            lib.wp_tail.argtypes = [ctypes.c_int, ctypes.c_uint]
            lib.wp_tail.restype = ctypes.c_ulonglong
            lib.wp_wait_epoch.argtypes = [
                ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_uint),
                ctypes.POINTER(ctypes.c_ulonglong)]
            lib.wp_wait_epoch.restype = ctypes.c_int
            lib.wp_epoch_done.argtypes = [ctypes.c_int, ctypes.c_uint,
                                          ctypes.c_ulonglong]
            lib.wp_requests.argtypes = [ctypes.c_int]
            lib.wp_requests.restype = ctypes.c_ulonglong
            lib.wp_fallbacks.argtypes = [ctypes.c_int]
            lib.wp_fallbacks.restype = ctypes.c_ulonglong
            lib.wp_latency.argtypes = [
                ctypes.c_int, ctypes.POINTER(ctypes.c_ulonglong)]
            lib.wp_latency.restype = ctypes.c_int
            _bind_record_drain(lib, "wp")
        except (OSError, subprocess.SubprocessError):
            return None
        _wp_lib = lib
        return _wp_lib


# -- meta-plane library (meta_plane.cc) --------------------------------

_MP_SRC = os.path.join(_DIR, "meta_plane.cc")
_MP_SO = os.path.join(_DIR, "_build", "libmeta_plane.so")
_POOL_H = os.path.join(_DIR, "plane_pool.h")
_mp_lib = None
_mp_tried = False


def load_meta_plane() -> "ctypes.CDLL | None":
    """Build (if needed) + load the native filer meta plane; None when
    unavailable — the filer then serves every write from Python (the
    same graceful-degradation contract as the volume write plane)."""
    global _mp_lib, _mp_tried
    with _lock:
        if _mp_lib is not None or _mp_tried:
            return _mp_lib
        _mp_tried = True
        try:
            if _build_if_stale(_MP_SRC, _MP_SO,
                               deps=[_POOL_H]) is None:
                return None
            lib = ctypes.CDLL(_MP_SO)
            lib.mp_start.argtypes = [
                ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
                ctypes.c_char_p, ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_int)]
            lib.mp_start.restype = ctypes.c_int
            lib.mp_stop.argtypes = [ctypes.c_int]
            lib.mp_arm.argtypes = [ctypes.c_int, ctypes.c_int]
            lib.mp_feed_fids.argtypes = [ctypes.c_int, ctypes.c_char_p]
            lib.mp_feed_fids.restype = ctypes.c_int
            lib.mp_fid_level.argtypes = [ctypes.c_int]
            lib.mp_fid_level.restype = ctypes.c_int
            lib.mp_mark_dir.argtypes = [ctypes.c_int, ctypes.c_char_p]
            lib.mp_mark_path.argtypes = [ctypes.c_int, ctypes.c_char_p]
            lib.mp_clear_dirs.argtypes = [ctypes.c_int]
            lib.mp_requests.argtypes = [ctypes.c_int]
            lib.mp_requests.restype = ctypes.c_ulonglong
            lib.mp_fallbacks.argtypes = [ctypes.c_int]
            lib.mp_fallbacks.restype = ctypes.c_ulonglong
            lib.mp_latency.argtypes = [
                ctypes.c_int, ctypes.POINTER(ctypes.c_ulonglong)]
            lib.mp_latency.restype = ctypes.c_int
            lib.mp_stats.argtypes = [
                ctypes.c_int, ctypes.POINTER(ctypes.c_ulonglong)]
            lib.mp_stats.restype = ctypes.c_int
            _bind_record_drain(lib, "mp")
            lib.mp_set_upload_delay_ms.argtypes = [ctypes.c_int,
                                                   ctypes.c_int]
        except (OSError, subprocess.SubprocessError):
            return None
        _mp_lib = lib
        return _mp_lib


# -- filer-read-plane library (filer_read_plane.cc) --------------------

_FRP_SRC = os.path.join(_DIR, "filer_read_plane.cc")
_FRP_SO = os.path.join(_DIR, "_build", "libfiler_read_plane.so")
_frp_lib = None
_frp_tried = False


def load_filer_read_plane() -> "ctypes.CDLL | None":
    """Build (if needed) + load the native filer read plane; None when
    unavailable — the filer then serves every read from Python (same
    graceful-degradation contract as the meta plane)."""
    global _frp_lib, _frp_tried
    with _lock:
        if _frp_lib is not None or _frp_tried:
            return _frp_lib
        _frp_tried = True
        try:
            if _build_if_stale(_FRP_SRC, _FRP_SO,
                               deps=[_POOL_H]) is None:
                return None
            lib = ctypes.CDLL(_FRP_SO)
            lib.frp_start.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                      ctypes.POINTER(ctypes.c_int)]
            lib.frp_start.restype = ctypes.c_int
            lib.frp_stop.argtypes = [ctypes.c_int]
            lib.frp_arm.argtypes = [ctypes.c_int, ctypes.c_int]
            lib.frp_gen.argtypes = [ctypes.c_int]
            lib.frp_gen.restype = ctypes.c_ulonglong
            lib.frp_put_entry.argtypes = [
                ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_ulonglong,
                ctypes.c_ulonglong]
            lib.frp_put_entry.restype = ctypes.c_int
            lib.frp_invalidate.argtypes = [ctypes.c_int,
                                           ctypes.c_char_p]
            lib.frp_clear.argtypes = [ctypes.c_int]
            lib.frp_entries.argtypes = [ctypes.c_int]
            lib.frp_entries.restype = ctypes.c_int
            lib.frp_requests.argtypes = [ctypes.c_int]
            lib.frp_requests.restype = ctypes.c_ulonglong
            lib.frp_fallbacks.argtypes = [ctypes.c_int]
            lib.frp_fallbacks.restype = ctypes.c_ulonglong
            lib.frp_latency.argtypes = [
                ctypes.c_int, ctypes.POINTER(ctypes.c_ulonglong)]
            lib.frp_latency.restype = ctypes.c_int
            lib.frp_stats.argtypes = [
                ctypes.c_int, ctypes.POINTER(ctypes.c_ulonglong)]
            lib.frp_stats.restype = ctypes.c_int
            _bind_record_drain(lib, "frp")
            lib.frp_set_fetch_delay_ms.argtypes = [ctypes.c_int,
                                                   ctypes.c_int]
        except (OSError, subprocess.SubprocessError):
            return None
        _frp_lib = lib
        return _frp_lib


_VT_SRC = os.path.join(os.path.dirname(__file__), "volume_tool.cc")
_VT_BIN = os.path.join(_DIR, "_build", "volume_tool")


def build_volume_tool() -> "str | None":
    """Build (if stale) the standalone C++ volume codec tool — the
    second implementation of the .dat/.idx storage surface (N1
    cross-impl parity role).  Returns the binary path or None when
    the toolchain is unavailable."""
    with _lock:
        return _build_if_stale(_VT_SRC, _VT_BIN, shared=False)
