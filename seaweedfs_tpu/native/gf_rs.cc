// GF(2^8) Reed-Solomon kernel, C++ native path.
//
// The reference's CPU engine is klauspost/reedsolomon (Go + SIMD
// assembly, SURVEY §2.6); this is our native equivalent for the
// latency-bound paths (degraded reads) and the no-TPU fallback, using
// the same math: GF(2^8) poly 29, multiply-by-constant via low/high
// nibble tables, vectorized with vpshufb under AVX2 (the same scheme
// klauspost's amd64 assembly uses).
//
// Built on demand by seaweedfs_tpu/native/__init__.py via g++; exposed
// through ctypes.  No Python.h dependency.

#include <cstddef>
#include <cstdint>
#include <cstring>

#if defined(__AVX2__)
#include <immintrin.h>
#endif

namespace {

constexpr int kFieldSize = 256;
constexpr int kPoly = 29;  // 0x11D low bits

uint8_t g_mul[kFieldSize][kFieldSize];
uint8_t g_low[kFieldSize][16];   // c * nibble
uint8_t g_high[kFieldSize][16];  // c * (nibble << 4)

struct TableInit {
  TableInit() {
    uint8_t log_t[kFieldSize] = {0};
    uint8_t exp_t[kFieldSize * 2 - 2] = {0};
    int b = 1;
    for (int l = 0; l < kFieldSize - 1; ++l) {
      log_t[b] = static_cast<uint8_t>(l);
      b <<= 1;
      if (b >= kFieldSize) b = (b - kFieldSize) ^ kPoly;
    }
    for (int i = 1; i < kFieldSize; ++i) {
      int l = log_t[i];
      exp_t[l] = static_cast<uint8_t>(i);
      exp_t[l + kFieldSize - 1] = static_cast<uint8_t>(i);
    }
    for (int a = 0; a < kFieldSize; ++a) {
      for (int c = 0; c < kFieldSize; ++c) {
        g_mul[a][c] = (a == 0 || c == 0)
                          ? 0
                          : exp_t[log_t[a] + log_t[c]];
      }
    }
    for (int c = 0; c < kFieldSize; ++c) {
      for (int n = 0; n < 16; ++n) {
        g_low[c][n] = g_mul[c][n];
        g_high[c][n] = g_mul[c][n << 4];
      }
    }
  }
} g_table_init;

// out ^= c * in  over n bytes (galois-mul-accumulate, the inner op of
// every RS row).
void mul_acc(uint8_t c, const uint8_t* in, uint8_t* out, size_t n) {
  if (c == 0) return;
  const uint8_t* mul_row = g_mul[c];
  size_t i = 0;
#if defined(__AVX512BW__)
  const __m512i low5 = _mm512_broadcast_i32x4(
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(g_low[c])));
  const __m512i high5 = _mm512_broadcast_i32x4(
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(g_high[c])));
  const __m512i mask5 = _mm512_set1_epi8(0x0f);
  for (; i + 64 <= n; i += 64) {
    __m512i x =
        _mm512_loadu_si512(reinterpret_cast<const void*>(in + i));
    __m512i lo = _mm512_and_si512(x, mask5);
    __m512i hi = _mm512_and_si512(_mm512_srli_epi64(x, 4), mask5);
    __m512i prod = _mm512_xor_si512(_mm512_shuffle_epi8(low5, lo),
                                    _mm512_shuffle_epi8(high5, hi));
    __m512i o =
        _mm512_loadu_si512(reinterpret_cast<const void*>(out + i));
    _mm512_storeu_si512(reinterpret_cast<void*>(out + i),
                        _mm512_xor_si512(o, prod));
  }
#endif
#if defined(__AVX2__)
  const __m256i low = _mm256_broadcastsi128_si256(
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(g_low[c])));
  const __m256i high = _mm256_broadcastsi128_si256(
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(g_high[c])));
  const __m256i mask = _mm256_set1_epi8(0x0f);
  for (; i + 32 <= n; i += 32) {
    __m256i x =
        _mm256_loadu_si256(reinterpret_cast<const __m256i*>(in + i));
    __m256i lo = _mm256_and_si256(x, mask);
    __m256i hi = _mm256_and_si256(_mm256_srli_epi64(x, 4), mask);
    __m256i prod = _mm256_xor_si256(_mm256_shuffle_epi8(low, lo),
                                    _mm256_shuffle_epi8(high, hi));
    __m256i o =
        _mm256_loadu_si256(reinterpret_cast<const __m256i*>(out + i));
    _mm256_storeu_si256(reinterpret_cast<__m256i*>(out + i),
                        _mm256_xor_si256(o, prod));
  }
#endif
  for (; i < n; ++i) out[i] ^= mul_row[in[i]];
}

}  // namespace

extern "C" {

// out[j] ^= mat[j*k + i] * in[i]  for all j<r, i<k, over n bytes.
// Callers zero the outputs first (or pass accumulate=0 to have us do
// it).  ins/outs are arrays of row pointers.
void gf_matrix_apply(const uint8_t* mat, int r, int k,
                     const uint8_t* const* ins, uint8_t* const* outs,
                     size_t n, int accumulate) {
  if (!accumulate) {
    for (int j = 0; j < r; ++j) std::memset(outs[j], 0, n);
  }
  // L2-sized tiles: (k + r) x kTile must stay cache-resident across
  // the k*r mul_acc passes (klauspost batches at 256KB/shard for the
  // same reason, weed ec_encoder.go:61); measured 6x over untiled.
  constexpr size_t kTile = 32 * 1024;
  for (size_t off = 0; off < n; off += kTile) {
    const size_t len = (n - off < kTile) ? (n - off) : kTile;
    for (int i = 0; i < k; ++i) {
      for (int j = 0; j < r; ++j) {
        mul_acc(mat[j * k + i], ins[i] + off, outs[j] + off, len);
      }
    }
  }
}

// single constant multiply-accumulate, exposed for tests/tools
void gf_mul_slice_acc(uint8_t c, const uint8_t* in, uint8_t* out,
                      size_t n) {
  mul_acc(c, in, out, n);
}

int gf_native_simd() {
#if defined(__AVX512BW__)
  return 3;
#elif defined(__AVX2__)
  return 2;
#else
  return 1;
#endif
}

}  // extern "C"
