// GF(2^8) Reed-Solomon kernel, C++ native path.
//
// The reference's CPU engine is klauspost/reedsolomon (Go + SIMD
// assembly, SURVEY §2.6); this is our native equivalent for the
// latency-bound paths (degraded reads) and the no-TPU fallback, using
// the same math over poly 0x11D.  Three tiers, chosen at runtime:
//
//   1. GFNI + AVX512BW (the scheme klauspost's fastest amd64 paths
//      use): multiply-by-constant as an 8x8 bit-matrix via
//      GF2P8AFFINEQB, register-blocked so every input byte is read
//      once and every output byte written once per call — memory
//      traffic (k+r)/k bytes per input byte, the streaming minimum.
//      Large calls additionally split across a few threads.
//   2. AVX512BW / AVX2 vpshufb low/high-nibble tables (klauspost's
//      classic scheme), L2-tiled.
//   3. Scalar table lookup.
//
// Built on demand by seaweedfs_tpu/native/__init__.py via g++; exposed
// through ctypes.  No Python.h dependency.

#include <cstddef>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#if defined(__x86_64__) || defined(__i386__)
#include <immintrin.h>
#define GF_X86 1
#endif

namespace {

constexpr int kFieldSize = 256;
constexpr int kPoly = 29;  // 0x11D low bits
constexpr int kMaxShards = 32;  // ShardBits is uint32 (ec_context)

uint8_t g_mul[kFieldSize][kFieldSize];
uint8_t g_low[kFieldSize][16];   // c * nibble
uint8_t g_high[kFieldSize][16];  // c * (nibble << 4)
// GF2P8AFFINEQB bit-matrix for y = c*x over 0x11D.  Output bit i of
// the instruction uses matrix qword byte (7-i) as the row mask over
// the input bits; row_i bit j = bit_i(c * 2^j), since y = sum_j
// x.bit[j] * (c*2^j).
uint64_t g_aff[kFieldSize];

struct TableInit {
  TableInit() {
    uint8_t log_t[kFieldSize] = {0};
    uint8_t exp_t[kFieldSize * 2 - 2] = {0};
    int b = 1;
    for (int l = 0; l < kFieldSize - 1; ++l) {
      log_t[b] = static_cast<uint8_t>(l);
      b <<= 1;
      if (b >= kFieldSize) b = (b - kFieldSize) ^ kPoly;
    }
    for (int i = 1; i < kFieldSize; ++i) {
      int l = log_t[i];
      exp_t[l] = static_cast<uint8_t>(i);
      exp_t[l + kFieldSize - 1] = static_cast<uint8_t>(i);
    }
    for (int a = 0; a < kFieldSize; ++a) {
      for (int c = 0; c < kFieldSize; ++c) {
        g_mul[a][c] = (a == 0 || c == 0)
                          ? 0
                          : exp_t[log_t[a] + log_t[c]];
      }
    }
    for (int c = 0; c < kFieldSize; ++c) {
      for (int n = 0; n < 16; ++n) {
        g_low[c][n] = g_mul[c][n];
        g_high[c][n] = g_mul[c][n << 4];
      }
      uint64_t m = 0;
      for (int i = 0; i < 8; ++i) {  // output bit i
        uint8_t row = 0;
        for (int j = 0; j < 8; ++j) {
          if ((g_mul[c][1 << j] >> i) & 1) row |= (uint8_t)(1 << j);
        }
        m |= (uint64_t)row << (8 * (7 - i));
      }
      g_aff[c] = m;
    }
  }
} g_table_init;

bool cpu_has_gfni_avx512() {
#if defined(GF_X86)
  static const bool ok = __builtin_cpu_supports("gfni") &&
                         __builtin_cpu_supports("avx512bw") &&
                         __builtin_cpu_supports("avx512f");
  return ok;
#else
  return false;
#endif
}

// out ^= c * in  over n bytes (galois-mul-accumulate, the inner op of
// every RS row) — the tiers-2/3 primitive.
void mul_acc(uint8_t c, const uint8_t* in, uint8_t* out, size_t n) {
  if (c == 0) return;
  const uint8_t* mul_row = g_mul[c];
  size_t i = 0;
#if defined(__AVX512BW__)
  const __m512i low5 = _mm512_broadcast_i32x4(
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(g_low[c])));
  const __m512i high5 = _mm512_broadcast_i32x4(
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(g_high[c])));
  const __m512i mask5 = _mm512_set1_epi8(0x0f);
  for (; i + 64 <= n; i += 64) {
    __m512i x =
        _mm512_loadu_si512(reinterpret_cast<const void*>(in + i));
    __m512i lo = _mm512_and_si512(x, mask5);
    __m512i hi = _mm512_and_si512(_mm512_srli_epi64(x, 4), mask5);
    __m512i prod = _mm512_xor_si512(_mm512_shuffle_epi8(low5, lo),
                                    _mm512_shuffle_epi8(high5, hi));
    __m512i o =
        _mm512_loadu_si512(reinterpret_cast<const void*>(out + i));
    _mm512_storeu_si512(reinterpret_cast<void*>(out + i),
                        _mm512_xor_si512(o, prod));
  }
#endif
#if defined(__AVX2__)
  const __m256i low = _mm256_broadcastsi128_si256(
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(g_low[c])));
  const __m256i high = _mm256_broadcastsi128_si256(
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(g_high[c])));
  const __m256i mask = _mm256_set1_epi8(0x0f);
  for (; i + 32 <= n; i += 32) {
    __m256i x =
        _mm256_loadu_si256(reinterpret_cast<const __m256i*>(in + i));
    __m256i lo = _mm256_and_si256(x, mask);
    __m256i hi = _mm256_and_si256(_mm256_srli_epi64(x, 4), mask);
    __m256i prod = _mm256_xor_si256(_mm256_shuffle_epi8(low, lo),
                                    _mm256_shuffle_epi8(high, hi));
    __m256i o =
        _mm256_loadu_si256(reinterpret_cast<const __m256i*>(out + i));
    _mm256_storeu_si256(reinterpret_cast<__m256i*>(out + i),
                        _mm256_xor_si256(o, prod));
  }
#endif
  for (; i < n; ++i) out[i] ^= mul_row[in[i]];
}

// Tier-2/3 kernel: L2-sized tiles so (k + r) x kTile stays
// cache-resident across the k*r mul_acc passes (klauspost batches at
// 256KB/shard for the same reason, weed ec_encoder.go:61).
void matrix_apply_tiled(const uint8_t* mat, int r, int k,
                        const uint8_t* const* ins,
                        uint8_t* const* outs, size_t off, size_t n) {
  constexpr size_t kTile = 32 * 1024;
  for (size_t t = off; t < off + n; t += kTile) {
    const size_t len = (off + n - t < kTile) ? (off + n - t) : kTile;
    for (int i = 0; i < k; ++i) {
      for (int j = 0; j < r; ++j) {
        mul_acc(mat[j * k + i], ins[i] + t, outs[j] + t, len);
      }
    }
  }
}

#if defined(GF_X86)

// Tier-1 kernel body: R output rows held in zmm accumulators while the
// k input rows stream through GF2P8AFFINEQB.  Processing 2x64 bytes
// per step gives each accumulator two independent dependency chains
// (the affine op has ~3-5 cycle latency).  `acc_init` distinguishes
// fresh outputs (start from zero) from accumulate-into-existing.
template <int R>
__attribute__((target("avx512f,avx512bw,gfni")))
void gfni_block(const uint64_t* aff, int k, const uint8_t* const* ins,
                uint8_t* const* outs, size_t off, size_t n,
                int accumulate) {
  __m512i A[R * kMaxShards];
  for (int j = 0; j < R; ++j)
    for (int s = 0; s < k; ++s)
      A[j * k + s] = _mm512_set1_epi64((long long)aff[j * k + s]);
  size_t i = off;
  for (; i + 128 <= off + n; i += 128) {
    __m512i acc0[R], acc1[R];
    for (int j = 0; j < R; ++j) {
      if (accumulate) {
        acc0[j] = _mm512_loadu_si512(
            reinterpret_cast<const void*>(outs[j] + i));
        acc1[j] = _mm512_loadu_si512(
            reinterpret_cast<const void*>(outs[j] + i + 64));
      } else {
        acc0[j] = _mm512_setzero_si512();
        acc1[j] = _mm512_setzero_si512();
      }
    }
    for (int s = 0; s < k; ++s) {
      __m512i x0 = _mm512_loadu_si512(
          reinterpret_cast<const void*>(ins[s] + i));
      __m512i x1 = _mm512_loadu_si512(
          reinterpret_cast<const void*>(ins[s] + i + 64));
      for (int j = 0; j < R; ++j) {
        acc0[j] = _mm512_xor_si512(
            acc0[j], _mm512_gf2p8affine_epi64_epi8(x0, A[j * k + s], 0));
        acc1[j] = _mm512_xor_si512(
            acc1[j], _mm512_gf2p8affine_epi64_epi8(x1, A[j * k + s], 0));
      }
    }
    for (int j = 0; j < R; ++j) {
      _mm512_storeu_si512(reinterpret_cast<void*>(outs[j] + i),
                          acc0[j]);
      _mm512_storeu_si512(reinterpret_cast<void*>(outs[j] + i + 64),
                          acc1[j]);
    }
  }
  for (; i + 64 <= off + n; i += 64) {
    __m512i acc[R];
    for (int j = 0; j < R; ++j)
      acc[j] = accumulate
                   ? _mm512_loadu_si512(
                         reinterpret_cast<const void*>(outs[j] + i))
                   : _mm512_setzero_si512();
    for (int s = 0; s < k; ++s) {
      __m512i x = _mm512_loadu_si512(
          reinterpret_cast<const void*>(ins[s] + i));
      for (int j = 0; j < R; ++j)
        acc[j] = _mm512_xor_si512(
            acc[j], _mm512_gf2p8affine_epi64_epi8(x, A[j * k + s], 0));
    }
    for (int j = 0; j < R; ++j)
      _mm512_storeu_si512(reinterpret_cast<void*>(outs[j] + i),
                          acc[j]);
  }
}

// Dispatch on r in groups of <=4 accumulator rows (4 rows x 2-way
// unroll = 8 live zmm accumulators + k matrix broadcasts fits the
// 32-register file; r>4 splits into row groups, each still streaming
// the inputs once per group).
__attribute__((target("avx512f,avx512bw,gfni")))
void gfni_apply_range(const uint8_t* mat, const uint64_t* aff, int r,
                      int k, const uint8_t* const* ins,
                      uint8_t* const* outs, size_t off, size_t n,
                      int accumulate) {
  const size_t vec_n = n & ~static_cast<size_t>(63);
  for (int j0 = 0; j0 < r; j0 += 4) {
    const int rr = (r - j0 < 4) ? (r - j0) : 4;
    const uint64_t* aff_g = aff + j0 * k;
    uint8_t* const* outs_g = outs + j0;
    switch (rr) {
      case 1:
        gfni_block<1>(aff_g, k, ins, outs_g, off, vec_n, accumulate);
        break;
      case 2:
        gfni_block<2>(aff_g, k, ins, outs_g, off, vec_n, accumulate);
        break;
      case 3:
        gfni_block<3>(aff_g, k, ins, outs_g, off, vec_n, accumulate);
        break;
      default:
        gfni_block<4>(aff_g, k, ins, outs_g, off, vec_n, accumulate);
        break;
    }
  }
  if (vec_n < n) {  // scalar tail, < 64 bytes
    const size_t t0 = off + vec_n, tn = n - vec_n;
    for (int j = 0; j < r; ++j) {
      if (!accumulate) std::memset(outs[j] + t0, 0, tn);
      for (int s = 0; s < k; ++s)
        mul_acc(mat[j * k + s], ins[s] + t0, outs[j] + t0, tn);
    }
  }
}

#endif  // GF_X86

}  // namespace

extern "C" {

// out[j] (^)= sum_i mat[j*k + i] * in[i]  for all j<r, i<k, over n
// bytes.  accumulate=1 XORs into existing outputs; accumulate=0
// overwrites (callers need not pre-zero).  ins/outs are arrays of row
// pointers.
void gf_matrix_apply(const uint8_t* mat, int r, int k,
                     const uint8_t* const* ins, uint8_t* const* outs,
                     size_t n, int accumulate) {
  if (r <= 0 || k <= 0) return;
#if defined(GF_X86)
  // Schemes beyond the aff[] stack buffer (k or r*k too large) fall
  // through to the tiled path, which handles any matrix size.
  if (cpu_has_gfni_avx512() && n >= 64 && k <= kMaxShards &&
      r * k <= kMaxShards * kMaxShards) {
    uint64_t aff[kMaxShards * kMaxShards];
    for (int j = 0; j < r; ++j)
      for (int s = 0; s < k; ++s)
        aff[j * k + s] = g_aff[mat[j * k + s]];
    // Split large calls across cores (64-byte aligned chunks).  The
    // kernel streams ~(k+r)/k bytes of memory per input byte, so a
    // single core saturates neither the ALUs nor DRAM on 2+ core
    // boxes; small calls stay single-threaded (thread spawn ~50us
    // would swamp the latency path).
    unsigned hw = std::thread::hardware_concurrency();
    size_t want = n / (4 << 20);  // 1 thread per ~4MB, cap at cores
    unsigned nt = want < 2 ? 1
                 : (want > hw ? hw : static_cast<unsigned>(want));
    if (nt <= 1) {
      gfni_apply_range(mat, aff, r, k, ins, outs, 0, n, accumulate);
    } else {
      std::vector<std::thread> ths;
      ths.reserve(nt);
      size_t chunk = ((n / nt) + 63) & ~static_cast<size_t>(63);
      for (unsigned t = 0; t < nt; ++t) {
        size_t off = static_cast<size_t>(t) * chunk;
        if (off >= n) break;
        // The last spawned thread must run to n: when n/nt is already
        // 64-aligned, nt*chunk < n and capping at chunk would leave
        // the final n%nt bytes unprocessed (uninitialized output with
        // accumulate=0).
        size_t len = (t == nt - 1 || n - off < chunk) ? (n - off)
                                                      : chunk;
        ths.emplace_back([=] {
          gfni_apply_range(mat, aff, r, k, ins, outs, off, len,
                           accumulate);
        });
      }
      for (auto& th : ths) th.join();
    }
    return;
  }
#endif
  if (!accumulate) {
    for (int j = 0; j < r; ++j) std::memset(outs[j], 0, n);
  }
  matrix_apply_tiled(mat, r, k, ins, outs, 0, n);
}

// single constant multiply-accumulate, exposed for tests/tools
void gf_mul_slice_acc(uint8_t c, const uint8_t* in, uint8_t* out,
                      size_t n) {
  mul_acc(c, in, out, n);
}

int gf_native_simd() {
  if (cpu_has_gfni_avx512()) return 4;
#if defined(__AVX512BW__)
  return 3;
#elif defined(__AVX2__)
  return 2;
#else
  return 1;
#endif
}

}  // extern "C"
