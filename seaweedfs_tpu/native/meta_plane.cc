// Native filer META plane (ISSUE 17) — the C++ sibling of
// write_plane.cc, one layer up: a single-threaded epoll HTTP front
// that serves the filer's hot write path with ZERO Python per
// request:
//
//   HTTP parse -> eligibility -> pre-assigned fid pop -> chunk upload
//   (pipelined C++->C++ to the volume write plane) -> entry JSON ->
//   metalog WAL line framing -> group-commit batch append (ONE
//   O_APPEND write per segment run per epoll iteration) -> watermark
//   publish -> 201 ack.
//
// The WAL line is byte-identical to meta_log.py append_raw:
//
//   {"nl":LEN,"wid":"WID","op":"create","tsNs":TS,
//    "oldEntry":null,"newEntry":ENTRY}\n
//
// so the unmodified PR 12 machinery (flock-elected applier, overlay
// followers, checkpointing) consumes these lines exactly as it
// consumes a sibling Python filer's.  This plane is, by protocol, just
// another sibling writer over the shared metalog dir: its own wid, its
// own watermark file, O_APPEND whole-batch interleave.
//
// Anything the hot path cannot prove cheap and safe — unknown parent
// directory, possible overwrite, query string, auth, multi-chunk body,
// exotic bytes in the path, empty fid pool, disarmed — answers
// 404 {"error":"meta plane fallback"} and the client retries against
// the Python filer port (the PR 11 fallback contract, verbatim).
//
// Directory knowledge is fed from Python (mp_mark_dir on every fresh
// directory create, mp_mark_path on every Python-path entry event), so
// the plane only ever acks op="create" for paths that provably did not
// exist: the parent dir was created fresh during this plane's
// lifetime and the name was never seen — by Python, a sibling, or
// this plane itself.

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <math.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <stdarg.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <time.h>
#include <unistd.h>

#include <atomic>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "plane_pool.h"

namespace {

constexpr int kMaxServers = 16;
constexpr size_t kMaxBody = 4u * 1024 * 1024;   // filer CHUNK_SIZE
constexpr size_t kMaxHeaders = 64 * 1024;
constexpr size_t kMaxPath = 512;
constexpr size_t kMaxDirs = 4096;               // Filer._known_dirs_cap
constexpr size_t kMaxChildren = 1u << 20;

uint64_t now_ns() {
  timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return uint64_t(ts.tv_sec) * 1000000000ull + uint64_t(ts.tv_nsec);
}

uint64_t mono_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return uint64_t(ts.tv_sec) * 1000000000ull + uint64_t(ts.tv_nsec);
}

int set_nonblock(int fd) {
  int fl = fcntl(fd, F_GETFL, 0);
  if (fl < 0) return -1;
  return fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

// ack latency buckets, mirroring server/write_plane.py ACK_BUCKETS_S
// (seconds): 1e-6 .. 1e-1, 1.0 — stored here in MICROseconds
const uint64_t kLatBuckets[] = {1,      2,      5,      10,     20,
                                50,     100,    200,    500,    1000,
                                2000,   5000,   10000,  20000,  50000,
                                100000, 1000000};
constexpr int kLatN = 17;

// -- per-request flight records (ISSUE 18) ----------------------------
//
// Every request — native ack or fallback — appends one fixed-width
// record to an SPSC overwrite-oldest ring; the Python drainer pulls
// them on a tick and feeds the trace/flight-recorder/histogram planes.
// The hot-path cost is one struct copy + one release store.

constexpr uint32_t kRecFlagClientRid = 1u;  // rid came off the wire
// wire rid of the plane-minted shape ("mp00c0ffee-1"): forwarded by
// a sibling plane on its upstream hop, not a real client trace id —
// the drainer keeps these off the per-record span path unless the
// record is independently interesting (error / over threshold)
constexpr uint32_t kRecFlagMintedUpstream = 2u;

inline uint32_t rid_rec_flags(const char* rid, bool client) {
  if (!client) return 0;
  uint32_t f = kRecFlagClientRid;
  if ((rid[0] == 'm' || rid[0] == 'w' || rid[0] == 'r') &&
      rid[1] == 'p' && rid[2] >= '0' && rid[2] <= '9' &&
      rid[3] >= '0' && rid[3] <= '9')
    f |= kRecFlagMintedUpstream;
  return f;
}

struct PlaneRec {
  char rid[40];            // NUL-padded request id
  uint64_t start_unix_ns;  // CLOCK_REALTIME at ingress (approx)
  uint64_t stage_ns[4];    // kRecStageNames order
  uint64_t bytes;          // request body size
  int64_t deadline_ms;     // X-Weed-Deadline-Ms at ingress; -1 absent
  int32_t status;          // HTTP status answered
  int32_t fallback;        // kRecFallbackNames index
  uint32_t flags;          // kRecFlag*
  uint32_t _pad;
};  // 112 bytes, mirrored by native.PlaneRecord (ctypes)

enum {
  kFbNone = 0,
  kFbIneligible = 1,
  kFbFidDry = 2,
  kFbUpstream = 3,
  kFbWal = 4,
  kFbOversize = 5,
  kFbChunked = 6,
};

// SWFS019 contract: every label below must appear verbatim as a
// string literal in the Python drain table
// (server/meta_plane_native.py) — devtools lint cross-checks.
const char* const kRecStageNames[] = {"parse", "upload", "wal", "ack"};
const char* const kRecFallbackNames[] = {
    "none", "ineligible", "fid_dry", "upstream", "wal", "oversize",
    "chunked"};
const char* const kStatsNames[] = {
    "requests",    "fallbacks", "fid_misses", "wal_errors",
    "upstream_errors", "parse_ns", "upload_ns", "wal_ns",
    "wal_batches", "wal_lines"};

struct RecRing {
  std::vector<PlaneRec> recs;
  uint64_t cap = 0;
  std::atomic<uint64_t> head{0};     // total produced (producer)
  std::atomic<uint64_t> tail{0};     // total consumed (drain thread)
  std::atomic<uint64_t> dropped{0};  // overwritten before drain
};

uint64_t rec_ring_cap_env() {
  const char* v = getenv("SEAWEEDFS_TPU_PLANE_REC_RING");
  if (v != nullptr && *v != '\0') {
    long n = atol(v);
    if (n >= 16 && n <= (1 << 20)) return uint64_t(n);
  }
  return 4096;
}

void rec_push(RecRing* r, const PlaneRec& rec) {
  if (r->cap == 0) return;
  uint64_t h = r->head.load(std::memory_order_relaxed);
  r->recs[h % r->cap] = rec;
  r->head.store(h + 1, std::memory_order_release);
}

// single drainer at a time (the Python side serializes with a lock)
int rec_drain(RecRing* r, PlaneRec* out, int cap) {
  if (r->cap == 0 || out == nullptr || cap <= 0) return 0;
  uint64_t h = r->head.load(std::memory_order_acquire);
  uint64_t t = r->tail.load(std::memory_order_relaxed);
  if (h > t + r->cap) {   // producer lapped us: oldest are gone
    r->dropped.fetch_add((h - r->cap) - t, std::memory_order_relaxed);
    t = h - r->cap;
  }
  int n = 0;
  while (t < h && n < cap) out[n++] = r->recs[t++ % r->cap];
  // the producer may have lapped the slots mid-copy — the torn
  // prefix (oldest copied entries) is dropped, never handed over
  uint64_t h2 = r->head.load(std::memory_order_acquire);
  uint64_t first = t - uint64_t(n);
  if (h2 > first + r->cap) {
    uint64_t torn = h2 - r->cap - first;
    if (torn > uint64_t(n)) torn = uint64_t(n);
    if (torn > 0) {
      memmove(out, out + torn,
              (size_t(n) - size_t(torn)) * sizeof(PlaneRec));
      n -= int(torn);
      r->dropped.fetch_add(torn, std::memory_order_relaxed);
    }
  }
  r->tail.store(t, std::memory_order_relaxed);
  return n;
}

uint64_t rec_dropped(RecRing* r) {
  // live view: committed drops + the current un-drained overrun
  uint64_t h = r->head.load(std::memory_order_acquire);
  uint64_t t = r->tail.load(std::memory_order_relaxed);
  uint64_t extra = (r->cap != 0 && h > t + r->cap)
                       ? (h - r->cap) - t : 0;
  return r->dropped.load(std::memory_order_relaxed) + extra;
}

// -- metalog segment naming (meta_log.py _segment_name) ---------------
//
// Python computes time.gmtime(ts_ns / 1e9): FLOAT division then
// floor.  The double math is replicated exactly so the two writers
// pick the same segment for the same stamp even at minute boundaries
// where double rounding of ts_ns/1e9 differs from integer division.
void segment_name(uint64_t ts_ns, char* day, char* minute) {
  double secs_f = floor(double(ts_ns) / 1e9);
  time_t secs = time_t(secs_f);
  tm t;
  gmtime_r(&secs, &t);
  snprintf(day, 16, "%04d-%02d-%02d", t.tm_year + 1900, t.tm_mon + 1,
           t.tm_mday);
  snprintf(minute, 8, "%02d-%02d", t.tm_hour, t.tm_min);
}

struct Conn {
  int fd = -1;
  uint64_t gen = 0;           // guards acks against fd reuse
  std::string in;
  std::string out;
  bool have_headers = false;
  size_t header_end = 0;
  size_t body_need = 0;
  std::string method;
  std::string target;
  std::string req_headers;    // raw header block (case-insens. search)
  std::string body;
  uint64_t req_start_ns = 0;  // CLOCK_MONOTONIC, first byte of request
  int inflight = 0;           // parked on the native pipeline
  bool close_after = false;
  bool want_write = false;
  char rid[40] = {0};         // X-Request-ID (or minted)
  bool rid_client = false;    // rid came off the wire
  int64_t deadline_ms = -1;   // X-Weed-Deadline-Ms at ingress
};

// one native request in flight against the volume write plane
struct Pending {
  int client_fd = -1;
  uint64_t client_gen = 0;
  std::string path;           // filer path, vetted bytes
  std::string name;           // basename
  std::string mime;           // "" | "application/octet-stream"
  std::string fid;            // "vid,hexkeycookie"
  size_t size = 0;
  uint64_t start_mono = 0;    // request first byte (ack histogram)
  uint64_t dispatch_mono = 0; // eligibility done -> upstream queued
  uint64_t enq_mono = 0;      // upstream-timeout clock
  uint64_t upload_ns = 0;     // set when the volume round trip lands
  char rid[40] = {0};
  uint32_t rid_flags = 0;
  int64_t deadline_ms = -1;
};

// upstream connections come from the shared persistent plane-socket
// pool (plane_pool.h, ISSUE 19): same pick/pipeline/expire behavior
// the inline PR 17 pool had, plus EAGER flush on dispatch — the
// upload hop no longer pays an epoll round trip per request
using Upstream = plane_pool::Upstream<Pending>;

// a parsed+uploaded request waiting on the end-of-iteration barrier
struct WalItem {
  Pending p;
  std::string etag;
  uint64_t chunk_mtime_ns = 0;
};

struct Server {
  int epfd = -1;
  int listen_fd = -1;
  int wake_pipe[2] = {-1, -1};
  std::thread loop;
  std::atomic<bool> stop{false};
  std::atomic<bool> armed{false};

  std::string log_dir;
  std::string wid;
  std::string wm_path;
  int wm_fd = -1;
  uint64_t wm_last = 0;

  // WAL segment writer (single-threaded: the event loop only)
  int seg_fd = -1;
  char seg_day[16] = {0};
  char seg_minute[8] = {0};
  uint64_t last_ts = 0;       // strictly monotonic stamp clock

  std::mutex fid_mu;
  std::deque<std::pair<std::string, std::string>> fids;  // (addr, fid)

  std::mutex dir_mu;
  std::unordered_map<std::string, std::unordered_set<std::string>> dirs;

  std::unordered_map<int, Conn> conns;
  plane_pool::Pool<Pending> pool;    // volume write-plane connections
  std::vector<WalItem> wal_pending;
  uint64_t gen_counter = 0;

  // telemetry (atomics: read from Python threads)
  std::atomic<uint64_t> requests{0};      // native 201 acks
  std::atomic<uint64_t> fallbacks{0};     // 404 handoffs
  std::atomic<uint64_t> fid_misses{0};
  std::atomic<uint64_t> wal_errors{0};
  std::atomic<uint64_t> upstream_errors{0};
  std::atomic<uint64_t> wal_batches{0};
  std::atomic<uint64_t> wal_lines{0};
  std::atomic<uint64_t> parse_ns{0};      // per-stage wall totals
  std::atomic<uint64_t> upload_ns{0};
  std::atomic<uint64_t> wal_ns{0};
  std::atomic<uint64_t> lat_count[kLatN + 1];
  std::atomic<uint64_t> lat_sum_ns{0};

  // per-request flight records + the upload-hop failpoint lever
  RecRing rec;
  std::atomic<int> upload_delay_ms{0};
  uint64_t rid_seq = 0;                   // event-loop thread only
  char rid_prefix[16] = {0};

  Server() {
    for (int i = 0; i <= kLatN; i++) lat_count[i] = 0;
  }
};

std::mutex g_servers_mu;
Server* g_servers[kMaxServers];
std::once_flag g_init_once;

void global_init() {
  for (int i = 0; i < kMaxServers; i++) g_servers[i] = nullptr;
  signal(SIGPIPE, SIG_IGN);
}

Server* get_server(int h) {
  if (h < 0 || h >= kMaxServers) return nullptr;
  std::lock_guard<std::mutex> lk(g_servers_mu);
  return g_servers[h];
}

// -- epoll helpers ----------------------------------------------------

void arm_fd(Server* s, int fd, bool want_write) {
  epoll_event ev{};
  ev.events = EPOLLIN | (want_write ? EPOLLOUT : 0u);
  ev.data.fd = fd;
  epoll_ctl(s->epfd, EPOLL_CTL_MOD, fd, &ev);
}

void conn_arm(Server* s, Conn* c, bool want_write) {
  if (c->want_write == want_write) return;
  c->want_write = want_write;
  arm_fd(s, c->fd, want_write);
}

void close_conn(Server* s, int fd) {
  auto it = s->conns.find(fd);
  if (it == s->conns.end()) return;
  epoll_ctl(s->epfd, EPOLL_CTL_DEL, fd, nullptr);
  close(fd);
  s->conns.erase(it);
}

// -- HTTP plumbing ----------------------------------------------------

// case-insensitive header lookup in a raw "K: v\r\n..." block
std::string header_value(const std::string& headers, const char* name) {
  size_t nlen = strlen(name);
  size_t pos = 0;
  while (pos < headers.size()) {
    size_t eol = headers.find("\r\n", pos);
    if (eol == std::string::npos) eol = headers.size();
    if (eol - pos > nlen && headers[pos + nlen] == ':' &&
        strncasecmp(headers.c_str() + pos, name, nlen) == 0) {
      size_t v = pos + nlen + 1;
      while (v < eol && (headers[v] == ' ' || headers[v] == '\t')) v++;
      return headers.substr(v, eol - v);
    }
    pos = eol + 2;
  }
  return "";
}

bool has_header(const std::string& headers, const char* name) {
  size_t nlen = strlen(name);
  size_t pos = 0;
  while (pos < headers.size()) {
    size_t eol = headers.find("\r\n", pos);
    if (eol == std::string::npos) eol = headers.size();
    if (eol - pos > nlen && headers[pos + nlen] == ':' &&
        strncasecmp(headers.c_str() + pos, name, nlen) == 0)
      return true;
    pos = eol + 2;
  }
  return false;
}

void respond(Server* s, Conn* c, int code, const char* reason,
             const std::string& body) {
  char head[256];
  int n = snprintf(head, sizeof(head),
                   "HTTP/1.1 %d %s\r\n"
                   "Content-Type: application/json\r\n"
                   "Content-Length: %zu\r\n"
                   "%s"
                   "\r\n",
                   code, reason, body.size(),
                   c->close_after ? "Connection: close\r\n" : "");
  c->out.append(head, size_t(n));
  c->out.append(body);
  conn_arm(s, c, true);
}

void respond_fallback(Server* s, Conn* c) {
  s->fallbacks.fetch_add(1, std::memory_order_relaxed);
  respond(s, c, 404, "Not Found",
          "{\"error\":\"meta plane fallback\"}");
}

// append one flight record; ack = total minus the named stages
void rec_emit(Server* s, const char* rid, uint32_t flags,
              int64_t deadline_ms, uint64_t total_ns, uint64_t parse,
              uint64_t upload, uint64_t wal, uint64_t bytes,
              int status, int fallback) {
  PlaneRec r{};
  snprintf(r.rid, sizeof(r.rid), "%s", rid);
  r.start_unix_ns = now_ns() - total_ns;
  r.stage_ns[0] = parse;
  r.stage_ns[1] = upload;
  r.stage_ns[2] = wal;
  uint64_t sum = parse + upload + wal;
  r.stage_ns[3] = total_ns > sum ? total_ns - sum : 0;
  r.bytes = bytes;
  r.deadline_ms = deadline_ms;
  r.status = status;
  r.fallback = fallback;
  r.flags = flags;
  rec_push(&s->rec, r);
}

// fallback record framed from the conn (pre-dispatch failures)
void rec_emit_conn(Server* s, Conn* c, uint64_t bytes, int status,
                   int fallback) {
  uint64_t total =
      c->req_start_ns != 0 ? mono_ns() - c->req_start_ns : 0;
  rec_emit(s, c->rid, rid_rec_flags(c->rid, c->rid_client),
           c->deadline_ms, total, total, 0, 0, bytes, status,
           fallback);
}

// fallback record framed from a dispatched Pending (upstream failures)
void rec_emit_pending(Server* s, const Pending& p, int fallback) {
  uint64_t now = mono_ns();
  uint64_t total = now - p.start_mono;
  uint64_t parse = p.dispatch_mono - p.start_mono;
  uint64_t upload =
      p.upload_ns != 0 ? p.upload_ns : now - p.dispatch_mono;
  rec_emit(s, p.rid, p.rid_flags, p.deadline_ms, total, parse, upload,
           0, p.size, 404, fallback);
}

// -- eligibility ------------------------------------------------------

// the exact byte set the entry JSON can embed with no escaping and the
// Python dispatcher would not transform: printable ASCII minus quote,
// backslash, percent (urllib.unquote), query/fragment markers
bool path_bytes_ok(const std::string& p) {
  for (unsigned char ch : p) {
    if (ch < 0x21 || ch > 0x7E) return false;
    if (ch == '"' || ch == '\\' || ch == '%' || ch == '?' ||
        ch == '#')
      return false;
  }
  return true;
}

bool split_parent(const std::string& path, std::string* parent,
                  std::string* name) {
  size_t slash = path.rfind('/');
  if (slash == std::string::npos || slash + 1 >= path.size())
    return false;
  *parent = slash == 0 ? std::string("/") : path.substr(0, slash);
  *name = path.substr(slash + 1);
  return true;
}

// -- WAL framing + group commit ---------------------------------------

// Python repr of a wall-clock float carries sub-microsecond digits;
// byte parity is NOT required (the applier persists each line's raw
// newEntry verbatim), only valid JSON that parses to the same second
void fmt_wall_seconds(uint64_t ns, char* out, size_t cap) {
  snprintf(out, cap, "%llu.%07llu",
           static_cast<unsigned long long>(ns / 1000000000ull),
           static_cast<unsigned long long>((ns % 1000000000ull) / 100));
}

std::string build_entry_json(const WalItem& w) {
  char mt[40];
  fmt_wall_seconds(w.chunk_mtime_ns, mt, sizeof(mt));
  std::string e;
  e.reserve(256 + w.p.path.size() + w.p.fid.size());
  e += "{\"fullPath\":\"";
  e += w.p.path;
  e += "\",\"isDirectory\":false,\"attributes\":{\"mtime\":";
  e += mt;
  e += ",\"crtime\":";
  e += mt;
  e += ",\"mode\":432,\"uid\":0,\"gid\":0,\"mime\":\"";
  e += w.p.mime;
  e += "\",\"ttlSec\":0,\"symlinkTarget\":\"\"},\"chunks\":[{"
       "\"fileId\":\"";
  e += w.p.fid;
  e += "\",\"offset\":0,\"size\":";
  e += std::to_string(w.p.size);
  e += ",\"eTag\":\"";
  e += w.etag;
  e += "\",\"mtime\":";
  e += std::to_string(w.chunk_mtime_ns);
  e += "}],\"extended\":{}}";
  return e;
}

bool seg_rotate(Server* s, const char* day, const char* minute) {
  if (s->seg_fd >= 0 && strcmp(day, s->seg_day) == 0 &&
      strcmp(minute, s->seg_minute) == 0)
    return true;
  if (s->seg_fd >= 0) {
    close(s->seg_fd);
    s->seg_fd = -1;
  }
  std::string day_dir = s->log_dir + "/" + day;
  mkdir(s->log_dir.c_str(), 0755);
  mkdir(day_dir.c_str(), 0755);
  std::string path = day_dir + "/" + minute + ".log";
  s->seg_fd =
      open(path.c_str(), O_WRONLY | O_CREAT | O_APPEND, 0644);
  if (s->seg_fd < 0) return false;
  snprintf(s->seg_day, sizeof(s->seg_day), "%s", day);
  snprintf(s->seg_minute, sizeof(s->seg_minute), "%s", minute);
  return true;
}

bool write_all(int fd, const char* buf, size_t len) {
  size_t off = 0;
  while (off < len) {
    ssize_t n = write(fd, buf + off, len - off);
    if (n <= 0) {
      if (n < 0 && errno == EINTR) continue;
      return false;   // short write = failed batch, never a false ack
    }
    off += size_t(n);
  }
  return true;
}

void publish_watermark(Server* s, uint64_t ts) {
  if (s->wm_fd < 0 || ts <= s->wm_last) return;
  s->wm_last = ts;
  char payload[32];
  // meta_log.py _format_wm: 20-digit zero-padded value, mod-97 check
  snprintf(payload, sizeof(payload), "%020llu.%02llu",
           static_cast<unsigned long long>(ts),
           static_cast<unsigned long long>(ts % 97));
  pwrite(s->wm_fd, payload, 23, 0);
}

void record_ack_latency(Server* s, uint64_t ns) {
  uint64_t us = ns / 1000;
  int i = 0;
  while (i < kLatN && us > kLatBuckets[i]) i++;
  s->lat_count[i].fetch_add(1, std::memory_order_relaxed);
  s->lat_sum_ns.fetch_add(ns, std::memory_order_relaxed);
}

void client_feed(Server* s, Conn* c);

void flush_client(Server* s, int fd) {
  auto it = s->conns.find(fd);
  if (it == s->conns.end()) return;
  Conn* c = &it->second;
  while (!c->out.empty()) {
    ssize_t n = send(c->fd, c->out.data(), c->out.size(), MSG_NOSIGNAL);
    if (n > 0) {
      c->out.erase(0, size_t(n));
      continue;
    }
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      conn_arm(s, c, true);
      return;
    }
    close_conn(s, fd);
    return;
  }
  if (c->close_after) {
    close_conn(s, fd);
    return;
  }
  conn_arm(s, c, false);
  // the conn may hold a pipelined follow-up request buffered behind
  // the one just answered
  if (c->inflight == 0 && !c->in.empty()) client_feed(s, c);
}

// drain this iteration's completed uploads: frame WAL lines, land each
// segment run with ONE write, publish the watermark, then ack — the
// group-commit barrier, in the exact order that makes acked == durable
void commit_batch(Server* s) {
  if (s->wal_pending.empty()) return;
  uint64_t t0 = mono_ns();
  struct Line {
    uint64_t ts;
    std::string text;
    size_t item;
  };
  std::vector<Line> lines;
  lines.reserve(s->wal_pending.size());
  for (size_t i = 0; i < s->wal_pending.size(); i++) {
    WalItem& w = s->wal_pending[i];
    uint64_t ts = now_ns();
    if (ts <= s->last_ts) ts = s->last_ts + 1;
    s->last_ts = ts;
    std::string entry = build_entry_json(w);
    std::string line;
    line.reserve(entry.size() + s->wid.size() + 96);
    line += "{\"nl\":";
    line += std::to_string(entry.size());
    line += ",\"wid\":\"";
    line += s->wid;
    line += "\",\"op\":\"create\",\"tsNs\":";
    line += std::to_string(ts);
    line += ",\"oldEntry\":null,\"newEntry\":";
    line += entry;
    line += "}\n";
    lines.push_back({ts, std::move(line), i});
  }
  // group contiguous same-segment runs, one kernel append per run
  // (mirrors meta_log.py _group_commit_drain — whole-batch O_APPEND
  // interleave is the shared-dir multi-writer contract)
  bool ok = true;
  size_t i = 0;
  while (i < lines.size() && ok) {
    char day[16], minute[8];
    segment_name(lines[i].ts, day, minute);
    size_t j = i;
    std::string buf;
    while (j < lines.size()) {
      char d2[16], m2[8];
      segment_name(lines[j].ts, d2, m2);
      if (strcmp(d2, day) != 0 || strcmp(m2, minute) != 0) break;
      buf += lines[j].text;
      j++;
    }
    if (!seg_rotate(s, day, minute) ||
        !write_all(s->seg_fd, buf.data(), buf.size())) {
      ok = false;
      break;
    }
    i = j;
  }
  uint64_t t1 = mono_ns();
  s->wal_ns.fetch_add(t1 - t0, std::memory_order_relaxed);
  if (ok) {
    publish_watermark(s, lines.back().ts);
    s->wal_batches.fetch_add(1, std::memory_order_relaxed);
    s->wal_lines.fetch_add(lines.size(), std::memory_order_relaxed);
  } else {
    s->wal_errors.fetch_add(1, std::memory_order_relaxed);
    if (s->seg_fd >= 0) {
      close(s->seg_fd);   // reopen next batch; never serve a bad fd
      s->seg_fd = -1;
    }
  }
  uint64_t wal_win = t1 - t0;   // shared batch window, per record
  std::vector<int> touched;
  for (WalItem& w : s->wal_pending) {
    rec_emit(s, w.p.rid, w.p.rid_flags, w.p.deadline_ms,
             mono_ns() - w.p.start_mono,
             w.p.dispatch_mono - w.p.start_mono, w.p.upload_ns,
             wal_win, w.p.size, ok ? 201 : 404,
             ok ? kFbNone : kFbWal);
    auto it = s->conns.find(w.p.client_fd);
    bool alive =
        it != s->conns.end() && it->second.gen == w.p.client_gen;
    if (!alive) continue;   // durable anyway; ack has nowhere to go
    Conn* c = &it->second;
    c->inflight = 0;
    c->req_start_ns = 0;
    if (ok) {
      s->requests.fetch_add(1, std::memory_order_relaxed);
      record_ack_latency(s, mono_ns() - w.p.start_mono);
      std::string body = "{\"name\":\"" + w.p.name +
                         "\",\"size\":" + std::to_string(w.p.size) +
                         "}";
      respond(s, c, 201, "Created", body);
    } else {
      // the chunk landed on the volume but the WAL append failed:
      // hand the request back to Python (which re-uploads; the
      // orphaned chunk is maintenance-job territory, exactly like
      // every other fallback-after-partial-work path)
      respond_fallback(s, c);
    }
    touched.push_back(w.p.client_fd);
  }
  s->wal_pending.clear();
  for (int fd : touched) flush_client(s, fd);
}

// -- request handling -------------------------------------------------

void dispatch_native(Server* s, Conn* c, const std::string& path,
                     const std::string& name, const std::string& mime,
                     const std::string& addr, const std::string& fid) {
  Pending p;
  p.client_fd = c->fd;
  p.client_gen = c->gen;
  p.path = path;
  p.name = name;
  p.mime = mime;
  p.fid = fid;
  p.size = c->body.size();
  p.start_mono = c->req_start_ns;
  p.dispatch_mono = mono_ns();
  p.enq_mono = p.dispatch_mono;
  // failpoint: deliberately slow the volume upload hop.  Runs after
  // the dispatch stamp so the stall lands in the record's upload
  // stage (measured dispatch -> volume ack) — the acceptance lever
  // for ISSUE 18
  int delay = s->upload_delay_ms.load(std::memory_order_relaxed);
  if (delay > 0) usleep(useconds_t(delay) * 1000);
  memcpy(p.rid, c->rid, sizeof(p.rid));
  p.rid_flags = rid_rec_flags(c->rid, c->rid_client);
  p.deadline_ms = c->deadline_ms;
  s->parse_ns.fetch_add(p.dispatch_mono - c->req_start_ns,
                        std::memory_order_relaxed);
  Upstream* u = s->pool.pick(addr);
  if (u == nullptr) {
    s->upstream_errors.fetch_add(1, std::memory_order_relaxed);
    rec_emit_conn(s, c, c->body.size(), 404, kFbUpstream);
    respond_fallback(s, c);
    return;
  }
  // forward the request id + remaining deadline on the plane-socket
  // hop so the volume plane's record stitches into the same trace
  char dlbuf[48];
  dlbuf[0] = '\0';
  if (c->deadline_ms >= 0) {
    long long elapsed_ms =
        (long long)((p.dispatch_mono - p.start_mono) / 1000000ull);
    long long left = (long long)c->deadline_ms - elapsed_ms;
    if (left < 1) left = 1;
    snprintf(dlbuf, sizeof(dlbuf), "X-Weed-Deadline-Ms: %lld\r\n",
             left);
  }
  char head[384];
  int n = snprintf(head, sizeof(head),
                   "POST /%s HTTP/1.1\r\n"
                   "Host: %s\r\n"
                   "X-Request-ID: %s\r\n"
                   "%s"
                   "Content-Length: %zu\r\n"
                   "\r\n",
                   fid.c_str(), addr.c_str(), c->rid, dlbuf,
                   c->body.size());
  u->out.append(head, size_t(n));
  u->out.append(c->body);
  u->inflight.push_back(std::move(p));
  c->inflight = 1;
  c->body.clear();
  // eager flush (the ISSUE 19 upload-hop lever): the established
  // keep-alive socket is almost always writable — send now instead
  // of paying an epoll round trip to learn that
  s->pool.flush(u);
}

void handle_request(Server* s, Conn* c) {
  const std::string& t = c->target;
  bool eligible =
      s->armed.load(std::memory_order_relaxed) &&
      (c->method == "POST" || c->method == "PUT") && !t.empty() &&
      t[0] == '/' && t.size() < kMaxPath && t.back() != '/' &&
      t.find("//") == std::string::npos && t.compare(0, 3, "/__") != 0 &&
      path_bytes_ok(t) && !c->body.empty() && c->body.size() <= kMaxBody;
  std::string mime;
  if (eligible) {
    mime = header_value(c->req_headers, "Content-Type");
    if (!mime.empty() && mime != "application/octet-stream")
      eligible = false;
    if (has_header(c->req_headers, "Authorization") ||
        has_header(c->req_headers, "Expect") ||
        has_header(c->req_headers, "X-Tenant"))
      eligible = false;
  }
  std::string parent, name;
  if (eligible) eligible = split_parent(t, &parent, &name);
  if (eligible) {
    // parent must be a directory created fresh during this plane's
    // lifetime, and the name never written by anyone — that is the
    // proof op="create" with oldEntry:null is the truth.  The name
    // is NOT claimed yet: a fid-dry fallback below must leave it
    // retryable on the plane port (a boot-time dry pool otherwise
    // poisons the first path a client hammers)
    std::lock_guard<std::mutex> lk(s->dir_mu);
    auto it = s->dirs.find(parent);
    if (it == s->dirs.end() || it->second.count(name) != 0) {
      eligible = false;
    } else if (it->second.size() >= kMaxChildren) {
      s->dirs.erase(it);     // overflow: this dir falls back from now
      eligible = false;
    }
  }
  std::string addr, fid;
  int fb = kFbIneligible;
  if (eligible) {
    std::lock_guard<std::mutex> lk(s->fid_mu);
    if (s->fids.empty()) {
      s->fid_misses.fetch_add(1, std::memory_order_relaxed);
      eligible = false;
      fb = kFbFidDry;
    } else {
      addr = std::move(s->fids.front().first);
      fid = std::move(s->fids.front().second);
      s->fids.pop_front();
    }
  }
  if (eligible) {
    // claim the name now that a fid is in hand; a concurrent twin
    // of the same name may have claimed it between the dir_mu holds
    std::lock_guard<std::mutex> lk(s->dir_mu);
    auto it = s->dirs.find(parent);
    bool claimed = false;
    if (it != s->dirs.end()) {
      if (it->second.size() >= kMaxChildren)
        s->dirs.erase(it);
      else
        claimed = it->second.insert(name).second;
    }
    if (!claimed) {
      eligible = false;
      std::lock_guard<std::mutex> lk2(s->fid_mu);
      s->fids.emplace_front(std::move(addr), std::move(fid));
    }
  }
  if (!eligible) {
    size_t nbytes = c->body.size();
    c->body.clear();
    rec_emit_conn(s, c, nbytes, 404, fb);
    respond_fallback(s, c);
    return;
  }
  dispatch_native(s, c, t, name, mime, addr, fid);
}

void client_feed(Server* s, Conn* c) {
  for (;;) {
    if (c->inflight > 0) return;   // parked behind the barrier
    if (!c->have_headers) {
      size_t he = c->in.find("\r\n\r\n");
      if (he == std::string::npos) {
        if (c->in.size() > kMaxHeaders) close_conn(s, c->fd);
        return;
      }
      if (c->req_start_ns == 0) c->req_start_ns = mono_ns();
      size_t eol = c->in.find("\r\n");
      std::string req_line = c->in.substr(0, eol);
      c->req_headers = c->in.substr(eol + 2, he - eol - 2);
      size_t sp1 = req_line.find(' ');
      size_t sp2 =
          sp1 == std::string::npos ? sp1 : req_line.find(' ', sp1 + 1);
      if (sp1 == std::string::npos || sp2 == std::string::npos) {
        close_conn(s, c->fd);
        return;
      }
      c->method = req_line.substr(0, sp1);
      c->target = req_line.substr(sp1 + 1, sp2 - sp1 - 1);
      std::string rv = header_value(c->req_headers, "X-Request-ID");
      if (!rv.empty()) {
        snprintf(c->rid, sizeof(c->rid), "%.39s", rv.c_str());
        c->rid_client = true;
      } else {
        snprintf(c->rid, sizeof(c->rid), "%s-%llx", s->rid_prefix,
                 static_cast<unsigned long long>(++s->rid_seq));
        c->rid_client = false;
      }
      std::string dv =
          header_value(c->req_headers, "X-Weed-Deadline-Ms");
      c->deadline_ms = dv.empty() ? -1 : atoll(dv.c_str());
      c->close_after =
          strcasecmp(
              header_value(c->req_headers, "Connection").c_str(),
              "close") == 0;
      std::string te =
          header_value(c->req_headers, "Transfer-Encoding");
      std::string cl = header_value(c->req_headers, "Content-Length");
      if (!te.empty()) {
        // no framing we can cheaply parse — refuse and close
        c->close_after = true;
        rec_emit_conn(s, c, 0, 404, kFbChunked);
        respond_fallback(s, c);
        flush_client(s, c->fd);
        return;
      }
      long long need = cl.empty() ? 0 : atoll(cl.c_str());
      if (need < 0 || size_t(need) > kMaxBody + 1) {
        c->close_after = true;   // body too big to swallow: hand off
        rec_emit_conn(s, c, need > 0 ? uint64_t(need) : 0, 404,
                      kFbOversize);
        respond_fallback(s, c);
        flush_client(s, c->fd);
        return;
      }
      c->body_need = size_t(need);
      c->have_headers = true;
      c->in.erase(0, he + 4);
    }
    if (c->in.size() < c->body_need) return;
    c->body = c->in.substr(0, c->body_need);
    c->in.erase(0, c->body_need);
    c->have_headers = false;
    c->body_need = 0;
    uint64_t start = c->req_start_ns;
    handle_request(s, c);
    // handle_request may have closed the conn (parse errors)
    auto it = s->conns.find(c->fd);
    if (it == s->conns.end() || &it->second != c) return;
    c->req_start_ns = 0;
    (void)start;
    if (c->inflight == 0 && !c->out.empty()) {
      flush_client(s, c->fd);
      it = s->conns.find(c->fd);
      if (it == s->conns.end()) return;
    }
  }
}

// one dropped in-flight upstream request (conn error / timeout),
// handed back by the pool: answer the waiting client with the 404
// fallback so Python re-serves the write
void ups_drop_pending(Server* s, Pending& p) {
  s->upstream_errors.fetch_add(1, std::memory_order_relaxed);
  rec_emit_pending(s, p, kFbUpstream);
  auto it = s->conns.find(p.client_fd);
  if (it == s->conns.end() || it->second.gen != p.client_gen) return;
  it->second.inflight = 0;
  it->second.req_start_ns = 0;
  respond_fallback(s, &it->second);
  flush_client(s, p.client_fd);
}

// parse one complete volume-plane response off u->in; false = need
// more bytes
bool ups_feed_one(Server* s, Upstream* u) {
  if (!u->have_headers) {
    size_t he = u->in.find("\r\n\r\n");
    if (he == std::string::npos) return false;
    u->header_end = he;
    int status = 0;
    if (u->in.size() > 12 && u->in.compare(0, 5, "HTTP/") == 0)
      status = atoi(u->in.c_str() + 9);
    u->status = status;
    std::string head = u->in.substr(0, he);
    std::string cl = header_value(head, "Content-Length");
    u->body_need = cl.empty() ? 0 : size_t(atoll(cl.c_str()));
    u->have_headers = true;
    u->in.erase(0, he + 4);
  }
  if (u->in.size() < u->body_need) return false;
  std::string body = u->in.substr(0, u->body_need);
  u->in.erase(0, u->body_need);
  u->have_headers = false;
  int status = u->status;
  u->status = 0;
  u->body_need = 0;
  if (u->inflight.empty()) return true;   // stray; resync on close
  Pending p = std::move(u->inflight.front());
  u->inflight.pop_front();
  uint64_t t = mono_ns();
  p.upload_ns = t - p.dispatch_mono;
  s->upload_ns.fetch_add(t - p.dispatch_mono,
                         std::memory_order_relaxed);
  if (status == 201) {
    WalItem w;
    w.etag = "";
    size_t e = body.find("\"eTag\":\"");
    if (e != std::string::npos) {
      size_t b = e + 8;
      size_t q = body.find('"', b);
      if (q != std::string::npos && q - b <= 16)
        w.etag = body.substr(b, q - b);
    }
    w.p = std::move(p);
    w.chunk_mtime_ns = now_ns();
    s->wal_pending.push_back(std::move(w));
    return true;
  }
  // volume plane refused (its own fallback contract) — hand the whole
  // request back to Python
  s->upstream_errors.fetch_add(1, std::memory_order_relaxed);
  rec_emit_pending(s, p, kFbUpstream);
  auto it = s->conns.find(p.client_fd);
  if (it != s->conns.end() && it->second.gen == p.client_gen) {
    it->second.inflight = 0;
    it->second.req_start_ns = 0;
    respond_fallback(s, &it->second);
    flush_client(s, p.client_fd);
  }
  return true;
}

// -- event loop -------------------------------------------------------

void event_loop(Server* s) {
  epoll_event evs[256];
  while (!s->stop.load(std::memory_order_relaxed)) {
    int n = epoll_wait(s->epfd, evs, 256, 200);
    if (n < 0) {
      if (errno == EINTR) continue;
      break;
    }
    for (int i = 0; i < n; i++) {
      int fd = evs[i].data.fd;
      uint32_t e = evs[i].events;
      if (fd == s->wake_pipe[0]) {
        char buf[64];
        while (read(fd, buf, sizeof(buf)) > 0) {
        }
        continue;
      }
      if (fd == s->listen_fd) {
        for (;;) {
          int cfd = accept4(s->listen_fd, nullptr, nullptr,
                            SOCK_NONBLOCK);
          if (cfd < 0) break;
          int one = 1;
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one,
                     sizeof(one));
          epoll_event cev{};
          cev.events = EPOLLIN;
          cev.data.fd = cfd;
          if (epoll_ctl(s->epfd, EPOLL_CTL_ADD, cfd, &cev) < 0) {
            close(cfd);
            continue;
          }
          Conn c;
          c.fd = cfd;
          c.gen = ++s->gen_counter;
          s->conns[cfd] = std::move(c);
        }
        continue;
      }
      Upstream* u = s->pool.find(fd);
      if (u != nullptr) {
        if (e & (EPOLLHUP | EPOLLERR)) {
          s->pool.close_conn(fd);
          continue;
        }
        if (e & EPOLLOUT) s->pool.flush(u);
        if ((u = s->pool.find(fd)) == nullptr) continue;
        if (e & EPOLLIN) {
          char buf[65536];
          for (;;) {
            ssize_t r = recv(fd, buf, sizeof(buf), 0);
            if (r > 0) {
              u->in.append(buf, size_t(r));
              if (r < ssize_t(sizeof(buf))) break;
              continue;
            }
            if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK))
              break;
            s->pool.close_conn(fd);
            u = nullptr;
            break;
          }
          if (u != nullptr)
            while (ups_feed_one(s, u)) {
            }
        }
        continue;
      }
      auto cit = s->conns.find(fd);
      if (cit == s->conns.end()) continue;
      Conn* c = &cit->second;
      if (e & (EPOLLHUP | EPOLLERR)) {
        close_conn(s, fd);
        continue;
      }
      if (e & EPOLLOUT) {
        flush_client(s, fd);
        cit = s->conns.find(fd);
        if (cit == s->conns.end()) continue;
        c = &cit->second;
      }
      if (e & EPOLLIN) {
        char buf[65536];
        bool dead = false;
        for (;;) {
          ssize_t r = recv(fd, buf, sizeof(buf), 0);
          if (r > 0) {
            c->in.append(buf, size_t(r));
            if (r < ssize_t(sizeof(buf))) break;
            continue;
          }
          if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK))
            break;
          dead = true;
          break;
        }
        if (dead) {
          close_conn(s, fd);
          continue;
        }
        client_feed(s, c);
      }
    }
    // end-of-iteration barrier: everything that finished its volume
    // round trip this pass lands in ONE WAL append (per segment run)
    // and acks together
    commit_batch(s);
    s->pool.expire(mono_ns());
  }
}

}  // namespace

// -- extern "C" API ----------------------------------------------------

extern "C" {

// Start a meta plane over `log_dir` (the shared metalog directory),
// writing lines as writer `wid` and publishing durable stamps into
// `wm_path` (pre-created by the Python driver via atomic replace).
// Binds host:port (0 = ephemeral), reports the bound port through
// out_port.  Returns a handle >= 0, or -1.
int mp_start(const char* host, int port, const char* log_dir,
             const char* wid, const char* wm_path, int* out_port) {
  std::call_once(g_init_once, global_init);
  int slot = -1;
  {
    std::lock_guard<std::mutex> lk(g_servers_mu);
    for (int i = 0; i < kMaxServers; i++)
      if (g_servers[i] == nullptr) {
        slot = i;
        break;
      }
  }
  if (slot < 0) return -1;
  Server* s = new Server();
  s->log_dir = log_dir;
  s->wid = wid;
  s->wm_path = wm_path;
  s->last_ts = now_ns();
  s->rec.cap = rec_ring_cap_env();
  s->rec.recs.resize(s->rec.cap);
  snprintf(s->rid_prefix, sizeof(s->rid_prefix), "mp%02d%06llx", slot,
           static_cast<unsigned long long>(now_ns() & 0xffffff));
  {
    const char* d = getenv("SEAWEEDFS_TPU_MP_UPLOAD_DELAY_MS");
    if (d != nullptr && *d != '\0') s->upload_delay_ms.store(atoi(d));
  }
  s->wm_fd = open(wm_path, O_WRONLY);
  s->epfd = epoll_create1(0);
  s->listen_fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (s->epfd < 0 || s->listen_fd < 0) goto fail;
  s->pool.epfd = s->epfd;
  s->pool.on_drop = [s](Pending& p) { ups_drop_pending(s, p); };
  {
    int one = 1;
    setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one,
               sizeof(one));
    sockaddr_in sa{};
    sa.sin_family = AF_INET;
    sa.sin_port = htons(uint16_t(port));
    if (inet_pton(AF_INET, host, &sa.sin_addr) != 1) goto fail;
    if (bind(s->listen_fd, reinterpret_cast<sockaddr*>(&sa),
             sizeof(sa)) < 0)
      goto fail;
    if (listen(s->listen_fd, 512) < 0) goto fail;
    socklen_t slen = sizeof(sa);
    if (getsockname(s->listen_fd, reinterpret_cast<sockaddr*>(&sa),
                    &slen) < 0)
      goto fail;
    if (out_port != nullptr) *out_port = int(ntohs(sa.sin_port));
    if (pipe2(s->wake_pipe, O_NONBLOCK) < 0) goto fail;
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = s->listen_fd;
    if (epoll_ctl(s->epfd, EPOLL_CTL_ADD, s->listen_fd, &ev) < 0)
      goto fail;
    ev.data.fd = s->wake_pipe[0];
    if (epoll_ctl(s->epfd, EPOLL_CTL_ADD, s->wake_pipe[0], &ev) < 0)
      goto fail;
  }
  s->loop = std::thread(event_loop, s);
  {
    std::lock_guard<std::mutex> lk(g_servers_mu);
    g_servers[slot] = s;
  }
  return slot;
fail:
  if (s->epfd >= 0) close(s->epfd);
  if (s->listen_fd >= 0) close(s->listen_fd);
  if (s->wm_fd >= 0) close(s->wm_fd);
  if (s->wake_pipe[0] >= 0) close(s->wake_pipe[0]);
  if (s->wake_pipe[1] >= 0) close(s->wake_pipe[1]);
  delete s;
  return -1;
}

void mp_stop(int h) {
  Server* s = nullptr;
  {
    std::lock_guard<std::mutex> lk(g_servers_mu);
    if (h < 0 || h >= kMaxServers) return;
    s = g_servers[h];
    g_servers[h] = nullptr;
  }
  if (s == nullptr) return;
  s->stop.store(true);
  char b = 1;
  ssize_t ignored = write(s->wake_pipe[1], &b, 1);
  (void)ignored;
  if (s->loop.joinable()) s->loop.join();
  for (auto& kv : s->conns) close(kv.second.fd);
  s->pool.close_all();
  if (s->seg_fd >= 0) close(s->seg_fd);
  if (s->wm_fd >= 0) close(s->wm_fd);
  close(s->listen_fd);
  close(s->epfd);
  close(s->wake_pipe[0]);
  close(s->wake_pipe[1]);
  delete s;
}

// arm/disarm the hot path (disarmed = every request answers the 404
// fallback; the listener stays up so clients need no re-discovery)
void mp_arm(int h, int on) {
  Server* s = get_server(h);
  if (s != nullptr) s->armed.store(on != 0);
}

// feed pre-assigned fids: newline-separated "host:port vid,fidhex"
// entries (the Python driver batches master assigns and derives the
// range locally).  Returns the pool level after the feed.
int mp_feed_fids(int h, const char* entries) {
  Server* s = get_server(h);
  if (s == nullptr || entries == nullptr) return -1;
  std::lock_guard<std::mutex> lk(s->fid_mu);
  const char* p = entries;
  while (*p != '\0') {
    const char* nl = strchr(p, '\n');
    size_t len = nl != nullptr ? size_t(nl - p) : strlen(p);
    const char* sp = static_cast<const char*>(memchr(p, ' ', len));
    if (sp != nullptr && sp > p && size_t(sp - p) < len - 1)
      s->fids.emplace_back(std::string(p, size_t(sp - p)),
                           std::string(sp + 1, len - size_t(sp - p) - 1));
    if (nl == nullptr) break;
    p = nl + 1;
  }
  return int(s->fids.size());
}

int mp_fid_level(int h) {
  Server* s = get_server(h);
  if (s == nullptr) return -1;
  std::lock_guard<std::mutex> lk(s->fid_mu);
  return int(s->fids.size());
}

// mark a directory created FRESH (provably empty at creation): its
// children become native-eligible
void mp_mark_dir(int h, const char* path) {
  Server* s = get_server(h);
  if (s == nullptr || path == nullptr) return;
  std::lock_guard<std::mutex> lk(s->dir_mu);
  if (s->dirs.size() >= kMaxDirs) s->dirs.clear();
  s->dirs[std::string(path)];
}

// mark a path written through ANY other route (Python, a sibling):
// future native writes to it fall back (overwrite semantics live in
// Python)
void mp_mark_path(int h, const char* path) {
  Server* s = get_server(h);
  if (s == nullptr || path == nullptr) return;
  std::string p(path);
  size_t slash = p.rfind('/');
  if (slash == std::string::npos || slash + 1 >= p.size()) return;
  std::string parent = slash == 0 ? std::string("/") : p.substr(0, slash);
  std::lock_guard<std::mutex> lk(s->dir_mu);
  auto it = s->dirs.find(parent);
  if (it == s->dirs.end()) return;
  if (it->second.size() >= kMaxChildren)
    s->dirs.erase(it);
  else
    it->second.insert(p.substr(slash + 1));
}

// drop all directory knowledge (delete/rename anywhere — mirrors
// Filer._known_dirs.clear(): rare, conservative, always safe)
void mp_clear_dirs(int h) {
  Server* s = get_server(h);
  if (s == nullptr) return;
  std::lock_guard<std::mutex> lk(s->dir_mu);
  s->dirs.clear();
}

unsigned long long mp_requests(int h) {
  Server* s = get_server(h);
  return s != nullptr ? s->requests.load() : 0;
}

unsigned long long mp_fallbacks(int h) {
  Server* s = get_server(h);
  return s != nullptr ? s->fallbacks.load() : 0;
}

// out[0..kLatN]: cumulative bucket counts; out[kLatN+1]=count,
// out[kLatN+2]=sum ns (same shape as wp_latency)
int mp_latency(int h, unsigned long long* out) {
  Server* s = get_server(h);
  if (s == nullptr || out == nullptr) return -1;
  unsigned long long total = 0;
  for (int i = 0; i <= kLatN; i++) {
    total += s->lat_count[i].load();
    out[i] = total;
  }
  out[kLatN + 1] = total;
  out[kLatN + 2] = s->lat_sum_ns.load();
  return kLatN;
}

// aggregate counters for the Python metrics bridge:
// [requests, fallbacks, fid_misses, wal_errors, upstream_errors,
//  parse_ns, upload_ns, wal_ns, wal_batches, wal_lines]
int mp_stats(int h, unsigned long long* out) {
  Server* s = get_server(h);
  if (s == nullptr || out == nullptr) return -1;
  out[0] = s->requests.load();
  out[1] = s->fallbacks.load();
  out[2] = s->fid_misses.load();
  out[3] = s->wal_errors.load();
  out[4] = s->upstream_errors.load();
  out[5] = s->parse_ns.load();
  out[6] = s->upload_ns.load();
  out[7] = s->wal_ns.load();
  out[8] = s->wal_batches.load();
  out[9] = s->wal_lines.load();
  return 10;
}

// drain up to `cap` per-request flight records into `out` (oldest
// first; overwritten-before-drain records are counted, never handed
// over).  Single concurrent drainer — the Python side holds a lock.
int mp_drain_records(int h, PlaneRec* out, int cap) {
  Server* s = get_server(h);
  if (s == nullptr) return -1;
  return rec_drain(&s->rec, out, cap);
}

unsigned long long mp_records_dropped(int h) {
  Server* s = get_server(h);
  return s != nullptr ? rec_dropped(&s->rec) : 0;
}

// failpoint: stall the volume upload hop by `ms` per request (0 = off)
void mp_set_upload_delay_ms(int h, int ms) {
  Server* s = get_server(h);
  if (s != nullptr) s->upload_delay_ms.store(ms < 0 ? 0 : ms);
}

}  // extern "C"
