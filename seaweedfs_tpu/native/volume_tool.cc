// volume_tool — SECOND IMPLEMENTATION of the needle volume codec
// (the N1 cross-impl role: the reference validates its Rust volume
// server against the Go one through a shared parity rig,
// test/volume_server/framework/cluster_rust.go; here an independent
// C++ implementation of the .dat/.idx storage surface is validated
// byte-for-byte against the Python engine).
//
// Formats reproduced from the reference (and matched bit-for-bit by
// tests/test_native_volume_tool.py against storage/needle.py):
//   superblock  8B: version, rp byte, ttl(2), compaction rev u16 BE,
//               extra-size u16 BE (weed/storage/super_block)
//   needle v2/v3 (data records, flags=0):
//               cookie u32 | id u64 | size u32 (all BE)
//               [dataSize u32 | data | flags u8]   (when size > 0)
//               crc32c(data) u32 | [appendAtNs u64 in v3]
//               stale-buffer padding quirk (needle_write_v2.go):
//               ALWAYS 1..8 bytes — v3 re-exposes the BE size field
//               then zeros; v2 re-exposes header[4:12] (the BE id)
//   tombstone:  size==0 record (no body), crc32c("")=0 footer
//   .idx entry 16B: id u64 | storedOffset u32 (bytes/8) | size i32
//               (tombstone rows: offset 0, size -1)
//
// Commands (TSV in/out; no JSON dependency):
//   create <dat> <idx> <version>      manifest on stdin:
//       w \t id \t cookie \t appendAtNs \t base64(data)
//       d \t id \t cookie \t appendAtNs
//   scan <dat>                        records on stdout:
//       off \t id \t cookie \t size \t crc_ok \t appendAtNs \t kind
//
// Build: g++ -O2 -o volume_tool volume_tool.cc

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <iostream>
#include <string>
#include <vector>

namespace {

// -- crc32c (reflected Castagnoli 0x82F63B78; matches storage/crc.py)
uint32_t crc_table[256];
void crc_init() {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++)
      c = (c & 1) ? (c >> 1) ^ 0x82F63B78u : c >> 1;
    crc_table[i] = c;
  }
}
uint32_t crc32c(const uint8_t* p, size_t n, uint32_t value = 0) {
  uint32_t c = value ^ 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++)
    c = crc_table[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// -- big-endian writers
void put32(std::string& out, uint32_t v) {
  for (int i = 3; i >= 0; i--) out.push_back(char((v >> (8 * i)) & 0xFF));
}
void put64(std::string& out, uint64_t v) {
  for (int i = 7; i >= 0; i--) out.push_back(char((v >> (8 * i)) & 0xFF));
}
uint32_t get32(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}
uint64_t get64(const uint8_t* p) {
  return (uint64_t(get32(p)) << 32) | get32(p + 4);
}

// -- base64 (standard alphabet, for the manifest payloads)
int b64val(char c) {
  if (c >= 'A' && c <= 'Z') return c - 'A';
  if (c >= 'a' && c <= 'z') return c - 'a' + 26;
  if (c >= '0' && c <= '9') return c - '0' + 52;
  if (c == '+') return 62;
  if (c == '/') return 63;
  return -1;
}
std::string b64decode(const std::string& s) {
  std::string out;
  int buf = 0, bits = 0;
  for (char c : s) {
    if (c == '=' || c == '\n' || c == '\r') continue;
    int v = b64val(c);
    if (v < 0) continue;
    buf = (buf << 6) | v;
    bits += 6;
    if (bits >= 8) {
      bits -= 8;
      out.push_back(char((buf >> bits) & 0xFF));
    }
  }
  return out;
}

constexpr int kHeader = 16, kPad = 8, kCrc = 4, kTs = 8;

int padding_length(uint32_t size, int version) {
  int footer = kCrc + (version == 3 ? kTs : 0);
  return kPad - ((kHeader + int(size) + footer) % kPad);
}

// serialize one data/tombstone needle exactly like Needle.to_bytes
// (flags=0 path; the stale-padding quirk included)
std::string encode_needle(int version, uint64_t id, uint32_t cookie,
                          uint64_t append_at_ns,
                          const std::string& data) {
  std::string out;
  uint32_t size = data.empty() ? 0 : uint32_t(4 + data.size() + 1);
  put32(out, cookie);
  put64(out, id);
  put32(out, size);
  if (!data.empty()) {
    put32(out, uint32_t(data.size()));
    out += data;
    out.push_back(0);  // flags
  }
  put32(out, crc32c((const uint8_t*)data.data(), data.size()));
  if (version == 3) put64(out, append_at_ns);
  int pad = padding_length(size, version);
  // stale-scratch padding (needle_write_v2.go bit-identity quirk):
  // v3 re-exposes the BE size field then zeros; v2 re-exposes the
  // BE needle id (no LastModified in the flags=0 path)
  std::string stale;
  if (version == 3) {
    put32(stale, size);
    stale.append(4, '\0');
  } else {
    put64(stale, id);
  }
  out += stale.substr(0, size_t(pad));
  return out;
}

std::string idx_entry(uint64_t id, uint32_t stored_offset,
                      int32_t size) {
  std::string out;
  put64(out, id);
  put32(out, stored_offset);
  put32(out, uint32_t(size));
  return out;
}

std::vector<std::string> split_tabs(const std::string& line) {
  std::vector<std::string> out;
  size_t start = 0;
  for (size_t i = 0; i <= line.size(); i++) {
    if (i == line.size() || line[i] == '\t') {
      out.push_back(line.substr(start, i - start));
      start = i + 1;
    }
  }
  return out;
}

int cmd_create(const char* dat_path, const char* idx_path,
               int version) {
  FILE* dat = fopen(dat_path, "wb");
  FILE* idx = fopen(idx_path, "wb");
  if (!dat || !idx) {
    fprintf(stderr, "cannot open output files\n");
    return 1;
  }
  // superblock: version, rp=000, ttl=0, compaction rev 0, no extra
  unsigned char sb[8] = {(unsigned char)version, 0, 0, 0, 0, 0, 0, 0};
  fwrite(sb, 1, 8, dat);
  long offset = 8;
  std::string line;
  // std::getline grows without bound — fgets with a fixed buffer
  // would silently SPLIT long payload lines and write a truncated
  // needle before erroring
  while (std::getline(std::cin, line)) {
    while (!line.empty() &&
           (line.back() == '\n' || line.back() == '\r'))
      line.pop_back();
    if (line.empty()) continue;
    auto f = split_tabs(line);
    if (f.size() < 4) {
      fprintf(stderr, "bad manifest line: %s\n", line.c_str());
      return 1;
    }
    uint64_t id = strtoull(f[1].c_str(), nullptr, 10);
    uint32_t cookie = uint32_t(strtoul(f[2].c_str(), nullptr, 10));
    uint64_t ts = strtoull(f[3].c_str(), nullptr, 10);
    if (f[0] == "w") {
      std::string data = b64decode(f.size() > 4 ? f[4] : "");
      std::string rec = encode_needle(version, id, cookie, ts, data);
      fwrite(rec.data(), 1, rec.size(), dat);
      if (!data.empty()) {
        // Python's write_needle gates nm.put on size_is_valid:
        // a zero-byte blob appends a dat record but NO idx row
        uint32_t size = uint32_t(4 + data.size() + 1);
        std::string ie = idx_entry(id, uint32_t(offset / kPad),
                                   int32_t(size));
        fwrite(ie.data(), 1, ie.size(), idx);
      }
      offset += long(rec.size());
    } else if (f[0] == "d") {
      // tombstone: zero-data record + idx row (offset 0, size -1)
      std::string rec = encode_needle(version, id, cookie, ts, "");
      fwrite(rec.data(), 1, rec.size(), dat);
      std::string ie = idx_entry(id, 0, -1);
      fwrite(ie.data(), 1, ie.size(), idx);
      offset += long(rec.size());
    } else {
      fprintf(stderr, "bad op %s\n", f[0].c_str());
      return 1;
    }
  }
  fclose(dat);
  fclose(idx);
  return 0;
}

int cmd_scan(const char* dat_path) {
  FILE* dat = fopen(dat_path, "rb");
  if (!dat) {
    fprintf(stderr, "cannot open %s\n", dat_path);
    return 1;
  }
  unsigned char sb[8];
  if (fread(sb, 1, 8, dat) != 8) return 1;
  int version = sb[0];
  uint16_t extra = (uint16_t(sb[6]) << 8) | sb[7];
  // records start 8-byte ALIGNED after any superblock extra blob
  // (the Python walker and the append path agree on this)
  long offset = (8 + long(extra) + kPad - 1) / kPad * kPad;
  fseek(dat, offset, SEEK_SET);
  std::vector<uint8_t> rec;
  for (;;) {
    uint8_t header[kHeader];
    if (fread(header, 1, kHeader, dat) != kHeader) break;
    uint32_t cookie = get32(header);
    uint64_t id = get64(header + 4);
    uint32_t raw_size = get32(header + 12);
    // high-bit sizes mark in-place deletions in the reference
    // format (types.size_is_deleted / 0x80000000): the record body
    // length uses the LOW 31 bits — treating the raw u32 as signed
    // int would go negative and blow up the resize
    bool deleted_mark = (raw_size & 0x80000000u) != 0;
    uint32_t size = raw_size & 0x7FFFFFFFu;
    long body = long(size) + kCrc + (version == 3 ? kTs : 0) +
                padding_length(size, version);
    rec.resize(size_t(body));
    if (fread(rec.data(), 1, size_t(body), dat) != size_t(body))
      break;
    uint32_t want_crc = get32(rec.data() + size);
    uint64_t ts = version == 3 ? get64(rec.data() + size + kCrc) : 0;
    const char* kind = deleted_mark ? "deleted"
                       : size == 0   ? "tombstone"
                                     : "write";
    bool crc_ok;
    if (size == 0) {
      crc_ok = want_crc == 0;
    } else {
      uint32_t data_size = get32(rec.data());
      crc_ok = data_size + 5 == size &&
               crc32c(rec.data() + 4, data_size) == want_crc;
    }
    printf("%ld\t%llu\t%u\t%u\t%d\t%llu\t%s\n", offset,
           (unsigned long long)id, cookie, size, crc_ok ? 1 : 0,
           (unsigned long long)ts, kind);
    offset += kHeader + body;
  }
  fclose(dat);
  return 0;
}

}  // namespace

int main(int argc, char** argv) {
  crc_init();
  if (argc >= 5 && strcmp(argv[1], "create") == 0)
    return cmd_create(argv[2], argv[3], atoi(argv[4]));
  if (argc >= 3 && strcmp(argv[1], "scan") == 0)
    return cmd_scan(argv[2]);
  fprintf(stderr,
          "usage: volume_tool create <dat> <idx> <version> "
          "< manifest.tsv\n       volume_tool scan <dat>\n");
  return 2;
}
