// Native HTTP write plane for the volume server — the C++ sibling of
// read_plane.cc on the WRITE side: a single-threaded epoll loop owning
// the needle-append hot path (`POST /<vid>,<fid>` with a
// Content-Length body), bypassing the Python HTTP stack entirely.
// arXiv:1709.05365's finding is that online-EC object stores bottleneck
// on host-side per-request CPU, not codec math; this plane removes the
// ~5 ms of per-request Python (HTTP machinery, json, GIL convoys) the
// PR 7 stage decomposition measured on the volume server.
//
// Ownership contract: while a volume is registered here, this library
// owns the .dat TAIL.  Both the plane's HTTP appends and the Python
// server's own appends (replication, overwrites, tombstones, raw
// repair writes) go through the same per-volume mutex (`wp_append`),
// so records never interleave.  Completed native appends are journaled
// per volume; the Python side drains the journal (`wp_drain`) into its
// NeedleMap + .idx under the volume lock — the .dat is the WAL, the
// .idx a checkpoint, and crash recovery replays the unindexed .dat
// tail (storage/volume.py _replay_dat_tail).
//
// Scope (deliberate): PLAIN anonymous needles only — no name, no mime
// beyond octet-stream, no TTL volume, version-3 volumes, replication
// 000.  Anything else answers 404 and the client falls back to the
// Python port (the read plane's exact fallback contract).  A needle id
// the plane has already seen also 404s: overwrite semantics (cookie
// check, unchanged dedup) stay in Python.
//
// Durability: the ack contract of util/group_commit holds across the
// boundary.  write(2) puts the record in the page cache before the ack
// is queued — SIGKILL-durable, byte-for-byte what the Python barrier's
// flush() guarantees.  On the -fsync tier acks PARK on a flush epoch:
// the Python handshake thread (server/write_plane.py) runs the
// volume's CommitBarrier (one os.fsync per epoch window — group commit
// across the language boundary) and releases the epoch; only then do
// the parked 201s leave the socket.
//
// Build: g++ -O2 -shared -fPIC (no deps); driven via ctypes from
// seaweedfs_tpu/server/write_plane.py.

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <shared_mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

// ---- crc32c (Castagnoli, reflected — storage/crc.py parity) ----------

uint32_t g_crc_table[8][256];

void crc_init() {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++)
      c = (c & 1) ? (c >> 1) ^ 0x82F63B78u : c >> 1;
    g_crc_table[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; i++)
    for (int t = 1; t < 8; t++)
      g_crc_table[t][i] =
          (g_crc_table[t - 1][i] >> 8) ^
          g_crc_table[0][g_crc_table[t - 1][i] & 0xFF];
}

#if defined(__x86_64__)
__attribute__((target("sse4.2"))) uint32_t crc32c_hw(uint32_t c,
                                                     const uint8_t* p,
                                                     size_t n) {
  c = ~c;
  while (n >= 8) {
    uint64_t v;
    memcpy(&v, p, 8);
    c = (uint32_t)__builtin_ia32_crc32di(c, v);
    p += 8;
    n -= 8;
  }
  while (n--) c = __builtin_ia32_crc32qi(c, *p++);
  return ~c;
}
bool g_have_sse42 = false;
#endif

uint32_t crc32c_sw(uint32_t c, const uint8_t* p, size_t n) {
  // slice-by-8
  c = ~c;
  while (n >= 8) {
    c ^= (uint32_t)p[0] | ((uint32_t)p[1] << 8) |
         ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24);
    uint32_t hi = (uint32_t)p[4] | ((uint32_t)p[5] << 8) |
                  ((uint32_t)p[6] << 16) | ((uint32_t)p[7] << 24);
    c = g_crc_table[7][c & 0xFF] ^ g_crc_table[6][(c >> 8) & 0xFF] ^
        g_crc_table[5][(c >> 16) & 0xFF] ^ g_crc_table[4][c >> 24] ^
        g_crc_table[3][hi & 0xFF] ^ g_crc_table[2][(hi >> 8) & 0xFF] ^
        g_crc_table[1][(hi >> 16) & 0xFF] ^ g_crc_table[0][hi >> 24];
    p += 8;
    n -= 8;
  }
  while (n--) c = g_crc_table[0][(c ^ *p++) & 0xFF] ^ (c >> 8);
  return ~c;
}

uint32_t crc32c(const uint8_t* p, size_t n) {
#if defined(__x86_64__)
  if (g_have_sse42) return crc32c_hw(0, p, n);
#endif
  return crc32c_sw(0, p, n);
}

// ---- on-disk record constants (storage/types.py parity) --------------

constexpr size_t kHeaderSize = 16;     // cookie(4) id(8) size(4)
constexpr size_t kChecksumSize = 4;
constexpr size_t kTimestampSize = 8;   // v3 AppendAtNs
constexpr size_t kPadding = 8;
constexpr uint8_t kFlagHasLastModified = 0x08;
constexpr size_t kLastModifiedLen = 5;
constexpr size_t kMaxBody = 64ull << 20;

inline void put32(std::string& b, uint32_t v) {
  char t[4] = {(char)(v >> 24), (char)(v >> 16), (char)(v >> 8),
               (char)v};
  b.append(t, 4);
}

inline void put64(std::string& b, uint64_t v) {
  put32(b, (uint32_t)(v >> 32));
  put32(b, (uint32_t)v);
}

// ---- journal entry handed back to Python -----------------------------

struct WpEntry {
  uint64_t key;
  uint64_t offset;      // absolute byte offset of the record in .dat
  uint64_t append_ns;
  uint32_t vid;
  uint32_t cookie;
  int32_t size;         // on-disk Size field (body size)
  uint32_t data_len;
};

// -- per-request flight records (ISSUE 18) ----------------------------
//
// Identical wire shape to meta_plane.cc's PlaneRec (native.PlaneRecord
// on the ctypes side): one fixed-width record per request into an SPSC
// overwrite-oldest ring, drained by the Python volume server.

constexpr uint32_t kRecFlagClientRid = 1u;  // rid came off the wire
// wire rid of the plane-minted shape (e.g. "mp00c0ffee-1" forwarded
// by the filer meta plane on its upstream hop): not a real client
// trace id — see meta_plane.cc kRecFlagMintedUpstream
constexpr uint32_t kRecFlagMintedUpstream = 2u;

inline uint32_t rid_rec_flags(const char* rid, bool client) {
  if (!client) return 0;
  uint32_t f = kRecFlagClientRid;
  if ((rid[0] == 'm' || rid[0] == 'w' || rid[0] == 'r') &&
      rid[1] == 'p' && rid[2] >= '0' && rid[2] <= '9' &&
      rid[3] >= '0' && rid[3] <= '9')
    f |= kRecFlagMintedUpstream;
  return f;
}

struct PlaneRec {
  char rid[40];
  uint64_t start_unix_ns;
  uint64_t stage_ns[4];    // kRecStageNames order
  uint64_t bytes;
  int64_t deadline_ms;     // -1 = absent
  int32_t status;
  int32_t fallback;        // kRecFallbackNames index
  uint32_t flags;
  uint32_t _pad;
};  // 112 bytes

enum {
  kFbNone = 0,
  kFbNotPlain = 1,
  kFbUnregistered = 2,
  kFbSeenKey = 3,
  kFbJournalFull = 4,
  kFbIoError = 5,
};

// SWFS019 contract: every label below must appear verbatim as a
// string literal in the Python drain table (server/write_plane.py).
const char* const kRecStageNames[] = {"recv", "append", "index", "ack"};
const char* const kRecFallbackNames[] = {
    "none", "not_plain", "unregistered", "seen_key", "journal_full",
    "io_error"};

struct RecRing {
  std::vector<PlaneRec> recs;
  uint64_t cap = 0;
  std::atomic<uint64_t> head{0};
  std::atomic<uint64_t> tail{0};
  std::atomic<uint64_t> dropped{0};
};

uint64_t rec_ring_cap_env() {
  const char* v = getenv("SEAWEEDFS_TPU_PLANE_REC_RING");
  if (v != nullptr && *v != '\0') {
    long n = atol(v);
    if (n >= 16 && n <= (1 << 20)) return uint64_t(n);
  }
  return 4096;
}

void rec_push(RecRing* r, const PlaneRec& rec) {
  if (r->cap == 0) return;
  uint64_t h = r->head.load(std::memory_order_relaxed);
  r->recs[h % r->cap] = rec;
  r->head.store(h + 1, std::memory_order_release);
}

int rec_drain(RecRing* r, PlaneRec* out, int cap) {
  if (r->cap == 0 || out == nullptr || cap <= 0) return 0;
  uint64_t h = r->head.load(std::memory_order_acquire);
  uint64_t t = r->tail.load(std::memory_order_relaxed);
  if (h > t + r->cap) {
    r->dropped.fetch_add((h - r->cap) - t, std::memory_order_relaxed);
    t = h - r->cap;
  }
  int n = 0;
  while (t < h && n < cap) out[n++] = r->recs[t++ % r->cap];
  uint64_t h2 = r->head.load(std::memory_order_acquire);
  uint64_t first = t - uint64_t(n);
  if (h2 > first + r->cap) {   // lapped mid-copy: drop torn prefix
    uint64_t torn = h2 - r->cap - first;
    if (torn > uint64_t(n)) torn = uint64_t(n);
    if (torn > 0) {
      memmove(out, out + torn,
              (size_t(n) - size_t(torn)) * sizeof(PlaneRec));
      n -= int(torn);
      r->dropped.fetch_add(torn, std::memory_order_relaxed);
    }
  }
  r->tail.store(t, std::memory_order_relaxed);
  return n;
}

uint64_t rec_dropped(RecRing* r) {
  uint64_t h = r->head.load(std::memory_order_acquire);
  uint64_t t = r->tail.load(std::memory_order_relaxed);
  uint64_t extra = (r->cap != 0 && h > t + r->cap)
                       ? (h - r->cap) - t : 0;
  return r->dropped.load(std::memory_order_relaxed) + extra;
}

struct VolumeState {
  int fd = -1;
  bool armed = false;   // accepts HTTP writes only after wp_arm
  bool fsync_mode = false;
  std::mutex mu;        // serializes appends (HTTP plane + wp_append)
  uint64_t tail = 0;
  uint64_t last_ns = 0;
  uint64_t cur_epoch = 1;      // open fsync-flush window
  bool epoch_requested = false;
  std::unordered_set<uint64_t> keys;
  std::deque<WpEntry> journal;
};

constexpr size_t kJournalCap = 65536;

struct Conn {
  int fd;
  std::string in;
  std::string out;
  bool close_after = false;
  // request-in-progress state
  bool have_headers = false;
  size_t body_need = 0;        // bytes of body still to receive
  std::string req_headers;     // header block of the pending request
  std::string body;
  uint64_t start_ns = 0;
  // fsync parking
  bool parked = false;
  uint32_t parked_vid = 0;
  uint64_t parked_epoch = 0;
  std::string pending;         // staged response, released by epoch
  // flight-record carry (finalized at ack time)
  char rid[40] = {0};
  bool rid_client = false;
  int64_t deadline_ms = -1;
  uint64_t rec_recv_ns = 0;
  uint64_t rec_append_ns = 0;
  uint64_t rec_index_ns = 0;
  uint64_t rec_bytes = 0;
};

// ack latency histogram bucket bounds, microseconds
constexpr uint64_t kLatBuckets[] = {1,    2,     5,     10,    20,
                                    50,   100,   200,   500,   1000,
                                    2000, 5000,  10000, 20000, 50000,
                                    100000, 1000000};
constexpr int kNumLat = sizeof(kLatBuckets) / sizeof(kLatBuckets[0]);

struct Server {
  int epfd = -1;
  int listen_fd = -1;
  int wake_pipe[2] = {-1, -1};
  std::thread loop;
  std::atomic<bool> stop{false};
  std::atomic<uint64_t> requests{0};
  std::atomic<uint64_t> fallbacks{0};
  std::atomic<uint64_t> lat_count[kNumLat + 1];
  std::atomic<uint64_t> lat_sum_ns{0};
  std::shared_mutex reg_mu;    // guards the volumes map structure
  std::unordered_map<uint32_t, VolumeState*> volumes;
  std::unordered_map<int, Conn*> conns;
  // fsync-epoch handshake (Python side: wp_wait_epoch/wp_epoch_done)
  std::mutex ep_mu;
  std::condition_variable ep_cv;
  std::deque<std::pair<uint32_t, uint64_t>> ep_requests;
  std::deque<std::pair<uint32_t, uint64_t>> ep_done;  // loop applies
  // per-request flight records
  RecRing rec;
  uint64_t rid_seq = 0;        // event-loop thread only
  char rid_prefix[16] = {0};
};

constexpr int kMaxServers = 16;
Server* g_servers[kMaxServers] = {nullptr};
std::mutex g_servers_mu;
std::once_flag g_init_once;

uint64_t now_ns() {
  timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
}

uint64_t mono_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
}

void note_latency(Server* s, uint64_t ns) {
  uint64_t us = ns / 1000;
  int i = 0;
  while (i < kNumLat && us > kLatBuckets[i]) i++;
  s->lat_count[i].fetch_add(1, std::memory_order_relaxed);
  s->lat_sum_ns.fetch_add(ns, std::memory_order_relaxed);
}

// append one flight record framed off the conn; ack = total residual
void rec_emit(Server* s, Conn* c, uint64_t total_ns, int status,
              int fallback) {
  PlaneRec r{};
  snprintf(r.rid, sizeof(r.rid), "%s", c->rid);
  r.start_unix_ns = now_ns() - total_ns;
  r.stage_ns[0] = c->rec_recv_ns;
  r.stage_ns[1] = c->rec_append_ns;
  r.stage_ns[2] = c->rec_index_ns;
  uint64_t sum = c->rec_recv_ns + c->rec_append_ns + c->rec_index_ns;
  r.stage_ns[3] = total_ns > sum ? total_ns - sum : 0;
  r.bytes = c->rec_bytes;
  r.deadline_ms = c->deadline_ms;
  r.status = status;
  r.fallback = fallback;
  r.flags = rid_rec_flags(c->rid, c->rid_client);
  rec_push(&s->rec, r);
}

void set_nonblock(int fd) {
  int fl = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

void close_conn(Server* s, Conn* c) {
  epoll_ctl(s->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
  close(c->fd);
  s->conns.erase(c->fd);
  delete c;
}

void arm(Server* s, Conn* c, bool want_write) {
  epoll_event ev{};
  ev.events = EPOLLIN | (want_write ? EPOLLOUT : 0);
  ev.data.fd = c->fd;
  epoll_ctl(s->epfd, EPOLL_CTL_MOD, c->fd, &ev);
}

// parse "<vid>,<keyhex><cookie8hex>" (read_plane.cc parity)
bool parse_fid(const char* p, size_t n, uint32_t* vid, uint64_t* key,
               uint32_t* cookie) {
  size_t comma = 0;
  while (comma < n && p[comma] != ',') comma++;
  if (comma == 0 || comma >= n) return false;
  uint64_t v = 0;
  for (size_t i = 0; i < comma; i++) {
    if (p[i] < '0' || p[i] > '9') return false;
    v = v * 10 + (p[i] - '0');
    if (v > 0xffffffffULL) return false;
  }
  const char* hex = p + comma + 1;
  size_t hn = n - comma - 1;
  if (hn < 9 || hn > 24) return false;
  uint64_t k = 0;
  uint64_t ck = 0;
  for (size_t i = 0; i < hn; i++) {
    char ch = hex[i];
    int d;
    if (ch >= '0' && ch <= '9') d = ch - '0';
    else if (ch >= 'a' && ch <= 'f') d = ch - 'a' + 10;
    else if (ch >= 'A' && ch <= 'F') d = ch - 'A' + 10;
    else return false;
    if (i < hn - 8) k = (k << 4) | d;
    else ck = (ck << 4) | d;
  }
  *vid = (uint32_t)v;
  *key = k;
  *cookie = (uint32_t)ck;
  return true;
}

void respond(Conn* c, std::string& sink, const char* status,
             const std::string& body) {
  char hdr[160];
  int n = snprintf(hdr, sizeof hdr,
                   "HTTP/1.1 %s\r\n"
                   "Content-Type: application/json\r\n"
                   "Content-Length: %zu\r\n\r\n",
                   status, body.size());
  sink.append(hdr, n);
  sink.append(body);
  (void)c;
}

// case-insensitive header lookup inside a raw header block
std::string header_value(const std::string& block, const char* name) {
  size_t nl = strlen(name);
  size_t pos = 0;
  while (pos < block.size()) {
    size_t eol = block.find("\r\n", pos);
    if (eol == std::string::npos) eol = block.size();
    if (eol - pos > nl + 1 && block[pos + nl] == ':' &&
        strncasecmp(block.data() + pos, name, nl) == 0) {
      size_t v = pos + nl + 1;
      while (v < eol && (block[v] == ' ' || block[v] == '\t')) v++;
      return block.substr(v, eol - v);
    }
    pos = eol + 2;
  }
  return "";
}

// does the query string carry the given key? ("name" in "?name=x&y=z")
bool query_has(const std::string& q, const char* key) {
  size_t kl = strlen(key);
  size_t pos = 0;
  while (pos < q.size()) {
    size_t amp = q.find('&', pos);
    if (amp == std::string::npos) amp = q.size();
    if (amp - pos > kl && q[pos + kl] == '=' &&
        q.compare(pos, kl, key) == 0)
      return true;
    pos = amp + 1;
  }
  return false;
}

uint64_t query_u64(const std::string& q, const char* key) {
  size_t kl = strlen(key);
  size_t pos = 0;
  while (pos < q.size()) {
    size_t amp = q.find('&', pos);
    if (amp == std::string::npos) amp = q.size();
    if (amp - pos > kl && q[pos + kl] == '=' &&
        q.compare(pos, kl, key) == 0) {
      uint64_t v = 0;
      for (size_t i = pos + kl + 1; i < amp; i++) {
        if (q[i] < '0' || q[i] > '9') return 0;
        v = v * 10 + (q[i] - '0');
      }
      return v;
    }
    pos = amp + 1;
  }
  return 0;
}

// serialize + append one plain needle record; returns byte offset or
// -1.  Caller holds NO locks; takes the volume mutex itself.
// On success fills *out (journaled under the same mutex).
bool append_plain(Server* s, VolumeState* vol, uint32_t vid,
                  uint64_t key, uint32_t cookie, const uint8_t* data,
                  size_t len, uint64_t last_modified, WpEntry* out,
                  bool* journal_full, uint64_t* append_ns_out,
                  uint64_t* index_ns_out) {
  uint64_t t_enter = mono_ns();
  // Size field: DataSize(4) + data + flags(1) + lastModified(5)
  int32_t size = (int32_t)(4 + len + 1 + kLastModifiedLen);
  uint32_t crc = crc32c(data, len);
  std::string rec;
  rec.reserve(kHeaderSize + size + kChecksumSize + kTimestampSize +
              kPadding);
  put32(rec, cookie);
  put64(rec, key);
  put32(rec, (uint32_t)size);
  put32(rec, (uint32_t)len);
  rec.append((const char*)data, len);
  rec.push_back((char)kFlagHasLastModified);
  // LastModified: low 5 bytes, big-endian
  char lm[kLastModifiedLen] = {
      (char)(last_modified >> 32), (char)(last_modified >> 24),
      (char)(last_modified >> 16), (char)(last_modified >> 8),
      (char)last_modified};
  rec.append(lm, kLastModifiedLen);
  put32(rec, crc);
  size_t ns_pos = rec.size();      // AppendAtNs patched under the lock
  put64(rec, 0);
  // v3 padding quirk (needle.py to_bytes): pads 8 when aligned, stale
  // bytes re-expose the big-endian Size field then zeros
  size_t pad = kPadding - ((kHeaderSize + (size_t)size +
                            kChecksumSize + kTimestampSize) % kPadding);
  char stale[8] = {(char)((uint32_t)size >> 24),
                   (char)((uint32_t)size >> 16),
                   (char)((uint32_t)size >> 8), (char)(uint32_t)size,
                   0, 0, 0, 0};
  rec.append(stale, pad);

  std::lock_guard<std::mutex> lk(vol->mu);
  if (vol->journal.size() >= kJournalCap) {
    *journal_full = true;
    return false;           // backpressure: fall back to Python
  }
  uint64_t ns = now_ns();
  if (ns <= vol->last_ns) ns = vol->last_ns + 1;
  vol->last_ns = ns;
  char nsb[8] = {(char)(ns >> 56), (char)(ns >> 48), (char)(ns >> 40),
                 (char)(ns >> 32), (char)(ns >> 24), (char)(ns >> 16),
                 (char)(ns >> 8), (char)ns};
  memcpy(&rec[ns_pos], nsb, 8);
  uint64_t off = vol->tail;
  if (off % kPadding) {            // realign a corrupt tail
    size_t fix = kPadding - (off % kPadding);
    char zeros[8] = {0};
    if (pwrite(vol->fd, zeros, fix, (off_t)off) != (ssize_t)fix)
      return false;
    off += fix;
  }
  const char* p = rec.data();
  size_t left = rec.size();
  off_t at = (off_t)off;
  while (left > 0) {
    ssize_t w = pwrite(vol->fd, p, left, at);
    if (w <= 0) {
      if (w < 0 && errno == EINTR) continue;
      return false;                // ENOSPC/EBADF: fall back
    }
    p += w;
    at += w;
    left -= (size_t)w;
  }
  vol->tail = off + rec.size();
  uint64_t t_written = mono_ns();
  vol->keys.insert(key);
  out->key = key;
  out->offset = off;
  out->append_ns = ns;
  out->vid = vid;
  out->cookie = cookie;
  out->size = size;
  out->data_len = (uint32_t)len;
  vol->journal.push_back(*out);
  if (append_ns_out != nullptr) *append_ns_out = t_written - t_enter;
  if (index_ns_out != nullptr) *index_ns_out = mono_ns() - t_written;
  (void)s;
  return true;
}

// handle one complete request (headers in c->req_headers, body in
// c->body).  Appends the response to c->out, or parks it on an fsync
// epoch.  Returns false when the connection must close.
bool handle_request(Server* s, Conn* c) {
  c->rec_recv_ns = mono_ns() - c->start_ns;   // body-receive window
  c->rec_append_ns = 0;
  c->rec_index_ns = 0;
  c->rec_bytes = c->body.size();
  const std::string& req = c->req_headers;
  size_t sp1 = req.find(' ');
  size_t sp2 = (sp1 == std::string::npos) ? std::string::npos
                                          : req.find(' ', sp1 + 1);
  if (sp2 == std::string::npos) return false;
  std::string method = req.substr(0, sp1);
  std::string target = req.substr(sp1 + 1, sp2 - sp1 - 1);
  if (method != "POST" && method != "PUT") {
    respond(c, c->out, "405 Method Not Allowed",
            "{\"error\":\"write plane accepts POST only\"}");
    rec_emit(s, c, mono_ns() - c->start_ns, 405, kFbNotPlain);
    return true;
  }
  std::string query;
  size_t q = target.find('?');
  if (q != std::string::npos) {
    query = target.substr(q + 1);
    target.resize(q);
  }
  uint32_t vid, cookie;
  uint64_t key;
  bool plain = !target.empty() && target[0] == '/' &&
               parse_fid(target.data() + 1, target.size() - 1, &vid,
                         &key, &cookie);
  // non-plain request shapes stay on the Python port: named uploads,
  // real mimes, authenticated writes, replication fan-in
  if (plain) {
    if (query_has(query, "name") || query_has(query, "type")) plain = false;
    std::string ctype = header_value(c->req_headers, "Content-Type");
    if (!ctype.empty() && ctype != "application/octet-stream" &&
        ctype.compare(0, 19, "multipart/form-data") != 0)
      plain = false;
    if (!header_value(c->req_headers, "Authorization").empty())
      plain = false;
    if (c->body.empty()) plain = false;   // 0-byte needles never map
  }
  WpEntry ent{};
  bool parked = false;
  int fb = kFbNotPlain;
  if (plain) {
    std::shared_lock<std::shared_mutex> reg(s->reg_mu);
    auto it = s->volumes.find(vid);
    VolumeState* vol =
        (it == s->volumes.end()) ? nullptr : it->second;
    fb = vol == nullptr ? kFbUnregistered : fb;
    if (vol != nullptr) {
      {
        std::lock_guard<std::mutex> lk(vol->mu);
        // unarmed = registered but keys not yet marked (the attach
        // is mid-handshake): accepting a write here could let an
        // overwrite of an existing key bypass Python's cookie check
        if (!vol->armed || vol->keys.count(key)) {
          vol = nullptr;
          fb = kFbSeenKey;
        }
      }
      if (vol != nullptr) {
        uint64_t ts = query_u64(query, "ts");
        if (ts == 0) ts = now_ns() / 1000000000ull;
        bool journal_full = false;
        if (append_plain(s, vol, vid, key, cookie,
                         (const uint8_t*)c->body.data(),
                         c->body.size(), ts, &ent, &journal_full,
                         &c->rec_append_ns, &c->rec_index_ns)) {
          char body[128];
          int n = snprintf(body, sizeof body,
                           "{\"name\":\"\",\"size\":%zu,"
                           "\"eTag\":\"%08x\",\"unchanged\":false}",
                           c->body.size(),
                           crc32c((const uint8_t*)c->body.data(),
                                  c->body.size()));
          std::string resp;
          respond(c, resp, "201 Created", std::string(body, n));
          s->requests.fetch_add(1, std::memory_order_relaxed);
          if (vol->fsync_mode) {
            // park the ack on the volume's open flush epoch; the
            // Python handshake runs the CommitBarrier and releases it
            std::lock_guard<std::mutex> lk(vol->mu);
            c->parked = true;
            c->parked_vid = vid;
            c->parked_epoch = vol->cur_epoch;
            c->pending = std::move(resp);
            parked = true;
            if (!vol->epoch_requested) {
              vol->epoch_requested = true;
              std::lock_guard<std::mutex> el(s->ep_mu);
              s->ep_requests.emplace_back(vid, vol->cur_epoch);
              s->ep_cv.notify_all();
            }
          } else {
            c->out.append(resp);
            uint64_t total = mono_ns() - c->start_ns;
            note_latency(s, total);
            rec_emit(s, c, total, 201, kFbNone);
          }
          c->body.clear();
          c->body.shrink_to_fit();
          (void)parked;
          return true;
        }
        fb = journal_full ? kFbJournalFull : kFbIoError;
      }
    }
  }
  // fallback: the Python port owns this write
  s->fallbacks.fetch_add(1, std::memory_order_relaxed);
  respond(c, c->out, "404 Not Found",
          "{\"error\":\"write plane fallback\"}");
  rec_emit(s, c, mono_ns() - c->start_ns, 404, fb);
  c->body.clear();
  c->body.shrink_to_fit();
  return true;
}

bool flush_out(Server* s, Conn* c) {
  while (!c->out.empty()) {
    ssize_t n = send(c->fd, c->out.data(), c->out.size(), MSG_NOSIGNAL);
    if (n > 0) {
      c->out.erase(0, (size_t)n);
      continue;
    }
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) return true;
    return false;
  }
  (void)s;
  return true;
}

// consume buffered input into requests; false closes the connection
bool feed(Server* s, Conn* c) {
  for (;;) {
    if (c->parked) return true;  // strictly serial while an ack parks
    if (!c->have_headers) {
      size_t end = c->in.find("\r\n\r\n");
      if (end == std::string::npos)
        return c->in.size() <= (64 << 10);  // header flood guard
      c->req_headers = c->in.substr(0, end);
      c->in.erase(0, end + 4);
      c->have_headers = true;
      c->start_ns = mono_ns();
      std::string rv = header_value(c->req_headers, "X-Request-ID");
      if (!rv.empty()) {
        snprintf(c->rid, sizeof(c->rid), "%.39s", rv.c_str());
        c->rid_client = true;
      } else {
        snprintf(c->rid, sizeof(c->rid), "%s-%llx", s->rid_prefix,
                 (unsigned long long)(++s->rid_seq));
        c->rid_client = false;
      }
      std::string dv =
          header_value(c->req_headers, "X-Weed-Deadline-Ms");
      c->deadline_ms = dv.empty() ? -1 : atoll(dv.c_str());
      std::string te = header_value(c->req_headers,
                                    "Transfer-Encoding");
      if (!te.empty()) return false;       // chunked: Python port
      std::string cl = header_value(c->req_headers, "Content-Length");
      uint64_t need = 0;
      for (char ch : cl) {
        if (ch < '0' || ch > '9') { need = 0; break; }
        need = need * 10 + (uint64_t)(ch - '0');
      }
      if (need > kMaxBody) return false;   // oversized: close
      c->body_need = (size_t)need;
      c->body.clear();
      c->body.reserve(c->body_need);
    }
    if (c->body_need > 0) {
      size_t take = c->in.size() < c->body_need ? c->in.size()
                                                : c->body_need;
      c->body.append(c->in, 0, take);
      c->in.erase(0, take);
      c->body_need -= take;
      if (c->body_need > 0) return true;   // await more body bytes
    }
    c->have_headers = false;
    if (!handle_request(s, c)) return false;
  }
}

void release_epochs(Server* s) {
  std::deque<std::pair<uint32_t, uint64_t>> done;
  {
    std::lock_guard<std::mutex> el(s->ep_mu);
    done.swap(s->ep_done);
  }
  if (done.empty()) return;
  for (auto& kv : s->conns) {
    Conn* c = kv.second;
    if (!c->parked) continue;
    for (auto& d : done) {
      if (c->parked_vid == d.first && c->parked_epoch <= d.second) {
        c->parked = false;
        c->out.append(c->pending);
        c->pending.clear();
        uint64_t total = mono_ns() - c->start_ns;
        note_latency(s, total);
        rec_emit(s, c, total, 201, kFbNone);
        break;
      }
    }
  }
  // a released conn may have both pending output and buffered input
  std::vector<Conn*> dead;
  for (auto& kv : s->conns) {
    Conn* c = kv.second;
    if (c->parked || (c->out.empty() && c->in.empty())) continue;
    bool ok = feed(s, c) && flush_out(s, c);
    if (!ok) dead.push_back(c);
    else arm(s, c, !c->out.empty());
  }
  for (Conn* c : dead) close_conn(s, c);
}

void event_loop(Server* s) {
  epoll_event evs[64];
  while (!s->stop.load(std::memory_order_relaxed)) {
    int n = epoll_wait(s->epfd, evs, 64, 200);
    // NOTE: epoch releases run AFTER the event batch below — a
    // close_conn here could free an fd that accept4 reuses later in
    // the same batch, making a stale evs[] entry poison the fresh
    // connection (the wake pipe guarantees another epoll cycle runs
    // promptly, so releases are not delayed in practice)
    for (int i = 0; i < n; i++) {
      int fd = evs[i].data.fd;
      if (fd == s->wake_pipe[0]) {
        char tmp[16];
        (void)!read(fd, tmp, sizeof tmp);
        continue;
      }
      if (fd == s->listen_fd) {
        for (;;) {
          int cfd = accept4(s->listen_fd, nullptr, nullptr,
                            SOCK_NONBLOCK);
          if (cfd < 0) break;
          int one = 1;
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
          Conn* c = new Conn{cfd};
          s->conns[cfd] = c;
          epoll_event ev{};
          ev.events = EPOLLIN;
          ev.data.fd = cfd;
          epoll_ctl(s->epfd, EPOLL_CTL_ADD, cfd, &ev);
        }
        continue;
      }
      auto it = s->conns.find(fd);
      if (it == s->conns.end()) continue;
      Conn* c = it->second;
      bool dead = false;
      if (evs[i].events & (EPOLLHUP | EPOLLERR)) dead = true;
      if (!dead && (evs[i].events & EPOLLIN)) {
        char buf[65536];
        for (;;) {
          ssize_t r = recv(fd, buf, sizeof buf, 0);
          if (r > 0) {
            c->in.append(buf, (size_t)r);
            continue;
          }
          if (r == 0) {
            dead = c->in.empty() && c->out.empty() && !c->parked;
            c->close_after = true;
            break;
          }
          if (errno == EAGAIN || errno == EWOULDBLOCK) break;
          dead = true;
          break;
        }
        if (!dead && !feed(s, c)) dead = true;
      }
      if (!dead && !flush_out(s, c)) dead = true;
      if (!dead && c->close_after && c->out.empty() && !c->parked)
        dead = true;
      if (dead) close_conn(s, c);
      else arm(s, c, !c->out.empty());
    }
    release_epochs(s);
  }
  for (auto& kv : s->conns) {
    close(kv.second->fd);
    delete kv.second;
  }
  s->conns.clear();
}

Server* get_server(int h) {
  std::lock_guard<std::mutex> lk(g_servers_mu);
  if (h < 0 || h >= kMaxServers) return nullptr;
  return g_servers[h];
}

}  // namespace

extern "C" {

int wp_start(const char* host, int port, int* bound_port) {
  std::call_once(g_init_once, [] {
    crc_init();
#if defined(__x86_64__)
    g_have_sse42 = __builtin_cpu_supports("sse4.2");
#endif
  });
  int slot = -1;
  {
    std::lock_guard<std::mutex> lk(g_servers_mu);
    for (int i = 0; i < kMaxServers; i++) {
      if (g_servers[i] == nullptr) {
        slot = i;
        break;
      }
    }
    if (slot < 0) return -1;
    g_servers[slot] = new Server();
  }
  Server* s = g_servers[slot];
  for (int i = 0; i <= kNumLat; i++) s->lat_count[i].store(0);
  s->rec.cap = rec_ring_cap_env();
  s->rec.recs.resize(s->rec.cap);
  snprintf(s->rid_prefix, sizeof(s->rid_prefix), "wp%02d%06llx", slot,
           (unsigned long long)(now_ns() & 0xffffff));
  s->listen_fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (s->listen_fd < 0) return -1;
  int one = 1;
  setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) return -1;
  if (bind(s->listen_fd, (sockaddr*)&addr, sizeof addr) < 0 ||
      listen(s->listen_fd, 1024) < 0) {
    close(s->listen_fd);
    return -1;
  }
  socklen_t alen = sizeof addr;
  getsockname(s->listen_fd, (sockaddr*)&addr, &alen);
  *bound_port = ntohs(addr.sin_port);
  s->epfd = epoll_create1(0);
  if (pipe2(s->wake_pipe, O_NONBLOCK) < 0) return -1;
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.fd = s->listen_fd;
  epoll_ctl(s->epfd, EPOLL_CTL_ADD, s->listen_fd, &ev);
  ev.data.fd = s->wake_pipe[0];
  epoll_ctl(s->epfd, EPOLL_CTL_ADD, s->wake_pipe[0], &ev);
  s->loop = std::thread(event_loop, s);
  return slot;
}

void wp_stop(int h) {
  Server* s;
  {
    std::lock_guard<std::mutex> lk(g_servers_mu);
    if (h < 0 || h >= kMaxServers || g_servers[h] == nullptr) return;
    s = g_servers[h];
    g_servers[h] = nullptr;
  }
  s->stop.store(true);
  (void)!write(s->wake_pipe[1], "x", 1);
  {
    // unblock a parked wp_wait_epoch
    std::lock_guard<std::mutex> el(s->ep_mu);
    s->ep_cv.notify_all();
  }
  s->loop.join();
  close(s->listen_fd);
  close(s->epfd);
  close(s->wake_pipe[0]);
  close(s->wake_pipe[1]);
  {
    std::unique_lock<std::shared_mutex> lk(s->reg_mu);
    for (auto& kv : s->volumes) {
      if (kv.second->fd >= 0) close(kv.second->fd);
      delete kv.second;
    }
    s->volumes.clear();
  }
  delete s;
}

int wp_add_volume(int h, unsigned vid, const char* dat_path,
                  unsigned long long tail, unsigned long long last_ns,
                  int fsync_mode) {
  Server* s = get_server(h);
  if (s == nullptr) return -1;
  int fd = open(dat_path, O_RDWR);
  if (fd < 0) return -1;
  std::unique_lock<std::shared_mutex> lk(s->reg_mu);
  auto it = s->volumes.find(vid);
  if (it != s->volumes.end()) {
    // refresh: close the stale fd, keep journal drained separately.
    // Disarmed until wp_arm: the caller re-marks the key set first,
    // and a write accepted in between would skip the overwrite check.
    std::lock_guard<std::mutex> vl(it->second->mu);
    if (it->second->fd >= 0) close(it->second->fd);
    it->second->fd = fd;
    it->second->tail = tail;
    it->second->last_ns = last_ns;
    it->second->fsync_mode = fsync_mode != 0;
    it->second->armed = false;
    it->second->keys.clear();
    return 0;
  }
  VolumeState* v = new VolumeState();
  v->fd = fd;
  v->tail = tail;
  v->last_ns = last_ns;
  v->fsync_mode = fsync_mode != 0;
  s->volumes[vid] = v;
  return 0;
}

// open the volume for native HTTP writes — called AFTER wp_mark_keys
// so the seen-key fallback set is complete before the first accept
int wp_arm(int h, unsigned vid) {
  Server* s = get_server(h);
  if (s == nullptr) return -1;
  std::shared_lock<std::shared_mutex> reg(s->reg_mu);
  auto it = s->volumes.find(vid);
  if (it == s->volumes.end()) return -1;
  std::lock_guard<std::mutex> lk(it->second->mu);
  it->second->armed = true;
  return 0;
}

int wp_mark_keys(int h, unsigned vid, const unsigned long long* keys,
                 int n) {
  Server* s = get_server(h);
  if (s == nullptr) return -1;
  std::shared_lock<std::shared_mutex> reg(s->reg_mu);
  auto it = s->volumes.find(vid);
  if (it == s->volumes.end()) return -1;
  std::lock_guard<std::mutex> lk(it->second->mu);
  it->second->keys.reserve(it->second->keys.size() + (size_t)n);
  for (int i = 0; i < n; i++) it->second->keys.insert(keys[i]);
  return 0;
}

void wp_remove_volume(int h, unsigned vid) {
  Server* s = get_server(h);
  if (s == nullptr) return;
  VolumeState* v = nullptr;
  {
    std::unique_lock<std::shared_mutex> lk(s->reg_mu);
    auto it = s->volumes.find(vid);
    if (it == s->volumes.end()) return;
    v = it->second;
    s->volumes.erase(it);
  }
  // every in-flight append/drain holds reg_mu shared across its
  // volume-mutex window; the unique_lock above waited them out, so v
  // is exclusively ours now
  std::deque<WpEntry> leftover;
  {
    std::lock_guard<std::mutex> lk(v->mu);
    if (v->fd >= 0) close(v->fd);
    v->fd = -1;
    leftover.swap(v->journal);
  }
  if (leftover.empty()) {
    delete v;
    return;
  }
  // undrained journal entries must stay reachable (they are .idx
  // records Python has not applied yet): park them in an orphan slot
  // (high bit set — the wrapper never registers vids that large)
  std::unique_lock<std::shared_mutex> lk(s->reg_mu);
  auto ins = s->volumes.emplace((unsigned)0x80000000u | vid, v);
  if (!ins.second) {
    // an orphan from an earlier detach still drains: append there
    std::lock_guard<std::mutex> ol(ins.first->second->mu);
    for (auto& e : leftover) ins.first->second->journal.push_back(e);
    delete v;
  } else {
    std::lock_guard<std::mutex> vl(v->mu);
    v->journal.swap(leftover);
  }
}

// append a fully-serialized record from the Python side (replication,
// tombstones, overwrites, raw repair writes).  Returns the byte
// offset, or -1 when the volume is not registered / write failed.
long long wp_append(int h, unsigned vid, unsigned long long key,
                    const unsigned char* rec, unsigned long long len,
                    unsigned long long append_ns) {
  Server* s = get_server(h);
  if (s == nullptr) return -1;
  std::shared_lock<std::shared_mutex> reg(s->reg_mu);
  auto it = s->volumes.find(vid);
  if (it == s->volumes.end() || it->second->fd < 0) return -1;
  VolumeState* v = it->second;
  std::lock_guard<std::mutex> lk(v->mu);
  uint64_t off = v->tail;
  if (off % kPadding) {
    size_t fix = kPadding - (off % kPadding);
    char zeros[8] = {0};
    if (pwrite(v->fd, zeros, fix, (off_t)off) != (ssize_t)fix)
      return -1;
    off += fix;
  }
  const unsigned char* p = rec;
  size_t left = (size_t)len;
  off_t at = (off_t)off;
  while (left > 0) {
    ssize_t w = pwrite(v->fd, p, left, at);
    if (w <= 0) {
      if (w < 0 && errno == EINTR) continue;
      return -1;
    }
    p += w;
    at += w;
    left -= (size_t)w;
  }
  v->tail = off + len;
  v->keys.insert(key);
  if (append_ns > v->last_ns) v->last_ns = append_ns;
  return (long long)off;
}

int wp_drain(int h, unsigned vid, WpEntry* out, int cap) {
  Server* s = get_server(h);
  if (s == nullptr) return 0;
  int n = 0;
  bool orphan_drained = false;
  for (unsigned slot : {vid, 0x80000000u | vid}) {
    // hold reg_mu shared across the volume-mutex window — the remove
    // path's unique_lock is what guarantees v stays alive here
    std::shared_lock<std::shared_mutex> reg(s->reg_mu);
    auto it = s->volumes.find(slot);
    if (it == s->volumes.end()) continue;
    VolumeState* v = it->second;
    std::lock_guard<std::mutex> lk(v->mu);
    while (n < cap && !v->journal.empty()) {
      out[n++] = v->journal.front();
      v->journal.pop_front();
    }
    if ((slot & 0x80000000u) && v->journal.empty())
      orphan_drained = true;
  }
  if (orphan_drained) {
    // reap the empty orphan under the exclusive registry lock (same
    // lock order as remove: reg_mu then volume mutex)
    std::unique_lock<std::shared_mutex> reg(s->reg_mu);
    auto it = s->volumes.find(0x80000000u | vid);
    if (it != s->volumes.end()) {
      VolumeState* v = it->second;
      bool empty;
      {
        std::lock_guard<std::mutex> lk(v->mu);
        empty = v->journal.empty();
      }
      if (empty) {
        s->volumes.erase(it);
        delete v;
      }
    }
  }
  return n;
}

int wp_pending(int h, unsigned vid) {
  Server* s = get_server(h);
  if (s == nullptr) return 0;
  int n = 0;
  for (unsigned slot : {vid, 0x80000000u | vid}) {
    std::shared_lock<std::shared_mutex> reg(s->reg_mu);
    auto it = s->volumes.find(slot);
    if (it == s->volumes.end()) continue;
    std::lock_guard<std::mutex> lk(it->second->mu);
    n += (int)it->second->journal.size();
  }
  return n;
}

unsigned long long wp_tail(int h, unsigned vid) {
  Server* s = get_server(h);
  if (s == nullptr) return 0;
  std::shared_lock<std::shared_mutex> reg(s->reg_mu);
  auto it = s->volumes.find(vid);
  if (it == s->volumes.end()) return 0;
  std::lock_guard<std::mutex> lk(it->second->mu);
  return it->second->tail;
}

// fsync-epoch handshake: block (up to timeout_ms) for a flush request,
// returning 1 with (*vid, *epoch) filled, 0 on timeout/stop.
int wp_wait_epoch(int h, int timeout_ms, unsigned* vid,
                  unsigned long long* epoch) {
  Server* s = get_server(h);
  if (s == nullptr) return 0;
  std::unique_lock<std::mutex> lk(s->ep_mu);
  if (!s->ep_cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                         [s] { return !s->ep_requests.empty() ||
                                      s->stop.load(); }))
    return 0;
  if (s->ep_requests.empty()) return 0;
  auto req = s->ep_requests.front();
  s->ep_requests.pop_front();
  lk.unlock();
  // close the volume's epoch window so later appends park on the next
  {
    std::shared_lock<std::shared_mutex> reg(s->reg_mu);
    auto it = s->volumes.find(req.first);
    if (it != s->volumes.end()) {
      std::lock_guard<std::mutex> vl(it->second->mu);
      if (it->second->cur_epoch == req.second) {
        it->second->cur_epoch = req.second + 1;
        it->second->epoch_requested = false;
      }
    }
  }
  *vid = req.first;
  *epoch = req.second;
  return 1;
}

void wp_epoch_done(int h, unsigned vid, unsigned long long epoch) {
  Server* s = get_server(h);
  if (s == nullptr) return;
  {
    std::lock_guard<std::mutex> el(s->ep_mu);
    s->ep_done.emplace_back(vid, epoch);
  }
  (void)!write(s->wake_pipe[1], "x", 1);
}

unsigned long long wp_requests(int h) {
  Server* s = get_server(h);
  return s == nullptr ? 0 : s->requests.load();
}

unsigned long long wp_fallbacks(int h) {
  Server* s = get_server(h);
  return s == nullptr ? 0 : s->fallbacks.load();
}

// latency snapshot: out[0..17] = cumulative bucket counts (le 1us..1s,
// +inf), out[18] = total acks, out[19] = sum of ack ns
int wp_latency(int h, unsigned long long* out) {
  Server* s = get_server(h);
  if (s == nullptr) return 0;
  uint64_t total = 0;
  for (int i = 0; i <= kNumLat; i++) {
    total += s->lat_count[i].load(std::memory_order_relaxed);
    out[i] = total;          // cumulative, Prometheus-style
  }
  out[kNumLat + 1] = total;
  out[kNumLat + 2] = s->lat_sum_ns.load(std::memory_order_relaxed);
  return kNumLat + 1;        // bucket cells written
}

// drain up to `cap` per-request flight records (oldest first; the
// Python side serializes drainers with a lock)
int wp_drain_records(int h, PlaneRec* out, int cap) {
  Server* s = get_server(h);
  if (s == nullptr) return -1;
  return rec_drain(&s->rec, out, cap);
}

unsigned long long wp_records_dropped(int h) {
  Server* s = get_server(h);
  return s != nullptr ? rec_dropped(&s->rec) : 0;
}

}  // extern "C"
