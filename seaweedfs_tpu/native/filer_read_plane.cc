// Native filer READ plane (ISSUE 19) — the read sibling of
// meta_plane.cc: a single-threaded epoll HTTP front that serves the
// filer's hot warm-read path with ZERO Python per request:
//
//   HTTP parse -> eligibility -> C-side entry-map lookup (path ->
//   volume read-plane addr + fid + size + mime) -> chunk fetch over a
//   persistent keep-alive plane socket (plane_pool.h, C++->C++
//   against the volume's read_plane.cc) -> 200 stream to the client.
//
// The entry map is ADVISORY knowledge fed from Python exactly like
// the meta plane's directory truth: the filer's own mutation events
// (Filer.subscribe listener) and every sibling writer's WAL lines
// (the meta plane's follower tap) INVALIDATE the touched path
// synchronously — before the writer's ack returns — so overwrite /
// delete coherence is exact: the map can only under-serve (fallback),
// never serve a pre-mutation chunk.  Fills arrive asynchronously
// (event fills + lazy warm fills from the Python read path) and are
// fenced by a generation counter: a fill whose token pre-dates the
// path's latest invalidation is refused (the meta-cache begin_fill
// protocol, C edition).
//
// Anything the hot path cannot prove cheap and exact — multi-chunk,
// ranged, TTL'd, content-encoded, unknown path, query string, auth,
// disarmed — answers 404 {"error":"read plane fallback"} and the
// client replays against the Python filer port (the PR 11/17 fallback
// contract, verbatim).  The full response is BUFFERED before the
// status line is written, so a client never sees a 200 that framed a
// Content-Length it won't receive: an upstream failure after dispatch
// still degrades to the clean 404 fallback, and a SIGKILL tears the
// connection without ever having promised bytes.

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#include <atomic>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "plane_pool.h"

namespace {

constexpr int kMaxServers = 16;
constexpr size_t kMaxBody = 64 * 1024;    // GETs carry no real body
constexpr size_t kMaxHeaders = 64 * 1024;
constexpr size_t kMaxPath = 512;
constexpr size_t kMaxEntries = 65536;     // entry-map overflow => clear

uint64_t now_ns() {
  timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return uint64_t(ts.tv_sec) * 1000000000ull + uint64_t(ts.tv_nsec);
}

uint64_t mono_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return uint64_t(ts.tv_sec) * 1000000000ull + uint64_t(ts.tv_nsec);
}

// response latency buckets, mirroring the meta plane's ack histogram
// (server/filer_read_plane_native.py RESPONSE_BUCKETS_S) — stored in
// MICROseconds
const uint64_t kLatBuckets[] = {1,      2,      5,      10,     20,
                                50,     100,    200,    500,    1000,
                                2000,   5000,   10000,  20000,  50000,
                                100000, 1000000};
constexpr int kLatN = 17;

// -- per-request flight records (ISSUE 18 wire format) ----------------

constexpr uint32_t kRecFlagClientRid = 1u;
constexpr uint32_t kRecFlagMintedUpstream = 2u;

inline uint32_t rid_rec_flags(const char* rid, bool client) {
  if (!client) return 0;
  uint32_t f = kRecFlagClientRid;
  if ((rid[0] == 'm' || rid[0] == 'w' || rid[0] == 'r') &&
      rid[1] == 'p' && rid[2] >= '0' && rid[2] <= '9' &&
      rid[3] >= '0' && rid[3] <= '9')
    f |= kRecFlagMintedUpstream;
  return f;
}

struct PlaneRec {
  char rid[40];
  uint64_t start_unix_ns;
  uint64_t stage_ns[4];    // kRecStageNames order
  uint64_t bytes;          // response body size
  int64_t deadline_ms;
  int32_t status;
  int32_t fallback;
  uint32_t flags;
  uint32_t _pad;
};  // 112 bytes, mirrored by native.PlaneRecord (ctypes)

enum {
  kFbNone = 0,
  kFbIneligible = 1,
  kFbUnknownPath = 2,
  kFbStale = 3,
  kFbUpstream = 4,
};

// SWFS019 contract: every label below must appear verbatim as a
// string literal in the Python drain table
// (server/filer_read_plane_native.py) — devtools lint cross-checks.
const char* const kRecStageNames[] = {"parse", "lookup", "fetch",
                                      "send"};
const char* const kRecFallbackNames[] = {
    "none", "ineligible", "unknown_path", "stale", "upstream"};
const char* const kStatsNames[] = {
    "requests", "fallbacks", "stale_misses", "upstream_errors",
    "parse_ns", "lookup_ns", "fetch_ns", "send_ns"};

struct RecRing {
  std::vector<PlaneRec> recs;
  uint64_t cap = 0;
  std::atomic<uint64_t> head{0};
  std::atomic<uint64_t> tail{0};
  std::atomic<uint64_t> dropped{0};
};

uint64_t rec_ring_cap_env() {
  const char* v = getenv("SEAWEEDFS_TPU_PLANE_REC_RING");
  if (v != nullptr && *v != '\0') {
    long n = atol(v);
    if (n >= 16 && n <= (1 << 20)) return uint64_t(n);
  }
  return 4096;
}

void rec_push(RecRing* r, const PlaneRec& rec) {
  if (r->cap == 0) return;
  uint64_t h = r->head.load(std::memory_order_relaxed);
  r->recs[h % r->cap] = rec;
  r->head.store(h + 1, std::memory_order_release);
}

int rec_drain(RecRing* r, PlaneRec* out, int cap) {
  if (r->cap == 0 || out == nullptr || cap <= 0) return 0;
  uint64_t h = r->head.load(std::memory_order_acquire);
  uint64_t t = r->tail.load(std::memory_order_relaxed);
  if (h > t + r->cap) {
    r->dropped.fetch_add((h - r->cap) - t, std::memory_order_relaxed);
    t = h - r->cap;
  }
  int n = 0;
  while (t < h && n < cap) out[n++] = r->recs[t++ % r->cap];
  // drop the torn prefix if the producer lapped the slots mid-copy
  uint64_t h2 = r->head.load(std::memory_order_acquire);
  uint64_t first = t - uint64_t(n);
  if (h2 > first + r->cap) {
    uint64_t torn = h2 - r->cap - first;
    if (torn > uint64_t(n)) torn = uint64_t(n);
    if (torn > 0) {
      memmove(out, out + torn,
              (size_t(n) - size_t(torn)) * sizeof(PlaneRec));
      n -= int(torn);
      r->dropped.fetch_add(torn, std::memory_order_relaxed);
    }
  }
  r->tail.store(t, std::memory_order_relaxed);
  return n;
}

uint64_t rec_dropped(RecRing* r) {
  uint64_t h = r->head.load(std::memory_order_acquire);
  uint64_t t = r->tail.load(std::memory_order_relaxed);
  uint64_t extra = (r->cap != 0 && h > t + r->cap)
                       ? (h - r->cap) - t : 0;
  return r->dropped.load(std::memory_order_relaxed) + extra;
}

// -- connection / request state ---------------------------------------

struct Conn {
  int fd = -1;
  uint64_t gen = 0;           // guards responses against fd reuse
  std::string in;
  std::string out;
  bool have_headers = false;
  size_t header_end = 0;
  size_t body_need = 0;
  std::string method;
  std::string target;
  std::string req_headers;
  std::string body;
  uint64_t req_start_ns = 0;  // CLOCK_MONOTONIC, first byte of request
  int inflight = 0;           // parked on an upstream fetch
  bool close_after = false;
  bool want_write = false;
  char rid[40] = {0};
  bool rid_client = false;
  int64_t deadline_ms = -1;
};

// one native fetch in flight against the volume read plane
struct Pending {
  int client_fd = -1;
  uint64_t client_gen = 0;
  std::string path;
  std::string mime;           // resolved Content-Type for the client
  uint64_t size = 0;          // registered chunk size (must match)
  uint64_t start_mono = 0;    // request first byte
  uint64_t lookup_mono = 0;   // parse done -> map lookup begins
  uint64_t dispatch_mono = 0; // lookup done -> upstream queued
  uint64_t enq_mono = 0;      // plane_pool timeout clock
  char rid[40] = {0};
  uint32_t rid_flags = 0;
  int64_t deadline_ms = -1;
};

using Upstream = plane_pool::Upstream<Pending>;

// one servable warm entry: exactly one plain chunk, whole-file, known
// geometry.  `gen` fences fills against later invalidations; a
// tombstone (valid=false) keeps the fence alive after invalidation.
struct EntryRec {
  std::string addr;   // volume read-plane host:port
  std::string fid;    // "vid,hexkeycookie"
  std::string mime;   // response Content-Type (resolved in Python)
  uint64_t size = 0;
  uint64_t gen = 0;   // stamp of the latest invalidation
  bool valid = false;
};

struct Server {
  int epfd = -1;
  int listen_fd = -1;
  int wake_pipe[2] = {-1, -1};
  std::thread loop;
  std::atomic<bool> stop{false};
  std::atomic<bool> armed{false};

  std::mutex entry_mu;
  std::unordered_map<std::string, EntryRec> entries;
  std::atomic<uint64_t> gen{0};       // invalidation generation clock
  uint64_t clear_gen = 0;             // gen at the last wholesale clear

  std::unordered_map<int, Conn> conns;
  plane_pool::Pool<Pending> pool;     // volume read-plane connections
  uint64_t gen_counter = 0;           // conn fd-reuse guard

  // telemetry (atomics: read from Python threads)
  std::atomic<uint64_t> requests{0};       // native 200s served
  std::atomic<uint64_t> fallbacks{0};      // 404 handoffs
  std::atomic<uint64_t> stale_misses{0};   // volume plane said 404
  std::atomic<uint64_t> upstream_errors{0};
  std::atomic<uint64_t> parse_ns{0};
  std::atomic<uint64_t> lookup_ns{0};
  std::atomic<uint64_t> fetch_ns{0};
  std::atomic<uint64_t> send_ns{0};
  std::atomic<uint64_t> lat_count[kLatN + 1];
  std::atomic<uint64_t> lat_sum_ns{0};

  RecRing rec;
  std::atomic<int> fetch_delay_ms{0};  // chaos/flight-deck failpoint
  uint64_t rid_seq = 0;
  char rid_prefix[16] = {0};

  Server() {
    for (int i = 0; i <= kLatN; i++) lat_count[i] = 0;
  }
};

std::mutex g_servers_mu;
Server* g_servers[kMaxServers];
std::once_flag g_init_once;

void global_init() {
  for (int i = 0; i < kMaxServers; i++) g_servers[i] = nullptr;
  signal(SIGPIPE, SIG_IGN);
}

Server* get_server(int h) {
  if (h < 0 || h >= kMaxServers) return nullptr;
  std::lock_guard<std::mutex> lk(g_servers_mu);
  return g_servers[h];
}

// -- epoll / HTTP plumbing (meta_plane.cc idiom) ----------------------

void conn_arm(Server* s, Conn* c, bool want_write) {
  if (c->want_write == want_write) return;
  c->want_write = want_write;
  epoll_event ev{};
  ev.events = EPOLLIN | (want_write ? EPOLLOUT : 0u);
  ev.data.fd = c->fd;
  epoll_ctl(s->epfd, EPOLL_CTL_MOD, c->fd, &ev);
}

void close_conn(Server* s, int fd) {
  auto it = s->conns.find(fd);
  if (it == s->conns.end()) return;
  epoll_ctl(s->epfd, EPOLL_CTL_DEL, fd, nullptr);
  close(fd);
  s->conns.erase(it);
}

std::string header_value(const std::string& headers, const char* name) {
  size_t nlen = strlen(name);
  size_t pos = 0;
  while (pos < headers.size()) {
    size_t eol = headers.find("\r\n", pos);
    if (eol == std::string::npos) eol = headers.size();
    if (eol - pos > nlen && headers[pos + nlen] == ':' &&
        strncasecmp(headers.c_str() + pos, name, nlen) == 0) {
      size_t v = pos + nlen + 1;
      while (v < eol && (headers[v] == ' ' || headers[v] == '\t')) v++;
      return headers.substr(v, eol - v);
    }
    pos = eol + 2;
  }
  return "";
}

bool has_header(const std::string& headers, const char* name) {
  size_t nlen = strlen(name);
  size_t pos = 0;
  while (pos < headers.size()) {
    size_t eol = headers.find("\r\n", pos);
    if (eol == std::string::npos) eol = headers.size();
    if (eol - pos > nlen && headers[pos + nlen] == ':' &&
        strncasecmp(headers.c_str() + pos, name, nlen) == 0)
      return true;
    pos = eol + 2;
  }
  return false;
}

void respond_json(Server* s, Conn* c, int code, const char* reason,
                  const std::string& body) {
  char head[256];
  int n = snprintf(head, sizeof(head),
                   "HTTP/1.1 %d %s\r\n"
                   "Content-Type: application/json\r\n"
                   "Content-Length: %zu\r\n"
                   "%s"
                   "\r\n",
                   code, reason, body.size(),
                   c->close_after ? "Connection: close\r\n" : "");
  c->out.append(head, size_t(n));
  c->out.append(body);
  conn_arm(s, c, true);
}

void respond_fallback(Server* s, Conn* c) {
  s->fallbacks.fetch_add(1, std::memory_order_relaxed);
  respond_json(s, c, 404, "Not Found",
               "{\"error\":\"read plane fallback\"}");
}

// the 200: mirror the Python front's header set for an eligible read
// (Content-Type + Content-Length) so plane-vs-python responses are
// interchangeable byte-for-byte in the body and equivalent on the
// wire.  The FULL body is already in hand — the framing promise is
// kept or never made.
void respond_data(Server* s, Conn* c, const std::string& mime,
                  const std::string& body) {
  char head[256];
  int n = snprintf(head, sizeof(head),
                   "HTTP/1.1 200 OK\r\n"
                   "Content-Type: %s\r\n"
                   "Content-Length: %zu\r\n"
                   "%s"
                   "\r\n",
                   mime.empty() ? "application/octet-stream"
                                : mime.c_str(),
                   body.size(),
                   c->close_after ? "Connection: close\r\n" : "");
  c->out.append(head, size_t(n));
  c->out.append(body);
  conn_arm(s, c, true);
}

void rec_emit(Server* s, const char* rid, uint32_t flags,
              int64_t deadline_ms, uint64_t total_ns, uint64_t parse,
              uint64_t lookup, uint64_t fetch, uint64_t bytes,
              int status, int fallback) {
  PlaneRec r{};
  snprintf(r.rid, sizeof(r.rid), "%s", rid);
  r.start_unix_ns = now_ns() - total_ns;
  r.stage_ns[0] = parse;
  r.stage_ns[1] = lookup;
  r.stage_ns[2] = fetch;
  uint64_t sum = parse + lookup + fetch;
  r.stage_ns[3] = total_ns > sum ? total_ns - sum : 0;
  r.bytes = bytes;
  r.deadline_ms = deadline_ms;
  r.status = status;
  r.fallback = fallback;
  r.flags = flags;
  rec_push(&s->rec, r);
}

void rec_emit_conn(Server* s, Conn* c, int status, int fallback) {
  uint64_t total =
      c->req_start_ns != 0 ? mono_ns() - c->req_start_ns : 0;
  rec_emit(s, c->rid, rid_rec_flags(c->rid, c->rid_client),
           c->deadline_ms, total, total, 0, 0, 0, status, fallback);
}

void rec_emit_pending(Server* s, const Pending& p, uint64_t bytes,
                      int status, int fallback) {
  uint64_t now = mono_ns();
  rec_emit(s, p.rid, p.rid_flags, p.deadline_ms, now - p.start_mono,
           p.lookup_mono - p.start_mono,
           p.dispatch_mono - p.lookup_mono, now - p.dispatch_mono,
           bytes, status, fallback);
}

// the exact byte set the Python dispatcher would pass through
// untransformed: printable ASCII minus quote, backslash, percent
// (urllib.unquote), query/fragment markers
bool path_bytes_ok(const std::string& p) {
  for (unsigned char ch : p) {
    if (ch < 0x21 || ch > 0x7E) return false;
    if (ch == '"' || ch == '\\' || ch == '%' || ch == '?' ||
        ch == '#')
      return false;
  }
  return true;
}

void record_latency(Server* s, uint64_t ns) {
  uint64_t us = ns / 1000;
  int i = 0;
  while (i < kLatN && us > kLatBuckets[i]) i++;
  s->lat_count[i].fetch_add(1, std::memory_order_relaxed);
  s->lat_sum_ns.fetch_add(ns, std::memory_order_relaxed);
}

void client_feed(Server* s, Conn* c);

void flush_client(Server* s, int fd) {
  auto it = s->conns.find(fd);
  if (it == s->conns.end()) return;
  Conn* c = &it->second;
  while (!c->out.empty()) {
    ssize_t n = send(c->fd, c->out.data(), c->out.size(), MSG_NOSIGNAL);
    if (n > 0) {
      c->out.erase(0, size_t(n));
      continue;
    }
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      conn_arm(s, c, true);
      return;
    }
    close_conn(s, fd);
    return;
  }
  if (c->close_after) {
    close_conn(s, fd);
    return;
  }
  conn_arm(s, c, false);
  if (c->inflight == 0 && !c->in.empty()) client_feed(s, c);
}

// invalidate `path` from the event-loop side (a stale fetch proved
// the registration wrong) — same fencing as frp_invalidate
void invalidate_entry(Server* s, const std::string& path) {
  std::lock_guard<std::mutex> lk(s->entry_mu);
  uint64_t g = s->gen.fetch_add(1, std::memory_order_relaxed) + 1;
  if (s->entries.size() >= kMaxEntries &&
      s->entries.find(path) == s->entries.end()) {
    s->entries.clear();
    s->clear_gen = g;
    return;
  }
  EntryRec& rec = s->entries[path];
  rec.valid = false;
  rec.gen = g;
  rec.addr.clear();
  rec.fid.clear();
  rec.mime.clear();
  rec.size = 0;
}

// -- request handling -------------------------------------------------

void dispatch_fetch(Server* s, Conn* c, const EntryRec& rec,
                    uint64_t lookup_mono) {
  Pending p;
  p.client_fd = c->fd;
  p.client_gen = c->gen;
  p.path = c->target;
  p.mime = rec.mime;
  p.size = rec.size;
  p.start_mono = c->req_start_ns;
  p.lookup_mono = lookup_mono;
  p.dispatch_mono = mono_ns();
  p.enq_mono = p.dispatch_mono;
  // failpoint: stall the volume fetch hop (chaos tests widen the
  // in-flight window with this before delivering SIGKILL)
  int delay = s->fetch_delay_ms.load(std::memory_order_relaxed);
  if (delay > 0) usleep(useconds_t(delay) * 1000);
  memcpy(p.rid, c->rid, sizeof(p.rid));
  p.rid_flags = rid_rec_flags(c->rid, c->rid_client);
  p.deadline_ms = c->deadline_ms;
  s->parse_ns.fetch_add(lookup_mono - c->req_start_ns,
                        std::memory_order_relaxed);
  s->lookup_ns.fetch_add(p.dispatch_mono - lookup_mono,
                         std::memory_order_relaxed);
  Upstream* u = s->pool.pick(rec.addr);
  if (u == nullptr) {
    s->upstream_errors.fetch_add(1, std::memory_order_relaxed);
    rec_emit_conn(s, c, 404, kFbUpstream);
    respond_fallback(s, c);
    return;
  }
  // forward the request id + remaining deadline so the volume plane's
  // flight record stitches into the same trace
  char dlbuf[48];
  dlbuf[0] = '\0';
  if (c->deadline_ms >= 0) {
    long long elapsed_ms =
        (long long)((p.dispatch_mono - p.start_mono) / 1000000ull);
    long long left = (long long)c->deadline_ms - elapsed_ms;
    if (left < 1) left = 1;
    snprintf(dlbuf, sizeof(dlbuf), "X-Weed-Deadline-Ms: %lld\r\n",
             left);
  }
  char head[384];
  int n = snprintf(head, sizeof(head),
                   "GET /%s HTTP/1.1\r\n"
                   "Host: %s\r\n"
                   "X-Request-ID: %s\r\n"
                   "%s"
                   "\r\n",
                   rec.fid.c_str(), rec.addr.c_str(), c->rid, dlbuf);
  u->out.append(head, size_t(n));
  u->inflight.push_back(std::move(p));
  c->inflight = 1;
  // eager flush (plane_pool.h): no epoll round trip on the hot hop
  s->pool.flush(u);
}

void handle_request(Server* s, Conn* c) {
  const std::string& t = c->target;
  bool eligible =
      s->armed.load(std::memory_order_relaxed) && c->method == "GET" &&
      !t.empty() && t[0] == '/' && t.size() < kMaxPath &&
      t.back() != '/' && t.find("//") == std::string::npos &&
      t.compare(0, 3, "/__") != 0 && path_bytes_ok(t) &&
      c->body.empty();
  if (eligible) {
    // anything that changes the RESPONSE (ranges, conditionals,
    // auth-derived denial, tenant QoS) stays with Python
    if (has_header(c->req_headers, "Range") ||
        has_header(c->req_headers, "Authorization") ||
        has_header(c->req_headers, "Expect") ||
        has_header(c->req_headers, "If-None-Match") ||
        has_header(c->req_headers, "If-Modified-Since") ||
        has_header(c->req_headers, "X-Tenant"))
      eligible = false;
  }
  if (!eligible) {
    c->body.clear();
    rec_emit_conn(s, c, 404, kFbIneligible);
    respond_fallback(s, c);
    return;
  }
  uint64_t lookup_mono = mono_ns();
  EntryRec rec;
  bool found = false;
  {
    std::lock_guard<std::mutex> lk(s->entry_mu);
    auto it = s->entries.find(t);
    if (it != s->entries.end() && it->second.valid) {
      rec = it->second;   // copy out: the fetch outlives the lock
      found = true;
    }
  }
  if (!found) {
    rec_emit_conn(s, c, 404, kFbUnknownPath);
    respond_fallback(s, c);
    return;
  }
  dispatch_fetch(s, c, rec, lookup_mono);
}

void client_feed(Server* s, Conn* c) {
  for (;;) {
    if (c->inflight > 0) return;   // parked on an upstream fetch
    if (!c->have_headers) {
      size_t he = c->in.find("\r\n\r\n");
      if (he == std::string::npos) {
        if (c->in.size() > kMaxHeaders) close_conn(s, c->fd);
        return;
      }
      if (c->req_start_ns == 0) c->req_start_ns = mono_ns();
      size_t eol = c->in.find("\r\n");
      std::string req_line = c->in.substr(0, eol);
      c->req_headers = c->in.substr(eol + 2, he - eol - 2);
      size_t sp1 = req_line.find(' ');
      size_t sp2 =
          sp1 == std::string::npos ? sp1 : req_line.find(' ', sp1 + 1);
      if (sp1 == std::string::npos || sp2 == std::string::npos) {
        close_conn(s, c->fd);
        return;
      }
      c->method = req_line.substr(0, sp1);
      c->target = req_line.substr(sp1 + 1, sp2 - sp1 - 1);
      std::string rv = header_value(c->req_headers, "X-Request-ID");
      if (!rv.empty()) {
        snprintf(c->rid, sizeof(c->rid), "%.39s", rv.c_str());
        c->rid_client = true;
      } else {
        snprintf(c->rid, sizeof(c->rid), "%s-%llx", s->rid_prefix,
                 static_cast<unsigned long long>(++s->rid_seq));
        c->rid_client = false;
      }
      std::string dv =
          header_value(c->req_headers, "X-Weed-Deadline-Ms");
      c->deadline_ms = dv.empty() ? -1 : atoll(dv.c_str());
      c->close_after =
          strcasecmp(
              header_value(c->req_headers, "Connection").c_str(),
              "close") == 0;
      std::string te =
          header_value(c->req_headers, "Transfer-Encoding");
      std::string cl = header_value(c->req_headers, "Content-Length");
      long long need = cl.empty() ? 0 : atoll(cl.c_str());
      if (!te.empty() || need < 0 || size_t(need) > kMaxBody) {
        // framing we won't parse on a read plane — refuse and close
        c->close_after = true;
        rec_emit_conn(s, c, 404, kFbIneligible);
        respond_fallback(s, c);
        flush_client(s, c->fd);
        return;
      }
      c->body_need = size_t(need);
      c->have_headers = true;
      c->in.erase(0, he + 4);
    }
    if (c->in.size() < c->body_need) return;
    c->body = c->in.substr(0, c->body_need);
    c->in.erase(0, c->body_need);
    c->have_headers = false;
    c->body_need = 0;
    handle_request(s, c);
    auto it = s->conns.find(c->fd);
    if (it == s->conns.end() || &it->second != c) return;
    c->req_start_ns = 0;
    if (c->inflight == 0 && !c->out.empty()) {
      flush_client(s, c->fd);
      it = s->conns.find(c->fd);
      if (it == s->conns.end()) return;
    }
  }
}

// one dropped in-flight fetch (conn error / timeout), handed back by
// the pool: the waiting client falls back to Python
void ups_drop_pending(Server* s, Pending& p) {
  s->upstream_errors.fetch_add(1, std::memory_order_relaxed);
  rec_emit_pending(s, p, 0, 404, kFbUpstream);
  auto it = s->conns.find(p.client_fd);
  if (it == s->conns.end() || it->second.gen != p.client_gen) return;
  it->second.inflight = 0;
  it->second.req_start_ns = 0;
  respond_fallback(s, &it->second);
  flush_client(s, p.client_fd);
}

// parse one complete volume-plane response off u->in; false = need
// more bytes
bool ups_feed_one(Server* s, Upstream* u) {
  if (!u->have_headers) {
    size_t he = u->in.find("\r\n\r\n");
    if (he == std::string::npos) return false;
    int status = 0;
    if (u->in.size() > 12 && u->in.compare(0, 5, "HTTP/") == 0)
      status = atoi(u->in.c_str() + 9);
    u->status = status;
    std::string head = u->in.substr(0, he);
    std::string cl = header_value(head, "Content-Length");
    u->body_need = cl.empty() ? 0 : size_t(atoll(cl.c_str()));
    u->have_headers = true;
    u->in.erase(0, he + 4);
  }
  if (u->in.size() < u->body_need) return false;
  std::string body = u->in.substr(0, u->body_need);
  u->in.erase(0, u->body_need);
  u->have_headers = false;
  int status = u->status;
  u->status = 0;
  u->body_need = 0;
  if (u->inflight.empty()) return true;   // stray; resync on close
  Pending p = std::move(u->inflight.front());
  u->inflight.pop_front();
  uint64_t t_fetched = mono_ns();
  s->fetch_ns.fetch_add(t_fetched - p.dispatch_mono,
                        std::memory_order_relaxed);
  auto cit = s->conns.find(p.client_fd);
  bool alive =
      cit != s->conns.end() && cit->second.gen == p.client_gen;
  if (status == 200 && body.size() == p.size) {
    if (alive) {
      Conn* c = &cit->second;
      c->inflight = 0;
      c->req_start_ns = 0;
      s->requests.fetch_add(1, std::memory_order_relaxed);
      respond_data(s, c, p.mime, body);
      record_latency(s, mono_ns() - p.start_mono);
      rec_emit_pending(s, p, body.size(), 200, kFbNone);
      uint64_t t_sent = mono_ns();
      s->send_ns.fetch_add(t_sent - t_fetched,
                           std::memory_order_relaxed);
      flush_client(s, p.client_fd);
    }
    return true;
  }
  // the volume plane refused: a 404 means OUR registration is stale
  // (vacuum/EC swap, delete raced the map) — drop it so the next
  // request falls back cleanly instead of re-fetching garbage
  if (status == 404) {
    s->stale_misses.fetch_add(1, std::memory_order_relaxed);
    invalidate_entry(s, p.path);
    if (alive) {
      cit->second.inflight = 0;
      cit->second.req_start_ns = 0;
      rec_emit_pending(s, p, 0, 404, kFbStale);
      respond_fallback(s, &cit->second);
      flush_client(s, p.client_fd);
    }
    return true;
  }
  s->upstream_errors.fetch_add(1, std::memory_order_relaxed);
  if (alive) {
    cit->second.inflight = 0;
    cit->second.req_start_ns = 0;
    rec_emit_pending(s, p, 0, 404, kFbUpstream);
    respond_fallback(s, &cit->second);
    flush_client(s, p.client_fd);
  }
  return true;
}

// -- event loop -------------------------------------------------------

void event_loop(Server* s) {
  epoll_event evs[256];
  while (!s->stop.load(std::memory_order_relaxed)) {
    int n = epoll_wait(s->epfd, evs, 256, 200);
    if (n < 0) {
      if (errno == EINTR) continue;
      break;
    }
    for (int i = 0; i < n; i++) {
      int fd = evs[i].data.fd;
      uint32_t e = evs[i].events;
      if (fd == s->wake_pipe[0]) {
        char buf[64];
        while (read(fd, buf, sizeof(buf)) > 0) {
        }
        continue;
      }
      if (fd == s->listen_fd) {
        for (;;) {
          int cfd = accept4(s->listen_fd, nullptr, nullptr,
                            SOCK_NONBLOCK);
          if (cfd < 0) break;
          int one = 1;
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one,
                     sizeof(one));
          epoll_event cev{};
          cev.events = EPOLLIN;
          cev.data.fd = cfd;
          if (epoll_ctl(s->epfd, EPOLL_CTL_ADD, cfd, &cev) < 0) {
            close(cfd);
            continue;
          }
          Conn c;
          c.fd = cfd;
          c.gen = ++s->gen_counter;
          s->conns[cfd] = std::move(c);
        }
        continue;
      }
      Upstream* u = s->pool.find(fd);
      if (u != nullptr) {
        if (e & (EPOLLHUP | EPOLLERR)) {
          s->pool.close_conn(fd);
          continue;
        }
        if (e & EPOLLOUT) s->pool.flush(u);
        if ((u = s->pool.find(fd)) == nullptr) continue;
        if (e & EPOLLIN) {
          char buf[65536];
          for (;;) {
            ssize_t r = recv(fd, buf, sizeof(buf), 0);
            if (r > 0) {
              u->in.append(buf, size_t(r));
              if (r < ssize_t(sizeof(buf))) break;
              continue;
            }
            if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK))
              break;
            s->pool.close_conn(fd);
            u = nullptr;
            break;
          }
          if (u != nullptr)
            while (ups_feed_one(s, u)) {
            }
        }
        continue;
      }
      auto cit = s->conns.find(fd);
      if (cit == s->conns.end()) continue;
      Conn* c = &cit->second;
      if (e & (EPOLLHUP | EPOLLERR)) {
        close_conn(s, fd);
        continue;
      }
      if (e & EPOLLOUT) {
        flush_client(s, fd);
        cit = s->conns.find(fd);
        if (cit == s->conns.end()) continue;
        c = &cit->second;
      }
      if (e & EPOLLIN) {
        char buf[65536];
        bool dead = false;
        for (;;) {
          ssize_t r = recv(fd, buf, sizeof(buf), 0);
          if (r > 0) {
            c->in.append(buf, size_t(r));
            if (r < ssize_t(sizeof(buf))) break;
            continue;
          }
          if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK))
            break;
          dead = true;
          break;
        }
        if (dead) {
          close_conn(s, fd);
          continue;
        }
        client_feed(s, c);
      }
    }
    s->pool.expire(mono_ns());
  }
}

}  // namespace

// -- extern "C" API ----------------------------------------------------

extern "C" {

// Start a filer read plane bound to host:port (0 = ephemeral); the
// bound port reports through out_port.  Returns a handle >= 0, or -1.
int frp_start(const char* host, int port, int* out_port) {
  std::call_once(g_init_once, global_init);
  int slot = -1;
  {
    std::lock_guard<std::mutex> lk(g_servers_mu);
    for (int i = 0; i < kMaxServers; i++)
      if (g_servers[i] == nullptr) {
        slot = i;
        break;
      }
  }
  if (slot < 0) return -1;
  Server* s = new Server();
  s->rec.cap = rec_ring_cap_env();
  s->rec.recs.resize(s->rec.cap);
  // the minted-rid prefix keeps the plane-sibling shape ("rpNN...")
  // so the volume plane flags our forwarded ids as minted-upstream
  snprintf(s->rid_prefix, sizeof(s->rid_prefix), "rp%02d%06llx", slot,
           static_cast<unsigned long long>(now_ns() & 0xffffff));
  {
    const char* d = getenv("SEAWEEDFS_TPU_FRP_FETCH_DELAY_MS");
    if (d != nullptr && *d != '\0') s->fetch_delay_ms.store(atoi(d));
  }
  s->epfd = epoll_create1(0);
  s->listen_fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (s->epfd < 0 || s->listen_fd < 0) goto fail;
  s->pool.epfd = s->epfd;
  s->pool.on_drop = [s](Pending& p) { ups_drop_pending(s, p); };
  {
    int one = 1;
    setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one,
               sizeof(one));
    sockaddr_in sa{};
    sa.sin_family = AF_INET;
    sa.sin_port = htons(uint16_t(port));
    if (inet_pton(AF_INET, host, &sa.sin_addr) != 1) goto fail;
    if (bind(s->listen_fd, reinterpret_cast<sockaddr*>(&sa),
             sizeof(sa)) < 0)
      goto fail;
    if (listen(s->listen_fd, 512) < 0) goto fail;
    socklen_t slen = sizeof(sa);
    if (getsockname(s->listen_fd, reinterpret_cast<sockaddr*>(&sa),
                    &slen) < 0)
      goto fail;
    if (out_port != nullptr) *out_port = int(ntohs(sa.sin_port));
    if (pipe2(s->wake_pipe, O_NONBLOCK) < 0) goto fail;
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = s->listen_fd;
    if (epoll_ctl(s->epfd, EPOLL_CTL_ADD, s->listen_fd, &ev) < 0)
      goto fail;
    ev.data.fd = s->wake_pipe[0];
    if (epoll_ctl(s->epfd, EPOLL_CTL_ADD, s->wake_pipe[0], &ev) < 0)
      goto fail;
  }
  s->loop = std::thread(event_loop, s);
  {
    std::lock_guard<std::mutex> lk(g_servers_mu);
    g_servers[slot] = s;
  }
  return slot;
fail:
  if (s->epfd >= 0) close(s->epfd);
  if (s->listen_fd >= 0) close(s->listen_fd);
  if (s->wake_pipe[0] >= 0) close(s->wake_pipe[0]);
  if (s->wake_pipe[1] >= 0) close(s->wake_pipe[1]);
  delete s;
  return -1;
}

void frp_stop(int h) {
  Server* s = nullptr;
  {
    std::lock_guard<std::mutex> lk(g_servers_mu);
    if (h < 0 || h >= kMaxServers) return;
    s = g_servers[h];
    g_servers[h] = nullptr;
  }
  if (s == nullptr) return;
  s->stop.store(true);
  char b = 1;
  ssize_t ignored = write(s->wake_pipe[1], &b, 1);
  (void)ignored;
  if (s->loop.joinable()) s->loop.join();
  for (auto& kv : s->conns) close(kv.second.fd);
  s->pool.close_all();
  close(s->listen_fd);
  close(s->epfd);
  close(s->wake_pipe[0]);
  close(s->wake_pipe[1]);
  delete s;
}

// arm/disarm the hot path (disarmed = every request answers the 404
// fallback; the listener stays up so clients need no re-discovery)
void frp_arm(int h, int on) {
  Server* s = get_server(h);
  if (s != nullptr) s->armed.store(on != 0);
}

// current invalidation generation — the fill-fence token.  Python
// captures this BEFORE looking an entry up (begin_fill protocol);
// frp_put_entry refuses a fill whose token pre-dates any later
// invalidation of that path.
unsigned long long frp_gen(int h) {
  Server* s = get_server(h);
  return s != nullptr ? s->gen.load(std::memory_order_relaxed) : 0;
}

// register/refresh one warm servable entry; returns 0 on insert, -1
// when the fill lost the fence race (an invalidation intervened) or
// the server is gone.  Refused fills are NOT an error — the path
// simply stays fallback until a fresher fill lands.
int frp_put_entry(int h, const char* path, const char* addr,
                  const char* fid, const char* mime,
                  unsigned long long size, unsigned long long gen0) {
  Server* s = get_server(h);
  if (s == nullptr || path == nullptr || addr == nullptr ||
      fid == nullptr)
    return -1;
  std::lock_guard<std::mutex> lk(s->entry_mu);
  if (gen0 < s->clear_gen) return -1;   // a wholesale clear intervened
  auto it = s->entries.find(path);
  if (it != s->entries.end() && it->second.gen > gen0) return -1;
  if (it == s->entries.end() && s->entries.size() >= kMaxEntries) {
    // overflow: drop everything (all reads fall back, never stale)
    s->entries.clear();
    s->clear_gen =
        s->gen.fetch_add(1, std::memory_order_relaxed) + 1;
    return -1;
  }
  EntryRec& rec = s->entries[path];
  rec.addr = addr;
  rec.fid = fid;
  rec.mime = mime != nullptr ? mime : "";
  rec.size = size;
  rec.valid = true;
  return 0;
}

// invalidate one path (EVERY mutation event lands here, from the
// filer's own listener and the WAL-follower tap, synchronously before
// the writer's ack returns): the map can no longer serve it, and the
// generation fence kills any in-flight fill that pre-dates this.
void frp_invalidate(int h, const char* path) {
  Server* s = get_server(h);
  if (s == nullptr || path == nullptr) return;
  invalidate_entry(s, std::string(path));
}

// drop all entries (teardown / coarse recovery)
void frp_clear(int h) {
  Server* s = get_server(h);
  if (s == nullptr) return;
  std::lock_guard<std::mutex> lk(s->entry_mu);
  s->entries.clear();
  s->clear_gen = s->gen.fetch_add(1, std::memory_order_relaxed) + 1;
}

// live entry-map size (tombstones included; gauge on /metrics)
int frp_entries(int h) {
  Server* s = get_server(h);
  if (s == nullptr) return -1;
  std::lock_guard<std::mutex> lk(s->entry_mu);
  return int(s->entries.size());
}

unsigned long long frp_requests(int h) {
  Server* s = get_server(h);
  return s != nullptr ? s->requests.load() : 0;
}

unsigned long long frp_fallbacks(int h) {
  Server* s = get_server(h);
  return s != nullptr ? s->fallbacks.load() : 0;
}

// out[0..kLatN]: cumulative bucket counts; out[kLatN+1]=count,
// out[kLatN+2]=sum ns (same shape as mp_latency)
int frp_latency(int h, unsigned long long* out) {
  Server* s = get_server(h);
  if (s == nullptr || out == nullptr) return -1;
  unsigned long long total = 0;
  for (int i = 0; i <= kLatN; i++) {
    total += s->lat_count[i].load();
    out[i] = total;
  }
  out[kLatN + 1] = total;
  out[kLatN + 2] = s->lat_sum_ns.load();
  return kLatN;
}

// aggregate counters for the Python metrics bridge:
// [requests, fallbacks, stale_misses, upstream_errors,
//  parse_ns, lookup_ns, fetch_ns, send_ns]
int frp_stats(int h, unsigned long long* out) {
  Server* s = get_server(h);
  if (s == nullptr || out == nullptr) return -1;
  out[0] = s->requests.load();
  out[1] = s->fallbacks.load();
  out[2] = s->stale_misses.load();
  out[3] = s->upstream_errors.load();
  out[4] = s->parse_ns.load();
  out[5] = s->lookup_ns.load();
  out[6] = s->fetch_ns.load();
  out[7] = s->send_ns.load();
  return 8;
}

int frp_drain_records(int h, PlaneRec* out, int cap) {
  Server* s = get_server(h);
  if (s == nullptr) return -1;
  return rec_drain(&s->rec, out, cap);
}

unsigned long long frp_records_dropped(int h) {
  Server* s = get_server(h);
  return s != nullptr ? rec_dropped(&s->rec) : 0;
}

// failpoint: stall the volume fetch hop by `ms` per request (0 = off)
void frp_set_fetch_delay_ms(int h, int ms) {
  Server* s = get_server(h);
  if (s != nullptr) s->fetch_delay_ms.store(ms < 0 ? 0 : ms);
}

}  // extern "C"
