// Native HTTP read plane for the volume server — the C++ sibling of the
// reference's second native implementation of the read surface
// (seaweed-volume/ Rust volume server, VOLUME_SERVER_RUST_PLAN.md) and
// of its RDMA read sidecar (seaweedfs-rdma-sidecar/rdma-engine):
// a single-threaded epoll loop serving `GET /<vid>,<fid>` straight from
// the .dat file descriptors via sendfile(2), bypassing the Python HTTP
// stack entirely on the hot read path.
//
// Scope (deliberate): plain anonymous needles only — the Python server
// registers an entry (vid, needle id) -> (cookie, absolute data offset,
// data length) at write time / on first read, and only for needles with
// no compression, no name/mime, no TTL and no chunk manifest; anything
// unregistered answers 404 and the client falls back to the full Python
// path (same contract as the UDS plane, server/uds_reader.py).  Deletes
// and vacuum drop entries/volumes; a dropped volume lazily re-registers.
//
// Wire behavior: HTTP/1.1, keep-alive, Content-Length framing,
// ETag "<cookie-hex>", 404 unknown, 400 malformed, 405 non-GET/HEAD.
//
// Build: g++ -O2 -shared -fPIC (no deps); driven via ctypes from
// seaweedfs_tpu/server/read_plane.py.

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/sendfile.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#include <atomic>
#include <mutex>
#include <shared_mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

uint64_t now_ns() {
  timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
}

uint64_t mono_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
}

// -- per-request flight records (ISSUE 18) ----------------------------
//
// Identical wire shape to meta_plane.cc / write_plane.cc PlaneRec
// (native.PlaneRecord on the ctypes side).

constexpr uint32_t kRecFlagClientRid = 1u;
// see meta_plane.cc kRecFlagMintedUpstream
constexpr uint32_t kRecFlagMintedUpstream = 2u;

inline uint32_t rid_rec_flags(const char* rid, bool client) {
  if (!client) return 0;
  uint32_t f = kRecFlagClientRid;
  if ((rid[0] == 'm' || rid[0] == 'w' || rid[0] == 'r') &&
      rid[1] == 'p' && rid[2] >= '0' && rid[2] <= '9' &&
      rid[3] >= '0' && rid[3] <= '9')
    f |= kRecFlagMintedUpstream;
  return f;
}

struct PlaneRec {
  char rid[40];
  uint64_t start_unix_ns;
  uint64_t stage_ns[4];    // kRecStageNames order
  uint64_t bytes;
  int64_t deadline_ms;     // -1 = absent
  int32_t status;
  int32_t fallback;        // kRecFallbackNames index
  uint32_t flags;
  uint32_t _pad;
};  // 112 bytes

enum {
  kFbNone = 0,
  kFbMethod = 1,
  kFbBadRequest = 2,
  kFbNotFound = 3,
};

// SWFS019 contract: every label below must appear verbatim as a
// string literal in the Python drain table (server/read_plane.py).
const char* const kRecStageNames[] = {"parse", "lookup", "send", "ack"};
const char* const kRecFallbackNames[] = {"none", "method",
                                         "bad_request", "not_found"};

struct RecRing {
  std::vector<PlaneRec> recs;
  uint64_t cap = 0;
  std::atomic<uint64_t> head{0};
  std::atomic<uint64_t> tail{0};
  std::atomic<uint64_t> dropped{0};
};

uint64_t rec_ring_cap_env() {
  const char* v = getenv("SEAWEEDFS_TPU_PLANE_REC_RING");
  if (v != nullptr && *v != '\0') {
    long n = atol(v);
    if (n >= 16 && n <= (1 << 20)) return (uint64_t)n;
  }
  return 4096;
}

void rec_push(RecRing* r, const PlaneRec& rec) {
  if (r->cap == 0) return;
  uint64_t h = r->head.load(std::memory_order_relaxed);
  r->recs[h % r->cap] = rec;
  r->head.store(h + 1, std::memory_order_release);
}

int rec_drain(RecRing* r, PlaneRec* out, int cap) {
  if (r->cap == 0 || out == nullptr || cap <= 0) return 0;
  uint64_t h = r->head.load(std::memory_order_acquire);
  uint64_t t = r->tail.load(std::memory_order_relaxed);
  if (h > t + r->cap) {
    r->dropped.fetch_add((h - r->cap) - t, std::memory_order_relaxed);
    t = h - r->cap;
  }
  int n = 0;
  while (t < h && n < cap) out[n++] = r->recs[t++ % r->cap];
  uint64_t h2 = r->head.load(std::memory_order_acquire);
  uint64_t first = t - (uint64_t)n;
  if (h2 > first + r->cap) {   // lapped mid-copy: drop torn prefix
    uint64_t torn = h2 - r->cap - first;
    if (torn > (uint64_t)n) torn = (uint64_t)n;
    if (torn > 0) {
      memmove(out, out + torn,
              ((size_t)n - (size_t)torn) * sizeof(PlaneRec));
      n -= (int)torn;
      r->dropped.fetch_add(torn, std::memory_order_relaxed);
    }
  }
  r->tail.store(t, std::memory_order_relaxed);
  return n;
}

uint64_t rec_dropped(RecRing* r) {
  uint64_t h = r->head.load(std::memory_order_acquire);
  uint64_t t = r->tail.load(std::memory_order_relaxed);
  uint64_t extra = (r->cap != 0 && h > t + r->cap)
                       ? (h - r->cap) - t : 0;
  return r->dropped.load(std::memory_order_relaxed) + extra;
}

struct Entry {
  uint32_t cookie;
  uint64_t off;    // absolute byte offset of the data payload in .dat
  uint32_t len;    // payload length
};

struct VolumeIdx {
  int fd = -1;
  std::unordered_map<uint64_t, Entry> needles;
};

struct Conn {
  int fd;
  std::string in;          // accumulated request bytes
  std::string out;         // pending response header bytes
  int file_fd = -1;        // pending sendfile source (-1 = none)
  off_t file_off = 0;
  size_t file_left = 0;
  bool close_after = false;
  // flight-record carry for the in-flight body response (at most one:
  // the request loop stalls while file_left > 0)
  bool rec_armed = false;
  uint64_t rec_handoff_mono = 0;
  uint64_t rec_parse_ns = 0;
  uint64_t rec_lookup_ns = 0;
  uint64_t rec_bytes = 0;
  char rid[40] = {0};
  bool rid_client = false;
  int64_t deadline_ms = -1;
};

struct Server {
  int epfd = -1;
  int listen_fd = -1;
  int wake_pipe[2] = {-1, -1};
  std::thread loop;
  std::atomic<bool> stop{false};
  std::atomic<uint64_t> served{0};
  std::shared_mutex idx_mu;
  std::unordered_map<uint32_t, VolumeIdx> volumes;
  std::unordered_map<int, Conn*> conns;
  // per-request flight records
  RecRing rec;
  uint64_t rid_seq = 0;    // event-loop thread only
  char rid_prefix[16] = {0};
};

constexpr int kMaxServers = 16;
Server* g_servers[kMaxServers] = {nullptr};
std::mutex g_servers_mu;

void set_nonblock(int fd) {
  int fl = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

void close_conn(Server* s, Conn* c) {
  epoll_ctl(s->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
  close(c->fd);
  if (c->file_fd >= 0) close(c->file_fd);
  s->conns.erase(c->fd);
  delete c;
}

void arm(Server* s, Conn* c, bool want_write) {
  epoll_event ev{};
  ev.events = EPOLLIN | (want_write ? EPOLLOUT : 0);
  ev.data.fd = c->fd;
  epoll_ctl(s->epfd, EPOLL_CTL_MOD, c->fd, &ev);
}

// parse "<vid>,<keyhex><cookie8hex>" -> vid, key, cookie
bool parse_fid(const char* p, size_t n, uint32_t* vid, uint64_t* key,
               uint32_t* cookie) {
  size_t comma = 0;
  while (comma < n && p[comma] != ',') comma++;
  if (comma == 0 || comma >= n) return false;
  uint64_t v = 0;
  for (size_t i = 0; i < comma; i++) {
    if (p[i] < '0' || p[i] > '9') return false;
    v = v * 10 + (p[i] - '0');
    if (v > 0xffffffffULL) return false;
  }
  const char* hex = p + comma + 1;
  size_t hn = n - comma - 1;
  if (hn < 9 || hn > 24) return false;  // >= 1 key nibble + 8 cookie
  uint64_t k = 0;
  uint64_t ck = 0;
  for (size_t i = 0; i < hn; i++) {
    char ch = hex[i];
    int d;
    if (ch >= '0' && ch <= '9') d = ch - '0';
    else if (ch >= 'a' && ch <= 'f') d = ch - 'a' + 10;
    else if (ch >= 'A' && ch <= 'F') d = ch - 'A' + 10;
    else return false;
    if (i < hn - 8) k = (k << 4) | d;
    else ck = (ck << 4) | d;
  }
  *vid = (uint32_t)v;
  *key = k;
  *cookie = (uint32_t)ck;
  return true;
}

void respond_simple(Conn* c, const char* status_line) {
  char buf[160];
  int n = snprintf(buf, sizeof buf,
                   "HTTP/1.1 %s\r\nContent-Length: 0\r\n\r\n",
                   status_line);
  c->out.append(buf, n);
}

// case-insensitive header lookup inside a raw header block (the
// request line leads the block; a method never matches "Name:")
std::string header_value(const std::string& block, const char* name) {
  size_t nl = strlen(name);
  size_t pos = 0;
  while (pos < block.size()) {
    size_t eol = block.find("\r\n", pos);
    if (eol == std::string::npos) eol = block.size();
    if (eol - pos > nl + 1 && block[pos + nl] == ':' &&
        strncasecmp(block.data() + pos, name, nl) == 0) {
      size_t v = pos + nl + 1;
      while (v < eol && (block[v] == ' ' || block[v] == '\t')) v++;
      return block.substr(v, eol - v);
    }
    pos = eol + 2;
  }
  return "";
}

// append one flight record framed off the conn's carry fields
void rec_emit(Server* s, Conn* c, uint64_t send_ns, uint64_t total_ns,
              int status, int fallback) {
  PlaneRec r{};
  snprintf(r.rid, sizeof(r.rid), "%s", c->rid);
  r.start_unix_ns = now_ns() - total_ns;
  r.stage_ns[0] = c->rec_parse_ns;
  r.stage_ns[1] = c->rec_lookup_ns;
  r.stage_ns[2] = send_ns;
  uint64_t sum = c->rec_parse_ns + c->rec_lookup_ns + send_ns;
  r.stage_ns[3] = total_ns > sum ? total_ns - sum : 0;
  r.bytes = c->rec_bytes;
  r.deadline_ms = c->deadline_ms;
  r.status = status;
  r.fallback = fallback;
  r.flags = rid_rec_flags(c->rid, c->rid_client);
  rec_push(&s->rec, r);
}

// returns false when the connection must close (malformed framing)
bool handle_one_request(Server* s, Conn* c, const std::string& req) {
  uint64_t t0 = mono_ns();
  c->rec_parse_ns = 0;
  c->rec_lookup_ns = 0;
  c->rec_bytes = 0;
  std::string rid = header_value(req, "X-Request-ID");
  if (!rid.empty()) {
    snprintf(c->rid, sizeof(c->rid), "%.39s", rid.c_str());
    c->rid_client = true;
  } else {
    snprintf(c->rid, sizeof(c->rid), "%s-%llx", s->rid_prefix,
             (unsigned long long)++s->rid_seq);
    c->rid_client = false;
  }
  std::string dl = header_value(req, "X-Weed-Deadline-Ms");
  c->deadline_ms = dl.empty() ? -1 : (int64_t)atoll(dl.c_str());
  // request line: METHOD SP target SP version
  size_t sp1 = req.find(' ');
  size_t sp2 = (sp1 == std::string::npos)
                   ? std::string::npos
                   : req.find(' ', sp1 + 1);
  if (sp2 == std::string::npos) return false;
  std::string method = req.substr(0, sp1);
  std::string target = req.substr(sp1 + 1, sp2 - sp1 - 1);
  bool head = method == "HEAD";
  if (method != "GET" && !head) {
    respond_simple(c, "405 Method Not Allowed");
    c->rec_parse_ns = mono_ns() - t0;
    rec_emit(s, c, 0, mono_ns() - t0, 405, kFbMethod);
    return true;
  }
  // strip query + leading slash
  size_t q = target.find('?');
  if (q != std::string::npos) target.resize(q);
  if (target.empty() || target[0] != '/') {
    respond_simple(c, "400 Bad Request");
    c->rec_parse_ns = mono_ns() - t0;
    rec_emit(s, c, 0, mono_ns() - t0, 400, kFbBadRequest);
    return true;
  }
  uint32_t vid, cookie;
  uint64_t key;
  if (!parse_fid(target.data() + 1, target.size() - 1, &vid, &key,
                 &cookie)) {
    respond_simple(c, "404 Not Found");
    c->rec_parse_ns = mono_ns() - t0;
    rec_emit(s, c, 0, mono_ns() - t0, 404, kFbNotFound);
    return true;
  }
  c->rec_parse_ns = mono_ns() - t0;
  uint64_t t_lk = mono_ns();
  int fd = -1;
  Entry e{};
  {
    std::shared_lock<std::shared_mutex> lk(s->idx_mu);
    auto vit = s->volumes.find(vid);
    if (vit != s->volumes.end() && vit->second.fd >= 0) {
      auto nit = vit->second.needles.find(key);
      if (nit != vit->second.needles.end() &&
          nit->second.cookie == cookie) {
        // dup under the lock: rp_remove_volume/rp_add_volume may
        // close the volume fd concurrently; the connection owns its
        // duplicate for the lifetime of the sendfile
        fd = dup(vit->second.fd);
        e = nit->second;
      }
    }
  }
  c->rec_lookup_ns = mono_ns() - t_lk;
  if (fd < 0) {
    respond_simple(c, "404 Not Found");
    rec_emit(s, c, 0, mono_ns() - t0, 404, kFbNotFound);
    return true;
  }
  char hdr[224];
  int hn = snprintf(hdr, sizeof hdr,
                    "HTTP/1.1 200 OK\r\n"
                    "Content-Type: application/octet-stream\r\n"
                    "Content-Length: %u\r\n"
                    "ETag: \"%08x\"\r\n"
                    "Accept-Ranges: bytes\r\n\r\n",
                    e.len, cookie);
  c->out.append(hdr, hn);
  c->rec_bytes = head ? 0 : e.len;
  if (!head && e.len > 0) {
    c->file_fd = fd;           // owned (dup); closed when drained
    c->file_off = (off_t)e.off;
    c->file_left = e.len;
    // record finalized in flush_out once the body drains: the send
    // stage spans the sendfile window, not just header queueing
    c->rec_armed = true;
    c->rec_handoff_mono = mono_ns();
  } else {
    close(fd);
    rec_emit(s, c, 0, mono_ns() - t0, 200, kFbNone);
  }
  s->served.fetch_add(1, std::memory_order_relaxed);
  return true;
}

// drain pending output; returns false on fatal error
bool flush_out(Server* s, Conn* c) {
  while (!c->out.empty()) {
    ssize_t n = send(c->fd, c->out.data(), c->out.size(), MSG_NOSIGNAL);
    if (n > 0) {
      c->out.erase(0, (size_t)n);
      continue;
    }
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) return true;
    return false;
  }
  while (c->file_left > 0) {
    ssize_t n = sendfile(c->fd, c->file_fd, &c->file_off,
                         c->file_left > (1 << 20) ? (1 << 20)
                                                  : c->file_left);
    if (n > 0) {
      c->file_left -= (size_t)n;
      continue;
    }
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) return true;
    return false;
  }
  if (c->file_fd >= 0) {
    close(c->file_fd);
    c->file_fd = -1;
  }
  if (c->rec_armed && c->file_left == 0) {
    uint64_t send_ns = mono_ns() - c->rec_handoff_mono;
    rec_emit(s, c, send_ns,
             c->rec_parse_ns + c->rec_lookup_ns + send_ns, 200,
             kFbNone);
    c->rec_armed = false;
  }
  return true;
}

void event_loop(Server* s) {
  epoll_event evs[64];
  while (!s->stop.load(std::memory_order_relaxed)) {
    int n = epoll_wait(s->epfd, evs, 64, 500);
    for (int i = 0; i < n; i++) {
      int fd = evs[i].data.fd;
      if (fd == s->wake_pipe[0]) {
        char tmp[16];
        (void)!read(fd, tmp, sizeof tmp);
        continue;
      }
      if (fd == s->listen_fd) {
        for (;;) {
          int cfd = accept4(s->listen_fd, nullptr, nullptr,
                            SOCK_NONBLOCK);
          if (cfd < 0) break;
          int one = 1;
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
          Conn* c = new Conn{cfd};
          s->conns[cfd] = c;
          epoll_event ev{};
          ev.events = EPOLLIN;
          ev.data.fd = cfd;
          epoll_ctl(s->epfd, EPOLL_CTL_ADD, cfd, &ev);
        }
        continue;
      }
      auto it = s->conns.find(fd);
      if (it == s->conns.end()) continue;
      Conn* c = it->second;
      bool dead = false;
      if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
        dead = true;
      }
      if (!dead && (evs[i].events & EPOLLIN)) {
        char buf[8192];
        for (;;) {
          ssize_t r = recv(fd, buf, sizeof buf, 0);
          if (r > 0) {
            c->in.append(buf, (size_t)r);
            if (c->in.size() > (64 << 10)) {  // header flood guard
              dead = true;
              break;
            }
            continue;
          }
          if (r == 0) {
            dead = c->in.empty() && c->out.empty() &&
                   c->file_left == 0;
            c->close_after = true;
            break;
          }
          if (errno == EAGAIN || errno == EWOULDBLOCK) break;
          dead = true;
          break;
        }
        // process complete requests (pipelining-tolerant), but only
        // while no body transfer is pending — responses must be
        // emitted in order
        while (!dead && c->file_left == 0) {
          size_t end = c->in.find("\r\n\r\n");
          if (end == std::string::npos) break;
          std::string req = c->in.substr(0, end);
          c->in.erase(0, end + 4);
          if (!handle_one_request(s, c, req)) {
            dead = true;
            break;
          }
        }
      }
      if (!dead && !flush_out(s, c)) dead = true;
      if (!dead && c->close_after && c->out.empty() &&
          c->file_left == 0) {
        dead = true;
      }
      if (dead) {
        close_conn(s, c);
      } else {
        arm(s, c, !c->out.empty() || c->file_left > 0);
      }
    }
  }
  // teardown
  for (auto& kv : s->conns) {
    close(kv.second->fd);
    if (kv.second->file_fd >= 0) close(kv.second->file_fd);
    delete kv.second;
  }
  s->conns.clear();
}

}  // namespace

extern "C" {

int rp_start(const char* host, int port, int* bound_port) {
  int slot = -1;
  {
    std::lock_guard<std::mutex> lk(g_servers_mu);
    for (int i = 0; i < kMaxServers; i++) {
      if (g_servers[i] == nullptr) {
        slot = i;
        break;
      }
    }
    if (slot < 0) return -1;
    g_servers[slot] = new Server();
  }
  Server* s = g_servers[slot];
  s->listen_fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (s->listen_fd < 0) return -1;
  int one = 1;
  setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) return -1;
  if (bind(s->listen_fd, (sockaddr*)&addr, sizeof addr) < 0 ||
      listen(s->listen_fd, 512) < 0) {
    close(s->listen_fd);
    return -1;
  }
  socklen_t alen = sizeof addr;
  getsockname(s->listen_fd, (sockaddr*)&addr, &alen);
  *bound_port = ntohs(addr.sin_port);
  s->rec.cap = rec_ring_cap_env();
  s->rec.recs.resize(s->rec.cap);
  snprintf(s->rid_prefix, sizeof(s->rid_prefix), "rp%02d%06llx", slot,
           (unsigned long long)(now_ns() & 0xffffff));
  s->epfd = epoll_create1(0);
  if (pipe2(s->wake_pipe, O_NONBLOCK) < 0) return -1;
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.fd = s->listen_fd;
  epoll_ctl(s->epfd, EPOLL_CTL_ADD, s->listen_fd, &ev);
  ev.data.fd = s->wake_pipe[0];
  epoll_ctl(s->epfd, EPOLL_CTL_ADD, s->wake_pipe[0], &ev);
  s->loop = std::thread(event_loop, s);
  return slot;
}

void rp_stop(int h) {
  Server* s;
  {
    std::lock_guard<std::mutex> lk(g_servers_mu);
    if (h < 0 || h >= kMaxServers || g_servers[h] == nullptr) return;
    s = g_servers[h];
    g_servers[h] = nullptr;
  }
  s->stop.store(true);
  (void)!write(s->wake_pipe[1], "x", 1);
  s->loop.join();
  close(s->listen_fd);
  close(s->epfd);
  close(s->wake_pipe[0]);
  close(s->wake_pipe[1]);
  {
    std::unique_lock<std::shared_mutex> lk(s->idx_mu);
    for (auto& kv : s->volumes) {
      if (kv.second.fd >= 0) close(kv.second.fd);
    }
  }
  delete s;
}

static Server* get_server(int h) {
  std::lock_guard<std::mutex> lk(g_servers_mu);
  if (h < 0 || h >= kMaxServers) return nullptr;
  return g_servers[h];
}

int rp_add_volume(int h, unsigned vid, const char* dat_path) {
  Server* s = get_server(h);
  if (s == nullptr) return -1;
  int fd = open(dat_path, O_RDONLY);
  if (fd < 0) return -1;
  std::unique_lock<std::shared_mutex> lk(s->idx_mu);
  VolumeIdx& v = s->volumes[vid];
  if (v.fd >= 0) close(v.fd);  // refresh (post-vacuum fd swap)
  v.fd = fd;
  v.needles.clear();
  return 0;
}

void rp_remove_volume(int h, unsigned vid) {
  Server* s = get_server(h);
  if (s == nullptr) return;
  std::unique_lock<std::shared_mutex> lk(s->idx_mu);
  auto it = s->volumes.find(vid);
  if (it != s->volumes.end()) {
    if (it->second.fd >= 0) close(it->second.fd);
    s->volumes.erase(it);
  }
}

int rp_put(int h, unsigned vid, unsigned long long nid,
           unsigned cookie, unsigned long long data_off,
           unsigned data_len) {
  Server* s = get_server(h);
  if (s == nullptr) return -1;
  std::unique_lock<std::shared_mutex> lk(s->idx_mu);
  auto it = s->volumes.find(vid);
  if (it == s->volumes.end() || it->second.fd < 0) return -1;
  it->second.needles[nid] = Entry{cookie, data_off, data_len};
  return 0;
}

void rp_del(int h, unsigned vid, unsigned long long nid) {
  Server* s = get_server(h);
  if (s == nullptr) return;
  std::unique_lock<std::shared_mutex> lk(s->idx_mu);
  auto it = s->volumes.find(vid);
  if (it != s->volumes.end()) it->second.needles.erase(nid);
}

unsigned long long rp_served(int h) {
  Server* s = get_server(h);
  return s == nullptr ? 0 : s->served.load();
}

// drain up to `cap` flight records into `out`; returns the count
int rp_drain_records(int h, PlaneRec* out, int cap) {
  Server* s = get_server(h);
  if (s == nullptr) return 0;
  return rec_drain(&s->rec, out, cap);
}

unsigned long long rp_records_dropped(int h) {
  Server* s = get_server(h);
  return s == nullptr ? 0 : rec_dropped(&s->rec);
}

}  // extern "C"
