// Shared persistent plane-socket pool (ISSUE 19).
//
// Both filer-side native planes talk to volume-side native planes over
// pipelined keep-alive TCP connections: the meta plane POSTs chunk
// bodies into write_plane.cc, the read plane GETs needle bytes out of
// read_plane.cc.  PR 17 grew this machinery inline in meta_plane.cc;
// this header is that pool factored out and shared, with one behavior
// change that IS the ISSUE 19 write-side lever: `flush()` sends
// EAGERLY.  The old dispatch path appended to the upstream buffer and
// armed EPOLLOUT, paying a full epoll round trip (wait return, event
// dispatch, flush) per upstream hop even though the established
// socket was writable the whole time — measured as the dominant share
// of the 1.91 ms upload hop (ROADMAP item 1).  Eager send drains the
// buffer inline at dispatch and falls back to EPOLLOUT only on a
// genuinely full socket (or a still-connecting one, where Linux
// send(2) answers EAGAIN until the handshake lands).
//
// The pool owns connection lifecycle (open/pick/flush/expire/close);
// response PARSING stays in each plane — the wire formats differ
// (201-JSON acks vs 200-octet-stream bodies) and so does what a
// completed response means.  `Pending` is the per-plane in-flight
// request type; the pool requires only that it expose `enq_mono`
// (the enqueue stamp the idle-timeout reaper keys on).  Failed
// connections hand their FIFO of in-flight requests back through
// `on_drop`, one at a time, for the plane to answer with its 404
// fallback contract.
//
// Single-threaded by contract: every method runs on the owning
// plane's event-loop thread (the same contract the inline pool had).

#ifndef SEAWEEDFS_TPU_NATIVE_PLANE_POOL_H_
#define SEAWEEDFS_TPU_NATIVE_PLANE_POOL_H_

#include <arpa/inet.h>
#include <errno.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <deque>
#include <functional>
#include <map>
#include <string>
#include <unordered_map>
#include <vector>

namespace plane_pool {

template <typename Pending>
struct Upstream {
  int fd = -1;
  std::string addr;
  std::string in;              // response bytes being assembled
  std::string out;             // request bytes awaiting the socket
  bool have_headers = false;   // response-parse state (plane-owned)
  size_t header_end = 0;
  size_t body_need = 0;
  int status = 0;
  std::deque<Pending> inflight;  // FIFO: planes answer in order
  bool want_write = false;
};

template <typename Pending>
struct Pool {
  int epfd = -1;
  size_t per_addr = 4;
  size_t pipeline_high = 32;   // per-conn inflight split point
  uint64_t timeout_ns = 5ull * 1000 * 1000 * 1000;
  // a dropped in-flight request (conn error / timeout); the plane
  // answers its client with the 404 fallback
  std::function<void(Pending&)> on_drop;

  std::map<std::string, std::vector<int>> by_addr;
  std::unordered_map<int, Upstream<Pending>> ups;

  Upstream<Pending>* find(int fd) {
    auto it = ups.find(fd);
    return it == ups.end() ? nullptr : &it->second;
  }

  void arm(Upstream<Pending>* u, bool want_write) {
    if (u->want_write == want_write) return;
    u->want_write = want_write;
    epoll_event ev{};
    ev.events = EPOLLIN | (want_write ? EPOLLOUT : 0u);
    ev.data.fd = u->fd;
    epoll_ctl(epfd, EPOLL_CTL_MOD, u->fd, &ev);
  }

  int open_conn(const std::string& addr) {
    size_t colon = addr.rfind(':');
    if (colon == std::string::npos) return -1;
    std::string host = addr.substr(0, colon);
    int port = atoi(addr.c_str() + colon + 1);
    sockaddr_in sa{};
    sa.sin_family = AF_INET;
    sa.sin_port = htons(uint16_t(port));
    if (inet_pton(AF_INET, host.c_str(), &sa.sin_addr) != 1) return -1;
    int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (fd < 0) return -1;
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    int rc = connect(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa));
    if (rc < 0 && errno != EINPROGRESS) {
      close(fd);
      return -1;
    }
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = fd;
    if (epoll_ctl(epfd, EPOLL_CTL_ADD, fd, &ev) < 0) {
      close(fd);
      return -1;
    }
    Upstream<Pending> u;
    u.fd = fd;
    u.addr = addr;
    ups[fd] = std::move(u);
    by_addr[addr].push_back(fd);
    return fd;
  }

  void fail_inflight(Upstream<Pending>* u) {
    while (!u->inflight.empty()) {
      Pending p = std::move(u->inflight.front());
      u->inflight.pop_front();
      if (on_drop) on_drop(p);
    }
  }

  void close_conn(int fd) {
    auto it = ups.find(fd);
    if (it == ups.end()) return;
    fail_inflight(&it->second);
    auto& v = by_addr[it->second.addr];
    for (size_t i = 0; i < v.size(); i++)
      if (v[i] == fd) {
        v.erase(v.begin() + long(i));
        break;
      }
    epoll_ctl(epfd, EPOLL_CTL_DEL, fd, nullptr);
    close(fd);
    ups.erase(it);
  }

  // least-loaded connection for `addr`, growing the per-addr set up
  // to `per_addr` once every member is past the pipeline split.  May
  // return a saturated conn (or null on connect failure) — the
  // caller degrades to its fallback contract.
  Upstream<Pending>* pick(const std::string& addr) {
    auto& v = by_addr[addr];
    Upstream<Pending>* best = nullptr;
    for (int fd : v) {
      Upstream<Pending>* u = &ups[fd];
      if (best == nullptr ||
          u->inflight.size() < best->inflight.size())
        best = u;
    }
    if (best != nullptr && best->inflight.size() < pipeline_high)
      return best;
    if (v.size() < per_addr) {
      int fd = open_conn(addr);
      if (fd >= 0) return &ups[fd];
    }
    return best;
  }

  // EAGER flush: drain u->out inline, arming EPOLLOUT only when the
  // socket pushes back.  Call right after appending a request (the
  // dispatch hop) and again on EPOLLOUT readiness.
  void flush(Upstream<Pending>* u) {
    while (!u->out.empty()) {
      ssize_t n =
          send(u->fd, u->out.data(), u->out.size(), MSG_NOSIGNAL);
      if (n > 0) {
        u->out.erase(0, size_t(n));
        continue;
      }
      if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        arm(u, true);
        return;
      }
      close_conn(u->fd);
      return;
    }
    arm(u, false);
  }

  // reap connections whose OLDEST in-flight request has been waiting
  // past the timeout (a wedged volume plane fails the whole conn; the
  // clients fall back and the next request redials)
  void expire(uint64_t now_mono_ns) {
    std::vector<int> dead;
    for (auto& kv : ups) {
      Upstream<Pending>& u = kv.second;
      if (!u.inflight.empty() &&
          now_mono_ns - u.inflight.front().enq_mono > timeout_ns)
        dead.push_back(kv.first);
    }
    for (int fd : dead) close_conn(fd);
  }

  // teardown after the event loop has stopped: raw close, no epoll,
  // no on_drop (the clients are being torn down too)
  void close_all() {
    for (auto& kv : ups) close(kv.second.fd);
    ups.clear();
    by_addr.clear();
  }
};

}  // namespace plane_pool

#endif  // SEAWEEDFS_TPU_NATIVE_PLANE_POOL_H_
