"""gRPC/protobuf wire plane tests (VERDICT r3 Missing #1 / Next #4).

The bar: a generated-stub client (protoc output + grpc channel, no
JSON-HTTP anywhere) drives assign -> write -> ec.encode against a live
cluster, plus the streamed bulk-file plane and the KeepConnected follow
stream.  Wire compatibility is asserted structurally: the method paths,
message field numbers, and package names match the reference protos
(/root/reference/weed/pb/master.proto, volume_server.proto)."""

import os
import time

import numpy as np
import pytest

grpc = pytest.importorskip("grpc")

from seaweedfs_tpu import operation
from seaweedfs_tpu.pb import master_pb2, volume_server_pb2
from seaweedfs_tpu.pb.master_service import master_stub
from seaweedfs_tpu.pb.volume_service import (fetch_file, send_file,
                                             volume_stub)
from seaweedfs_tpu.server.master_server import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer


@pytest.fixture
def cluster(tmp_path):
    master = MasterServer(volume_size_limit_mb=64).start()
    vols = []
    for i in range(2):
        d = tmp_path / f"v{i}"
        d.mkdir()
        vols.append(VolumeServer([str(d)], master.url,
                                 pulse_seconds=0.3).start())
    time.sleep(0.5)
    yield master, vols
    for vs in vols:
        vs.stop()
    master.stop()


def test_grpc_ports_exposed(cluster):
    master, vols = cluster
    assert master.grpc_port > 0
    assert all(vs.grpc_port > 0 for vs in vols)


def test_assign_write_read_via_grpc_stub(cluster):
    """assign (gRPC) -> write (HTTP data path, as in the reference) ->
    lookup (gRPC) -> read back."""
    master, vols = cluster
    with grpc.insecure_channel(f"127.0.0.1:{master.grpc_port}") as ch:
        m = master_stub(ch)
        a = m.Assign(master_pb2.AssignRequest(count=1))
        assert a.fid and a.location.url
        blob = os.urandom(4096)
        operation.upload(a.location.url, a.fid, blob, auth=a.auth)
        lk = m.LookupVolume(master_pb2.LookupVolumeRequest(
            volume_or_file_ids=[a.fid.split(",")[0]]))
        assert len(lk.volume_id_locations) == 1
        urls = [l.url for l in lk.volume_id_locations[0].locations]
        assert a.location.url in urls
        assert operation.read(master.url, a.fid) == blob

        # volume sizes reach the master on the next heartbeat pulse
        deadline = time.time() + 5
        while time.time() < deadline:
            stats = m.Statistics(master_pb2.StatisticsRequest())
            if stats.used_size > 0:
                break
            time.sleep(0.2)
        assert stats.used_size > 0 and stats.file_count >= 1


def test_ec_encode_mount_read_via_grpc(cluster):
    """The full EC workflow over pure gRPC: readonly -> generate ->
    mount -> shard info -> streamed shard read, then degraded read of
    the original blob through the normal read path."""
    master, vols = cluster
    with grpc.insecure_channel(f"127.0.0.1:{master.grpc_port}") as ch:
        m = master_stub(ch)
        a = m.Assign(master_pb2.AssignRequest(count=1))
        blob = np.random.default_rng(3).integers(
            0, 256, 256 * 1024, dtype=np.uint8).tobytes()
        operation.upload(a.location.url, a.fid, blob, auth=a.auth)
        vid = int(a.fid.split(",")[0])
        src = next(vs for vs in vols if a.location.url == vs.url)

        with grpc.insecure_channel(
                f"127.0.0.1:{src.grpc_port}") as vch:
            v = volume_stub(vch)
            v.VolumeMarkReadonly(
                volume_server_pb2.VolumeMarkReadonlyRequest(
                    volume_id=vid))
            v.VolumeEcShardsGenerate(
                volume_server_pb2.VolumeEcShardsGenerateRequest(
                    volume_id=vid))
            v.VolumeEcShardsMount(
                volume_server_pb2.VolumeEcShardsMountRequest(
                    volume_id=vid, shard_ids=list(range(14))))
            info = v.VolumeEcShardsInfo(
                volume_server_pb2.VolumeEcShardsInfoRequest(
                    volume_id=vid))
            assert len(info.ec_shard_infos) == 14
            shard_size = info.ec_shard_infos[0].size
            assert shard_size > 0

            # streamed shard read returns real bytes
            chunks = list(v.VolumeEcShardRead(
                volume_server_pb2.VolumeEcShardReadRequest(
                    volume_id=vid, shard_id=0, offset=0,
                    size=min(shard_size, 8192))))
            got = b"".join(c.data for c in chunks)
            assert len(got) == min(shard_size, 8192)

        time.sleep(0.7)  # let the heartbeat register the ec shards
        assert operation.read(master.url, a.fid) == blob


def test_streamed_copyfile_receivefile(cluster, tmp_path):
    """Bulk plane: push a file via client-streamed ReceiveFile, pull it
    back via server-streamed CopyFile, byte-compare."""
    master, vols = cluster
    vs = vols[0]
    src = tmp_path / "push.bin"
    blob = os.urandom(6 << 20)
    src.write_bytes(blob)
    with grpc.insecure_channel(f"127.0.0.1:{vs.grpc_port}") as ch:
        v = volume_stub(ch)
        n = send_file(v, str(src), volume_id=424242, ext=".dat")
        assert n == len(blob)
        dest = tmp_path / "pull.bin"
        n2 = fetch_file(v, str(dest), volume_id=424242, ext=".dat")
        assert n2 == len(blob)
        assert dest.read_bytes() == blob


def test_keepconnected_follow_stream(cluster):
    """KeepConnected pushes a leader greeting, a topology snapshot, and
    live volume-location deltas when new volumes appear."""
    master, vols = cluster
    with grpc.insecure_channel(f"127.0.0.1:{master.grpc_port}") as ch:
        m = master_stub(ch)

        def greet():
            yield master_pb2.KeepConnectedRequest(
                client_type="test", client_address="127.0.0.1")
            time.sleep(5)  # keep the stream open

        stream = m.KeepConnected(greet())
        first = next(stream)
        assert first.volume_location.leader  # leadership greeting
        # snapshot frames for nodes with volumes may follow; force a
        # delta by growing a volume
        a = m.Assign(master_pb2.AssignRequest(
            count=1, collection="follow"))
        assert a.fid
        deadline = time.time() + 10
        saw_new_vid = False
        while time.time() < deadline and not saw_new_vid:
            msg = next(stream)
            if msg.volume_location.new_vids:
                saw_new_vid = True
        assert saw_new_vid
        stream.cancel()


def test_wire_compat_field_numbers():
    """Spot-check wire compatibility with the reference protos: field
    numbers of key messages match master.proto:234-266 / 213-231 and
    volume_server.proto:314-346."""
    f = master_pb2.AssignRequest.DESCRIPTOR.fields_by_name
    assert f["count"].number == 1
    assert f["replication"].number == 2
    assert f["collection"].number == 3
    assert f["disk_type"].number == 10
    f = master_pb2.AssignResponse.DESCRIPTOR.fields_by_name
    assert f["fid"].number == 1
    assert f["count"].number == 4
    assert f["auth"].number == 6
    assert f["location"].number == 8
    f = master_pb2.Location.DESCRIPTOR.fields_by_name
    assert f["url"].number == 1 and f["grpc_port"].number == 3
    f = volume_server_pb2.CopyFileRequest.DESCRIPTOR.fields_by_name
    assert f["volume_id"].number == 1 and f["ext"].number == 2
    assert f["ignore_source_file_not_found"].number == 7
    f = volume_server_pb2.ReceiveFileInfo.DESCRIPTOR.fields_by_name
    assert f["volume_id"].number == 1 and f["file_size"].number == 6
    f = volume_server_pb2.VolumeEcShardsCopyRequest.DESCRIPTOR \
        .fields_by_name
    assert f["shard_ids"].number == 3
    assert f["source_data_node"].number == 5
    assert f["copy_vif_file"].number == 7
    # service path names the Go client dials
    assert master_pb2.DESCRIPTOR.services_by_name["Seaweed"] is not None
    svc = volume_server_pb2.DESCRIPTOR.services_by_name["VolumeServer"]
    assert svc.full_name == "volume_server_pb.VolumeServer"


def test_grpc_plane_enforces_admin_guard(tmp_path):
    """The gRPC plane runs the same guard as HTTP: with an admin key
    configured, credential-less admin RPCs (VolumeDelete, heartbeats)
    are rejected UNAUTHENTICATED, and ReceiveFile validates ext (no
    path traversal)."""
    from seaweedfs_tpu import security

    sec = security.SecurityConfig(admin_key="topsecret")
    master = MasterServer(volume_size_limit_mb=8,
                          security_config=sec).start()
    d = tmp_path / "v0"
    d.mkdir()
    vs = VolumeServer([str(d)], master.url, pulse_seconds=0.3,
                      security_config=sec).start()
    try:
        time.sleep(0.4)
        with grpc.insecure_channel(f"127.0.0.1:{vs.grpc_port}") as ch:
            v = volume_stub(ch)
            with pytest.raises(grpc.RpcError) as ei:
                v.VolumeDelete(volume_server_pb2.VolumeDeleteRequest(
                    volume_id=1))
            assert ei.value.code() == grpc.StatusCode.UNAUTHENTICATED
            # with the admin JWT attached, the call is authorized: it
            # now fails only because volume 1 doesn't exist (i.e. the
            # guard passed and the handler ran)
            md = [("authorization",
                   f"Bearer {sec.admin_jwt()}")]
            with pytest.raises(grpc.RpcError) as ei:
                v.VolumeDelete(volume_server_pb2.VolumeDeleteRequest(
                    volume_id=1), metadata=md)
            assert ei.value.code() != grpc.StatusCode.UNAUTHENTICATED

            # path traversal in ReceiveFile ext is rejected
            def gen():
                yield volume_server_pb2.ReceiveFileRequest(
                    info=volume_server_pb2.ReceiveFileInfo(
                        volume_id=9, ext="/../../../tmp/pwn"))
                yield volume_server_pb2.ReceiveFileRequest(
                    file_content=b"x")
            resp = v.ReceiveFile(gen(), metadata=md)
            assert resp.error
    finally:
        vs.stop()
        master.stop()


def test_http_watch_cursor_is_gap_free(tmp_path):
    """/cluster/watch delivers events published BETWEEN two polls (the
    hub ring retains them; a per-poll queue would drop them)."""
    from seaweedfs_tpu.server.httpd import http_json

    master = MasterServer(volume_size_limit_mb=8).start()
    d = tmp_path / "v0"
    d.mkdir()
    vs = VolumeServer([str(d)], master.url, pulse_seconds=0.3).start()
    try:
        time.sleep(0.4)
        snap = http_json(
            "GET", f"{master.url}/cluster/watch?snapshot=1")
        cursor = snap["cursor"]
        # publish an event while NO poll is outstanding
        with grpc.insecure_channel(
                f"127.0.0.1:{master.grpc_port}") as ch:
            m = master_stub(ch)
            m.Assign(master_pb2.AssignRequest(count=1,
                                              collection="gapfree"))
        deadline = time.time() + 10
        got_vids = []
        while time.time() < deadline and not got_vids:
            r = http_json("GET", f"{master.url}/cluster/watch"
                          f"?since={cursor}&timeout=2")
            assert not r.get("lagged")
            cursor = r["cursor"]
            for ev in r["events"]:
                got_vids.extend(ev.get("newVids", []))
        assert got_vids, "volume-location delta lost between polls"
    finally:
        vs.stop()
        master.stop()


def test_volume_list_returns_topology_tree(cluster):
    """VolumeList (master_grpc_server_volume.go) — the RPC `weed
    shell` opens every session with: the full dc -> rack -> node tree
    with per-disk volume inventories, matching what the heartbeats
    registered."""
    master, vols = cluster
    with grpc.insecure_channel(f"127.0.0.1:{master.grpc_port}") as ch:
        m = master_stub(ch)
        a = m.Assign(master_pb2.AssignRequest(count=1))
        blob = os.urandom(2048)
        operation.upload(a.location.url, a.fid, blob, auth=a.auth)
        vid = int(a.fid.split(",")[0])

        # the new volume reaches the tree on the next heartbeat pulse
        deadline = time.time() + 10
        found = None
        while time.time() < deadline and found is None:
            r = m.VolumeList(master_pb2.VolumeListRequest())
            for dc in r.topology_info.data_center_infos:
                for rk in dc.rack_infos:
                    for dn in rk.data_node_infos:
                        for v in dn.diskInfos[""].volume_infos:
                            if v.id == vid and v.size > 0:
                                found = (dn, v)
            if found is None:
                time.sleep(0.2)
        assert found, f"volume {vid} never appeared in VolumeList"
        dn, v = found
        assert dn.id in [vs.url for vs in vols]
        assert r.volume_size_limit_mb == 64
        assert r.topology_info.id == master.raft.topology_id
        # per-disk accounting is self-consistent
        di = dn.diskInfos[""]
        assert di.volume_count == len(di.volume_infos)
        assert di.free_volume_count == \
            di.max_volume_count - di.volume_count
        assert 0 < di.active_volume_count <= di.volume_count
