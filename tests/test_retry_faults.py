"""Unit surface of the robustness plane: faults.py spec
parsing/arming semantics and util/retry's backoff, budget, and
per-peer circuit breaker."""

import time

import pytest

from seaweedfs_tpu import faults, stats
from seaweedfs_tpu.util import retry


@pytest.fixture(autouse=True)
def _isolate():
    faults.reset()
    retry.reset()
    yield
    faults.reset()
    retry.reset()


# -- faults ---------------------------------------------------------------

def test_spec_parsing_and_actions():
    n = faults.arm_spec(
        "a.b=error,n=2; c.d=delay,ms=1 ;e.f=truncate,match=peerX")
    assert n == 3
    with pytest.raises(faults.FaultInjected):
        faults.fire("a.b")
    with pytest.raises(faults.FaultInjected):
        faults.fire("a.b")
    assert faults.fire("a.b") is None          # n exhausted
    t0 = time.perf_counter()
    assert faults.fire("c.d") is None          # delay, then continue
    assert time.perf_counter() - t0 >= 0.001
    assert faults.fire("e.f", key="zzz") is None      # match miss
    assert faults.fire("e.f", key="--peerX--") == "truncate"
    assert faults.triggered() == {"a.b": 2, "c.d": 1, "e.f": 1}


def test_spec_rejects_malformed():
    for bad in ("nosuchshape", "a.b=explode", "a.b=error,zz=1",
                "a.b=error,p="):
        with pytest.raises(ValueError):
            faults.parse_spec(bad)


def test_probability_deterministic_with_seed():
    def fires(seed):
        faults.reset()
        faults.arm("p.q", "truncate", p=0.5, seed=seed)
        return [faults.fire("p.q") is not None for _ in range(32)]
    a, b = fires(1234), fires(1234)
    assert a == b, "same seed must fire identically"
    assert any(a) and not all(a), "p=0.5 should mix hits and misses"


def test_unarmed_site_is_free():
    assert faults.fire("never.armed") is None
    assert faults.triggered() == {}


def test_fault_injected_is_oserror():
    # transport-failure handlers must treat injected faults like the
    # real faults they stand in for
    assert issubclass(faults.FaultInjected, OSError)


# -- backoff --------------------------------------------------------------

def test_full_jitter_bounds():
    base, cap = 0.1, 1.0
    for attempt in range(1, 8):
        for _ in range(20):
            d = retry.backoff_delay(attempt, base, cap)
            assert 0 <= d <= min(cap, base * 2 ** (attempt - 1))


def test_retry_call_retries_idempotent_then_succeeds():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    assert retry.retry_call(flaky, site="u1", peer="peerA",
                            base=0.001, cap=0.002) == "ok"
    assert len(calls) == 3
    assert retry.peer_state("peerA") == retry.CLOSED


def test_retry_call_never_reissues_non_idempotent():
    calls = []

    def dies():
        calls.append(1)
        raise OSError("boom")

    with pytest.raises(OSError):
        retry.retry_call(dies, site="u2", idempotent=False,
                         base=0.001)
    assert len(calls) == 1


def test_retry_budget_exhaustion_fails_fast(monkeypatch):
    monkeypatch.setenv("SEAWEEDFS_TPU_RETRY_BUDGET", "2")
    monkeypatch.setenv("SEAWEEDFS_TPU_RETRY_BUDGET_REFILL", "0")
    retry.reset()
    calls = []

    def dies():
        calls.append(1)
        raise OSError("down")

    # budget 2: the first call retries twice; the next call's retry
    # is refused and it fails after its FIRST attempt
    with pytest.raises(OSError):
        retry.retry_call(dies, site="u3", attempts=3, base=0.001)
    assert len(calls) == 3
    calls.clear()
    with pytest.raises(OSError):
        retry.retry_call(dies, site="u3", attempts=3, base=0.001)
    assert len(calls) == 1, "exhausted budget must fail fast"
    assert retry.budget_remaining() < 1


# -- breaker --------------------------------------------------------------

def test_breaker_trips_halfopens_and_heals(monkeypatch):
    monkeypatch.setenv("SEAWEEDFS_TPU_BREAKER_THRESHOLD", "3")
    monkeypatch.setenv("SEAWEEDFS_TPU_BREAKER_COOLDOWN_MS", "80")
    for _ in range(3):
        retry.record_failure("pX", "err")
    assert retry.peer_state("pX") == retry.OPEN
    with pytest.raises(retry.BreakerOpen):
        retry.check_peer("pX")
    time.sleep(0.1)
    assert retry.peer_state("pX") == retry.HALF_OPEN
    retry.check_peer("pX")          # admitted as the single probe
    with pytest.raises(retry.BreakerOpen):
        retry.check_peer("pX")      # second concurrent probe refused
    retry.record_success("pX")
    assert retry.peer_state("pX") == retry.CLOSED
    retry.check_peer("pX")          # closed: free passage


def test_breaker_halfopen_failure_reopens(monkeypatch):
    monkeypatch.setenv("SEAWEEDFS_TPU_BREAKER_THRESHOLD", "2")
    monkeypatch.setenv("SEAWEEDFS_TPU_BREAKER_COOLDOWN_MS", "40")
    retry.record_failure("pY")
    retry.record_failure("pY")
    assert retry.peer_state("pY") == retry.OPEN
    time.sleep(0.06)
    retry.check_peer("pY")          # probe admitted
    retry.record_failure("pY")      # probe failed
    assert retry.peer_state("pY") == retry.OPEN
    snap = retry.health_snapshot()
    assert snap["pY"]["trips"] == 2


def test_halfopen_probe_slot_released_on_unrecorded_exception(
        monkeypatch):
    """A probe whose call dies on a NON-transport exception (outside
    retry_on — nothing ever records a verdict) must give the slot
    back: before the fix, `probing` stayed set forever and every
    future check_peer refused the peer until process restart."""
    monkeypatch.setenv("SEAWEEDFS_TPU_BREAKER_THRESHOLD", "2")
    monkeypatch.setenv("SEAWEEDFS_TPU_BREAKER_COOLDOWN_MS", "40")
    retry.record_failure("pW")
    retry.record_failure("pW")
    time.sleep(0.06)
    with pytest.raises(ValueError):
        retry.retry_call(lambda: (_ for _ in ()).throw(
            ValueError("bad payload")), peer="pW")
    # the wedge: a held slot would raise BreakerOpen here forever
    retry.check_peer("pW")          # fresh probe admitted
    retry.record_success("pW")
    assert retry.peer_state("pW") == retry.CLOSED


def test_retry_call_fails_fast_on_open_breaker(monkeypatch):
    monkeypatch.setenv("SEAWEEDFS_TPU_BREAKER_THRESHOLD", "1")
    retry.record_failure("pZ")
    calls = []
    with pytest.raises(retry.BreakerOpen):
        retry.retry_call(lambda: calls.append(1), peer="pZ")
    assert not calls, "open breaker must refuse before the attempt"


def test_breaker_state_metrics_exposed():
    for _ in range(retry.breaker_threshold()):
        retry.record_failure("1.2.3.4:5", "x")
    text = stats.PROCESS.render()
    assert 'peer_breaker_state{peer="1.2.3.4:5"} 2.0' in text
    assert 'peer_breaker_trips_total{peer="1.2.3.4:5"}' in text


def test_pooled_client_retries_and_trips_breaker(monkeypatch):
    """End to end through the real client funnel: GETs to a dead port
    retry under the policy, feed the breaker, and eventually fail
    fast."""
    from seaweedfs_tpu.server.httpd import http_bytes
    monkeypatch.setenv("SEAWEEDFS_TPU_BREAKER_THRESHOLD", "4")
    monkeypatch.setenv("SEAWEEDFS_TPU_RETRY_MAX_ATTEMPTS", "2")
    monkeypatch.setenv("SEAWEEDFS_TPU_RETRY_BASE_MS", "1")
    dead = "127.0.0.1:9"  # discard port: nothing listens
    with pytest.raises(OSError):
        http_bytes("GET", f"{dead}/x", timeout=2)
    with pytest.raises(OSError):
        http_bytes("GET", f"{dead}/x", timeout=2)
    assert retry.peer_state(dead) == retry.OPEN
    t0 = time.perf_counter()
    with pytest.raises(retry.BreakerOpen):
        http_bytes("GET", f"{dead}/x", timeout=2)
    assert time.perf_counter() - t0 < 0.5, \
        "open breaker must fail fast, not burn a connect timeout"
    text = stats.PROCESS.render()
    assert "retry_attempts_total" in text
