"""GF(2^8) field math tests.

Golden values are derived from the reference's table generator
(seaweed-volume/vendor/reed-solomon-erasure/build.rs) recomputed by hand:
poly 0x11D log/exp tables are standard and checkable against known values.
"""

import numpy as np
import pytest

from seaweedfs_tpu.ops import gf256, rs_matrix


def test_log_exp_tables_roundtrip():
    for i in range(1, 256):
        assert gf256.EXP_TABLE[gf256.LOG_TABLE[i]] == i
    # exp table duplicated upper half
    for log in range(255):
        assert gf256.EXP_TABLE[log] == gf256.EXP_TABLE[log + 255]


def test_known_table_values():
    # alpha = 2 is the generator: log(2) == 1, exp(1) == 2.
    assert gf256.LOG_TABLE[1] == 0
    assert gf256.LOG_TABLE[2] == 1
    assert gf256.EXP_TABLE[0] == 1
    assert gf256.EXP_TABLE[1] == 2
    # 2^8 reduces by 0x11D: exp(8) = 0x1D = 29
    assert gf256.EXP_TABLE[8] == 29


def test_mul_matches_russian_peasant():
    def slow_mul(a, b):
        r = 0
        for _ in range(8):
            if b & 1:
                r ^= a
            b >>= 1
            carry = a & 0x80
            a = (a << 1) & 0xFF
            if carry:
                a ^= 0x1D
        return r

    rng = np.random.default_rng(0)
    for a, b in rng.integers(0, 256, size=(200, 2)):
        assert gf256.gf_mul(int(a), int(b)) == slow_mul(int(a), int(b))


def test_field_axioms():
    rng = np.random.default_rng(1)
    xs = rng.integers(0, 256, size=50)
    for a in xs:
        a = int(a)
        assert gf256.gf_mul(a, 1) == a
        assert gf256.gf_mul(a, 0) == 0
        if a != 0:
            assert gf256.gf_mul(a, gf256.gf_inv(a)) == 1
    for a, b, c in rng.integers(0, 256, size=(50, 3)):
        a, b, c = int(a), int(b), int(c)
        assert gf256.gf_mul(a, b) == gf256.gf_mul(b, a)
        assert gf256.gf_mul(a, b ^ c) == gf256.gf_mul(a, b) ^ gf256.gf_mul(a, c)


def test_exp_edge_cases():
    # reference galois_8.rs:90-102 semantics
    assert gf256.gf_exp(0, 0) == 1
    assert gf256.gf_exp(0, 5) == 0
    assert gf256.gf_exp(7, 0) == 1
    assert gf256.gf_exp(2, 1) == 2
    assert gf256.gf_exp(2, 8) == 29


def test_mul_by_pow2_decomposition():
    rng = np.random.default_rng(2)
    for c, x in rng.integers(0, 256, size=(100, 2)):
        c, x = int(c), int(x)
        acc = 0
        for b in range(8):
            if (x >> b) & 1:
                acc ^= int(gf256.MUL_BY_POW2[c, b])
        assert acc == gf256.gf_mul(c, x)


def test_vandermonde_values():
    v = rs_matrix.vandermonde(4, 3)
    # row r, col c = r^c
    assert v[0].tolist() == [1, 0, 0]      # exp(0,0)=1, exp(0,c>0)=0
    assert v[1].tolist() == [1, 1, 1]
    assert v[2].tolist() == [1, 2, 4]
    assert v[3].tolist() == [1, 3, 5]      # 3^2 = 5 in GF(2^8)


def test_matrix_inverse():
    rng = np.random.default_rng(3)
    for n in (1, 2, 5, 10):
        # Vandermonde-derived matrices are invertible
        m = rs_matrix.build_matrix(n, n + 3)[: n]
        assert np.array_equal(m, np.eye(n, dtype=np.uint8))
        sub = rs_matrix.build_matrix(n, n + 3)[3: 3 + n]
        inv = rs_matrix.gf_invert_matrix(sub)
        assert np.array_equal(
            gf256.gf_matmul(sub, inv), np.eye(n, dtype=np.uint8))


def test_singular_matrix_raises():
    m = np.zeros((2, 2), dtype=np.uint8)
    with pytest.raises(ValueError):
        rs_matrix.gf_invert_matrix(m)


def test_build_matrix_identity_top():
    for d, p in ((10, 4), (6, 3), (3, 2), (1, 1)):
        g = rs_matrix.build_matrix(d, d + p)
        assert g.shape == (d + p, d)
        assert np.array_equal(g[:d], np.eye(d, dtype=np.uint8))


def test_build_matrix_known_rs_3_2():
    # Independently computed klauspost-style matrix for RS(3,2):
    # V = vandermonde(5,3); G = V @ inv(V[:3,:3]).  Parity rows must be
    # deterministic; spot-check via explicit gf math.
    g = rs_matrix.build_matrix(3, 5)
    v = rs_matrix.vandermonde(5, 3)
    top_inv = rs_matrix.gf_invert_matrix(v[:3, :3])
    expect = gf256.gf_matmul(v, top_inv)
    assert np.array_equal(g, expect)
    # and G restricted to any 3 rows is invertible (MDS property)
    import itertools
    for rows in itertools.combinations(range(5), 3):
        sub = g[list(rows)]
        rs_matrix.gf_invert_matrix(sub)  # must not raise


def test_gf_apply_matrix_matches_scalar():
    rng = np.random.default_rng(4)
    mat = rng.integers(0, 256, size=(4, 10)).astype(np.uint8)
    data = rng.integers(0, 256, size=(10, 33)).astype(np.uint8)
    out = gf256.gf_apply_matrix(mat, data)
    for j in range(4):
        for col in range(33):
            acc = 0
            for i in range(10):
                acc ^= gf256.gf_mul(int(mat[j, i]), int(data[i, col]))
            assert out[j, col] == acc
