"""Volume engine tests: write/read/delete/overwrite/vacuum round-trips
(the unit-level analog of the reference's storage tests, SURVEY §4.1)."""

import os

import pytest

from seaweedfs_tpu.storage import types
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.replica_placement import ReplicaPlacement
from seaweedfs_tpu.storage.ttl import read_ttl
from seaweedfs_tpu.storage.volume import (
    CookieMismatch, NeedleDeleted, NeedleNotFound, Volume)
from seaweedfs_tpu.storage.volume_info import (
    EcShardConfig, VolumeInfo, maybe_load_volume_info, save_volume_info)


@pytest.fixture
def vol(tmp_path):
    v = Volume(str(tmp_path), 7, collection="col",
               replica_placement=ReplicaPlacement.from_string("000"))
    yield v
    v.close()


def test_write_read_roundtrip(vol):
    n = Needle(cookie=0xABCD, id=1, data=b"x" * 1000)
    off, size, unchanged = vol.write_needle(n)
    assert not unchanged and size == 1000
    m = vol.read_needle(1, cookie=0xABCD)
    assert m.data == b"x" * 1000


def test_write_same_content_is_unchanged(vol):
    n = Needle(cookie=5, id=2, data=b"dup")
    vol.write_needle(n)
    _, _, unchanged = vol.write_needle(Needle(cookie=5, id=2, data=b"dup"))
    assert unchanged


def test_overwrite_requires_cookie(vol):
    vol.write_needle(Needle(cookie=5, id=3, data=b"v1"))
    with pytest.raises(CookieMismatch):
        vol.write_needle(Needle(cookie=6, id=3, data=b"v2"))
    vol.write_needle(Needle(cookie=5, id=3, data=b"v2"))
    assert vol.read_needle(3).data == b"v2"


def test_delete_and_tombstone(vol):
    vol.write_needle(Needle(cookie=1, id=4, data=b"gone"))
    freed = vol.delete_needle(Needle(cookie=1, id=4))
    assert freed > 0
    with pytest.raises(NeedleDeleted):
        vol.read_needle(4)
    # reopen: tombstone replays from .idx
    vol.close()
    v2 = Volume(vol.dir, vol.id, collection=vol.collection)
    with pytest.raises((NeedleDeleted, NeedleNotFound)):
        v2.read_needle(4)
    v2.close()
    vol._dat = open(vol.file_name(".dat"), "r+b")  # let fixture close()
    vol.nm._idx_file = open(vol.file_name(".idx"), "r+b")


def test_reopen_preserves_data(tmp_path):
    v = Volume(str(tmp_path), 9)
    v.write_needle(Needle(cookie=3, id=10, data=b"persist"))
    v.close()
    v2 = Volume(str(tmp_path), 9)
    assert v2.read_needle(10).data == b"persist"
    assert v2.version == types.CURRENT_VERSION
    v2.close()


def test_ttl_volume_applies_to_needles(tmp_path):
    v = Volume(str(tmp_path), 11, ttl=read_ttl("5d"))
    v.write_needle(Needle(cookie=1, id=1, data=b"ttl"))
    n = v.read_needle(1)
    assert str(n.ttl) == "5d"
    v.close()


def test_vacuum_reclaims_garbage(tmp_path):
    v = Volume(str(tmp_path), 12)
    for i in range(10):
        v.write_needle(Needle(cookie=i, id=i + 1, data=bytes(200)))
    for i in range(5):
        v.delete_needle(Needle(cookie=i, id=i + 1))
    assert v.garbage_level() > 0
    size_before = v.dat_size()
    rev_before = v.super_block.compaction_revision
    v.vacuum()
    assert v.dat_size() < size_before
    assert v.super_block.compaction_revision == rev_before + 1
    assert v.garbage_level() == 0
    for i in range(5, 10):
        assert v.read_needle(i + 1).data == bytes(200)
    for i in range(5):
        with pytest.raises((NeedleDeleted, NeedleNotFound)):
            v.read_needle(i + 1)
    v.close()


def test_append_at_ns_monotonic(vol):
    ids = []
    for i in range(3):
        vol.write_needle(Needle(cookie=1, id=100 + i, data=b"t"))
        ids.append(vol.last_append_at_ns)
    assert ids == sorted(ids) and len(set(ids)) == 3


def test_volume_info_roundtrip(tmp_path):
    p = str(tmp_path / "1.vif")
    vi = VolumeInfo(version=3, replication="010", dat_file_size=12345,
                    ec_shard_config=EcShardConfig(10, 4))
    save_volume_info(p, vi)
    back = maybe_load_volume_info(p)
    assert back.version == 3
    assert back.replication == "010"
    assert back.dat_file_size == 12345
    assert back.ec_shard_config.data_shards == 10
    assert back.ec_shard_config.parity_shards == 4
    # empty file behaves as absent (volume_info.go:46)
    open(p, "w").close()
    assert maybe_load_volume_info(p) is None


def test_read_only_volume_rejects_writes(vol):
    vol.read_only = True
    with pytest.raises(PermissionError):
        vol.write_needle(Needle(cookie=1, id=50, data=b"no"))


def test_vacuum_makeup_diff_replays_concurrent_writes(tmp_path):
    """Writes and deletes landing BETWEEN compact() and
    commit_compact() must survive the vacuum (volume_vacuum.go:241
    makeupDiff) — the round-2 build serialized writes behind the
    whole compaction instead."""
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.volume import Volume

    v = Volume(str(tmp_path), 77)
    for i in range(1, 6):
        v.write_needle(Needle(cookie=i, id=i,
                              data=b"pre-%d" % i * 50))
    v.delete_needle(Needle(cookie=2, id=2))

    v.compact()  # snapshot taken; shadows written

    # mutations AFTER the snapshot: create, overwrite, delete
    v.write_needle(Needle(cookie=6, id=6, data=b"post-new"))
    v.write_needle(Needle(cookie=3, id=3, data=b"post-overwrite"))
    v.delete_needle(Needle(cookie=4, id=4))

    v.commit_compact()

    assert v.read_needle(1).data == b"pre-1" * 50
    with pytest.raises(KeyError):
        v.read_needle(2)  # deleted pre-snapshot: reclaimed
    assert v.read_needle(3).data == b"post-overwrite"
    with pytest.raises(KeyError):
        v.read_needle(4)  # deleted post-snapshot: replayed
    assert v.read_needle(5).data == b"pre-5" * 50
    assert v.read_needle(6).data == b"post-new"
    # a fresh load from disk agrees (the .idx tail replay persisted)
    v.close()
    v2 = Volume(str(tmp_path), 77)
    assert v2.read_needle(6).data == b"post-new"
    assert v2.read_needle(3).data == b"post-overwrite"
    with pytest.raises(KeyError):
        v2.read_needle(4)
    v2.close()


def test_compact_rejects_concurrent_compaction(tmp_path):
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.volume import Volume

    v = Volume(str(tmp_path), 78)
    v.write_needle(Needle(cookie=1, id=1, data=b"x"))
    v.compact()
    with pytest.raises(RuntimeError, match="already compacting"):
        v.compact()
    v.commit_compact()
    v.vacuum()  # flag cleared: a fresh cycle works
    v.close()
