"""Native C++ read plane (native/read_plane.cc + server/read_plane.py):
cross-implementation parity with the Python read path — the pattern the
reference uses to validate its Rust volume server against Go
(test/volume_server/rust/rust_volume_test.go) — plus lifecycle
correctness (delete, vacuum, volume drop, fallback semantics)."""

import time

import pytest

from seaweedfs_tpu import operation
from seaweedfs_tpu.server.httpd import http_bytes, http_json
from seaweedfs_tpu.server.master_server import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer

pytest.importorskip("seaweedfs_tpu.server.read_plane")
from seaweedfs_tpu.native import load_read_plane  # noqa: E402

pytestmark = pytest.mark.skipif(load_read_plane() is None,
                                reason="no native toolchain")


@pytest.fixture
def cluster(tmp_path):
    master = MasterServer(volume_size_limit_mb=32).start()
    vs = VolumeServer([str(tmp_path / "v0")], master.url,
                      pulse_seconds=0.2).start()
    time.sleep(0.4)
    yield master, vs
    vs.stop()
    master.stop()


def _rp_get(vs, fid):
    return http_bytes(
        "GET", f"127.0.0.1:{vs.read_plane.port}/{fid}", timeout=5)


def test_parity_with_python_path(cluster):
    """Same fid through both implementations -> identical bytes."""
    master, vs = cluster
    assert vs.read_plane is not None
    fids = []
    for i in range(20):
        a = operation.assign(master.url)
        payload = bytes([i]) * (100 + 37 * i)
        operation.upload(a.url, a.fid, payload)
        fids.append((a.fid, payload))
    for fid, want in fids:
        st_py, body_py, _ = http_bytes("GET", f"{vs.url}/{fid}")
        st_rp, body_rp, hdrs = _rp_get(vs, fid)
        assert st_py == st_rp == 200, fid
        assert body_py == body_rp == want, fid
        assert hdrs["Content-Length"] == str(len(want))
    assert vs.read_plane.served() >= 20


def test_cookie_mismatch_and_unknown_404(cluster):
    master, vs = cluster
    a = operation.assign(master.url)
    operation.upload(a.url, a.fid, b"guarded")
    vid, rest = a.fid.split(",", 1)
    bad_cookie = rest[:-8] + ("0" * 8 if rest[-8:] != "0" * 8
                              else "1" * 8)
    st, _, _ = _rp_get(vs, f"{vid},{bad_cookie}")
    assert st == 404
    st, _, _ = _rp_get(vs, f"{vid},ffffffffffffffff")
    assert st == 404
    st, _, _ = _rp_get(vs, "not-a-fid")
    assert st == 404


def test_named_and_mime_needles_stay_on_python_path(cluster):
    """Needles with a name/mime have HTTP semantics the plane doesn't
    carry: it must 404 them so clients fall back."""
    master, vs = cluster
    a = operation.assign(master.url)
    operation.upload(a.url, a.fid, b"<b>html</b>", name="page.html",
                     mime="text/html")
    st, _, _ = _rp_get(vs, a.fid)
    assert st == 404
    # the full path still serves it with its mime
    st, body, hdrs = http_bytes("GET", f"{vs.url}/{a.fid}")
    assert st == 200 and body == b"<b>html</b>"
    assert hdrs["Content-Type"].startswith("text/html")


def _warm(vs, fid):
    """Deterministic plane warm: a Python-port read lazily registers
    the needle (the plane's documented contract) — registration off
    the write path rides the native write plane's pump tick now, so
    tests must not assume it landed the instant the upload acked."""
    st, _, _ = http_bytes("GET", f"{vs.url}/{fid}")
    assert st == 200


def test_delete_drops_entry(cluster):
    master, vs = cluster
    a = operation.assign(master.url)
    operation.upload(a.url, a.fid, b"temporary")
    _warm(vs, a.fid)
    assert _rp_get(vs, a.fid)[0] == 200
    operation.delete(master.url, a.fid)
    st, _, _ = _rp_get(vs, a.fid)
    assert st == 404


def test_vacuum_drops_then_lazily_reregisters(cluster):
    """Compaction moves offsets: the plane's volume index is dropped
    before the .dat swap, and a Python read re-registers survivors
    against the fresh file."""
    master, vs = cluster
    a = operation.assign(master.url)
    operation.upload(a.url, a.fid, b"keep-me")
    b = operation.assign(master.url)
    operation.upload(b.url, b.fid, b"delete-me")
    _warm(vs, a.fid)
    assert _rp_get(vs, a.fid)[0] == 200
    operation.delete(master.url, b.fid)
    vid = int(a.fid.split(",")[0])
    r = http_json("POST", f"{vs.url}/admin/vacuum",
                  {"volumeId": vid})
    assert "error" not in r
    # dropped: the plane no longer serves the volume...
    assert _rp_get(vs, a.fid)[0] == 404
    # ...until a read through the Python path re-registers it
    st, body, _ = http_bytes("GET", f"{vs.url}/{a.fid}")
    assert st == 200 and body == b"keep-me"
    st, body, _ = _rp_get(vs, a.fid)
    assert st == 200 and body == b"keep-me"


def test_operation_read_uses_fast_path_transparently(cluster):
    """operation.read returns correct bytes with the plane active (the
    fast path must be invisible to callers)."""
    master, vs = cluster
    a = operation.assign(master.url)
    operation.upload(a.url, a.fid, b"through-the-plane" * 50)
    assert operation.read(master.url, a.fid) == \
        b"through-the-plane" * 50


def test_keepalive_many_requests_one_connection(cluster):
    """The plane holds keep-alive: many sequential requests through
    the pooled client complete on one socket."""
    master, vs = cluster
    a = operation.assign(master.url)
    operation.upload(a.url, a.fid, b"ka")
    _warm(vs, a.fid)
    before = vs.read_plane.served()
    for _ in range(50):
        st, body, _ = _rp_get(vs, a.fid)
        assert st == 200 and body == b"ka"
    assert vs.read_plane.served() >= before + 50
