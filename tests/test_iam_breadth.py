"""Round-5 IAM breadth: group-inherited grants in the auth path,
service accounts (iam.proto ServiceAccount), key rotation, and the
export/import + bucket access/lock shell families
(weed/shell/command_s3_group_*.go, command_s3_serviceaccount_*.go,
command_s3_accesskey_rotate.go, command_s3_iam_export.go,
command_s3_bucket_access.go, command_s3_bucket_lock.go;
weed/s3api/auth_credentials.go evaluateIAMPolicies)."""

import json
import time

import pytest

from seaweedfs_tpu.iam.identity import (Credential, Identity,
                                        IdentityStore)
from seaweedfs_tpu.shell import run_command
from seaweedfs_tpu.shell.commands import CommandEnv


POLICY_RW_DOCS = json.dumps({
    "Version": "2012-10-17",
    "Statement": [{"Effect": "Allow",
                   "Action": ["s3:GetObject", "s3:PutObject",
                              "s3:ListBucket"],
                   "Resource": "arn:aws:s3:::docs/*"}]})


def _store(tmp_path, name="s3.json") -> IdentityStore:
    return IdentityStore(str(tmp_path / name))


# -- group grants in can_do ------------------------------------------------


def test_group_policy_grants_members(tmp_path):
    store = _store(tmp_path)
    store.put(Identity("carol", [Credential("AK1", "SK1")]))
    store.put_policy("docs-rw", POLICY_RW_DOCS)
    store.put_group("writers", {"name": "writers",
                                "members": ["carol"],
                                "policyNames": ["docs-rw"],
                                "disabled": False})
    carol = store.get("carol")
    assert carol.can_do("Read", "docs", "a.txt")
    assert carol.can_do("Write", "docs", "a.txt")
    assert not carol.can_do("Write", "other")
    # grants survive a reload from disk (derived state recomputed)
    again = IdentityStore(store.path).get("carol")
    assert again.can_do("Write", "docs", "x")
    # detaching the group revokes for every member atomically
    store.delete_group("writers")
    assert not store.get("carol").can_do("Read", "docs", "a.txt")


def test_disabled_group_grants_nothing(tmp_path):
    store = _store(tmp_path)
    store.put(Identity("dave", [Credential("AK2", "SK2")]))
    store.put_policy("docs-rw", POLICY_RW_DOCS)
    store.put_group("g", {"name": "g", "members": ["dave"],
                          "policyNames": ["docs-rw"],
                          "disabled": True})
    assert not store.get("dave").can_do("Read", "docs", "a")


def test_policy_edit_propagates_to_group_members(tmp_path):
    store = _store(tmp_path)
    store.put(Identity("erin", [Credential("AK3", "SK3")]))
    store.put_group("g", {"name": "g", "members": ["erin"],
                          "policyNames": ["p"], "disabled": False})
    assert not store.get("erin").can_do("Read", "docs", "a")
    store.put_policy("p", POLICY_RW_DOCS)
    assert store.get("erin").can_do("Read", "docs", "a")
    store.delete_policy("p")
    assert not store.get("erin").can_do("Read", "docs", "a")


def test_malformed_group_fails_closed_not_mid_recompute(tmp_path):
    """ISSUE 6 satellite: a malformed group entry (non-dict, bogus
    member/policy lists) must DROP that group's grant and keep the
    recompute going — raising mid-recompute left a half-updated grant
    map (some identities stale, some cleared)."""
    store = _store(tmp_path)
    store.put(Identity("carol", [Credential("AK1", "SK1")]))
    store.put(Identity("dave", [Credential("AK2", "SK2")]))
    store.put_policy("docs-rw", POLICY_RW_DOCS)
    store.put_group("writers", {"name": "writers",
                                "members": ["carol"],
                                "policyNames": ["docs-rw"]})
    assert store.get("carol").can_do("Write", "docs", "a.txt")
    # a malformed group lands (corrupt config push): non-list members
    store.put_group("broken", {"members": 42,
                               "policyNames": ["docs-rw"]})
    # ...and an outright non-dict entry straight in the map, as a
    # corrupted s3.json reload would produce
    store._groups["worse"] = "not-a-dict"
    store.put_group("also", {"members": ["dave"],
                             "policyNames": 7})
    # no exception above, the healthy group's grant still stands, and
    # the malformed ones granted nothing
    carol = store.get("carol")
    assert carol.can_do("Write", "docs", "a.txt")
    assert not store.get("dave").can_do("Write", "docs", "a.txt")


# -- service accounts ------------------------------------------------------


def test_service_account_auth_and_restriction(tmp_path):
    store = _store(tmp_path)
    store.put(Identity("app-owner", [Credential("AKP", "SKP")],
                       actions=["Read:data", "Write:data",
                                "List:data"]))
    store.put_service_account({
        "id": "sa-1", "parentUser": "app-owner",
        "credential": {"accessKey": "SAKEY", "secretKey": "SASEC"},
        "actions": ["Read:data"], "expiration": 0,
        "disabled": False})
    ident = store.by_access_key("SAKEY")
    assert ident is not None and ident.name == "app-owner"
    assert store.secret_for("SAKEY") == "SASEC"
    assert ident.can_do("Read", "data", "f")
    # restricted below the parent: Write denied through the SA key
    assert not ident.can_do("Write", "data", "f")
    # unrestricted SA inherits the parent's full set
    store.put_service_account({
        "id": "sa-2", "parentUser": "app-owner",
        "credential": {"accessKey": "SAKEY2", "secretKey": "X"},
        "actions": [], "expiration": 0, "disabled": False})
    assert store.by_access_key("SAKEY2").can_do("Write", "data", "f")


def test_service_account_expiry_and_parent_disable(tmp_path):
    store = _store(tmp_path)
    store.put(Identity("p", [Credential("PK", "PS")],
                       actions=["Read:b"]))
    store.put_service_account({
        "id": "sa-e", "parentUser": "p",
        "credential": {"accessKey": "EK", "secretKey": "ES"},
        "actions": [], "expiration": int(time.time()) - 5,
        "disabled": False})
    assert store.secret_for("EK") is None          # expired
    store.put_service_account({
        "id": "sa-l", "parentUser": "p",
        "credential": {"accessKey": "LK", "secretKey": "LS"},
        "actions": [], "expiration": 0, "disabled": False})
    assert store.secret_for("LK") == "LS"
    parent = store.get("p")
    parent.disabled = True
    store.put(parent)
    assert store.secret_for("LK") is None          # parent disabled
    # deleting the SA removes the key entirely
    store.delete_service_account("sa-l")
    assert store.by_access_key("LK") is None


# -- shell families (no cluster needed for the store-only commands) -------


@pytest.fixture()
def env(tmp_path):
    e = CommandEnv("http://127.0.0.1:1")     # master never dialed here
    e.iam_config = str(tmp_path / "s3.json")
    return e


def test_shell_group_family(env):
    run_command(env, "s3.user.create -user=u1")
    with pytest.raises(RuntimeError):
        run_command(env, "s3.group.create -name=g -policies=missing")
    run_command(env,
                "s3.policy -name=rw -content=" + POLICY_RW_DOCS
                .replace(" ", ""))
    run_command(env, "s3.group.create -name=g -policies=rw")
    with pytest.raises(RuntimeError):
        run_command(env, "s3.group.create -name=g")
    run_command(env, "s3.group.add.user -name=g -user=u1")
    assert "u1 already in g" in run_command(
        env, "s3.group.add.user -name=g -user=u1")
    show = json.loads(run_command(env, "s3.group.show -name=g"))
    assert show["members"] == ["u1"]
    assert "members=1" in run_command(env, "s3.group.list")
    # the grant is live through the same store file
    store = IdentityStore(env.iam_config)
    assert store.get("u1").can_do("Write", "docs", "f")
    run_command(env, "s3.group.remove.user -name=g -user=u1")
    assert not IdentityStore(env.iam_config).get("u1").can_do(
        "Write", "docs", "f")
    run_command(env, "s3.group.delete -name=g")
    assert "(no groups)" in run_command(env, "s3.group.list")


def test_shell_policy_command(env):
    assert "(no managed policies)" in run_command(env,
                                                  "s3.policy -list")
    with pytest.raises(Exception):
        run_command(env, "s3.policy -name=bad -content={\"x\":1}")
    run_command(env, "s3.policy -name=rw -content=" +
                POLICY_RW_DOCS.replace(" ", ""))
    assert "rw" in run_command(env, "s3.policy -list")
    assert "GetObject" in run_command(env, "s3.policy -name=rw")
    run_command(env, "s3.policy -name=rw -delete")
    assert "(no managed policies)" in run_command(env,
                                                  "s3.policy -list")


def test_shell_serviceaccount_family(env):
    run_command(env,
                "s3.user.create -user=parent -actions=Read:b,List:b")
    # cannot exceed the parent
    with pytest.raises(RuntimeError):
        run_command(env, "s3.serviceaccount.create -user=parent "
                         "-actions=Write:b")
    out = run_command(env, "s3.serviceaccount.create -user=parent "
                           "-actions=Read:b -expiry=1h")
    sa_id = out.splitlines()[0].split()[1]
    key = [ln for ln in out.splitlines()
           if ln.startswith("accessKey:")][0].split()[1]
    assert sa_id.startswith("sa-")
    listing = run_command(env, "s3.serviceaccount.list -user=parent")
    assert sa_id in listing and "active" in listing
    shown = json.loads(run_command(
        env, f"s3.serviceaccount.show -id={sa_id}"))
    assert shown["credential"]["secretKey"] == "<redacted>"
    store = IdentityStore(env.iam_config)
    ident = store.by_access_key(key)
    assert ident.can_do("Read", "b") and \
        not ident.can_do("List", "b")
    run_command(env, f"s3.serviceaccount.delete -id={sa_id}")
    assert "(no service accounts)" in run_command(
        env, "s3.serviceaccount.list")


def test_shell_accesskey_rotate(env):
    out = run_command(env, "s3.user.create -user=rot")
    old = [ln for ln in out.splitlines()
           if ln.startswith("accessKey:")][0].split()[1]
    out = run_command(env, "s3.accesskey.rotate -user=rot")
    assert f"rotated {old} ->" in out
    new = out.splitlines()[0].split()[-1]
    store = IdentityStore(env.iam_config)
    assert store.by_access_key(old) is None
    assert store.by_access_key(new).name == "rot"
    # ambiguous with two keys unless -accessKey names one
    run_command(env, "s3.accesskey.create -user=rot")
    with pytest.raises(RuntimeError):
        run_command(env, "s3.accesskey.rotate -user=rot")
    run_command(env, f"s3.accesskey.rotate -user=rot -accessKey={new}")
    assert IdentityStore(env.iam_config).by_access_key(new) is None


def test_shell_iam_export_import(env, tmp_path):
    run_command(env, "s3.user.create -user=ex1 -actions=Read:b")
    run_command(env, "s3.policy -name=rw -content=" +
                POLICY_RW_DOCS.replace(" ", ""))
    run_command(env, "s3.group.create -name=g -policies=rw")
    run_command(env, "s3.serviceaccount.create -user=ex1")
    dump = str(tmp_path / "dump.json")
    run_command(env, f"s3.iam.export -file={dump}")
    doc = json.load(open(dump))
    assert doc["groups"]["g"]["policyNames"] == ["rw"]
    assert doc["serviceAccounts"][0]["parentUser"] == "ex1"
    # wipe by importing into a fresh config, then verify round-trip
    env2 = CommandEnv("http://127.0.0.1:1")
    env2.iam_config = str(tmp_path / "other.json")
    run_command(env2, "s3.user.create -user=existing")
    out = run_command(env2, f"s3.iam.import -file={dump} -merge")
    assert "imported" in out
    store = IdentityStore(env2.iam_config)
    assert store.get("ex1") is not None
    assert store.get("existing") is not None       # -merge kept it
    assert store.get_policy("rw") is not None
    # full replace drops entries not in the dump
    run_command(env2, f"s3.iam.import -file={dump}")
    assert IdentityStore(env2.iam_config).get("existing") is None


def test_bucket_access_none_warns_about_group_grants(env):
    """Review r5: -access=none cannot strip group-inherited grants;
    the command must say so instead of reporting 'none'."""
    run_command(env, "s3.user.create -user=gm")
    run_command(env, "s3.policy -name=rw -content=" +
                POLICY_RW_DOCS.replace(" ", ""))
    run_command(env, "s3.group.create -name=g -policies=rw")
    run_command(env, "s3.group.add.user -name=g -user=gm")
    out = run_command(env,
                      "s3.bucket.access -name=docs -user=gm "
                      "-access=none")
    assert "WARNING" in out and "inherited via groups" in out
    # and the view path shows the surviving grant too
    out = run_command(env, "s3.bucket.access -name=docs -user=gm")
    assert "docs" in out and "none" not in out


def test_bucket_access_none_strips_path_scoped_grants(env):
    """Review r5 (2nd pass): path-scoped grants (Read:b/prefix) target
    the bucket too; -access=none must strip them, not report 'none'
    while they survive."""
    run_command(env, "s3.user.create -user=ps "
                     "-actions=Read:accb/docs,Write:accb,Read:other")
    out = run_command(env, "s3.bucket.access -name=accb -user=ps")
    assert "Read:accb/docs" in out and "Write:accb" in out
    run_command(env, "s3.bucket.access -name=accb -user=ps "
                     "-access=none")
    i = IdentityStore(env.iam_config).get("ps")
    assert not i.can_do("Read", "accb", "docs/f.txt")
    assert i.can_do("Read", "other")          # untouched
    out = run_command(env, "s3.bucket.access -name=accb -user=ps")
    assert "none" in out


def test_service_account_shrinks_with_parent_revocation(tmp_path):
    """Review r5 (2nd pass): the subset invariant holds at AUTH time —
    revoking the parent's grant revokes it from SAs that named it."""
    store = _store(tmp_path)
    store.put(Identity("boss", [Credential("BK", "BS")],
                       actions=["Read:pay", "Write:pay"]))
    store.put_service_account({
        "id": "sa-w", "parentUser": "boss",
        "credential": {"accessKey": "WK", "secretKey": "WS"},
        "actions": ["Write:pay"], "expiration": 0,
        "disabled": False})
    assert store.by_access_key("WK").can_do("Write", "pay")
    boss = store.get("boss")
    boss.actions = ["Read:pay"]
    boss.static_actions = ["Read:pay"]
    store.put(boss)
    sa_ident = store.by_access_key("WK")
    assert not sa_ident.can_do("Write", "pay")
    assert not sa_ident.can_do("Read", "pay")   # never granted to SA
