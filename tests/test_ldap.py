"""LDAP identity provider (VERDICT r3 Missing #6; weed/iam/ldap/
ldap_provider.go): from-scratch RFC 4511 BER client driven against a
real socket server, plus the SFTP gateway consuming it."""

import pytest

from seaweedfs_tpu.iam.ldap import (LdapClient, LdapError,
                                    LdapProvider, MiniLdapServer)

from conftest import needs_crypto as _needs_crypto

USERS = {
    "uid=ada,ou=people,dc=example,dc=com": (
        "lovelace", {"uid": ["ada"], "cn": ["Ada Lovelace"],
                     "mail": ["ada@example.com"]}),
    "uid=alan,ou=people,dc=example,dc=com": (
        "turing1912", {"uid": ["alan"], "cn": ["Alan Turing"]}),
    "cn=svc,dc=example,dc=com": ("svcpass", {"cn": ["svc"]}),
}


@pytest.fixture
def ldap_server():
    s = MiniLdapServer(USERS).start()
    yield s
    s.stop()


def test_bind_and_search(ldap_server):
    c = LdapClient("127.0.0.1", ldap_server.port)
    try:
        assert c.bind("uid=ada,ou=people,dc=example,dc=com",
                      "lovelace")
        hit = c.search_one("dc=example,dc=com", "uid", "ada",
                           ["cn", "mail"])
        assert hit is not None
        dn, attrs = hit
        assert dn == "uid=ada,ou=people,dc=example,dc=com"
        assert attrs["cn"] == ["Ada Lovelace"]
        assert c.search_one("dc=example,dc=com", "uid", "nobody",
                            ["cn"]) is None
    finally:
        c.close()
    c2 = LdapClient("127.0.0.1", ldap_server.port)
    try:
        assert not c2.bind("uid=ada,ou=people,dc=example,dc=com",
                           "wrong")
    finally:
        c2.close()


def test_provider_dn_template(ldap_server):
    p = LdapProvider(
        "127.0.0.1", ldap_server.port,
        user_dn_template="uid={},ou=people,dc=example,dc=com")
    ident = p.authenticate("ada", "lovelace")
    assert ident and ident["name"] == "ada"
    assert p.authenticate("ada", "wrong") is None
    assert p.authenticate("ada", "") is None  # RFC 4513: no
    # unauthenticated-bind "success"


def test_provider_search_flow_with_attr_mapping(ldap_server):
    p = LdapProvider(
        "127.0.0.1", ldap_server.port,
        base_dn="dc=example,dc=com",
        bind_dn="cn=svc,dc=example,dc=com", bind_password="svcpass",
        user_attr="uid",
        attr_map={"displayName": "cn", "email": "mail"})
    ident = p.authenticate("ada", "lovelace")
    assert ident["displayName"] == "Ada Lovelace"
    assert ident["email"] == "ada@example.com"
    assert ident["dn"] == "uid=ada,ou=people,dc=example,dc=com"
    assert p.authenticate("ghost", "x") is None
    assert p.authenticate("alan", "turing1912")["name"] == "alan"


def test_provider_outage_raises_not_rejects():
    p = LdapProvider("127.0.0.1", 1,  # nothing listens there
                     user_dn_template="uid={},dc=x")
    with pytest.raises(OSError):
        p.authenticate("ada", "pw")


@_needs_crypto
def test_sftp_login_via_ldap(ldap_server, tmp_path):
    """End-to-end: an sftp client authenticates with directory
    credentials (no local user) and gets a working session."""
    import time

    from seaweedfs_tpu.server.filer_server import FilerServer
    from seaweedfs_tpu.server.master_server import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer
    from seaweedfs_tpu.sftp.client import SftpClient
    from seaweedfs_tpu.sftp.server import SftpService
    from seaweedfs_tpu.sftp.users import UserStore

    provider = LdapProvider(
        "127.0.0.1", ldap_server.port,
        user_dn_template="uid={},ou=people,dc=example,dc=com")
    master = MasterServer().start()
    vs = VolumeServer([str(tmp_path / "v0")], master.url,
                      pulse_seconds=0.3).start()
    time.sleep(0.4)
    filer = FilerServer(master.url).start()
    svc = SftpService(filer.filer, UserStore(), ldap=provider)
    svc.start()
    try:
        c = SftpClient("127.0.0.1", svc.port, "ada",
                       password="lovelace")
        c.mkdir("/home/ada/docs")
        c.write_file("/home/ada/docs/hi.txt", b"via ldap")
        assert c.read_file("/home/ada/docs/hi.txt") == b"via ldap"
        c.close()

        # repeat login works (the directory stays the source of
        # truth; nothing was provisioned into the local store)
        c2 = SftpClient("127.0.0.1", svc.port, "ada",
                        password="lovelace")
        assert c2.read_file("/home/ada/docs/hi.txt") == b"via ldap"
        c2.close()
        assert svc.users.get("ada") is None

        with pytest.raises(Exception):
            SftpClient("127.0.0.1", svc.port, "ada",
                       password="wrongpass")
    finally:
        svc.stop()
        filer.stop()
        vs.stop()
        master.stop()
