"""Pallas kernel bit-identity vs the numpy twin (interpret mode on CPU)."""

import numpy as np
import pytest

from seaweedfs_tpu.ops import gf256, rs_matrix
from seaweedfs_tpu.ops.rs_pallas import (TILE_WORDS, expand_tables,
                                         gf_apply_matrix_pallas)


@pytest.mark.parametrize("d,p", [(10, 4), (6, 3)])
def test_pallas_parity_bit_identical(d, p):
    rng = np.random.default_rng(d * 10 + p)
    mat = rs_matrix.parity_matrix(d, p)
    data = rng.integers(0, 256, size=(d, TILE_WORDS * 4), dtype=np.uint8)
    got = np.asarray(gf_apply_matrix_pallas(mat, data))
    want = gf256.gf_apply_matrix(mat, data)
    assert np.array_equal(got, want)


def test_pallas_unaligned_length_padding():
    rng = np.random.default_rng(3)
    mat = rs_matrix.parity_matrix(4, 2)
    data = rng.integers(0, 256, size=(4, 12345), dtype=np.uint8)
    got = np.asarray(gf_apply_matrix_pallas(mat, data))
    want = gf256.gf_apply_matrix(mat, data)
    assert got.shape == (2, 12345)
    assert np.array_equal(got, want)


def test_pallas_decode_matrix_apply():
    # arbitrary (non-parity) matrices must work through the same kernel
    rng = np.random.default_rng(4)
    mat = rng.integers(0, 256, size=(3, 5)).astype(np.uint8)
    data = rng.integers(0, 256, size=(5, 4096), dtype=np.uint8)
    got = np.asarray(gf_apply_matrix_pallas(mat, data))
    want = gf256.gf_apply_matrix(mat, data)
    assert np.array_equal(got, want)


def test_expand_tables_shape():
    mat = rs_matrix.parity_matrix(10, 4)
    t = expand_tables(mat)
    assert t.shape == (4 * 10 * 8,)
    assert t.dtype == np.uint32
