"""Analyzer engine + rules + lockgraph tests.

Every SWFS rule gets a positive fixture (must flag), a negative fixture
(must stay silent), and a suppression check; the engine tests cover
noqa semantics and the baseline workflow; the lockgraph tests construct
a real AB/BA inversion across two threads and assert the cycle is
caught."""

import json
import textwrap
import threading

import pytest

from seaweedfs_tpu.devtools import lockgraph as lg
from seaweedfs_tpu.devtools.analyze import (FileContext, fingerprints,
                                            load_baseline,
                                            partition_baseline,
                                            run_paths, save_baseline)
from seaweedfs_tpu.devtools.rules import RULES


def check(source: str, rule_id: str):
    """Run one rule over an inline snippet; returns findings."""
    src = textwrap.dedent(source)
    ctx = FileContext("<fixture>.py", "fixture.py", src)
    rule = next(r for r in RULES if r.id == rule_id)
    return [f for f in rule.check(ctx)
            if not ctx.suppressed(f.rule, f.line)]


def analyze_tree(tmp_path, name, source):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    findings, errors = run_paths([str(tmp_path)])
    assert not errors
    return findings


# -- SWFS001: lock discipline --------------------------------------------

LOCKY = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0
        def incr(self):
            with self._lock:
                self.n += 1
        def reset(self):
            self.n = 0{noqa}
"""


def test_swfs001_flags_unguarded_mutation():
    found = check(LOCKY.format(noqa=""), "SWFS001")
    assert len(found) == 1
    assert found[0].line and "Counter.n" in found[0].message


def test_swfs001_noqa_suppresses():
    assert check(LOCKY.format(noqa="  # noqa: SWFS001"), "SWFS001") == []


def test_swfs001_negative_all_guarded_and_conventions():
    src = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0          # __init__ is pre-publication
        def incr(self):
            with self._lock:
                self.n += 1
        def _bump_locked(self):
            self.n += 1         # _locked suffix: caller holds
        def _bump2(self):
            \"\"\"Caller holds the lock.\"\"\"
            self.n += 1
    """
    assert check(src, "SWFS001") == []


def test_swfs001_foreign_noqa_does_not_suppress():
    found = check(LOCKY.format(noqa="  # noqa: BLE001"), "SWFS001")
    assert len(found) == 1


# -- SWFS002: blocking in jit --------------------------------------------

def test_swfs002_flags_sleep_in_jit():
    src = """
    import time, jax

    @jax.jit
    def kernel(x):
        time.sleep(1)
        return x
    """
    found = check(src, "SWFS002")
    assert len(found) == 1 and "time.sleep" in found[0].message


def test_swfs002_partial_jit_and_pallas():
    src = """
    import functools, jax
    import jax.experimental.pallas as pl

    def _rs_kernel(ref):
        open("/tmp/x")

    @functools.partial(jax.jit, static_argnames=("n",))
    def wrapper(x, n):
        f.result()
        return pl.pallas_call(_rs_kernel)(x)
    """
    found = check(src, "SWFS002")
    assert {f.message.split("(")[0] for f in found} and len(found) == 2


def test_swfs002_negative_outside_jit():
    src = """
    import time
    def plain(x):
        time.sleep(1)
        return x
    """
    assert check(src, "SWFS002") == []


# -- SWFS003: struct widths ----------------------------------------------

def test_swfs003_flags_native_order():
    found = check("import struct\nstruct.pack('IQ', 1, 2)\n", "SWFS003")
    assert len(found) == 1 and "byte order" in found[0].message


def test_swfs003_flags_slice_width_mismatch():
    src = """
    import struct
    def f(buf):
        return struct.unpack(">I", buf[0:8])
    """
    found = check(src, "SWFS003")
    assert len(found) == 1 and "4 byte" in found[0].message


def test_swfs003_negative_exact_widths():
    src = """
    import struct
    def f(buf):
        a = struct.unpack(">I", buf[:4])
        b = struct.unpack(">H", buf[6:8])
        c = struct.unpack(">Q", buf)        # width not static: ok
        return a, b, c
    """
    assert check(src, "SWFS003") == []


def test_swfs003_flags_invalid_format():
    found = check("import struct\nstruct.pack('>Z', 1)\n", "SWFS003")
    assert len(found) == 1 and "invalid" in found[0].message


# -- SWFS004: swallowed exceptions ---------------------------------------

def test_swfs004_flags_swallowed_broad():
    src = """
    def f():
        try:
            g()
        except Exception:
            pass
    """
    assert len(check(src, "SWFS004")) == 1


def test_swfs004_flags_bare_except():
    src = """
    def f():
        try:
            g()
        except:
            log()
    """
    found = check(src, "SWFS004")
    assert len(found) == 1 and "bare" in found[0].message


def test_swfs004_negative_handled_or_narrow():
    src = """
    def f():
        try:
            g()
        except OSError:
            pass              # narrow: allowed
        try:
            g()
        except Exception as e:
            log(e)            # broad but handled: allowed
        try:
            g()
        except:
            raise             # bare but re-raised: allowed
    """
    assert check(src, "SWFS004") == []


# -- SWFS005: unclosed handles -------------------------------------------

def test_swfs005_flags_chained_and_discarded():
    src = """
    def f(p):
        data = open(p).read()
        open(p, "wb")
        return data
    """
    found = check(src, "SWFS005")
    assert len(found) == 2


def test_swfs005_negative_with_close_escape():
    src = """
    def f(p):
        with open(p) as fh:
            return fh.read()

    def g(p):
        fh = open(p)
        try:
            return fh.read()
        finally:
            fh.close()

    def h(p):
        fh = open(p)
        return fh              # escapes to the caller

    def i(p, sink):
        fh = open(p)
        sink(fh)               # ownership transferred

    def j(self, p):
        self._f = open(p)      # lifecycle-managed attribute

    def k(p):
        open(p, "wb").close()  # immediate close (touch)
    """
    assert check(src, "SWFS005") == []


# -- SWFS006: wall clock in deterministic paths --------------------------

def test_swfs006_flags_marked_module():
    src = """
    # swfs: deterministic — replay must be stable
    import time
    def replay(rec):
        rec["at"] = time.time()
    """
    found = check(src, "SWFS006")
    assert len(found) == 1 and "time.time" in found[0].message


def test_swfs006_negative_unmarked_module():
    src = """
    import time
    def stamp(rec):
        rec["at"] = time.time()
    """
    assert check(src, "SWFS006") == []


def test_swfs006_deterministic_paths_stay_clean():
    # the shipped deterministic modules must not regress
    import seaweedfs_tpu.server.raft as raft
    import seaweedfs_tpu.storage.idx as idx
    findings, errors = run_paths([raft.__file__, idx.__file__])
    assert not errors
    assert [f for f in findings if f.rule == "SWFS006"] == []


# -- engine: noqa / baseline ---------------------------------------------

# -- SWFS007: leaked trace spans ------------------------------------------

def test_swfs007_flags_discarded_and_unfinished():
    found = check("""
        from seaweedfs_tpu import tracing

        def handler():
            tracing.start_span("op", role="x")

        def handler2():
            sp = tracing.start_span("op")
            sp.set("k", 1)
    """, "SWFS007")
    assert len(found) == 2
    assert "discarded" in found[0].message
    assert "never" in found[1].message and "'sp'" in found[1].message


def test_swfs007_flags_ctx_manager_form_discarded():
    found = check("""
        from seaweedfs_tpu import tracing

        def handler():
            tracing.span("op", role="x")
    """, "SWFS007")
    assert len(found) == 1


def test_swfs007_negative_with_finish_escape():
    found = check("""
        from seaweedfs_tpu import tracing

        def with_block():
            with tracing.span("op") as sp:
                sp.set("k", 1)

        def manual_pair():
            sp = tracing.start_span("op")
            try:
                pass
            finally:
                sp.finish()

        def escapes():
            return tracing.start_span("op")

        def passed_on(consume):
            sp = tracing.start_span("op")
            consume(sp)
    """, "SWFS007")
    assert found == []


def test_swfs007_noqa_suppresses():
    found = check("""
        from seaweedfs_tpu import tracing

        def handler():
            tracing.start_span("op")  # noqa: SWFS007
    """, "SWFS007")
    assert found == []


def test_swfs008_flags_unclosed_sink_and_source():
    found = check("""
        from seaweedfs_tpu.storage.erasure_coding.shard_sink import (
            RemoteShardSink)

        def scatter(url):
            sink = RemoteShardSink(url, 1, 0)
            sink.write(b"x")

        def probe(url):
            RemoteShardSink(url, 1, 1).write(b"x")

        def fetch(paths):
            src = LocalShardSource(paths[0])
            src.read_into(0, 10, bytearray(10))
    """, "SWFS008")
    assert len(found) == 3
    msgs = " | ".join(f.message for f in found)
    assert "'sink'" in msgs
    assert "drops the stream" in msgs
    assert "'src'" in msgs


def test_swfs008_negative_with_close_escape():
    found = check("""
        def with_block(url):
            with RemoteShardSink(url, 1, 0) as sink:
                sink.write(b"x")

        def close_in_finally(url):
            sink = RemoteShardSink(url, 1, 0)
            try:
                sink.write(b"x")
            finally:
                sink.close()

        def container(urls):
            sinks = [RemoteShardSink(u, 1, i)
                     for i, u in enumerate(urls)]
            return sinks

        def passed_on(consume, path):
            src = LocalShardSource(path)
            consume(src)

        def fetcher_escapes(sources, work):
            fetcher = MultiSourceFetcher(sources, work)
            return fetcher
    """, "SWFS008")
    assert found == []


def test_swfs008_noqa_suppresses():
    found = check("""
        def leak(url):
            sink = RemoteShardSink(url, 1, 0)  # noqa: SWFS008
            sink.write(b"x")
    """, "SWFS008")
    assert found == []


# -- SWFS010: gateway without QoS admission -------------------------------

_GATEWAY = """
    from seaweedfs_tpu.server.httpd import HttpServer

    class MyGateway:
        def __init__(self):
            self.http = HttpServer()
            self.http.metrics = object()
            self.http.fallback = self._dispatch{extra}

        def _dispatch(self, req):
            return 200, {{}}
"""


def test_swfs010_flags_gateway_without_admission():
    found = check(_GATEWAY.format(extra=""), "SWFS010")
    assert len(found) == 1
    assert "MyGateway" in found[0].message
    assert "qos.install" in found[0].message


def test_swfs010_negative_qos_install_or_direct_assign():
    ok = _GATEWAY.format(extra="""
            from seaweedfs_tpu import qos
            qos.install(self.http, "mine")""")
    assert check(ok, "SWFS010") == []
    ok2 = _GATEWAY.format(extra="""
            self.http.admission = self._admit""")
    assert check(ok2, "SWFS010") == []


def test_swfs010_negative_non_gateway_listeners():
    # control plane: routes + metrics but no fallback (master shape)
    src = """
    class ControlPlane:
        def __init__(self):
            self.http = HttpServer()
            self.http.metrics = object()
            self.http.route("GET", "/x", self._x)
    """
    assert check(src, "SWFS010") == []
    # auxiliary listener: fallback but no role metrics (webdav shape)
    src2 = """
    class Aux:
        def __init__(self):
            self.http = HttpServer()
            self.http.fallback = self._dispatch
    """
    assert check(src2, "SWFS010") == []


def test_swfs010_repo_gateways_are_clean():
    """The three enforcement points from the QoS plane stay wired."""
    import seaweedfs_tpu
    import os
    root = os.path.dirname(seaweedfs_tpu.__file__)
    findings, errors = run_paths(
        [os.path.join(root, "s3", "s3_server.py"),
         os.path.join(root, "server", "filer_server.py"),
         os.path.join(root, "server", "volume_server.py")])
    assert not errors
    assert [f for f in findings if f.rule == "SWFS010"] == []


def test_swfs011_flags_t0_t1_subtraction():
    src = """
    import time
    def f():
        t0 = time.time()
        work()
        return time.time() - t0
    """
    found = check(src, "SWFS011")
    assert len(found) == 1
    assert "monotonic" in found[0].message


def test_swfs011_flags_bound_name_pair():
    src = """
    import time
    def f():
        start = time.time()
        end = time.time()
        dt = end - start
    """
    assert len(check(src, "SWFS011")) == 1


def test_swfs011_flags_deadline_remaining():
    src = """
    import time
    def f(deadline):
        return deadline - time.time()
    """
    assert len(check(src, "SWFS011")) == 1


def test_swfs011_negative_monotonic_and_records():
    src = """
    import time
    def f():
        t0 = time.monotonic()
        dur = time.monotonic() - t0       # the fix
        stamp = time.time()               # a record, no arithmetic
        return dur, stamp
    """
    assert check(src, "SWFS011") == []


def test_swfs011_scope_is_per_function():
    # a name bound to time.time() in ANOTHER scope is not evidence
    src = """
    import time
    def setup():
        t0 = time.time()
        return t0
    def use(t0, t1):
        return t1 - t0
    """
    assert check(src, "SWFS011") == []


def test_swfs011_noqa_suppresses():
    src = """
    import time
    def f(mtime):
        return time.time() - mtime  # noqa: SWFS011
    """
    assert check(src, "SWFS011") == []



@pytest.fixture(scope="module")
def package_findings(package_analysis):
    """The session-shared full-package scan (tests/conftest.py) —
    the 011/012/013 trio each re-ran the whole ~250-file scan (~7 s
    apiece) and 014/015 added scoped rescans; one pass serves all."""
    return package_analysis


def _no_new(package_findings, rule_id):
    from seaweedfs_tpu.devtools.analyze import (default_baseline_path,
                                                load_baseline,
                                                partition_baseline)
    new, _old = partition_baseline(
        [f for f in package_findings if f.rule == rule_id],
        load_baseline(default_baseline_path()))
    assert new == [], [f.render() for f in new]


def test_swfs011_repo_is_clean(package_findings):
    _no_new(package_findings, "SWFS011")


def test_bare_noqa_suppresses_everything():
    src = """
    def f():
        try:
            g()
        except Exception:  # noqa
            pass
    """
    assert check(src, "SWFS004") == []


def test_baseline_roundtrip(tmp_path):
    findings = analyze_tree(tmp_path, "legacy.py", """
        def f():
            try:
                g()
            except Exception:
                pass
    """)
    assert len(findings) == 1
    bl = tmp_path / "baseline.json"
    save_baseline(str(bl), findings)
    new, old = partition_baseline(findings, load_baseline(str(bl)))
    assert new == [] and len(old) == 1
    # an edit to the offending line invalidates its fingerprint
    findings[0].snippet = "except Exception:  # changed"
    new, old = partition_baseline(findings, load_baseline(str(bl)))
    assert len(new) == 1 and old == []


def test_fingerprints_distinguish_duplicate_lines(tmp_path):
    findings = analyze_tree(tmp_path, "dup.py", """
        def f():
            try:
                g()
            except Exception:
                pass
        def h():
            try:
                g()
            except Exception:
                pass
    """)
    assert len(findings) == 2
    fps = [fp for _, fp in fingerprints(findings)]
    assert len(set(fps)) == 2


def test_cli_json_output(tmp_path, capsys):
    from seaweedfs_tpu.devtools.analyze import run_cli
    p = tmp_path / "bad.py"
    p.write_text("def f():\n    try:\n        g()\n"
                 "    except Exception:\n        pass\n")
    rc = run_cli([str(p)], json_out=True, no_baseline=True)
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert doc["findings"][0]["rule"] == "SWFS004"


# -- lockgraph ------------------------------------------------------------

@pytest.fixture
def graph():
    return lg.LockGraph()


def _tracked_pair(graph):
    a = lg.TrackedLock(graph, "lock-A", threading.Lock())
    b = lg.TrackedLock(graph, "lock-B", threading.Lock())
    return a, b


def test_lockgraph_detects_ab_ba_cycle(graph):
    a, b = _tracked_pair(graph)

    def ab():
        with a:
            with b:
                pass

    def ba():
        with b:
            with a:
                pass

    for fn in (ab, ba):
        t = threading.Thread(target=fn)
        t.start()
        t.join()
    cycles = graph.cycles()
    assert len(cycles) == 1
    assert set(cycles[0]["cycle"]) == {"lock-A", "lock-B"}
    assert cycles[0]["stacks"]          # both edges carry stacks


def test_lockgraph_consistent_order_is_clean(graph):
    a, b = _tracked_pair(graph)
    for _ in range(3):
        with a:
            with b:
                pass
    assert graph.cycles() == []
    assert graph.report()["edges"] == [["lock-A", "lock-B"]]


def test_lockgraph_three_lock_cycle(graph):
    a = lg.TrackedLock(graph, "A", threading.Lock())
    b = lg.TrackedLock(graph, "B", threading.Lock())
    c = lg.TrackedLock(graph, "C", threading.Lock())
    for first, second in ((a, b), (b, c), (c, a)):
        with first:
            with second:
                pass
    assert len(graph.cycles()) == 1


def test_lockgraph_reentrant_is_not_a_cycle(graph):
    a = lg.TrackedLock(graph, "R", threading.RLock())
    with a:
        with a:
            pass
    assert graph.cycles() == []


def test_lockgraph_condition_wait_keeps_books_straight(graph):
    lock = lg.TrackedLock(graph, "cv-lock", threading.RLock())
    cv = threading.Condition(lock)
    ready = threading.Event()

    def waiter():
        with cv:
            ready.set()
            cv.wait(timeout=5)
        assert graph.held() == []       # fully released after exit

    t = threading.Thread(target=waiter)
    t.start()
    assert ready.wait(5)
    with cv:
        cv.notify()
    t.join(5)
    assert not t.is_alive()
    assert graph.cycles() == []


def test_lockgraph_hold_while_blocking(graph, monkeypatch):
    a = lg.TrackedLock(graph, "sleepy", threading.Lock())
    with a:
        graph.on_blocking_call("time.sleep", "0.2s")
    v = [x for x in graph.violations
         if x["kind"] == "hold-while-blocking"]
    assert len(v) == 1 and v[0]["held"] == ["sleepy"]


def test_lockgraph_report_flush(tmp_path, graph):
    graph.out_path = str(tmp_path / "report.json")
    a, b = _tracked_pair(graph)
    with a:
        with b:
            pass
    graph.flush()
    doc = json.loads((tmp_path / "report.json").read_text())
    assert doc["violations"] == []
    assert ["lock-A", "lock-B"] in doc["edges"]


# -- SWFS012: blocking flush/fsync under a lock ---------------------------

def test_swfs012_flags_flush_under_with_lock():
    src = """
    class V:
        def write(self, data):
            with self.lock:
                self._dat.write(data)
                self._dat.flush()
    """
    found = check(src, "SWFS012")
    assert len(found) == 1
    assert "group-commit" in found[0].message


def test_swfs012_flags_fsync_in_acquire_region():
    src = """
    import os
    class V:
        def write(self, data):
            self.lock.acquire()
            try:
                self._dat.write(data)
                os.fsync(self._dat.fileno())
            finally:
                self.lock.release()
    """
    assert len(check(src, "SWFS012")) == 1


def test_swfs012_exempts_group_commit_helper_and_teardown():
    src = """
    import os
    class V:
        def _group_commit_flush(self):
            with self.lock:
                self._dat.flush()
                os.fsync(self._dat.fileno())

        def close(self):
            with self.lock:
                self._dat.flush()
                self._dat.close()
    """
    assert check(src, "SWFS012") == []


def test_swfs012_silent_outside_lock_and_on_args():
    src = """
    class V:
        def write(self, data):
            with self.lock:
                self._dat.write(data)
            self._dat.flush()          # outside: the barrier shape

        def drain(self, sock):
            with self.lock:
                sock.flush(1024)       # an argful flush is not the
                                       # zero-arg durability barrier
    """
    assert check(src, "SWFS012") == []


def test_swfs012_noqa_suppresses():
    src = """
    class V:
        def seal(self):
            with self._lock:
                self._f.flush()  # noqa: SWFS012 — once-per-seal
    """
    assert check(src, "SWFS012") == []


def test_swfs012_repo_is_clean(package_findings):
    _no_new(package_findings, "SWFS012")


# -- SWFS013: unbounded full-body read on a data-plane path ---------------

def check_at(source: str, rule_id: str, relpath: str):
    """check() with a caller-chosen relpath (SWFS013 scopes by
    data-plane tree)."""
    src = textwrap.dedent(source)
    ctx = FileContext("<fixture>.py", relpath, src)
    rule = next(r for r in RULES if r.id == rule_id)
    return [f for f in rule.check(ctx)
            if not ctx.suppressed(f.rule, f.line)]


def test_swfs013_flags_unbounded_read_in_server_tree():
    src = """
    def serve(path):
        with open(path, "rb") as f:
            return 200, f.read()
    """
    found = check_at(src, "SWFS013", "seaweedfs_tpu/server/x.py")
    assert len(found) == 1
    assert "stream" in found[0].message


def test_swfs013_flags_assigned_handle():
    src = """
    def serve(path):
        f = open(path, "rb")
        data = f.read()
        f.close()
        return data
    """
    assert len(check_at(src, "SWFS013",
                        "seaweedfs_tpu/filer/x.py")) == 1


def test_swfs013_silent_on_bounded_read_and_foreign_objects():
    src = """
    def serve(path, resp):
        with open(path, "rb") as f:
            head = f.read(4096)        # bounded: fine
        body = resp.read()             # http client response, not an
        return head + body             # open() handle
    """
    assert check_at(src, "SWFS013",
                    "seaweedfs_tpu/server/x.py") == []


def test_swfs013_silent_outside_data_plane_trees():
    src = """
    def tool(path):
        with open(path, "rb") as f:
            return f.read()
    """
    assert check_at(src, "SWFS013",
                    "seaweedfs_tpu/devtools/x.py") == []


def test_swfs013_noqa_suppresses():
    src = """
    def inventory(path):
        with open(path, "rb") as f:
            return f.read()  # noqa: SWFS013 — bounded by format
    """
    assert check_at(src, "SWFS013",
                    "seaweedfs_tpu/server/x.py") == []


def test_swfs013_repo_is_clean(package_findings):
    _no_new(package_findings, "SWFS013")


# -- SWFS014: blocking call inside an async def ---------------------------

def test_swfs014_flags_sleep_and_client_funnel_in_coroutine():
    src = """
    import time
    async def handler(req):
        time.sleep(0.1)
        st, body, _ = http_bytes("GET", "peer/x")
        return 200, body
    """
    found = check_at(src, "SWFS014", "seaweedfs_tpu/server/x.py")
    assert len(found) == 2
    assert "event loop" in found[0].message


def test_swfs014_flags_bare_open_and_urlopen():
    src = """
    import urllib.request
    async def handler(path):
        f = open(path, "rb")
        r = urllib.request.urlopen("http://x/")
        return f, r
    """
    assert len(check_at(src, "SWFS014",
                        "seaweedfs_tpu/server/x.py")) == 2


def test_swfs014_executor_handoff_shapes_are_silent():
    src = """
    import asyncio, time
    async def handler(loop, pool, path):
        def work():
            time.sleep(0.1)          # runs on the pool: fine
            with open(path, "rb") as f:
                return f.read()
        data = await loop.run_in_executor(pool, work)
        lazy = await loop.run_in_executor(
            pool, lambda: open(path, "rb").read())
        await asyncio.sleep(0.01)    # async sleep: fine
        return data, lazy
    """
    assert check_at(src, "SWFS014", "seaweedfs_tpu/server/x.py") == []


def test_swfs014_sync_functions_out_of_scope():
    src = """
    import time
    def handler(req):
        time.sleep(0.1)
        return http_json("GET", "peer/x")
    """
    assert check_at(src, "SWFS014", "seaweedfs_tpu/server/x.py") == []


def test_swfs014_noqa_suppresses():
    src = """
    import time
    async def handler(req):
        time.sleep(0.1)  # noqa: SWFS014 — fixture pacing
    """
    assert check_at(src, "SWFS014", "seaweedfs_tpu/server/x.py") == []


def test_swfs014_repo_is_clean(package_findings):
    assert [f for f in package_findings
            if f.rule == "SWFS014"] == []


# -- SWFS015: per-request serialization/commit on the filer hot path ------

def test_swfs015_flags_per_request_db_commit():
    src = """
    class Store:
        def insert_entry(self, entry):
            self._db.execute("INSERT", ())
            self._db.commit()
    """
    found = check_at(src, "SWFS015",
                     "seaweedfs_tpu/filer/abstract_sql.py")
    assert len(found) == 1
    assert "per request" in found[0].message


def test_swfs015_flags_store_side_entry_serialization():
    src = """
    import json
    class Store:
        def insert_entry(self, entry):
            self._rows[entry.full_path] = json.dumps(entry.to_json())
        def update_entry(self, entry):
            self._rows[entry.full_path] = entry.to_json()
    """
    assert len(check_at(src, "SWFS015",
                        "seaweedfs_tpu/filer/lsm_store.py")) == 2


def test_swfs015_designated_helpers_are_exempt():
    src = """
    class Store:
        def apply_events(self, records):
            for r in records:
                self._db.execute("INSERT", r)
            self._db.commit()
        def close(self):
            self._db.commit()
        def _group_commit_flush(self):
            self._db.commit()
        def _checkpoint_flush(self):
            self._conn.commit()
    class Plane:
        def commit(self, op, new_entry, old_entry):
            return new_entry.to_json()
    """
    assert check_at(src, "SWFS015",
                    "seaweedfs_tpu/filer/abstract_sql.py") == []


def test_swfs015_non_db_commit_and_response_render_are_silent():
    src = """
    class Filer:
        def create_entry(self, entry):
            self._barrier.commit()
        def _list(self, req):
            return 200, {"entries": [e.to_json() for e in self.page()]}
    """
    assert check_at(src, "SWFS015",
                    "seaweedfs_tpu/filer/filer.py") == []


def test_swfs015_out_of_scope_modules_are_silent():
    src = """
    class Store:
        def insert_entry(self, entry):
            self._db.commit()
            return entry.to_json()
    """
    assert check_at(src, "SWFS015",
                    "seaweedfs_tpu/filer/redis_store.py") == []
    assert check_at(src, "SWFS015",
                    "seaweedfs_tpu/server/volume_server.py") == []


def test_swfs015_noqa_suppresses():
    src = """
    class Store:
        def insert_entry(self, entry):
            self._db.commit()  # noqa: SWFS015 — kill-switch path
    """
    assert check_at(src, "SWFS015",
                    "seaweedfs_tpu/filer/abstract_sql.py") == []


def test_swfs015_repo_is_clean(package_findings):
    assert [f for f in package_findings
            if f.rule == "SWFS015"] == []


# -- SWFS016: bare numeric timeout on a hot-path network call -------------

def test_swfs016_flags_bare_keyword_literal():
    src = """
    def read(url, fid):
        status, body, _ = http_bytes("GET", f"{url}/{fid}", None, None,
                                     timeout=60)
        return body
    """
    found = check_at(src, "SWFS016", "seaweedfs_tpu/operation.py")
    assert len(found) == 1
    assert "io_timeout" in found[0].message


def test_swfs016_flags_bare_positional_literal():
    src = """
    def probe(url):
        return http_bytes("GET", f"{url}/status", None, None, 5)
    """
    assert len(check_at(src, "SWFS016",
                        "seaweedfs_tpu/operation.py")) == 1


def test_swfs016_deadline_derived_timeout_passes():
    src = """
    from .util import deadline as _deadline

    def read(url, fid):
        return http_bytes(
            "GET", f"{url}/{fid}", None, None,
            timeout=_deadline.io_timeout(60.0, site="volume.read"))

    def relay(url):
        t = _deadline.io_timeout(10.0, site="x")
        return http_relay(url, "POST", url, None, t)
    """
    assert check_at(src, "SWFS016",
                    "seaweedfs_tpu/operation.py") == []


def test_swfs016_scoped_to_hot_path_modules():
    src = """
    def poke(url):
        return http_json("GET", f"{url}/x", timeout=30)
    """
    # a shell command / test helper is not the request path
    assert check_at(src, "SWFS016",
                    "seaweedfs_tpu/shell/commands.py") == []
    assert len(check_at(src, "SWFS016",
                        "seaweedfs_tpu/filer/filer.py")) == 1


def test_swfs016_plane_client_covered():
    src = """
    def plane_read(addr, fid):
        return _plane_request(addr, "GET", f"/{fid}", b"", 10.0)
    """
    assert len(check_at(src, "SWFS016",
                        "seaweedfs_tpu/operation.py")) == 1


def test_swfs016_noqa_suppresses():
    src = """
    def snapshot(master):
        return master_json(master, "GET", "/watch",
                           timeout=10)  # noqa: SWFS016
    """
    assert check_at(src, "SWFS016",
                    "seaweedfs_tpu/wdclient.py") == []


def test_swfs016_repo_is_clean(package_findings):
    assert [f for f in package_findings
            if f.rule == "SWFS016"] == []

# -- SWFS017: metric name built dynamically at the mint site --------------

def test_swfs017_flags_fstring_name():
    src = """
    def serve(m, vid):
        m.counter_add(f"reads_{vid}_total", 1.0)
    """
    found = check(src, "SWFS017")
    assert len(found) == 1
    assert "label" in found[0].message


def test_swfs017_flags_concat_format_and_mod():
    src = """
    def mint(m, kind):
        m.gauge_set("prefix_" + kind, 2.0)
        m.histogram_observe("stage_%s_seconds" % kind, 0.5)
        m.counter_add("ops_{}_total".format(kind), 1.0)
    """
    assert len(check(src, "SWFS017")) == 3


def test_swfs017_resolves_scope_local_name():
    src = """
    def mint(m, vid):
        hist = f"{vid}_stage_seconds"
        m.histogram_observe(hist, 0.5)
    """
    assert len(check(src, "SWFS017")) == 1


def test_swfs017_literal_and_label_pass():
    src = """
    def serve(m, vid, d, ms):
        m.counter_add("reads_total", 1.0, vid=vid)
        g = "device_h2d_gbps" if d == "h2d" else "device_d2h_gbps"
        m.gauge_set(g, 1.0)
        for key, gauge in (("in_use", "mem_in_use_bytes"),
                           ("peak", "mem_peak_bytes")):
            if key in ms:
                m.gauge_set(gauge, float(ms[key]))
    """
    assert check(src, "SWFS017") == []


def test_swfs017_outer_scope_binding_not_evidence():
    # a dynamic name bound in the OUTER scope is the outer scope's
    # problem; the inner function's own literal stays clean
    src = """
    def outer(m, vid):
        name = f"x_{vid}"
        def inner():
            m.counter_add("fixed_total", 1.0)
        return inner
    """
    assert check(src, "SWFS017") == []


def test_swfs017_noqa_suppresses():
    src = """
    def finish(self):
        hist = f"{self.name}_stage_seconds"
        self.metrics.histogram_observe(  # noqa: SWFS017 — code-site
            hist, 0.5, stage="total")
    """
    assert check(src, "SWFS017") == []


def test_swfs017_repo_is_clean(package_findings):
    assert [f for f in package_findings
            if f.rule == "SWFS017"] == []

# -- SWFS018: MetaLog append reachable from the armed hot path ------------

def test_swfs018_flags_unguarded_append():
    src = """
    class Filer:
        def _notify(self, event):
            return self.meta_log.append(event)
    """
    found = check_at(src, "SWFS018", "seaweedfs_tpu/filer/filer.py")
    assert len(found) == 1
    assert "meta-plane guard" in found[0].message


def test_swfs018_guarded_fallback_passes():
    src = """
    class Filer:
        def _notify(self, event):
            if self.meta_plane is not None:
                return self.meta_plane.commit(event)
            return self.meta_log.append(event)

        def _raw(self, op, new, old):
            if not self.meta_plane:
                return self.meta_log.append_raw(op, new, old)
    """
    assert check_at(src, "SWFS018",
                    "seaweedfs_tpu/filer/filer.py") == []


def test_swfs018_other_modules_and_appends_pass():
    # meta_plane.py's own append_raw half is the designated armed-path
    # appender; unrelated list.append never matches
    src = """
    class MetaPlane:
        def commit(self, op, new, old):
            return self.log.append_raw(op, new, old)

    def collect(items, out):
        out.append(items)
    """
    assert check_at(src, "SWFS018",
                    "seaweedfs_tpu/filer/meta_plane.py") == []
    src2 = """
    def gather(self, out):
        out.append(self.meta_log)
    """
    assert check_at(src2, "SWFS018",
                    "seaweedfs_tpu/filer/filer.py") == []


def test_swfs018_noqa_suppresses():
    src = """
    class Filer:
        def _boot_replay(self, event):
            return self.meta_log.append(event)  # noqa: SWFS018 — boot
    """
    assert check_at(src, "SWFS018",
                    "seaweedfs_tpu/filer/filer.py") == []


def test_swfs018_repo_is_clean(package_findings):
    assert [f for f in package_findings
            if f.rule == "SWFS018"] == []


# -- SWFS019: native-plane label drift -------------------------------------

WRITE_DRIVER_FULL = """
    RECORD_STAGES = ("recv", "append", "index", "ack")
    RECORD_FALLBACKS = ("none", "not_plain", "unregistered",
                        "seen_key", "journal_full", "io_error")
"""


def test_swfs019_flags_missing_stage_label():
    # the real write_plane.cc exports "index"; a driver without that
    # literal misattributes every drained record
    src = """
    RECORD_STAGES = ("recv", "append", "ack")
    RECORD_FALLBACKS = ("none", "not_plain", "unregistered",
                        "seen_key", "journal_full", "io_error")
    """
    found = check_at(src, "SWFS019",
                     "seaweedfs_tpu/server/write_plane.py")
    assert len(found) == 1, found
    assert '"index"' in found[0].message
    assert "RECORD_STAGES" in found[0].message


def test_swfs019_flags_missing_fallback_label():
    src = """
    RECORD_STAGES = ("recv", "append", "index", "ack")
    RECORD_FALLBACKS = ("none", "not_plain", "unregistered",
                        "seen_key", "io_error")
    """
    found = check_at(src, "SWFS019",
                     "seaweedfs_tpu/server/write_plane.py")
    assert len(found) == 1, found
    assert '"journal_full"' in found[0].message


def test_swfs019_complete_tables_pass():
    assert check_at(WRITE_DRIVER_FULL, "SWFS019",
                    "seaweedfs_tpu/server/write_plane.py") == []


def test_swfs019_other_modules_pass():
    # an unpaired module never matches, whatever its contents
    assert check_at("RECORD_STAGES = ()", "SWFS019",
                    "seaweedfs_tpu/server/volume_server.py") == []


def test_swfs019_noqa_suppresses():
    src = """
    RECORD_STAGES = ("recv", "append", "ack")  # noqa: SWFS019 — alias
    RECORD_FALLBACKS = ("none", "not_plain", "unregistered",
                        "seen_key", "journal_full", "io_error")
    """
    assert check_at(src, "SWFS019",
                    "seaweedfs_tpu/server/write_plane.py") == []


def test_swfs019_repo_is_clean(package_findings):
    assert [f for f in package_findings
            if f.rule == "SWFS019"] == []

# -- SWFS020: filer GET-path lookup without a read-plane fence -------------

def test_swfs020_flags_unfenced_get_lookup():
    src = """
    class FilerServer:
        def _get(self, req, path):
            entry = self.filer.find_entry(path)
            return 200, entry
    """
    found = check_at(src, "SWFS020",
                     "seaweedfs_tpu/server/filer_server.py")
    assert len(found) == 1
    assert "read-plane fence" in found[0].message


def test_swfs020_fenced_lookup_passes():
    src = """
    class FilerServer:
        def _get(self, req, path):
            nr = self.native_read
            token = nr.begin_fill() if nr is not None else 0
            entry = self.filer.find_entry(path)
            return 200, entry
    """
    assert check_at(src, "SWFS020",
                    "seaweedfs_tpu/server/filer_server.py") == []


def test_swfs020_fence_after_lookup_still_flags():
    # ordering IS the contract: a token captured after the SELECT can
    # outrank an invalidation that raced the lookup
    src = """
    class FilerServer:
        def _get(self, req, path):
            entry = self.filer.find_entry(path)
            token = self.native_read.begin_fill()
            return 200, entry
    """
    found = check_at(src, "SWFS020",
                     "seaweedfs_tpu/server/filer_server.py")
    assert len(found) == 1


def test_swfs020_non_get_handlers_and_other_modules_pass():
    src = """
    class FilerServer:
        def _meta_lookup(self, req):
            return self.filer.find_entry(req.query["path"])

        def _tus(self, req, path):
            return self.filer.find_entry(path)
    """
    assert check_at(src, "SWFS020",
                    "seaweedfs_tpu/server/filer_server.py") == []
    src2 = """
    class Anything:
        def _get(self, req, path):
            return self.filer.find_entry(path)
    """
    assert check_at(src2, "SWFS020",
                    "seaweedfs_tpu/server/volume_server.py") == []


def test_swfs020_noqa_suppresses():
    src = """
    class FilerServer:
        def _get_probe(self, path):
            return self.filer.find_entry(path)  # noqa: SWFS020 — cold
    """
    assert check_at(src, "SWFS020",
                    "seaweedfs_tpu/server/filer_server.py") == []


def test_swfs020_repo_is_clean(package_findings):
    assert [f for f in package_findings
            if f.rule == "SWFS020"] == []


# -- SWFS021: autopilot knob mutated outside the control registry ----------

def test_swfs021_flags_setter_call_outside_registry():
    src = """
    from seaweedfs_tpu.util import hedge

    def tune(req):
        hedge.set_ratio(0.5)
        return 200, {}
    """
    found = check_at(src, "SWFS021",
                     "seaweedfs_tpu/server/debug.py")
    assert len(found) == 1
    assert "outside the control registry" in found[0].message


def test_swfs021_registry_and_defining_module_pass():
    src = """
    from .util import hedge
    ap.register(Actuator("hedge.ratio", get=hedge.effective_ratio,
                         set=hedge.set_ratio, lo=0.02, hi=0.3))
    hedge.set_ratio(0.1)
    """
    assert check_at(src, "SWFS021",
                    "seaweedfs_tpu/autopilot.py") == []
    src2 = """
    def reset():
        set_min_threshold_ms(None)
        set_ratio(None)
    """
    assert check_at(src2, "SWFS021",
                    "seaweedfs_tpu/util/hedge.py") == []
    # in-module delegation (set_mem_limit -> set_limit) is wiring
    src3 = """
    class TwoTier:
        def set_mem_limit(self, limit_bytes):
            self.mem.set_limit(limit_bytes)
    """
    assert check_at(src3, "SWFS021",
                    "seaweedfs_tpu/util/chunk_cache.py") == []


def test_swfs021_flags_env_knob_writes():
    src = """
    import os

    def boot():
        os.environ["SEAWEEDFS_TPU_BROWNOUT_FACTOR"] = "2.0"

    def boot2():
        os.environ.setdefault("SEAWEEDFS_TPU_HEDGE_MIN_MS", "10")
    """
    found = check_at(src, "SWFS021",
                     "seaweedfs_tpu/server/filer_server.py")
    assert len(found) == 2
    assert all("env" in f.message for f in found)
    # non-knob env writes stay silent
    src2 = """
    import os
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("SEAWEEDFS_TPU_TREE_ROOT", "1")
    """
    assert check_at(src2, "SWFS021",
                    "seaweedfs_tpu/server/filer_server.py") == []


def test_swfs021_noqa_suppresses():
    src = """
    def reset():
        set_brownout_factor(None)  # noqa: SWFS021 — reset to baseline
    """
    assert check_at(src, "SWFS021",
                    "seaweedfs_tpu/server/volume_server.py") == []


def test_swfs021_repo_is_clean(package_findings):
    assert [f for f in package_findings
            if f.rule == "SWFS021"] == []
