"""Minimal Elasticsearch-wire fake for store tests (the external
process the elastic filer store speaks to — the role resp_fake.py
plays for the redis store).  Implements exactly the surface
ElasticClient drives: doc CRUD, _delete_by_query, _search with
bool-filter (term / prefix / range on flat fields), sort, size."""

from __future__ import annotations

import json
import threading
import urllib.parse

from seaweedfs_tpu.server.httpd import HttpServer, Request


class FakeElastic:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.http = HttpServer(host, port)
        self.docs: dict[tuple[str, str], dict] = {}
        self.lock = threading.Lock()
        self.search_calls = 0
        self.http.fallback = self._route

    def start(self) -> "FakeElastic":
        self.http.start()
        return self

    def stop(self) -> None:
        self.http.stop()

    @property
    def address(self) -> str:
        return f"{self.http.host}:{self.http.port}"

    # -- request routing ---------------------------------------------------

    def _route(self, req: Request):
        parts = [urllib.parse.unquote(p)
                 for p in req.path.strip("/").split("/") if p]
        if not parts:
            return 200, {"cluster_name": "fake-es",
                         "version": {"number": "7.99.0"}}
        idx = parts[0]
        if len(parts) == 1:
            # index lifecycle (create / exists-check)
            if req.method == "PUT":
                with self.lock:
                    self.indices = getattr(self, "indices", set())
                    self.indices.add(idx)
                return 200, {"acknowledged": True, "index": idx}
            if req.method in ("GET", "HEAD"):
                with self.lock:
                    known = idx in getattr(self, "indices", set())
                if known:
                    return 200, {idx: {"mappings": {}}}
                return 404, {"error": {
                    "type": "index_not_found_exception"}}
        if len(parts) >= 2 and parts[1] == "_refresh":
            return 200, {"_shards": {"successful": 1}}
        if len(parts) >= 2 and parts[1] == "_search":
            return self._search(idx, req)
        if len(parts) >= 2 and parts[1] == "_delete_by_query":
            return self._delete_by_query(idx, req)
        if len(parts) >= 3 and parts[1] == "_doc":
            doc_id = parts[2]
            if req.method == "PUT":
                with self.lock:
                    self.docs[(idx, doc_id)] = req.json()
                return 200, {"result": "updated", "_id": doc_id}
            if req.method == "GET":
                with self.lock:
                    src = self.docs.get((idx, doc_id))
                if src is None:
                    return 404, {"found": False, "_id": doc_id}
                return 200, {"found": True, "_id": doc_id,
                             "_source": src}
            if req.method == "DELETE":
                with self.lock:
                    existed = self.docs.pop((idx, doc_id),
                                            None) is not None
                return (200 if existed else 404), {
                    "result": "deleted" if existed else "not_found"}
        return 400, {"error": f"unsupported {req.method} {req.path}"}

    # -- query evaluation --------------------------------------------------

    @staticmethod
    def _clause_matches(clause: dict, src: dict) -> bool:
        kind, body = next(iter(clause.items()))
        if kind == "term":
            field, want = next(iter(body.items()))
            return src.get(field) == want
        if kind == "prefix":
            field, want = next(iter(body.items()))
            return str(src.get(field, "")).startswith(want)
        if kind == "range":
            field, spec = next(iter(body.items()))
            val = src.get(field)
            if val is None:
                return False
            for op, bound in spec.items():
                if op == "gt" and not val > bound:
                    return False
                if op == "gte" and not val >= bound:
                    return False
                if op == "lt" and not val < bound:
                    return False
                if op == "lte" and not val <= bound:
                    return False
            return True
        if kind == "bool":
            filters = body.get("filter", [])
            if isinstance(filters, dict):
                filters = [filters]
            if not all(FakeElastic._clause_matches(c, src)
                       for c in filters):
                return False
            should = body.get("should", [])
            if should and not any(
                    FakeElastic._clause_matches(c, src)
                    for c in should):
                return False
            return True
        if kind == "match_all":
            return True
        raise ValueError(f"unsupported query clause {kind!r}")

    def _matching(self, idx: str, query: dict) -> list:
        with self.lock:
            items = [(doc_id, dict(src))
                     for (i, doc_id), src in self.docs.items()
                     if i == idx]
        return [(doc_id, src) for doc_id, src in items
                if self._clause_matches(query, src)]

    def _search(self, idx: str, req: Request):
        self.search_calls += 1
        b = req.json()
        hits = self._matching(idx, b.get("query", {"match_all": {}}))
        for spec in b.get("sort", []):
            field, order = next(iter(spec.items()))
            if isinstance(order, dict):
                order = order.get("order", "asc")
            hits.sort(key=lambda t: str(t[1].get(field, "")),
                      reverse=order == "desc")
        size = int(b.get("size", 10))
        return 200, {"hits": {"total": {"value": len(hits)},
                              "hits": [{"_id": d, "_source": s}
                                       for d, s in hits[:size]]}}

    def _delete_by_query(self, idx: str, req: Request):
        b = req.json()
        doomed = self._matching(idx, b.get("query", {}))
        with self.lock:
            for doc_id, _src in doomed:
                self.docs.pop((idx, doc_id), None)
        return 200, {"deleted": len(doomed)}
