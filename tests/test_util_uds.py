"""util/chunk_cache, util/log_buffer, and the UDS zero-copy read plane
(VERDICT r3 Missing #5/#9, Next task: chunk cache + log buffer +
RDMA-analog)."""

import os
import time

import pytest

from seaweedfs_tpu import operation
from seaweedfs_tpu.server.master_server import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.util.chunk_cache import (DiskChunkCache,
                                            MemChunkCache,
                                            TieredChunkCache)
from seaweedfs_tpu.util.log_buffer import LogBuffer


def test_mem_chunk_cache_lru_eviction():
    c = MemChunkCache(limit_bytes=100)
    c.set("a", b"x" * 40)
    c.set("b", b"y" * 40)
    assert c.get("a") == b"x" * 40  # a is now most-recent
    c.set("c", b"z" * 40)           # evicts b (LRU)
    assert c.get("b") is None
    assert c.get("a") is not None and c.get("c") is not None
    c.set("huge", b"!" * 200)       # larger than limit: not cached
    assert c.get("huge") is None


def test_disk_chunk_cache_bounded(tmp_path):
    c = DiskChunkCache(str(tmp_path / "cache"), limit_bytes=100)
    c.set("a", b"1" * 40)
    c.set("b", b"2" * 40)
    c.set("c", b"3" * 40)           # evicts a
    assert c.get("a") is None
    assert c.get("b") == b"2" * 40
    assert c.get("c") == b"3" * 40
    # a fresh instance adopts leftover files for BYTE ACCOUNTING only
    # — serving them would be a stale-read hole (the invalidation
    # events that covered them died with the old process); re-written
    # keys become servable again and adopted bytes still bound the dir
    c2 = DiskChunkCache(str(tmp_path / "cache"), limit_bytes=100)
    assert c2.get("b") is None
    c2.set("b", b"fresh" * 8)
    assert c2.get("b") == b"fresh" * 8
    assert c2._bytes <= 100


def test_tiered_cache_promotes_and_invalidates(tmp_path):
    c = TieredChunkCache(mem_limit=1000,
                         disk_dir=str(tmp_path / "d"),
                         disk_limit=10_000)
    c.set("f@0", b"block0", group="/f")
    c.set("f@1", b"block1", group="/f")
    c.mem.delete("f@0")             # force disk-tier hit
    assert c.get("f@0") == b"block0"
    assert c.mem.get("f@0") == b"block0"  # promoted back
    c.invalidate_group("/f")
    assert c.get("f@0") is None and c.get("f@1") is None


def test_log_buffer_threshold_flush():
    pages = []
    lb = LogBuffer(pages.append, flush_bytes=100)
    lb.add({"n": 1}, 40)
    lb.add({"n": 2}, 40)
    assert not pages and len(lb.snapshot()) == 2
    lb.add({"n": 3}, 40)            # crosses threshold: one page
    assert len(pages) == 1 and [r["n"] for r in pages[0]] == [1, 2, 3]
    assert not lb.snapshot()
    lb.add({"n": 4}, 10)
    lb.flush()
    assert [r["n"] for r in pages[1]] == [4]


def test_log_buffer_failed_flush_keeps_records():
    calls = []

    def failing(recs):
        calls.append(list(recs))
        raise RuntimeError("sink down")

    lb = LogBuffer(failing, flush_bytes=10)
    with pytest.raises(RuntimeError):
        lb.add({"n": 1}, 20)
    assert len(lb.snapshot()) == 1  # nothing lost
    lb.flush_fn = calls.append
    lb.flush()
    assert calls[-1] == [{"n": 1}]


@pytest.fixture
def mini(tmp_path):
    master = MasterServer().start()
    vs = VolumeServer([str(tmp_path / "v0")], master.url,
                      pulse_seconds=0.3).start()
    time.sleep(0.4)
    yield master, vs
    vs.stop()
    master.stop()


def test_uds_zero_copy_read_plane(mini):
    """The UDS fast path serves real needle bytes via sendfile; the
    HTTP plane never sees the read."""
    from seaweedfs_tpu.server.uds_reader import uds_read_needle

    master, vs = mini
    blob = os.urandom(128 * 1024)
    fid = operation.submit(master.url, blob)
    assert vs.uds_server is not None
    assert os.path.exists(vs.uds_server.sock_path)

    part = fid.split(",", 1)[1]
    vid = int(fid.split(",", 1)[0])
    key, cookie = int(part[:-8], 16), int(part[-8:], 16)
    n = uds_read_needle(vs.uds_server.sock_path, vid, key)
    assert n.cookie == cookie
    assert bytes(n.data) == blob

    # unknown needle reports a miss, transport stays usable
    with pytest.raises(LookupError):
        uds_read_needle(vs.uds_server.sock_path, vid, key + 999)

    # operation.read prefers the UDS plane: sever the HTTP data path
    # for this fid's URL by poisoning the probe cache is complex —
    # instead assert equality through the public read (which may use
    # either plane) AND through the explicit UDS call above.
    assert operation.read(master.url, fid) == blob


def test_mount_chunk_cache_serves_repeat_reads(mini, tmp_path):
    """Mount block cache: the second read of the same region comes
    from cache (no filer round trip), and a changed file invalidates
    its blocks via the event stream."""
    from seaweedfs_tpu.mount.weedfs import WeedFS
    from seaweedfs_tpu.server.filer_server import FilerServer
    from seaweedfs_tpu.server.httpd import http_bytes

    master, vs = mini
    filer = FilerServer(master.url).start()
    fs = WeedFS(filer.url, attr_ttl=0.2)
    try:
        blob = os.urandom(3 << 20)
        marker_ns = time.time_ns()
        http_bytes("POST", f"{filer.url}/big.bin", blob)
        # let the event poll consume the write's invalidation BEFORE
        # the first read populates blocks: with the event still
        # pending, whether the cache survives to the second read was
        # a sub-10ms race against the poll tick
        deadline = time.time() + 5
        while time.time() < deadline and fs._since_ns < marker_ns:
            time.sleep(0.05)
        assert fs._since_ns >= marker_ns, "event poll never advanced"
        got = fs.read("/big.bin", 2 << 20, 100)
        assert got == blob[100:100 + (2 << 20)]

        fetches = []
        orig = fs._ranged_get
        fs._ranged_get = lambda *a: (fetches.append(a), orig(*a))[1]
        got = fs.read("/big.bin", 1 << 20, 4096)
        assert got == blob[4096:4096 + (1 << 20)]
        assert not fetches, "cached blocks should serve the re-read"

        # update the file: events invalidate, new content is served
        blob2 = os.urandom(1 << 20)
        http_bytes("POST", f"{filer.url}/big.bin", blob2)
        deadline = time.time() + 8
        while time.time() < deadline:
            if fs.read("/big.bin", 4096, 0) == blob2[:4096]:
                break
            time.sleep(0.1)
        assert fs.read("/big.bin", 4096, 0) == blob2[:4096]
    finally:
        fs.close()
        filer.stop()
